"""Benchmark harness: one JSON line for the driver.

Measures the GSPMD trainer's packed-SFT step throughput on the flagship
Qwen2.5-0.5B-geometry decoder (bf16, remat, scan-over-layers) on whatever
accelerator is attached, and reports MFU against the chip's bf16 peak.

`vs_baseline` compares our trainer MFU to 0.20 — the ballpark dense-7B
train-step MFU of the reference's Megatron/FSDP GPU trainer in the published
boba² runs (BASELINE.md; AReaL does not publish MFU directly, 0.20 is the
standard H800 Megatron figure for this class of run).
"""

from __future__ import annotations

import json
import time

import numpy as np


BASELINE_TRAINER_MFU = 0.20

# bf16 peak FLOP/s per chip by device kind substring.
PEAK_FLOPS = [
    ("v6", 918e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),  # v5p
    ("v4", 275e12),
]


def peak_flops(device_kind: str) -> float:
    kind = device_kind.lower()
    for sub, f in PEAK_FLOPS:
        if sub in kind:
            return f
    return 100e12  # unknown accelerator / CPU: nominal figure


def count_params(params) -> int:
    import jax

    return int(sum(np.prod(x.shape) for x in jax.tree.leaves(params)))


def main() -> None:
    import jax

    from areal_tpu.api.alloc_mode import ParallelStrategy
    from areal_tpu.api.cli_args import (
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.sft.lm_engine import JaxLMEngine
    from areal_tpu.models.qwen2 import ModelConfig
    from areal_tpu.utils.data import pad_sequences_to_tensors

    dev = jax.devices()[0]
    on_accel = dev.platform != "cpu"

    if on_accel:
        model = ModelConfig(
            vocab_size=151936,
            hidden_size=896,
            intermediate_size=4864,
            num_hidden_layers=24,
            num_attention_heads=14,
            num_key_value_heads=2,
            tie_word_embeddings=True,
            dtype="bfloat16",
            param_dtype="bfloat16",
            remat=True,
            scan_layers=True,
        )
        tokens_per_step = 4096
        seq_len = 512
        warmup, iters = 2, 8
    else:  # CPU smoke fallback so the harness always emits a line
        model = ModelConfig(
            vocab_size=1024,
            hidden_size=128,
            intermediate_size=256,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            dtype="float32",
            param_dtype="float32",
        )
        tokens_per_step = 512
        seq_len = 128
        warmup, iters = 1, 3

    cfg = TrainEngineConfig(
        experiment_name="bench",
        trial_name="b",
        path="",
        init_from_scratch=True,
        dtype=model.dtype,
        mb_spec=MicroBatchSpec(max_tokens_per_mb=tokens_per_step + seq_len),
        optimizer=OptimizerConfig(
            lr=1e-4,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=model.remat,
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = model
    eng.create_process_group(ParallelStrategy())
    eng.initialize(None, FinetuneSpec(1, 1000, 1))

    rng = np.random.RandomState(0)
    n_seqs = tokens_per_step // seq_len
    seqs = []
    for _ in range(n_seqs):
        ids = rng.randint(1, model.vocab_size, (seq_len,))
        mask = np.ones(seq_len, dtype=np.int32)
        seqs.append(dict(input_ids=ids, loss_mask=mask))
    batch = pad_sequences_to_tensors(seqs)

    for _ in range(warmup):
        eng.train_lm(batch)
    t0 = time.perf_counter()
    for _ in range(iters):
        eng.train_lm(batch)
    dt = (time.perf_counter() - t0) / iters

    n_params = count_params(eng.params)
    # 6ND dense matmul FLOPs + causal attention term 6·L·T·ctx·H (fwd+bwd).
    attn_flops = (
        6 * model.num_hidden_layers * tokens_per_step * seq_len
        * model.num_attention_heads * (model.hidden_size // model.num_attention_heads)
    )
    flops = 6 * n_params * tokens_per_step + attn_flops
    mfu = flops / dt / peak_flops(dev.device_kind)
    tokens_per_sec = tokens_per_step / dt

    print(
        json.dumps(
            {
                "metric": "trainer_mfu_qwen2.5-0.5b_bf16_packed_sft"
                if on_accel
                else "trainer_mfu_cpu_smoke",
                "value": round(mfu, 4),
                "unit": "fraction_of_peak",
                "vs_baseline": round(mfu / BASELINE_TRAINER_MFU, 3),
                "detail": {
                    "device": dev.device_kind,
                    "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
                    "step_time_s": round(dt, 4),
                    "n_params": n_params,
                    "tokens_per_step": tokens_per_step,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
