"""Benchmark harness: one JSON line for the driver.

Two measurements on whatever accelerator is attached:

1. TRAIN (primary metric): GSPMD trainer packed-SFT step on the flagship
   Qwen2.5-0.5B geometry (bf16, remat, scan-over-layers, Pallas flash
   attention) at a realistic 64k tokens/step. MFU uses the explicit
   per-token matmul FLOPs model (areal_tpu/utils/flops.py) — embedding
   *lookup* excluded, lm_head matmul + causal attention term included —
   against the chip's bf16 peak.
2. DECODE (detail): in-process continuous-batching engine
   (areal_tpu/engine/jax_decode.py) serving concurrent requests; reports
   steady-state generated tokens/sec/chip — the rollout half of the
   async-RL throughput story (BASELINE.md "rollout tokens/sec").

`vs_baseline` compares trainer MFU to 0.20 — the ballpark dense-model
train-step MFU of the reference's Megatron/FSDP GPU trainer in the
published boba² runs (BASELINE.md; AReaL does not publish MFU directly,
0.20 is the standard H800 Megatron figure for this class of run).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

BASELINE_TRAINER_MFU = 0.20

# ---------------------------------------------------------------------------
# Driver-environment resilience.
#
# The accelerator in the driver environment is reached through a remote-compile
# relay; when that relay hiccups, XLA surfaces transport-class errors
# (UNAVAILABLE / "Connection refused" / DEADLINE_EXCEEDED) out of otherwise
# valid programs.  Round-2's bench made a single unguarded attempt and died
# with rc=1 before emitting any JSON.  Policy now:
#   1. preflight: a trivial jit compiles first, so relay failures surface in
#      seconds, not after the 24-layer trainer program is built;
#   2. transport-class failures are retried with bounded exponential backoff
#      (the compile cache makes retries cheap);
#   3. whatever happens, exactly one JSON line is printed and rc is 0 —
#      on unrecoverable accelerator failure we re-exec ourselves on CPU
#      (JAX_PLATFORMS=cpu) so the driver still records a parsed line, with
#      the accelerator error recorded in `detail`.
# ---------------------------------------------------------------------------

_TRANSPORT_MARKERS = (
    "UNAVAILABLE",
    "Connection refused",
    "Connection Failed",
    "Connect error",
    "DEADLINE_EXCEEDED",
    "transport",
    "Socket closed",
)

# HBM OOM ("Attempting to reserve ...") can be transient on a shared chip,
# so it is retryable by default — but callers with their own OOM fallback
# (the no-remat bench attempt) must see it immediately, not after three
# wasted compile-and-OOM cycles.
_OOM_MARKER = "RESOURCE_EXHAUSTED: Attempting to reserve"


def _is_transport_error(e: BaseException, *, retry_oom: bool = True) -> bool:
    msg = f"{type(e).__name__}: {e}"
    if retry_oom and _OOM_MARKER in msg:
        return True
    return any(m in msg for m in _TRANSPORT_MARKERS)


def _retry_transport(fn, *, what: str, attempts: int = 6, base_delay: float = 5.0,
                     max_delay: float = 120.0, retry_oom: bool = True):
    """Run fn(); retry on transport-class errors with exponential backoff."""
    last = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classify, re-raise non-transport
            if not _is_transport_error(e, retry_oom=retry_oom):
                raise
            last = e
            delay = min(base_delay * (2**i), max_delay)
            print(
                f"[bench] transport error in {what} (attempt {i + 1}/{attempts}): "
                f"{type(e).__name__}: {e}; retrying in {delay:.0f}s",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(delay)
    raise last


def preflight() -> None:
    """Compile+run a trivial program so relay failures surface early/cheaply."""
    import jax
    import jax.numpy as jnp

    def tiny():
        x = jnp.ones((256, 256), dtype=jnp.bfloat16)
        y = jax.jit(lambda a: (a @ a).sum())(x)
        jax.block_until_ready(y)

    _retry_transport(tiny, what="preflight jit", attempts=8, base_delay=5.0)


def bench_train(model, tokens_per_step, seq_len, mb_tokens, warmup, iters):
    from areal_tpu.api.alloc_mode import ParallelStrategy
    from areal_tpu.api.cli_args import (
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.sft.lm_engine import JaxLMEngine
    from areal_tpu.utils.data import pad_sequences_to_tensors

    cfg = TrainEngineConfig(
        experiment_name="bench",
        trial_name="b",
        path="",
        init_from_scratch=True,
        dtype=model.dtype,
        mb_spec=MicroBatchSpec(max_tokens_per_mb=mb_tokens),
        optimizer=OptimizerConfig(
            lr=1e-4,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=model.remat,
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = model
    eng.create_process_group(ParallelStrategy())
    eng.initialize(None, FinetuneSpec(1, 1000, 1))

    rng = np.random.RandomState(0)
    seqs = [
        dict(
            input_ids=rng.randint(1, model.vocab_size, (seq_len,)),
            loss_mask=np.ones(seq_len, dtype=np.int32),
        )
        for _ in range(tokens_per_step // seq_len)
    ]
    batch = pad_sequences_to_tensors(seqs)

    for _ in range(warmup):
        eng.train_lm(batch)
    stats = []
    t0 = time.perf_counter()
    for _ in range(iters):
        stats.append(eng.train_lm(batch))
    dt = (time.perf_counter() - t0) / iters
    eng.destroy()
    # engine-reported MFU (same flops model), averaged over timed iters
    mfu = float(np.mean([s["mfu"] for s in stats]))
    tps = float(np.mean([s["tokens_per_sec_per_chip"] for s in stats]))
    return dict(
        mfu=mfu,
        tokens_per_sec_per_chip=tps,
        step_time_s=dt,
        tokens_per_step=tokens_per_step,
    )


def _wait_for_running(eng, timeout_s: float, poll_s: float = 0.01) -> bool:
    """Poll the engine until at least one request is actively decoding.
    Returns False on deadline — callers must NOT then measure pause latency
    against the idle engine (it would masquerade as an under-load number)."""
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        if eng.get_metrics()["running_requests"] > 0:
            return True
        time.sleep(poll_s)
    return False


def bench_decode(model, n_requests, prompt_len, new_tokens, max_running,
                 runahead=1, chunk=None, kv_layout="paged"):
    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.models.qwen2 import init_params

    import jax

    dcfg = JaxDecodeConfig(
        context_length=prompt_len + new_tokens + 128,
        max_running_requests=max_running,
        new_tokens_per_chunk=chunk or min(128, new_tokens),
        decode_runahead_chunks=runahead,
        kv_layout=kv_layout,
        dtype=model.dtype,
        kv_cache_dtype=model.dtype,
    )
    eng = JaxDecodeEngine(dcfg, InferenceEngineConfig(max_concurrent_rollouts=n_requests))
    eng.set_model(init_params(model, jax.random.PRNGKey(0)), model)
    eng.initialize()

    rng = np.random.RandomState(1)
    g = GenerationHyperparameters(
        max_new_tokens=new_tokens, temperature=1.0, top_p=1.0
    )
    n_warm = max(2, max_running)
    # pre-generated on one thread: RandomState is not thread-safe under the
    # pool.map fan-out below
    prompts = [
        rng.randint(1, model.vocab_size, (prompt_len,)).tolist()
        for _ in range(n_warm + n_requests)
    ]

    def one(i):
        req = ModelRequest(input_ids=prompts[i], gconfig=g)
        return eng.generate(req, timeout=1800)

    interrupt_latency = {}

    def measure_interrupt():
        # Weight-update pause window under load: pause_generation blocks
        # through the in-flight chunk (VERDICT weak #7 asks for this number
        # — the reference aborts mid-request; we land on chunk boundaries).
        # Wait until requests are actually decoding (a fixed sleep misses
        # the whole load window on a fast backend), then pause.
        if not _wait_for_running(eng, 30.0):
            # Pausing anyway would time an IDLE-engine pause and report it
            # as the under-load latency — record the sentinel instead.
            print(
                "[bench] pause probe: no running requests within 30s; "
                "recording pause_s=-1 (not measured) instead of an "
                "idle-engine pause",
                file=sys.stderr,
                flush=True,
            )
            interrupt_latency["pause_s"] = -1.0
            return
        t0 = time.perf_counter()
        eng.pause_generation()
        interrupt_latency["pause_s"] = time.perf_counter() - t0
        eng.continue_generation()

    # Deterministic compile warmup (the same class of fix the prefix bench
    # needed, r05 notes): every batched-prefill wave size and the chunk fn
    # at every KV bucket the context growth reaches — compiled here, not
    # inside the timed window. gconfig=g warms exactly the sampler variant
    # the timed region uses; the fork path is skipped (unique prompts
    # below never fork).
    eng.prewarm(prompt_len=prompt_len, gconfig=g, include_fork=False)
    with ThreadPoolExecutor(max_workers=n_requests + 1) as pool:
        # UNTIMED load pass: covers live-traffic interleavings prewarm's
        # idle-engine waves don't (retire-then-admit while decoding), and
        # hosts the pause-latency probe — a real under-load pause window
        # measured on a warm engine, without eating ~4 s of the timed
        # throughput region.
        stopper = pool.submit(measure_interrupt)
        list(pool.map(one, range(n_warm)))
        stopper.result()
        m0 = eng.get_metrics()  # timed-window deltas, not since-init totals
        t0 = time.perf_counter()
        results = list(pool.map(one, range(n_warm, n_warm + n_requests)))
        dt = time.perf_counter() - t0
        m1 = eng.get_metrics()
    eng.destroy()
    gen_tokens = sum(len(r.output_tokens) for r in results)
    # device-idle split over the timed window: the host gap between a
    # chunk's results landing and the next dispatch — the time the
    # run-ahead scheduler exists to hide
    busy = m1["device_busy_s"] - m0["device_busy_s"]
    idle = m1["device_idle_s"] - m0["device_idle_s"]
    # honest ITL: per-token dispatch->ready device time only (host work is
    # reported separately as the idle fraction)
    itl_ms = np.concatenate(
        [np.asarray(r.itl, dtype=np.float64) for r in results if r.itl]
    ) * 1000.0
    return dict(
        decode_tokens_per_sec_per_chip=gen_tokens / dt,
        decode_requests=n_requests,
        decode_new_tokens=new_tokens,
        decode_runahead_chunks=runahead,
        decode_kv_layout=kv_layout,
        # per-chunk KV copy traffic over the timed window: workspace =
        # gather + scatter; paged drops the scatter half (xla impl) or
        # both halves (pallas in-pool reads)
        decode_kv_copy_bytes=(
            m1["kv_workspace_copy_bytes_total"]
            - m0["kv_workspace_copy_bytes_total"]
        ),
        decode_device_idle_frac=(
            idle / (busy + idle) if (busy + idle) > 0 else 0.0
        ),
        decode_itl_p50_ms=float(np.percentile(itl_ms, 50)) if itl_ms.size else 0.0,
        decode_itl_p99_ms=float(np.percentile(itl_ms, 99)) if itl_ms.size else 0.0,
        interrupt_pause_latency_s=interrupt_latency.get("pause_s", -1.0),
    )


def bench_decode_compare(model, n_requests, prompt_len, new_tokens,
                         max_running, chunk=None):
    """Run-ahead (the default) vs legacy synchronous scheduling at the same
    wave config. Headline numbers come from the run-ahead engine; the sync
    run's throughput and device-idle fraction land under `decode_sync_*` so
    the overlap win (idle fraction strictly down, tokens/s no worse) is a
    single-report read. The run-ahead engine runs FIRST: the second engine
    in a process inherits warm XLA/persistent-cache state, so the
    advantaged position goes to the sync baseline — any reported win is a
    conservative one."""
    out = bench_decode(
        model, n_requests, prompt_len, new_tokens, max_running, runahead=1,
        chunk=chunk,
    )
    sync = bench_decode(
        model, n_requests, prompt_len, new_tokens, max_running, runahead=0,
        chunk=chunk,
    )
    out["decode_sync_tokens_per_sec_per_chip"] = sync[
        "decode_tokens_per_sec_per_chip"
    ]
    out["decode_sync_device_idle_frac"] = sync["decode_device_idle_frac"]
    out["decode_sync_itl_p50_ms"] = sync["decode_itl_p50_ms"]
    out["decode_sync_itl_p99_ms"] = sync["decode_itl_p99_ms"]
    return out


def bench_paged_compare(model, n_requests, prompt_len, new_tokens,
                        max_running, chunk=None):
    """In-pool paged attention (kv_layout="paged", the default) vs the
    legacy gather/scatter workspace layout at the same wave config.
    Headline numbers come from the paged engine; the workspace run lands
    under `decode_ws_*` plus its measured gather/scatter round-trip bytes
    (`decode_ws_gather_scatter_bytes`) — the per-chunk HBM traffic the
    in-pool path eliminates outright. The paged engine runs FIRST so the
    warm-process advantage goes to the workspace baseline (same
    conservative ordering as bench_decode_compare)."""
    out = bench_decode(
        model, n_requests, prompt_len, new_tokens, max_running,
        chunk=chunk, kv_layout="paged",
    )
    ws = bench_decode(
        model, n_requests, prompt_len, new_tokens, max_running,
        chunk=chunk, kv_layout="workspace",
    )
    out["decode_ws_tokens_per_sec_per_chip"] = ws[
        "decode_tokens_per_sec_per_chip"
    ]
    out["decode_ws_itl_p50_ms"] = ws["decode_itl_p50_ms"]
    out["decode_ws_itl_p99_ms"] = ws["decode_itl_p99_ms"]
    out["decode_ws_gather_scatter_bytes"] = ws["decode_kv_copy_bytes"]
    out["paged_over_ws_speedup"] = (
        out["decode_tokens_per_sec_per_chip"]
        / ws["decode_tokens_per_sec_per_chip"]
        if ws["decode_tokens_per_sec_per_chip"] > 0
        else 0.0
    )
    return out


def bench_spec_compare(model, n_requests, prompt_len, new_tokens, max_running,
                       chunk=None, spec_k=7, echo_vocab=64):
    """n-gram speculative decoding (spec_decode="ngram") vs the
    non-speculative oracle on a prompt-echoing workload.

    Untrained random weights never repeat under greedy decoding (no
    induction behavior), so the workload makes the model itself echo:
    the residual-mixing kernels (attn o_kernel, mlp down_kernel) are
    zeroed, which reduces greedy decoding to a deterministic
    last-token -> next-token map over a small vocab (`echo_vocab`) — it
    must enter a cycle within O(sqrt(vocab)) steps, the repetition regime
    prompt-lookup exploits in trained math/code rollouts that quote their
    prompts. BOTH engines serve the same echo model, so the comparison
    isolates the engine cost: one W-wide verify forward per up-to-W
    emitted tokens versus `chunk` sequential decode steps per chunk.

    Reports end-to-end tok/s for both engines, the speedup, and the
    acceptance telemetry (mean accepted-per-chunk, draft hit rate,
    rejected waste). The spec engine runs FIRST so the warm-XLA-process
    advantage goes to the baseline (same conservative ordering as
    bench_decode_compare)."""
    import dataclasses as _dc

    import jax

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.models.qwen2 import init_params

    echo_model = _dc.replace(model, vocab_size=min(model.vocab_size, echo_vocab))
    params = init_params(echo_model, jax.random.PRNGKey(0))
    zero = lambda a: a * 0.0  # noqa: E731

    def echoify(layer):
        return {
            **layer,
            "attn": {**layer["attn"], "o_kernel": zero(layer["attn"]["o_kernel"])},
            "mlp": {**layer["mlp"], "down_kernel": zero(layer["mlp"]["down_kernel"])},
        }

    if "layers" in params:
        params["layers"] = echoify(params["layers"])
    else:
        for name in list(params):
            if name.startswith("layers_"):
                params[name] = echoify(params[name])

    g = GenerationHyperparameters(max_new_tokens=new_tokens, greedy=True)
    rng = np.random.RandomState(5)
    n_warm = max(2, max_running)
    prompts = [
        rng.randint(1, echo_model.vocab_size, (prompt_len,)).tolist()
        for _ in range(n_warm + n_requests)
    ]

    def run(spec: bool):
        dcfg = JaxDecodeConfig(
            context_length=prompt_len + new_tokens + 128,
            max_running_requests=max_running,
            new_tokens_per_chunk=chunk or min(128, new_tokens),
            spec_decode="ngram" if spec else "off",
            spec_k=spec_k,
            dtype=model.dtype,
            kv_cache_dtype=model.dtype,
        )
        eng = JaxDecodeEngine(
            dcfg, InferenceEngineConfig(max_concurrent_rollouts=n_requests)
        )
        eng.set_model(params, echo_model)
        eng.initialize()
        try:
            eng.prewarm(prompt_len=prompt_len, gconfig=g, include_fork=False)

            def one(i):
                return eng.generate(
                    ModelRequest(input_ids=prompts[i], gconfig=g), timeout=1800
                )

            with ThreadPoolExecutor(max_workers=n_requests) as pool:
                # untimed load pass: live-traffic interleavings + the spec
                # path's first drafted dispatches land outside the clock
                list(pool.map(one, range(n_warm)))
                m0 = eng.get_metrics()
                t0 = time.perf_counter()
                results = list(
                    pool.map(one, range(n_warm, n_warm + n_requests))
                )
                dt = time.perf_counter() - t0
                m1 = eng.get_metrics()
            gen = sum(len(r.output_tokens) for r in results)
            out = dict(tok_s=gen / dt, m0=m0, m1=m1, results=results)
            return out
        finally:
            eng.destroy()

    spec = run(True)
    base = run(False)
    # greedy streams must agree between the engines — a speedup bought
    # with different tokens would be a correctness bug, not a win
    for a, b in zip(spec["results"], base["results"]):
        assert a.output_tokens == b.output_tokens, "spec stream diverged"
    m0, m1 = spec["m0"], spec["m1"]
    d_chunks = m1["spec_chunks_total"] - m0["spec_chunks_total"]
    d_drafted = (
        m1["spec_drafted_tokens_total"] - m0["spec_drafted_tokens_total"]
    )
    d_rejected = (
        m1["spec_rejected_tokens_total"] - m0["spec_rejected_tokens_total"]
    )
    d_accept = d_drafted - d_rejected  # accepted = drafted - rejected
    return dict(
        spec_tokens_per_sec_per_chip=spec["tok_s"],
        spec_off_tokens_per_sec_per_chip=base["tok_s"],
        spec_over_off_speedup=(
            spec["tok_s"] / base["tok_s"] if base["tok_s"] > 0 else 0.0
        ),
        spec_accepted_per_chunk_mean=(
            d_accept / d_chunks if d_chunks else 0.0
        ),
        spec_draft_hit_rate=(
            (d_drafted - d_rejected) / d_drafted if d_drafted else 0.0
        ),
        spec_rejected_tokens=d_rejected,
        spec_verify_chunks=d_chunks,
        spec_k=spec_k,
        spec_itl_p50_ms=m1["itl_p50_ms"],
        spec_new_tokens=new_tokens,
    )


def bench_kvoffload(model, n_sessions, prompt_len, new_tokens, max_running,
                    host_mb=256.0, chunk=None):
    """Tiered KV cache under oversubscription: host-RAM offload
    (`kv_host_pool_mb`) vs today's drop-and-reprefill, on a session-reuse
    trace whose working set exceeds the device slots.

    Trace (identical for both engines): `n_sessions` > `max_running`
    sessions start concurrently and are interrupted mid-stream
    (pause+abort — the weight-update flush every async-RL step performs);
    the sessions that never got a slot run to completion first, which
    forces the LRU eviction of every parked session's KV; then the
    interrupted sessions RESUME (prompt + partial tokens, same rid). With
    the host tier the eviction offloaded their KV and the resume promotes
    it back (fresh blocks + async upload); without it the resume re-runs
    prefill over the whole conversation. Reported: resume TTFT for both
    engines (the number long-context session reuse lives or dies on),
    re-prefill tokens avoided, and the swap traffic that bought it. The
    offload engine runs FIRST so the warm-XLA-process advantage goes to
    the re-prefill baseline (same conservative ordering as
    bench_decode_compare)."""
    import asyncio
    import threading
    import uuid as _uuid
    from dataclasses import replace as _dc_replace

    import jax

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.models.qwen2 import init_params

    params = init_params(model, jax.random.PRNGKey(0))
    rng = np.random.RandomState(11)
    prompts = [
        rng.randint(1, model.vocab_size, (prompt_len,)).tolist()
        for _ in range(n_sessions)
    ]
    g = GenerationHyperparameters(
        max_new_tokens=new_tokens, temperature=1.0, top_p=1.0
    )

    def run(mb: float) -> dict:
        dcfg = JaxDecodeConfig(
            context_length=prompt_len + new_tokens + 128,
            max_running_requests=max_running,
            new_tokens_per_chunk=chunk or min(128, new_tokens),
            kv_host_pool_mb=mb,
            dtype=model.dtype,
            kv_cache_dtype=model.dtype,
        )
        eng = JaxDecodeEngine(
            dcfg, InferenceEngineConfig(max_concurrent_rollouts=n_sessions)
        )
        eng.set_model(params, model)
        eng.initialize()
        try:
            eng.prewarm(prompt_len=prompt_len, gconfig=g, include_fork=False)
            # phase 1: all sessions start; interrupt them mid-stream
            first = [None] * n_sessions
            rids = [f"sess-{i}-{_uuid.uuid4()}" for i in range(n_sessions)]

            def one_first(i):
                first[i] = eng.generate(
                    ModelRequest(
                        rid=rids[i], input_ids=prompts[i], gconfig=g
                    ),
                    timeout=1800,
                )

            threads = [
                threading.Thread(target=one_first, args=(i,), daemon=True)
                for i in range(n_sessions)
            ]
            for t in threads:
                t.start()
            if not _wait_for_running(eng, 60.0):
                raise RuntimeError("kvoffload bench: sessions never started")
            # let the running wave emit some tokens before the flush
            deadline = time.perf_counter() + 60.0
            while (
                eng.get_metrics()["generated_tokens_total"] < max_running
                and time.perf_counter() < deadline
            ):
                time.sleep(0.005)
            eng.pause_generation()
            eng.abort_all()
            eng.continue_generation()
            for t in threads:
                t.join(120)
            interrupted = [
                i for i, r in enumerate(first)
                if r is not None and len(r.output_tokens) > 0
            ]
            fresh = [
                i for i, r in enumerate(first)
                if r is not None and len(r.output_tokens) == 0
            ]
            # phase 2a: the never-ran sessions complete first — their slot
            # demand LRU-evicts every parked session (offload vs drop)
            with ThreadPoolExecutor(max_workers=max(len(fresh), 1)) as pool:
                list(
                    pool.map(
                        lambda i: eng.generate(
                            ModelRequest(input_ids=prompts[i], gconfig=g),
                            timeout=1800,
                        ),
                        fresh,
                    )
                )
            m0 = eng.get_metrics()
            # phase 2b: the interrupted sessions resume (same rid,
            # prompt + partials) — TTFT here is swap-in vs re-prefill
            def resume(i):
                r1 = first[i]
                return eng.generate(
                    ModelRequest(
                        rid=rids[i],  # same rid: the resume-affinity key
                        input_ids=list(prompts[i]) + list(r1.output_tokens),
                        gconfig=_dc_replace(
                            g,
                            max_new_tokens=max(
                                new_tokens - len(r1.output_tokens), 1
                            ),
                        ),
                    ),
                    timeout=1800,
                )

            t0 = time.perf_counter()
            with ThreadPoolExecutor(
                max_workers=max(len(interrupted), 1)
            ) as pool:
                resumed = list(pool.map(resume, interrupted))
            resume_wall = time.perf_counter() - t0
            m1 = eng.get_metrics()
            ttfts = np.asarray([r.ttft for r in resumed], dtype=np.float64)
            return dict(
                ttft_mean_ms=float(ttfts.mean() * 1e3) if ttfts.size else 0.0,
                ttft_p50_ms=(
                    float(np.percentile(ttfts, 50) * 1e3) if ttfts.size else 0.0
                ),
                resume_wall_s=resume_wall,
                n_resumes=len(interrupted),
                avoided=(
                    m1["reprefill_tokens_avoided_total"]
                    - m0["reprefill_tokens_avoided_total"]
                ),
                swap_out=m1["kv_swap_out_bytes_total"],
                swap_in=m1["kv_swap_in_bytes_total"],
                hit_rate=m1["kv_host_hit_rate"],
                prefills=m1["prefills_total"] - m0["prefills_total"],
            )
        finally:
            eng.destroy()

    on = run(host_mb)
    off = run(0.0)
    return dict(
        kvoffload_resume_ttft_ms=on["ttft_mean_ms"],
        kvoffload_resume_ttft_p50_ms=on["ttft_p50_ms"],
        kvoffload_reprefill_resume_ttft_ms=off["ttft_mean_ms"],
        kvoffload_reprefill_resume_ttft_p50_ms=off["ttft_p50_ms"],
        kvoffload_resume_ttft_speedup=(
            off["ttft_mean_ms"] / on["ttft_mean_ms"]
            if on["ttft_mean_ms"] > 0
            else 0.0
        ),
        kvoffload_resumes=on["n_resumes"],
        kvoffload_reprefill_tokens_avoided=on["avoided"],
        kvoffload_baseline_tokens_avoided=off["avoided"],  # must be 0
        kvoffload_swap_out_bytes=on["swap_out"],
        kvoffload_swap_in_bytes=on["swap_in"],
        kvoffload_host_hit_rate=on["hit_rate"],
        kvoffload_resume_prefills=on["prefills"],
        kvoffload_baseline_resume_prefills=off["prefills"],
        kvoffload_host_pool_mb=host_mb,
        kvoffload_sessions=n_sessions,
        kvoffload_prompt_len=prompt_len,
    )


def bench_kvquant(model, n_sessions, prompt_len, new_tokens, max_running,
                  pool_mb=0.5, chunk=None, spec_k=4):
    """Int8 paged KV pool vs fp at FIXED pool MB (ISSUE 11).

    Three legs, every engine paged:

    1. **Capacity + throughput at fixed bytes**: both engines get
       `kv_pool_tokens` derived from the SAME `pool_mb` budget — int8
       fits ~2x the tokens (1 byte/element + one f32 scale per
       (row, head) vs the fp element size), so at a budget sized to
       pressure the fp pool the int8 engine keeps the whole working set
       resident while fp preempts/offloads. Reports pool tokens,
       resident-session capacity, end-to-end tok/s and the
       preemption/swap traffic for both. The int8 engine runs FIRST so
       the warm-XLA-process advantage goes to the fp baseline (same
       conservative ordering as bench_decode_compare).
    2. **Wire bytes**: one session per dtype is prefilled, parked and
       exported — the migration payload (blocks + scales, shipped as-is
       with no requantization) is the /drain and disaggregation unit, so
       its ratio IS the wire saving.
    3. **Drift, measured not assumed**: greedy + sampled streams vs the
       fp oracle (token match fraction, max |logprob delta| over the
       matched prefix) and the speculative accept-rate on an echo
       workload for both dtypes (the accept-rate shift is the honest
       cost speculation pays for quantized verify logits). NOTE the CPU
       smoke runs RANDOM weights, the worst case for drift: near-uniform
       logits flip argmax/categorical under tiny KV perturbations, so
       the match fractions here are a floor — trained checkpoints sit
       far higher (the math-workload reward comparison is the TPU run's
       job).
    """
    import asyncio as _asyncio
    import dataclasses
    import threading as _threading

    import jax

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.models.qwen2 import init_params

    params = init_params(model, jax.random.PRNGKey(0))
    rng = np.random.RandomState(17)
    prompts = [
        rng.randint(1, model.vocab_size, (prompt_len,)).tolist()
        for _ in range(n_sessions)
    ]
    g = GenerationHyperparameters(
        max_new_tokens=new_tokens, temperature=1.0, top_p=1.0
    )
    L = model.num_hidden_layers
    nkv = model.num_key_value_heads
    hd = model.head_dim_

    def bytes_per_token(dt: str) -> int:
        elem = 1 if dt == "int8" else np.dtype(model.dtype).itemsize
        scale = 4 if dt == "int8" else 0
        return 2 * L * nkv * (hd * elem + scale)

    def mk(dt, *, pool_tokens=None, host_mb=0.0, spec="off",
           R=max_running, role="unified"):
        dcfg = JaxDecodeConfig(
            context_length=prompt_len + new_tokens + 128,
            max_running_requests=R,
            new_tokens_per_chunk=chunk or min(128, new_tokens),
            kv_layout="paged",
            kv_dtype=dt,
            kv_pool_tokens=pool_tokens,
            kv_host_pool_mb=host_mb,
            spec_decode=spec,
            spec_k=spec_k,
            role=role,
            dtype=model.dtype,
            kv_cache_dtype=model.dtype,
        )
        eng = JaxDecodeEngine(
            dcfg, InferenceEngineConfig(max_concurrent_rollouts=n_sessions)
        )
        eng.set_model(params, model)
        eng.initialize()
        return eng

    sess_len = prompt_len + new_tokens

    def throughput(dt: str) -> dict:
        pool_tokens = int(pool_mb * 1024 * 1024 // bytes_per_token(dt))
        eng = mk(dt, pool_tokens=pool_tokens, host_mb=max(64.0, pool_mb * 4))
        try:
            eng.prewarm(prompt_len=prompt_len, gconfig=g, include_fork=False)
            t0 = time.perf_counter()
            with ThreadPoolExecutor(max_workers=n_sessions) as pool:
                rs = list(
                    pool.map(
                        lambda p: eng.generate(
                            ModelRequest(input_ids=p, gconfig=g),
                            timeout=1800,
                        ),
                        prompts,
                    )
                )
            wall = time.perf_counter() - t0
            m = eng.get_metrics()
            toks = sum(len(r.output_tokens) for r in rs)
            return dict(
                pool_tokens=m["kv_pool_tokens_total"],
                resident_sessions=m["kv_pool_tokens_total"] // sess_len,
                tok_s=toks / wall if wall > 0 else 0.0,
                preemptions=m["preemptions_total"],
                swap_out=m["kv_swap_out_bytes_total"],
                swap_in=m["kv_swap_in_bytes_total"],
                block_nbytes=m["kv_block_nbytes"],
            )
        finally:
            eng.destroy()

    def migrate_bytes(dt: str) -> int:
        eng = mk(dt, R=2, role="prefill")
        try:
            out = {}

            def _go():
                out["r"] = _asyncio.run(
                    eng.aprefill(
                        ModelRequest(
                            rid="mig", input_ids=prompts[0], gconfig=g
                        )
                    )
                )

            t = _threading.Thread(target=_go, daemon=True)
            t.start()
            t.join(300)
            sess = eng.export_session("mig")
            assert sess is not None
            return sum(
                sess[x].nbytes
                for x in ("k", "v", "ks", "vs")
                if x in sess
            )
        finally:
            eng.destroy()

    def streams(dt: str, gg, n=4) -> list:
        eng = mk(dt, R=max_running)
        try:
            with ThreadPoolExecutor(max_workers=n) as pool:
                return list(
                    pool.map(
                        lambda p: eng.generate(
                            ModelRequest(input_ids=p, gconfig=gg),
                            timeout=1800,
                        ),
                        prompts[:n],
                    )
                )
        finally:
            eng.destroy()

    # spec leg: the echo model of bench_spec_compare (residual-mixing
    # kernels zeroed -> greedy decoding cycles), so drafts actually
    # accept and the dtype's accept-rate shift is observable. Params are
    # rebuilt per call with the echo surgery applied.
    def spec_accept(dt: str) -> float:
        zero = lambda a: a * 0.0  # noqa: E731

        def echoify(layer):
            return {
                **layer,
                "attn": {
                    **layer["attn"],
                    "o_kernel": zero(layer["attn"]["o_kernel"]),
                },
                "mlp": {
                    **layer["mlp"],
                    "down_kernel": zero(layer["mlp"]["down_kernel"]),
                },
            }

        eparams = dict(params)
        if "layers" in eparams:
            eparams["layers"] = echoify(eparams["layers"])
        else:
            for name in list(eparams):
                if name.startswith("layers_"):
                    eparams[name] = echoify(eparams[name])
        dcfg = JaxDecodeConfig(
            context_length=prompt_len + new_tokens + 128,
            max_running_requests=2,
            new_tokens_per_chunk=chunk or min(128, new_tokens),
            kv_layout="paged",
            kv_dtype=dt,
            spec_decode="ngram",
            spec_k=spec_k,
            dtype=model.dtype,
            kv_cache_dtype=model.dtype,
        )
        eng = JaxDecodeEngine(
            dcfg, InferenceEngineConfig(max_concurrent_rollouts=4)
        )
        eng.set_model(eparams, model)
        eng.initialize()
        try:
            gg = dataclasses.replace(g, greedy=True)
            with ThreadPoolExecutor(max_workers=2) as pool:
                list(
                    pool.map(
                        lambda p: eng.generate(
                            ModelRequest(input_ids=p, gconfig=gg),
                            timeout=1800,
                        ),
                        prompts[:2],
                    )
                )
            return float(
                eng.get_metrics()["spec_accepted_per_chunk_mean"]
            )
        finally:
            eng.destroy()

    # int8 first: warm-process advantage goes to the fp baseline
    q = throughput("int8")
    f = throughput("fp")
    mig_i8 = migrate_bytes("int8")
    mig_fp = migrate_bytes("fp")

    drift = {}
    for name, gg in (
        ("greedy", dataclasses.replace(g, greedy=True)),
        ("sampled", dataclasses.replace(g, temperature=0.8, top_p=0.9)),
    ):
        fp_rs = streams("fp", gg)
        i8_rs = streams("int8", gg)
        matched = total = 0
        max_dlp = 0.0
        for rf, ri in zip(fp_rs, i8_rs):
            total += max(len(rf.output_tokens), 1)
            for a, b, la, lb in zip(
                rf.output_tokens, ri.output_tokens,
                rf.output_logprobs, ri.output_logprobs,
            ):
                if a != b:
                    break
                matched += 1
                max_dlp = max(max_dlp, abs(la - lb))
        drift[f"kvquant_{name}_token_match_frac"] = (
            round(matched / total, 4) if total else 0.0
        )
        drift[f"kvquant_{name}_max_logprob_delta_matched"] = round(
            max_dlp, 6
        )
    acc_fp = spec_accept("fp")
    acc_i8 = spec_accept("int8")

    return dict(
        kvquant_pool_mb=pool_mb,
        kvquant_fp_pool_tokens=f["pool_tokens"],
        kvquant_int8_pool_tokens=q["pool_tokens"],
        kvquant_fp_resident_sessions=f["resident_sessions"],
        kvquant_int8_resident_sessions=q["resident_sessions"],
        # headline: resident-session (token) capacity at fixed pool MB
        kvquant_capacity_ratio=(
            round(q["pool_tokens"] / f["pool_tokens"], 4)
            if f["pool_tokens"]
            else 0.0
        ),
        kvquant_fp_tok_s=round(f["tok_s"], 2),
        kvquant_int8_tok_s=round(q["tok_s"], 2),
        kvquant_tok_s_ratio=(
            round(q["tok_s"] / f["tok_s"], 4) if f["tok_s"] > 0 else 0.0
        ),
        kvquant_fp_preemptions=f["preemptions"],
        kvquant_int8_preemptions=q["preemptions"],
        kvquant_fp_swap_out_bytes=f["swap_out"],
        kvquant_int8_swap_out_bytes=q["swap_out"],
        kvquant_fp_block_nbytes=f["block_nbytes"],
        kvquant_int8_block_nbytes=q["block_nbytes"],
        # bytes PER BLOCK moved by any swap/migrate hop: the per-unit
        # saving even when absolute swap traffic differs (int8 usually
        # swaps less because more fits resident)
        kvquant_block_bytes_ratio=round(
            f["block_nbytes"] / q["block_nbytes"], 4
        ),
        kvquant_fp_migrate_bytes=mig_fp,
        kvquant_int8_migrate_bytes=mig_i8,
        kvquant_migrate_bytes_ratio=(
            round(mig_fp / mig_i8, 4) if mig_i8 else 0.0
        ),
        kvquant_fp_spec_accept_per_chunk=round(acc_fp, 4),
        kvquant_int8_spec_accept_per_chunk=round(acc_i8, 4),
        kvquant_spec_accept_shift=round(acc_i8 - acc_fp, 4),
        kvquant_sessions=n_sessions,
        kvquant_prompt_len=prompt_len,
        kvquant_new_tokens=new_tokens,
        **drift,
    )


def bench_wquant(model, n_sessions, prompt_len, new_tokens, max_running,
                 pool_mb=0.5, chunk=None, n_push=3):
    """Int8 weight serving vs fp at a FIXED HBM budget (ISSUE 16).

    Three legs, every engine paged, kv_dtype fp throughout so the weight
    knob is the ONLY difference:

    1. **Capacity + throughput at fixed bytes**: both engines get a KV
       pool budget of `pool_mb` PLUS whatever their weight_dtype left of
       the fp weight footprint — int8 kernels (1 byte + one f32 scale per
       output channel) free ~half the dense-kernel bytes, and at a fixed
       HBM budget that headroom IS extra resident KV. Reports pool
       tokens, resident-session capacity, end-to-end tok/s and decode
       ITL for both. The int8 engine runs FIRST so the warm-XLA-process
       advantage goes to the fp baseline. NOTE the decode speedup claim
       (fused dequant-matmul reads half the weight HBM per chunk) is a
       TPU-bandwidth effect; the CPU smoke's XLA fallback pays dequant
       FLOPs instead, so tok_s_ratio here is a floor.
    2. **Wire bytes + commit pause**: the same full tree is framed
       (pack_buckets) as the producer ships it — bf16-cast fp kernels vs
       producer-quantized int8 + f32 scales — and pushed through
       update_weights_from_tensor n_push times per dtype; reports the
       framed wire bytes and the mean install pause, both ~2x smaller
       quantized.
    3. **Drift, measured not assumed**: greedy + sampled streams vs the
       fp oracle (token match fraction, max |logprob delta| over the
       matched prefix). Same random-weights caveat as bench_kvquant: the
       CPU smoke's near-uniform logits are the drift worst case.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.core.weight_transfer import flatten_named, pack_buckets
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.models.qwen2 import init_params, quantize_weights

    params = init_params(model, jax.random.PRNGKey(0))
    rng = np.random.RandomState(23)
    prompts = [
        rng.randint(1, model.vocab_size, (prompt_len,)).tolist()
        for _ in range(n_sessions)
    ]
    g = GenerationHyperparameters(
        max_new_tokens=new_tokens, temperature=1.0, top_p=1.0
    )
    L = model.num_hidden_layers
    nkv = model.num_key_value_heads
    hd = model.head_dim_
    kv_tok_bytes = 2 * L * nkv * hd * np.dtype(model.dtype).itemsize

    # the wire trees, exactly as the producer ships them: bf16 cast, then
    # (for int8) producer quantization — jax_engine._dcn_payload's order
    bf16 = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        params,
    )
    wire = {
        "fp": flatten_named(bf16),
        "int8": flatten_named(quantize_weights(bf16)),
    }
    weight_bytes = {
        dt: sum(a.nbytes for a in named.values())
        for dt, named in wire.items()
    }
    freed = {
        "fp": 0,
        "int8": weight_bytes["fp"] - weight_bytes["int8"],
    }
    # the ~2x story measured over the kernels that actually quantize
    # (embed/lm_head/norms stay fp and dominate tiny smoke models)
    kern_i8 = kern_fp = 0
    for name, a in wire["int8"].items():
        if name.endswith("/q"):
            base = name[: -len("/q")]
            kern_fp += wire["fp"][base].nbytes
            kern_i8 += a.nbytes + wire["int8"][base + "/scale"].nbytes

    def mk(dt, *, pool_tokens=None, host_mb=0.0, R=max_running):
        dcfg = JaxDecodeConfig(
            context_length=prompt_len + new_tokens + 128,
            max_running_requests=R,
            new_tokens_per_chunk=chunk or min(128, new_tokens),
            kv_layout="paged",
            weight_dtype=dt,
            kv_pool_tokens=pool_tokens,
            kv_host_pool_mb=host_mb,
            dtype=model.dtype,
            kv_cache_dtype=model.dtype,
        )
        eng = JaxDecodeEngine(
            dcfg, InferenceEngineConfig(max_concurrent_rollouts=n_sessions)
        )
        eng.set_model(params, model)
        eng.initialize()
        return eng

    sess_len = prompt_len + new_tokens

    def throughput(dt: str) -> dict:
        # fixed budget: pool_mb + whatever this dtype freed of the fp
        # weight footprint goes to resident KV
        pool_tokens = int(
            (pool_mb * 1024 * 1024 + freed[dt]) // kv_tok_bytes
        )
        eng = mk(dt, pool_tokens=pool_tokens, host_mb=max(64.0, pool_mb * 4))
        try:
            eng.prewarm(prompt_len=prompt_len, gconfig=g, include_fork=False)
            t0 = time.perf_counter()
            with ThreadPoolExecutor(max_workers=n_sessions) as pool:
                rs = list(
                    pool.map(
                        lambda p: eng.generate(
                            ModelRequest(input_ids=p, gconfig=g),
                            timeout=1800,
                        ),
                        prompts,
                    )
                )
            wall = time.perf_counter() - t0
            m = eng.get_metrics()
            toks = sum(len(r.output_tokens) for r in rs)
            return dict(
                pool_tokens=m["kv_pool_tokens_total"],
                resident_sessions=m["kv_pool_tokens_total"] // sess_len,
                tok_s=toks / wall if wall > 0 else 0.0,
                itl_p50_ms=float(m.get("itl_p50_ms", 0.0) or 0.0),
                preemptions=m["preemptions_total"],
            )
        finally:
            eng.destroy()

    def push_pause(dt: str) -> float:
        eng = mk(dt, R=4)
        try:
            # untimed warm push compiles/primes nothing timed below
            eng.update_weights_from_tensor(wire[dt], version=1)
            t0 = time.perf_counter()
            for i in range(n_push):
                eng.update_weights_from_tensor(wire[dt], version=i + 2)
                jax.block_until_ready(eng.params)
            return (time.perf_counter() - t0) / n_push
        finally:
            eng.destroy()

    def streams(dt: str, gg, n=4) -> list:
        eng = mk(dt, R=max_running)
        try:
            with ThreadPoolExecutor(max_workers=n) as pool:
                return list(
                    pool.map(
                        lambda p: eng.generate(
                            ModelRequest(input_ids=p, gconfig=gg),
                            timeout=1800,
                        ),
                        prompts[:n],
                    )
                )
        finally:
            eng.destroy()

    # int8 first: warm-process advantage goes to the fp baseline
    q = throughput("int8")
    f = throughput("fp")
    framed_bytes = {
        dt: sum(len(b) for b in pack_buckets(named, chunk_mb=512))
        for dt, named in wire.items()
    }
    pause_i8 = push_pause("int8")
    pause_fp = push_pause("fp")

    drift = {}
    for name, gg in (
        ("greedy", dataclasses.replace(g, greedy=True)),
        ("sampled", dataclasses.replace(g, temperature=0.8, top_p=0.9)),
    ):
        fp_rs = streams("fp", gg)
        i8_rs = streams("int8", gg)
        matched = total = 0
        max_dlp = 0.0
        for rf, ri in zip(fp_rs, i8_rs):
            total += max(len(rf.output_tokens), 1)
            for a, b, la, lb in zip(
                rf.output_tokens, ri.output_tokens,
                rf.output_logprobs, ri.output_logprobs,
            ):
                if a != b:
                    break
                matched += 1
                max_dlp = max(max_dlp, abs(la - lb))
        drift[f"wquant_{name}_token_match_frac"] = (
            round(matched / total, 4) if total else 0.0
        )
        drift[f"wquant_{name}_max_logprob_delta_matched"] = round(
            max_dlp, 6
        )

    return dict(
        wquant_pool_mb=pool_mb,
        wquant_fp_weight_bytes=weight_bytes["fp"],
        wquant_int8_weight_bytes=weight_bytes["int8"],
        wquant_weight_freed_bytes=freed["int8"],
        wquant_fp_pool_tokens=f["pool_tokens"],
        wquant_int8_pool_tokens=q["pool_tokens"],
        wquant_fp_resident_sessions=f["resident_sessions"],
        wquant_int8_resident_sessions=q["resident_sessions"],
        wquant_capacity_ratio=(
            round(q["pool_tokens"] / f["pool_tokens"], 4)
            if f["pool_tokens"]
            else 0.0
        ),
        wquant_fp_tok_s=round(f["tok_s"], 2),
        wquant_int8_tok_s=round(q["tok_s"], 2),
        wquant_tok_s_ratio=(
            round(q["tok_s"] / f["tok_s"], 4) if f["tok_s"] > 0 else 0.0
        ),
        wquant_fp_itl_p50_ms=round(f["itl_p50_ms"], 3),
        wquant_int8_itl_p50_ms=round(q["itl_p50_ms"], 3),
        wquant_fp_preemptions=f["preemptions"],
        wquant_int8_preemptions=q["preemptions"],
        wquant_fp_wire_bytes=framed_bytes["fp"],
        wquant_int8_wire_bytes=framed_bytes["int8"],
        # headline: framed push bytes, fp over int8 (~2x: int8 data + one
        # f32 scale per output channel vs bf16 kernels)
        wquant_wire_bytes_ratio=(
            round(framed_bytes["fp"] / framed_bytes["int8"], 4)
            if framed_bytes["int8"]
            else 0.0
        ),
        wquant_kernel_wire_bytes_ratio=(
            round(kern_fp / kern_i8, 4) if kern_i8 else 0.0
        ),
        wquant_fp_commit_pause_s=round(pause_fp, 4),
        wquant_int8_commit_pause_s=round(pause_i8, 4),
        wquant_commit_pause_ratio=(
            round(pause_fp / pause_i8, 4) if pause_i8 > 0 else 0.0
        ),
        wquant_sessions=n_sessions,
        wquant_prompt_len=prompt_len,
        wquant_new_tokens=new_tokens,
        **drift,
    )


def bench_fleet(model, n_replicas, n_groups, group_size, prompt_len,
                new_tokens, max_running, chunk=None, turns=2):
    """Fleet router bench (ISSUE 8): prefix-affinity routing vs
    least_requests across in-process decode replicas, plus a mid-trace
    replica kill proving exactly-once failover.

    Trace (identical for both policies, fresh replicas per run): n_groups
    GRPO-style groups of group_size same-prompt members (distinct rids),
    mixed prompt lengths across groups, bursty staggered arrival, and
    `turns` session turns per member (turn k+1 extends turn k's context —
    the multi-turn reuse shape). Prefix affinity should land group members
    and session turns on the replica already holding their donor KV
    (dup-prompt fork / suffix prefill instead of a full prefill), which is
    the mechanism behind the p50 TTFT win; least_requests spreads them
    blindly. The affinity run goes FIRST so any warm-process advantage
    goes to the baseline.

    Failover leg (fresh 2-replica fleet, prefix_affinity): a wave of
    requests starts, one replica is killed mid-trace (HTTP listener down +
    engine aborted), and every request must still complete exactly once —
    the router's health poll requeues the corpse's qids onto the survivor
    and the clients' router-aware retries re-send with the same delivery
    id (xid), which the servers' idempotency table deduplicates. Reported:
    recovery time (kill -> last affected completion), requests lost (must
    be 0), router requeues, and a direct dedup probe (two concurrent
    /generate with one xid -> one generation)."""
    import asyncio
    import threading
    import uuid as _uuid

    import jax

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
        RouterConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.launcher.decode_server import DecodeServer
    from areal_tpu.launcher.router import DecodeRouter
    from areal_tpu.utils import name_resolve
    from areal_tpu.utils.http import arequest_with_retry, close_current_session
    from areal_tpu.models.qwen2 import init_params

    name_resolve.reconfigure(name_resolve.NameResolveConfig(type="memory"))
    params = init_params(model, jax.random.PRNGKey(0))
    rng = np.random.RandomState(23)
    plens = [int(prompt_len * f) for f in (1.0, 0.75, 1.25, 0.5)]
    ctx = int(prompt_len * 1.25) + turns * (new_tokens + 8) + 128
    gcfg = GenerationHyperparameters(
        max_new_tokens=new_tokens, temperature=1.0, top_p=1.0
    )
    group_prompts = [
        rng.randint(1, model.vocab_size, (plens[g % len(plens)],)).tolist()
        for g in range(n_groups)
    ]

    def _http_get(addr, ep):
        async def _g():
            try:
                return await arequest_with_retry(
                    addr, ep, method="GET", max_retries=1, timeout=10
                )
            finally:
                await close_current_session()

        return asyncio.run(_g())

    class _Replica:
        """One decode engine + HTTP server on a private loop thread."""

        def __init__(self, warm_plen):
            dcfg = JaxDecodeConfig(
                context_length=ctx,
                max_running_requests=max_running,
                new_tokens_per_chunk=chunk or min(128, new_tokens),
                dtype=model.dtype,
                kv_cache_dtype=model.dtype,
            )
            self.engine = JaxDecodeEngine(dcfg, InferenceEngineConfig())
            self.engine.set_model(params, model)
            self.engine.initialize()
            self.engine.prewarm(prompt_len=warm_plen, gconfig=gcfg)
            self.server = DecodeServer(
                JaxDecodeConfig(), engine=self.engine, shutdown_grace=0.5
            )
            self.addr = None
            self._loop = None
            self._ready = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
            assert self._ready.wait(60), "fleet replica failed to start"

        def _run(self):
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                self.addr = await self.server.start(host="127.0.0.1", port=0)
                self._ready.set()

            self._loop.run_until_complete(_start())
            self._loop.run_forever()

        def kill(self):
            """Die like a crashed replica: listener down (in-flight
            handlers cancelled after shutdown_grace), engine aborted."""
            asyncio.run_coroutine_threadsafe(
                self.server.stop(), self._loop
            ).result(30)
            self.engine.pause_generation()
            self.engine.abort_all()

        def stop(self, destroy=True):
            # stop the server first, THEN the loop: a coroutine that stops
            # its own loop strands run_coroutine_threadsafe's completion
            # callback (the future never resolves)
            try:
                asyncio.run_coroutine_threadsafe(
                    self.server.stop(), self._loop
                ).result(30)
            except Exception as e:  # noqa: BLE001 — already killed
                print(f"[fleet] replica stop: {e!r}", file=sys.stderr)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)
            if destroy:
                self.engine.destroy()

    class _RouterThread:
        def __init__(self, policy, servers, exp, trial):
            self.router = DecodeRouter(
                exp,
                trial,
                servers,
                config=RouterConfig(
                    schedule_policy=policy,
                    health_poll_interval=0.25,
                    dead_after_failures=2,
                    queue_timeout_s=30.0,
                ),
            )
            self.addr = None
            self._loop = None
            self._ready = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
            assert self._ready.wait(30), "fleet router failed to start"

        def _run(self):
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                self.addr = await self.router.start("127.0.0.1", 0)
                self._ready.set()

            self._loop.run_until_complete(_start())
            self._loop.run_forever()

        def stop(self):
            # two-step (see _Replica.stop): never loop.stop() from inside
            # the awaited coroutine
            asyncio.run_coroutine_threadsafe(
                self.router.stop(), self._loop
            ).result(30)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)

    def _client(exp, trial):
        c = RemoteInfEngine(
            InferenceEngineConfig(
                experiment_name=exp,
                trial_name=trial,
                request_timeout=600,
                request_retries=1,
                fleet_failover_retries=3,
            )
        )
        return c

    def run_policy(policy):
        exp, trial = "benchfleet", f"{policy}-{_uuid.uuid4().hex[:6]}"
        replicas = [_Replica(min(plens)) for _ in range(n_replicas)]
        addrs = [r.addr for r in replicas]
        rt = _RouterThread(policy, addrs, exp, trial)
        client = _client(exp, trial)
        client.addresses = list(addrs)
        ttfts, itls, stats = [], [], {}
        try:
            time.sleep(0.6)  # one poll round: pressure snapshots exist
            # hit-rate baseline AFTER prewarm: its warmup prefills must not
            # dilute the trace's prefix_cache_hit_rate
            m0s = [r.engine.get_metrics() for r in replicas]

            async def member(g, m):
                rid = f"g{g}-m{m}-{_uuid.uuid4().hex[:6]}"
                ids = list(group_prompts[g])
                for _t in range(turns):
                    r = await client.agenerate(
                        ModelRequest(rid=rid, input_ids=ids, gconfig=gcfg)
                    )
                    ttfts.append(r.ttft)
                    if len(r.output_tokens) > 1:
                        itls.append(
                            (r.latency - r.ttft) / (len(r.output_tokens) - 1)
                        )
                    # next turn extends this turn's context (session reuse)
                    ids = ids + list(r.output_tokens) + [7, 11, 13, 17]

            async def group(g):
                # bursty arrival: groups land in waves
                await asyncio.sleep((g % 3) * 0.15)
                await asyncio.gather(
                    *[member(g, m) for m in range(group_size)]
                )

            async def drive():
                try:
                    await asyncio.gather(*[group(g) for g in range(n_groups)])
                finally:
                    await close_current_session()

            t0 = time.perf_counter()
            asyncio.run(drive())
            wall = time.perf_counter() - t0
            hits = tot = 0
            for r, m0 in zip(replicas, m0s):
                m = r.engine.get_metrics()
                h = (
                    m["prefix_forks_total"]
                    - m0["prefix_forks_total"]
                    + m["prefix_inplace_total"]
                    - m0["prefix_inplace_total"]
                    + m["suffix_prefills_total"]
                    - m0["suffix_prefills_total"]
                )
                hits += h
                tot += h + m["prefills_total"] - m0["prefills_total"]
            rm = _http_get(rt.addr, "/metrics")
            tarr = np.asarray(ttfts, dtype=np.float64) * 1e3
            iarr = np.asarray(itls, dtype=np.float64) * 1e3
            stats = dict(
                ttft_p50_ms=float(np.percentile(tarr, 50)),
                ttft_p99_ms=float(np.percentile(tarr, 99)),
                itl_p50_ms=float(np.percentile(iarr, 50)) if iarr.size else 0.0,
                itl_p99_ms=float(np.percentile(iarr, 99)) if iarr.size else 0.0,
                prefix_hit_rate=hits / tot if tot else 0.0,
                router_affinity_hit_rate=rm.get("affinity_hit_rate", 0.0),
                wall_s=wall,
                n_requests=len(ttfts),
            )
        finally:
            rt.stop()
            for r in replicas:
                r.stop()
        return stats

    def run_failover():
        exp, trial = "benchfleet", f"failover-{_uuid.uuid4().hex[:6]}"
        replicas = [_Replica(min(plens)) for _ in range(2)]
        addrs = [r.addr for r in replicas]
        rt = _RouterThread("prefix_affinity", addrs, exp, trial)
        client = _client(exp, trial)
        client.addresses = list(addrs)
        n_reqs = n_groups * group_size
        done_t: dict[str, float] = {}
        results: dict[str, object] = {}
        try:
            time.sleep(0.6)

            async def one(g, m):
                rid = f"fo-g{g}-m{m}"
                r = await client.agenerate(
                    ModelRequest(
                        rid=rid, input_ids=group_prompts[g], gconfig=gcfg
                    )
                )
                results[rid] = r
                done_t[rid] = time.perf_counter()

            kill_box = {}

            async def killer():
                # kill mid-trace: once the fleet has emitted ~20% of the
                # expected tokens (but before everything finishes)
                target = 0.2 * n_reqs * new_tokens
                deadline = time.perf_counter() + 120
                while time.perf_counter() < deadline:
                    emitted = sum(
                        r.engine.get_metrics()["generated_tokens_total"]
                        for r in replicas
                    )
                    # fire mid-trace: enough tokens out, but never wait
                    # past half the wave completing
                    if emitted >= target or len(done_t) >= max(1, n_reqs // 2):
                        break
                    await asyncio.sleep(0.02)
                kill_box["t"] = time.perf_counter()
                await asyncio.get_running_loop().run_in_executor(
                    None, replicas[0].kill
                )

            async def drive():
                try:
                    tasks = [
                        asyncio.create_task(one(g, m))
                        for g in range(n_groups)
                        for m in range(group_size)
                    ]
                    k = asyncio.create_task(killer())
                    await asyncio.gather(*tasks)
                    await k
                finally:
                    await close_current_session()

            asyncio.run(drive())
            lost = sum(
                1
                for r in results.values()
                if len(r.output_tokens) != new_tokens
            ) + (n_reqs - len(results))
            recovery = (
                max(
                    (t for t in done_t.values() if t > kill_box["t"]),
                    default=kill_box["t"],
                )
                - kill_box["t"]
            )
            rm = _http_get(rt.addr, "/metrics")

            # direct rid-dedup probe on the survivor: two concurrent
            # /generate with one xid must produce ONE generation
            sm0 = replicas[1].engine.get_metrics()
            xid = f"dedup-{_uuid.uuid4().hex[:6]}"
            payload = dict(
                rid=xid,
                input_ids=group_prompts[0][:32],
                gconfig=dict(max_new_tokens=4, temperature=1.0),
                xid=xid,
            )

            async def probe():
                try:
                    return await asyncio.gather(
                        *[
                            arequest_with_retry(
                                replicas[1].addr, "/generate",
                                payload=payload, max_retries=1, timeout=120,
                            )
                            for _ in range(2)
                        ]
                    )
                finally:
                    await close_current_session()

            p1, p2 = asyncio.run(probe())
            sm1 = replicas[1].engine.get_metrics()
            dedup_ok = int(
                p1["output_tokens"] == p2["output_tokens"]
                and _http_get(replicas[1].addr, "/metrics")["idem_hits_total"]
                >= 1
            )
            return dict(
                recovery_s=recovery,
                requests=n_reqs,
                completed=len(results),
                lost=lost,
                router_requeues=rm.get("requeues_total", 0),
                router_failovers=rm.get("failovers_total", 0),
                dedup_probe_ok=dedup_ok,
                survivor_prefills=sm1["prefills_total"] - sm0["prefills_total"],
            )
        finally:
            rt.stop()
            replicas[0].stop(destroy=True)
            replicas[1].stop()

    aff = run_policy("prefix_affinity")
    lr = run_policy("least_requests")
    fo = run_failover()
    return dict(
        fleet_replicas=n_replicas,
        fleet_groups=n_groups,
        fleet_group_size=group_size,
        fleet_turns=turns,
        fleet_affinity_ttft_p50_ms=aff["ttft_p50_ms"],
        fleet_affinity_ttft_p99_ms=aff["ttft_p99_ms"],
        fleet_affinity_itl_p50_ms=aff["itl_p50_ms"],
        fleet_affinity_itl_p99_ms=aff["itl_p99_ms"],
        fleet_affinity_prefix_hit_rate=aff["prefix_hit_rate"],
        fleet_affinity_router_hit_rate=aff["router_affinity_hit_rate"],
        fleet_affinity_wall_s=aff["wall_s"],
        fleet_leastreq_ttft_p50_ms=lr["ttft_p50_ms"],
        fleet_leastreq_ttft_p99_ms=lr["ttft_p99_ms"],
        fleet_leastreq_itl_p50_ms=lr["itl_p50_ms"],
        fleet_leastreq_itl_p99_ms=lr["itl_p99_ms"],
        fleet_leastreq_prefix_hit_rate=lr["prefix_hit_rate"],
        fleet_leastreq_wall_s=lr["wall_s"],
        fleet_affinity_ttft_p50_speedup=(
            lr["ttft_p50_ms"] / aff["ttft_p50_ms"]
            if aff["ttft_p50_ms"] > 0
            else 0.0
        ),
        fleet_requests_per_policy=aff["n_requests"],
        fleet_failover_recovery_s=fo["recovery_s"],
        fleet_failover_requests=fo["requests"],
        fleet_failover_completed=fo["completed"],
        fleet_failover_lost=fo["lost"],
        fleet_failover_router_requeues=fo["router_requeues"],
        fleet_failover_router_failovers=fo["router_failovers"],
        fleet_dedup_probe_ok=fo["dedup_probe_ok"],
    )


def bench_disagg(model, n_decode_reqs, n_prefill_reqs, prompt_short,
                 prompt_long, new_tokens, max_running, chunk=None,
                 drain_sessions=4, drain_prompt=96, drain_tokens=48):
    """Disaggregated prefill/decode bench (ISSUE 10).

    Leg 1 — head-of-line ITL: a mixed trace of decode-heavy requests
    (short prompt, long generation) and prefill-heavy requests (long
    prompt, tiny generation) replayed against two equal-size fleets:

      * DISAGG: 1 prefill-role + 1 decode-role replica. The router sends
        every prompt to the prefill replica (prefix affinity), which
        streams the finished KV server->server to the decode replica
        (host-tier import); the decode replica's scheduler NEVER runs a
        transformer prefill between decode chunks.
      * UNIFIED: 2 unified replicas (the same router, classic policy).
        Every long prefill runs inside some replica's scheduler loop,
        stalling every resident decode slot for its duration — the
        head-of-line hit this bench measures.

    Reported: p50/p99 of per-request mean ITL (client-observed wall,
    which includes the stalls the engine's device-only ITL hides) for
    the decode-heavy requests, with the disagg fleet run FIRST so any
    process-warm advantage goes to the unified baseline. Asserted: every
    request completes exactly once with its full token budget on both
    fleets (no lost/duplicated requests).

    Leg 2 — drain migration, per kv layout (paged AND workspace), with
    half the sessions greedy and half sampled: sessions generate
    mid-stream on replica A, `/drain` parks them (clients see
    stop_reason="interrupt") and streams every parked session to
    replica B, and the resumes run on B. Asserted: B runs ZERO prompt
    prefills (every resume is a host-tier promotion of the migrated
    blocks), and partial+resumed streams are BIT-IDENTICAL to a
    never-interrupted oracle engine (tokens AND logprobs, greedy and
    sampled)."""
    import asyncio
    import threading
    import uuid as _uuid

    import jax

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
        RouterConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.launcher.decode_server import DecodeServer
    from areal_tpu.launcher.router import DecodeRouter
    from areal_tpu.utils import name_resolve
    from areal_tpu.utils.http import arequest_with_retry, close_current_session
    from areal_tpu.models.qwen2 import init_params

    name_resolve.reconfigure(name_resolve.NameResolveConfig(type="memory"))
    params = init_params(model, jax.random.PRNGKey(0))
    rng = np.random.RandomState(31)
    n_chunk = chunk or min(128, new_tokens)
    ctx = prompt_long + max(new_tokens, 16) + n_chunk + 128
    decode_prompts = [
        rng.randint(1, model.vocab_size, (prompt_short,)).tolist()
        for _ in range(n_decode_reqs)
    ]
    prefill_prompts = [
        rng.randint(1, model.vocab_size, (prompt_long,)).tolist()
        for _ in range(n_prefill_reqs)
    ]

    def _post(addr, ep, payload, timeout=120):
        async def _p():
            try:
                return await arequest_with_retry(
                    addr, ep, payload=payload, max_retries=1, timeout=timeout
                )
            finally:
                await close_current_session()

        return asyncio.run(_p())

    class _Replica:
        def __init__(self, role="unified", prewarm_plans=(), kv_layout="paged",
                     host_mb=0.0, seed=1):
            dcfg = JaxDecodeConfig(
                context_length=ctx,
                max_running_requests=max_running,
                new_tokens_per_chunk=n_chunk,
                dtype=model.dtype,
                kv_cache_dtype=model.dtype,
                kv_layout=kv_layout,
                kv_host_pool_mb=host_mb,
                role=role,
                kv_migrate_chunk_mb=8.0,
                random_seed=seed,
            )
            self.engine = JaxDecodeEngine(dcfg, InferenceEngineConfig())
            self.engine.set_model(params, model)
            self.engine.initialize()
            # warm EVERY prompt bucket the trace will hit (short decode
            # prompts AND long prefill prompts) on every replica of both
            # fleets, so the timed window measures scheduling, not
            # first-compiles
            for plen, wcfg in prewarm_plans:
                self.engine.prewarm(prompt_len=plen, gconfig=wcfg)
            # pass the REAL engine config so /health advertises the role
            self.server = DecodeServer(dcfg, engine=self.engine,
                                       shutdown_grace=0.5)
            self.addr = None
            self._loop = None
            self._ready = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
            assert self._ready.wait(60), "disagg replica failed to start"

        def _run(self):
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                self.addr = await self.server.start(host="127.0.0.1", port=0)
                self._ready.set()

            self._loop.run_until_complete(_start())
            self._loop.run_forever()

        def stop(self):
            try:
                asyncio.run_coroutine_threadsafe(
                    self.server.stop(), self._loop
                ).result(30)
            except Exception as e:  # noqa: BLE001 — already down
                print(f"[disagg] replica stop: {e!r}", file=sys.stderr)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)
            self.engine.destroy()

    class _RouterThread:
        def __init__(self, servers, exp, trial):
            self.router = DecodeRouter(
                exp,
                trial,
                servers,
                config=RouterConfig(
                    schedule_policy="prefix_affinity",
                    health_poll_interval=0.25,
                    queue_timeout_s=60.0,
                ),
            )
            self.addr = None
            self._loop = None
            self._ready = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
            assert self._ready.wait(30), "disagg router failed to start"

        def _run(self):
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                self.addr = await self.router.start("127.0.0.1", 0)
                self._ready.set()

            self._loop.run_until_complete(_start())
            self._loop.run_forever()

        def stop(self):
            asyncio.run_coroutine_threadsafe(
                self.router.stop(), self._loop
            ).result(30)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)

    gcfg_decode = GenerationHyperparameters(
        max_new_tokens=new_tokens, temperature=1.0, top_p=1.0
    )
    gcfg_prefill = GenerationHyperparameters(
        max_new_tokens=8, temperature=1.0, top_p=1.0
    )
    # several sequential long prefills per worker keep prefill pressure on
    # for the WHOLE decode window (one burst would be over before the
    # decode streams finish on small configs)
    prefill_turns = 3

    def run_itl_leg(label, replicas):
        exp, trial = "benchdisagg", f"{label}-{_uuid.uuid4().hex[:6]}"
        addrs = [r.addr for r in replicas]
        rt = _RouterThread(addrs, exp, trial)
        client = RemoteInfEngine(
            InferenceEngineConfig(
                experiment_name=exp,
                trial_name=trial,
                request_timeout=600,
                request_retries=1,
            )
        )
        client.addresses = list(addrs)
        results: dict[str, object] = {}
        try:
            time.sleep(0.8)  # >= one poll round: roles + pressure known
            for r in replicas:
                # percentiles below must describe the TRACE, not prewarm
                r.engine.reset_timing_windows()
            m0s = [r.engine.get_metrics() for r in replicas]

            async def decode_req(i):
                rid = f"d{i}"
                r = await client.agenerate(
                    ModelRequest(
                        rid=rid, input_ids=decode_prompts[i],
                        gconfig=gcfg_decode,
                    )
                )
                assert rid not in results, f"duplicate completion {rid}"
                results[rid] = r

            async def prefill_worker(i):
                # continuous long-prefill pressure landing MID-decode:
                # the head-of-line shape a co-located scheduler serializes
                # in front of every resident decode slot's next chunk
                await asyncio.sleep(0.02 * i)
                for t in range(prefill_turns):
                    rid = f"p{i}-t{t}"
                    r = await client.agenerate(
                        ModelRequest(
                            rid=rid, input_ids=prefill_prompts[i],
                            gconfig=gcfg_prefill,
                        )
                    )
                    assert rid not in results, f"duplicate completion {rid}"
                    results[rid] = r
                    await asyncio.sleep(0.01)

            async def drive():
                try:
                    await asyncio.gather(
                        *[decode_req(i) for i in range(n_decode_reqs)],
                        *[prefill_worker(i) for i in range(n_prefill_reqs)],
                    )
                finally:
                    await close_current_session()

            t0 = time.perf_counter()
            asyncio.run(drive())
            wall = time.perf_counter() - t0
        finally:
            rt.stop()
        # exactly-once: every request completed once with its full budget
        n_expected = n_decode_reqs + n_prefill_reqs * prefill_turns
        assert len(results) == n_expected, f"{label}: lost requests"
        for i in range(n_decode_reqs):
            r = results[f"d{i}"]
            assert len(r.output_tokens) == new_tokens, (
                f"{label}: d{i} truncated ({len(r.output_tokens)})"
            )
        # WALL inter-token latency from the engines that actually decode
        # (ready→ready per emitted token, so the inter-chunk host gap —
        # where a co-located scheduler serializes long prefills — counts;
        # client-side latency/ttft can't see this: the remote protocol is
        # not streaming, so TTFT ≈ latency there). In the disagg fleet
        # every decode chunk runs on the decode-role replica; in the
        # unified fleet both replicas decode, so their windows merge.
        decoding = [
            r for r in replicas
            if r.engine.config.role != "prefill"
        ]
        ms = [r.engine.get_metrics() for r in decoding]
        import itertools as _it

        samples = np.asarray(
            list(
                _it.chain.from_iterable(
                    r.engine._chunk_wall_itl_ms for r in decoding
                )
            ),
            dtype=np.float64,
        )
        return dict(
            itl_p50_ms=(
                float(np.percentile(samples, 50)) if samples.size else 0.0
            ),
            itl_p99_ms=(
                float(np.percentile(samples, 99)) if samples.size else 0.0
            ),
            itl_dev_p99_ms=max(m["itl_p99_ms"] for m in ms),
            wall_s=wall,
            m0s=m0s,
        )

    # -- leg 1: disagg FIRST (warm advantage to the unified baseline) ---
    warm_plans = (
        (prompt_short, gcfg_decode),
        (prompt_long, gcfg_prefill),
    )
    dis_replicas = [
        _Replica(role="prefill", prewarm_plans=warm_plans),
        _Replica(role="decode", prewarm_plans=warm_plans),
    ]
    try:
        disagg = run_itl_leg("disagg", dis_replicas)
        # post-prewarm deltas: what the TRACE did, not the warmup
        dm, d0 = dis_replicas[1].engine.get_metrics(), disagg["m0s"][1]
        pm, p0 = dis_replicas[0].engine.get_metrics(), disagg["m0s"][0]
        decode_trace_prefills = dm["prefills_total"] - d0["prefills_total"]
        disagg_detail = dict(
            decode_replica_prefills=decode_trace_prefills,
            decode_replica_host_hits=(
                dm["kv_host_hits_total"] - d0["kv_host_hits_total"]
            ),
            decode_replica_migrated_in=(
                dm["kv_migrated_in_sessions_total"]
                - d0["kv_migrated_in_sessions_total"]
            ),
            decode_ttft_transfer_p99_ms=dm["ttft_transfer_p99_ms"],
            prefill_replica_prefills=(
                pm["prefills_total"] - p0["prefills_total"]
            ),
            prefill_ttft_prefill_p99_ms=pm["ttft_prefill_p99_ms"],
        )
        # the mechanism itself: the decode replica's scheduler never ran a
        # transformer prompt prefill during the trace — every admission
        # was a host-tier promotion of migrated blocks
        assert decode_trace_prefills == 0, (
            f"decode replica ran {decode_trace_prefills} prefills — "
            "the prefill handoff is not covering the trace"
        )
    finally:
        for r in dis_replicas:
            r.stop()
    uni_replicas = [
        _Replica(role="unified", prewarm_plans=warm_plans) for _ in range(2)
    ]
    try:
        unified = run_itl_leg("unified", uni_replicas)
    finally:
        for r in uni_replicas:
            r.stop()

    # -- leg 2: drain migration, both kv layouts, greedy + sampled ------
    def run_drain(kv_layout):
        greedy = GenerationHyperparameters(
            max_new_tokens=drain_tokens, greedy=True
        )
        sampled = GenerationHyperparameters(
            max_new_tokens=drain_tokens, temperature=0.8, top_p=0.9
        )
        gcfgs = [
            greedy if i % 2 == 0 else sampled for i in range(drain_sessions)
        ]
        drng = np.random.RandomState(77)
        prompts = [
            drng.randint(1, model.vocab_size, (drain_prompt,)).tolist()
            for _ in range(drain_sessions)
        ]
        # oracle: never-interrupted runs, same seed + admission order
        oracle_eng = JaxDecodeEngine(
            JaxDecodeConfig(
                context_length=ctx,
                max_running_requests=max_running,
                new_tokens_per_chunk=n_chunk,
                dtype=model.dtype,
                kv_cache_dtype=model.dtype,
                kv_layout=kv_layout,
                random_seed=7,
            ),
            InferenceEngineConfig(),
        )
        oracle_eng.set_model(params, model)
        oracle_eng.initialize()
        oracle = {}
        try:
            for i in range(drain_sessions):
                r = oracle_eng.generate(
                    ModelRequest(
                        rid=f"s{i}", input_ids=prompts[i], gconfig=gcfgs[i]
                    ),
                    timeout=300,
                )
                oracle[f"s{i}"] = (list(r.output_tokens), list(r.output_logprobs))
        finally:
            oracle_eng.destroy()

        a = _Replica(role="unified", kv_layout=kv_layout, host_mb=256.0,
                     seed=7)
        b = _Replica(role="unified", kv_layout=kv_layout, seed=7)
        try:
            partials: dict[str, dict] = {}
            lock = threading.Lock()

            def submit(i):
                out = _post(
                    a.addr, "/generate",
                    dict(
                        rid=f"s{i}",
                        input_ids=prompts[i],
                        gconfig=dict(
                            max_new_tokens=gcfgs[i].max_new_tokens,
                            greedy=gcfgs[i].greedy,
                            temperature=gcfgs[i].temperature,
                            top_p=gcfgs[i].top_p,
                        ),
                    ),
                    timeout=300,
                )
                with lock:
                    partials[f"s{i}"] = out

            threads = []
            for i in range(drain_sessions):
                t = threading.Thread(target=submit, args=(i,), daemon=True)
                t.start()
                threads.append(t)
                # sequential-enough arrival: admission order (and so the
                # sampling base keys) matches the oracle's
                time.sleep(0.15)
            # drain once every session is admitted and mid-stream
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                m = a.engine.get_metrics()
                if (
                    m["running_requests"] >= drain_sessions
                    and m["generated_tokens_total"] >= drain_sessions
                ):
                    break
                time.sleep(0.02)
            drain_out = _post(
                a.addr, "/drain", {"targets": [b.addr]}, timeout=300
            )
            for t in threads:
                t.join(timeout=120)
            assert len(partials) == drain_sessions, "lost interrupt responses"
            b0 = b.engine.get_metrics()
            full: dict[str, tuple] = {}
            for i in range(drain_sessions):
                rid = f"s{i}"
                part = partials[rid]
                assert part["stop_reason"] == "interrupt", part["stop_reason"]
                resume_ids = prompts[i] + [int(t) for t in part["output_tokens"]]
                left = gcfgs[i].max_new_tokens - len(part["output_tokens"])
                out = _post(
                    b.addr, "/generate",
                    dict(
                        rid=rid,
                        input_ids=resume_ids,
                        gconfig=dict(
                            max_new_tokens=left,
                            greedy=gcfgs[i].greedy,
                            temperature=gcfgs[i].temperature,
                            top_p=gcfgs[i].top_p,
                        ),
                    ),
                    timeout=300,
                )
                full[rid] = (
                    [int(t) for t in part["output_tokens"]]
                    + [int(t) for t in out["output_tokens"]],
                    [float(x) for x in part["output_logprobs"]]
                    + [float(x) for x in out["output_logprobs"]],
                )
            b1 = b.engine.get_metrics()
            mismatched = sum(
                1
                for rid, (toks, lps) in full.items()
                if toks != oracle[rid][0] or lps != oracle[rid][1]
            )
            reprefills = b1["prefills_total"] - b0["prefills_total"]
            assert drain_out["drained"] == drain_sessions, drain_out
            assert drain_out["failed"] == 0, drain_out
            assert reprefills == 0, (
                f"{kv_layout}: {reprefills} resumes paid a re-prefill"
            )
            assert mismatched == 0, (
                f"{kv_layout}: {mismatched} drained streams diverged"
            )
            return dict(
                drained=drain_out["drained"],
                kv_bytes=drain_out["bytes"],
                resume_reprefills=reprefills,
                resume_host_hits=(
                    b1["kv_host_hits_total"] - b0["kv_host_hits_total"]
                ),
                reprefill_tokens_avoided=(
                    b1["reprefill_tokens_avoided_total"]
                    - b0["reprefill_tokens_avoided_total"]
                ),
                streams_bitidentical=int(mismatched == 0),
            )
        finally:
            a.stop()
            b.stop()

    drain_paged = run_drain("paged")
    drain_ws = run_drain("workspace")

    return dict(
        disagg_decode_reqs=n_decode_reqs,
        disagg_prefill_reqs=n_prefill_reqs,
        disagg_itl_p50_ms=disagg["itl_p50_ms"],
        disagg_itl_p99_ms=disagg["itl_p99_ms"],
        disagg_itl_dev_p99_ms=disagg["itl_dev_p99_ms"],
        disagg_wall_s=disagg["wall_s"],
        unified_itl_p50_ms=unified["itl_p50_ms"],
        unified_itl_p99_ms=unified["itl_p99_ms"],
        unified_itl_dev_p99_ms=unified["itl_dev_p99_ms"],
        unified_wall_s=unified["wall_s"],
        disagg_decode_itl_p99_speedup=(
            unified["itl_p99_ms"] / disagg["itl_p99_ms"]
            if disagg["itl_p99_ms"] > 0
            else 0.0
        ),
        disagg_decode_itl_p50_speedup=(
            unified["itl_p50_ms"] / disagg["itl_p50_ms"]
            if disagg["itl_p50_ms"] > 0
            else 0.0
        ),
        **{f"disagg_{k}": v for k, v in disagg_detail.items()},
        **{f"disagg_drain_paged_{k}": v for k, v in drain_paged.items()},
        **{f"disagg_drain_ws_{k}": v for k, v in drain_ws.items()},
    )


def bench_kvfabric(model, prompt_len, head_len, tail_len, new_tokens,
                   n_dedup, max_running, chunk=8, n_ttft_reps=3,
                   page_size=None, attn_impl=None, seed=47):
    """Fleet KV fabric bench (ISSUE 17).

    Leg 1 — INTRA-REPLICA DEDUP: `n_dedup` requests share a `head_len`
    head but carry DIVERGENT `tail_len` tails, so the rid/tuple-prefix
    donor paths all miss (request i is never a string-prefix of request
    j). The content-addressed block index still satisfies the shared
    head from whichever resident session produced it first. Asserted:
    the fabric engine's streams are token-identical (greedy) to a
    fabric-off oracle that pays `n_dedup` full prefills, with
    `n_dedup-1` local fabric hits and the avoided-token counter covering
    the shared heads.

    Leg 2 — REMOTE FETCH + WARM START: replica A is hot (several
    resident prompts), replica B is cold. One request lands on B with
    the router-style `kv_fabric` hint naming A; B pulls the run over
    /kv_fetch -> /kv_recv -> /kv_commit and serves with a suffix prefill
    (remote attribution, fetched bytes counted as fabric — not
    migration — traffic). B then /warm_start's its pool from A and the
    timed comparison is TTFT (wall of a 1-new-token /generate) of
    warm-started prompts vs same-length fresh prompts: the headline
    `kvfabric_warm_ttft_speedup`. Compile costs are paid by untimed
    warm-up requests on BOTH paths; timed reps report the median.

    Leg 3 — WEIGHT FLIP MID-TRACE: B installs a new weight version
    (parked-prefix invalidation + version bump — the real install
    sequence). A push of A's old-version run is rejected by the
    version-salted content keys (honest miss, 0 stale-block serves) and
    the next /generate on B pays an honest full prefill while staying
    bit-identical to the oracle."""
    import asyncio
    import threading

    import jax

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.core import kv_fabric
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.launcher.decode_server import DecodeServer
    from areal_tpu.utils.http import arequest_with_retry, close_current_session
    from areal_tpu.models.qwen2 import init_params

    params = init_params(model, jax.random.PRNGKey(0))
    rng = np.random.RandomState(seed)
    ctx = prompt_len + new_tokens + chunk + 128
    gcfg = GenerationHyperparameters(max_new_tokens=new_tokens, greedy=True)

    def mk_engine(fabric=True):
        extra = {}
        if page_size is not None:
            extra["page_size"] = page_size
        if attn_impl is not None:
            extra["paged_attn_impl"] = attn_impl
        dcfg = JaxDecodeConfig(
            context_length=ctx,
            max_running_requests=max_running,
            new_tokens_per_chunk=chunk,
            dtype=model.dtype,
            kv_cache_dtype=model.dtype,
            kv_layout="paged",
            kv_fabric=fabric,
            kv_migrate_chunk_mb=1.0,
            random_seed=1,
            **extra,
        )
        eng = JaxDecodeEngine(dcfg, InferenceEngineConfig())
        eng.set_model(params, model)
        eng.initialize()
        return eng, dcfg

    def _tokens(n):
        return rng.randint(1, model.vocab_size, (n,)).tolist()

    def _chain_of(eng, tokens):
        return kv_fabric.chain_keys(
            tokens,
            eng._alloc.block_size,
            int(eng._version),
            str(eng.config.kv_dtype),
        )

    # ---- leg 1: intra-replica dedup, fabric vs fabric-off oracle ------
    head = _tokens(head_len)
    dedup_prompts = [head + _tokens(tail_len) for _ in range(n_dedup)]

    def run_dedup(fabric):
        eng, _ = mk_engine(fabric=fabric)
        try:
            streams = []
            t0 = time.perf_counter()
            for i, p in enumerate(dedup_prompts):
                r = eng.generate(
                    ModelRequest(rid=f"dd{i}", input_ids=p, gconfig=gcfg),
                    timeout=300,
                )
                streams.append(list(r.output_tokens))
            wall = time.perf_counter() - t0
            return streams, eng.get_metrics(), wall
        finally:
            eng.destroy()

    oracle_streams, oracle_m, oracle_wall = run_dedup(False)
    fabric_streams, fabric_m, fabric_wall = run_dedup(True)
    assert oracle_m["prefills_total"] == n_dedup, (
        "oracle reused the diverging-tail prompts without the fabric: "
        f"{oracle_m['prefills_total']} prefills for {n_dedup} requests"
    )
    assert fabric_streams == oracle_streams, (
        "fabric-deduped streams diverged from the re-prefill oracle"
    )
    dedup_hits = fabric_m["kv_fabric_local_hits_total"]
    dedup_avoided = fabric_m["kv_fabric_local_tokens_avoided_total"]
    assert dedup_hits >= n_dedup - 1, (
        f"only {dedup_hits} local fabric hits for {n_dedup} shared-head "
        "requests"
    )
    assert dedup_avoided >= (n_dedup - 1) * 64, (
        f"local dedup avoided only {dedup_avoided} tokens"
    )

    # ---- legs 2+3: two replicas on the wire ---------------------------
    n_warm = n_ttft_reps + 1  # one untimed warm-up rep per path
    hot_prompts = [_tokens(prompt_len) for _ in range(n_warm)]
    fetch_prompt = _tokens(prompt_len)
    flip_prompt = _tokens(prompt_len)
    cold_prompts = [_tokens(prompt_len) for _ in range(n_warm)]
    ttft_gcfg = dict(max_new_tokens=1, greedy=True)

    ora, _ = mk_engine(fabric=False)
    try:
        fetch_oracle = list(
            ora.generate(
                ModelRequest(rid="fo", input_ids=fetch_prompt, gconfig=gcfg),
                timeout=300,
            ).output_tokens
        )
        flip_oracle = list(
            ora.generate(
                ModelRequest(rid="po", input_ids=flip_prompt, gconfig=gcfg),
                timeout=300,
            ).output_tokens
        )
    finally:
        ora.destroy()

    a_eng, a_cfg = mk_engine()
    b_eng, b_cfg = mk_engine()

    async def _post(addr, ep, payload, timeout=300):
        return await arequest_with_retry(
            addr, ep, payload=payload, max_retries=1, timeout=timeout
        )

    async def _mget(addr):
        return await arequest_with_retry(
            addr, "/metrics", method="GET", max_retries=1, timeout=30
        )

    async def scenario():
        sa = DecodeServer(a_cfg, engine=a_eng, shutdown_grace=0.2)
        sb = DecodeServer(b_cfg, engine=b_eng, shutdown_grace=0.2)
        aa = await sa.start(host="127.0.0.1", port=0)
        ba = await sb.start(host="127.0.0.1", port=0)
        out: dict[str, object] = {}
        try:
            # populate A: the warm-start donors, the fetch run, the
            # flip-leg run. CONCURRENTLY, so each session occupies its
            # own slot — sequential requests would all reuse the lowest
            # free slot and each admission would retire the previous
            # donor's block registration
            await asyncio.gather(
                *[
                    _post(aa, "/generate", dict(
                        rid=f"hot{i}", input_ids=p, gconfig=ttft_gcfg,
                    ))
                    for i, p in enumerate(hot_prompts)
                ],
                _post(aa, "/generate", dict(
                    rid="hotf", input_ids=fetch_prompt,
                    gconfig=dict(max_new_tokens=new_tokens, greedy=True),
                )),
                _post(aa, "/generate", dict(
                    rid="hotp", input_ids=flip_prompt, gconfig=ttft_gcfg,
                )),
            )

            # remote fetch: B serves the request after pulling A's run
            chain = _chain_of(a_eng, fetch_prompt[:-1])
            r = await _post(ba, "/generate", dict(
                rid="rf", input_ids=fetch_prompt,
                gconfig=dict(max_new_tokens=new_tokens, greedy=True),
                kv_fabric=dict(peer=aa, keys=kv_fabric.encode_digest(chain)),
            ))
            out["fetch_stream"] = list(r["output_tokens"])
            out["m_fetch"] = await _mget(ba)

            # cold TTFT: fresh same-length prompts, first rep untimed
            # (pays the prefill compile), median of the rest
            cold_ms = []
            for i, p in enumerate(cold_prompts):
                t0 = time.perf_counter()
                await _post(ba, "/generate", dict(
                    rid=f"cold{i}", input_ids=p, gconfig=ttft_gcfg,
                ))
                if i > 0:
                    cold_ms.append((time.perf_counter() - t0) * 1e3)

            # warm start B's pool from A, then TTFT over the warm-started
            # prompts (first rep untimed: pays the suffix-prefill compile)
            ws = await _post(ba, "/warm_start", dict(
                peers=[aa], max_sessions=max_running,
            ))
            out["warm_start"] = ws
            warm_ms = []
            for i, p in enumerate(hot_prompts):
                t0 = time.perf_counter()
                await _post(ba, "/generate", dict(
                    rid=f"warm{i}", input_ids=p, gconfig=ttft_gcfg,
                ))
                if i > 0:
                    warm_ms.append((time.perf_counter() - t0) * 1e3)
            out["cold_ms"] = cold_ms
            out["warm_ms"] = warm_ms
            out["m_warm"] = await _mget(ba)

            # weight flip mid-trace on B: the real install sequence
            # (parked-prefix invalidation, then the version bump)
            b_eng.pause_generation()
            with b_eng._sched_lock:
                b_eng._invalidate_parked()
            b_eng.continue_generation()
            b_eng.set_version(int(b_eng._version) + 1)
            # A pushes its old-version run: every block must be rejected
            # by the version-salted keys, never committed
            push = await _post(aa, "/kv_fetch", dict(
                keys=kv_fabric.encode_digest(_chain_of(a_eng, flip_prompt[:-1])),
                target=ba,
            ))
            out["flip_push"] = push
            m0 = await _mget(ba)
            r = await _post(ba, "/generate", dict(
                rid="flip", input_ids=flip_prompt,
                gconfig=dict(max_new_tokens=new_tokens, greedy=True),
            ))
            out["flip_stream"] = list(r["output_tokens"])
            m1 = await _mget(ba)
            out["flip_delta"] = {
                k: m1[k] - m0[k]
                for k in (
                    "kv_fabric_local_hits_total",
                    "kv_fabric_remote_hits_total",
                    "kv_fabric_sessions_in_total",
                    "prefills_total",
                )
            }
            out["m_a"] = await _mget(aa)
            out["m_b"] = m1
            return out
        finally:
            await sa.stop()
            await sb.stop()
            await close_current_session()

    def _run_async(coro, timeout=600):
        result: dict[str, object] = {}

        def go():
            try:
                result["v"] = asyncio.run(coro)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                result["e"] = e

        t = threading.Thread(target=go, daemon=True)
        t.start()
        t.join(timeout)
        assert not t.is_alive(), "kvfabric wire scenario timed out"
        if "e" in result:
            raise result["e"]
        return result["v"]

    try:
        wire = _run_async(scenario())
    finally:
        a_eng.destroy()
        b_eng.destroy()

    # remote fetch: bit-identity + attribution
    assert wire["fetch_stream"] == fetch_oracle, (
        "remote-fetched stream diverged from the re-prefill oracle"
    )
    mf = wire["m_fetch"]
    assert mf["kv_fabric"]["fetch_sessions"] >= 1, "the fetch never landed"
    assert mf["kv_fabric"]["fetch_failures"] == 0
    assert mf["kv_fabric_remote_hits_total"] >= 1, (
        "the fetched run was never promoted into the request"
    )
    assert mf["kv_fabric_fetch_bytes_total"] > 0
    assert mf["kv_migrated_in_sessions_total"] == 0, (
        "fabric traffic leaked into the migration counters"
    )

    # warm start: sessions landed and the warm reps hit them
    ws = wire["warm_start"]
    assert ws["sessions"] >= 1 and ws["bytes"] > 0 and ws["failures"] == 0, (
        f"warm start failed: {ws}"
    )
    mw = wire["m_warm"]
    assert mw["kv_fabric_remote_hits_total"] >= 1 + n_ttft_reps, (
        "warm-started prompts re-prefilled instead of hitting the pool"
    )
    cold_ttft_ms = float(np.median(wire["cold_ms"]))
    warm_ttft_ms = float(np.median(wire["warm_ms"]))

    # weight flip: zero stale-block serves, honest full prefill
    fd = wire["flip_delta"]
    stale_serves = (
        fd["kv_fabric_local_hits_total"]
        + fd["kv_fabric_remote_hits_total"]
        + fd["kv_fabric_sessions_in_total"]
    )
    assert stale_serves == 0, (
        f"stale blocks served across the weight flip: {fd}"
    )
    assert fd["prefills_total"] == 1, (
        "the post-flip request did not pay an honest full prefill"
    )
    assert wire["flip_stream"] == flip_oracle, (
        "post-flip stream diverged from the oracle"
    )

    # fleet aggregate (what the router's /metrics sums over pressure):
    # remote fetches alone must account for avoided re-prefill tokens
    ma, mb = wire["m_a"], wire["m_b"]
    fleet_remote_avoided = (
        ma["kv_fabric_remote_tokens_avoided_total"]
        + mb["kv_fabric_remote_tokens_avoided_total"]
    )
    fleet_avoided = (
        ma["reprefill_tokens_avoided_total"]
        + mb["reprefill_tokens_avoided_total"]
    )
    assert fleet_remote_avoided > 0, (
        "no re-prefill tokens were avoided by REMOTE fetches fleet-wide"
    )

    return dict(
        kvfabric_dedup_requests=n_dedup,
        kvfabric_dedup_local_hits=dedup_hits,
        kvfabric_dedup_tokens_avoided=dedup_avoided,
        kvfabric_dedup_frac_prompt_avoided=(
            dedup_avoided / float(sum(len(p) for p in dedup_prompts))
        ),
        kvfabric_dedup_bitidentical=float(fabric_streams == oracle_streams),
        kvfabric_dedup_wall_s=fabric_wall,
        kvfabric_dedup_oracle_wall_s=oracle_wall,
        kvfabric_remote_hits=mb["kv_fabric_remote_hits_total"],
        kvfabric_remote_tokens_avoided=(
            mb["kv_fabric_remote_tokens_avoided_total"]
        ),
        kvfabric_fetch_bytes=mb["kv_fabric_fetch_bytes_total"],
        kvfabric_remote_bitidentical=float(
            wire["fetch_stream"] == fetch_oracle
        ),
        kvfabric_warm_sessions=ws["sessions"],
        kvfabric_warm_bytes=ws["bytes"],
        kvfabric_cold_ttft_ms=cold_ttft_ms,
        kvfabric_warm_ttft_ms=warm_ttft_ms,
        kvfabric_warm_ttft_speedup=(
            cold_ttft_ms / warm_ttft_ms if warm_ttft_ms > 0 else 0.0
        ),
        kvfabric_stale_serves_after_flip=stale_serves,
        kvfabric_flip_bitidentical=float(wire["flip_stream"] == flip_oracle),
        kvfabric_fleet_reprefill_tokens_avoided=fleet_avoided,
        kvfabric_fleet_remote_tokens_avoided=fleet_remote_avoided,
    )


def bench_chaos(model, n_replicas, n_groups, group_size, prompt_len,
                new_tokens, max_running, chunk=None, turns=2, seed=123):
    """Chaos bench (ISSUE 9 tentpole proof): replay the fleet session-reuse
    trace under a seeded fault schedule and assert the system DEGRADES
    instead of corrupting data.

    Two runs over the identical trace (greedy sampling, so every stream is
    a pure function of its prompt — independent of replica placement,
    batch composition, and retry interleaving):

      1. ORACLE — fresh replicas, no injector.
      2. CHAOS  — fresh replicas, `core.fault_injection` armed with a
         seeded plan covering four distinct fault modes on the request
         path: pre-effect aborts (client.http.send — the server never saw
         the request), ERROR-AFTER-EFFECT (client.http.recv — the
         generation landed, the response is lost; only the server's xid
         idempotency table keeps the same-xid transport retry from
         double-generating), torn response bodies (client.http.body — a
         2xx whose JSON is truncated mid-flight), fixed+jittered delays
         (server.generate — the SLOW-replica shape, a replica that answers
         late rather than dying), plus a router.schedule abort (the
         router's own handler failing over to the client's transport
         retry).

    The fleet is DISAGGREGATED (ISSUE 10): one prefill-role replica joins
    the `n_replicas` unified ones, so every request's prompt runs on the
    prefill replica and the KV streams to a decode replica before
    generation. The schedule adds `kv.migrate.send` (sender dies
    mid-stream — the full-session replay under the same xid must land the
    handoff exactly once via interval-merged staging + commit dedup) and
    a torn `kv.migrate.recv` frame (rejected by the manifest length-check
    before a byte stages; the frame retry re-covers it).

    Exactly-once is asserted three ways: every (group, member, turn)
    stream completes exactly once client-side (0 lost, no duplicate
    completion key), every accepted token stream is BIT-IDENTICAL to the
    unfaulted oracle, and engine-side admissions exceed the logical
    request count only by fault-recovery re-prefills (an honest miss —
    a resume landing where its KV is not — re-prefills rather than
    wedging), each traceable to an injected fault: the extra-admission
    count is bounded by the faults fired, and a double-imported or
    abandoned migration would break the bound or the bit-identity.
    Reported: distinct fault modes fired, per-mode
    counters, idempotency replays, and recovery latency (worst per-request
    completion-time inflation vs the oracle — what the injected faults
    cost the requests they hit).

    SUPERVISED leg (ISSUE 13): the same trace runs a third time under a
    FleetSupervisor with every `supervisor.*` seam armed — spawn failures
    (twice, then success), a hung drain (injected delay past the drain
    deadline -> rollback), a supervisor death mid-kill (abort; the next
    tick replans), and health flaps — plus a mid-trace replica kill the
    supervisor must notice and replace. The leg asserts the control plane
    CONVERGES: the dead replica is replaced through the backoff machinery
    (no crash-loop), the surplus replica is eventually drained and
    retired (after one rollback), the fleet lands back at the
    min-capacity floor, and the trace itself stays exactly-once and
    bit-identical to the oracle throughout the churn."""
    import asyncio
    import threading
    import uuid as _uuid

    import jax

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
        RouterConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.core import fault_injection
    from areal_tpu.core.fault_injection import FaultPlan, FaultPoint
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.launcher.decode_server import DecodeServer
    from areal_tpu.launcher.router import DecodeRouter
    from areal_tpu.utils import name_resolve
    from areal_tpu.utils.http import arequest_with_retry, close_current_session
    from areal_tpu.models.qwen2 import init_params

    name_resolve.reconfigure(name_resolve.NameResolveConfig(type="memory"))
    params = init_params(model, jax.random.PRNGKey(0))
    rng = np.random.RandomState(seed)
    plens = [int(prompt_len * f) for f in (1.0, 0.75, 1.25, 0.5)]
    ctx = int(prompt_len * 1.25) + turns * (new_tokens + 8) + 128
    # greedy: the oracle contract — streams depend only on the prompt
    gcfg = GenerationHyperparameters(max_new_tokens=new_tokens, greedy=True)
    group_prompts = [
        rng.randint(1, model.vocab_size, (plens[g % len(plens)],)).tolist()
        for g in range(n_groups)
    ]
    n_logical = n_groups * group_size * turns

    def _http_get(addr, ep):
        async def _g():
            try:
                return await arequest_with_retry(
                    addr, ep, method="GET", max_retries=1, timeout=10
                )
            finally:
                await close_current_session()

        return asyncio.run(_g())

    class _Replica:
        def __init__(self, warm_plen, role="unified"):
            dcfg = JaxDecodeConfig(
                context_length=ctx,
                max_running_requests=max_running,
                new_tokens_per_chunk=chunk or min(128, new_tokens),
                dtype=model.dtype,
                kv_cache_dtype=model.dtype,
                role=role,
                kv_migrate_chunk_mb=0.05,  # several frames per session:
                # gives the kv.migrate fault points mid-stream hits
            )
            self.engine = JaxDecodeEngine(dcfg, InferenceEngineConfig())
            self.engine.set_model(params, model)
            self.engine.initialize()
            self.engine.prewarm(prompt_len=warm_plen, gconfig=gcfg)
            # the real dcfg (not a default) so /health advertises the role
            self.server = DecodeServer(
                dcfg, engine=self.engine, shutdown_grace=0.5
            )
            self.addr = None
            self._loop = None
            self._ready = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
            assert self._ready.wait(60), "chaos replica failed to start"

        def _run(self):
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                self.addr = await self.server.start(host="127.0.0.1", port=0)
                self._ready.set()

            self._loop.run_until_complete(_start())
            self._loop.run_forever()

        def admissions(self):
            m = self.engine.get_metrics()
            return (
                m["prefills_total"]
                + m["prefix_forks_total"]
                + m["prefix_inplace_total"]
                + m["suffix_prefills_total"]
            )

        def kill(self):
            """Die like a crashed replica. Idempotent: the supervisor's
            replace path re-kills whatever the bench already killed."""
            if getattr(self, "_killed", False):
                return
            self._killed = True
            asyncio.run_coroutine_threadsafe(
                self.server.stop(), self._loop
            ).result(30)
            self.engine.pause_generation()
            self.engine.abort_all()

        def stop(self):
            try:
                asyncio.run_coroutine_threadsafe(
                    self.server.stop(), self._loop
                ).result(30)
            except Exception as e:  # noqa: BLE001 — already down
                print(f"[chaos] replica stop: {e!r}", file=sys.stderr)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)
            self.engine.destroy()

    class _RouterThread:
        def __init__(self, servers, exp, trial):
            self.router = DecodeRouter(
                exp,
                trial,
                servers,
                config=RouterConfig(
                    schedule_policy="prefix_affinity",
                    health_poll_interval=0.25,
                    dead_after_failures=4,
                    queue_timeout_s=60.0,
                ),
            )
            self.addr = None
            self._loop = None
            self._ready = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
            assert self._ready.wait(30), "chaos router failed to start"

        def _run(self):
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                self.addr = await self.router.start("127.0.0.1", 0)
                self._ready.set()

            self._loop.run_until_complete(_start())
            self._loop.run_forever()

        def stop(self):
            asyncio.run_coroutine_threadsafe(
                self.router.stop(), self._loop
            ).result(30)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)

    def run_trace(label, plan):
        exp, trial = "benchchaos", f"{label}-{_uuid.uuid4().hex[:6]}"
        # disaggregated fleet: n_replicas unified (decode-capable) + one
        # prefill-role replica every prompt runs on; identical for oracle
        # and chaos runs, so greedy streams stay a pure function of the
        # prompt regardless of which faults fire on the handoff path
        replicas = [_Replica(min(plens)) for _ in range(n_replicas)]
        replicas.append(_Replica(min(plens), role="prefill"))
        addrs = [r.addr for r in replicas]
        rt = _RouterThread(addrs, exp, trial)
        client = RemoteInfEngine(
            InferenceEngineConfig(
                experiment_name=exp,
                trial_name=trial,
                request_timeout=300,
                request_retries=3,
                fleet_failover_retries=2,
            )
        )
        client.addresses = list(addrs)
        streams: dict = {}
        lat: dict = {}
        out: dict = {}
        # arm AFTER replica prewarm/startup so the schedule perturbs the
        # trace, not the fixture setup
        fault_injection.configure(plan)
        try:
            time.sleep(0.6)  # one poll round
            adm0 = sum(r.admissions() for r in replicas)
            _ADM_KEYS = ("prefills_total", "prefix_forks_total",
                         "prefix_inplace_total", "suffix_prefills_total")
            adm_base = [
                {k: r.engine.get_metrics()[k] for k in _ADM_KEYS}
                for r in replicas
            ]

            async def member(g, m):
                rid = f"c{g}-m{m}"
                ids = list(group_prompts[g])
                for t in range(turns):
                    t0 = time.perf_counter()
                    r = await client.agenerate(
                        ModelRequest(rid=rid, input_ids=ids, gconfig=gcfg)
                    )
                    key = (g, m, t)
                    assert key not in streams, f"duplicate completion {key}"
                    streams[key] = tuple(r.output_tokens)
                    lat[key] = time.perf_counter() - t0
                    ids = ids + list(r.output_tokens) + [7, 11, 13, 17]

            async def group(g):
                await asyncio.sleep((g % 3) * 0.1)
                await asyncio.gather(
                    *[member(g, m) for m in range(group_size)]
                )

            async def drive():
                try:
                    await asyncio.gather(*[group(g) for g in range(n_groups)])
                finally:
                    await close_current_session()

            t0 = time.perf_counter()
            asyncio.run(drive())
            out["wall_s"] = time.perf_counter() - t0
            out["streams"] = streams
            out["lat"] = lat
            out["admissions"] = sum(r.admissions() for r in replicas) - adm0
            # per-replica admission-counter deltas: when the exactly-once
            # assert trips, this names the replica and path that
            # over-admitted instead of leaving a bare count
            out["admission_detail"] = [
                {
                    "addr": r.addr,
                    "role": getattr(r.engine.config, "role", "unified"),
                    **{
                        k: r.engine.get_metrics()[k] - adm_base[i][k]
                        for k in _ADM_KEYS
                    },
                }
                for i, r in enumerate(replicas)
            ]
            out["idem_hits"] = sum(
                _http_get(r.addr, "/metrics")["idem_hits_total"]
                for r in replicas
            )
            out["migrated_in"] = sum(
                r.engine.get_metrics()["kv_migrated_in_sessions_total"]
                for r in replicas
            )
            out["migrate_dedups"] = sum(
                _http_get(r.addr, "/metrics")["kv_migrate"]["commit_dedups"]
                for r in replicas
            )
            out["router_metrics"] = _http_get(rt.addr, "/metrics")
            out["fault_counters"] = fault_injection.snapshot()
        finally:
            fault_injection.deactivate()
            rt.stop()
            for r in replicas:
                r.stop()
        return out

    class _SupervisorThread:
        """FleetSupervisor on its own loop thread: it owns spawn / drain /
        kill scheduling while the bench thread only reads get_metrics()."""

        def __init__(self, sup):
            self.sup = sup
            self._loop = None
            self._ready = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
            assert self._ready.wait(30), "supervisor failed to start"

        def _run(self):
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                await self.sup.start(host="127.0.0.1", port=0)
                self._ready.set()

            self._loop.run_until_complete(_start())
            self._loop.run_forever()

        def stop(self):
            asyncio.run_coroutine_threadsafe(
                self.sup.stop(), self._loop
            ).result(30)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)

    def run_supervised(plan, kill_delay):
        """Chaos leg 3: the trace under a FleetSupervisor with the
        supervisor.* seams armed plus a mid-trace replica kill. The fleet
        starts one replica ABOVE the floor so the supervisor has a
        legitimate scale-down to attempt (whose first drain hangs and
        rolls back) while the kill forces a replace (whose first spawn
        attempts fail). Membership is discovery-driven: the router seeds
        no servers and follows the supervisor's name_resolve
        registrations, so a retired replica actually leaves rotation."""
        from areal_tpu.api.cli_args import SupervisorConfig
        from areal_tpu.launcher.supervisor import FleetSupervisor

        exp, trial = "benchchaos", f"sup-{_uuid.uuid4().hex[:6]}"
        replicas = [_Replica(min(plens)) for _ in range(n_replicas + 1)]
        spawned: list = []
        spawn_lock = threading.Lock()
        rt = _RouterThread([], exp, trial)

        def spawn_fn(role):
            r = _Replica(min(plens), role=role)
            with spawn_lock:
                spawned.append(r)
            return r

        scfg = SupervisorConfig(
            enabled=True,
            tick_interval_s=0.25,
            min_replicas=n_replicas,
            max_replicas=n_replicas + 1,
            util_inflight_target=max_running,
            scale_up_util=0.9,
            scale_down_util=0.35,
            scale_up_queue_depth=3,
            scale_up_cooldown_s=1.0,
            scale_down_cooldown_s=1.0,
            replace_cooldown_s=0.5,
            rerole_enabled=False,  # unified fleet: topology stays put
            spawn_max_attempts=4,  # 2 injected failures + margin
            spawn_backoff_s=0.2,
            spawn_backoff_max_s=1.0,
            drain_deadline_s=3.0,
            health_fail_threshold=2,
            health_timeout_s=2.0,
        )
        sup = FleetSupervisor(
            rt.addr,
            spawn_fn,
            config=scfg,
            experiment_name=exp,
            trial_name=trial,
        )
        for r in replicas:
            sup.adopt(r, role="unified")
        st = _SupervisorThread(sup)
        client = RemoteInfEngine(
            InferenceEngineConfig(
                experiment_name=exp,
                trial_name=trial,
                request_timeout=300,
                request_retries=3,
                fleet_failover_retries=2,
            )
        )
        client.addresses = [r.addr for r in replicas]
        streams: dict = {}
        fault_injection.configure(plan)
        try:
            time.sleep(0.75)  # discovery + one poll round

            async def member(g, m):
                rid = f"s{g}-m{m}"
                ids = list(group_prompts[g])
                for t in range(turns):
                    r = await client.agenerate(
                        ModelRequest(rid=rid, input_ids=ids, gconfig=gcfg)
                    )
                    key = (g, m, t)
                    assert key not in streams, f"duplicate completion {key}"
                    streams[key] = tuple(r.output_tokens)
                    ids = ids + list(r.output_tokens) + [7, 11, 13, 17]

            async def group(g):
                await asyncio.sleep((g % 3) * 0.1)
                await asyncio.gather(
                    *[member(g, m) for m in range(group_size)]
                )

            async def killer():
                await asyncio.sleep(kill_delay)
                victim = replicas[min(1, len(replicas) - 1)]
                print(
                    f"[chaos] supervised: killing {victim.addr}",
                    file=sys.stderr,
                )
                await asyncio.get_running_loop().run_in_executor(
                    None, victim.kill
                )

            async def drive():
                try:
                    k = asyncio.ensure_future(killer())
                    await asyncio.gather(*[group(g) for g in range(n_groups)])
                    await k
                finally:
                    await close_current_session()

            t0 = time.perf_counter()
            asyncio.run(drive())
            wall = time.perf_counter() - t0

            # convergence: the replace (through 2 spawn failures), the
            # rolled-back-then-committed scale-down, and a fleet back at
            # the floor with nothing in flight
            deadline_t = time.monotonic() + 60
            while time.monotonic() < deadline_t:
                m = sup.get_metrics()
                if (
                    m["replacements_total"] >= 1
                    and m["scale_downs_total"] >= 1
                    and m["drain_rollbacks_total"] >= 1
                    and m["spawn_failures_total"] >= 2
                    and m["fleet_alive"] == n_replicas
                    and m["pending_spawns"] == 0
                    and m["disruptive_inflight"] == 0
                ):
                    break
                time.sleep(0.25)
            sup_metrics = sup.get_metrics()
            sup_body = _http_get(sup.addr, "/supervisor")
            counters = fault_injection.snapshot()
        finally:
            fault_injection.deactivate()
            st.stop()
            rt.stop()
            with spawn_lock:
                fleet = replicas + spawned
            for r in fleet:
                r.stop()
        return dict(
            streams=streams,
            wall_s=wall,
            sup=sup_metrics,
            sup_body=sup_body,
            fault_counters=counters,
        )

    # seeded schedule: >= 4 distinct modes on the request path. Explicit
    # hit indices (`at`) guarantee each mode actually fires on any trace
    # with a handful of requests; `times` bounds repeated firing.
    plan = FaultPlan(
        seed=seed,
        points=[
            FaultPoint(site="client.http.send", mode="abort",
                       at=(1, 6), times=2,
                       match={"endpoint": "/generate"}),
            FaultPoint(site="client.http.recv", mode="error_after_effect",
                       at=(0, 4), times=2,
                       match={"endpoint": "/generate"}),
            FaultPoint(site="client.http.body", mode="torn",
                       at=(2,), times=1,
                       match={"endpoint": "/generate"}),
            FaultPoint(site="server.generate", mode="delay",
                       at=(3, 8), times=2, delay_s=0.2, jitter_s=0.1),
            FaultPoint(site="router.schedule", mode="abort",
                       at=(2,), times=1),
            # the disaggregated handoff path (ISSUE 10): a sender dying
            # mid-KV-stream — the full-session replay under the same xid
            # must land the handoff exactly once — and a torn KV frame the
            # receiver's manifest length-check rejects before staging
            FaultPoint(site="kv.migrate.send", mode="abort",
                       at=(1,), times=1),
            FaultPoint(site="kv.migrate.recv", mode="torn",
                       at=(4,), times=1),
        ],
    )

    oracle = run_trace("oracle", None)
    chaos = run_trace("chaos", plan)

    assert len(oracle["streams"]) == n_logical, "oracle lost requests"
    lost = n_logical - len(chaos["streams"])
    mismatched = sum(
        1
        for k, v in oracle["streams"].items()
        if chaos["streams"].get(k) != v
    )
    extra_admissions = chaos["admissions"] - n_logical
    counters = chaos["fault_counters"]
    modes_fired = {k.split("|")[1] for k in counters}
    faults_total = sum(counters.values())
    # worst per-request completion-time inflation vs the unfaulted oracle:
    # what the injected faults cost the requests they hit (retries, replay
    # round-trips, injected delay)
    recovery_max_s = max(
        chaos["lat"][k] - oracle["lat"][k] for k in oracle["lat"]
    )
    assert lost == 0, f"chaos lost {lost} requests"
    assert mismatched == 0, (
        f"{mismatched} streams diverged from the unfaulted oracle"
    )
    # Engine-side exactly-once, split by kind. Extra admissions beyond
    # the logical count are the HONEST-MISS recovery path (a fault lands
    # a resume where its migrated KV is not — schedule abort before the
    # affinity was recorded, failover off an aborted target — and the
    # replica re-prefills rather than wedging; streams stay bit-identical
    # so it is wasted work, never duplicated output). Each such re-prefill
    # must be traceable to an injected fault: negative (lost work) or
    # more re-prefills than faults means real double-generation.
    assert 0 <= extra_admissions <= faults_total, (
        f"{extra_admissions} extra engine-side admissions with only "
        f"{faults_total} injected faults: {chaos['admission_detail']}"
    )
    assert {"abort", "error_after_effect", "delay", "torn"} <= modes_fired, (
        f"schedule only exercised {sorted(modes_fired)}"
    )
    assert chaos["idem_hits"] >= 1, (
        "error-after-effect never exercised the idempotency replay"
    )
    kv_faults = {
        k: v for k, v in counters.items() if k.startswith("kv.migrate")
    }
    assert kv_faults, "kv.migrate fault points never fired"
    assert chaos["migrated_in"] >= 1, (
        "no KV session ever migrated — the handoff path went untested"
    )

    # leg 3: the control plane under fire (ISSUE 13). Seam indices:
    # spawn 0,1 = the replace's first two attempts; drain 0 = the first
    # scale-down's drain (hung past the 3 s deadline -> rollback); kill 0
    # = the supervisor dying mid-transition (the next tick replans; the
    # /drain in-progress guard + idempotent re-drain make the retry
    # safe); health 2,4 land on different replicas in consecutive ticks
    # (single-probe flaps, below the dead threshold).
    sup_plan = FaultPlan(
        seed=seed + 1,
        points=[
            FaultPoint(site="supervisor.spawn", mode="abort",
                       at=(0, 1), times=2),
            FaultPoint(site="supervisor.drain", mode="delay",
                       at=(0,), times=1, delay_s=8.0),
            FaultPoint(site="supervisor.kill", mode="abort",
                       at=(0,), times=1),
            FaultPoint(site="supervisor.health", mode="abort",
                       at=(2, 4), times=2),
        ],
    )
    kill_delay = min(2.0, max(0.5, 0.4 * oracle["wall_s"]))
    supervised = run_supervised(sup_plan, kill_delay)

    sup_lost = n_logical - len(supervised["streams"])
    sup_mismatched = sum(
        1
        for k, v in oracle["streams"].items()
        if supervised["streams"].get(k) != v
    )
    sup_counters = supervised["fault_counters"]
    sup_sites = {k.split("|")[0] for k in sup_counters}
    sup_m = supervised["sup"]
    assert sup_lost == 0, f"supervised leg lost {sup_lost} requests"
    assert sup_mismatched == 0, (
        f"{sup_mismatched} supervised streams diverged from the oracle"
    )
    assert {
        "supervisor.spawn",
        "supervisor.drain",
        "supervisor.kill",
        "supervisor.health",
    } <= sup_sites, f"supervisor seams unexercised: {sorted(sup_sites)}"
    assert sup_m["replacements_total"] >= 1, (
        "the killed replica was never replaced"
    )
    assert sup_m["spawn_failures_total"] >= 2, (
        "injected spawn failures never hit the backoff machinery"
    )
    assert sup_m["crash_loops_total"] == 0, (
        "the replace crash-looped instead of recovering"
    )
    assert sup_m["drain_rollbacks_total"] >= 1, (
        "the hung drain never rolled an action back"
    )
    assert sup_m["scale_downs_total"] >= 1, (
        "the surplus replica was never retired"
    )
    assert (
        sup_m["fleet_alive"] == n_replicas
        and sup_m["pending_spawns"] == 0
    ), f"fleet failed to converge to the floor: {sup_m}"
    sup_alive_slots = [
        s for s in supervised["sup_body"]["slots"] if s["alive"]
    ]
    assert len(sup_alive_slots) == n_replicas, (
        f"/supervisor reports {len(sup_alive_slots)} alive slots"
    )

    # leg 4: the fabric fetch path under fire (ISSUE 17). Self-contained
    # two-peer scenarios off the trace; the same kv.migrate.* seams that
    # cover session migration cover fabric fetches (shared _stream_kv
    # wire). TORN: the fetch's first /kv_recv frame is torn — the frame
    # retry re-covers it and staging interval-merge + commit dedup land
    # the run EXACTLY ONCE. ABORT: every send attempt dies (past the
    # replay budget) — the serving side abandons the stream and the
    # requesting replica DEGRADES to a local full prefill, bit-identical,
    # with zero fabric sessions imported (no torn half-run ever serves).
    from areal_tpu.core import kv_fabric

    def mk_fabric_engine():
        dcfg = JaxDecodeConfig(
            context_length=ctx,
            max_running_requests=max_running,
            new_tokens_per_chunk=chunk or min(128, new_tokens),
            dtype=model.dtype,
            kv_cache_dtype=model.dtype,
            kv_layout="paged",
            page_size=16,  # 96-token smoke prompts span >= 5 complete
            # blocks — past the 64-token fabric floor
            paged_attn_impl="xla",
            kv_migrate_chunk_mb=0.05,  # several frames per fetch: the
            # seams land mid-stream
        )
        eng = JaxDecodeEngine(dcfg, InferenceEngineConfig())
        eng.set_model(params, model)
        eng.initialize()
        return eng, dcfg

    fab_prompt = rng.randint(1, model.vocab_size, (prompt_len,)).tolist()
    fa_eng, fa_cfg = mk_fabric_engine()
    fb_eng, fb_cfg = mk_fabric_engine()
    fc_eng, fc_cfg = mk_fabric_engine()

    torn_plan = FaultPlan(
        seed=seed + 2,
        points=[
            FaultPoint(site="kv.migrate.recv", mode="torn",
                       at=(0,), times=1),
        ],
    )
    abort_plan = FaultPlan(
        seed=seed + 3,
        points=[
            # all three send attempts (retries=2) die: past the budget
            FaultPoint(site="kv.migrate.send", mode="abort",
                       at=(0, 1, 2), times=3),
        ],
    )

    async def fabric_scenario():
        sa = DecodeServer(fa_cfg, engine=fa_eng, shutdown_grace=0.2)
        sb = DecodeServer(fb_cfg, engine=fb_eng, shutdown_grace=0.2)
        sc = DecodeServer(fc_cfg, engine=fc_eng, shutdown_grace=0.2)
        aa = await sa.start(host="127.0.0.1", port=0)
        ba = await sb.start(host="127.0.0.1", port=0)
        ca = await sc.start(host="127.0.0.1", port=0)
        out: dict[str, object] = {}
        try:
            gpayload = dict(max_new_tokens=new_tokens, greedy=True)
            # A pays the one full prefill: its stream is the oracle
            r = await arequest_with_retry(
                aa, "/generate",
                payload=dict(rid="fa", input_ids=fab_prompt,
                             gconfig=gpayload),
                max_retries=1, timeout=300,
            )
            out["oracle"] = list(r["output_tokens"])
            hint = dict(
                peer=aa,
                keys=kv_fabric.encode_digest(kv_fabric.chain_keys(
                    fab_prompt[:-1],
                    fa_eng._alloc.block_size,
                    int(fa_eng._version),
                    str(fa_eng.config.kv_dtype),
                )),
            )
            fault_injection.configure(torn_plan)
            try:
                r = await arequest_with_retry(
                    ba, "/generate",
                    payload=dict(rid="fb", input_ids=fab_prompt,
                                 gconfig=gpayload, kv_fabric=hint),
                    max_retries=1, timeout=300,
                )
            finally:
                out["torn_counters"] = fault_injection.snapshot()
                fault_injection.deactivate()
            out["torn_stream"] = list(r["output_tokens"])
            out["m_torn"] = await arequest_with_retry(
                ba, "/metrics", method="GET", max_retries=1, timeout=30
            )
            fault_injection.configure(abort_plan)
            try:
                r = await arequest_with_retry(
                    ca, "/generate",
                    payload=dict(rid="fc", input_ids=fab_prompt,
                                 gconfig=gpayload, kv_fabric=hint),
                    max_retries=1, timeout=300,
                )
            finally:
                out["abort_counters"] = fault_injection.snapshot()
                fault_injection.deactivate()
            out["abort_stream"] = list(r["output_tokens"])
            out["m_abort"] = await arequest_with_retry(
                ca, "/metrics", method="GET", max_retries=1, timeout=30
            )
            out["m_serve"] = await arequest_with_retry(
                aa, "/metrics", method="GET", max_retries=1, timeout=30
            )
            return out
        finally:
            await sa.stop()
            await sb.stop()
            await sc.stop()
            await close_current_session()

    try:
        fab = asyncio.run(fabric_scenario())
    finally:
        fa_eng.destroy()
        fb_eng.destroy()
        fc_eng.destroy()

    torn_faults = {
        k: int(v)
        for k, v in fab["torn_counters"].items()
        if k.startswith("kv.migrate")
    }
    abort_faults = {
        k: int(v)
        for k, v in fab["abort_counters"].items()
        if k.startswith("kv.migrate")
    }
    assert torn_faults, "the torn fabric-fetch fault never fired"
    assert sum(abort_faults.values()) >= 3, (
        f"abort seam fired {abort_faults}: the fetch replay budget was "
        "never exhausted"
    )
    mt, mab, msv = fab["m_torn"], fab["m_abort"], fab["m_serve"]
    # torn frame -> replay -> exactly once: one committed fabric session,
    # one remote hit, the stream bit-identical to the full-prefill oracle
    assert fab["torn_stream"] == fab["oracle"], (
        "torn-then-replayed fabric fetch corrupted the stream"
    )
    assert mt["kv_fabric_sessions_in_total"] == 1, (
        f"torn fetch landed {mt['kv_fabric_sessions_in_total']} sessions "
        "(exactly-once violated)"
    )
    assert mt["kv_fabric_remote_hits_total"] == 1
    assert mt["kv_fabric"]["fetch_failures"] == 0
    # aborted fetch -> degraded to a LOCAL full prefill: zero fabric
    # sessions imported, zero fabric hits, one honest prefill, the
    # stream still bit-identical
    assert fab["abort_stream"] == fab["oracle"], (
        "the degraded (aborted-fetch) request corrupted the stream"
    )
    assert mab["kv_fabric_sessions_in_total"] == 0, (
        "an aborted fetch still imported a fabric session"
    )
    assert mab["kv_fabric_remote_hits_total"] == 0
    assert mab["kv_fabric_local_hits_total"] == 0
    assert mab["prefills_total"] == 1, (
        f"{mab['prefills_total']} prefills on the degraded replica: the "
        "re-prefill ran more (or less) than exactly once"
    )
    assert msv["kv_migrate"]["out_failures"] >= 1, (
        "the serving side never recorded the abandoned fetch stream"
    )

    rm = chaos["router_metrics"]
    return dict(
        chaos_replicas=n_replicas,
        chaos_requests=n_logical,
        chaos_lost=lost,
        chaos_recovery_reprefills=extra_admissions,
        chaos_streams_bitidentical=int(mismatched == 0),
        chaos_exactly_once=float(
            lost == 0
            and mismatched == 0
            and 0 <= extra_admissions <= faults_total
        ),
        chaos_fault_modes_fired=len(modes_fired),
        chaos_faults_injected=faults_total,
        chaos_idem_replays=chaos["idem_hits"],
        chaos_kv_migrated_sessions=chaos["migrated_in"],
        chaos_kv_migrate_commit_dedups=chaos["migrate_dedups"],
        chaos_kv_migrate_faults={k: int(v) for k, v in sorted(kv_faults.items())},
        chaos_recovery_max_s=recovery_max_s,
        chaos_oracle_wall_s=oracle["wall_s"],
        chaos_wall_s=chaos["wall_s"],
        chaos_router_requeues=rm.get("requeues_total", 0),
        chaos_router_queue_sheds=rm.get("queue_sheds_total", 0),
        chaos_fault_counters={k: int(v) for k, v in sorted(counters.items())},
        chaos_supervised_exactly_once=float(
            sup_lost == 0 and sup_mismatched == 0
        ),
        chaos_supervised_wall_s=supervised["wall_s"],
        chaos_supervised_replacements=sup_m["replacements_total"],
        chaos_supervised_spawn_failures=sup_m["spawn_failures_total"],
        chaos_supervised_crash_loops=sup_m["crash_loops_total"],
        chaos_supervised_drain_rollbacks=sup_m["drain_rollbacks_total"],
        chaos_supervised_scale_downs=sup_m["scale_downs_total"],
        chaos_supervised_health_flaps=sup_m["health_flaps_total"],
        chaos_supervised_fleet_alive=sup_m["fleet_alive"],
        chaos_supervisor_faults={
            k: int(v)
            for k, v in sorted(sup_counters.items())
            if k.startswith("supervisor.")
        },
        chaos_fabric_torn_sessions_in=mt["kv_fabric_sessions_in_total"],
        chaos_fabric_torn_remote_hits=mt["kv_fabric_remote_hits_total"],
        chaos_fabric_abort_sessions_in=mab["kv_fabric_sessions_in_total"],
        chaos_fabric_abort_reprefills=mab["prefills_total"],
        chaos_fabric_streams_bitidentical=float(
            fab["torn_stream"] == fab["oracle"]
            and fab["abort_stream"] == fab["oracle"]
        ),
        chaos_fabric_exactly_once=float(
            mt["kv_fabric_sessions_in_total"] == 1
            and mab["kv_fabric_sessions_in_total"] == 0
            and mab["prefills_total"] == 1
        ),
        chaos_fabric_faults={
            **{f"torn:{k}": v for k, v in sorted(torn_faults.items())},
            **{f"abort:{k}": v for k, v in sorted(abort_faults.items())},
        },
    )


def bench_autoscale(model, n_base, n_peak, n_groups, group_size, prompt_len,
                    new_tokens, max_running, chunk=None, lull_gap=0.7,
                    kill_after_s=2.0, slo_band=1.10, itl_grace_ms=0.0,
                    seed=321):
    """Autoscale bench (ISSUE 13 headline): a bursty diurnal trace with a
    mid-trace replica kill, served twice.

      SUPERVISED — the fleet starts at the `n_base` floor under a
        FleetSupervisor (max `n_peak`). The burst builds queue/util
        pressure that scales the fleet up; the killed replica is
        replaced through the spawn machinery; the trailing lull scales
        the surplus back down. Membership is discovery-driven (router
        seeds no servers; the supervisor registers/deregisters replicas
        in name_resolve), so retired capacity actually leaves rotation.
        Spawns come from a WARM POOL (pre-built spares `spawn_fn` pops,
        falling back to a cold build when the pool runs dry) — the
        standard warm-pool autoscaling model: the bench measures the
        control plane's decisions and exactly-once guarantees, not
        engine boot time, and the bill counts only replicas standing IN
        the fleet.
      STATIC — the best static provisioning: `n_peak` replicas from the
        first request. It takes the same mid-burst kill and (having no
        control plane) runs the rest of the trace a replica short,
        surviving on the router's failover.

    The trace is diurnal: a leading lull (groups spaced `lull_gap` s
    apart), a burst (the middle ~40% of groups arriving nearly at once),
    a trailing lull. The kill lands `kill_after_s` into the burst — late
    enough that the supervised fleet has scaled toward the peak, so both
    fleets lose a replica that was doing real work.

    Claim proved by the assertions: the supervised fleet MATCHES the
    static fleet's client-observed p99 TTFT and wall-ITL (within a 10%
    noise band — it typically wins the burst tail, because it ends the
    burst at full peak while the static fleet stays a replica short) at
    MATERIALLY fewer replica-seconds. Billing: the static bill is the
    peak reservation (`n_peak x wall` — a static deployment pays for
    capacity whether or not a crash idles it; its alive-seconds are also
    reported), the supervised bill is the supervisor's integral of
    replicas actually standing. Exactly-once: every request completes
    exactly once in both runs, and the two runs' greedy streams are
    bit-identical to each other (placement- and churn-independent).

    Client-observed SLO decomposition: per request, wall = client
    completion time, decode span = engine latency minus engine TTFT, so
    `wall - decode_span` is the wall TTFT (router queueing, scheduling,
    failover retries included — exactly what a static-vs-elastic fleet
    changes) and decode_span / (tokens - 1) is the wall ITL."""
    import asyncio
    import threading
    import uuid as _uuid

    import jax

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
        RouterConfig,
        SupervisorConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.launcher.decode_server import DecodeServer
    from areal_tpu.launcher.router import DecodeRouter
    from areal_tpu.launcher.supervisor import FleetSupervisor
    from areal_tpu.utils import name_resolve
    from areal_tpu.utils.http import close_current_session
    from areal_tpu.models.qwen2 import init_params

    assert 1 <= n_base < n_peak, "need headroom between floor and peak"
    name_resolve.reconfigure(name_resolve.NameResolveConfig(type="memory"))
    params = init_params(model, jax.random.PRNGKey(0))
    rng = np.random.RandomState(seed)
    ctx = prompt_len + new_tokens + 128
    gcfg = GenerationHyperparameters(max_new_tokens=new_tokens, greedy=True)
    group_prompts = [
        rng.randint(1, model.vocab_size, (prompt_len,)).tolist()
        for _ in range(n_groups)
    ]
    n_logical = n_groups * group_size

    # diurnal arrival plan: lull / burst / lull by group index
    burst_lo, burst_hi = int(n_groups * 0.3), int(n_groups * 0.7)
    starts, t = [], 0.0
    for g in range(n_groups):
        starts.append(t)
        t += 0.05 if burst_lo <= g < burst_hi else lull_gap
    t_burst = starts[burst_lo]
    t_kill = t_burst + kill_after_s

    class _Replica:
        def __init__(self):
            dcfg = JaxDecodeConfig(
                context_length=ctx,
                max_running_requests=max_running,
                new_tokens_per_chunk=chunk or min(128, new_tokens),
                dtype=model.dtype,
                kv_cache_dtype=model.dtype,
            )
            self.engine = JaxDecodeEngine(dcfg, InferenceEngineConfig())
            self.engine.set_model(params, model)
            self.engine.initialize()
            self.engine.prewarm(prompt_len=prompt_len, gconfig=gcfg)
            self.server = DecodeServer(
                dcfg, engine=self.engine, shutdown_grace=0.5
            )
            self.addr = None
            self._loop = None
            self._killed = False
            self._ready = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
            assert self._ready.wait(60), "autoscale replica failed to start"

        def _run(self):
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                self.addr = await self.server.start(host="127.0.0.1", port=0)
                self._ready.set()

            self._loop.run_until_complete(_start())
            self._loop.run_forever()

        def kill(self):
            if self._killed:
                return
            self._killed = True
            asyncio.run_coroutine_threadsafe(
                self.server.stop(), self._loop
            ).result(30)
            self.engine.pause_generation()
            self.engine.abort_all()

        def stop(self):
            try:
                asyncio.run_coroutine_threadsafe(
                    self.server.stop(), self._loop
                ).result(30)
            except Exception as e:  # noqa: BLE001 — already killed
                print(f"[autoscale] replica stop: {e!r}", file=sys.stderr)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)
            self.engine.destroy()

    class _RouterThread:
        def __init__(self, servers, exp, trial):
            self.router = DecodeRouter(
                exp,
                trial,
                servers,
                config=RouterConfig(
                    schedule_policy="prefix_affinity",
                    health_poll_interval=0.25,
                    dead_after_failures=3,
                    queue_timeout_s=60.0,
                ),
            )
            self.addr = None
            self._loop = None
            self._ready = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
            assert self._ready.wait(30), "autoscale router failed to start"

        def _run(self):
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                self.addr = await self.router.start("127.0.0.1", 0)
                self._ready.set()

            self._loop.run_until_complete(_start())
            self._loop.run_forever()

        def stop(self):
            asyncio.run_coroutine_threadsafe(
                self.router.stop(), self._loop
            ).result(30)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)

    class _SupervisorThread:
        def __init__(self, sup):
            self.sup = sup
            self._loop = None
            self._ready = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
            assert self._ready.wait(30), "supervisor failed to start"

        def _run(self):
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _start():
                await self.sup.start(host="127.0.0.1", port=0)
                self._ready.set()

            self._loop.run_until_complete(_start())
            self._loop.run_forever()

        def stop(self):
            asyncio.run_coroutine_threadsafe(
                self.sup.stop(), self._loop
            ).result(30)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=10)

    def run_fleet(label, supervised):
        exp, trial = "benchautoscale", f"{label}-{_uuid.uuid4().hex[:6]}"
        n_start = n_base if supervised else n_peak
        replicas = [_Replica() for _ in range(n_start)]
        spawned: list = []
        spawn_lock = threading.Lock()
        # warm pool: expected spawn demand is (n_peak - n_base) scale-ups
        # plus one replacement; a dry pool falls back to a cold build
        spares: list = (
            [_Replica() for _ in range(n_peak - n_base + 1)]
            if supervised
            else []
        )
        # supervised membership is discovery-only: the supervisor's
        # registrations are the fleet. The static fleet seeds the router.
        rt = _RouterThread(
            [] if supervised else [r.addr for r in replicas], exp, trial
        )
        st = None
        if supervised:
            def spawn_fn(role):
                with spawn_lock:
                    r = spares.pop() if spares else None
                if r is None:
                    r = _Replica()  # cold path: pool ran dry
                with spawn_lock:
                    spawned.append(r)
                return r

            scfg = SupervisorConfig(
                enabled=True,
                tick_interval_s=0.15,
                min_replicas=n_base,
                max_replicas=n_peak,
                util_inflight_target=max_running,
                scale_up_util=0.85,
                scale_down_util=0.25,
                scale_up_queue_depth=2,
                scale_up_cooldown_s=0.5,
                scale_down_cooldown_s=1.5,
                replace_cooldown_s=0.5,
                rerole_enabled=False,
                spawn_max_attempts=3,
                spawn_backoff_s=0.2,
                spawn_backoff_max_s=1.0,
                drain_deadline_s=5.0,
                health_fail_threshold=2,
                health_timeout_s=2.0,
            )
            sup = FleetSupervisor(
                rt.addr,
                spawn_fn,
                config=scfg,
                experiment_name=exp,
                trial_name=trial,
            )
            for r in replicas:
                sup.adopt(r)
            st = _SupervisorThread(sup)
        client = RemoteInfEngine(
            InferenceEngineConfig(
                experiment_name=exp,
                trial_name=trial,
                request_timeout=300,
                # fail over fast: when the mid-trace kill (or a
                # supervisor scale-down) retires an addr, one refused
                # connect should move the request on, not a retry loop
                request_retries=1,
                fleet_failover_retries=3,
            )
        )
        client.addresses = [r.addr for r in replicas]
        done: dict = {}
        ttfts: list = []
        itls: list = []
        killed_at: dict = {}
        try:
            time.sleep(0.75)  # discovery + one poll round

            async def member(g, m):
                rid = f"a{g}-m{m}-{_uuid.uuid4().hex[:6]}"
                t0 = time.perf_counter()
                r = await client.agenerate(
                    ModelRequest(
                        rid=rid,
                        input_ids=list(group_prompts[g]),
                        gconfig=gcfg,
                    )
                )
                wall = time.perf_counter() - t0
                key = (g, m)
                assert key not in done, f"duplicate completion {key}"
                done[key] = tuple(r.output_tokens)
                # client-observed split: wall minus the engine decode span
                # = TTFT as the user sees it (queueing, scheduling, and
                # failover retries included)
                span = max(0.0, r.latency - r.ttft)
                ttfts.append(max(0.0, wall - span))
                if len(r.output_tokens) > 1:
                    itls.append(span / (len(r.output_tokens) - 1))

            async def group(g):
                await asyncio.sleep(starts[g])
                await asyncio.gather(
                    *[member(g, m) for m in range(group_size)]
                )

            async def killer(t_start):
                await asyncio.sleep(t_kill)
                victim = replicas[n_base - 1]  # alive in BOTH fleets
                killed_at["t"] = time.perf_counter() - t_start
                print(
                    f"[autoscale] {label}: killing {victim.addr} at "
                    f"t={killed_at['t']:.2f}s",
                    file=sys.stderr,
                )
                await asyncio.get_running_loop().run_in_executor(
                    None, victim.kill
                )

            async def drive():
                t_start = time.perf_counter()
                try:
                    k = asyncio.ensure_future(killer(t_start))
                    await asyncio.gather(*[group(g) for g in range(n_groups)])
                    await k
                finally:
                    await close_current_session()

            t0 = time.perf_counter()
            asyncio.run(drive())
            wall = time.perf_counter() - t0
            sup_metrics = None
            rs = None
            if supervised:
                # billing snapshot at trace end: capacity actually
                # standing DURING the trace, integrated by the supervisor
                rs = float(st.sup.get_metrics()["replica_seconds"])
                # then let the control loop converge (the replacement
                # spawn may still be in flight) before reading counters
                deadline = time.monotonic() + 45.0
                while time.monotonic() < deadline:
                    m = st.sup.get_metrics()
                    if (
                        m["replacements_total"] >= 1
                        and m["pending_spawns"] == 0
                        and m["disruptive_inflight"] == 0
                    ):
                        break
                    time.sleep(0.25)
                sup_metrics = st.sup.get_metrics()
        finally:
            if st is not None:
                st.stop()
            rt.stop()
            with spawn_lock:
                fleet = replicas + spawned + spares
            for r in fleet:
                r.stop()
        tk = killed_at.get("t", wall)
        if not supervised:
            # a static deployment reserves the peak fleet for the whole
            # trace; the crash does not refund the reservation
            rs = n_peak * wall
        tarr = np.asarray(ttfts, dtype=np.float64)
        iarr = np.asarray(itls, dtype=np.float64) * 1e3
        return dict(
            done=done,
            wall=wall,
            kill_t=tk,
            rs=rs,
            alive_rs=n_start * min(tk, wall)
            + max(0, n_start - 1) * max(0.0, wall - tk),
            ttft_p50=float(np.percentile(tarr, 50)),
            ttft_p99=float(np.percentile(tarr, 99)),
            itl_p50=float(np.percentile(iarr, 50)) if iarr.size else 0.0,
            itl_p99=float(np.percentile(iarr, 99)) if iarr.size else 0.0,
            sup=sup_metrics,
        )

    static = run_fleet("static", supervised=False)
    elastic = run_fleet("elastic", supervised=True)

    assert len(static["done"]) == n_logical, (
        f"static fleet lost {n_logical - len(static['done'])} requests"
    )
    assert len(elastic["done"]) == n_logical, (
        f"supervised fleet lost {n_logical - len(elastic['done'])} requests"
    )
    diverged = sum(
        1 for k, v in static["done"].items() if elastic["done"].get(k) != v
    )
    assert diverged == 0, (
        f"{diverged} greedy streams diverged between the static and "
        f"supervised runs"
    )
    sup_m = elastic["sup"]
    assert sup_m["scale_ups_total"] >= 1, "the burst never scaled the fleet up"
    assert sup_m["replacements_total"] >= 1, (
        "the killed replica was never replaced"
    )
    assert sup_m["crash_loops_total"] == 0, "spawns crash-looped"
    ttft_ratio = elastic["ttft_p99"] / max(1e-9, static["ttft_p99"])
    itl_ratio = elastic["itl_p99"] / max(1e-9, static["itl_p99"])
    rs_ratio = elastic["rs"] / max(1e-9, static["rs"])
    assert ttft_ratio <= slo_band, (
        f"supervised p99 TTFT {elastic['ttft_p99']:.3f}s vs static "
        f"{static['ttft_p99']:.3f}s (ratio {ttft_ratio:.2f} > {slo_band})"
    )
    # the ratio gate OR an absolute grace floor: on the CPU smoke the
    # per-request decode spans are a few ms, so a sub-ms absolute gap
    # can read as a large ratio while meaning nothing for the SLO
    assert (
        itl_ratio <= slo_band
        or (elastic["itl_p99"] - static["itl_p99"]) <= itl_grace_ms
    ), (
        f"supervised p99 wall-ITL {elastic['itl_p99']:.2f}ms vs static "
        f"{static['itl_p99']:.2f}ms (ratio {itl_ratio:.2f} > {slo_band}, "
        f"gap > {itl_grace_ms}ms)"
    )
    assert rs_ratio <= 0.9, (
        f"supervised replica-seconds {elastic['rs']:.1f} not materially "
        f"below the static reservation {static['rs']:.1f} "
        f"(ratio {rs_ratio:.2f} > 0.9)"
    )
    return dict(
        autoscale_requests=n_logical,
        autoscale_lost=0,
        autoscale_duplicates=0,
        autoscale_streams_bitidentical=int(diverged == 0),
        autoscale_replica_seconds_ratio=1.0 / rs_ratio,
        autoscale_supervised_replica_seconds=elastic["rs"],
        autoscale_static_replica_seconds=static["rs"],
        autoscale_static_alive_replica_seconds=static["alive_rs"],
        autoscale_ttft_p99_ratio=ttft_ratio,
        autoscale_itl_p99_ratio=itl_ratio,
        autoscale_supervised_ttft_p50_s=elastic["ttft_p50"],
        autoscale_supervised_ttft_p99_s=elastic["ttft_p99"],
        autoscale_static_ttft_p99_s=static["ttft_p99"],
        autoscale_supervised_itl_p99_ms=elastic["itl_p99"],
        autoscale_static_itl_p99_ms=static["itl_p99"],
        autoscale_scale_ups=sup_m["scale_ups_total"],
        autoscale_scale_downs=sup_m["scale_downs_total"],
        autoscale_replacements=sup_m["replacements_total"],
        autoscale_spawn_failures=sup_m["spawn_failures_total"],
        autoscale_crash_loops=sup_m["crash_loops_total"],
        autoscale_supervised_wall_s=elastic["wall"],
        autoscale_static_wall_s=static["wall"],
        autoscale_kill_t_s=elastic["kill_t"],
    )


def bench_weightsync(model, n_pushes, chunk_mb, prompt_len, new_tokens):
    """Staged weight-sync bench: transfer time vs commit-pause time.

    Spins a real decode server (HTTP, loopback) + RemoteInfEngine client,
    keeps a background stream of generation running, and pushes fresh
    full-tree weights `n_pushes` times through the staged path. Reports the
    two windows the overlapped protocol splits: staging/transfer seconds
    (generation LIVE — tokens keep flowing) and commit-pause seconds (the
    only window generation stops), plus wire throughput and the tokens
    generated during the staging windows as direct overlap evidence.
    """
    import asyncio
    import threading

    import jax

    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine
    from areal_tpu.core.weight_transfer import flatten_named
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.launcher.decode_server import DecodeServer
    from areal_tpu.models.qwen2 import init_params

    dcfg = JaxDecodeConfig(
        context_length=prompt_len + new_tokens + 128,
        max_running_requests=8,
        # fine-grained chunks: the commit pause lands on a chunk boundary,
        # so chunk size sets the floor of the measured pause window
        new_tokens_per_chunk=min(8, new_tokens),
        dtype=model.dtype,
        kv_cache_dtype=model.dtype,
    )
    eng = JaxDecodeEngine(dcfg, InferenceEngineConfig())
    params = init_params(model, jax.random.PRNGKey(0))
    eng.set_model(params, model)
    eng.initialize()

    # serve over a private event loop in a daemon thread
    server = DecodeServer(JaxDecodeConfig(), engine=eng)
    loop = asyncio.new_event_loop()
    ready = threading.Event()
    addr_box = {}

    def _serve():
        asyncio.set_event_loop(loop)

        async def _start():
            addr_box["addr"] = await server.start(host="127.0.0.1", port=0)
            ready.set()

        loop.run_until_complete(_start())
        loop.run_forever()

    srv_thread = threading.Thread(target=_serve, daemon=True)
    srv_thread.start()
    assert ready.wait(60), "decode server failed to start"

    client = RemoteInfEngine(
        InferenceEngineConfig(setup_timeout=60, request_timeout=600)
    )
    client.initialize(addr=addr_box["addr"])

    # background generation stream: proves tokens flow through staging
    stop = threading.Event()
    rng = np.random.RandomState(7)
    prompts = [
        rng.randint(1, model.vocab_size, (prompt_len,)).tolist()
        for _ in range(64)
    ]
    g = GenerationHyperparameters(max_new_tokens=new_tokens, temperature=1.0)

    def _gen_loop(j):
        k = j
        while not stop.is_set():
            try:
                eng.generate(
                    ModelRequest(input_ids=prompts[k % len(prompts)], gconfig=g),
                    timeout=600,
                )
            except Exception as e:  # noqa: BLE001 — engine shutting down
                print(f"[weightsync] gen loop exit: {e!r}", file=sys.stderr)
                return
            k += 4
        return

    gen_threads = [
        threading.Thread(target=_gen_loop, args=(j,), daemon=True)
        for j in range(4)
    ]
    for t in gen_threads:
        t.start()

    # let generation reach steady state first: the commit pause waits for
    # the in-flight chunk, so measuring against a cold engine would charge
    # first-compile time to the pause window
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if eng.get_metrics()["generated_tokens_total"] > 4 * new_tokens:
            break
        time.sleep(0.1)

    named = flatten_named(params)
    wire_bytes = sum(a.nbytes for a in named.values())
    # untimed warm push (compiles nothing, but primes HTTP pools + staging)
    client.update_weights_from_tensor(named, version=1, chunk_mb=chunk_mb)
    base = client.get_metrics()
    tokens_during_staging = 0
    for i in range(n_pushes):
        tok0 = eng.get_metrics()["generated_tokens_total"]
        push_id = client.stage_weights(named, chunk_mb=chunk_mb)
        tok1 = eng.get_metrics()["generated_tokens_total"]
        client.commit_staged(push_id, version=i + 2)
        tokens_during_staging += tok1 - tok0
    m = client.get_metrics()
    stop.set()
    for t in gen_threads:
        t.join(timeout=30)
    client.destroy()

    async def _stop():
        await server.stop()
        loop.stop()

    asyncio.run_coroutine_threadsafe(_stop(), loop)
    srv_thread.join(timeout=30)
    eng.destroy()

    transfer_s = (m["staging_secs"] - base["staging_secs"]) / n_pushes
    commit_s = (m["commit_pause_secs"] - base["commit_pause_secs"]) / n_pushes
    return dict(
        weightsync_transfer_s=transfer_s,
        weightsync_commit_pause_s=commit_s,
        weightsync_pause_share=commit_s / max(transfer_s + commit_s, 1e-9),
        weightsync_wire_mb=wire_bytes / 1024 / 1024,
        weightsync_mb_per_s=wire_bytes / 1024 / 1024 / max(transfer_s, 1e-9),
        weightsync_tokens_during_staging=float(tokens_during_staging)
        / n_pushes,
        # raw(bf16-equivalent)/sent over the staged frames: 1.0 for fp
        # pushes, ~2x once the producer ships int8 + f32 scales (ISSUE 16)
        weightsync_wire_compression=m.get("weight_sync_compression", 1.0),
    )


def _pp_bubble_sim(pp, v, n_mbs, t_f, t_b, schedule="1f1b"):
    """Event-driven earliest-start execution of a pipeline timetable on pp
    independent ranks — the MPMD rendering the hybrid ICI/DCN mesh deploys
    (each slice runs its own stage stream; only activation/cotangent hops
    cross the DCN boundary). Jobs run in the schedule's per-rank order but
    start as soon as their cross-rank dependencies land, so the returned
    idle fraction is the timetable's intrinsic bubble. The lockstep SPMD
    scan that renders the same timetable inside ONE slice pads every round
    to the global round clock (its wall time is reported separately as
    `pp_*_step_s`); the simulated bubble is what the interleaving buys on
    the multi-slice deployment: ~(pp-1)/(v*M + pp-1) vs (pp-1)/(M + pp-1).

    t_f / t_b are per-CHUNK forward/backward costs (a chunk is 1/v of a
    rank's layers); the returned fraction is scale-invariant in them.
    """
    C = pp * v
    delta = C - 1
    rounds = (
        delta
        + ((n_mbs - 1) // pp) * C
        + (v - 1) * pp
        + (n_mbs - 1) % pp
        + pp
    )
    free = [0.0] * pp
    done_f: dict = {}
    done_b: dict = {}

    def run_f(s, m, vc):
        c = vc * pp + s
        dep = done_f[(m, c - 1)] if c else 0.0
        end = max(free[s], dep) + t_f
        free[s] = done_f[(m, c)] = end

    def run_b(s, m, vc, barrier=0.0):
        c = vc * pp + s
        dep = done_b[(m, c + 1)] if c < C - 1 else done_f[(m, C - 1)]
        end = max(free[s], dep, done_f[(m, c)], barrier) + t_b
        free[s] = done_b[(m, c)] = end

    if schedule == "gpipe":
        # all forwards in microbatch order, then all backwards in reverse
        # microbatch order, after a global barrier (the autodiff of the
        # round scan replays residuals only once every forward is done)
        for r in range(n_mbs + C - 1):
            for s in range(pp):
                for vc in range(v):
                    n = r - (vc * pp + s)
                    if 0 <= n < n_mbs:
                        run_f(s, n, vc)
        barrier = max(done_f.values())
        for r in range(n_mbs + C - 1):
            for s in reversed(range(pp)):
                for vc in reversed(range(v)):
                    n = r - ((C - 1) - (vc * pp + s))
                    if 0 <= n < n_mbs:
                        run_b(s, n_mbs - 1 - n, vc, barrier)
    else:  # the (interleaved) 1F1B timetable, same n-counter decode as
        # parallel/pipeline.py's round scan
        for r in range(rounds):
            for s in range(pp):
                n = r - s
                if n >= 0:
                    m = (n // C) * pp + n % pp
                    if m < n_mbs:
                        run_f(s, m, (n // pp) % v)
            for s in range(pp):
                nb = r - delta - (pp - 1 - s)
                if nb >= 0:
                    m = (nb // C) * pp + nb % pp
                    if m < n_mbs:
                        run_b(s, m, v - 1 - ((nb // pp) % v))
    makespan = max(done_b.values())
    busy = n_mbs * C * (t_f + t_b)
    return 1.0 - busy / (pp * makespan)


def bench_pp_schedules(model, pp, n_mbs, seq_len, warmup, iters):
    """Pipeline-schedule micro-bench: the SAME stacked micro-batch stream
    through the pp>1 trunk under "gpipe" vs "1f1b" vs "1f1b_interleaved"
    (v=1 and v=2), reporting per-leg wall time, the compiled program's
    temp (activation) memory, and the timetable's bubble fraction
    (`_pp_bubble_sim` with the leg's measured per-chunk cost). Two deltas
    matter: gpipe-vs-1f1b is the stash bound (gpipe residuals grow with M;
    1f1b is capped at 2·pp-1 stage inputs), and v=2-vs-v=1 is the
    interleaving trade — bubble shrinks ~1/v AND the per-round backward
    touches half the layers, so the transient vjp residual footprint
    (the temp-memory term that dominates past a few layers per stage)
    drops even as the stash grows to v·(2·pp-1) chunk inputs."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from areal_tpu.api.alloc_mode import ParallelStrategy
    from areal_tpu.api.cli_args import (
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.jax_engine import _memory_analysis_dict
    from areal_tpu.engine.sft.lm_engine import (
        JaxLMEngine,
        compute_packed_sft_loss,
    )

    ndev = jax.device_count()
    if ndev < pp or ndev % pp:
        return {"ppsched_skipped": f"{ndev} devices incompatible with pp={pp}"}

    # every leg needs L divisible by pp*v (v up to 2) and enough depth per
    # virtual chunk that the residual-vs-stash trade is visible
    v_max = 2
    L = model.num_hidden_layers
    if L < 2 * pp * v_max or L % (pp * v_max):
        L = max(L, 2 * pp * v_max)
        L += -L % (pp * v_max)
        model = _dc.replace(model, num_hidden_layers=L)

    rng = np.random.RandomState(0)
    stacked = {
        "input_ids": np.asarray(
            rng.randint(1, model.vocab_size, (n_mbs, seq_len)), np.int32
        ),
        "position_ids": np.tile(
            np.arange(seq_len, dtype=np.int32), (n_mbs, 1)
        ),
        "segment_ids": np.zeros((n_mbs, seq_len), np.int32),
        "loss_mask": np.ones((n_mbs, seq_len), np.int32),
    }
    stacked = {k: jnp.asarray(v) for k, v in stacked.items()}
    weights = jnp.ones((n_mbs,), jnp.float32)

    out = {"pp_size": pp, "pp_n_mbs": n_mbs, "pp_seq_len": seq_len}
    legs = (
        ("gpipe", "gpipe", 1),
        ("1f1b", "1f1b", 1),
        ("1f1b_interleaved_v1", "1f1b_interleaved", 1),
        ("1f1b_interleaved_v2", "1f1b_interleaved", 2),
    )
    for tag, sched, virt in legs:
        cfg = TrainEngineConfig(
            experiment_name="bench",
            trial_name="ppsched",
            path="",
            init_from_scratch=True,
            dtype=model.dtype,
            mb_spec=MicroBatchSpec(max_tokens_per_mb=seq_len),
            optimizer=OptimizerConfig(lr=1e-4),
            gradient_checkpointing=model.remat,
        )
        cfg.jax.pipeline_schedule = sched
        cfg.jax.virtual_pp_size = virt
        # the interleaved engine stores layers chunk-major, so each leg
        # gets a fresh engine (params re-initialized in its own layout)
        eng = JaxLMEngine(cfg)
        eng.model_config = model
        eng.create_process_group(
            ParallelStrategy(
                pipeline_parallel_size=pp, data_parallel_size=ndev // pp
            )
        )
        eng.initialize(None, FinetuneSpec(1, 1000, 1))
        fn = eng._get_pipelined_grad_step(compute_packed_sft_loss)
        compiled = fn.lower(eng.params, stacked, weights).compile()
        mem = _memory_analysis_dict(compiled)
        for _ in range(warmup):
            jax.block_until_ready(fn(eng.params, stacked, weights))
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(fn(eng.params, stacked, weights))
        step_s = (time.perf_counter() - t0) / iters
        eng.destroy()
        out[f"pp_{tag}_step_s"] = step_s
        out[f"pp_{tag}_temp_bytes"] = mem.get("temp_size_in_bytes", 0)
        t_chunk = step_s / (2 * n_mbs * virt)  # measured per-chunk cost
        out[f"pp_{tag}_bubble_frac"] = _pp_bubble_sim(
            pp, virt, n_mbs, t_chunk, t_chunk, schedule=sched
        )
    if out.get("pp_gpipe_temp_bytes"):
        out["pp_temp_ratio_gpipe_over_1f1b"] = out["pp_gpipe_temp_bytes"] / max(
            out["pp_1f1b_temp_bytes"], 1
        )
    v1, v2 = "pp_1f1b_interleaved_v1", "pp_1f1b_interleaved_v2"
    out["pp_bubble_ratio_v1_over_v2"] = out[f"{v1}_bubble_frac"] / max(
        out[f"{v2}_bubble_frac"], 1e-9
    )
    out["pp_temp_ratio_v1_over_v2"] = out[f"{v1}_temp_bytes"] / max(
        out[f"{v2}_temp_bytes"], 1
    )
    return out


def bench_prefix_decode(model, n_groups, group_size, prompt_len, new_tokens):
    """Prefill-heavy decode, grouped vs ungrouped prompts.

    GRPO issues group_size samples of the SAME prompt; the engine prefills
    each unique prompt once and forks the KV for the rest (jax_decode.py
    prefix registry). This measures that win directly: identical token
    volume, (a) every prompt unique (one prefill per request) vs (b)
    n_groups unique prompts shared group_size ways (one prefill per group).
    """
    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.models.qwen2 import init_params

    import jax

    n_requests = n_groups * group_size
    dcfg = JaxDecodeConfig(
        context_length=prompt_len + new_tokens + 128,
        max_running_requests=n_requests,
        new_tokens_per_chunk=min(32, new_tokens),
        dtype=model.dtype,
        kv_cache_dtype=model.dtype,
    )
    g = GenerationHyperparameters(
        max_new_tokens=new_tokens, temperature=1.0, top_p=1.0
    )
    rng = np.random.RandomState(7)
    params = init_params(model, jax.random.PRNGKey(0))

    def run(prompts: list[list[int]]) -> float:
        eng = JaxDecodeEngine(
            dcfg, InferenceEngineConfig(max_concurrent_rollouts=n_requests)
        )
        eng.set_model(params, model)
        eng.initialize()

        def batch(ps, timed: bool) -> float:
            eng.pause_generation()  # line up all requests, then go
            with ThreadPoolExecutor(max_workers=n_requests) as pool:
                futs = [
                    pool.submit(
                        eng.generate,
                        ModelRequest(input_ids=list(p), gconfig=g),
                        1800,
                    )
                    for p in ps
                ]
                while eng._request_q.qsize() < len(ps):
                    time.sleep(0.01)
                t0 = time.perf_counter()
                eng.continue_generation()
                results = [f.result() for f in futs]
                dt = time.perf_counter() - t0
            gen = sum(len(r.output_tokens) for r in results)
            return gen / dt if timed else 0.0

        try:
            # Shape-representative warm pass: a full UNTIMED batch with the
            # same duplication pattern but fresh random tokens, so every
            # program the timed pass needs — batched-prefill B∈{1,2,4,8}
            # per bucket, the fork path, and the chunk-fn active-row
            # buckets hit while the batch drains — is compiled before the
            # clock starts. (A 2-request warmup once left the B=8 wave and
            # drain buckets compiling INSIDE the timing; measured "speedup"
            # was mostly compile noise: 1.4x where steady state is ~6x.)
            # Warm prompts share no prefix with the timed ones, so the
            # prefix registry cannot leak warm KV into the measurement.
            warm = [
                rng.randint(1, model.vocab_size, (prompt_len,)).tolist()
                for _ in range(len(set(map(tuple, prompts))))
            ]
            pattern = {}
            warm_prompts = []
            for p in prompts:
                key = tuple(p)
                if key not in pattern:
                    pattern[key] = warm[len(pattern)]
                warm_prompts.append(list(pattern[key]))
            batch(warm_prompts, timed=False)
            return batch(prompts, timed=True)
        finally:
            eng.destroy()

    unique = [
        rng.randint(1, model.vocab_size, (prompt_len,)).tolist()
        for _ in range(n_requests)
    ]
    grouped = []
    for i in range(n_groups):
        grouped.extend([list(unique[i])] * group_size)
    tps_unique = run(unique)
    tps_grouped = run(grouped)
    return dict(
        prefix_ungrouped_tok_s=tps_unique,
        prefix_grouped_tok_s=tps_grouped,
        prefix_share_speedup=tps_grouped / max(tps_unique, 1e-9),
        prefix_groups=n_groups,
        prefix_group_size=group_size,
        prefix_prompt_len=prompt_len,
    )


def bench_grpo(
    model,
    n_prompts,
    group_size,
    prompt_len,
    new_tokens,
    warmup_steps,
    steps,
    mb_tokens,
):
    """The real thing: async GRPO end-to-end — decode-engine rollouts
    through the RLVR workflow (staleness-gated, >=2 batches in flight),
    decoupled-loss PPO update, weight push back into the decode engine.

    Accounting matches the reference's benchmark README
    (benchmark/verl_v0_3_0_post1_76084d3/README.md:33-43): throughput =
    total effective tokens / end-to-end wall time over the timed steps;
    additionally samples/sec/chip (BASELINE.json's primary metric) and
    rollout generated-tokens/sec.
    """
    from areal_tpu.api.alloc_mode import ParallelStrategy
    from areal_tpu.api.cli_args import (
        InferenceEngineConfig,
        JaxDecodeConfig,
        MicroBatchSpec,
        NormConfig,
        OptimizerConfig,
        PPOActorConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec, WeightUpdateMeta
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.engine.ppo.actor import JaxPPOActor

    samples_per_step = n_prompts * group_size
    actor_cfg = PPOActorConfig(
        experiment_name="bench",
        trial_name="grpo",
        path="",
        init_from_scratch=True,
        dtype=model.dtype,
        mb_spec=MicroBatchSpec(max_tokens_per_mb=mb_tokens),
        optimizer=OptimizerConfig(
            lr=1e-5,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=model.remat,
        group_size=group_size,
        ppo_n_minibatches=1,
        eps_clip=0.2,
        kl_ctl=0.0,
        adv_norm=NormConfig(
            mean_level="group", std_level="group", group_size=group_size
        ),
        use_decoupled_loss=True,
        temperature=1.0,
    )
    actor = JaxPPOActor(actor_cfg)
    actor.model_config = model
    actor.create_process_group(ParallelStrategy())
    actor.initialize(None, FinetuneSpec(1, 100_000, samples_per_step))

    rollout = JaxDecodeEngine(
        JaxDecodeConfig(
            context_length=prompt_len + new_tokens + 128,
            max_running_requests=64,
            new_tokens_per_chunk=min(128, new_tokens),
            dtype=model.dtype,
            kv_cache_dtype=model.dtype,
        ),
        InferenceEngineConfig(
            max_concurrent_rollouts=samples_per_step * 2,
            consumer_batch_size=samples_per_step,
            max_head_offpolicyness=2,
            request_timeout=3600,
        ),
    )
    rollout.set_model(actor.params, model)
    rollout.initialize()
    actor.connect_engine(rollout, WeightUpdateMeta.from_memory())
    try:
        return _bench_grpo_run(
            actor, rollout, model, n_prompts, group_size, prompt_len,
            new_tokens, warmup_steps, steps,
        )
    finally:
        # _retry_transport re-enters on transient failure: leaked engines
        # would stack KV caches + optimizer state until a hard OOM
        rollout.destroy()
        actor.destroy()


def _bench_grpo_run(
    actor, rollout, model, n_prompts, group_size, prompt_len,
    new_tokens, warmup_steps, steps,
):
    import jax

    from areal_tpu.api.cli_args import GenerationHyperparameters
    from areal_tpu.workflow.rlvr import RLVRWorkflow

    samples_per_step = n_prompts * group_size
    rng = np.random.RandomState(3)

    class CycleLoader:
        """prepare_batch keeps >=2 batches in flight; never run dry."""

        batch_size = n_prompts  # prompts per training batch

        def __iter__(self):
            while True:
                yield [
                    dict(
                        input_ids=rng.randint(
                            1, model.vocab_size, (prompt_len,)
                        ).tolist()
                    )
                    for _ in range(n_prompts)
                ]

    def reward(prompt, completion, prompt_ids, completion_ids, **kw):
        # synthetic verifiable reward: cheap, deterministic, nonzero spread
        return float(sum(completion_ids[:8]) % 7) / 7.0

    workflow = RLVRWorkflow(
        reward,
        GenerationHyperparameters(
            n_samples=group_size,
            max_new_tokens=new_tokens,
            temperature=1.0,
            top_p=1.0,
        ),
    )
    loader = CycleLoader()

    loader_it = iter(loader)

    def one_step(version: int, sync: bool = False):
        # time_perf breakdown (reference accounting,
        # benchmark/verl_v0_3_0_post1_76084d3/README.md:33-43): e2e =
        # rollout-wait + train + weight-push. Rollout-wait is what the
        # trainer BLOCKS on — async generation overlaps ≥2 batches deep;
        # sync mode submits THIS step's prompts and waits for them (the
        # reference's synchronous-RL baseline, blog/AReaL_v0_3.md:10).
        t0 = time.perf_counter()
        if sync:
            batch = rollout.rollout_batch(next(loader_it), workflow=workflow)
        else:
            batch = rollout.prepare_batch(loader, workflow=workflow)
        rollout_wait_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        batch["prox_logp"] = actor.compute_logp(batch)
        actor.compute_advantages(batch)
        stats = actor.ppo_update(batch)
        train_s = time.perf_counter() - t1
        actor.set_version(version)
        t_push = time.perf_counter()
        rollout.pause()
        actor.update_weights(None)
        rollout.set_version(version)
        rollout.resume()
        push_s = time.perf_counter() - t_push
        gen_tokens = int((batch["versions"] >= 0).sum())
        total_tokens = int(batch["attention_mask"].sum())
        return gen_tokens, total_tokens, rollout_wait_s, train_s, push_s, stats

    version = 0
    for _ in range(warmup_steps):
        version += 1
        one_step(version, sync=True)  # sync warmup compiles every program

    # Sync baseline FIRST (an async phase leaves >=2 batches in flight,
    # which would subsidize a later sync measurement).
    sync_steps = max(2, steps - 1)
    t0 = time.perf_counter()
    for _ in range(sync_steps):
        version += 1
        one_step(version, sync=True)
    sync_e2e = time.perf_counter() - t0

    version += 1
    one_step(version)  # untimed: fill the async pipeline

    gen_tot = tok_tot = 0
    wait_tot = train_tot = push_tot = 0.0
    t0 = time.perf_counter()
    for _ in range(steps):
        version += 1
        gen_tokens, total_tokens, wait_s, train_s, push_s, _ = one_step(
            version
        )
        gen_tot += gen_tokens
        tok_tot += total_tokens
        wait_tot += wait_s
        train_tot += train_s
        push_tot += push_s
    e2e = time.perf_counter() - t0
    n_chips = max(jax.device_count(), 1)
    return dict(
        grpo_sync_step_time_s=sync_e2e / sync_steps,
        grpo_async_vs_sync_speedup=(sync_e2e / sync_steps) / (e2e / steps),
        grpo_samples_per_sec_per_chip=samples_per_step * steps / e2e / n_chips,
        grpo_rollout_tokens_per_sec_per_chip=gen_tot / e2e / n_chips,
        grpo_effective_tokens_per_sec_per_chip=tok_tot / e2e / n_chips,
        grpo_step_time_s=e2e / steps,
        grpo_time_rollout_wait_s=wait_tot / steps,
        grpo_time_train_s=train_tot / steps,
        grpo_weight_push_s=push_tot / steps,
        grpo_prompts_per_step=n_prompts,
        grpo_group_size=group_size,
        grpo_new_tokens=new_tokens,
        grpo_steps=steps,
    )


def bench_chaostrain(
    model,
    n_prompts,
    group_size,
    prompt_len,
    new_tokens,
    steps,
    mb_tokens,
    kill_step=2,
):
    """Trainer-side chaos: a small deterministic GRPO loop killed at seeded
    fault points (mid engine.save, the save-vs-marker gap, the
    consume-vs-dump gap, mid weight-push), resumed from the committed
    recovery point, and checked against an unfaulted oracle — plus a leg
    where the NEWEST committed checkpoint is deliberately torn and recovery
    must fall back to its predecessor.

    Proof obligations per leg (the headline is the AND of all of them):
    - exactly-once: the sample-ledger WAL ends with one entry per training
      step, rid union == every generated trajectory, 0 lost / 0 duplicated
      (the wait()-to-dump window is rolled back and replayed, never
      double-journaled);
    - monotone weight versions: the resumed engine version equals the
      committed version, WAL entry versions never regress;
    - bit-determinism: post-resume per-step losses and the final weight
      fingerprint match the oracle (greedy decoding + shuffle-free loader +
      rollout_id-sorted batches + fixed init keys make the loop replayable).
    """
    import shutil
    import tempfile

    from areal_tpu.api.alloc_mode import ParallelStrategy
    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
        MicroBatchSpec,
        NormConfig,
        OptimizerConfig,
        PPOActorConfig,
        RecoverConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec, StepInfo, WeightUpdateMeta
    from areal_tpu.core import fault_injection
    from areal_tpu.core.fault_injection import (
        FaultPlan,
        FaultPoint,
        InjectedFault,
    )
    from areal_tpu.core.sample_ledger import SampleWAL
    from areal_tpu.dataset import SimpleDataLoader
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.engine.ppo.actor import JaxPPOActor
    from areal_tpu.utils import recover as recover_mod
    from areal_tpu.utils.recover import RecoverHandler, ledger_wal_path
    from areal_tpu.workflow.rlvr import RLVRWorkflow

    samples_per_step = n_prompts * group_size
    rng = np.random.RandomState(11)
    # fixed dataset, one epoch == `steps` batches: every leg sees the same
    # prompts in the same order (loader position is checkpointed state)
    dataset = [
        dict(input_ids=rng.randint(1, model.vocab_size, (prompt_len,)).tolist())
        for _ in range(n_prompts * steps)
    ]
    ft_spec = FinetuneSpec(1, len(dataset), samples_per_step)

    def reward(prompt, completion, prompt_ids, completion_ids, **kw):
        return float(sum(completion_ids[:8]) % 7) / 7.0

    class Env:
        pass

    def build(fileroot):
        env = Env()
        env.rcfg = RecoverConfig(
            experiment_name="bench", trial_name="chaostrain",
            fileroot=fileroot, mode="fault", freq_steps=1, keep_last=2,
        )
        actor_cfg = PPOActorConfig(
            experiment_name="bench",
            trial_name="chaostrain",
            path="",
            init_from_scratch=True,  # fixed PRNG keys: identical across legs
            dtype=model.dtype,
            mb_spec=MicroBatchSpec(max_tokens_per_mb=mb_tokens),
            optimizer=OptimizerConfig(
                lr=1e-3,
                warmup_steps_proportion=0.0,
                lr_scheduler_type="constant",
                gradient_clipping=1.0,
            ),
            gradient_checkpointing=model.remat,
            group_size=group_size,
            ppo_n_minibatches=1,
            eps_clip=0.2,
            kl_ctl=0.0,
            # batch-level normalization: greedy decoding makes group members
            # identical, so group-level norm would zero every advantage and
            # the oracle would be a trivially-flat loop
            adv_norm=NormConfig(
                mean_level="batch", std_level="batch", group_size=group_size
            ),
            use_decoupled_loss=True,
            temperature=1.0,
        )
        env.actor = JaxPPOActor(actor_cfg)
        env.actor.model_config = model
        env.actor.create_process_group(ParallelStrategy())
        env.actor.initialize(None, ft_spec)
        env.rollout = JaxDecodeEngine(
            JaxDecodeConfig(
                context_length=prompt_len + new_tokens + 128,
                max_running_requests=64,
                new_tokens_per_chunk=min(128, new_tokens),
                dtype=model.dtype,
                kv_cache_dtype=model.dtype,
            ),
            InferenceEngineConfig(
                max_concurrent_rollouts=samples_per_step * 2,
                consumer_batch_size=samples_per_step,
                max_head_offpolicyness=steps + 2,
                request_timeout=3600,
            ),
        )
        env.rollout.set_model(env.actor.params, model)
        env.rollout.initialize()
        env.actor.connect_engine(env.rollout, WeightUpdateMeta.from_memory())
        env.rollout.attach_ledger_wal(ledger_wal_path(env.rcfg))
        env.workflow = RLVRWorkflow(
            reward,
            GenerationHyperparameters(
                n_samples=group_size, max_new_tokens=new_tokens,
                temperature=1.0, top_p=1.0, greedy=True,
            ),
        )
        env.loader = SimpleDataLoader(
            dataset, batch_size=n_prompts, shuffle=False
        )
        env.handler = RecoverHandler(env.rcfg, ft_spec)
        return env

    def destroy(env):
        env.rollout.destroy()
        env.actor.destroy()

    def _si(g):
        return StepInfo(
            epoch=0, epoch_step=g, global_step=g, steps_per_epoch=steps
        )

    def _loss_of(stats):
        s = stats[0]
        for k in ("loss", "actor/loss"):
            if k in s:
                return float(s[k])
        for k in sorted(s):
            if k.endswith("loss"):
                return float(s[k])
        return float("nan")

    def _fingerprint(actor):
        import jax

        return float(
            sum(
                float(np.abs(np.asarray(x)).sum())
                for x in jax.tree_util.tree_leaves(actor.params)
            )
        )

    def one_step(env, g):
        batch = env.rollout.rollout_batch(
            next(env.data_iter), workflow=env.workflow
        )
        # wait() shuffles result order; re-sort by the ledger's rollout_id
        # stamp so the training batch is identical across crash/resume legs
        order = np.argsort(np.asarray(batch["rollout_id"]), kind="stable")
        batch = {k: np.asarray(v)[order] for k, v in batch.items()}
        batch["prox_logp"] = env.actor.compute_logp(batch)
        env.actor.compute_advantages(batch)
        stats = env.actor.ppo_update(batch)
        env.actor.set_version(g + 1)
        env.rollout.pause()
        env.actor.update_weights(None)
        env.rollout.set_version(g + 1)
        env.rollout.resume()
        return _loss_of(stats)

    def dump(env, g):
        """Returns True when a dump-internal fault seam fired (the injector
        aborts mid-dump, RecoverHandler degrades — the on-disk state is
        exactly a process that died there, so the leg abandons the loop)."""
        before = fault_injection.snapshot()
        env.handler.dump(
            env.actor, _si(g), dataloader=env.loader, rollout=env.rollout
        )
        after = fault_injection.snapshot()
        return any(after.get(k, 0) > before.get(k, 0) for k in after)

    def run_leg(fileroot, plan):
        """Run to completion or the seeded kill; returns (committed per-step
        losses, crashed step or None, final fingerprint or None)."""
        env = build(fileroot)
        env.data_iter = iter(env.loader)
        if plan is not None:
            fault_injection.configure(plan)
        losses, crashed_at, fp = {}, None, None
        try:
            for g in range(steps):
                try:
                    loss = one_step(env, g)
                except InjectedFault:
                    crashed_at = g
                    break
                if dump(env, g):
                    crashed_at = g
                    break
                losses[g] = loss
            if crashed_at is None:
                fp = _fingerprint(env.actor)
        finally:
            fault_injection.deactivate()
            destroy(env)
        return losses, crashed_at, fp

    def resume_leg(fileroot, committed_losses):
        """Fresh env (a restarted trainer), recover, replay to completion."""
        env = build(fileroot)
        try:
            info = env.handler.load(
                env.actor,
                dataloader=env.loader,
                inference_engine=env.rollout,
                weight_update_meta=WeightUpdateMeta.from_memory(),
            )
            assert info is not None, "no recoverable state after crash"
            start = info.last_step_info.next().global_step
            resumed_version = env.actor.get_version()
            env.data_iter = iter(env.loader)
            losses = dict(committed_losses)
            for g in range(start, steps):
                losses[g] = one_step(env, g)
                dump(env, g)
            return dict(
                start=start,
                resumed_version=resumed_version,
                losses=losses,
                fp=_fingerprint(env.actor),
                wal=SampleWAL(ledger_wal_path(env.rcfg)).replay(),
            )
        finally:
            destroy(env)

    def check_wal(wal):
        versions = [e["version"] for e in wal]
        rids = [r for e in wal for r in e["rids"]]
        lost = steps * n_prompts - len(set(rids))
        dup = len(rids) - len(set(rids))
        exactly_once = (
            versions == list(range(steps)) and lost == 0 and dup == 0
        )
        monotonic = versions == sorted(versions)
        return exactly_once, monotonic, lost, dup

    KILL_SITES = (
        "recover.dump.save",     # mid engine.save: torn tmp dir left behind
        "recover.dump.marker",   # save-vs-marker gap: sealed but uncommitted
        "train.step",            # consume-vs-dump gap: batch journaled, not committed
        "train.weights.push",    # mid push: update applied in memory, lost
    )
    tmp_roots = []

    def mkroot(tag):
        d = tempfile.mkdtemp(prefix=f"chaostrain-{tag}-")
        tmp_roots.append(d)
        return d

    try:
        # -- oracle: the unfaulted run every leg must reproduce ----------
        oracle_root = mkroot("oracle")
        oracle_losses, crashed, oracle_fp = run_leg(oracle_root, None)
        assert crashed is None and len(oracle_losses) == steps
        ora_wal = SampleWAL(
            ledger_wal_path(
                RecoverConfig(
                    experiment_name="bench", trial_name="chaostrain",
                    fileroot=oracle_root, mode="fault",
                )
            )
        ).replay()
        ora_once, ora_mono, _, _ = check_wal(ora_wal)

        legs = []
        loss_diffs, fp_diffs = [], []
        all_once, all_mono = ora_once, ora_mono
        lost_total = dup_total = 0

        # -- seeded kill legs -------------------------------------------
        for site in KILL_SITES:
            root = mkroot(site.replace(".", "-"))
            plan = FaultPlan(
                seed=5,
                points=(
                    FaultPoint(
                        site=site, mode="abort", at=(kill_step,), times=1
                    ),
                ),
            )
            committed, crashed_at, _ = run_leg(root, plan)
            assert crashed_at == kill_step, (site, crashed_at)
            res = resume_leg(root, committed)
            assert res["start"] == kill_step, (site, res["start"])
            once, mono, lost, dup = check_wal(res["wal"])
            mono = mono and res["resumed_version"] == res["start"]
            diff = max(
                abs(res["losses"][g] - oracle_losses[g]) for g in range(steps)
            )
            fpd = abs(res["fp"] - oracle_fp)
            legs.append(
                dict(site=site, crashed_at=crashed_at, resume=res["start"],
                     once=once, loss_diff=diff)
            )
            loss_diffs.append(diff)
            fp_diffs.append(fpd)
            all_once &= once
            all_mono &= mono
            lost_total += lost
            dup_total += dup

        # -- torn-newest leg: bit-rot the newest COMMITTED checkpoint ----
        torn_root = mkroot("torn")
        full_losses, crashed, _ = run_leg(torn_root, None)
        assert crashed is None
        rcfg_t = RecoverConfig(
            experiment_name="bench", trial_name="chaostrain",
            fileroot=torn_root, mode="fault", keep_last=2,
        )
        newest = os.path.join(
            recover_mod.recover_root(rcfg_t), f"step-{steps - 1}"
        )
        with open(os.path.join(newest, "recover_info.pkl"), "ab") as f:
            f.write(b"\x00bitrot")  # size+checksum mismatch vs manifest
        recover_mod.reset_metrics()
        # drop the committed final step's losses: the torn checkpoint means
        # step steps-1 must be REPLAYED from the predecessor, not trusted
        res = resume_leg(torn_root, {g: full_losses[g] for g in range(steps - 1)})
        torn_skipped = recover_mod.get_metrics()["recover_torn_skipped_total"]
        assert res["start"] == steps - 1, res["start"]
        once, mono, lost, dup = check_wal(res["wal"])
        torn_diff = max(
            abs(res["losses"][g] - oracle_losses[g]) for g in range(steps)
        )
        loss_diffs.append(torn_diff)
        fp_diffs.append(abs(res["fp"] - oracle_fp))
        all_once &= once
        all_mono &= mono
        lost_total += lost
        dup_total += dup
        legs.append(
            dict(site="torn-newest", crashed_at=None, resume=res["start"],
                 once=once, loss_diff=torn_diff)
        )

        max_loss_diff = max(loss_diffs)
        max_fp_diff = max(fp_diffs)
        ok = (
            all_once
            and all_mono
            and lost_total == 0
            and dup_total == 0
            and torn_skipped >= 1
            and max_loss_diff < 1e-6
            and max_fp_diff < 1e-4
        )
        return dict(
            chaostrain_exactly_once=ok,
            chaostrain_kill_legs=len(KILL_SITES),
            chaostrain_lost_samples=lost_total,
            chaostrain_double_trained=dup_total,
            chaostrain_versions_monotonic=all_mono,
            chaostrain_loss_max_abs_diff=max_loss_diff,
            chaostrain_fingerprint_max_abs_diff=max_fp_diff,
            chaostrain_torn_skipped=int(torn_skipped),
            chaostrain_steps=steps,
            chaostrain_kill_step=kill_step,
            chaostrain_legs=[
                f"{leg['site']}@{leg['crashed_at']}→resume{leg['resume']}"
                f" once={leg['once']} Δloss={leg['loss_diff']:.2e}"
                for leg in legs
            ],
        )
    finally:
        for d in tmp_roots:
            shutil.rmtree(d, ignore_errors=True)


# --mode choice -> bench entry point. The argparse choices are derived from
# this table and the dev-mode headline metrics live beside it, so a new mode
# cannot ship half-wired; tests/test_bench_modes.py pins the sync.
BENCH_MODE_FNS = {
    "train": bench_train,
    "decode": bench_decode_compare,
    "pagedattn": bench_paged_compare,
    "prefix": bench_prefix_decode,
    "grpo": bench_grpo,
    "ppsched": bench_pp_schedules,
    "weightsync": bench_weightsync,
    "specdecode": bench_spec_compare,
    "kvoffload": bench_kvoffload,
    "kvquant": bench_kvquant,
    "wquant": bench_wquant,
    "fleet": bench_fleet,
    "chaos": bench_chaos,
    "chaostrain": bench_chaostrain,
    "disagg": bench_disagg,
    "kvfabric": bench_kvfabric,
    "autoscale": bench_autoscale,
}
BENCH_MODES = ("all", *BENCH_MODE_FNS)
# headline metric per dev mode (modes that skip the trainer MFU line)
MODE_HEADLINES = {
    "decode": ("decode_tokens_per_sec_per_chip", "tok/s/chip"),
    "pagedattn": ("paged_over_ws_speedup", "x"),
    "prefix": ("prefix_share_speedup", "x"),
    "grpo": ("grpo_samples_per_sec_per_chip", "samples/s/chip"),
    "ppsched": ("pp_bubble_ratio_v1_over_v2", "x"),
    "weightsync": ("weightsync_commit_pause_s", "s"),
    "specdecode": ("spec_over_off_speedup", "x"),
    "kvoffload": ("kvoffload_resume_ttft_speedup", "x"),
    "kvquant": ("kvquant_capacity_ratio", "x"),
    "wquant": ("wquant_wire_bytes_ratio", "x"),
    "fleet": ("fleet_affinity_ttft_p50_speedup", "x"),
    "chaos": ("chaos_exactly_once", "bool"),
    "chaostrain": ("chaostrain_exactly_once", "bool"),
    "disagg": ("disagg_decode_itl_p99_speedup", "x"),
    "kvfabric": ("kvfabric_warm_ttft_speedup", "x"),
    "autoscale": ("autoscale_replica_seconds_ratio", "x"),
}


def _emit(metric: str, value: float, detail: dict) -> None:
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 4),
                "unit": "fraction_of_peak",
                "vs_baseline": round(value / BASELINE_TRAINER_MFU, 3),
                "detail": detail,
            }
        ),
        flush=True,
    )


def _run_child(env_extra: dict, timeout: float) -> dict | None:
    """Run this script as a child bench; return its parsed JSON line."""
    env = dict(os.environ)
    env["AREAL_BENCH_CHILD"] = "1"
    env.update(env_extra)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {"__error__": f"bench child timed out after {timeout:.0f}s"}
    except Exception as e:  # noqa: BLE001 — orchestrator must not die
        return {"__error__": f"bench child failed to launch: {e!r}"}
    sys.stderr.write(out.stderr[-4000:])
    for ln in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(ln)
        except json.JSONDecodeError:
            continue
    tail = (out.stderr or out.stdout or "")[-1500:]
    return {"__error__": f"bench child rc={out.returncode}: {tail}"}


def _orchestrate() -> None:
    """Parent mode. Invariant: a JSON line is on stdout within the first
    few minutes, no matter what the accelerator relay does.

    Round-4 postmortem: the old order (accel probing first, CPU fallback
    last) emitted NOTHING when the driver's wall-clock limit landed inside
    the 2700 s accel-probe budget during a relay outage (BENCH_r04.json
    rc=124, parsed=null). So the phases are now:

      1. CPU smoke FIRST — cheap, bounded, its line printed immediately
         with ``tpu_unavailable: "pending"``. From this point the driver
         always has a parsed line, whenever it kills us.
      2. Accelerator attempts for the remaining budget (watchdogged
         subprocess per attempt; a hung backend init costs one watchdog
         window, not the bench). On success the TPU line is printed LAST,
         superseding the smoke line for a driver that parses the final
         JSON line.
      3. If the relay never answers, re-print the CPU line with
         ``tpu_unavailable: true`` + the accel error, so the final line
         carries the outage diagnosis.

    The budget is env-tunable: AREAL_BENCH_BUDGET (total wall seconds,
    default 3300) or a driver-provided absolute deadline in
    AREAL_BENCH_DEADLINE (unix epoch seconds) — whichever is sooner.
    """
    t_start = time.monotonic()
    total_budget = float(os.environ.get("AREAL_BENCH_BUDGET", 3300))
    deadline = t_start + total_budget
    env_deadline = os.environ.get("AREAL_BENCH_DEADLINE")
    if env_deadline:
        try:
            deadline = min(deadline, time.monotonic() + (float(env_deadline) - time.time()))
        except ValueError:
            pass

    # Phase 1: CPU smoke line, immediately. Never allowed to outlive the
    # deadline — a tight driver window must still see this line.
    cpu_timeout = max(
        60.0, min(1200.0, (deadline - t_start) * 0.4, deadline - time.monotonic() - 30.0)
    )
    cpu_rec = _run_child({"JAX_PLATFORMS": "cpu"}, cpu_timeout)
    cpu_ok = cpu_rec is not None and "__error__" not in cpu_rec
    if cpu_ok:
        d = cpu_rec.setdefault("detail", {})
        d["tpu_unavailable"] = "pending"
        print(json.dumps(cpu_rec), flush=True)
    else:
        _emit(
            "trainer_mfu_unavailable",
            0.0,
            {
                "tpu_unavailable": "pending",
                "cpu_fallback_error": (cpu_rec or {}).get("__error__", "")[:1000],
            },
        )

    # Phase 2: accelerator attempts with whatever budget remains.
    accel_error = "no accel attempt fit in the budget"
    attempt = 0
    while time.monotonic() < deadline - 60:
        attempt += 1
        rec = _run_child({}, max(60.0, deadline - time.monotonic()))
        if rec is not None and "__error__" not in rec:
            print(json.dumps(rec), flush=True)
            return
        accel_error = (rec or {}).get("__error__", "unknown")
        print(
            f"[bench] accelerator attempt {attempt} failed: {accel_error}",
            file=sys.stderr,
        )
        # A hung backend init (watchdog rc=17) or transport-class failure
        # can be a transient relay outage: retry within the budget. A real
        # crash (anything else) will not heal — stop burning the budget.
        healable = "rc=17" in accel_error or any(
            m in accel_error for m in _TRANSPORT_MARKERS
        )
        if not healable:
            break
        time.sleep(min(30.0, max(0.0, deadline - time.monotonic())))

    # Phase 3: final line = the CPU result stamped with the outage.
    # `tpu_unavailable` is the machine-readable infra marker: the
    # accelerator could not be reached/initialized — NOT that the bench
    # code is broken (the CPU line above proves the code runs).
    if cpu_ok:
        d = cpu_rec.setdefault("detail", {})
        d["accelerator_error"] = accel_error[:2000]
        d["tpu_unavailable"] = True
        print(json.dumps(cpu_rec), flush=True)
    else:
        _emit(
            "trainer_mfu_unavailable",
            0.0,
            {
                "accelerator_error": accel_error[:2000],
                "tpu_unavailable": True,
                "cpu_fallback_error": (cpu_rec or {}).get("__error__", "")[:1000],
            },
        )


def _arm_backend_watchdog(seconds: float | None = None):
    """Kill the child if jax backend init hangs (relay down ≠ error: calls
    block forever). Disarmed once devices enumerate. 120 s covers the
    ~60 s healthy first contact; a hung init is killed fast so the
    orchestrator's retry loop gets more bites at the budget."""
    if seconds is None:
        seconds = float(os.environ.get("AREAL_BENCH_INIT_WATCHDOG", 120))
    import threading

    timer = threading.Timer(
        seconds,
        lambda: (
            print(
                f"[bench] jax backend init hung >{seconds:.0f}s; aborting child",
                file=sys.stderr,
                flush=True,
            ),
            os._exit(17),
        ),
    )
    timer.daemon = True
    timer.start()
    return timer


def main() -> None:
    from areal_tpu.platforms import (
        enable_compilation_cache,
        honor_jax_platforms_env,
    )

    honor_jax_platforms_env()  # the CPU-fallback child sets JAX_PLATFORMS=cpu
    enable_compilation_cache()  # warm reruns skip the 10-min relay compiles

    watchdog = _arm_backend_watchdog()

    import jax

    from areal_tpu.models.qwen2 import ModelConfig

    dev = jax.devices()[0]
    watchdog.cancel()
    on_accel = dev.platform != "cpu"
    mode = os.environ.get("AREAL_BENCH_MODE", "all")

    def want(m: str) -> bool:
        return mode in ("all", m)

    if on_accel:
        preflight()
        # The fused vocab-chunked LM loss (ops/fused_xent.py) removes the
        # f32 [T, vocab] logits from HBM, which frees enough memory to run
        # WITHOUT remat at the 4096-token micro-batch — measured 0.312 MFU
        # vs 0.274 with remat (v5e). Keep remat=True as the OOM fallback so
        # a busier chip still produces a number instead of a crash.
        def flagship(remat: bool) -> ModelConfig:
            return ModelConfig(
                vocab_size=151936,
                hidden_size=896,
                intermediate_size=4864,
                num_hidden_layers=24,
                num_attention_heads=14,
                num_key_value_heads=2,
                tie_word_embeddings=True,
                dtype="bfloat16",
                param_dtype="bfloat16",
                remat=remat,
                scan_layers=True,
            )

        def train_attempt(remat: bool):
            return _retry_transport(
                lambda: bench_train(
                    flagship(remat),
                    tokens_per_step=65536,
                    seq_len=1024,
                    mb_tokens=4096,
                    warmup=2,
                    iters=5,
                ),
                what=f"bench_train(remat={remat})",
                attempts=4,
                base_delay=15.0,
                # no-remat attempt: an OOM goes straight to the remat
                # fallback instead of burning retry cycles
                retry_oom=remat,
            )

        model = flagship(False)
        train = {"mfu": 0.0}
        decode = {}
        if want("train"):
            try:
                train = train_attempt(False)
            except Exception as e:  # noqa: BLE001 — fall back on OOM only
                if _OOM_MARKER not in f"{type(e).__name__}: {e}":
                    raise
                print(
                    "[bench] no-remat step OOMed; retrying with remat",
                    file=sys.stderr,
                    flush=True,
                )
                model = flagship(True)
                train = train_attempt(True)
        if want("decode"):
            decode = _retry_transport(
                lambda: bench_decode_compare(
                    model, n_requests=128, prompt_len=128, new_tokens=256,
                    max_running=64,
                ),
                what="bench_decode",
                attempts=3,
                base_delay=15.0,
            )
        if want("pagedattn"):
            decode.update(
                _retry_transport(
                    lambda: bench_paged_compare(
                        model, n_requests=128, prompt_len=128, new_tokens=256,
                        max_running=64,
                    ),
                    what="bench_paged_compare",
                    attempts=3,
                    base_delay=15.0,
                )
            )
        if want("prefix"):
            decode.update(
                _retry_transport(
                    lambda: bench_prefix_decode(
                        model, n_groups=4, group_size=8, prompt_len=512,
                        new_tokens=32,
                    ),
                    what="bench_prefix_decode",
                    attempts=3,
                    base_delay=15.0,
                )
            )
        if want("ppsched"):
            decode.update(
                _retry_transport(
                    lambda: bench_pp_schedules(
                        flagship(True), pp=2, n_mbs=8, seq_len=1024,
                        warmup=1, iters=3,
                    ),
                    what="bench_pp_schedules",
                    attempts=2,
                    base_delay=15.0,
                )
            )
        if want("weightsync"):
            decode.update(
                _retry_transport(
                    lambda: bench_weightsync(
                        model, n_pushes=3, chunk_mb=64, prompt_len=128,
                        new_tokens=128,
                    ),
                    what="bench_weightsync",
                    attempts=2,
                    base_delay=15.0,
                )
            )
        if want("specdecode"):
            decode.update(
                _retry_transport(
                    lambda: bench_spec_compare(
                        model, n_requests=64, prompt_len=128, new_tokens=256,
                        max_running=64, spec_k=7,
                    ),
                    what="bench_spec_compare",
                    attempts=3,
                    base_delay=15.0,
                )
            )
        if want("kvoffload"):
            decode.update(
                _retry_transport(
                    lambda: bench_kvoffload(
                        model, n_sessions=96, prompt_len=512, new_tokens=256,
                        max_running=64, host_mb=2048.0,
                    ),
                    what="bench_kvoffload",
                    attempts=3,
                    base_delay=15.0,
                )
            )
        if want("kvquant"):
            decode.update(
                _retry_transport(
                    # pool_mb sized so the fp pool holds ~half the 96
                    # concurrent (512+256)-token sessions while int8
                    # holds nearly all of them
                    lambda: bench_kvquant(
                        model, n_sessions=96, prompt_len=512,
                        new_tokens=256, max_running=64, pool_mb=300.0,
                    ),
                    what="bench_kvquant",
                    attempts=3,
                    base_delay=15.0,
                )
            )
        if want("wquant"):
            decode.update(
                _retry_transport(
                    # same session mix as kvquant: pool_mb sized so the
                    # bf16-weight engine pressures its pool while int8's
                    # freed weight HBM keeps the working set resident
                    lambda: bench_wquant(
                        model, n_sessions=96, prompt_len=512,
                        new_tokens=256, max_running=64, pool_mb=300.0,
                    ),
                    what="bench_wquant",
                    attempts=3,
                    base_delay=15.0,
                )
            )
        if want("fleet"):
            decode.update(
                _retry_transport(
                    lambda: bench_fleet(
                        model, n_replicas=3, n_groups=8, group_size=8,
                        prompt_len=512, new_tokens=128, max_running=32,
                    ),
                    what="bench_fleet",
                    attempts=2,
                    base_delay=15.0,
                )
            )
        if want("chaos"):
            decode.update(
                _retry_transport(
                    lambda: bench_chaos(
                        model, n_replicas=2, n_groups=4, group_size=4,
                        prompt_len=256, new_tokens=64, max_running=16,
                    ),
                    what="bench_chaos",
                    attempts=2,
                    base_delay=15.0,
                )
            )
        if want("disagg"):
            decode.update(
                _retry_transport(
                    lambda: bench_disagg(
                        model, n_decode_reqs=16, n_prefill_reqs=8,
                        prompt_short=64, prompt_long=2048, new_tokens=256,
                        max_running=32, drain_sessions=8, drain_prompt=512,
                        drain_tokens=128,
                    ),
                    what="bench_disagg",
                    attempts=2,
                    base_delay=15.0,
                )
            )
        if want("kvfabric"):
            decode.update(
                _retry_transport(
                    # long prompts so the avoided prefill dominates the
                    # warm TTFT; default page size (128) keeps the kernel
                    # attention path — 7 complete blocks per 1k prompt
                    lambda: bench_kvfabric(
                        model, prompt_len=1024, head_len=512, tail_len=128,
                        new_tokens=64, n_dedup=8, max_running=24,
                        chunk=8, n_ttft_reps=3,
                    ),
                    what="bench_kvfabric",
                    attempts=2,
                    base_delay=15.0,
                )
            )
        if want("autoscale"):
            decode.update(
                _retry_transport(
                    lambda: bench_autoscale(
                        # chunked decode (32 scheduler round trips per
                        # request) keeps the burst backlog standing for
                        # several supervisor ticks; elastic pays the
                        # scale-up lag in the burst tail, so the SLO band
                        # is looser than parity — the headline is the
                        # replica-seconds bill
                        model, n_base=2, n_peak=4, n_groups=16,
                        group_size=8, prompt_len=256, new_tokens=128,
                        max_running=16, chunk=4, kill_after_s=1.0,
                        slo_band=1.25,
                    ),
                    what="bench_autoscale",
                    attempts=2,
                    base_delay=15.0,
                )
            )
        if want("grpo"):
            # GRPO co-locates trainer (fwd+bwd+opt) and decode engine on
            # one chip: run the actor with remat on to leave HBM headroom
            # for the decode param copy + KV cache.
            def grpo_attempt():
                return bench_grpo(
                    flagship(True),
                    n_prompts=16,
                    group_size=8,
                    prompt_len=128,
                    new_tokens=256,
                    warmup_steps=1,
                    steps=3,
                    mb_tokens=4096,
                )

            decode.update(
                _retry_transport(
                    grpo_attempt, what="bench_grpo", attempts=3,
                    base_delay=15.0,
                )
            )
        if want("train"):
            # Scale evidence: the largest model one v5e chip fits per the
            # HBM estimator (utils/hbm.py) — Qwen2.5-3B geometry with LoRA
            # (bf16 base 6.2 GiB, adamw state only on adapters; full-FT
            # 1.5B needs 18.6 GiB and does NOT fit). Bonus metric: failure
            # must not cost the primary line.
            def lora3b():
                m = ModelConfig(
                    vocab_size=151936,
                    hidden_size=2048,
                    intermediate_size=11008,
                    num_hidden_layers=36,
                    num_attention_heads=16,
                    num_key_value_heads=2,
                    tie_word_embeddings=True,
                    dtype="bfloat16",
                    param_dtype="bfloat16",
                    remat=True,
                    scan_layers=True,
                    lora_rank=32,
                    lora_alpha=64.0,
                )
                return bench_train(
                    m, tokens_per_step=16384, seq_len=1024, mb_tokens=4096,
                    warmup=1, iters=3,
                )

            try:
                r = _retry_transport(
                    lora3b, what="bench_train_3b_lora", attempts=2,
                    base_delay=15.0,
                )
                train.update({f"lora3b_{k}": v for k, v in r.items()})
            except Exception as e:  # noqa: BLE001
                print(f"[bench] 3B-LoRA bonus phase failed: {e}", file=sys.stderr)
        metric = "trainer_mfu_qwen2.5-0.5b_bf16_packed_sft"
    else:  # CPU smoke fallback so the harness always emits a line
        model = ModelConfig(
            vocab_size=1024,
            hidden_size=128,
            intermediate_size=256,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            dtype="float32",
            param_dtype="float32",
        )
        train = {"mfu": 0.0}
        decode = {}
        if want("train"):
            train = bench_train(
                model, tokens_per_step=512, seq_len=128, mb_tokens=640,
                warmup=1, iters=3,
            )
        if want("decode"):
            # enough CHUNKS per request that the steady-state decode loop
            # dominates admission/prefill transients — the run-ahead vs
            # sync comparison is meaningless on a one-chunk-per-request
            # window, so chunk=8 gives an 8-deep stream per request
            decode = bench_decode_compare(
                model, n_requests=8, prompt_len=16, new_tokens=64,
                max_running=4, chunk=8,
            )
        if want("pagedattn"):
            # same steady-state-dominated shape as the decode smoke: enough
            # chunks per request that the per-chunk gather/scatter (or its
            # absence) is what the timed window measures
            decode.update(
                bench_paged_compare(
                    model, n_requests=8, prompt_len=16, new_tokens=64,
                    max_running=4, chunk=8,
                )
            )
        if want("prefix"):
            decode.update(
                bench_prefix_decode(
                    model, n_groups=2, group_size=2, prompt_len=32,
                    new_tokens=8,
                )
            )
        if want("ppsched"):
            decode.update(
                bench_pp_schedules(
                    model, pp=2, n_mbs=8, seq_len=128, warmup=1, iters=2
                )
            )
        if want("weightsync"):
            decode.update(
                bench_weightsync(
                    model, n_pushes=2, chunk_mb=0.01, prompt_len=16,
                    new_tokens=32,
                )
            )
        if want("specdecode"):
            # long enough generation that the greedy echo cycle locks in
            # and most verify chunks ride at full acceptance (the ramp-in
            # chunks before the cycle establishes accept little)
            decode.update(
                bench_spec_compare(
                    model, n_requests=8, prompt_len=16, new_tokens=192,
                    max_running=4, chunk=8, spec_k=7,
                )
            )
        if want("kvoffload"):
            # pool slots (4) well below the 8-session working set, long
            # prompts so the avoided re-prefill dominates the resume TTFT
            decode.update(
                bench_kvoffload(
                    model, n_sessions=8, prompt_len=256, new_tokens=64,
                    max_running=4, host_mb=64.0, chunk=8,
                )
            )
        if want("kvquant"):
            # pool_mb sized so the f32 pool pressures the 4-slot working
            # set (8 sessions x 320 tokens) while int8 holds it resident
            decode.update(
                bench_kvquant(
                    model, n_sessions=8, prompt_len=256, new_tokens=64,
                    max_running=4, pool_mb=0.7, chunk=8,
                )
            )
        if want("wquant"):
            # tiny-model weights are small vs the pool, so the smoke
            # mostly proves mechanics (wire ratio, commit pause, drift);
            # the capacity headroom story is the TPU leg's job
            decode.update(
                bench_wquant(
                    model, n_sessions=8, prompt_len=256, new_tokens=64,
                    max_running=4, pool_mb=0.7, chunk=8, n_push=2,
                )
            )
        if want("fleet"):
            # prompts long enough (>= 64-token affinity block AND the
            # engine's 64-token min shared prefix) that affinity routing
            # can turn group members into dup-prompt forks / session
            # turns into suffix prefills on the affine replica
            decode.update(
                bench_fleet(
                    model, n_replicas=2, n_groups=4, group_size=4,
                    prompt_len=128, new_tokens=16, max_running=4, chunk=8,
                )
            )
        if want("chaos"):
            # greedy streams + a seeded 7-point schedule over 2 decode
            # replicas + 1 prefill replica; prompts past the 64-token
            # affinity block so the chaos trace exercises the same
            # fork/suffix reuse paths the fleet smoke does while faults
            # land mid-stream AND mid-KV-handoff
            decode.update(
                bench_chaos(
                    model, n_replicas=2, n_groups=3, group_size=2,
                    prompt_len=96, new_tokens=16, max_running=4, chunk=8,
                )
            )
        if want("disagg"):
            # long prefills (256 tok on the tiny model) landing mid-trace
            # against 8-token decode chunks: the co-located baseline
            # serializes each prefill ahead of the next decode chunk, the
            # disaggregated fleet never does — that gap is the p99 ITL
            # headline. Drain leg: 4 sessions (greedy+sampled alternating)
            # per kv layout, migrated mid-stream and resumed bit-identically
            decode.update(
                bench_disagg(
                    model, n_decode_reqs=8, n_prefill_reqs=4,
                    prompt_short=48, prompt_long=1024, new_tokens=256,
                    max_running=16, chunk=4, drain_sessions=4,
                    drain_prompt=96, drain_tokens=48,
                )
            )
        if want("kvfabric"):
            # 32-token blocks (xla attention) and 1k prompts: the
            # warm-started replica's suffix prefill runs 32 tokens where
            # the cold one runs 1024 — long enough that the avoided
            # prefill clears the scheduler-tick noise floor on CPU.
            # Dedup leg: 4 requests sharing a 128-token head (4 complete
            # blocks) with 32-token divergent tails
            decode.update(
                bench_kvfabric(
                    model, prompt_len=1024, head_len=128, tail_len=32,
                    new_tokens=16, n_dedup=4, max_running=16, chunk=8,
                    n_ttft_reps=3, page_size=32, attn_impl="xla",
                )
            )
        if want("autoscale"):
            # diurnal lull -> burst -> lull with a mid-burst replica kill:
            # the supervised fleet starts at the 2-replica floor, rides
            # the burst up toward the 3-replica peak, replaces the killed
            # replica, and sheds the surplus in the trailing lull, while
            # the static comparator reserves the peak fleet throughout
            decode.update(
                bench_autoscale(
                    # sized so the burst (7 groups x 4 members, ~0.5s per
                    # 64-token request at chunk 2) holds in-flight demand
                    # well above the 2-replica capacity for ~1.5s — several
                    # supervisor ticks — with the kill landing mid-burst.
                    # The smoke's SLO band is wide: single-process CPU
                    # percentiles are GIL/compile-cache noise — the
                    # machinery and the exactly-once claims are what this
                    # smoke pins
                    model, n_base=2, n_peak=3, n_groups=24, group_size=4,
                    prompt_len=64, new_tokens=64, max_running=4, chunk=2,
                    # the kill lands after the supervised fleet has reached
                    # peak, so BOTH fleets lose a working replica mid-burst
                    kill_after_s=1.25, slo_band=2.5, itl_grace_ms=2.0,
                )
            )
        if want("grpo"):
            decode.update(
                bench_grpo(
                    model, n_prompts=2, group_size=2, prompt_len=16,
                    new_tokens=16, warmup_steps=1, steps=2, mb_tokens=256,
                )
            )
        if want("chaostrain"):
            # 4-step deterministic GRPO loop (greedy decode, shuffle-free
            # loader, batch-level adv norm) killed at each seeded trainer
            # seam at step 2, resumed from the committed recovery point and
            # checked against the unfaulted oracle; plus the torn-newest
            # checkpoint leg recovering from the predecessor
            decode.update(
                bench_chaostrain(
                    model, n_prompts=2, group_size=2, prompt_len=16,
                    new_tokens=16, steps=4, mb_tokens=256,
                )
            )
        metric = "trainer_mfu_cpu_smoke"

    detail = {
        "device": dev.device_kind,
        "mode": mode,
        **{k: round(v, 4) if isinstance(v, float) else v for k, v in train.items()},
        **{k: round(v, 4) if isinstance(v, float) else v for k, v in decode.items()},
    }
    if "step_time_s" in train:
        detail["step_time_s"] = round(train["step_time_s"], 3)
    if mode in ("all", "train"):
        _emit(metric, train["mfu"], detail)
    else:
        # dev modes skip the trainer: emitting the MFU metric as 0.0 would
        # read as a catastrophic regression. Headline the mode's own number.
        headline = MODE_HEADLINES[mode]
        print(
            json.dumps(
                {
                    "metric": f"bench_{mode}_{'cpu_smoke' if not on_accel else 'tpu'}",
                    "value": round(float(decode.get(headline[0], 0.0)), 4),
                    "unit": headline[1],
                    "vs_baseline": 0.0,
                    "detail": detail,
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    if os.environ.get("AREAL_BENCH_CHILD"):
        # child mode: one measurement attempt; the parent handles fallback
        main()
    else:
        import argparse

        p = argparse.ArgumentParser()
        p.add_argument(
            "--mode",
            default=os.environ.get("AREAL_BENCH_MODE", "all"),
            choices=list(BENCH_MODES),
            help="which measurements to run (default: all)",
        )
        args = p.parse_args()
        os.environ["AREAL_BENCH_MODE"] = args.mode  # children inherit
        _orchestrate()
        sys.exit(0)
