"""Benchmark harness: one JSON line for the driver.

Two measurements on whatever accelerator is attached:

1. TRAIN (primary metric): GSPMD trainer packed-SFT step on the flagship
   Qwen2.5-0.5B geometry (bf16, remat, scan-over-layers, Pallas flash
   attention) at a realistic 64k tokens/step. MFU uses the explicit
   per-token matmul FLOPs model (areal_tpu/utils/flops.py) — embedding
   *lookup* excluded, lm_head matmul + causal attention term included —
   against the chip's bf16 peak.
2. DECODE (detail): in-process continuous-batching engine
   (areal_tpu/engine/jax_decode.py) serving concurrent requests; reports
   steady-state generated tokens/sec/chip — the rollout half of the
   async-RL throughput story (BASELINE.md "rollout tokens/sec").

`vs_baseline` compares trainer MFU to 0.20 — the ballpark dense-model
train-step MFU of the reference's Megatron/FSDP GPU trainer in the
published boba² runs (BASELINE.md; AReaL does not publish MFU directly,
0.20 is the standard H800 Megatron figure for this class of run).
"""

from __future__ import annotations

import json
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

BASELINE_TRAINER_MFU = 0.20


def bench_train(model, tokens_per_step, seq_len, mb_tokens, warmup, iters):
    from areal_tpu.api.alloc_mode import ParallelStrategy
    from areal_tpu.api.cli_args import (
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.sft.lm_engine import JaxLMEngine
    from areal_tpu.utils.data import pad_sequences_to_tensors

    cfg = TrainEngineConfig(
        experiment_name="bench",
        trial_name="b",
        path="",
        init_from_scratch=True,
        dtype=model.dtype,
        mb_spec=MicroBatchSpec(max_tokens_per_mb=mb_tokens),
        optimizer=OptimizerConfig(
            lr=1e-4,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=model.remat,
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = model
    eng.create_process_group(ParallelStrategy())
    eng.initialize(None, FinetuneSpec(1, 1000, 1))

    rng = np.random.RandomState(0)
    seqs = [
        dict(
            input_ids=rng.randint(1, model.vocab_size, (seq_len,)),
            loss_mask=np.ones(seq_len, dtype=np.int32),
        )
        for _ in range(tokens_per_step // seq_len)
    ]
    batch = pad_sequences_to_tensors(seqs)

    for _ in range(warmup):
        eng.train_lm(batch)
    stats = []
    t0 = time.perf_counter()
    for _ in range(iters):
        stats.append(eng.train_lm(batch))
    dt = (time.perf_counter() - t0) / iters
    eng.destroy()
    # engine-reported MFU (same flops model), averaged over timed iters
    mfu = float(np.mean([s["mfu"] for s in stats]))
    tps = float(np.mean([s["tokens_per_sec_per_chip"] for s in stats]))
    return dict(
        mfu=mfu,
        tokens_per_sec_per_chip=tps,
        step_time_s=dt,
        tokens_per_step=tokens_per_step,
    )


def bench_decode(model, n_requests, prompt_len, new_tokens, max_running):
    from areal_tpu.api.cli_args import (
        GenerationHyperparameters,
        InferenceEngineConfig,
        JaxDecodeConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.engine.jax_decode import JaxDecodeEngine
    from areal_tpu.models.qwen2 import init_params

    import jax

    dcfg = JaxDecodeConfig(
        context_length=prompt_len + new_tokens + 128,
        max_running_requests=max_running,
        new_tokens_per_chunk=min(128, new_tokens),
        dtype=model.dtype,
        kv_cache_dtype=model.dtype,
    )
    eng = JaxDecodeEngine(dcfg, InferenceEngineConfig(max_concurrent_rollouts=n_requests))
    eng.set_model(init_params(model, jax.random.PRNGKey(0)), model)
    eng.initialize()

    rng = np.random.RandomState(1)
    g = GenerationHyperparameters(
        max_new_tokens=new_tokens, temperature=1.0, top_p=1.0
    )

    def one(i):
        req = ModelRequest(
            input_ids=rng.randint(1, model.vocab_size, (prompt_len,)).tolist(),
            gconfig=g,
        )
        return eng.generate(req, timeout=1800)

    with ThreadPoolExecutor(max_workers=n_requests) as pool:
        # warmup wave triggers prefill+chunk compiles
        list(pool.map(one, range(max(2, max_running // 8))))
        t0 = time.perf_counter()
        results = list(pool.map(one, range(n_requests)))
        dt = time.perf_counter() - t0
    eng.destroy()
    gen_tokens = sum(len(r.output_tokens) for r in results)
    return dict(
        decode_tokens_per_sec_per_chip=gen_tokens / dt,
        decode_requests=n_requests,
        decode_new_tokens=new_tokens,
    )


def main() -> None:
    import jax

    from areal_tpu.models.qwen2 import ModelConfig

    dev = jax.devices()[0]
    on_accel = dev.platform != "cpu"

    if on_accel:
        model = ModelConfig(
            vocab_size=151936,
            hidden_size=896,
            intermediate_size=4864,
            num_hidden_layers=24,
            num_attention_heads=14,
            num_key_value_heads=2,
            tie_word_embeddings=True,
            dtype="bfloat16",
            param_dtype="bfloat16",
            remat=True,
            scan_layers=True,
        )
        # mb of 4096 tokens: the f32 [T, vocab] logits + their grad dominate
        # HBM (151936-wide vocab → ~2.5 GiB per 4k tokens); 16 grad-accum
        # micro-batches make up the 64k-token step.
        train = bench_train(
            model,
            tokens_per_step=65536,
            seq_len=1024,
            mb_tokens=4096,
            warmup=2,
            iters=5,
        )
        decode = bench_decode(
            model, n_requests=128, prompt_len=128, new_tokens=256,
            max_running=64,
        )
        metric = "trainer_mfu_qwen2.5-0.5b_bf16_packed_sft"
    else:  # CPU smoke fallback so the harness always emits a line
        model = ModelConfig(
            vocab_size=1024,
            hidden_size=128,
            intermediate_size=256,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            dtype="float32",
            param_dtype="float32",
        )
        train = bench_train(
            model, tokens_per_step=512, seq_len=128, mb_tokens=640,
            warmup=1, iters=3,
        )
        decode = bench_decode(
            model, n_requests=4, prompt_len=16, new_tokens=16, max_running=4
        )
        metric = "trainer_mfu_cpu_smoke"

    detail = {
        "device": dev.device_kind,
        **{k: round(v, 4) if isinstance(v, float) else v for k, v in train.items()},
        **{k: round(v, 1) if isinstance(v, float) else v for k, v in decode.items()},
    }
    detail["step_time_s"] = round(train["step_time_s"], 3)
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(train["mfu"], 4),
                "unit": "fraction_of_peak",
                "vs_baseline": round(train["mfu"] / BASELINE_TRAINER_MFU, 3),
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
