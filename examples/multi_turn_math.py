"""Multi-turn self-correction math RL — wrong answers get feedback
("Your answer is incorrect. Please try again.") and another attempt, with
rewards discounted per extra turn.

Parity: /root/reference/examples/multi-turn-math/ (train.py +
multi_turn_workflow.py: evaluate each turn, append feedback on failure,
discount the final reward by gamma^turns). The TPU build's
MultiTurnWorkflow (workflow/multi_turn.py) keeps the whole conversation in
one token stream with feedback spans loss-masked, so the trainer consumes
an ordinary packed batch.

Usage:

  # offline smoke (CPU, synthetic arithmetic):
  python examples/multi_turn_math.py --config examples/configs/multi_turn_math.yaml \\
      tokenizer_path=synthetic-arith train_dataset.path=synthetic-arith \\
      actor.path= decode.model_path= actor.init_from_scratch=true

  # single-host TPU, GSM8K with Qwen2.5-0.5B:
  python examples/multi_turn_math.py --config examples/configs/multi_turn_math.yaml
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from areal_tpu.platforms import honor_jax_platforms_env

honor_jax_platforms_env()

from gsm8k_grpo import main as grpo_main


def main(argv):
    grpo_main(list(argv) + ["workflow=multi_turn"])


if __name__ == "__main__":
    from areal_tpu.utils.experiment import run_with_status

    run_with_status(main, sys.argv[1:])
