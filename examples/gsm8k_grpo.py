"""Async GRPO on GSM8K — the runnable entry point of the TPU build.

Parity: /root/reference/examples/math/gsm8k_grpo.py:34 (single-file training
script; user owns the loop). TPU differences: the train engine is the GSPMD
JaxPPOActor (one process drives all local chips), and rollout either runs
in-process on the same chips (COLOCATE — the default when `allocation_mode`
is empty or has no `+`) or against decode-server subprocesses spawned by the
local launcher (DECOUPLED — `allocation_mode: "jax:d1t1+d1"` style).

Usage:

  # fully offline smoke (CPU or one chip; synthetic arithmetic dataset):
  python examples/gsm8k_grpo.py --config examples/configs/arith_grpo_smoke.yaml

  # single-host TPU, colocated decode + train, Qwen2.5-0.5B on GSM8K:
  python examples/gsm8k_grpo.py --config examples/configs/gsm8k_grpo.yaml

  # decoupled: launcher spawns decode server(s) then this trainer:
  python -m areal_tpu.launcher.local examples/gsm8k_grpo.py \
      --config examples/configs/gsm8k_grpo.yaml \
      allocation_mode=jax:d1t1+d1

Override any config field with key=value, e.g. `actor.optimizer.lr=1e-5`.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.platforms import honor_jax_platforms_env

honor_jax_platforms_env()  # make JAX_PLATFORMS=cpu smoke runs stay on CPU

from areal_tpu.api.alloc_mode import AllocationMode, AllocationType
from areal_tpu.api.cli_args import GRPOConfig, load_expr_config, save_config
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo, WeightUpdateMeta
from areal_tpu.dataset import (
    SimpleDataLoader,
    get_custom_dataset,
    load_tokenizer,
)
from areal_tpu.engine.ppo.actor import JaxPPOActor
from areal_tpu.utils import seeding, stats_tracker
from areal_tpu.utils.evaluator import Evaluator
from areal_tpu.utils.recover import RecoverHandler, ledger_wal_path
from areal_tpu.utils.saver import Saver
from areal_tpu.utils.stats_logger import StatsLogger
from areal_tpu.workflow.rlvr import RLVRWorkflow


def gsm8k_reward_fn(prompt, completion, prompt_ids, completion_ids, **data):
    from areal_tpu.reward.math_parser import math_verify_reward

    return math_verify_reward(prompt, completion, prompt_ids, completion_ids, **data)




def pick_reward_fn(dataset_path: str):
    name = dataset_path.split("/")[-1].lower()
    if name == "countdown":
        from areal_tpu.reward.countdown import countdown_reward

        return countdown_reward
    if name == "clevr_count_70k":
        from areal_tpu.reward.vqa import clevr_count_reward

        return clevr_count_reward
    if name == "geometry3k":
        from areal_tpu.reward.vqa import geometry3k_reward

        return geometry3k_reward
    if name == "synthetic-arith":
        from areal_tpu.dataset.arith import arith_reward_fn

        return arith_reward_fn
    if name == "synthetic-vision":
        from areal_tpu.reward.vqa import synthetic_vision_reward

        return synthetic_vision_reward
    return gsm8k_reward_fn


def build_rollout(config: GRPOConfig, alloc: AllocationMode, actor, tokenizer):
    """COLOCATE -> in-process decode engine sharing the actor's chips;
    DECOUPLED -> HTTP client over launcher-spawned decode servers."""
    if alloc.type_ == AllocationType.DECOUPLED_TRAIN:
        from areal_tpu.core.remote_inf_engine import (
            JaxDecodeBackend,
            RemoteInfEngine,
        )

        rollout = RemoteInfEngine(
            config.rollout, JaxDecodeBackend(), tokenizer=tokenizer
        )
        rollout.initialize(
            train_data_parallel_size=actor.data_parallel_world_size
        )
        meta = WeightUpdateMeta(type="dcn")
        return rollout, meta
    # COLOCATE: decode engine on the trainer's devices, memory weight updates
    from areal_tpu.engine.jax_decode import JaxDecodeEngine

    # tokenizer enables server-side stop STRINGS (TIR's ``` terminator);
    # stop token ids work either way
    rollout = JaxDecodeEngine(config.decode, config.rollout, tokenizer=tokenizer)
    rollout.set_model(actor.params, actor.model_config)
    if config.workflow == "vision_rlvr" and not config.decode.model_path:
        # offline vision smoke: tiny tower + smoke image token, so the
        # synthetic-vision dataset serves end-to-end without hub access
        import jax

        from areal_tpu.models.qwen2_vl import init_vision_params
        from areal_tpu.models.smoke import (
            SMOKE_IMAGE_TOKEN,
            smoke_mrope_sections,
            smoke_vision_config,
        )

        vis = smoke_vision_config()
        rollout.set_vision_model(
            init_vision_params(vis, jax.random.PRNGKey(7)),
            vis,
            SMOKE_IMAGE_TOKEN,
            mrope_sections=smoke_mrope_sections(),
        )
    rollout.initialize()
    return rollout, WeightUpdateMeta.from_memory(alloc)


def main(args):
    config, _ = load_expr_config(args, GRPOConfig)
    config: GRPOConfig

    rank = int(os.getenv("AREAL_TPU_PROCESS_ID", "0"))
    seeding.set_random_seed(config.seed, key=f"trainer{rank}")
    tokenizer = load_tokenizer(config.tokenizer_path)

    from areal_tpu.utils import name_resolve

    name_resolve.reconfigure(config.cluster.name_resolve)
    alloc = AllocationMode.from_str(config.allocation_mode)

    actor = JaxPPOActor(config.actor)
    if not config.actor.path:
        # Offline smoke mode: no HF checkpoint — train the canonical tiny
        # from-scratch decoder (shared with the decode server's
        # --scratch-model mode so decoupled smoke runs line up).
        from areal_tpu.models.smoke import smoke_model_config

        actor.model_config = smoke_model_config(
            dtype=config.actor.dtype,
            vocab_size=getattr(tokenizer, "vocab_size", None),
        )
    actor.create_process_group(alloc.train)

    train_dataset = get_custom_dataset(
        path=config.train_dataset.path,
        split="train",
        type=config.train_dataset.type or "rl",
        tokenizer=tokenizer,
        max_length=config.train_dataset.max_length,
        rank=actor.data_parallel_rank,
        world_size=actor.data_parallel_world_size,
    )
    valid_dataset = get_custom_dataset(
        path=(config.valid_dataset or config.train_dataset).path,
        split="test",
        type=(config.valid_dataset or config.train_dataset).type or "rl",
        tokenizer=tokenizer,
        max_length=(config.valid_dataset or config.train_dataset).max_length,
        rank=actor.data_parallel_rank,
        world_size=actor.data_parallel_world_size,
    )
    train_dataloader = SimpleDataLoader(
        train_dataset,
        batch_size=config.train_dataset.batch_size,
        shuffle=config.train_dataset.shuffle,
        seed=config.seed,
        drop_last=config.train_dataset.drop_last,
    )
    valid_dataloader = SimpleDataLoader(
        valid_dataset,
        batch_size=(config.valid_dataset or config.train_dataset).batch_size,
        shuffle=False,
    )
    steps_per_epoch = len(train_dataloader)
    ft_spec = FinetuneSpec(
        total_train_epochs=config.total_train_epochs,
        dataset_size=steps_per_epoch * config.train_dataset.batch_size,
        train_batch_size=config.train_dataset.batch_size,
    )
    actor.initialize(None, ft_spec)

    rollout, weight_update_meta = build_rollout(config, alloc, actor, tokenizer)
    actor.connect_engine(rollout, weight_update_meta)

    ref = None
    if config.actor.kl_ctl > 0 and config.ref is not None and config.ref.path:
        ref = JaxPPOActor(config.ref)
        ref.model_config = actor.model_config
        ref.create_process_group(alloc.train)
        ref.initialize(None, ft_spec)

    reward_fn = pick_reward_fn(config.train_dataset.path)
    if getattr(tokenizer, "eos_token_id", None) is not None:
        if tokenizer.eos_token_id not in config.gconfig.stop_token_ids:
            config.gconfig.stop_token_ids.append(tokenizer.eos_token_id)
    if config.workflow not in ("rlvr", "multi_turn", "vision_rlvr", "tir"):
        raise ValueError(
            f"workflow={config.workflow!r} not in "
            "('rlvr', 'multi_turn', 'vision_rlvr', 'tir')"
        )
    processor = None
    if config.workflow == "vision_rlvr":
        from areal_tpu.models.smoke import OFFLINE_SENTINELS

        if config.tokenizer_path not in OFFLINE_SENTINELS:
            from transformers import AutoProcessor

            processor = AutoProcessor.from_pretrained(config.tokenizer_path)
        # offline: the synthetic-vision dataset ships pre-tokenized prompts
        # + pre-processed patches, so no processor is needed

    def make_workflow(gconfig, dump_dir=None):
        if config.workflow == "multi_turn":
            # self-correction loop: wrong answer -> feedback prompt ->
            # retry, rewards discounted per extra turn (ref:
            # examples/multi-turn-math/train.py)
            from areal_tpu.workflow.multi_turn import MultiTurnWorkflow

            return MultiTurnWorkflow(
                reward_fn=reward_fn,
                gconfig=gconfig,
                tokenizer=tokenizer,
                max_turns=config.max_turns,
                turn_discount=config.turn_discount,
                dump_dir=dump_dir,
            )
        if config.workflow == "tir":
            # tool-integrated reasoning: ```python blocks execute in a
            # sandbox mid-generation (ref: examples/tir/tir_workflow.py)
            from areal_tpu.workflow.tir import TIRWorkflow

            return TIRWorkflow(
                reward_fn=reward_fn,
                gconfig=gconfig,
                tokenizer=tokenizer,
                max_tool_calls=config.max_tool_calls,
                tool_timeout_seconds=config.tool_timeout_seconds,
                dump_dir=dump_dir,
            )
        if config.workflow == "vision_rlvr":
            from areal_tpu.workflow.vision_rlvr import VisionRLVRWorkflow

            return VisionRLVRWorkflow(
                reward_fn=reward_fn,
                gconfig=gconfig,
                tokenizer=tokenizer,
                processor=processor,
                dump_dir=dump_dir,
            )
        return RLVRWorkflow(
            reward_fn=reward_fn,
            gconfig=gconfig,
            tokenizer=tokenizer,
            dump_dir=dump_dir,
        )

    workflow = make_workflow(
        config.gconfig,
        dump_dir=os.path.join(
            StatsLogger.get_log_path(config.stats_logger), "generated"
        ),
    )
    eval_workflow = make_workflow(config.gconfig.new(temperature=0.6))

    saver = Saver(config.saver, ft_spec)
    stats_logger = StatsLogger(config.stats_logger, ft_spec)
    evaluator = Evaluator(config.evaluator, ft_spec)
    recover_handler = RecoverHandler(config.recover, ft_spec)
    # exactly-once sample accounting: journal consumed batches to a WAL
    # colocated with the recovery state; load() rolls it back to the
    # committed seq and restores the staleness cap from consumed counts
    if hasattr(rollout, "attach_ledger_wal"):
        rollout.attach_ledger_wal(ledger_wal_path(config.recover))
    recover_info = recover_handler.load(
        actor,
        saver,
        evaluator,
        train_dataloader,
        inference_engine=rollout,
        weight_update_meta=weight_update_meta,
    )
    start_step = (
        recover_info.last_step_info.next().global_step
        if recover_info is not None
        else 0
    )
    if rank == 0:
        save_config(config, StatsLogger.get_log_path(config.stats_logger))

    max_steps = config.total_train_steps or (
        config.total_train_epochs * steps_per_epoch
    )

    for global_step in range(start_step, max_steps):
        epoch = global_step // steps_per_epoch
        step = global_step % steps_per_epoch
        step_info = StepInfo(
            global_step=global_step,
            epoch=epoch,
            epoch_step=step,
            steps_per_epoch=steps_per_epoch,
        )

        with stats_tracker.record_timing("rollout"):
            if config.async_training:
                batch = rollout.prepare_batch(
                    train_dataloader, workflow=workflow
                )
            else:
                batch = rollout.rollout_batch(
                    next(iter(train_dataloader)), workflow=workflow
                )

        if config.actor.recompute_logprob or config.actor.use_decoupled_loss:
            with stats_tracker.record_timing("recompute_logp"):
                batch["prox_logp"] = actor.compute_logp(batch)

        if ref is not None:
            with stats_tracker.record_timing("ref_logp"):
                batch["ref_logp"] = ref.compute_logp(batch)

        with stats_tracker.record_timing("compute_advantage"):
            actor.compute_advantages(batch)

        with (
            stats_tracker.record_timing("train_step"),
            stats_tracker.scope("grpo_actor"),
        ):
            stats = actor.ppo_update(batch)

        rollout.pause()
        with stats_tracker.record_timing("update_weights"):
            actor.set_version(global_step + 1)
            actor.update_weights(weight_update_meta)
            rollout.set_version(global_step + 1)

        with stats_tracker.record_timing("save"):
            saver.save(actor, epoch, step, global_step, tokenizer=tokenizer)

        with stats_tracker.record_timing("checkpoint_for_recover"):
            recover_handler.dump(
                actor,
                step_info,
                saver,
                evaluator,
                train_dataloader,
                tokenizer=tokenizer,
                rollout=rollout,
            )

        with stats_tracker.record_timing("eval"):

            def evaluate_fn():
                cnt = 0
                for items in valid_dataloader:
                    for item in items:
                        rollout.submit(item, eval_workflow)
                        cnt += 1
                rollout.wait(cnt, timeout=None)

            evaluator.evaluate(evaluate_fn, epoch, step, global_step)

        stats[0].update(stats_tracker.export_all())
        stats_logger.commit(epoch, step, global_step, stats)
        rollout.resume()

    stats_logger.close()
    rollout.destroy()
    if ref is not None:
        ref.destroy()
    actor.destroy()


if __name__ == "__main__":
    from areal_tpu.utils.experiment import run_with_status

    run_with_status(main, sys.argv[1:])
