"""Vision GRPO on CLEVR counting — RLVR for a vision-language model: the
processor renders multimodal chat prompts, image patches ride the request
to the decode engine's vision tower, and training stays token-only.

Parity: /root/reference/examples/vlm/clevr_count_70k_grpo.py (Qwen2.5-VL
on clevr_count_70k with a boxed-count binary reward). TPU differences: the
in-process decode engine owns the vision tower (models/qwen2_vl.py,
m-rope + window-major patch encoding) instead of an SGLang server.

Usage:

  # fully-offline smoke (CPU): tiny tower + synthetic counting images
  python examples/clevr_grpo.py --config examples/configs/clevr_grpo.yaml \\
      tokenizer_path=synthetic-arith train_dataset.path=synthetic-vision \\
      actor.path= decode.model_path= actor.init_from_scratch=true

  # single-host TPU, Qwen2.5-VL-3B on clevr_count_70k (hub access):
  python examples/clevr_grpo.py --config examples/configs/clevr_grpo.yaml
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from areal_tpu.platforms import honor_jax_platforms_env

honor_jax_platforms_env()

from gsm8k_grpo import main as grpo_main


def main(argv):
    grpo_main(list(argv) + ["workflow=vision_rlvr"])


if __name__ == "__main__":
    from areal_tpu.utils.experiment import run_with_status

    run_with_status(main, sys.argv[1:])
