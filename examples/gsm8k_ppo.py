"""PPO (actor + critic) on GSM8K — the value-function variant.

Parity: the reference's PPO recipes (PPOConfig in areal/api/cli_args.py:
1246; actor+critic pairs in areal/engine/ppo/). Identical loop shape to
examples/gsm8k_grpo.py plus: a critic engine computes per-token values
before the advantage pass (GAE uses them instead of group baselines) and
takes its own update per step.

Usage (same config system; `critic.*` keys configure the value model):

  python examples/gsm8k_ppo.py --config examples/configs/arith_grpo_smoke.yaml \
      actor.adv_norm.mean_level=batch actor.adv_norm.std_level=batch \
      actor.gae_lambda=0.95 actor.discount=1.0
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.platforms import honor_jax_platforms_env

honor_jax_platforms_env()

from areal_tpu.api.alloc_mode import AllocationMode
from areal_tpu.api.cli_args import PPOConfig, load_expr_config, save_config
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo
from areal_tpu.dataset import SimpleDataLoader, get_custom_dataset
from areal_tpu.engine.ppo.actor import JaxPPOActor
from areal_tpu.engine.ppo.critic import JaxPPOCritic
from areal_tpu.utils import name_resolve, seeding, stats_tracker
from areal_tpu.utils.recover import RecoverHandler
from areal_tpu.utils.saver import Saver
from areal_tpu.utils.stats_logger import StatsLogger
from areal_tpu.workflow.rlvr import RLVRWorkflow

from examples.gsm8k_grpo import build_rollout, load_tokenizer, pick_reward_fn


def main(args):
    config, _ = load_expr_config(args, PPOConfig)
    config: PPOConfig

    rank = int(os.getenv("AREAL_TPU_PROCESS_ID", "0"))
    seeding.set_random_seed(config.seed, key=f"trainer{rank}")
    tokenizer = load_tokenizer(config.tokenizer_path)
    name_resolve.reconfigure(config.cluster.name_resolve)
    alloc = AllocationMode.from_str(config.allocation_mode)

    actor = JaxPPOActor(config.actor)
    critic = JaxPPOCritic(config.critic)
    if not config.actor.path:
        from areal_tpu.models.smoke import smoke_model_config

        actor.model_config = smoke_model_config(
            dtype=config.actor.dtype,
            vocab_size=getattr(tokenizer, "vocab_size", None),
        )
    if not config.critic.path:
        from areal_tpu.models.smoke import smoke_model_config

        critic.model_config = smoke_model_config(
            dtype=config.critic.dtype,
            vocab_size=getattr(tokenizer, "vocab_size", None),
            is_critic=True,
        )
    actor.create_process_group(alloc.train)
    critic.create_process_group(alloc.train)

    train_dataset = get_custom_dataset(
        path=config.train_dataset.path,
        split="train",
        type=config.train_dataset.type or "rl",
        tokenizer=tokenizer,
        max_length=config.train_dataset.max_length,
        rank=actor.data_parallel_rank,
        world_size=actor.data_parallel_world_size,
    )
    train_dataloader = SimpleDataLoader(
        train_dataset,
        batch_size=config.train_dataset.batch_size,
        shuffle=config.train_dataset.shuffle,
        seed=config.seed,
    )
    steps_per_epoch = len(train_dataloader)
    ft_spec = FinetuneSpec(
        total_train_epochs=config.total_train_epochs,
        dataset_size=steps_per_epoch * config.train_dataset.batch_size,
        train_batch_size=config.train_dataset.batch_size,
    )
    actor.initialize(None, ft_spec)
    critic.initialize(None, ft_spec)

    rollout, weight_update_meta = build_rollout(config, alloc, actor, tokenizer)
    actor.connect_engine(rollout, weight_update_meta)

    reward_fn = pick_reward_fn(config.train_dataset.path)
    if getattr(tokenizer, "eos_token_id", None) is not None:
        if tokenizer.eos_token_id not in config.gconfig.stop_token_ids:
            config.gconfig.stop_token_ids.append(tokenizer.eos_token_id)
    workflow = RLVRWorkflow(
        reward_fn=reward_fn, gconfig=config.gconfig, tokenizer=tokenizer
    )

    saver = Saver(config.saver, ft_spec)
    critic_saver = Saver(config.saver, ft_spec)
    stats_logger = StatsLogger(config.stats_logger, ft_spec)
    # RecoverHandler checkpoints ONE engine per recover root; restoring the
    # actor while the critic re-initializes would silently corrupt GAE
    # baselines, so recover is rejected here until a two-engine handler
    # exists.
    if config.recover.mode != "disabled":
        raise NotImplementedError(
            "gsm8k_ppo.py does not support recover yet: the recover "
            "checkpoint covers the actor only and a restored run would pair "
            "it with a fresh critic; set recover.mode=disabled"
        )
    recover_handler = RecoverHandler(config.recover, ft_spec)
    start_step = 0
    if rank == 0:
        save_config(config, StatsLogger.get_log_path(config.stats_logger))
    max_steps = config.total_train_steps or (
        config.total_train_epochs * steps_per_epoch
    )

    for global_step in range(start_step, max_steps):
        epoch = global_step // steps_per_epoch
        step = global_step % steps_per_epoch

        with stats_tracker.record_timing("rollout"):
            batch = rollout.prepare_batch(train_dataloader, workflow=workflow)

        if config.actor.recompute_logprob or config.actor.use_decoupled_loss:
            with stats_tracker.record_timing("recompute_logp"):
                batch["prox_logp"] = actor.compute_logp(batch)

        with stats_tracker.record_timing("critic_values"):
            batch["values"] = critic.compute_values(batch)

        with stats_tracker.record_timing("compute_advantage"):
            actor.compute_advantages(batch)

        with (
            stats_tracker.record_timing("train_step"),
            stats_tracker.scope("ppo_actor"),
        ):
            stats = actor.ppo_update(batch)

        with (
            stats_tracker.record_timing("critic_step"),
            stats_tracker.scope("ppo_critic"),
        ):
            critic_stats = critic.ppo_update(batch)
            stats[0].update(critic_stats[0])

        rollout.pause()
        with stats_tracker.record_timing("update_weights"):
            actor.set_version(global_step + 1)
            actor.update_weights(weight_update_meta)
            rollout.set_version(global_step + 1)
            critic.set_version(global_step + 1)

        saver.save(actor, epoch, step, global_step, tokenizer=tokenizer)
        critic_saver.save(
            critic, epoch, step, global_step, name="critic",
            tokenizer=tokenizer,
        )
        recover_handler.dump(
            actor,
            StepInfo(
                global_step=global_step,
                epoch=epoch,
                epoch_step=step,
                steps_per_epoch=steps_per_epoch,
            ),
            saver,
            None,
            train_dataloader,
            tokenizer=tokenizer,
        )
        stats[0].update(stats_tracker.export_all())
        stats_logger.commit(epoch, step, global_step, stats)
        rollout.resume()

    stats_logger.close()
    rollout.destroy()
    critic.destroy()
    actor.destroy()


if __name__ == "__main__":
    from areal_tpu.utils.experiment import run_with_status

    run_with_status(main, sys.argv[1:])
