"""Pairwise reward-model training on HH-RLHF — runnable entry point.

Parity: /root/reference/examples/alignment/hhrlhf_rw.py — Bradley–Terry
loss over (chosen, rejected) preference pairs on the scalar-value-head
critic, same config system and loop contract as the other examples.

Usage:

  # fully offline smoke (CPU or one chip): synthetic arithmetic pairs
  # (chosen = correct answer, rejected = wrong answer)
  python examples/hhrlhf_rw.py --config examples/configs/arith_rw_smoke.yaml

  # single-host TPU, Qwen2.5-0.5B on Anthropic/hh-rlhf:
  python examples/hhrlhf_rw.py --config examples/configs/hhrlhf_rw.yaml
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.platforms import honor_jax_platforms_env

honor_jax_platforms_env()

import numpy as np

from areal_tpu.api.alloc_mode import AllocationMode
from areal_tpu.api.cli_args import RWConfig, load_expr_config, save_config
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo
from areal_tpu.dataset import (
    SimpleDataLoader,
    get_custom_dataset,
    load_tokenizer,
)
from areal_tpu.engine.rw.rw_engine import JaxRWEngine
from areal_tpu.utils import seeding, stats_tracker
from areal_tpu.utils.data import pad_sequences_to_tensors
from areal_tpu.utils.evaluator import Evaluator
from areal_tpu.utils.recover import RecoverHandler
from areal_tpu.utils.saver import Saver
from areal_tpu.utils.stats_logger import StatsLogger




def to_pair_batch(items) -> dict:
    """Interleave pairs as rows (2i, 2i+1) = (chosen, rejected) — the
    layout JaxRWEngine.train_rw expects."""
    seqs = []
    for x in items:
        for key in ("chosen_input_ids", "rejected_input_ids"):
            ids = np.asarray(x[key], dtype=np.int32)
            seqs.append(dict(input_ids=ids, loss_mask=np.ones_like(ids)))
    return pad_sequences_to_tensors(seqs)


def main(args):
    config, _ = load_expr_config(args, RWConfig)
    config: RWConfig

    rank = int(os.getenv("AREAL_TPU_PROCESS_ID", "0"))
    seeding.set_random_seed(config.seed, key=f"trainer{rank}")
    tokenizer = load_tokenizer(config.tokenizer_path)

    from areal_tpu.utils import name_resolve

    name_resolve.reconfigure(config.cluster.name_resolve)
    alloc = AllocationMode.from_str(config.allocation_mode)

    engine = JaxRWEngine(config.model)
    if not config.model.path:
        from areal_tpu.models.smoke import smoke_model_config

        engine.model_config = smoke_model_config(
            dtype=config.model.dtype,
            vocab_size=getattr(tokenizer, "vocab_size", None),
            is_critic=True,
        )
    engine.create_process_group(alloc.train)

    def make_ds(dcfg, split):
        return get_custom_dataset(
            path=dcfg.path,
            split=split,
            type="rw",
            tokenizer=tokenizer,
            max_length=dcfg.max_length,
            rank=engine.data_parallel_rank,
            world_size=engine.data_parallel_world_size,
        )

    train_dataset = make_ds(config.train_dataset, "train")
    valid_dataset = make_ds(
        config.valid_dataset or config.train_dataset, "test"
    )
    train_dataloader = SimpleDataLoader(
        train_dataset,
        batch_size=config.train_dataset.batch_size,
        shuffle=config.train_dataset.shuffle,
        seed=config.seed,
    )
    valid_dataloader = SimpleDataLoader(
        valid_dataset,
        batch_size=(config.valid_dataset or config.train_dataset).batch_size,
        shuffle=False,
    )
    steps_per_epoch = len(train_dataloader)
    ft_spec = FinetuneSpec(
        total_train_epochs=config.total_train_epochs,
        dataset_size=steps_per_epoch * config.train_dataset.batch_size,
        train_batch_size=config.train_dataset.batch_size,
    )
    engine.initialize(None, ft_spec)

    saver = Saver(config.saver, ft_spec)
    stats_logger = StatsLogger(config.stats_logger, ft_spec)
    evaluator = Evaluator(config.evaluator, ft_spec)
    recover_handler = RecoverHandler(config.recover, ft_spec)
    recover_info = recover_handler.load(
        engine, saver, evaluator, train_dataloader
    )
    start_step = (
        recover_info.last_step_info.next().global_step
        if recover_info is not None
        else 0
    )
    if rank == 0:
        save_config(config, StatsLogger.get_log_path(config.stats_logger))

    max_steps = config.total_train_steps or (
        config.total_train_epochs * steps_per_epoch
    )

    global_step = start_step
    data_iter = iter(train_dataloader)
    while global_step < max_steps:
        try:
            items = next(data_iter)
        except StopIteration:
            data_iter = iter(train_dataloader)
            items = next(data_iter)
        epoch = global_step // steps_per_epoch
        step = global_step % steps_per_epoch

        with stats_tracker.record_timing("train_step"):
            stats = engine.train_rw(to_pair_batch(items))
        engine.set_version(global_step + 1)

        saver.save(engine, epoch, step, global_step, tokenizer=tokenizer)
        recover_handler.dump(
            engine,
            StepInfo(
                global_step=global_step,
                epoch=epoch,
                epoch_step=step,
                steps_per_epoch=steps_per_epoch,
            ),
            saver,
            evaluator,
            train_dataloader,
            tokenizer=tokenizer,
        )

        def evaluate_fn():
            losses = [
                engine.eval_rw(to_pair_batch(v_items))
                for v_items in valid_dataloader
            ]
            stats_tracker.scalar(eval_loss=float(np.mean(losses)))

        evaluator.evaluate(evaluate_fn, epoch, step, global_step)

        stats.update(stats_tracker.export_all())
        stats_logger.commit(epoch, step, global_step, stats)
        global_step += 1

    stats_logger.close()
    engine.destroy()


if __name__ == "__main__":
    from areal_tpu.utils.experiment import run_with_status

    run_with_status(main, sys.argv[1:])
