"""boba² — the north-star recipe: async GRPO math RL on a 7B reasoning
model across a v5p pod slice.

Parity: the reference's boba² release (/root/reference/blog/AReaL_v0_3.md:
183-186 — 7B math RL with fully asynchronous rollout, decoupled PPO loss,
staleness η=4, group sampling) and its runnable math entry
(/root/reference/examples/math/ + recipe yaml). TPU differences:

- The allocation string carves ONE pod slice into decode servers + GSPMD
  trainer: ``jax:d16t4+d16t4`` = 64 v5p chips serving rollouts (16 engines
  x tp4) + 64 chips training (fsdp-dp16 x tp4). XLA collectives over ICI
  replace the reference's NCCL groups; weight pushes ride the DCN
  framed-bucket path (core/weight_transfer.py).
- ``--plan-check`` validates the WHOLE plan on any host before touching a
  chip: closed-form HBM accounting for both halves
  (AllocationMode.check_hbm) plus an AOT compile of the full-depth sharded
  train program (JaxTrainEngine.plan_compile_check) — run it on a laptop
  with N virtual CPU devices to prove the v5p program builds.

Usage:

  # validate the 7B plan without hardware (any machine):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=64 \\
      python examples/boba2_grpo.py --config examples/configs/boba2_7b_grpo.yaml \\
      --plan-check

  # launch on the pod slice (launcher spawns decode servers + trainer):
  python -m areal_tpu.launcher.local examples/boba2_grpo.py \\
      --config examples/configs/boba2_7b_grpo.yaml

  # offline tiny-geometry smoke of the same loop (CPU, synthetic data):
  python examples/boba2_grpo.py --config examples/configs/boba2_7b_grpo.yaml \\
      +smoke (see tests/test_examples_smoke.py for the override set)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.platforms import honor_jax_platforms_env

honor_jax_platforms_env()

# Known 7B-class tensor geometries, keyed by the tail of the model path.
# The plan check must work on machines with no checkpoint and no network
# (ModelConfig.from_hf_config needs local files), so the recipe carries the
# geometry of its target models explicitly.
_GEOMETRIES = {
    "qwen2.5-7b": dict(
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_hidden_layers=28,
        num_attention_heads=28,
        num_key_value_heads=4,
        tie_word_embeddings=False,
    ),
    # R1-Distill-Qwen-7B shares the Qwen2.5-7B geometry
    "deepseek-r1-distill-qwen-7b": dict(
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_hidden_layers=28,
        num_attention_heads=28,
        num_key_value_heads=4,
        tie_word_embeddings=False,
    ),
}


def _target_model_config(config):
    """ModelConfig for the recipe's model: from the local checkpoint when
    present, else from the carried geometry table."""
    from areal_tpu.models.qwen2 import ModelConfig

    path = config.actor.path
    if path and os.path.isdir(path):
        return ModelConfig.from_hf_config(
            path, dtype=config.actor.dtype, param_dtype=config.actor.dtype
        )
    key = (path or "").split("/")[-1].lower()
    for name, geom in _GEOMETRIES.items():
        if name in key:
            return ModelConfig(
                dtype=config.actor.dtype,
                param_dtype=config.actor.dtype,
                scan_layers=True,
                remat=config.actor.gradient_checkpointing,
                **geom,
            )
    raise SystemExit(
        f"--plan-check: no local checkpoint at {path!r} and no carried "
        f"geometry matches; add one to _GEOMETRIES"
    )


def plan_check(argv) -> None:
    """Validate HBM fit for both allocation halves and AOT-compile the
    full-depth sharded train program. Exits 0 iff the plan is launchable."""
    import jax

    from areal_tpu.api.alloc_mode import AllocationMode
    from areal_tpu.api.cli_args import GRPOConfig, load_expr_config

    config, _ = load_expr_config(argv, GRPOConfig)
    alloc = AllocationMode.from_str(config.allocation_mode)
    model_cfg = _target_model_config(config)
    device_kind = os.environ.get("AREAL_PLAN_DEVICE", "TPU v5p")

    report = alloc.check_hbm(
        model_cfg,
        device_kind,
        microbatch_tokens=config.actor.mb_spec.max_tokens_per_mb,
        remat=config.actor.gradient_checkpointing,
        decode_slots=config.decode.max_running_requests,
        decode_context=config.decode.context_length,
        decode_pool_tokens=config.decode.kv_pool_tokens,
    )
    print(f"[plan-check] HBM fit on {device_kind!r}: OK")
    for half, bd in report.items():
        print(f"[plan-check]   {half}: {bd}")

    train = alloc.train
    need = train.world_size
    have = len(jax.devices())
    if have < need:
        print(
            f"[plan-check] {need} devices required for the AOT compile but "
            f"only {have} present — set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need} (CPU is fine); "
            "skipping compile step"
        )
        return
    from areal_tpu.engine.sft.lm_engine import JaxLMEngine

    eng = JaxLMEngine(config.actor)
    eng.model_config = model_cfg
    eng.create_process_group(train)
    try:
        ma = eng.plan_compile_check(
            mb_tokens=config.actor.mb_spec.max_tokens_per_mb
        )
        print(f"[plan-check] full-depth train program compiled: {ma}")
    finally:
        eng.destroy()
    print("[plan-check] PASS")


def main(argv):
    if "--plan-check" in argv:
        plan_check([a for a in argv if a != "--plan-check"])
        return
    # The training loop IS the async-GRPO loop: prepare_batch keeps >=2
    # batches in flight against the decode servers, staleness-gated by
    # max_head_offpolicyness (η), with the decoupled behav/prox loss.
    from gsm8k_grpo import main as grpo_main

    grpo_main(argv)


if __name__ == "__main__":
    if "--plan-check" in sys.argv[1:]:
        main(sys.argv[1:])
    else:
        from areal_tpu.utils.experiment import run_with_status

        run_with_status(main, sys.argv[1:])
