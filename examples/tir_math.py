"""Tool-integrated reasoning (TIR) math RL — the model writes ```python
blocks that execute in a sandbox mid-generation, and the interpreter
output is spliced back into the context (masked from the loss).

Parity: /root/reference/examples/tir/ (tir_workflow.py: segment-wise
generation with tool-call interception, tool outputs loss-masked;
train_tir.py entry). The TPU build's TIRWorkflow (workflow/tir.py) runs
the same episode loop against the in-process decode engine or decode
servers; the sandbox is the subprocess-isolated runner of reward/tir
tooling (grandchild reaping, wall-clock timeout).

Usage:

  # offline smoke (CPU, synthetic arithmetic — tool calls optional):
  python examples/tir_math.py --config examples/configs/tir_math.yaml \\
      tokenizer_path=synthetic-arith train_dataset.path=synthetic-arith \\
      actor.path= decode.model_path= actor.init_from_scratch=true

  # single-host TPU, ToRL data with Qwen2.5-Math:
  python examples/tir_math.py --config examples/configs/tir_math.yaml
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from areal_tpu.platforms import honor_jax_platforms_env

honor_jax_platforms_env()

from gsm8k_grpo import main as grpo_main


def main(argv):
    # the entry pins the workflow; everything else is the shared async-GRPO
    # loop (gsm8k_grpo.main), configured by tir_math.yaml
    grpo_main(list(argv) + ["workflow=tir"])


if __name__ == "__main__":
    from areal_tpu.utils.experiment import run_with_status

    run_with_status(main, sys.argv[1:])
