import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.utils.functional import (
    dynamic_sampling,
    gather_logprobs,
    gather_logprobs_entropy,
    masked_normalization,
    ppo_actor_loss_fn,
    ppo_critic_loss_fn,
    reward_overlong_penalty,
)


def test_gather_logprobs_matches_manual():
    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(key, (5, 11))
    labels = jnp.array([0, 3, 5, 10, 1])
    lp = gather_logprobs(logits, labels)
    ref = jax.nn.log_softmax(logits, axis=-1)[jnp.arange(5), labels]
    np.testing.assert_allclose(np.asarray(lp), np.asarray(ref), rtol=1e-5)


def test_gather_logprobs_entropy():
    logits = jnp.zeros((3, 4))  # uniform
    labels = jnp.array([0, 1, 2])
    lp, ent = gather_logprobs_entropy(logits, labels)
    np.testing.assert_allclose(np.asarray(lp), np.log(1 / 4) * np.ones(3), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ent), np.log(4) * np.ones(3), rtol=1e-6)


def test_masked_normalization():
    x = jnp.array([1.0, 2.0, 3.0, 100.0])
    mask = jnp.array([1.0, 1.0, 1.0, 0.0])
    out = masked_normalization(x, mask)
    masked_vals = np.asarray(out)[:3]
    assert abs(masked_vals.mean()) < 1e-4


def test_ppo_loss_onpolicy_equals_pg():
    # on-policy: logprobs == proximal == old -> ratio 1, loss = -mean(adv)
    lp = jnp.array([-1.0, -2.0, -3.0])
    adv = jnp.array([1.0, -1.0, 0.5])
    mask = jnp.ones(3)
    loss, stat = ppo_actor_loss_fn(lp, lp, lp, adv, 0.2, mask)
    np.testing.assert_allclose(float(loss), -float(adv.mean()), rtol=1e-6)
    assert not bool(stat["clip_mask"].any())


def test_ppo_loss_clipping_engages():
    old = jnp.array([-1.0])
    new = old + 1.0  # ratio e > 1.2
    adv = jnp.array([1.0])
    mask = jnp.ones(1)
    loss, stat = ppo_actor_loss_fn(new, old, old, adv, 0.2, mask)
    # clipped at 1.2: loss = -1.2 * adv
    np.testing.assert_allclose(float(loss), -1.2, rtol=1e-6)


def test_ppo_loss_decoupled_behav_weight():
    # proximal != old: behav importance weight multiplies the loss
    prox = jnp.array([-1.0])
    old = jnp.array([-1.5])
    new = prox  # ratio vs prox = 1
    adv = jnp.array([1.0])
    mask = jnp.ones(1)
    loss, stat = ppo_actor_loss_fn(new, prox, old, adv, 0.2, mask)
    w = float(jnp.exp(prox - old)[0])
    np.testing.assert_allclose(float(loss), -w, rtol=1e-6)
    # cap below w -> token masked out of behav weighting
    loss_capped, stat2 = ppo_actor_loss_fn(
        new, prox, old, adv, 0.2, mask, behav_imp_weight_cap=1.1
    )
    np.testing.assert_allclose(float(loss_capped), 0.0, atol=1e-7)


def test_ppo_loss_dual_clip():
    old = jnp.array([-1.0])
    new = old + 2.0  # ratio e^2 ≈ 7.4 > c_clip
    adv = jnp.array([-2.0])  # negative advantage
    mask = jnp.ones(1)
    loss_noclip, _ = ppo_actor_loss_fn(new, old, old, adv, 0.2, mask)
    loss_cclip, stat = ppo_actor_loss_fn(new, old, old, adv, 0.2, mask, c_clip=3.0)
    # dual clip bounds the loss magnitude for negative advantages
    assert float(loss_cclip) <= float(loss_noclip)
    assert bool(stat["dual_clip_mask"].any())


def test_ppo_loss_gradient_flows():
    def f(lp):
        loss, _ = ppo_actor_loss_fn(
            lp, jnp.zeros(2), jnp.zeros(2), jnp.ones(2), 0.2, jnp.ones(2)
        )
        return loss

    g = jax.grad(f)(jnp.zeros(2))
    assert np.all(np.isfinite(np.asarray(g)))
    assert np.any(np.asarray(g) != 0)


def test_critic_loss_clip():
    v = jnp.array([2.0])
    old_v = jnp.array([0.0])
    target = jnp.array([0.0])
    loss, stat = ppo_critic_loss_fn(v, old_v, target, value_eps_clip=0.5)
    # clipped value = 0.5 -> clipped loss = 0.125; orig = 2.0 -> max = 2.0
    np.testing.assert_allclose(float(loss), 2.0, rtol=1e-6)


def test_dynamic_sampling_filters_uniform_groups():
    data = dict(
        rewards=np.array([1.0, 1.0, 0.0, 1.0]),
        input_ids=np.arange(4 * 3).reshape(4, 3),
        meta="keep",
    )
    out, stats = dynamic_sampling(data, group_size=2)
    assert stats == dict(n_group_kept=1, n_group_filtered=1)
    assert out["rewards"].shape == (2,)
    np.testing.assert_array_equal(out["rewards"], [0.0, 1.0])
    assert out["input_ids"].shape == (2, 3)
    assert out["meta"] == "keep"


def test_dynamic_sampling_all_filtered_returns_original():
    data = dict(rewards=np.array([1.0, 1.0]))
    out, stats = dynamic_sampling(data, group_size=2)
    assert out["rewards"].shape == (2,)
    assert stats["n_group_filtered"] == 1


def test_reward_overlong_penalty():
    loss_mask = np.zeros((2, 98), dtype=np.int32)
    loss_mask[0, :10] = 1
    loss_mask[1, :] = 1
    data = dict(
        rewards=np.array([1.0, 1.0], dtype=np.float32),
        loss_mask=loss_mask,
    )
    out = reward_overlong_penalty(
        data, overlong_tokens=20, overlong_penalty_factor=1.0, max_response_length=100
    )
    assert out["rewards"][0] == pytest.approx(1.0)  # within budget
    # second: exceeds (100-20)=80 by 18 -> penalty -18/20
    assert out["rewards"][1] == pytest.approx(1.0 - 18 / 20)
