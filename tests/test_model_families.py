"""HF numerical parity for the widened model-family registry.

The reference supports gemma / mixtral / qwen2_moe through its per-family
from_hf converters (realhf/api/from_hf/{gemma,mixtral,qwen2.py + registry});
here one flag-parameterized decoder covers them, so each family gets a
golden test against the transformers implementation on a tiny random
checkpoint, exercising config parsing, weight mapping, and forward math.
"""

import json

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax

from areal_tpu.models.hf_io import load_hf_params, save_hf_params
from areal_tpu.models.qwen2 import ModelConfig, decode_step, forward, prefill

torch = pytest.importorskip("torch")


def _decode_consistency(cfg, params, T=10, atol=2e-3):
    """prefill + decode_step must agree with the packed training forward —
    the decode engine serves THESE functions, and family-specific terms
    (o_bias, wpe, shared expert) are easy to drop from one path only."""
    rng = np.random.RandomState(7)
    ids = rng.randint(0, cfg.vocab_size, (T,))
    ref = np.asarray(
        forward(params, ids, np.arange(T), np.zeros(T, dtype=np.int32), cfg)
    )
    logits, ks, vs = prefill(params, ids[:-1], np.arange(T - 1), cfg)
    np.testing.assert_allclose(np.asarray(logits), ref[:-1], atol=atol, rtol=1e-3)

    L = cfg.num_hidden_layers
    nKV, hd = cfg.num_key_value_heads, cfg.head_dim_
    S, R = T + 4, 2
    k_cache = np.zeros((L, R, S, nKV, hd), np.float32)
    v_cache = np.zeros((L, R, S, nKV, hd), np.float32)
    k_cache[:, 0, : T - 1] = np.asarray(ks)
    v_cache[:, 0, : T - 1] = np.asarray(vs)
    lg, _, _ = decode_step(
        params,
        np.array([ids[-1], 0], np.int32),
        np.array([T - 1, 0], np.int32),
        k_cache,
        v_cache,
        cfg,
        active=np.array([True, False]),
    )
    np.testing.assert_allclose(np.asarray(lg)[0], ref[-1], atol=atol, rtol=1e-3)


def _randomize_biases(model):
    """HF inits GPT-2 biases to zero; perturb them so bias-dropping bugs
    can't hide behind zeros."""
    with torch.no_grad():
        for n, p in model.named_parameters():
            if n.endswith(".bias"):
                p.add_(torch.randn_like(p) * 0.05)


def _save_tiny(model, tmp_path, expect_type):
    model_dir = tmp_path / "hf"
    model.save_pretrained(model_dir, safe_serialization=True)
    with open(model_dir / "config.json") as f:
        assert json.load(f)["model_type"] == expect_type
    return str(model_dir)


def _parity(model, model_dir, vocab, T=12, atol=2e-3, **overrides):
    cfg = ModelConfig.from_hf_config(
        model_dir, dtype="float32", param_dtype="float32", **overrides
    )
    params = load_hf_params(model_dir, cfg)
    rng = np.random.RandomState(1)
    ids = rng.randint(0, vocab, (T,))
    with torch.no_grad():
        hf_logits = model(torch.tensor(ids)[None]).logits[0].numpy()
    ours = np.asarray(
        forward(params, ids, np.arange(T), np.zeros(T, dtype=np.int32), cfg)
    )
    np.testing.assert_allclose(ours, hf_logits, atol=atol, rtol=1e-3)
    return cfg, params


def test_gemma_numerical_parity(tmp_path):
    """Gemma-1: GeGLU MLP, zero-centered RMSNorm, sqrt(H)-scaled embeddings,
    tied lm_head, explicit head_dim != H/nH."""
    from transformers import GemmaConfig, GemmaForCausalLM

    hf_cfg = GemmaConfig(
        vocab_size=96,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,  # nH*hd = 64 != H=32: the real gemma geometry quirk
        max_position_embeddings=128,
        rms_norm_eps=1e-6,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = GemmaForCausalLM(hf_cfg).eval().float()
    model_dir = _save_tiny(model, tmp_path, "gemma")
    cfg, params = _parity(model, model_dir, 96)
    _decode_consistency(cfg, params)
    assert cfg.norm_zero_centered and cfg.normalize_embed
    assert cfg.tie_word_embeddings and not cfg.qkv_bias
    assert cfg.hidden_act == "gelu_pytorch_tanh"


def test_mixtral_numerical_parity(tmp_path):
    """Mixtral: block_sparse_moe.* weight names, w1/w3/w2 expert layout,
    renormalized top-k routing."""
    from transformers import MixtralConfig, MixtralForCausalLM

    hf_cfg = MixtralConfig(
        vocab_size=96,
        hidden_size=32,
        intermediate_size=48,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=128,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = MixtralForCausalLM(hf_cfg).eval().float()
    model_dir = _save_tiny(model, tmp_path, "mixtral")
    # ample capacity: HF routes without drops; match it for the golden check
    cfg, params = _parity(model, model_dir, 96, capacity_factor=8.0)
    assert cfg.num_experts == 4 and cfg.norm_topk_prob
    assert cfg.moe_intermediate_size_ == 48

    # roundtrip preserves mixtral naming
    out = save_hf_params(params, cfg, str(tmp_path / "ckpt"))
    reloaded = load_hf_params(out, cfg, dtype="float32")
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        params,
        reloaded,
    )


def test_qwen2_moe_numerical_parity(tmp_path):
    """Qwen2-MoE: routed experts + sigmoid-gated shared expert, qkv bias,
    unnormalized top-k gates (norm_topk_prob=False)."""
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

    hf_cfg = Qwen2MoeConfig(
        vocab_size=96,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_experts=4,
        num_experts_per_tok=2,
        moe_intermediate_size=16,
        shared_expert_intermediate_size=48,
        norm_topk_prob=False,
        decoder_sparse_step=1,
        mlp_only_layers=[],
        max_position_embeddings=128,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = Qwen2MoeForCausalLM(hf_cfg).eval().float()
    model_dir = _save_tiny(model, tmp_path, "qwen2_moe")
    cfg, params = _parity(model, model_dir, 96, capacity_factor=8.0)
    _decode_consistency(cfg, params)
    assert cfg.shared_expert_intermediate_size == 48
    assert cfg.qkv_bias and not cfg.norm_topk_prob


def test_gpt2_numerical_parity(tmp_path):
    """GPT-2: LayerNorm+bias, learned wpe positions, fused Conv1D c_attn
    split at load, fc MLP with gelu_new, tied head."""
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(
        vocab_size=96,
        n_positions=64,
        n_embd=32,
        n_layer=2,
        n_head=4,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = GPT2LMHeadModel(hf_cfg).eval().float()
    _randomize_biases(model)
    model_dir = _save_tiny(model, tmp_path, "gpt2")
    cfg, params = _parity(model, model_dir, 96)
    _decode_consistency(cfg, params)
    assert cfg.norm_type == "layernorm" and cfg.pos_embed == "learned"
    assert cfg.mlp_style == "fc" and cfg.attn_out_bias
    assert cfg.hidden_act == "gelu_new" and cfg.tie_word_embeddings
    assert cfg.intermediate_size == 128  # 4 * n_embd default

    # roundtrip re-fuses c_attn and keeps transformer.* Conv1D layout
    out = save_hf_params(params, cfg, str(tmp_path / "ckpt"))
    reloaded = load_hf_params(out, cfg, dtype="float32")
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        params,
        reloaded,
    )


def test_qwen2_moe_heterogeneous_rejected(tmp_path):
    with pytest.raises(NotImplementedError):
        ModelConfig.from_hf_config(
            {
                "model_type": "qwen2_moe",
                "vocab_size": 96,
                "hidden_size": 32,
                "intermediate_size": 64,
                "num_hidden_layers": 4,
                "num_attention_heads": 4,
                "mlp_only_layers": [0, 1],
            }
        )


def test_gemma2_rejected():
    with pytest.raises(NotImplementedError):
        ModelConfig.from_hf_config(
            {
                "model_type": "gemma2",
                "vocab_size": 96,
                "hidden_size": 32,
                "intermediate_size": 64,
                "num_hidden_layers": 2,
                "num_attention_heads": 4,
            }
        )


def test_mistral_sliding_window_parity(tmp_path):
    """Mistral v0.1-class sliding window: parity vs HF at T > window, the
    regime where ignoring the window is silently wrong; decode/prefill
    must agree with forward; flash impl must reject loudly."""
    from transformers import MistralConfig, MistralForCausalLM

    hf_cfg = MistralConfig(
        vocab_size=96,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        sliding_window=8,
        max_position_embeddings=128,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = MistralForCausalLM(hf_cfg).eval().float()
    model_dir = _save_tiny(model, tmp_path, "mistral")
    cfg, params = _parity(model, model_dir, 96, T=24)
    assert cfg.sliding_window == 8
    _decode_consistency(cfg, params, T=24)

    from areal_tpu.models.qwen2 import resolve_attn_impl

    with pytest.raises(NotImplementedError):
        resolve_attn_impl(
            ModelConfig(sliding_window=8, attn_impl="flash")
        )
    # auto resolves to the O(T)-memory chunked online-softmax path
    assert resolve_attn_impl(
        ModelConfig(sliding_window=8, attn_impl="auto")
    ) == "chunked"


def test_qwen2_max_window_layers_semantics():
    """HF windows layers with layer_idx >= max_window_layers: the stock
    Qwen2.5 shape (mwl == L) must mean NO window (review regression)."""
    base = dict(
        model_type="qwen2", vocab_size=96, hidden_size=32,
        intermediate_size=64, num_hidden_layers=4, num_attention_heads=4,
        use_sliding_window=True, sliding_window=8,
    )
    # mwl == L (stock shape): no layer windowed
    cfg = ModelConfig.from_hf_config({**base, "max_window_layers": 4})
    assert cfg.sliding_window is None
    # key absent: conservative no-window
    cfg = ModelConfig.from_hf_config(dict(base))
    assert cfg.sliding_window is None
    # mwl == 0: every layer windowed
    cfg = ModelConfig.from_hf_config({**base, "max_window_layers": 0})
    assert cfg.sliding_window == 8
    # mixed stack: loud rejection
    with pytest.raises(NotImplementedError):
        ModelConfig.from_hf_config({**base, "max_window_layers": 2})
