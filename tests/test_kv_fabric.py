"""Fleet-global KV fabric: content-addressed prefix blocks (ISSUE 17).

Coverage layers:

1. Content-key contracts (pure kv_fabric): block-boundary chaining and
   position binding, weight-version / kv-dtype salt distinctness,
   digest round-trip + caps + malformed input, longest-run semantics.
2. Router: the prefix-affinity map hashes with the SAME chained content
   keys (salted — a weight flip retires stale affinity), and the
   scheduler attaches a remote-fetch hint when a sibling advertises a
   longer resident run than the chosen replica.
3. Engine intra-replica dedup: a request whose prompt shares a
   block-aligned head with a DIVERGING resident run forks from it (the
   tuple-prefix donor path cannot see it) — bit-identical to a fresh
   full-prefill oracle, attributed to the fabric counters, never to the
   rid-exact host counters.
4. Fleet fetch over the wire: /kv_fetch streams content-keyed block runs
   between live servers; the receiving engine promotes them (remote
   attribution) and continues the stream bit-identically; /warm_start
   pulls a cold replica's first blocks from its peers.
5. Staleness: a fetched run computed under another weight version is
   rejected as an honest miss — zero stale-block serves.
6. Cheap drain: export_session with a refetchable key set ships a
   meta-only identity frame (no KV bytes); the importer never promotes
   it as if it held blocks.
"""

import asyncio
import threading

import numpy as np
import pytest

import jax

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
    RouterConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.core import kv_fabric
from areal_tpu.core.weight_transfer import (
    WeightStaging,
    pack_kv_session,
    unpack_kv_sessions,
)
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.launcher.decode_server import DecodeServer
from areal_tpu.launcher.router import DecodeRouter
from areal_tpu.models.qwen2 import ModelConfig, init_params
from areal_tpu.utils.http import arequest_with_retry

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = init_params(TINY, jax.random.PRNGKey(0))
    return _PARAMS


def _engine(*, role="unified", host_mb=0.0, R=3, context=256, page=8,
            chunk=4, seed=1, fabric=True):
    cfg = JaxDecodeConfig(
        context_length=context,
        max_running_requests=R,
        new_tokens_per_chunk=chunk,
        page_size=page,
        kv_layout="paged",
        paged_attn_impl="xla",
        kv_host_pool_mb=host_mb,
        role=role,
        kv_migrate_chunk_mb=0.01,
        kv_fabric=fabric,
        dtype="float32",
        kv_cache_dtype="float32",
        random_seed=seed,
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(_params(), TINY)
    eng.initialize()
    return eng


def _run_async(coro, timeout=120):
    result = {}

    def go():
        try:
            result["v"] = asyncio.run(coro)
        except BaseException as e:  # noqa: BLE001
            result["e"] = e

    t = threading.Thread(target=go, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "async scenario timed out"
    if "e" in result:
        raise result["e"]
    return result.get("v")


def _prefill(eng, req):
    return _run_async(eng.aprefill(req))


_GREEDY = GenerationHyperparameters(max_new_tokens=10, greedy=True)
_SAMPLED = GenerationHyperparameters(
    max_new_tokens=10, temperature=0.8, top_p=0.9
)


def _prompt(n, seed=3):
    return np.random.RandomState(seed).randint(1, 64, (n,)).tolist()


def _chain_of(eng, tokens):
    """The engine's own content chain for `tokens` (its pool block size,
    current weight version, configured kv dtype)."""
    return kv_fabric.chain_keys(
        tokens,
        eng._alloc.block_size,
        int(eng._version),
        str(eng.config.kv_dtype),
    )


# -- 1. content-key contracts -------------------------------------------


def test_chain_keys_block_boundaries_and_position_binding():
    toks = list(range(100, 230))  # 130 tokens
    keys = kv_fabric.chain_keys(toks, 64, 0, "fp")
    # only COMPLETE blocks are keyed: 130 // 64 = 2, the 2-token tail not
    assert len(keys) == 2
    # a flip in block 0 changes EVERY downstream key (chaining)
    toks2 = list(toks)
    toks2[3] += 1
    keys2 = kv_fabric.chain_keys(toks2, 64, 0, "fp")
    assert keys2[0] != keys[0] and keys2[1] != keys[1]
    # a flip in block 1 leaves block 0's key intact (position binding:
    # key equality at i means the whole prefix through i matches)
    toks3 = list(toks)
    toks3[70] += 1
    keys3 = kv_fabric.chain_keys(toks3, 64, 0, "fp")
    assert keys3[0] == keys[0] and keys3[1] != keys[1]
    # a flip in the unkeyed tail changes nothing
    toks4 = list(toks)
    toks4[129] += 1
    assert kv_fabric.chain_keys(toks4, 64, 0, "fp") == keys
    # deterministic across calls (blake2b, not process-salted hash())
    assert kv_fabric.chain_keys(toks, 64, 0, "fp") == keys
    # max_blocks caps the chain without changing the kept keys
    assert kv_fabric.chain_keys(toks, 64, 0, "fp", max_blocks=1) == keys[:1]


def test_chain_keys_salted_by_weight_version_and_kv_dtype():
    toks = _prompt(128, seed=21)
    base = kv_fabric.chain_keys(toks, 64, 3, "fp")
    flipped = kv_fabric.chain_keys(toks, 64, 4, "fp")
    int8 = kv_fabric.chain_keys(toks, 64, 3, "int8")
    # a weight flip or a dtype change retires EVERY key: stale blocks can
    # never be mistaken for current ones (the staleness contract)
    assert not set(base) & set(flipped)
    assert not set(base) & set(int8)
    assert not set(flipped) & set(int8)


def test_digest_round_trip_cap_and_malformed():
    keys = kv_fabric.chain_keys(_prompt(640, seed=22), 64, 0, "fp")
    assert len(keys) == 10
    digest = kv_fabric.encode_digest(keys)
    assert kv_fabric.decode_digest(digest) == keys
    # cap truncates, hard cap bounds any caller value
    assert kv_fabric.decode_digest(
        kv_fabric.encode_digest(keys, cap=4)
    ) == keys[:4]
    assert (
        len(
            kv_fabric.decode_digest(
                kv_fabric.encode_digest(
                    range(kv_fabric.DIGEST_HARD_CAP + 100), cap=10**9
                )
            )
        )
        == kv_fabric.DIGEST_HARD_CAP
    )
    # malformed inputs decode to the empty set, never raise
    assert kv_fabric.decode_digest("") == []
    assert kv_fabric.decode_digest("!!!not-base64!!!") == []
    assert kv_fabric.decode_digest("AAA=") == []  # not a multiple of 8
    assert kv_fabric.decode_digest(None) == []
    assert kv_fabric.encode_digest([]) == ""


def test_longest_run():
    chain = [11, 22, 33, 44]
    assert kv_fabric.longest_run(chain, {11, 22, 33, 44}) == 4
    # chaining lets membership of key n-1 stand for the whole prefix
    assert kv_fabric.longest_run(chain, {33}) == 3
    assert kv_fabric.longest_run(chain, {99}) == 0
    assert kv_fabric.longest_run([], {11}) == 0


# -- 2. router ----------------------------------------------------------


def test_router_prefix_hashes_use_salted_content_keys():
    r = DecodeRouter(servers=["s1"], config=RouterConfig())
    r._versions = {"s1": 0}
    prefix = _prompt(256, seed=23)
    req = {"input_prefix": prefix, "prompt_len": len(prefix)}
    block = max(1, r.config.prefix_block_tokens)
    nb = min(len(prefix) // block, r.config.prefix_max_blocks)
    want = kv_fabric.chain_keys(
        prefix, block, 0, r._fleet_kv_dtype(), max_blocks=nb
    )
    assert r._prefix_hashes(req) == list(reversed(want))
    # the weight-version salt: a fleet-wide flip retires every affinity
    # entry instead of steering new-version requests at stale KV
    r._versions = {"s1": 1}
    h1 = r._prefix_hashes(req)
    assert h1 != list(reversed(want))
    assert not set(h1) & set(want)


def test_router_attaches_remote_fetch_hint_and_prices_it():
    cfg = RouterConfig(schedule_policy="prefix_affinity")
    r = DecodeRouter(servers=["s1", "s2"], config=cfg)
    r._versions = {"s1": 0, "s2": 0}
    prefix = _prompt(256, seed=24)
    block = max(1, cfg.prefix_block_tokens)
    nb = min(len(prefix) // block, cfg.prefix_max_blocks)
    chain = kv_fabric.chain_keys(
        prefix, block, 0, r._fleet_kv_dtype(), max_blocks=nb
    )
    # s2 advertises the whole run resident but is far too hot to route to
    r._fabric_index = {"s2": set(chain)}
    r._measured_tokens["s2"] = 1e9
    req = {
        "qid": "q1",
        "input_prefix": prefix,
        "prompt_len": len(prefix),
        "new_token_budget": 10,
        "group_size": 1,
    }
    out = r._try_schedule_locked(req)
    assert out is not None and out["url"] == "s1"
    hint = out.get("kv_fabric")
    assert hint is not None and hint["peer"] == "s2"
    assert kv_fabric.decode_digest(hint["keys"]) == chain
    assert r._counters["fabric_remote_hints_total"] == 1
    # marginal-cost pricing: the fetched run discounts the charged cost
    # by (1 - fetch_cost_factor) of the covered tokens
    factor = cfg.kv_fabric_fetch_cost_factor
    expected = max(
        r._request_cost(req) - nb * block * (1.0 - factor), 0.0
    )
    assert r._token_usage["s1"] == pytest.approx(expected)


def test_router_routes_to_local_fabric_holder_without_affinity_entry():
    cfg = RouterConfig(schedule_policy="prefix_affinity")
    r = DecodeRouter(servers=["s1", "s2"], config=cfg)
    r._versions = {"s1": 0, "s2": 0}
    prefix = _prompt(256, seed=25)
    block = max(1, cfg.prefix_block_tokens)
    nb = min(len(prefix) // block, cfg.prefix_max_blocks)
    chain = kv_fabric.chain_keys(
        prefix, block, 0, r._fleet_kv_dtype(), max_blocks=nb
    )
    # no _prefix_map entry — but s2 advertises the blocks (content-dedup
    # or an earlier fetch); the scheduler routes there, no wire transfer
    r._fabric_index = {"s2": set(chain)}
    req = {
        "qid": "q2",
        "input_prefix": prefix,
        "prompt_len": len(prefix),
        "new_token_budget": 10,
        "group_size": 1,
    }
    out = r._try_schedule_locked(req)
    assert out is not None and out["url"] == "s2"
    assert "kv_fabric" not in out  # already local: nothing to fetch
    assert r._counters["fabric_local_routes_total"] == 1


# -- 3. engine intra-replica dedup --------------------------------------


@pytest.mark.parametrize("gname", ["greedy", "sampled"])
def test_intra_replica_dedup_diverging_tail_bit_identity(gname):
    """Request 2 shares an 80-token block-aligned head with request 1 but
    DIVERGES afterwards: the tuple-prefix donor paths cannot serve it
    (r1's registered run is not a prefix of r2's prompt), the fabric
    device rung forks the shared blocks, and the stream stays
    bit-identical to a fresh full-prefill oracle."""
    g = _GREEDY if gname == "greedy" else _SAMPLED
    head = _prompt(80, seed=31)
    p1 = head + _prompt(16, seed=32)
    p2 = head + _prompt(16, seed=33)
    # the oracle runs the SAME request sequence with the fabric off: d2's
    # diverging tail defeats the tuple-prefix donor there, so it pays a
    # full re-prefill — and the sampling-key draw order matches
    oracle = _engine(fabric=False)
    try:
        oracle.generate(
            ModelRequest(rid="d1", input_ids=p1, gconfig=g), timeout=120
        )
        ro = oracle.generate(
            ModelRequest(rid="d2", input_ids=p2, gconfig=g), timeout=120
        )
        # the oracle really did pay the second full prefill
        assert oracle.get_metrics()["prefills_total"] == 2
    finally:
        oracle.destroy()
    eng = _engine()
    try:
        eng.generate(
            ModelRequest(rid="d1", input_ids=p1, gconfig=g), timeout=120
        )
        m0 = eng.get_metrics()
        assert m0["kv_fabric_enabled"] is True
        assert m0["kv_fabric_blocks_resident"] > 0
        r2 = eng.generate(
            ModelRequest(rid="d2", input_ids=p2, gconfig=g), timeout=120
        )
        m1 = eng.get_metrics()
        assert r2.output_tokens == ro.output_tokens
        # token-exact; logprobs to float tolerance — the fabric fork runs
        # the SAME suffix-prefill kernel as tuple-prefix sharing, whose
        # fusion differs from a monolithic prefill by ~1 ulp
        assert r2.output_logprobs == pytest.approx(
            ro.output_logprobs, abs=1e-5
        )
        # attributed to the fabric, NOT to the rid-exact host counters
        assert m1["kv_fabric_local_hits_total"] - m0[
            "kv_fabric_local_hits_total"
        ] == 1
        avoided = (
            m1["kv_fabric_local_tokens_avoided_total"]
            - m0["kv_fabric_local_tokens_avoided_total"]
        )
        assert avoided >= 64  # the whole shared block run
        assert m1["kv_host_hits_total"] == m0["kv_host_hits_total"]
        assert (
            m1["reprefill_tokens_avoided_total"]
            - m0["reprefill_tokens_avoided_total"]
            == avoided
        )
    finally:
        eng.destroy()


def test_fabric_registry_stale_on_weight_flip():
    prompt = _prompt(96, seed=34)
    eng = _engine()
    try:
        eng.generate(
            ModelRequest(rid="w", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
        m0 = eng.get_metrics()
        assert m0["kv_fabric_blocks_resident"] > 0
        assert m0["kv_fabric_digest"]
        # a bare version bump: resident keys carry the OLD salt, so a
        # new-version chain for the very same tokens can never match —
        # honest misses by construction, 0 stale-block serves
        eng.set_version(1)
        old = set(kv_fabric.decode_digest(m0["kv_fabric_digest"]))
        new_chain = _chain_of(eng, prompt[: len(prompt) - 1])
        assert not old & set(new_chain)
        # the weight INSTALL flush drops the registry outright (digest
        # hygiene: stop advertising blocks nobody can ever match)
        eng.pause_generation()
        with eng._sched_lock:
            eng._invalidate_parked()
        eng.continue_generation()
        m1 = eng.get_metrics()
        assert m1["kv_fabric_blocks_resident"] == 0
        assert kv_fabric.decode_digest(m1["kv_fabric_digest"]) == []
    finally:
        eng.destroy()


def test_host_store_indexes_blocks_and_matches_runs():
    prompt = _prompt(96, seed=35)
    eng = _engine(host_mb=16.0)
    try:
        _prefill(eng, ModelRequest(rid="h", input_ids=prompt,
                                   gconfig=_GREEDY))
        eng.pause_generation()
        with eng._sched_lock:
            assert eng._evict_parked_lru() is not None
        eng.continue_generation()
        chain = _chain_of(eng, prompt[:-1])
        assert len(chain) >= 8
        with eng._host_lock:
            store = eng._host_store
            assert set(chain) <= set(store.fabric_keys())
            got = store.match_blocks(chain)
            assert got is not None
            entry, n = got
            assert entry.rid == "h" and n == len(chain)
            # a shorter chain matches its own depth, not the entry's
            got2 = store.match_blocks(chain[:9])
            assert got2 is not None and got2[1] == 9
            # a diverging chain is a clean miss
            assert store.match_blocks([123456789]) is None
        # the host-resident blocks show up in the advertised digest
        m = eng.get_metrics()
        assert set(chain) <= set(
            kv_fabric.decode_digest(m["kv_fabric_digest"])
        )
    finally:
        eng.destroy()


# -- 4. fleet fetch over the wire ---------------------------------------


async def _start_server(engine, dcfg):
    srv = DecodeServer(dcfg, engine=engine, shutdown_grace=0.2)
    addr = await srv.start(host="127.0.0.1", port=0)
    return srv, addr


def test_kv_fetch_peer_to_peer_remote_hit_bit_identity():
    """Replica A holds the prompt's blocks; replica B receives the
    /generate carrying the router's fetch hint, pulls the run from A over
    /kv_fetch, and serves the request with a suffix prefill instead of a
    full one — bit-identically, with remote attribution."""
    prompt = _prompt(96, seed=41)
    oracle = _engine(fabric=False)
    try:
        ro = oracle.generate(
            ModelRequest(rid="f2", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
    finally:
        oracle.destroy()
    a = _engine()
    b = _engine()

    async def scenario():
        sa, aa = await _start_server(a, a.config)
        sb, ba = await _start_server(b, b.config)
        try:
            await arequest_with_retry(
                aa, "/generate",
                payload=dict(
                    rid="f1",
                    input_ids=prompt,
                    gconfig=dict(max_new_tokens=10, greedy=True),
                ),
                max_retries=1, timeout=120,
            )
            chain = _chain_of(a, prompt[: len(prompt) - 1])
            assert len(chain) >= 8
            out = await arequest_with_retry(
                ba, "/generate",
                payload=dict(
                    rid="f2",
                    input_ids=prompt,
                    gconfig=dict(max_new_tokens=10, greedy=True),
                    kv_fabric=dict(
                        peer=aa, keys=kv_fabric.encode_digest(chain)
                    ),
                ),
                max_retries=1, timeout=120,
            )
            ma = await arequest_with_retry(
                aa, "/metrics", method="GET", max_retries=1, timeout=30
            )
            mb = await arequest_with_retry(
                ba, "/metrics", method="GET", max_retries=1, timeout=30
            )
            return out, ma, mb
        finally:
            await sa.stop()
            await sb.stop()

    try:
        out, ma, mb = _run_async(scenario(), timeout=240)
    finally:
        a.destroy()
        b.destroy()
    assert out["output_tokens"] == ro.output_tokens
    # token-exact; logprobs to float tolerance (suffix-prefill numerics,
    # same contract as local tuple-prefix sharing)
    assert out["output_logprobs"] == pytest.approx(
        ro.output_logprobs, abs=1e-5
    )
    # server-side accounting: A served the run, B fetched + promoted it
    assert ma["kv_fabric"]["serve_sessions"] == 1
    assert ma["kv_fabric"]["serve_bytes"] > 0
    assert mb["kv_fabric"]["fetch_sessions"] == 1
    assert mb["kv_fabric"]["fetch_failures"] == 0
    assert mb["kv_fabric_sessions_in_total"] == 1
    assert mb["kv_fabric_fetch_bytes_total"] > 0
    assert mb["kv_fabric_remote_hits_total"] == 1
    assert mb["kv_fabric_remote_tokens_avoided_total"] >= 64
    # fetched sessions are fabric traffic, not migration traffic
    assert mb["kv_migrated_in_sessions_total"] == 0
    assert mb["reprefill_tokens_avoided_total"] >= 64


def test_warm_start_pulls_top_runs_from_peers():
    prompt = _prompt(96, seed=43)
    a = _engine()
    b = _engine()

    async def scenario():
        sa, aa = await _start_server(a, a.config)
        sb, ba = await _start_server(b, b.config)
        try:
            await arequest_with_retry(
                aa, "/generate",
                payload=dict(
                    rid="w1",
                    input_ids=prompt,
                    gconfig=dict(max_new_tokens=10, greedy=True),
                ),
                max_retries=1, timeout=120,
            )
            out = await arequest_with_retry(
                ba, "/warm_start",
                payload=dict(peers=[aa], max_sessions=2),
                max_retries=1, timeout=120,
            )
            mb = await arequest_with_retry(
                ba, "/metrics", method="GET", max_retries=1, timeout=30
            )
            return out, mb
        finally:
            await sa.stop()
            await sb.stop()

    try:
        out, mb = _run_async(scenario(), timeout=240)
        assert out["status"] == "ok"
        assert out["sessions"] >= 1 and out["bytes"] > 0
        assert out["failures"] == 0
        assert mb["kv_fabric"]["warm_start_sessions"] >= 1
        assert mb["kv_fabric_sessions_in_total"] >= 1
        # the warmed blocks are resident and advertised before any
        # request arrives — the router can route prefixes here on the
        # strength of the digest alone
        assert mb["kv_fabric_blocks_resident"] >= 8
        # and the first matching request promotes instead of prefilling
        m0 = b.get_metrics()
        r = b.generate(
            ModelRequest(rid="w2", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
        m1 = b.get_metrics()
        assert len(r.output_tokens) == 10
        assert m1["kv_fabric_remote_hits_total"] - m0[
            "kv_fabric_remote_hits_total"
        ] == 1
        assert m1["prefills_total"] == m0["prefills_total"]
    finally:
        a.destroy()
        b.destroy()


# -- 5. staleness -------------------------------------------------------


def test_fetched_run_from_other_weight_version_is_honest_miss():
    prompt = _prompt(96, seed=45)
    a = _engine()
    b = _engine()
    try:
        a.generate(
            ModelRequest(rid="s1", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
        chain = _chain_of(a, prompt[: len(prompt) - 1])
        sessions = a.export_fabric_blocks(keys=chain)
        assert len(sessions) == 1
        sess = sessions[0]
        assert sess["meta"]["rid"].startswith("fabric-")
        # a weight commit on B raced the fetch: the run's version salt no
        # longer matches — the import is rejected, nothing stale is served
        b.set_version(7)
        assert (
            b.import_session(sess["meta"], sess["k"], sess["v"])
            == "stale_version"
        )
        m0 = b.get_metrics()
        assert m0["kv_fabric_sessions_in_total"] == 0
        r = b.generate(
            ModelRequest(rid="s2", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
        m1 = b.get_metrics()
        assert len(r.output_tokens) == 10
        assert m1["kv_fabric_remote_hits_total"] == 0
        assert m1["kv_fabric_local_hits_total"] == 0
        assert m1["prefills_total"] - m0["prefills_total"] == 1
    finally:
        a.destroy()
        b.destroy()


def test_export_fabric_blocks_copy_semantics():
    """Serving the fabric never consumes local state: the donor keeps its
    registration and still forks its own siblings afterwards."""
    prompt = _prompt(96, seed=47)
    eng = _engine()
    try:
        eng.generate(
            ModelRequest(rid="c1", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
        chain = _chain_of(eng, prompt[: len(prompt) - 1])
        before = eng.get_metrics()["kv_fabric_blocks_resident"]
        assert eng.export_fabric_blocks(keys=chain)
        assert eng.export_fabric_blocks(keys=chain)  # repeatable
        m = eng.get_metrics()
        assert m["kv_fabric_blocks_resident"] == before
        # the donor still serves a same-prompt fork locally
        r = eng.generate(
            ModelRequest(rid="c2", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
        assert len(r.output_tokens) == 10
        assert eng.get_metrics()["prefills_total"] == 1
    finally:
        eng.destroy()


# -- 6. cheap drain (meta-only sessions) --------------------------------


def test_meta_only_export_wire_round_trip_and_honest_import():
    prompt = _prompt(96, seed=49)
    pre = _engine(role="prefill")
    try:
        _prefill(pre, ModelRequest(rid="m", input_ids=prompt,
                                   gconfig=_GREEDY))
        chain = _chain_of(pre, prompt[: len(prompt) - 1])
        # the surviving fleet advertises every block: identity alone ships
        sess = pre.export_session("m", refetchable=set(chain))
        assert sess is not None
        assert sess["meta"].get("meta_only") is True
        assert "k" not in sess
        m = pre.get_metrics()
        assert m["kv_fabric_meta_only_exports_total"] == 1
        assert m["kv_migrated_out_sessions_total"] == 1
    finally:
        pre.destroy()

    # single kvmeta frame on the wire — no kvdata buckets at all
    frames = list(pack_kv_session(sess["meta"], None, None, chunk_mb=0.01))
    assert len(frames) == 1
    st = WeightStaging()
    st.add_bucket(frames[0])
    sessions = unpack_kv_sessions(st.finalize())
    assert len(sessions) == 1
    meta, k, v, scales = sessions[0]
    assert meta.get("meta_only") is True
    assert k is None and v is None and scales is None

    dec = _engine(role="decode")
    try:
        assert dec.import_session(meta, k, v) == "ok"
        m0 = dec.get_metrics()
        # identity landed, but zero KV bytes — and the entry must never
        # promote as if it held blocks
        assert m0["kv_migrated_in_sessions_total"] == 1
        assert m0["kv_migrated_in_bytes_total"] == 0
        r = dec.generate(
            ModelRequest(rid="m", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
        m1 = dec.get_metrics()
        assert len(r.output_tokens) == 10
        # honest degradation: no sibling held the blocks here, so the
        # resume re-prefilled (no phantom fabric hit, no crash)
        assert m1["prefills_total"] - m0["prefills_total"] == 1
        assert m1["kv_fabric_remote_hits_total"] == 0
    finally:
        dec.destroy()


def test_refetchable_gate_requires_full_coverage():
    """A session whose blocks are NOT all refetchable exports its bytes —
    the meta-only shortcut only fires when the fleet truly covers it."""
    prompt = _prompt(96, seed=51)
    pre = _engine(role="prefill")
    try:
        _prefill(pre, ModelRequest(rid="p", input_ids=prompt,
                                   gconfig=_GREEDY))
        chain = _chain_of(pre, prompt[: len(prompt) - 1])
        sess = pre.export_session("p", refetchable=set(chain[:-1]))
        assert sess is not None
        assert not sess["meta"].get("meta_only")
        assert sess["k"] is not None
        assert pre.get_metrics()["kv_fabric_meta_only_exports_total"] == 0
    finally:
        pre.destroy()
