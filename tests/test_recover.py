"""Saver / Evaluator / RecoverHandler: freq gates, checkpoint round-trips,
and full train-state recovery (parity: areal/utils/{saver,evaluator,recover}.py).
"""

import os

import numpy as np
import pytest

import jax

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    EvaluatorConfig,
    MicroBatchSpec,
    OptimizerConfig,
    RecoverConfig,
    SaverConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo
from areal_tpu.dataset import SimpleDataLoader
from areal_tpu.engine.sft.lm_engine import JaxLMEngine
from areal_tpu.models.qwen2 import ModelConfig
from areal_tpu.utils.data import pad_sequences_to_tensors
from areal_tpu.utils.evaluator import Evaluator
from areal_tpu.utils.recover import (
    RecoverHandler,
    check_if_auto_recover,
    discard_recover_state,
)
from areal_tpu.utils.saver import Saver

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

FT = FinetuneSpec(total_train_epochs=2, dataset_size=16, train_batch_size=4)


def _make_engine(cpu_devices):
    cfg = TrainEngineConfig(
        experiment_name="rec",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=128),
        optimizer=OptimizerConfig(
            lr=1e-2,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=False,
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = TINY
    eng.create_process_group(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    eng.initialize(None, FT)
    return eng


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    seqs = []
    for L in (9, 13, 7, 11):
        ids = rng.randint(1, 64, (L,))
        mask = np.zeros(L, dtype=np.int32)
        mask[L // 2 :] = 1
        seqs.append(dict(input_ids=ids, loss_mask=mask))
    return pad_sequences_to_tensors(seqs)


def test_saver_freq_gate(tmp_path, cpu_devices):
    cfg = SaverConfig(
        experiment_name="rec", trial_name="t", fileroot=str(tmp_path), freq_steps=2
    )
    eng = _make_engine(cpu_devices)
    saver = Saver(cfg, FT)
    p0 = saver.save(eng, epoch=0, step=0, global_step=0)
    assert p0 is None  # gate not reached yet
    p1 = saver.save(eng, epoch=0, step=1, global_step=1)
    assert p1 is not None and os.path.exists(
        os.path.join(p1, "model.safetensors")
    )
    assert "epoch0epochstep1globalstep1" in p1
    eng.destroy()


def test_evaluator_freq_gate():
    ev = Evaluator(
        EvaluatorConfig(experiment_name="rec", trial_name="t", freq_steps=3), FT
    )
    ran = [ev.evaluate(lambda: None, 0, s, s) for s in range(6)]
    assert sum(ran) == 2


def test_recover_roundtrip(tmp_path, cpu_devices):
    rcfg = RecoverConfig(
        experiment_name="rec",
        trial_name="t",
        fileroot=str(tmp_path),
        mode="auto",
        freq_steps=1,
    )
    assert not check_if_auto_recover(rcfg)

    eng = _make_engine(cpu_devices)
    dl = SimpleDataLoader(list(range(16)), batch_size=4, seed=3)
    it = iter(dl)
    next(it)
    next(it)  # advance 2 batches

    # train 3 steps so moments are nontrivial
    for s in range(3):
        eng.train_lm(_batch(s))
    eng.set_version(3)

    saver = Saver(
        SaverConfig(
            experiment_name="rec", trial_name="t", fileroot=str(tmp_path), freq_steps=2
        ),
        FT,
    )
    saver.freq_ctl.check(steps=1)  # advance gate state to something nonzero
    handler = RecoverHandler(rcfg, FT)
    step_info = StepInfo(epoch=0, epoch_step=2, global_step=2, steps_per_epoch=4)
    root = handler.dump(eng, step_info, saver=saver, dataloader=dl)
    assert root is not None
    assert check_if_auto_recover(rcfg)
    params_before = jax.tree.leaves(eng.params)
    opt_before = jax.tree.leaves(eng.opt_state)
    eng.destroy()

    # fresh engine; load everything back
    eng2 = _make_engine(cpu_devices)
    saver2 = Saver(
        SaverConfig(
            experiment_name="rec", trial_name="t", fileroot=str(tmp_path), freq_steps=2
        ),
        FT,
    )
    dl2 = SimpleDataLoader(list(range(16)), batch_size=4, seed=3)
    handler2 = RecoverHandler(rcfg, FT)
    info = handler2.load(eng2, saver=saver2, dataloader=dl2)
    assert info is not None
    assert info.last_step_info.global_step == 2
    assert eng2.get_version() == 3
    assert saver2.state_dict() == saver.state_dict()
    assert dl2.state_dict() == dl.state_dict()
    for a, b in zip(params_before, jax.tree.leaves(eng2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(opt_before, jax.tree.leaves(eng2.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # training must continue identically from the restored state
    s1 = eng2.train_lm(_batch(99))
    assert np.isfinite(s1["loss"])
    eng2.destroy()

    discard_recover_state(rcfg)
    assert not check_if_auto_recover(rcfg)


def test_orbax_sharded_checkpoint_preserves_shardings(tmp_path, cpu_devices):
    """The recover format is orbax: each restored leaf comes back already
    laid out on the engine's NamedShardings (no host-gathered pickle)."""
    from areal_tpu.api.io_struct import SaveLoadMeta

    eng = _make_engine(cpu_devices)
    eng.train_lm(_batch(0))
    eng.set_version(5)
    path = str(tmp_path / "orbax_ckpt")
    eng.save(SaveLoadMeta(path=path, weight_format="orbax", with_optim=True))
    assert os.path.isdir(os.path.join(path, "orbax_state"))

    eng2 = _make_engine(cpu_devices)
    eng2.load(SaveLoadMeta(path=path, weight_format="orbax", with_optim=True))
    assert eng2.get_version() == 5
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(eng.params),
        jax.tree_util.tree_leaves_with_path(eng2.params),
    ):
        assert pa == pb
        assert a.sharding == b.sharding, f"sharding lost for {pa}"
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    eng.destroy()
    eng2.destroy()
