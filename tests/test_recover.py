"""Saver / Evaluator / RecoverHandler: freq gates, checkpoint round-trips,
and full train-state recovery (parity: areal/utils/{saver,evaluator,recover}.py).
"""

import os

import numpy as np
import pytest

import jax

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    EvaluatorConfig,
    MicroBatchSpec,
    OptimizerConfig,
    RecoverConfig,
    SaverConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo
from areal_tpu.dataset import SimpleDataLoader
from areal_tpu.engine.sft.lm_engine import JaxLMEngine
from areal_tpu.models.qwen2 import ModelConfig
from areal_tpu.utils.data import pad_sequences_to_tensors
from areal_tpu.utils.evaluator import Evaluator
from areal_tpu.utils.recover import (
    RecoverHandler,
    check_if_auto_recover,
    discard_recover_state,
    get_metrics,
    recover_root,
    reset_metrics,
    verify_step_dir,
)
from areal_tpu.utils.saver import Saver

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

FT = FinetuneSpec(total_train_epochs=2, dataset_size=16, train_batch_size=4)


def _make_engine(cpu_devices):
    cfg = TrainEngineConfig(
        experiment_name="rec",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=128),
        optimizer=OptimizerConfig(
            lr=1e-2,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=False,
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = TINY
    eng.create_process_group(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    eng.initialize(None, FT)
    return eng


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    seqs = []
    for L in (9, 13, 7, 11):
        ids = rng.randint(1, 64, (L,))
        mask = np.zeros(L, dtype=np.int32)
        mask[L // 2 :] = 1
        seqs.append(dict(input_ids=ids, loss_mask=mask))
    return pad_sequences_to_tensors(seqs)


def test_saver_freq_gate(tmp_path, cpu_devices):
    cfg = SaverConfig(
        experiment_name="rec", trial_name="t", fileroot=str(tmp_path), freq_steps=2
    )
    eng = _make_engine(cpu_devices)
    saver = Saver(cfg, FT)
    p0 = saver.save(eng, epoch=0, step=0, global_step=0)
    assert p0 is None  # gate not reached yet
    p1 = saver.save(eng, epoch=0, step=1, global_step=1)
    assert p1 is not None and os.path.exists(
        os.path.join(p1, "model.safetensors")
    )
    assert "epoch0epochstep1globalstep1" in p1
    eng.destroy()


def test_evaluator_freq_gate():
    ev = Evaluator(
        EvaluatorConfig(experiment_name="rec", trial_name="t", freq_steps=3), FT
    )
    ran = [ev.evaluate(lambda: None, 0, s, s) for s in range(6)]
    assert sum(ran) == 2


def test_recover_roundtrip(tmp_path, cpu_devices):
    rcfg = RecoverConfig(
        experiment_name="rec",
        trial_name="t",
        fileroot=str(tmp_path),
        mode="auto",
        freq_steps=1,
    )
    assert not check_if_auto_recover(rcfg)

    eng = _make_engine(cpu_devices)
    dl = SimpleDataLoader(list(range(16)), batch_size=4, seed=3)
    it = iter(dl)
    next(it)
    next(it)  # advance 2 batches

    # train 3 steps so moments are nontrivial
    for s in range(3):
        eng.train_lm(_batch(s))
    eng.set_version(3)

    saver = Saver(
        SaverConfig(
            experiment_name="rec", trial_name="t", fileroot=str(tmp_path), freq_steps=2
        ),
        FT,
    )
    saver.freq_ctl.check(steps=1)  # advance gate state to something nonzero
    handler = RecoverHandler(rcfg, FT)
    step_info = StepInfo(epoch=0, epoch_step=2, global_step=2, steps_per_epoch=4)
    root = handler.dump(eng, step_info, saver=saver, dataloader=dl)
    assert root is not None
    assert check_if_auto_recover(rcfg)
    params_before = jax.tree.leaves(eng.params)
    opt_before = jax.tree.leaves(eng.opt_state)
    eng.destroy()

    # fresh engine; load everything back
    eng2 = _make_engine(cpu_devices)
    saver2 = Saver(
        SaverConfig(
            experiment_name="rec", trial_name="t", fileroot=str(tmp_path), freq_steps=2
        ),
        FT,
    )
    dl2 = SimpleDataLoader(list(range(16)), batch_size=4, seed=3)
    handler2 = RecoverHandler(rcfg, FT)
    info = handler2.load(eng2, saver=saver2, dataloader=dl2)
    assert info is not None
    assert info.last_step_info.global_step == 2
    assert eng2.get_version() == 3
    assert saver2.state_dict() == saver.state_dict()
    assert dl2.state_dict() == dl.state_dict()
    for a, b in zip(params_before, jax.tree.leaves(eng2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(opt_before, jax.tree.leaves(eng2.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # training must continue identically from the restored state
    s1 = eng2.train_lm(_batch(99))
    assert np.isfinite(s1["loss"])
    eng2.destroy()

    discard_recover_state(rcfg)
    assert not check_if_auto_recover(rcfg)


def test_orbax_sharded_checkpoint_preserves_shardings(tmp_path, cpu_devices):
    """The recover format is orbax: each restored leaf comes back already
    laid out on the engine's NamedShardings (no host-gathered pickle)."""
    from areal_tpu.api.io_struct import SaveLoadMeta

    eng = _make_engine(cpu_devices)
    eng.train_lm(_batch(0))
    eng.set_version(5)
    path = str(tmp_path / "orbax_ckpt")
    eng.save(SaveLoadMeta(path=path, weight_format="orbax", with_optim=True))
    assert os.path.isdir(os.path.join(path, "orbax_state"))

    eng2 = _make_engine(cpu_devices)
    eng2.load(SaveLoadMeta(path=path, weight_format="orbax", with_optim=True))
    assert eng2.get_version() == 5
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(eng.params),
        jax.tree_util.tree_leaves_with_path(eng2.params),
    ):
        assert pa == pb
        assert a.sharding == b.sharding, f"sharding lost for {pa}"
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    eng.destroy()
    eng2.destroy()


# -- crash-atomic versioned recovery (ISSUE 14 tentpole) ---------------------


class _FakeStateEngine:
    """Tiny engine standing in for JaxLMEngine: checkpoint = one json file,
    so the atomic-layout / torn-skip / prune mechanics are testable without
    building a real model."""

    def __init__(self, weight=0.0):
        self.weight = float(weight)
        self._version = 0
        self.pushed = 0

    def save(self, meta):
        os.makedirs(meta.path, exist_ok=True)
        import json

        with open(os.path.join(meta.path, "state.json"), "w") as f:
            json.dump(dict(weight=self.weight, version=self._version), f)

    def load(self, meta):
        import json

        with open(os.path.join(meta.path, "state.json")) as f:
            st = json.load(f)
        self.weight = st["weight"]

    def get_version(self):
        return self._version

    def set_version(self, v):
        self._version = v

    def update_weights(self, meta):
        self.pushed += 1


def _rcfg(tmp_path, **kw):
    kw.setdefault("freq_steps", 1)
    return RecoverConfig(
        experiment_name="atom", trial_name="t", fileroot=str(tmp_path),
        mode="auto", **kw
    )


def _si(g):
    return StepInfo(epoch=0, epoch_step=g, global_step=g, steps_per_epoch=100)


def test_dump_layout_is_committed_and_verified(tmp_path):
    cfg = _rcfg(tmp_path)
    h = RecoverHandler(cfg, FT)
    eng = _FakeStateEngine(weight=1.5)
    path = h.dump(eng, _si(0), force=True)
    assert path is not None and path.endswith("step-0")
    assert os.path.isfile(os.path.join(path, "MANIFEST.json"))
    ok, reason = verify_step_dir(path)
    assert ok, reason
    root = recover_root(cfg)
    assert not any(n.endswith(".tmp") for n in os.listdir(root))
    assert check_if_auto_recover(cfg)


def test_keep_last_prunes_oldest(tmp_path):
    cfg = _rcfg(tmp_path, keep_last=2)
    h = RecoverHandler(cfg, FT)
    eng = _FakeStateEngine()
    for g in range(4):
        assert h.dump(eng, _si(g), force=True) is not None
    root = recover_root(cfg)
    steps = sorted(n for n in os.listdir(root) if n.startswith("step-"))
    assert steps == ["step-2", "step-3"]


def test_load_skips_torn_newest_falls_back(tmp_path):
    """A torn newest checkpoint (crash mid-dump or bit rot) costs one
    recovery point, never the run: load lands on the predecessor."""
    reset_metrics()
    cfg = _rcfg(tmp_path, keep_last=2)
    h = RecoverHandler(cfg, FT)
    eng = _FakeStateEngine(weight=10.0)
    h.dump(eng, _si(0), force=True)
    eng.weight = 20.0
    eng.set_version(1)
    newest = h.dump(eng, _si(1), force=True)
    # tear the newest: truncate the engine state behind the manifest
    with open(os.path.join(newest, "checkpoint", "state.json"), "w") as f:
        f.write("{")
    ok, _ = verify_step_dir(newest)
    assert not ok
    assert check_if_auto_recover(cfg)  # step-0 still verifies

    eng2 = _FakeStateEngine()
    h2 = RecoverHandler(cfg, FT)
    info = h2.load(eng2)
    assert info is not None
    assert info.last_step_info.global_step == 0
    assert eng2.weight == 10.0
    assert eng2.get_version() == 0
    assert get_metrics().get("recover_torn_skipped_total", 0) == 1


def test_check_if_auto_recover_reports_half_deleted_dir(tmp_path):
    """ISSUE 14 satellite: a half-deleted checkpoint dir must read as "no
    recoverable state" up front instead of exploding at load time."""
    cfg = _rcfg(tmp_path)
    h = RecoverHandler(cfg, FT)
    eng = _FakeStateEngine()
    path = h.dump(eng, _si(0), force=True)
    os.remove(os.path.join(path, "recover_info.pkl"))
    assert not check_if_auto_recover(cfg)
    assert RecoverHandler(cfg, FT).load(_FakeStateEngine()) is None


def test_dump_failure_degrades_not_raises(tmp_path):
    """A failed dump (here: injected abort mid-save) logs + counts + leaves
    the previous committed step intact; the loop keeps training."""
    from areal_tpu.core.fault_injection import (
        FaultPlan, FaultPoint, configure, deactivate,
    )

    reset_metrics()
    cfg = _rcfg(tmp_path, keep_last=2)
    h = RecoverHandler(cfg, FT)
    eng = _FakeStateEngine(weight=7.0)
    h.dump(eng, _si(0), force=True)
    configure(FaultPlan(seed=1, points=[
        FaultPoint(site="recover.dump.save", mode="abort", times=1)
    ]))
    try:
        assert h.dump(eng, _si(1), force=True) is None
    finally:
        deactivate()
    assert get_metrics().get("recover_dump_failures_total", 0) == 1
    # the crashed attempt is a .tmp dir, never a candidate; step-0 loads
    info = RecoverHandler(cfg, FT).load(_FakeStateEngine())
    assert info is not None and info.last_step_info.global_step == 0
    # and the next gate retries successfully, replacing the torn tmp
    assert h.dump(eng, _si(1), force=True) is not None


def test_recover_handler_freq_ctl_roundtrip(tmp_path):
    """The handler's own gate state rides in the checkpoint: after resume
    it must not re-fire early or skip a dump (ISSUE 14 satellite)."""
    cfg = _rcfg(tmp_path, freq_steps=3)
    h = RecoverHandler(cfg, FT)
    eng = _FakeStateEngine()
    fired = [h.dump(eng, _si(g)) is not None for g in range(4)]
    assert fired == [False, False, True, False]  # gate fires on the 3rd step

    h2 = RecoverHandler(cfg, FT)
    info = h2.load(_FakeStateEngine())
    assert info is not None
    # the committed state is the gate AS OF the fired dump (the g=3 check
    # happened after the commit and is rolled back with the crash). The
    # resumed gate continues that cadence exactly: three steps to the next
    # fire — not zero (immediate re-fire) and not a skipped save.
    fired2 = [h2.dump(eng, _si(g)) is not None for g in range(4, 8)]
    assert fired2 == [False, False, True, False]


def test_replayed_step_redump_displaces_atomically(tmp_path):
    """Re-dumping the same global step (a replayed step after recovery)
    must commit the new content and leave no .old/.tmp residue."""
    cfg = _rcfg(tmp_path)
    h = RecoverHandler(cfg, FT)
    eng = _FakeStateEngine(weight=1.0)
    p = h.dump(eng, _si(0), force=True)
    eng.weight = 2.0
    p2 = h.dump(eng, _si(0), force=True)
    assert p == p2
    ok, reason = verify_step_dir(p2)
    assert ok, reason
    eng2 = _FakeStateEngine()
    RecoverHandler(cfg, FT).load(eng2)
    assert eng2.weight == 2.0
    root = recover_root(cfg)
    assert all(not n.endswith((".tmp", ".old")) for n in os.listdir(root))
