"""Code-verifier sandbox reward (parity: functioncall/code/verify.py:111 —
the reference's batched testcase execution shapes)."""

import json
import time

import pytest

from areal_tpu.reward.code_verify import (
    code_reward_fn,
    code_verify,
    extract_code,
    run_problem,
)

ADD_STDIO = "a, b = map(int, input().split())\nprint(a + b)\n"
ADD_FN = "def add(a, b):\n    return a + b\n"


def test_stdio_pass_and_fail():
    io_spec = {"inputs": ["1 2", "10 20"], "outputs": ["3", "30"]}
    assert run_problem(ADD_STDIO, io_spec) is True
    bad = {"inputs": ["1 2"], "outputs": ["4"]}
    assert run_problem(ADD_STDIO, bad) is False


def test_fn_name_style():
    io_spec = {
        "fn_name": "add",
        "inputs": [[1, 2], [5, 7]],
        "outputs": [3, 12],
    }
    assert run_problem(ADD_FN, io_spec) is True
    assert run_problem("def add(a, b):\n    return a - b\n", io_spec) is False


def test_crashing_and_missing_code():
    io_spec = {"inputs": ["1 2"], "outputs": ["3"]}
    assert run_problem("raise RuntimeError('boom')", io_spec) is False
    assert run_problem("syntax error here ((", io_spec) is False


def test_infinite_loop_times_out_quickly():
    io_spec = {"inputs": ["1 2"], "outputs": ["3"]}
    t0 = time.monotonic()
    ok = run_problem(
        "while True:\n    pass\n",
        io_spec,
        timeout_per_case=1.0,
        total_timeout=10.0,
    )
    assert ok is False
    assert time.monotonic() - t0 < 10.0


def test_batched_code_verify_reference_shapes():
    """The reference call shape: id2info + generateds + query_ids with
    JSON-string input_output blobs -> list of 0/1."""
    id2info = {
        "q0": {
            "input_output": json.dumps(
                {"inputs": ["1 2"], "outputs": ["3"]}
            )
        },
        "q1": {
            "input_output": json.dumps(
                {"fn_name": "add", "inputs": [[2, 2]], "outputs": [4]}
            ),
            "timeout": 2,
        },
        "q2": {
            "input_output": json.dumps(
                {"inputs": ["1 2"], "outputs": ["999"]}
            )
        },
    }
    out = code_verify(
        id2info,
        [ADD_STDIO, ADD_FN, ADD_STDIO],
        ["q0", "q1", "q2"],
    )
    assert out == [1, 1, 0]


def test_extract_code_last_block():
    text = "thinking...\n```python\nx = 1\n```\nmore\n```py\nprint('final')\n```"
    assert extract_code(text) == "print('final')"
    assert extract_code("no code at all") is None


def test_code_reward_fn_rlvr_signature():
    completion = f"The answer:\n```python\n{ADD_STDIO}```"
    r = code_reward_fn(
        "p",
        completion,
        [],
        [],
        input_output={"inputs": ["3 4"], "outputs": ["7"]},
    )
    assert r == 1.0
    assert code_reward_fn("p", "no code", [], [], input_output={}) == 0.0
