from areal_tpu.utils.timeutil import FrequencyControl


def test_step_gate():
    fc = FrequencyControl(freq_step=3)
    fires = [fc.check(steps=1) for _ in range(7)]
    assert fires == [False, False, True, False, False, True, False]


def test_epoch_gate():
    fc = FrequencyControl(freq_epoch=2)
    assert not fc.check(epochs=1)
    assert fc.check(epochs=1)


def test_initial_value():
    fc = FrequencyControl(freq_step=100, initial_value=True)
    assert fc.check(steps=1)
    assert not fc.check(steps=1)


def test_disabled_never_fires():
    fc = FrequencyControl()
    assert not any(fc.check(steps=1, epochs=1) for _ in range(10))


def test_state_dict_roundtrip():
    fc = FrequencyControl(freq_step=5)
    for _ in range(4):
        fc.check(steps=1)
    state = fc.state_dict()
    fc2 = FrequencyControl(freq_step=5)
    fc2.load_state_dict(state)
    assert fc2.check(steps=1)  # 5th step fires


def test_gates_are_independent():
    # Regression: a step-fire must not reset the seconds gate's baseline.
    import time as _time

    fc = FrequencyControl(freq_step=1, freq_sec=0.3)
    t0 = _time.monotonic()
    fired_by_time = False
    while _time.monotonic() - t0 < 0.5:
        fc.check(steps=1)  # fires on steps every call
        _time.sleep(0.05)
        if fc._last_time > t0:
            fired_by_time = True
    assert fired_by_time, "seconds gate was starved by step fires"
