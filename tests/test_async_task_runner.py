import asyncio
import time

import pytest

from areal_tpu.core.async_task_runner import AsyncTaskRunner, TaskRunnerError


@pytest.fixture()
def runner():
    r = AsyncTaskRunner(queue_size=64, name="test")
    r.start()
    yield r
    r.destroy()


def test_submit_and_wait(runner):
    async def work(x):
        await asyncio.sleep(0.01)
        return x * 2

    for i in range(5):
        runner.submit(lambda i=i: work(i))
    results = runner.wait(5, timeout=5)
    assert sorted(r.result for r in results) == [0, 2, 4, 6, 8]


def test_wait_timeout_preserves_results(runner):
    async def slow():
        await asyncio.sleep(10)

    async def fast():
        return 1

    runner.submit(fast)
    runner.submit(slow)
    with pytest.raises(TimeoutError):
        runner.wait(2, timeout=0.3)
    # the fast result must not be lost
    results = runner.wait(1, timeout=1)
    assert results[0].result == 1


def test_exceptions_captured(runner):
    async def boom():
        raise ValueError("boom")

    runner.submit(boom)
    [res] = runner.wait(1, timeout=5)
    assert isinstance(res.exception, ValueError)


def test_exceptions_raised_when_requested(runner):
    async def boom():
        raise ValueError("boom")

    runner.submit(boom)
    with pytest.raises(TaskRunnerError):
        runner.wait(1, timeout=5, raise_errors=True)


def test_pause_resume(runner):
    done = []

    async def work():
        done.append(1)
        return 1

    runner.pause()
    runner.submit(work)
    time.sleep(0.2)
    assert not done  # paused: not launched
    runner.resume()
    runner.wait(1, timeout=5)
    assert done


def test_inflight_tracking(runner):
    async def slow():
        await asyncio.sleep(0.2)

    for _ in range(3):
        runner.submit(slow)
    assert runner.inflight == 3
    runner.wait(3, timeout=5)
    assert runner.inflight == 0


# -- failure accounting (ISSUE 9 satellite) ---------------------------------


def test_failed_task_releases_inflight_exactly_once(runner):
    """A raising task must emit exactly ONE TaskResult and drop the
    inflight counter exactly once — a leak here wedged StalenessManager
    capacity (the submitted slot stayed `running` forever)."""

    async def boom():
        raise ValueError("episode died")

    for _ in range(4):
        runner.submit(boom)
    results = runner.wait(4, timeout=5)
    assert len(results) == 4
    assert all(isinstance(r.exception, ValueError) for r in results)
    assert runner.inflight == 0
    assert runner.poll_results() == []  # no extra results emitted


def test_cancelled_task_emits_result_and_releases_slot():
    """Cancellation (pause-drain / shutdown) must still surface a
    TaskResult carrying CancelledError so the executor releases the
    capacity slot — the old path re-raised without emitting, leaking both
    the inflight count and the StalenessManager running slot."""
    r = AsyncTaskRunner(queue_size=8, name="cancel-test")
    r.start()
    started = []

    async def hang():
        started.append(1)
        await asyncio.sleep(60)

    r.submit(hang)
    deadline = time.monotonic() + 5
    while not started and time.monotonic() < deadline:
        time.sleep(0.01)
    assert started, "task never started"
    r.destroy()  # cancels the pending task on shutdown
    results = r.poll_results()
    assert len(results) == 1
    assert isinstance(results[0].exception, asyncio.CancelledError)
    assert r.inflight == 0
