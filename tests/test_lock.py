"""DistributedLock over name_resolve (parity: areal/utils/lock.py +
areal/tests/torchrun lock test — mutual exclusion under contention)."""

import threading
import time

from areal_tpu.utils.lock import DistributedLock
from areal_tpu.utils.name_resolve import (
    MemoryNameRecordRepository,
    NfsNameRecordRepository,
)


def test_mutual_exclusion_threads():
    repo = MemoryNameRecordRepository()
    counter = {"v": 0}

    def worker():
        for _ in range(20):
            with DistributedLock("ctr", repo=repo, retry_interval=0.001):
                v = counter["v"]
                time.sleep(0.0005)  # widen the race window
                counter["v"] = v + 1

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert counter["v"] == 80


def test_acquire_timeout_and_release():
    repo = MemoryNameRecordRepository()
    a = DistributedLock("x", repo=repo)
    b = DistributedLock("x", repo=repo, retry_interval=0.01)
    assert a.acquire()
    assert not b.acquire(timeout=0.1)
    a.release()
    assert b.acquire(timeout=1.0)
    b.release()
    assert not a.locked()


def test_release_does_not_steal(tmp_path):
    """If A's lock lapsed and B holds it, A.release must not delete B's."""
    repo = NfsNameRecordRepository(str(tmp_path / "nr"))
    a = DistributedLock("y", repo=repo)
    b = DistributedLock("y", repo=repo)
    assert a.acquire()
    # simulate A's entry lapsing: forcibly delete, then B acquires
    repo.delete(a.key)
    assert b.acquire(timeout=1.0)
    a.release()  # must NOT remove B's lock
    assert b.locked()
    b.release()


def test_cross_process_nfs(tmp_path):
    """Two processes contend via the NFS backend."""
    import subprocess
    import sys

    root = str(tmp_path / "nr")
    script = f"""
import sys, time
sys.path.insert(0, {repr('/root/repo')})
from areal_tpu.utils.lock import DistributedLock
from areal_tpu.utils.name_resolve import NfsNameRecordRepository
repo = NfsNameRecordRepository({root!r})
with DistributedLock("p", repo=repo, retry_interval=0.01):
    time.sleep(0.4)
print("done")
"""
    p1 = subprocess.Popen([sys.executable, "-c", script],
                          stdout=subprocess.PIPE)
    repo = NfsNameRecordRepository(root)
    # wait until the child actually holds the lock before contending
    deadline = time.monotonic() + 15.0
    lock = DistributedLock("p", repo=repo, retry_interval=0.02)
    while not lock.locked():
        assert time.monotonic() < deadline, "child never acquired"
        time.sleep(0.02)
    t0 = time.monotonic()
    assert lock.acquire(timeout=10.0)
    waited = time.monotonic() - t0
    lock.release()
    assert p1.wait(10) == 0
    assert waited > 0.15, f"should have waited for the child, waited {waited}"
