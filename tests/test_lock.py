"""Direct unit tests for areal_tpu/utils/lock.py.

DistributedLock (parity: areal/utils/lock.py + areal/tests/torchrun lock
test — mutual exclusion under contention) plus the in-process OrderedLock:
reentrancy, timeout, and the rank-ordering contract the areal-lint
lock-order analyzer (AR102/AR103) assumes — the runtime and the static
checker must enforce the same hierarchy rules.
"""

import threading
import time

import pytest

from areal_tpu.utils.lock import (
    DistributedLock,
    LockOrderViolation,
    OrderedLock,
)
from areal_tpu.utils.name_resolve import (
    MemoryNameRecordRepository,
    NfsNameRecordRepository,
)


def test_mutual_exclusion_threads():
    repo = MemoryNameRecordRepository()
    counter = {"v": 0}

    def worker():
        for _ in range(20):
            with DistributedLock("ctr", repo=repo, retry_interval=0.001):
                v = counter["v"]
                time.sleep(0.0005)  # widen the race window
                counter["v"] = v + 1

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert counter["v"] == 80


def test_acquire_timeout_and_release():
    repo = MemoryNameRecordRepository()
    a = DistributedLock("x", repo=repo)
    b = DistributedLock("x", repo=repo, retry_interval=0.01)
    assert a.acquire()
    assert not b.acquire(timeout=0.1)
    a.release()
    assert b.acquire(timeout=1.0)
    b.release()
    assert not a.locked()


def test_release_does_not_steal(tmp_path):
    """If A's lock lapsed and B holds it, A.release must not delete B's."""
    repo = NfsNameRecordRepository(str(tmp_path / "nr"))
    a = DistributedLock("y", repo=repo)
    b = DistributedLock("y", repo=repo)
    assert a.acquire()
    # simulate A's entry lapsing: forcibly delete, then B acquires
    repo.delete(a.key)
    assert b.acquire(timeout=1.0)
    a.release()  # must NOT remove B's lock
    assert b.locked()
    b.release()


def test_cross_process_nfs(tmp_path):
    """Two processes contend via the NFS backend."""
    import subprocess
    import sys

    root = str(tmp_path / "nr")
    script = f"""
import sys, time
sys.path.insert(0, {repr('/root/repo')})
from areal_tpu.utils.lock import DistributedLock
from areal_tpu.utils.name_resolve import NfsNameRecordRepository
repo = NfsNameRecordRepository({root!r})
with DistributedLock("p", repo=repo, retry_interval=0.01):
    time.sleep(0.4)
print("done")
"""
    p1 = subprocess.Popen([sys.executable, "-c", script],
                          stdout=subprocess.PIPE)
    repo = NfsNameRecordRepository(root)
    # wait until the child actually holds the lock before contending
    deadline = time.monotonic() + 15.0
    lock = DistributedLock("p", repo=repo, retry_interval=0.02)
    while not lock.locked():
        assert time.monotonic() < deadline, "child never acquired"
        time.sleep(0.02)
    t0 = time.monotonic()
    assert lock.acquire(timeout=10.0)
    waited = time.monotonic() - t0
    lock.release()
    assert p1.wait(10) == 0
    assert waited > 0.15, f"should have waited for the child, waited {waited}"


def test_distributed_lock_not_reentrant():
    """DistributedLock is NOT reentrant: the holder's second acquire spins
    on the existing entry until timeout (documented contract)."""
    repo = MemoryNameRecordRepository()
    a = DistributedLock("re", repo=repo, retry_interval=0.01)
    assert a.acquire()
    assert not a.acquire(timeout=0.1)
    a.release()
    assert a.acquire(timeout=1.0)
    a.release()


# -- OrderedLock: the ordering contract the lock-order analyzer assumes ----


def test_ordered_lock_rank_order_enforced():
    low = OrderedLock("d._low", rank=10)
    high = OrderedLock("d._high", rank=20)
    # declared direction: fine
    with low:
        with high:
            assert high.held_by_me()
    # inverted direction: surfaced immediately instead of deadlocking later
    with high:
        with pytest.raises(LockOrderViolation):
            low.acquire()
    # a failed acquire must not leak held-stack state
    assert not low.held_by_me() and not high.held_by_me()
    with low:
        with high:
            pass


def test_ordered_lock_equal_rank_rejected():
    a = OrderedLock("d._a", rank=10)
    b = OrderedLock("d._b", rank=10)
    with a:
        with pytest.raises(LockOrderViolation):
            b.acquire()


def test_ordered_lock_domains_do_not_interact():
    sched = OrderedLock("jax_decode._sched_lock", rank=10)
    stats = OrderedLock("remote_inf._stats_lock", rank=5)
    # lower rank, different domain: no constraint
    with sched:
        with stats:
            pass


def test_ordered_lock_reentrancy():
    r = OrderedLock("d._r", rank=10, reentrant=True)
    with r:
        with r:  # RLock re-entry permitted
            assert r.held_by_me()
    assert not r.held_by_me()
    n = OrderedLock("d._n", rank=10)
    with n:
        # non-reentrant re-acquire raises instead of self-deadlocking
        with pytest.raises(LockOrderViolation):
            n.acquire()
    assert not n.locked()


def test_ordered_lock_timeout_and_contention():
    lock = OrderedLock("d._t", rank=10)
    acquired = threading.Event()
    release = threading.Event()

    def holder():
        with lock:
            acquired.set()
            release.wait(5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert acquired.wait(5)
    t0 = time.monotonic()
    assert not lock.acquire(timeout=0.2)  # times out under contention
    assert 0.15 <= time.monotonic() - t0 < 2.0
    assert lock.locked() and not lock.held_by_me()
    release.set()
    assert lock.acquire(timeout=5.0)
    lock.release()
    t.join(5)


def test_ordered_lock_per_thread_stacks():
    """Held stacks are thread-local: thread B holding the high lock does
    not constrain thread A's low->high acquisition."""
    low = OrderedLock("d2._low", rank=10)
    high = OrderedLock("d2._high", rank=20)
    got_high = threading.Event()
    done = threading.Event()

    def b():
        with high:
            got_high.set()
            done.wait(5)

    t = threading.Thread(target=b, daemon=True)
    t.start()
    assert got_high.wait(5)
    with low:  # must not raise: B's stack is not ours
        assert not high.held_by_me()
    done.set()
    t.join(5)


def test_ordered_lock_mutual_exclusion():
    lock = OrderedLock("d._mx", rank=10)
    counter = {"v": 0}

    def worker():
        for _ in range(200):
            with lock:
                v = counter["v"]
                counter["v"] = v + 1

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert counter["v"] == 800


def test_engine_hierarchy_ranks_match_analyzer_contract():
    """The decode engine's declared hierarchy is what the static analyzer
    checks (docs/architecture.md): _sched_lock(10) -> _weight_lock(20) ->
    _metrics_lock(30), all in one domain."""
    from areal_tpu.api.cli_args import JaxDecodeConfig
    from areal_tpu.engine.jax_decode import JaxDecodeEngine

    eng = JaxDecodeEngine(JaxDecodeConfig())
    assert eng._sched_lock.rank < eng._weight_lock.rank < eng._metrics_lock.rank
    assert (
        eng._sched_lock.domain
        == eng._weight_lock.domain
        == eng._metrics_lock.domain
    )
    # the declared direction composes; the inversion raises
    with eng._sched_lock:
        with eng._weight_lock:
            with eng._metrics_lock:
                pass
    with eng._weight_lock:
        with pytest.raises(LockOrderViolation):
            eng._sched_lock.acquire()
