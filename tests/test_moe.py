"""MoE decoder: routing correctness, EP sharding, HF roundtrip, training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.models.qwen2 import (
    ModelConfig,
    PADDING_SEGMENT,
    forward,
    init_params,
    moe_mlp,
    param_logical_axes,
    param_shapes,
)
from areal_tpu.parallel import mesh as mesh_lib

MOE_CFG = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
    num_experts=4,
    num_experts_per_tok=2,
    moe_intermediate_size=16,
    attn_impl="dense",
)


def test_moe_param_shapes_and_axes_align():
    shapes = param_shapes(MOE_CFG)
    axes = param_logical_axes(MOE_CFG)
    mlp_s = shapes["layers"]["mlp"]
    mlp_a = axes["layers"]["mlp"]
    assert mlp_s["gate_kernel"] == (2, 4, 32, 16)  # [L, E, H, Mm]
    assert mlp_a["gate_kernel"] == ("layers", "experts", "embed", "mlp")
    assert mlp_s["router_kernel"] == (2, 32, 4)


def test_moe_mlp_matches_explicit_topk_reference():
    """Dispatch/combine einsums == naive per-token top-k mixture (ample capacity)."""
    rng = np.random.RandomState(0)
    T, H, E, K, Mm = 64, 16, 4, 2, 8
    cfg = ModelConfig(
        hidden_size=H,
        num_experts=E,
        num_experts_per_tok=K,
        moe_intermediate_size=Mm,
        capacity_factor=8.0,  # no drops
        norm_topk_prob=True,
    )
    p = {
        "router_kernel": jnp.asarray(rng.randn(H, E), jnp.float32),
        "gate_kernel": jnp.asarray(rng.randn(E, H, Mm) * 0.3, jnp.float32),
        "up_kernel": jnp.asarray(rng.randn(E, H, Mm) * 0.3, jnp.float32),
        "down_kernel": jnp.asarray(rng.randn(E, Mm, H) * 0.3, jnp.float32),
    }
    x = jnp.asarray(rng.randn(T, H), jnp.float32)
    y, aux = moe_mlp(p, x, cfg)

    # naive reference
    probs = jax.nn.softmax(x @ p["router_kernel"], axis=-1)
    vals, idx = jax.lax.top_k(probs, K)
    vals = vals / vals.sum(-1, keepdims=True)
    y_ref = np.zeros((T, H), np.float32)
    for t in range(T):
        for k in range(K):
            e = int(idx[t, k])
            h = np.asarray(x[t]) @ np.asarray(p["gate_kernel"][e])
            u = np.asarray(x[t]) @ np.asarray(p["up_kernel"][e])
            act = (h / (1 + np.exp(-h))) * u
            y_ref[t] += float(vals[t, k]) * (act @ np.asarray(p["down_kernel"][e]))
    np.testing.assert_allclose(np.asarray(y), y_ref, atol=2e-4, rtol=2e-4)
    assert np.isfinite(float(aux)) and float(aux) >= 1.0 - 1e-3  # >= 1 by Cauchy-Schwarz


def test_moe_capacity_drops_tokens():
    """With capacity 1 slot per expert, most assignments are dropped and the
    output magnitude shrinks — but shapes and finiteness hold."""
    rng = np.random.RandomState(1)
    T, H, E = 32, 8, 2
    cfg = ModelConfig(
        hidden_size=H, num_experts=E, num_experts_per_tok=1,
        moe_intermediate_size=4, capacity_factor=0.06,  # C = 1
    )
    p = {
        "router_kernel": jnp.asarray(rng.randn(H, E), jnp.float32),
        "gate_kernel": jnp.asarray(rng.randn(E, H, 4), jnp.float32),
        "up_kernel": jnp.asarray(rng.randn(E, H, 4), jnp.float32),
        "down_kernel": jnp.asarray(rng.randn(E, 4, H), jnp.float32),
    }
    x = jnp.asarray(rng.randn(T, H), jnp.float32)
    y, _ = moe_mlp(p, x, cfg)
    assert y.shape == (T, H)
    # dropped tokens produce zero rows
    nonzero_rows = int((np.abs(np.asarray(y)).sum(-1) > 1e-6).sum())
    assert nonzero_rows <= 2 * E  # at most C(=1) tokens per expert survive


@pytest.mark.slow
def test_moe_forward_and_grad_finite():
    params = init_params(MOE_CFG, jax.random.PRNGKey(0))
    T = 32
    ids = jnp.asarray(np.arange(T) % 64, jnp.int32)
    pos = jnp.arange(T, dtype=jnp.int32)
    seg = jnp.zeros(T, jnp.int32)
    logits, aux = forward(params, ids, pos, seg, MOE_CFG, with_aux=True)
    assert logits.shape == (T, 64)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) > 0

    def loss(p):
        lg, aux = forward(p, ids, pos, seg, MOE_CFG, with_aux=True)
        return jnp.mean(lg**2) + 0.01 * aux

    grads = jax.grad(loss)(params)
    flat = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)
    # router gets gradient signal (through combine weights and aux)
    gnorm_router = float(
        jnp.linalg.norm(grads["layers"]["mlp"]["router_kernel"])
    )
    assert gnorm_router > 0


def test_moe_ep_sharding_compiles_on_mesh(cpu_devices):
    mesh = mesh_lib.build_mesh(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    rules = mesh_lib.default_rules()
    axes = param_logical_axes(MOE_CFG)
    shardings = jax.tree.map(
        lambda a: mesh_lib.named_sharding(mesh, a, rules),
        axes,
        is_leaf=lambda x: isinstance(x, tuple),
    )
    params = init_params(MOE_CFG, jax.random.PRNGKey(0))
    params = jax.tree.map(jax.device_put, params, shardings)
    # expert dim sharded over dp=4
    spec = shardings["layers"]["mlp"]["gate_kernel"].spec
    assert "dp" in str(spec)

    T = 128
    ids = jnp.asarray(np.arange(T) % 64, jnp.int32)
    pos = jnp.arange(T, dtype=jnp.int32)
    seg = jnp.zeros(T, jnp.int32)

    @jax.jit
    def f(p):
        return forward(p, ids, pos, seg, MOE_CFG)

    out = f(params)
    # matches unsharded run
    ref = forward(init_params(MOE_CFG, jax.random.PRNGKey(0)), ids, pos, seg, MOE_CFG)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_moe_hf_roundtrip(tmp_path):
    from areal_tpu.models.hf_io import load_hf_params, save_hf_params

    params = init_params(MOE_CFG, jax.random.PRNGKey(3))
    out_dir = str(tmp_path / "ckpt")
    save_hf_params(params, MOE_CFG, out_dir)
    # config.json for from_hf_config-style consumers
    import json

    with open(f"{out_dir}/config.json", "w") as f:
        json.dump({"model_type": "qwen3_moe"}, f)
    loaded = load_hf_params(out_dir, MOE_CFG, dtype="float32")

    def cmp(a, b):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1e-6
        )

    jax.tree.map(cmp, params, loaded)
