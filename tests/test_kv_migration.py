"""Disaggregated prefill/decode: KV-block streaming between replica roles
and cross-replica session migration (ISSUE 10).

Coverage layers:

1. Engine contracts: prefill-only admission parks exactly the prompt's KV
   (the `HostKVEntry` resume shape); `export_session` / `import_session`
   move a session between engines BIT-IDENTICALLY — a decode engine that
   imported a migrated session continues the stream with zero transformer
   prefill and emits the same tokens AND logprobs (greedy and sampled,
   both kv layouts) as a never-migrated oracle.
2. Staleness: an import whose KV was computed under a different weight
   version is rejected as an honest miss (tombstoned), and the resume
   re-prefills under the current weights — the cross-replica extension of
   the install-flush rule.
3. Server wire: `/prefill` with a target streams the session server→
   server over the framed KV wire (interval-merged staging); `/kv_commit`
   is idempotent per xid (a replayed migration lands exactly once); a
   torn frame is rejected before staging and the re-sent frame recovers;
   `/drain` migrates every parked session to a survivor that resumes all
   of them with zero re-prefill.
4. Router: a fleet with prefill-role replicas schedules (decode by
   kv-pool headroom, prefill by prefix affinity) and ships both URLs.
"""

import asyncio
import threading
import time

import numpy as np
import pytest

import jax

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
    RouterConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.core.weight_transfer import (
    WeightStaging,
    pack_kv_session,
    unpack_kv_sessions,
)
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.launcher.decode_server import DecodeServer
from areal_tpu.launcher.router import DecodeRouter
from areal_tpu.models.qwen2 import ModelConfig, init_params
from areal_tpu.utils.http import arequest_with_retry, close_current_session

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = init_params(TINY, jax.random.PRNGKey(0))
    return _PARAMS


def _engine(*, role="unified", kv_layout="paged", host_mb=0.0, R=3,
            context=256, page=8, chunk=4, seed=1):
    cfg = JaxDecodeConfig(
        context_length=context,
        max_running_requests=R,
        new_tokens_per_chunk=chunk,
        page_size=page,
        kv_layout=kv_layout,
        paged_attn_impl="xla",
        kv_host_pool_mb=host_mb,
        role=role,
        kv_migrate_chunk_mb=0.01,  # several frames per session on TINY
        dtype="float32",
        kv_cache_dtype="float32",
        random_seed=seed,
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(_params(), TINY)
    eng.initialize()
    return eng


def _run_async(coro, timeout=120):
    result = {}

    def go():
        try:
            result["v"] = asyncio.run(coro)
        except BaseException as e:  # noqa: BLE001
            result["e"] = e

    t = threading.Thread(target=go, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "async scenario timed out"
    if "e" in result:
        raise result["e"]
    return result.get("v")


def _prefill(eng, req):
    return _run_async(eng.aprefill(req))


_GREEDY = GenerationHyperparameters(max_new_tokens=10, greedy=True)
_SAMPLED = GenerationHyperparameters(
    max_new_tokens=10, temperature=0.8, top_p=0.9
)


def _prompt(n=40, seed=3):
    return np.random.RandomState(seed).randint(1, 64, (n,)).tolist()


# -- 1. engine contracts -----------------------------------------------


def test_prefill_only_parks_exact_coverage_and_resumes_locally():
    eng = _engine()
    try:
        prompt = _prompt()
        r = _prefill(eng, ModelRequest(rid="a", input_ids=prompt,
                                       gconfig=_GREEDY))
        assert r.stop_reason == "prefill"
        assert r.output_tokens == [] and r.output_logprobs == []
        assert eng.list_exportable_sessions() == ["a"]
        # the parked session IS the interrupt shape: a local /generate
        # with the same rid + prompt resumes with zero prefill work
        m0 = eng.get_metrics()
        full = eng.generate(
            ModelRequest(rid="a", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
        m1 = eng.get_metrics()
        assert len(full.output_tokens) == 10
        assert m1["prefills_total"] == m0["prefills_total"]
        # consumed: the parked entry was an exact match, nothing exportable
        assert eng.list_exportable_sessions() == []
        # oracle: a fresh engine generating directly emits the same stream
        oracle = _engine()
        try:
            ro = oracle.generate(
                ModelRequest(rid="a", input_ids=prompt, gconfig=_GREEDY),
                timeout=120,
            )
        finally:
            oracle.destroy()
        assert full.output_tokens == ro.output_tokens
        assert full.output_logprobs == ro.output_logprobs
    finally:
        eng.destroy()


@pytest.mark.parametrize("kv_layout", ["paged", "workspace"])
@pytest.mark.parametrize("gname", ["greedy", "sampled"])
def test_export_import_stream_bit_identity(kv_layout, gname):
    g = _GREEDY if gname == "greedy" else _SAMPLED
    prompt = _prompt(44, seed=5)
    oracle = _engine(kv_layout=kv_layout)
    try:
        ro = oracle.generate(
            ModelRequest(rid="m", input_ids=prompt, gconfig=g), timeout=120
        )
    finally:
        oracle.destroy()

    pre = _engine(role="prefill", kv_layout=kv_layout)
    try:
        _prefill(pre, ModelRequest(rid="m", input_ids=prompt, gconfig=g))
        sess = pre.export_session("m")
        assert sess is not None
        m = pre.get_metrics()
        assert m["kv_migrated_out_sessions_total"] == 1
        assert m["kv_migrated_out_bytes_total"] > 0
        # exported sessions leave the exportable set (the move semantics)
        assert pre.list_exportable_sessions() == []
    finally:
        pre.destroy()
    assert sess["meta"]["covered"] == len(prompt) - 1
    assert sess["meta"]["tokens"] == prompt[:-1]

    # wire round-trip through the framed-bucket staging (multiple frames)
    frames = list(
        pack_kv_session(sess["meta"], sess["k"], sess["v"], chunk_mb=0.01)
    )
    assert len(frames) > 1
    st = WeightStaging()
    for f in frames:
        st.add_bucket(f)
    sessions = unpack_kv_sessions(st.finalize())
    assert len(sessions) == 1
    meta, k, v, scales = sessions[0]
    assert scales is None  # fp session: no scale blocks on the wire
    assert np.array_equal(np.asarray(k), sess["k"])
    assert np.array_equal(np.asarray(v), sess["v"])

    dec = _engine(role="decode", kv_layout=kv_layout)
    try:
        assert dec.import_session(meta, k, v) == "ok"
        m0 = dec.get_metrics()
        rd = dec.generate(
            ModelRequest(rid="m", input_ids=prompt, gconfig=g), timeout=120
        )
        m1 = dec.get_metrics()
        # zero transformer prefill: the resume is a host-tier promotion
        assert m1["prefills_total"] == m0["prefills_total"]
        assert m1["kv_host_hits_total"] - m0["kv_host_hits_total"] == 1
        assert (
            m1["reprefill_tokens_avoided_total"]
            - m0["reprefill_tokens_avoided_total"]
            == len(prompt) - 1
        )
        assert m1["kv_migrated_in_sessions_total"] == 1
        # the migrated stream is bit-identical to the never-migrated one
        assert rd.output_tokens == ro.output_tokens
        assert rd.output_logprobs == ro.output_logprobs
    finally:
        dec.destroy()


def test_import_version_mismatch_is_honest_miss():
    prompt = _prompt(36, seed=9)
    pre = _engine(role="prefill")
    try:
        _prefill(pre, ModelRequest(rid="v", input_ids=prompt,
                                   gconfig=_GREEDY))
        sess = pre.export_session("v")
    finally:
        pre.destroy()
    dec = _engine(role="decode")
    try:
        dec.set_version(7)  # a weight commit raced the migration
        assert dec.import_session(sess["meta"], sess["k"], sess["v"]) == (
            "stale_version"
        )
        m0 = dec.get_metrics()
        assert m0["kv_migrate_version_rejects_total"] == 1
        assert m0["kv_migrated_in_sessions_total"] == 0
        # the resume pays an honest re-prefill under the current weights
        # (same params here, so the stream itself still matches a fresh
        # generation) and the lookup counts a host-tier MISS
        rd = dec.generate(
            ModelRequest(rid="v", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
        m1 = dec.get_metrics()
        assert m1["prefills_total"] - m0["prefills_total"] == 1
        assert m1["kv_host_misses_total"] - m0["kv_host_misses_total"] == 1
        assert len(rd.output_tokens) == 10
    finally:
        dec.destroy()


def test_import_rejects_malformed_sessions():
    prompt = _prompt(30, seed=11)
    pre = _engine(role="prefill")
    try:
        _prefill(pre, ModelRequest(rid="x", input_ids=prompt,
                                   gconfig=_GREEDY))
        sess = pre.export_session("x")
    finally:
        pre.destroy()
    dec = _engine(role="decode")
    try:
        # wrong block geometry
        bad_k = np.zeros((1, 1, 2, 1, 2), np.float32)
        assert dec.import_session(sess["meta"], bad_k, bad_k) == "rejected"
        # coverage/token mismatch
        meta = dict(sess["meta"], covered=sess["meta"]["covered"] + 1)
        assert dec.import_session(meta, sess["k"], sess["v"]) == "rejected"
        assert dec.get_metrics()["kv_migrated_in_sessions_total"] == 0
        # unknown rid exports nothing
        assert dec.export_session("nope") is None
    finally:
        dec.destroy()


def test_export_from_host_tier_after_eviction():
    """A session that was already offloaded to the host tier (pool
    pressure) exports from there — drain covers host-resident sessions,
    not just parked ones."""
    prompt = _prompt(40, seed=13)
    eng = _engine(host_mb=16.0)
    try:
        _prefill(eng, ModelRequest(rid="h", input_ids=prompt,
                                   gconfig=_GREEDY))
        # force the parked slot into the host tier
        eng.pause_generation()
        with eng._sched_lock:
            assert eng._evict_parked_lru() is not None
        eng.continue_generation()
        assert eng.get_metrics()["kv_host_pool_entries"] == 1
        assert eng.list_exportable_sessions() == ["h"]
        sess = eng.export_session("h")
        assert sess is not None
        assert sess["meta"]["covered"] == len(prompt) - 1
        assert eng.list_exportable_sessions() == []
    finally:
        eng.destroy()
    dec = _engine(role="decode")
    try:
        assert dec.import_session(sess["meta"], sess["k"], sess["v"]) == "ok"
        m0 = dec.get_metrics()
        rd = dec.generate(
            ModelRequest(rid="h", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
        assert dec.get_metrics()["prefills_total"] == m0["prefills_total"]
        assert len(rd.output_tokens) == 10
    finally:
        dec.destroy()


# -- 3. server wire ----------------------------------------------------


async def _start_server(engine, dcfg):
    srv = DecodeServer(dcfg, engine=engine, shutdown_grace=0.2)
    addr = await srv.start(host="127.0.0.1", port=0)
    return srv, addr


def test_prefill_handoff_http_and_kv_commit_idempotency():
    """/prefill with a target streams the session to the decode server;
    the decode server's /generate resumes it with zero prefill; a
    replayed /kv_commit (same xid) dedups instead of double-importing."""
    prompt = _prompt(40, seed=17)
    oracle = _engine()
    try:
        ro = oracle.generate(
            ModelRequest(rid="hh", input_ids=prompt, gconfig=_GREEDY),
            timeout=120,
        )
    finally:
        oracle.destroy()
    pre = _engine(role="prefill")
    dec = _engine(role="decode")

    async def scenario():
        ps, pa = await _start_server(pre, pre.config)
        ds, da = await _start_server(dec, dec.config)
        try:
            out = await arequest_with_retry(
                pa, "/prefill",
                payload=dict(
                    rid="hh",
                    input_ids=prompt,
                    gconfig=dict(max_new_tokens=10, greedy=True),
                    target=da,
                    xid="handoff-1",
                ),
                max_retries=1, timeout=120,
            )
            assert out["stop_reason"] == "prefill"
            assert out["migrated"] is True and out["kv_bytes"] > 0
            # idempotent /prefill replay (lost response): cached result
            out2 = await arequest_with_retry(
                pa, "/prefill",
                payload=dict(
                    rid="hh",
                    input_ids=prompt,
                    gconfig=dict(max_new_tokens=10, greedy=True),
                    target=da,
                    xid="handoff-1",
                ),
                max_retries=1, timeout=120,
            )
            assert out2.get("dedup") == "completed"
            m0 = dec.get_metrics()
            gen = await arequest_with_retry(
                da, "/generate",
                payload=dict(
                    rid="hh",
                    input_ids=prompt,
                    gconfig=dict(max_new_tokens=10, greedy=True),
                ),
                max_retries=1, timeout=120,
            )
            m1 = dec.get_metrics()
            assert gen["output_tokens"] == ro.output_tokens
            assert gen["output_logprobs"] == ro.output_logprobs
            assert m1["prefills_total"] == m0["prefills_total"]
            # exactly one inbound commit landed on the decode server
            srv_m = await arequest_with_retry(
                da, "/metrics", method="GET", max_retries=1, timeout=30
            )
            assert srv_m["kv_migrate"]["in_commits"] == 1
            assert m1["kv_migrated_in_sessions_total"] == 1
        finally:
            await ps.stop()
            await ds.stop()
            await close_current_session()

    try:
        _run_async(scenario())
    finally:
        pre.destroy()
        dec.destroy()


def test_kv_recv_torn_frame_rejected_then_retry_lands_exactly_once():
    """A torn KV frame is a 4xx/5xx BEFORE anything stages; re-sending
    the full frame set (the sender's replay) plus a duplicate commit
    imports the session exactly once."""
    prompt = _prompt(38, seed=19)
    pre = _engine(role="prefill")
    try:
        _prefill(pre, ModelRequest(rid="t", input_ids=prompt,
                                   gconfig=_GREEDY))
        sess = pre.export_session("t")
    finally:
        pre.destroy()
    frames = list(
        pack_kv_session(sess["meta"], sess["k"], sess["v"], chunk_mb=0.01)
    )
    assert len(frames) >= 2
    dec = _engine(role="decode")

    async def scenario():
        ds, da = await _start_server(dec, dec.config)
        try:
            # frame 0 torn in flight: rejected, nothing staged
            with pytest.raises(Exception):
                await arequest_with_retry(
                    da, "/kv_recv?xid=mig1", data=frames[0][: len(frames[0]) // 2],
                    max_retries=1, timeout=30,
                )
            # premature commit: staging incomplete -> 400, staging KEPT
            with pytest.raises(Exception):
                await arequest_with_retry(
                    da, "/kv_commit", payload=dict(xid="mig1"),
                    max_retries=1, timeout=30,
                )
            # full replay (duplicates of any previously-staged bytes are
            # interval-merged) then commit
            for f in frames:
                await arequest_with_retry(
                    da, f"/kv_recv?xid=mig1", data=f, max_retries=1,
                    timeout=30,
                )
            out = await arequest_with_retry(
                da, "/kv_commit", payload=dict(xid="mig1"), max_retries=1,
                timeout=30,
            )
            assert out["imported"] == 1 and out["rids"] == ["t"]
            # replayed commit (lost response): dedup, no second import
            out2 = await arequest_with_retry(
                da, "/kv_commit", payload=dict(xid="mig1"), max_retries=1,
                timeout=30,
            )
            assert out2.get("dedup") is True
            assert dec.get_metrics()["kv_migrated_in_sessions_total"] == 1
        finally:
            await ds.stop()
            await close_current_session()

    try:
        _run_async(scenario())
    finally:
        dec.destroy()


def test_migrate_replay_budget_survives_two_composed_failures():
    """A sender abort and a torn frame are INDEPENDENT failures: when
    both compose on ONE migration (the abort on attempt 0, the tear on
    the replay), the two-replay budget still lands the handoff exactly
    once instead of abandoning the session to a re-prefill."""
    from areal_tpu.core import fault_injection
    from areal_tpu.core.fault_injection import FaultPlan, FaultPoint

    prompt = _prompt(36, seed=29)
    pre = _engine(role="prefill")
    pre.config.kv_migrate_chunk_mb = 0.01  # several frames per session
    dec = _engine(role="decode")

    async def scenario():
        ps, pa = await _start_server(pre, pre.config)
        ds, da = await _start_server(dec, dec.config)
        fault_injection.configure(FaultPlan(
            seed=7,
            points=[
                # attempt 0 dies before its first frame ...
                FaultPoint(site="kv.migrate.send", mode="abort",
                           at=(0,), times=1),
                # ... and attempt 1 (the replay) loses a frame to TWO
                # consecutive tears — enough to defeat the per-frame
                # HTTP retry, so only the outer replay budget saves it
                FaultPoint(site="kv.migrate.recv", mode="torn",
                           at=(1, 2), times=2),
            ],
        ))
        try:
            out = await arequest_with_retry(
                pa, "/prefill",
                payload=dict(
                    rid="rb",
                    input_ids=prompt,
                    gconfig=dict(max_new_tokens=8, greedy=True),
                    target=da,
                    xid="budget-1",
                ),
                max_retries=1, timeout=120,
            )
            # attempt 2 replays the full stream clean: the handoff landed
            assert out["migrated"] is True and out["kv_bytes"] > 0
            fired = fault_injection.snapshot()
            assert any(k.startswith("kv.migrate.send") for k in fired)
            assert any(k.startswith("kv.migrate.recv") for k in fired)
            srv_m = await arequest_with_retry(
                da, "/metrics", method="GET", max_retries=1, timeout=30
            )
            assert srv_m["kv_migrate"]["in_commits"] == 1
            assert dec.get_metrics()["kv_migrated_in_sessions_total"] == 1
        finally:
            fault_injection.deactivate()
            await ps.stop()
            await ds.stop()
            await close_current_session()

    try:
        _run_async(scenario())
    finally:
        pre.destroy()
        dec.destroy()


def test_drain_migrates_parked_sessions_zero_reprefill():
    """/drain parks in-flight generations and streams every session to
    the survivor; all resumes are host-tier promotions (zero prefills)
    and partial+resumed streams match the never-interrupted oracle."""
    prompts = [_prompt(40, seed=23 + i) for i in range(2)]
    # long enough (40 chunks at chunk=4) that the drain reliably lands
    # mid-stream even when a loaded host delays the /drain round-trip —
    # at 48 tokens the streams could finish first and the parts came
    # back "length", a pre-existing flake
    _BUDGET = 160
    g = GenerationHyperparameters(max_new_tokens=_BUDGET, greedy=True)
    oracle = _engine(seed=5)
    try:
        oracles = [
            oracle.generate(
                ModelRequest(rid=f"s{i}", input_ids=prompts[i], gconfig=g),
                timeout=120,
            ).output_tokens
            for i in range(2)
        ]
    finally:
        oracle.destroy()
    a = _engine(seed=5, host_mb=16.0)
    b = _engine(seed=5)

    async def scenario():
        sa, aa = await _start_server(a, a.config)
        sb, ba = await _start_server(b, b.config)
        try:
            loop = asyncio.get_running_loop()

            async def gen(addr, i, ids, budget):
                return await arequest_with_retry(
                    addr, "/generate",
                    payload=dict(
                        rid=f"s{i}",
                        input_ids=ids,
                        gconfig=dict(max_new_tokens=budget, greedy=True),
                    ),
                    max_retries=1, timeout=120,
                )

            tasks = []
            for i in range(2):
                tasks.append(
                    loop.create_task(gen(aa, i, prompts[i], _BUDGET))
                )
                await asyncio.sleep(0.05)  # admission order == oracle's
            # wait until both are mid-stream, then drain
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                m = a.get_metrics()
                if (
                    m["running_requests"] >= 2
                    and m["generated_tokens_total"] >= 2
                ):
                    break
                await asyncio.sleep(0.01)
            drain = await arequest_with_retry(
                aa, "/drain", payload=dict(targets=[ba]), max_retries=1,
                timeout=120,
            )
            parts = [await t for t in tasks]
            assert all(p["stop_reason"] == "interrupt" for p in parts)
            assert drain["drained"] == 2 and drain["failed"] == 0
            m0 = b.get_metrics()
            full = []
            for i, p in enumerate(parts):
                part_toks = [int(t) for t in p["output_tokens"]]
                out = await gen(
                    ba, i, prompts[i] + part_toks, _BUDGET - len(part_toks)
                )
                full.append(part_toks + [int(t) for t in out["output_tokens"]])
            m1 = b.get_metrics()
            assert m1["prefills_total"] == m0["prefills_total"]
            assert m1["kv_host_hits_total"] - m0["kv_host_hits_total"] == 2
            assert full == oracles
        finally:
            await sa.stop()
            await sb.stop()
            await close_current_session()

    try:
        _run_async(scenario(), timeout=240)
    finally:
        a.destroy()
        b.destroy()


# -- 4. router role-awareness ------------------------------------------


def _mk_router(servers, roles, pressure):
    r = DecodeRouter(servers=servers, config=RouterConfig())
    r.servers = list(servers)
    r._roles = dict(roles)
    r._pressure = {s: dict(p) for s, p in pressure.items()}
    r._versions = {s: 0 for s in servers}
    return r


def test_router_disagg_pick_decode_by_headroom_prefill_by_affinity():
    servers = ["p1:1", "p2:1", "d1:1", "d2:1"]
    roles = {"p1:1": "prefill", "p2:1": "prefill",
             "d1:1": "decode", "d2:1": "decode"}
    # d1 nearly full, d2 mostly free: decode must land on d2
    pressure = {
        "d1:1": dict(kv_blocks_total=100, kv_block_size=8,
                     kv_tokens_allocated=760, kv_host_pool_enabled=True),
        "d2:1": dict(kv_blocks_total=100, kv_block_size=8,
                     kv_tokens_allocated=80, kv_host_pool_enabled=True),
        "p1:1": dict(kv_blocks_total=100, kv_block_size=8,
                     kv_tokens_allocated=0, kv_host_pool_enabled=False),
        "p2:1": dict(kv_blocks_total=100, kv_block_size=8,
                     kv_tokens_allocated=0, kv_host_pool_enabled=False),
    }
    r = _mk_router(servers, roles, pressure)
    req = dict(qid="q1", prompt_len=128, new_token_budget=64,
               input_prefix=list(range(128)))
    out = r._try_schedule_locked(req)
    assert out is not None
    assert out["url"] == "d2:1"
    assert out["prefill_url"] in ("p1:1", "p2:1")
    first_prefill = out["prefill_url"]
    assert r._counters["disagg_schedules_total"] == 1
    # decode accounting was charged the DECODE share only (the prompt is
    # discounted on handed-off requests — its KV arrives over the wire)
    assert r._qid_cost["q1"] == pytest.approx(0.4 * 64)
    # same prefix again: prefill affinity sticks to the same replica
    out2 = r._try_schedule_locked(
        dict(qid="q2", prompt_len=128, new_token_budget=64,
             input_prefix=list(range(128)))
    )
    assert out2["prefill_url"] == first_prefill
    # a resume keeps its decode home and skips the handoff
    out3 = r._try_schedule_locked(
        dict(qid="q1", prompt_len=128, new_token_budget=64,
             input_prefix=list(range(128)))
    )
    assert out3["url"] == out["url"]
    assert "prefill_url" not in out3


def test_router_unified_fleet_unchanged_without_prefill_roles():
    servers = ["a:1", "b:1"]
    r = _mk_router(servers, {"a:1": "unified", "b:1": "unified"}, {})
    out = r._try_schedule_locked(
        dict(qid="q", prompt_len=32, new_token_budget=16)
    )
    assert out is not None and "prefill_url" not in out


def test_router_disagg_degrades_when_prefill_replicas_saturated():
    """Every prefill replica inadmissible -> decode URL only (the decode
    replica prefills itself); no handoff, no crash."""
    servers = ["p1:1", "d1:1"]
    roles = {"p1:1": "prefill", "d1:1": "decode"}
    pressure = {
        # prefill replica: zero headroom
        "p1:1": dict(kv_blocks_total=10, kv_block_size=8,
                     kv_tokens_allocated=80, kv_host_pool_enabled=False),
        "d1:1": dict(kv_blocks_total=100, kv_block_size=8,
                     kv_tokens_allocated=0, kv_host_pool_enabled=True),
    }
    r = _mk_router(servers, roles, pressure)
    out = r._try_schedule_locked(
        dict(qid="q", prompt_len=128, new_token_budget=64)
    )
    assert out is not None and out["url"] == "d1:1"
    assert "prefill_url" not in out
