"""Countdown task: reward semantics + offline dataset solvability
(ref: /root/reference/examples/countdown/reward_score.py scoring rules)."""

from areal_tpu.dataset import get_custom_dataset
from areal_tpu.reward.countdown import (
    FORMAT_SCORE,
    SCORE,
    _safe_eval,
    countdown_reward,
    extract_equation,
)


def _r(completion, target, numbers):
    return countdown_reward(None, completion, [], [], target=target,
                            numbers=numbers)


def test_scoring_rules():
    # correct equation, each number once
    assert _r("thinking... <answer>(3 + 4) * 2</answer>", 14, [2, 3, 4]) == SCORE
    # wrong value but well-formed -> format score
    assert _r("<answer>(3 + 4) + 2</answer>", 14, [2, 3, 4]) == FORMAT_SCORE
    # number reused -> format score (validation failure)
    assert _r("<answer>(3 * 3) + 2</answer>", 11, [2, 3, 4]) == FORMAT_SCORE
    # missing answer tag -> zero
    assert _r("the answer is (3+4)*2", 14, [2, 3, 4]) == 0.0
    # last tag wins
    assert (
        _r("<answer>1</answer> no wait <answer>(3 + 4) * 2</answer>",
           14, [2, 3, 4])
        == SCORE
    )


def test_safe_eval_rejects_non_arithmetic():
    assert _safe_eval("__import__('os')") is None
    assert _safe_eval("(lambda: 1)()") is None
    assert _safe_eval("2 ** 10") is None  # pow not in the countdown op set
    assert _safe_eval("1 / 0") is None
    assert _safe_eval("(3 + 4) * 2") == 14.0
    assert _safe_eval("-3 + 4") == 1.0


def test_extract_equation():
    assert extract_equation("x <answer> 1+1 </answer> y") == "1+1"
    assert extract_equation("no tags") is None


def test_offline_dataset_is_solvable_by_construction():
    items = get_custom_dataset(path="countdown", split="train", n_items=64)
    assert len(items) == 64
    for x in items:
        # the generator's own solution must score 1.0 under the reward
        got = countdown_reward(
            None,
            f"<answer>{x['solution']}</answer>",
            [],
            [],
            target=x["target"],
            numbers=x["numbers"],
        )
        assert got == SCORE, x
        assert str(x["target"]) in x["prompt"]
    # train/test splits differ
    test_items = get_custom_dataset(path="countdown", split="test", n_items=8)
    assert test_items[0]["prompt"] != items[0]["prompt"]


def test_dataset_deterministic():
    a = get_custom_dataset(path="countdown", split="train", n_items=8)
    b = get_custom_dataset(path="countdown", split="train", n_items=8)
    assert [x["prompt"] for x in a] == [x["prompt"] for x in b]


def test_reward_rejects_digit_concatenation_exploit():
    # '3_4' is a python int literal (34) whose digits still pass the
    # uses-each-number check — must score format-only, not 1.0
    assert _r("<answer>3_4 * 1</answer>", 34, [3, 4, 1]) == FORMAT_SCORE
