"""DistRolloutCoordinator: group-preserving FFD balance + host gather."""

import numpy as np
import pytest

from areal_tpu.core.dist_rollout import (
    DistRolloutCoordinator,
    merge_host_batches,
    redistribute,
)


def make_batch(lens, T=None):
    T = T or max(lens)
    B = len(lens)
    am = np.zeros((B, T), dtype=np.int32)
    ids = np.zeros((B, T), dtype=np.int32)
    for i, l in enumerate(lens):
        am[i, :l] = 1
        ids[i, :l] = np.arange(1, l + 1) + 100 * i
    return {"input_ids": ids, "attention_mask": am}


def test_redistribute_preserves_rows_and_groups():
    lens = [30, 29, 5, 6, 20, 21, 4, 3]  # 4 groups of 2
    batch = make_batch(lens)
    out, plan = redistribute(batch, group_size=2, dp_size=2)
    # Permutation: every original row appears exactly once.
    assert sorted(plan.row_order.tolist()) == list(range(8))
    # Groups stay adjacent: rows 2g, 2g+1 remain neighbours.
    pos = {int(r): i for i, r in enumerate(plan.row_order)}
    for g in range(4):
        assert abs(pos[2 * g] - pos[2 * g + 1]) == 1
    # Balance: the two shards' token totals are closer than the naive split.
    naive = [sum(lens[:4]), sum(lens[4:])]
    assert max(plan.shard_tokens) - min(plan.shard_tokens) <= max(naive) - min(naive)
    # Rows carried their content.
    for new_i, old_i in enumerate(plan.row_order):
        np.testing.assert_array_equal(
            out["input_ids"][new_i], batch["input_ids"][old_i]
        )


def test_redistribute_group_divisibility_error():
    batch = make_batch([4, 5, 6])
    with pytest.raises(AssertionError):
        redistribute(batch, group_size=2, dp_size=1)


def test_redistribute_scalar_and_1d_fields_pass_through():
    batch = make_batch([4, 5, 6, 7])
    batch["rewards"] = np.array([1.0, 2.0, 3.0, 4.0], dtype=np.float32)
    out, plan = redistribute(batch, group_size=1, dp_size=2)
    np.testing.assert_array_equal(out["rewards"], batch["rewards"][plan.row_order])


class _FakeRollout:
    def __init__(self, batch):
        self.batch = batch

    def prepare_batch(self, dataloader, **kw):
        return self.batch

    def rollout_batch(self, data, **kw):
        return self.batch


class _FakeTrain:
    def __init__(self, dp):
        self.dp = dp

    def data_parallel_world_size(self):
        return self.dp


def test_coordinator_simulated_two_hosts():
    # Two "hosts" each produce half the batch with different pad lengths;
    # the injected allgather merges them like process_allgather would.
    host0 = make_batch([10, 12, 3, 4], T=12)
    host1 = make_batch([25, 24, 7, 8], T=25)

    def fake_allgather(local):
        return merge_host_batches([host0, host1])

    coord = DistRolloutCoordinator(
        _FakeTrain(dp=2), _FakeRollout(host0), allgather_fn=fake_allgather
    )
    out, plan = coord.prepare_batch(None, granularity=2)
    assert out["input_ids"].shape[0] == 8
    # The two long groups (25,24) and (10,12) should land on different shards.
    assert max(plan.shard_tokens) - min(plan.shard_tokens) <= 22
    # All 8 rows present.
    assert sorted(plan.row_order.tolist()) == list(range(8))
