"""Pipeline parallelism: the GPipe shard_map trunk (parallel/pipeline.py +
models/qwen2.forward_pipelined) must be numerically equivalent to the
sequential scan-over-layers path.

Parity: the reference's native PP schedules (realhf/.../static_schedule.py:159,
pipe_runner.py:778) are validated there by train-parity tests; here the
equivalence oracle is the pp=1 engine on the same weights and batch.
"""

import numpy as np
import pytest

import jax

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    MicroBatchSpec,
    OptimizerConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.engine.sft.lm_engine import JaxLMEngine
from areal_tpu.models.qwen2 import ModelConfig
from areal_tpu.parallel import mesh as mesh_lib
from areal_tpu.utils.data import pad_sequences_to_tensors

TINY4 = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,  # 2 layers per stage at pp=2
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)


def _engine(strategy, lr=1e-2):
    cfg = TrainEngineConfig(
        experiment_name="pp",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        # small budget => several micro-batches => a real pipeline stream
        mb_spec=MicroBatchSpec(max_tokens_per_mb=64),
        optimizer=OptimizerConfig(
            lr=lr,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=False,
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = TINY4
    eng.create_process_group(strategy)
    eng.initialize(None, FinetuneSpec(1, 64, 8))
    return eng


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    seqs = []
    for L in (9, 30, 7, 25, 11, 13, 8, 21):
        ids = rng.randint(1, 64, (L,))
        mask = np.zeros(L, dtype=np.int32)
        mask[L // 2 :] = 1
        seqs.append(dict(input_ids=ids, loss_mask=mask))
    return pad_sequences_to_tensors(seqs)


@pytest.mark.slow
def test_pp2_train_matches_sequential(cpu_devices):
    """Same init, same batch: pp=2 pipelined train step must produce the
    same losses and keep producing the same losses across steps (i.e. the
    gradients/optimizer updates match too)."""
    eng_pp = _engine(
        ParallelStrategy(
            pipeline_parallel_size=2,
            data_parallel_size=2,
            tensor_parallel_size=2,
        )
    )
    eng_seq = _engine(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    # layer stack is sharded over pp in the pipelined engine
    spec = eng_pp._param_shardings["layers"]["attn"]["q_kernel"].spec
    assert spec[0] == mesh_lib.AXIS_PP, spec

    losses_pp, losses_seq = [], []
    for step in range(3):
        batch = _batch(step)
        s_pp = eng_pp.train_lm(batch)
        s_seq = eng_seq.train_lm(batch)
        losses_pp.append(s_pp["loss"])
        losses_seq.append(s_seq["loss"])
    np.testing.assert_allclose(losses_pp, losses_seq, rtol=2e-4, atol=1e-5)
    # losses must actually change across steps (optimizer applied)
    assert abs(losses_pp[0] - losses_pp[-1]) > 1e-4
    eng_pp.destroy()
    eng_seq.destroy()


@pytest.mark.slow
def test_pp2_forward_matches_sequential(cpu_devices):
    """No-grad forward (the compute_logp path) through the pipeline equals
    the sequential forward."""
    eng_pp = _engine(
        ParallelStrategy(
            pipeline_parallel_size=2,
            data_parallel_size=2,
            tensor_parallel_size=2,
        )
    )
    eng_seq = _engine(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    batch = _batch(42)

    def logp_hook(logits, mb):
        logprobs = jax.nn.log_softmax(logits, axis=-1)
        ids = mb["input_ids"]
        shifted = jax.numpy.roll(ids, -1)
        return jax.numpy.take_along_axis(
            logprobs, shifted[:, None], axis=-1
        )[:, 0]

    out_pp = eng_pp.forward(batch, post_hook=logp_hook)
    out_seq = eng_seq.forward(batch, post_hook=logp_hook)
    np.testing.assert_allclose(out_pp, out_seq, rtol=2e-4, atol=1e-5)
    eng_pp.destroy()
    eng_seq.destroy()
