import numpy as np
import pytest

from areal_tpu.utils.stats_tracker import DistributedStatsTracker, ReduceType


def test_denominator_conditioned_mean():
    t = DistributedStatsTracker()
    mask = np.array([True, True, False, True])
    vals = np.array([1.0, 2.0, 100.0, 3.0], dtype=np.float32)
    t.denominator(n_tokens=mask)
    t.stat("n_tokens", loss=vals)
    out = t.export()
    assert out["loss/avg"] == pytest.approx(2.0)
    assert out["loss/min"] == pytest.approx(1.0)
    assert out["loss/max"] == pytest.approx(3.0)
    assert out["n_tokens"] == 3.0


def test_scopes():
    t = DistributedStatsTracker()
    with t.scope("ppo"):
        with t.scope("actor"):
            t.scalar(lr=0.1)
    out = t.export()
    assert out == {"ppo/actor/lr": pytest.approx(0.1)}


def test_record_timing():
    t = DistributedStatsTracker()
    with t.record_timing("rollout"):
        pass
    out = t.export()
    assert "timeperf/rollout" in out
    assert out["timeperf/rollout"] >= 0


def test_sum_reduce():
    t = DistributedStatsTracker()
    mask = np.array([True, True])
    t.denominator(m=mask)
    t.stat("m", reward=np.array([1.0, 5.0], dtype=np.float32),
           reduce_type=ReduceType.SUM)
    out = t.export()
    assert out["reward"] == pytest.approx(6.0)


def test_export_resets():
    t = DistributedStatsTracker()
    t.scalar(x=1.0)
    assert t.export() != {}
    assert t.export() == {}


def test_denominator_must_exist():
    t = DistributedStatsTracker()
    with pytest.raises(ValueError):
        t.stat("missing", v=np.array([1.0], dtype=np.float32))


def test_shape_mismatch_rejected():
    t = DistributedStatsTracker()
    t.denominator(m=np.array([True, False]))
    with pytest.raises(ValueError):
        t.stat("m", v=np.array([1.0], dtype=np.float32))


def test_bad_denominator_dtype():
    t = DistributedStatsTracker()
    with pytest.raises(ValueError):
        t.denominator(m=np.array([1.0, 2.0]))


def test_denominator_alignment_across_steps():
    # Regression: stat appended more often than denominator must pair each
    # numerator with the mask current at stat() time, not cycle old masks.
    t = DistributedStatsTracker()
    t.denominator(m=np.array([True, True]))
    t.stat("m", x=np.array([1.0, 1.0], dtype=np.float32))
    t.denominator(m=np.array([True, False]))
    t.stat("m", x=np.array([2.0, 2.0], dtype=np.float32))
    t.stat("m", x=np.array([3.0, 3.0], dtype=np.float32))
    out = t.export()
    # masked values: [1,1] (2 elts) + [2] + [3] -> mean = 7/4 = 1.75
    import pytest as _pytest
    assert out["x/avg"] == _pytest.approx(1.75)
