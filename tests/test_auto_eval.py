"""AutomaticEvaluator: checkpoint discovery, ordered publish, recovery.

Reference behavior: realhf/scheduler/evaluator.py — one eval job per saved
checkpoint, bounded concurrency, submit and publish in global-step order,
pre-existing outputs treated as already logged after restart.
"""

import json
import os
import sys

from areal_tpu.evaluation.auto import AutomaticEvaluator, EvalStatus


def _fake_eval_cmd(fail_for=None):
    # writes {"score": <globalstep from ckpt name>} as the result
    code = (
        "import json,os,sys\n"
        "ckpt, out = sys.argv[1], sys.argv[2]\n"
        "g = ckpt.rsplit('globalstep',1)[1]\n"
        f"fail = {fail_for!r}\n"
        "if fail is not None and g == str(fail): sys.exit(3)\n"
        "os.makedirs(out, exist_ok=True)\n"
        "json.dump({'score': int(g)}, open(os.path.join(out,'result.json'),'w'))\n"
    )
    return [sys.executable, "-c", code, "{ckpt}", "{out}"]


def _make_ckpt(root, epoch, step, g):
    d = os.path.join(root, f"epoch{epoch}epochstep{step}globalstep{g}")
    os.makedirs(d, exist_ok=True)
    return d


def test_discovers_evaluates_and_publishes_in_order(tmp_path):
    ckpt_root = str(tmp_path / "ckpts")
    out_root = str(tmp_path / "out")
    published = []
    ev = AutomaticEvaluator(
        ckpt_root,
        out_root,
        eval_cmd=_fake_eval_cmd(),
        publish=lambda g, r: published.append((g, r["score"])),
        max_concurrent_jobs=2,
    )
    # checkpoints appear out of order
    _make_ckpt(ckpt_root, 0, 5, 10)
    _make_ckpt(ckpt_root, 0, 2, 4)
    ev.drain(timeout=30)
    _make_ckpt(ckpt_root, 1, 1, 12)
    ev.drain(timeout=30)
    assert published == [(4, 4), (10, 10), (12, 12)]
    assert ev.statuses == {4: "logged", 10: "logged", 12: "logged"}
    assert json.load(open(os.path.join(out_root, "globalstep4", "result.json")))


def test_failed_job_does_not_block_later_steps(tmp_path):
    ckpt_root = str(tmp_path / "ckpts")
    out_root = str(tmp_path / "out")
    published = []
    ev = AutomaticEvaluator(
        ckpt_root,
        out_root,
        eval_cmd=_fake_eval_cmd(fail_for=4),
        publish=lambda g, r: published.append(g),
    )
    _make_ckpt(ckpt_root, 0, 2, 4)
    _make_ckpt(ckpt_root, 0, 5, 10)
    ev.drain(timeout=30)
    assert published == [10]
    assert ev.statuses[4] == "failed" and ev.statuses[10] == "logged"


def test_restart_treats_existing_output_as_logged(tmp_path):
    ckpt_root = str(tmp_path / "ckpts")
    out_root = str(tmp_path / "out")
    os.makedirs(os.path.join(out_root, "globalstep4"))
    _make_ckpt(ckpt_root, 0, 2, 4)
    published = []
    ev = AutomaticEvaluator(
        ckpt_root,
        out_root,
        eval_cmd=_fake_eval_cmd(),
        publish=lambda g, r: published.append(g),
    )
    ev.drain(timeout=30)
    assert published == []  # not re-evaluated after restart
    assert ev.statuses == {4: "logged"}


def test_concurrency_bound(tmp_path):
    ckpt_root = str(tmp_path / "ckpts")
    out_root = str(tmp_path / "out")
    slow = [
        sys.executable,
        "-c",
        (
            "import json,os,sys,time\n"
            "time.sleep(0.3)\n"
            "os.makedirs(sys.argv[2], exist_ok=True)\n"
            "json.dump({'score': 1}, open(os.path.join(sys.argv[2],'result.json'),'w'))\n"
        ),
        "{ckpt}",
        "{out}",
    ]
    ev = AutomaticEvaluator(
        ckpt_root, out_root, eval_cmd=slow, max_concurrent_jobs=1,
        publish=lambda g, r: None,
    )
    for g in (1, 2, 3):
        _make_ckpt(ckpt_root, 0, g, g)
    ev.step()
    running = [s for s in ev.statuses.values() if s == "running"]
    assert len(running) == 1
    ev.drain(timeout=30)
    assert all(s == "logged" for s in ev.statuses.values())
