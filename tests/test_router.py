"""Decode-fleet router (parity: realhf/tests/system/test_gserver_manager.py —
routing policies, qid affinity, staleness gate, rollout accounting)."""

import asyncio
import threading

import pytest
from aiohttp import web

from areal_tpu.launcher.router import DecodeRouter
from areal_tpu.utils import name_resolve, names
from areal_tpu.utils.http import arequest_with_retry, close_current_session


class FakeServer:
    """Minimal decode-server stand-in: /health with a version, plus an
    optional /metrics active-token gauge (None = no metrics endpoint)."""

    def __init__(self, version=0, active_tokens=None):
        self.version = version
        self.active_tokens = active_tokens
        self._runner = None
        self.addr = None

    async def _health(self, request):
        return web.json_response({"status": "ok", "version": self.version})

    async def _metrics(self, request):
        if self.active_tokens is None:
            raise web.HTTPNotFound()
        return web.json_response({"active_tokens": self.active_tokens})

    async def start(self):
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = self._runner.addresses[0][1]
        self.addr = f"127.0.0.1:{port}"
        return self.addr

    async def stop(self):
        if self._runner:
            await self._runner.cleanup()


def _run_async(coro, timeout=60):
    """Run a coroutine on a dedicated loop thread."""
    result = {}

    def go():
        result["v"] = asyncio.run(coro)

    t = threading.Thread(target=go, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "async scenario timed out"
    return result.get("v")


async def _scenario_routing():
    s1, s2 = FakeServer(version=3), FakeServer(version=3)
    a1, a2 = await s1.start(), await s2.start()
    router = DecodeRouter(
        servers=[a1, a2],
        schedule_policy="least_requests",
        max_concurrent_rollouts=2,
        max_head_offpolicyness=1000,
        train_batch_size=4,
        health_poll_interval=0.2,
    )
    addr = await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.5)  # let the poll loop see both servers

        # least-requests spreads; qid affinity sticks
        r1 = await arequest_with_retry(
            addr, "/schedule_request",
            payload=dict(qid="q1", prompt_len=10, group_size=4,
                         new_token_budget=16),
        )
        r2 = await arequest_with_retry(
            addr, "/schedule_request",
            payload=dict(qid="q2", prompt_len=10, group_size=4,
                         new_token_budget=16),
        )
        assert {r1["url"], r2["url"]} == {a1, a2}, "load not spread"
        assert r1["version"] == 3
        r1b = await arequest_with_retry(
            addr, "/schedule_request",
            payload=dict(qid="q1", prompt_len=10, group_size=4,
                         new_token_budget=16),
        )
        assert r1b["url"] == r1["url"], "qid affinity broken"

        # rollout accounting: capacity gate at 2 concurrent
        ok1 = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="q1")
        )
        ok2 = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="q2")
        )
        full = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="q3")
        )
        assert ok1["success"] and ok2["success"]
        assert not full["success"] and "capacity" in full["reason"]
        await arequest_with_retry(
            addr, "/finish_rollout", payload=dict(qid="q1", accepted=True)
        )
        again = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="q3")
        )
        assert again["success"]

        health = await arequest_with_retry(addr, "/health", method="GET")
        assert set(health["servers"]) == {a1, a2}
        return True
    finally:
        await close_current_session()
        await router.stop()
        await s1.stop()
        await s2.stop()


def test_router_routing_affinity_capacity():
    assert _run_async(_scenario_routing())


async def _scenario_staleness(tmp_root):
    name_resolve.reconfigure(
        name_resolve.NameResolveConfig(type="memory")
    )
    s1 = FakeServer(version=0)
    a1 = await s1.start()
    router = DecodeRouter(
        experiment_name="rexp",
        trial_name="rt",
        servers=[a1],
        max_head_offpolicyness=1,
        train_batch_size=4,
        health_poll_interval=0.2,
    )
    addr = await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.4)
        # no samples consumed yet: not staled
        out = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="a")
        )
        assert out["success"]
        await arequest_with_retry(
            addr, "/finish_rollout", payload=dict(qid="a", accepted=True)
        )
        # trainer consumed 12 samples at batch 4 -> expected version 3 >
        # fleet version 0 + offpolicyness 1 -> gate closes
        name_resolve.add(
            names.training_samples("rexp", "rt"), "12", replace=True
        )
        out = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="b")
        )
        assert not out["success"] and "staled" in out["reason"]
        # weight push bumps the fleet version -> gate reopens
        s1.version = 3
        await asyncio.sleep(0.6)
        out = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="c")
        )
        assert out["success"]
        return True
    finally:
        await close_current_session()
        await router.stop()
        await s1.stop()


def test_router_staleness_gate(tmp_path):
    assert _run_async(_scenario_staleness(tmp_path))


async def _scenario_token_load_rebalance():
    """least_token_usage follows the servers' MEASURED /metrics load, not
    just the router's own estimates (parity: least-token scheduling in
    realhf/system/gserver_manager.py:339): a synthetic skew pushes all new
    work to the lighter server, and flipping the skew rebalances."""
    s1 = FakeServer(version=1, active_tokens=50_000)
    s2 = FakeServer(version=1, active_tokens=100)
    a1, a2 = await s1.start(), await s2.start()
    router = DecodeRouter(
        servers=[a1, a2],
        schedule_policy="least_token_usage",
        health_poll_interval=0.2,
    )
    addr = await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.5)  # poll loop sees both /metrics
        picks = []
        for i in range(4):
            r = await arequest_with_retry(
                addr, "/schedule_request",
                payload=dict(qid=f"skew-{i}", prompt_len=64, group_size=1,
                             new_token_budget=64),
            )
            picks.append(r["url"])
        assert picks == [a2] * 4, f"skewed load not avoided: {picks}"

        # flip the skew; after the next poll new requests go the other way
        s1.active_tokens, s2.active_tokens = 100, 50_000
        await asyncio.sleep(0.6)
        r = await arequest_with_retry(
            addr, "/schedule_request",
            payload=dict(qid="flip", prompt_len=64, group_size=1,
                         new_token_budget=64),
        )
        assert r["url"] == a1, "router did not rebalance on measured load"

        health = await arequest_with_retry(addr, "/health", method="GET")
        assert set(health["token_loads"]) == {a1, a2}
        assert health["token_loads"][a2] > health["token_loads"][a1]
        return True
    finally:
        await close_current_session()
        await router.stop()
        await s1.stop()
        await s2.stop()


def test_router_token_load_rebalance():
    assert _run_async(_scenario_token_load_rebalance())
