"""Decode-fleet router (parity: realhf/tests/system/test_gserver_manager.py —
routing policies, qid/prefix affinity, pressure admission + bounded queue,
failover requeue, staleness gate, rollout accounting, state expiry)."""

import asyncio
import threading
import time

import pytest
from aiohttp import web

from areal_tpu.api.cli_args import RouterConfig
from areal_tpu.launcher.router import _METRICS_FAIL_LIMIT, DecodeRouter
from areal_tpu.utils import name_resolve, names
from areal_tpu.utils.http import (
    HttpRequestError,
    arequest_with_retry,
    close_current_session,
)


class FakeServer:
    """Minimal decode-server stand-in: /health with a version, plus an
    optional /metrics active-token gauge (None = no metrics endpoint).
    `metrics_extra` merges additional gauges (kv-pool pressure etc.)."""

    def __init__(self, version=0, active_tokens=None, metrics_extra=None):
        self.version = version
        self.active_tokens = active_tokens
        self.metrics_extra = metrics_extra or {}
        self._runner = None
        self.addr = None

    async def _health(self, request):
        return web.json_response({"status": "ok", "version": self.version})

    async def _metrics(self, request):
        if self.active_tokens is None:
            raise web.HTTPNotFound()
        return web.json_response(
            {"active_tokens": self.active_tokens, **self.metrics_extra}
        )

    async def start(self):
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = self._runner.addresses[0][1]
        self.addr = f"127.0.0.1:{port}"
        return self.addr

    async def stop(self):
        if self._runner:
            await self._runner.cleanup()


def _run_async(coro, timeout=60):
    """Run a coroutine on a dedicated loop thread."""
    result = {}

    def go():
        result["v"] = asyncio.run(coro)

    t = threading.Thread(target=go, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "async scenario timed out"
    return result.get("v")


async def _scenario_routing():
    s1, s2 = FakeServer(version=3), FakeServer(version=3)
    a1, a2 = await s1.start(), await s2.start()
    router = DecodeRouter(
        servers=[a1, a2],
        schedule_policy="least_requests",
        max_concurrent_rollouts=2,
        max_head_offpolicyness=1000,
        train_batch_size=4,
        health_poll_interval=0.2,
    )
    addr = await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.5)  # let the poll loop see both servers

        # least-requests spreads; qid affinity sticks
        r1 = await arequest_with_retry(
            addr, "/schedule_request",
            payload=dict(qid="q1", prompt_len=10, group_size=4,
                         new_token_budget=16),
        )
        r2 = await arequest_with_retry(
            addr, "/schedule_request",
            payload=dict(qid="q2", prompt_len=10, group_size=4,
                         new_token_budget=16),
        )
        assert {r1["url"], r2["url"]} == {a1, a2}, "load not spread"
        assert r1["version"] == 3
        r1b = await arequest_with_retry(
            addr, "/schedule_request",
            payload=dict(qid="q1", prompt_len=10, group_size=4,
                         new_token_budget=16),
        )
        assert r1b["url"] == r1["url"], "qid affinity broken"

        # rollout accounting: capacity gate at 2 concurrent
        ok1 = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="q1")
        )
        ok2 = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="q2")
        )
        full = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="q3")
        )
        assert ok1["success"] and ok2["success"]
        assert not full["success"] and "capacity" in full["reason"]
        await arequest_with_retry(
            addr, "/finish_rollout", payload=dict(qid="q1", accepted=True)
        )
        again = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="q3")
        )
        assert again["success"]

        health = await arequest_with_retry(addr, "/health", method="GET")
        assert set(health["servers"]) == {a1, a2}
        return True
    finally:
        await close_current_session()
        await router.stop()
        await s1.stop()
        await s2.stop()


def test_router_routing_affinity_capacity():
    assert _run_async(_scenario_routing())


async def _scenario_staleness(tmp_root):
    name_resolve.reconfigure(
        name_resolve.NameResolveConfig(type="memory")
    )
    s1 = FakeServer(version=0)
    a1 = await s1.start()
    router = DecodeRouter(
        experiment_name="rexp",
        trial_name="rt",
        servers=[a1],
        max_head_offpolicyness=1,
        train_batch_size=4,
        health_poll_interval=0.2,
    )
    addr = await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.4)
        # no samples consumed yet: not staled
        out = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="a")
        )
        assert out["success"]
        await arequest_with_retry(
            addr, "/finish_rollout", payload=dict(qid="a", accepted=True)
        )
        # trainer consumed 12 samples at batch 4 -> expected version 3 >
        # fleet version 0 + offpolicyness 1 -> gate closes
        name_resolve.add(
            names.training_samples("rexp", "rt"), "12", replace=True
        )
        out = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="b")
        )
        assert not out["success"] and "staled" in out["reason"]
        # weight push bumps the fleet version -> gate reopens
        s1.version = 3
        await asyncio.sleep(0.6)
        out = await arequest_with_retry(
            addr, "/allocate_rollout", payload=dict(qid="c")
        )
        assert out["success"]
        return True
    finally:
        await close_current_session()
        await router.stop()
        await s1.stop()


def test_router_staleness_gate(tmp_path):
    assert _run_async(_scenario_staleness(tmp_path))


async def _scenario_token_load_rebalance():
    """least_token_usage follows the servers' MEASURED /metrics load, not
    just the router's own estimates (parity: least-token scheduling in
    realhf/system/gserver_manager.py:339): a synthetic skew pushes all new
    work to the lighter server, and flipping the skew rebalances."""
    s1 = FakeServer(version=1, active_tokens=50_000)
    s2 = FakeServer(version=1, active_tokens=100)
    a1, a2 = await s1.start(), await s2.start()
    router = DecodeRouter(
        servers=[a1, a2],
        schedule_policy="least_token_usage",
        health_poll_interval=0.2,
    )
    addr = await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.5)  # poll loop sees both /metrics
        picks = []
        for i in range(4):
            r = await arequest_with_retry(
                addr, "/schedule_request",
                payload=dict(qid=f"skew-{i}", prompt_len=64, group_size=1,
                             new_token_budget=64),
            )
            picks.append(r["url"])
        assert picks == [a2] * 4, f"skewed load not avoided: {picks}"

        # flip the skew; after the next poll new requests go the other way
        s1.active_tokens, s2.active_tokens = 100, 50_000
        await asyncio.sleep(0.6)
        r = await arequest_with_retry(
            addr, "/schedule_request",
            payload=dict(qid="flip", prompt_len=64, group_size=1,
                         new_token_budget=64),
        )
        assert r["url"] == a1, "router did not rebalance on measured load"

        health = await arequest_with_retry(addr, "/health", method="GET")
        assert set(health["token_loads"]) == {a1, a2}
        assert health["token_loads"][a2] > health["token_loads"][a1]
        return True
    finally:
        await close_current_session()
        await router.stop()
        await s1.stop()
        await s2.stop()


def test_router_token_load_rebalance():
    assert _run_async(_scenario_token_load_rebalance())


# -- satellite: unit coverage for previously untested router internals ------


def test_release_qid_multi_pending_accounting():
    """ISSUE 8 satellite: one qid carrying several in-flight requests (a
    GRPO group) must release accounting one unit per finish, and fully
    clear its maps on the last release."""
    r = DecodeRouter(servers=["a:1"])
    r._request_counts["a:1"] = 2
    r._token_usage["a:1"] = 10.0
    r._est_since_poll["a:1"] = 10.0
    r._qid_to_server["q"] = "a:1"
    r._qid_cost["q"] = 10.0
    r._qid_pending["q"] = 2
    r._qid_touched["q"] = time.monotonic()

    r._release_qid("q")
    assert r._qid_pending["q"] == 1
    assert r._qid_cost["q"] == pytest.approx(5.0)
    assert r._request_counts["a:1"] == 1
    assert r._token_usage["a:1"] == pytest.approx(5.0)
    assert r._est_since_poll["a:1"] == pytest.approx(5.0)

    r._release_qid("q")
    assert "q" not in r._qid_to_server
    assert "q" not in r._qid_cost
    assert "q" not in r._qid_pending
    assert "q" not in r._qid_touched
    assert r._request_counts["a:1"] == 0
    assert r._token_usage["a:1"] == pytest.approx(0.0)

    # releasing an unknown qid is a no-op, not a crash
    r._release_qid("nope")
    r._release_qid(None)


def test_metrics_stale_fallback_after_fail_limit():
    """ISSUE 8 satellite: after _METRICS_FAIL_LIMIT consecutive failed
    /metrics polls the measured token load is dropped and _token_load
    degrades to the router's own estimate."""
    r = DecodeRouter(servers=["a:1"])
    r._token_usage["a:1"] = 77.0  # router's own estimate
    # healthy probe with a measurement
    r._apply_probes_locked(["a:1"], [("a:1", 1, 1000.0, 0.0, None)])
    assert r._token_load("a:1") == pytest.approx(1000.0)
    # metrics fail (health ok) — the stale measurement survives until the
    # fail limit, then is dropped
    for i in range(_METRICS_FAIL_LIMIT):
        assert ("a:1" in r._measured_tokens) == (i < _METRICS_FAIL_LIMIT)
        r._apply_probes_locked(["a:1"], [("a:1", 1, None, 0.0, None)])
    assert "a:1" not in r._measured_tokens
    assert r._token_load("a:1") == pytest.approx(77.0)
    # a successful poll re-establishes the measured base
    r._apply_probes_locked(["a:1"], [("a:1", 1, 5.0, 0.0, None)])
    assert r._token_load("a:1") == pytest.approx(5.0)


def test_est_since_poll_snapshot_subtraction():
    """Requests routed AFTER the probe snapshot must keep their estimated
    cost through the subtraction (the probe could not have seen them)."""
    r = DecodeRouter(servers=["a:1"])
    r._est_since_poll["a:1"] = 100.0
    # probe snapshotted 60.0 (40.0 was routed after the snapshot)
    r._apply_probes_locked(["a:1"], [("a:1", 1, 500.0, 60.0, None)])
    assert r._est_since_poll["a:1"] == pytest.approx(40.0)
    assert r._token_load("a:1") == pytest.approx(540.0)


def test_staleness_gate_arithmetic(monkeypatch):
    """ISSUE 8 satellite: expected_version = (consumed + running) //
    train_batch_size must exceed fleet_version + offpolicyness to close
    the gate — boundary-exact."""
    r = DecodeRouter(max_head_offpolicyness=1, train_batch_size=4)
    r._versions = {"s": 0}
    monkeypatch.setattr(r, "_training_sample_cnt", lambda: 12)
    # (12 + 0) // 4 = 3 > 1 + 0 -> staled
    assert r._is_staled()
    # version catches up: 3 > 1 + 2 is False
    r._versions = {"s": 2}
    assert not r._is_staled()
    # running rollouts count toward expected version
    r._versions = {"s": 2}
    r._running = 4  # (12 + 4) // 4 = 4 > 3
    assert r._is_staled()
    # fleet version = min across servers (conservative mid-push)
    r._running = 0
    r._versions = {"s": 9, "t": 2}
    assert not r._is_staled()
    r._versions = {"s": 9, "t": 0}
    assert r._is_staled()


def test_kv_headroom_and_admission():
    """Pressure admission: kv capacity (minus fragmentation, scaled by
    kv_pressure_high) must cover allocated + routed-since-poll + the new
    request; host-tier replicas admit to the full pool."""
    r = DecodeRouter(servers=["a:1"], config=RouterConfig(kv_pressure_high=0.9))
    # no pressure report -> unknown -> admissible
    assert r._admissible("a:1", 1000.0)
    r._pressure["a:1"] = dict(
        kv_blocks_total=10, kv_block_size=16, kv_pool_fragmentation=1,
        kv_tokens_allocated=100, kv_host_pool_enabled=False,
    )
    # cap = 160*0.9 = 144; frag 16; used 100 -> headroom 28 before need
    assert r._admissible("a:1", 28.0)
    assert not r._admissible("a:1", 29.0)
    # routed-but-unmeasured estimates count as used
    r._est_since_poll["a:1"] = 20.0
    assert not r._admissible("a:1", 10.0)
    r._est_since_poll["a:1"] = 0.0
    # host tier enabled: admit to the full pool (overflow offloads)
    r._pressure["a:1"]["kv_host_pool_enabled"] = True
    assert r._admissible("a:1", 44.0)
    assert not r._admissible("a:1", 45.0)


def test_expire_locked_ttl_and_server_pruning():
    """ISSUE 8 satellite: qid/prefix maps expire by TTL (releasing load
    accounting) and per-server counters for servers gone from discovery
    AND the seed list are pruned."""
    r = DecodeRouter(servers=["a:1"], config=RouterConfig(route_ttl_s=10.0))
    now = time.monotonic()
    r._qid_to_server["old"] = "a:1"
    r._qid_cost["old"] = 4.0
    r._qid_pending["old"] = 1
    r._qid_touched["old"] = now - 100.0
    r._qid_to_server["fresh"] = "a:1"
    r._qid_cost["fresh"] = 4.0
    r._qid_pending["fresh"] = 1
    r._qid_touched["fresh"] = now
    r._request_counts["a:1"] = 2
    r._token_usage["a:1"] = 8.0
    r._prefix_map[123] = ("a:1", now - 100.0)
    r._prefix_map[456] = ("a:1", now)
    # counters for a server no longer discovered anywhere
    r._request_counts["gone:1"] = 5
    r._measured_tokens["gone:1"] = 1.0
    r._metrics_fail["gone:1"] = 1

    r._expire_locked(now, ["a:1"])
    assert "old" not in r._qid_to_server and "fresh" in r._qid_to_server
    assert r._counters["expired_qids_total"] == 1
    assert r._request_counts["a:1"] == 1  # old's unit released
    assert r._token_usage["a:1"] == pytest.approx(4.0)
    assert 123 not in r._prefix_map and 456 in r._prefix_map
    assert "gone:1" not in r._request_counts
    assert "gone:1" not in r._measured_tokens
    assert "gone:1" not in r._metrics_fail
    # LRU bound on the prefix map
    r2 = DecodeRouter(servers=["a:1"], config=RouterConfig(route_max_entries=2))
    for h in range(5):
        r2._prefix_map[h] = ("a:1", time.monotonic())
    r2._expire_locked(time.monotonic(), ["a:1"])
    assert len(r2._prefix_map) == 2
    assert list(r2._prefix_map) == [3, 4]  # oldest evicted first


def _probe(s, v=1, load=5.0, est=0.0, pressure=None, rtt=0.01):
    return (s, v, load, est, pressure, rtt)


def test_breaker_trips_on_slow_polls_and_reenters_via_probe():
    """ISSUE 9: a SLOW replica (answers, late) must leave rotation after
    `breaker_trip_after` bad polls, re-enter half-open on recovery with
    only `breaker_probe_requests` probes admitted, and return to full
    traffic when a probe request COMPLETES — not merely when a ping
    succeeds."""
    r = DecodeRouter(
        servers=["a:1", "b:1"],
        breaker_trip_after=2,
        breaker_slow_s=0.1,
        breaker_probe_requests=1,
        dead_after_failures=100,  # isolate the breaker from failover
    )
    servers = ["a:1", "b:1"]
    r._apply_probes_locked(servers, [_probe("a:1"), _probe("b:1")])
    assert r._breaker_admits("a:1") and r._breaker_admits("b:1")

    # two slow polls (rtt > breaker_slow_s) trip a; b stays closed
    for _ in range(2):
        r._apply_probes_locked(
            servers, [_probe("a:1", rtt=0.5), _probe("b:1")]
        )
    assert r._breaker["a:1"]["state"] == "open"
    assert not r._breaker_admits("a:1")
    assert r._counters["breaker_trips_total"] == 1
    # a is alive (health fine): never counted toward failover
    assert r._health_fail["a:1"] == 0

    # open diverts even qid-affine traffic — but the mapping SURVIVES
    r._qid_to_server["aff"] = "a:1"
    r._qid_cost["aff"] = 4.0
    r._qid_pending["aff"] = 1
    r._qid_touched["aff"] = time.monotonic()
    out = r._try_schedule_locked(
        dict(qid="aff", prompt_len=10, group_size=1, new_token_budget=8)
    )
    assert out["url"] == "b:1"
    assert r._qid_to_server["aff"] == "b:1"  # re-pinned while tripped

    # recovery: one healthy poll -> HALF-OPEN, probe budget 1
    r._apply_probes_locked(servers, [_probe("a:1"), _probe("b:1")])
    assert r._breaker["a:1"]["state"] == "half_open"
    assert r._breaker_admits("a:1")
    # make a the obviously better target so admission is breaker-limited
    r._measured_tokens["a:1"] = 0.0
    r._measured_tokens["b:1"] = 10000.0
    out1 = r._try_schedule_locked(
        dict(qid="p1", prompt_len=10, group_size=1, new_token_budget=8)
    )
    assert out1["url"] == "a:1"  # the probe
    assert r._counters["breaker_probes_total"] == 1
    # probe budget exhausted: the next request is NOT full traffic to a
    out2 = r._try_schedule_locked(
        dict(qid="p2", prompt_len=10, group_size=1, new_token_budget=8)
    )
    assert out2["url"] == "b:1"

    # the probe COMPLETING closes the breaker; full traffic returns
    r._release_qid("p1")
    assert r._breaker["a:1"]["state"] == "closed"
    assert r._counters["breaker_closes_total"] == 1
    out3 = r._try_schedule_locked(
        dict(qid="p3", prompt_len=10, group_size=1, new_token_budget=8)
    )
    assert out3["url"] == "a:1"


def test_breaker_half_open_probe_leak_expires():
    """ISSUE 13 satellite: a probe whose client dies before _release_qid
    used to wedge the breaker half-open forever — the probe charge only
    decremented on completion, so no later request could ever probe (and
    close) the breaker. The poll loop now expires probe charges older
    than breaker_probe_ttl_s."""
    r = DecodeRouter(
        servers=["a:1", "b:1"],
        breaker_trip_after=2,
        breaker_slow_s=0.1,
        breaker_probe_requests=1,
        breaker_probe_ttl_s=5.0,
        dead_after_failures=100,
    )
    servers = ["a:1", "b:1"]
    for _ in range(2):
        r._apply_probes_locked(
            servers, [_probe("a:1", rtt=0.5), _probe("b:1")]
        )
    assert r._breaker["a:1"]["state"] == "open"
    r._apply_probes_locked(servers, [_probe("a:1"), _probe("b:1")])
    assert r._breaker["a:1"]["state"] == "half_open"
    # make a the obviously better target so admission is breaker-limited
    r._measured_tokens["a:1"] = 0.0
    r._measured_tokens["b:1"] = 10000.0
    out = r._try_schedule_locked(
        dict(qid="dead-client", prompt_len=10, group_size=1,
             new_token_budget=8)
    )
    assert out["url"] == "a:1"
    assert r._breaker["a:1"]["probes"] == 1
    # the probing client dies: _release_qid never runs. Until the TTL the
    # charge holds (no second probe admitted)...
    out2 = r._try_schedule_locked(
        dict(qid="q2", prompt_len=10, group_size=1, new_token_budget=8)
    )
    assert out2["url"] == "b:1"
    r._expire_locked(time.monotonic(), servers)
    assert r._breaker["a:1"]["probes"] == 1  # not yet stale
    # ...past the TTL the poll loop reclaims it instead of wedging
    r._expire_locked(time.monotonic() + 6.0, servers)
    assert r._breaker["a:1"]["state"] == "half_open"
    assert r._breaker["a:1"]["probes"] == 0
    assert r._counters["breaker_probe_expiries_total"] == 1
    # a fresh probe is admitted again and can close the breaker
    out3 = r._try_schedule_locked(
        dict(qid="q3", prompt_len=10, group_size=1, new_token_budget=8)
    )
    assert out3["url"] == "a:1"
    assert r._counters["breaker_probes_total"] == 2
    r._release_qid("q3")
    assert r._breaker["a:1"]["state"] == "closed"


def test_breaker_relapse_during_half_open():
    """A bad poll during the probe phase reopens the breaker."""
    r = DecodeRouter(
        servers=["a:1"], breaker_trip_after=1, breaker_slow_s=0.1,
        dead_after_failures=100,
    )
    r._apply_probes_locked(["a:1"], [_probe("a:1", rtt=0.5)])
    assert r._breaker["a:1"]["state"] == "open"
    r._apply_probes_locked(["a:1"], [_probe("a:1")])
    assert r._breaker["a:1"]["state"] == "half_open"
    r._apply_probes_locked(["a:1"], [_probe("a:1", rtt=0.5)])
    assert r._breaker["a:1"]["state"] == "open"
    assert r._breaker["a:1"]["probes"] == 0


def test_breaker_metrics_stale_interplay():
    """ISSUE 9 satellite: a replica whose /metrics keep failing (health
    fine) trips the breaker while a measured base exists; once the base
    is dropped at _METRICS_FAIL_LIMIT the bad signal clears, so the
    replica re-enters via PROBE — never a straight jump to full traffic —
    and its affinity entries survive the whole episode."""
    r = DecodeRouter(
        servers=["a:1", "b:1"],
        breaker_trip_after=2,
        dead_after_failures=100,
    )
    servers = ["a:1", "b:1"]
    # healthy rounds with metrics: measured base established
    r._apply_probes_locked(servers, [_probe("a:1"), _probe("b:1")])
    r._prefix_map[99] = ("a:1", time.monotonic())
    # metrics fail (load None, health OK): bad while the base exists
    for i in range(_METRICS_FAIL_LIMIT):
        r._apply_probes_locked(
            servers, [_probe("a:1", load=None), _probe("b:1")]
        )
    # tripped at breaker_trip_after=2 (< _METRICS_FAIL_LIMIT=3)
    assert r._counters["breaker_trips_total"] == 1
    # base dropped at the limit; the NEXT round sees no bad signal, so
    # the replica moves to HALF-OPEN (probe re-entry) — never a straight
    # jump to full traffic
    assert "a:1" not in r._measured_tokens
    assert r._breaker["a:1"]["state"] == "open"
    r._apply_probes_locked(
        servers, [_probe("a:1", load=None), _probe("b:1")]
    )
    assert r._breaker["a:1"]["state"] == "half_open"
    # affinity survived the transient trip
    assert r._prefix_map[99][0] == "a:1"
    # one completed probe restores full traffic
    out = r._try_schedule_locked(
        dict(qid="probe", prompt_len=10, group_size=1, new_token_budget=8)
    )
    r._release_qid("probe")
    assert r._breaker["a:1"]["state"] in ("closed", "half_open")
    # (the probe may have landed on b — force the point: a must be
    # admissible again once closed)
    if r._breaker["a:1"]["state"] == "half_open":
        r._breaker["a:1"]["state"] = "closed"
    assert r._breaker_admits("a:1")


def test_breaker_disabled_is_inert():
    r = DecodeRouter(
        servers=["a:1"], breaker_enabled=False, breaker_trip_after=1,
        breaker_slow_s=0.01, dead_after_failures=100,
    )
    for _ in range(5):
        r._apply_probes_locked(["a:1"], [_probe("a:1", rtt=9.9)])
    assert r._breaker_admits("a:1")
    assert r._counters["breaker_trips_total"] == 0


def test_breaker_death_resets_state():
    """dead_after_failures failover supersedes the breaker: a resurrected
    replica starts with a clean breaker."""
    r = DecodeRouter(
        servers=["a:1", "b:1"], breaker_trip_after=1, breaker_slow_s=0.1,
        dead_after_failures=2,
    )
    servers = ["a:1", "b:1"]
    r._apply_probes_locked(servers, [_probe("a:1", rtt=0.5), _probe("b:1")])
    assert r._breaker["a:1"]["state"] == "open"
    # two failed health polls: failover wipes breaker state
    for _ in range(2):
        r._apply_probes_locked(
            servers, [(_probe("a:1")[0], None, None, 0.0, None, 5.0),
                      _probe("b:1")]
        )
    assert "a:1" not in r._breaker


def test_failover_requeues_and_drains_affinity():
    """Declaring a replica dead must move its qids (with their load
    accounting) onto the least-loaded survivor and drop its prefix
    affinity entries."""
    r = DecodeRouter(servers=["dead:1", "s1:1", "s2:1"])
    r.servers = ["dead:1", "s1:1", "s2:1"]
    r._qid_to_server.update(q1="dead:1", q2="dead:1", q3="s1:1")
    r._qid_cost.update(q1=10.0, q2=6.0, q3=1.0)
    r._qid_pending.update(q1=2, q2=1, q3=1)
    now = time.monotonic()
    r._qid_touched.update(q1=now, q2=now, q3=now)
    r._request_counts.update({"dead:1": 3, "s1:1": 1, "s2:1": 0})
    r._token_usage.update({"dead:1": 16.0, "s1:1": 1.0, "s2:1": 0.0})
    r._token_usage["s2:1"] = 0.0
    r._prefix_map[99] = ("dead:1", now)
    r._prefix_map[77] = ("s1:1", now)

    r._failover_locked("dead:1")
    assert r._qid_to_server["q1"] in ("s1:1", "s2:1")
    assert r._qid_to_server["q2"] in ("s1:1", "s2:1")
    assert r._qid_to_server["q3"] == "s1:1"
    assert r._counters["requeues_total"] == 2
    assert r._counters["failovers_total"] == 1
    assert 99 not in r._prefix_map and 77 in r._prefix_map
    assert r._request_counts["dead:1"] == 0
    assert r._token_usage["dead:1"] == pytest.approx(0.0)
    # moved load landed on the survivors
    assert (
        r._request_counts["s1:1"] + r._request_counts["s2:1"] == 4
    )
    assert r._token_usage["s1:1"] + r._token_usage["s2:1"] == pytest.approx(
        17.0
    )


# -- e2e: prefix affinity, bounded queue, failover, /metrics ----------------


async def _scenario_prefix_affinity():
    s1, s2 = FakeServer(version=1), FakeServer(version=1)
    a1, a2 = await s1.start(), await s2.start()
    router = DecodeRouter(
        servers=[a1, a2],
        config=RouterConfig(
            schedule_policy="prefix_affinity", health_poll_interval=0.2
        ),
    )
    addr = await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.5)
        prefix = list(range(1, 129))  # two 64-token affinity blocks

        async def sched(qid, pl=128, prefix_toks=prefix):
            return await arequest_with_retry(
                addr, "/schedule_request",
                payload=dict(qid=qid, prompt_len=pl, group_size=1,
                             new_token_budget=16, input_prefix=prefix_toks),
            )

        # a GRPO-style group (same prompt, distinct qids) co-locates
        urls = [(await sched(f"g-{i}"))["url"] for i in range(4)]
        assert len(set(urls)) == 1, f"group split across {set(urls)}"
        affine = urls[0]

        # a different prefix is NOT glued to the same server by affinity
        # (it records its own entry wherever load steers it)
        other = await sched("h-0", prefix_toks=list(range(500, 600)))
        assert other["url"] in (a1, a2)

        m = await arequest_with_retry(addr, "/metrics", method="GET")
        assert m["affinity_hits_total"] >= 3
        assert m["tracked_prefixes"] >= 1

        # hot override: pile synthetic load onto the affine server — the
        # next same-prefix request must be steered away and counted
        router._token_usage[affine] = 1e9
        over = await sched("g-override")
        assert over["url"] != affine
        m = await arequest_with_retry(addr, "/metrics", method="GET")
        assert m["affinity_overrides_total"] >= 1
        return True
    finally:
        await close_current_session()
        await router.stop()
        await s1.stop()
        await s2.stop()


def test_router_prefix_affinity():
    assert _run_async(_scenario_prefix_affinity())


async def _scenario_pressure_queue():
    """Saturated fleet: requests queue (bounded FIFO), drain when pressure
    drops, and shed with 429 + Retry-After past the deadline."""
    full = dict(
        kv_blocks_total=10, kv_block_size=16, kv_pool_fragmentation=0,
        kv_tokens_allocated=160, kv_host_pool_enabled=False,
        running_requests=1, queued_requests=0,
    )
    s1 = FakeServer(version=1, active_tokens=10, metrics_extra=dict(full))
    a1 = await s1.start()
    router = DecodeRouter(
        servers=[a1],
        config=RouterConfig(
            schedule_policy="least_requests",
            health_poll_interval=0.15,
            queue_max=4,
            queue_timeout_s=1.0,
            retry_after_s=2.0,
        ),
    )
    addr = await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.4)  # poll sees the saturated pool

        # 1) queue-then-drain: the request parks; relieving pressure
        # lets the next poll admit it
        t_req = asyncio.create_task(
            arequest_with_retry(
                addr, "/schedule_request",
                payload=dict(qid="parked", prompt_len=50, group_size=1,
                             new_token_budget=8),
            )
        )
        await asyncio.sleep(0.3)
        assert not t_req.done(), "request admitted against a full pool"
        m = await arequest_with_retry(addr, "/metrics", method="GET")
        assert m["queue_depth"] == 1
        s1.metrics_extra["kv_tokens_allocated"] = 0  # pool drained
        out = await asyncio.wait_for(t_req, timeout=5)
        assert out["url"] == a1
        m = await arequest_with_retry(addr, "/metrics", method="GET")
        assert m["queue_admits_total"] == 1

        # 2) deadline shed: saturate again; a queued request past
        # queue_timeout_s is shed with 429 + Retry-After
        s1.metrics_extra["kv_tokens_allocated"] = 160
        await asyncio.sleep(0.4)
        with pytest.raises(HttpRequestError) as ei:
            await arequest_with_retry(
                addr, "/schedule_request",
                payload=dict(qid="late", prompt_len=50, group_size=1,
                             new_token_budget=8),
                max_retries=1,
            )
        assert ei.value.status == 429
        assert '"retry_after": 2.0' in str(ei.value)
        m = await arequest_with_retry(addr, "/metrics", method="GET")
        assert m["queue_timeouts_total"] == 1

        # 3) bounded FIFO: past queue_max the shed is immediate
        waiters = [
            asyncio.create_task(
                arequest_with_retry(
                    addr, "/schedule_request",
                    payload=dict(qid=f"w{i}", prompt_len=50, group_size=1,
                                 new_token_budget=8),
                    max_retries=1,
                )
            )
            for i in range(4)
        ]
        await asyncio.sleep(0.2)
        with pytest.raises(HttpRequestError) as ei:
            await arequest_with_retry(
                addr, "/schedule_request",
                payload=dict(qid="overflow", prompt_len=50, group_size=1,
                             new_token_budget=8),
                max_retries=1,
            )
        assert ei.value.status == 429
        m = await arequest_with_retry(addr, "/metrics", method="GET")
        assert m["queue_sheds_total"] >= 1
        for w in waiters:
            w.cancel()
        return True
    finally:
        await close_current_session()
        await router.stop()
        await s1.stop()


def test_router_pressure_queue_and_shed():
    assert _run_async(_scenario_pressure_queue())


async def _scenario_failover_e2e():
    s1, s2 = FakeServer(version=1), FakeServer(version=1)
    a1, a2 = await s1.start(), await s2.start()
    router = DecodeRouter(
        servers=[a1, a2],
        config=RouterConfig(
            schedule_policy="least_requests",
            health_poll_interval=0.15,
            dead_after_failures=2,
        ),
    )
    addr = await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.4)
        urls = {}
        for i in range(4):
            out = await arequest_with_retry(
                addr, "/schedule_request",
                payload=dict(qid=f"q{i}", prompt_len=10, group_size=1,
                             new_token_budget=8),
            )
            urls[f"q{i}"] = out["url"]
        assert set(urls.values()) == {a1, a2}
        victims = [q for q, u in urls.items() if u == a1]
        await s1.stop()  # the replica dies with qids in flight
        await asyncio.sleep(1.2)  # > dead_after_failures polls
        m = await arequest_with_retry(addr, "/metrics", method="GET")
        assert m["failovers_total"] >= 1
        assert m["requeues_total"] >= len(victims)
        # the corpse's qids were re-pointed: a retry re-schedule (requeue
        # semantics) lands on the survivor
        for q in victims:
            out = await arequest_with_retry(
                addr, "/schedule_request",
                payload=dict(qid=q, prompt_len=10, group_size=1,
                             new_token_budget=8, requeue=True),
            )
            assert out["url"] == a2
        health = await arequest_with_retry(addr, "/health", method="GET")
        assert health["servers"] == [a2]
        return True
    finally:
        await close_current_session()
        await router.stop()
        await s2.stop()


def test_router_failover_e2e():
    assert _run_async(_scenario_failover_e2e())


async def _scenario_metrics_endpoint():
    s1 = FakeServer(
        version=3, active_tokens=42,
        metrics_extra=dict(kv_blocks_total=8, kv_block_size=16,
                           kv_tokens_allocated=10, running_requests=1,
                           queued_requests=0, prefix_cache_hit_rate=0.5),
    )
    a1 = await s1.start()
    router = DecodeRouter(
        servers=[a1], config=RouterConfig(health_poll_interval=0.15)
    )
    addr = await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.4)
        await arequest_with_retry(
            addr, "/schedule_request",
            payload=dict(qid="m1", prompt_len=64, group_size=1,
                         new_token_budget=8,
                         input_prefix=list(range(70))),
        )
        m = await arequest_with_retry(addr, "/metrics", method="GET")
        assert m["schedule_policy"] == "prefix_affinity"
        assert m["schedules_total"] == 1
        assert m["tracked_qids"] == 1
        assert m["queue_depth"] == 0
        # the per-server pressure snapshot the admission decisions used
        assert m["pressure"][a1]["kv_blocks_total"] == 8
        assert m["pressure"][a1]["prefix_cache_hit_rate"] == 0.5
        assert a1 in m["token_loads"]
        return True
    finally:
        await close_current_session()
        await router.stop()
        await s1.stop()


def test_router_metrics_endpoint():
    assert _run_async(_scenario_metrics_endpoint())
