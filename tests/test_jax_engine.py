"""Train-engine tests on the 8-virtual-device CPU mesh (parity with
areal/tests/test_train_engine.py's mock-input pattern, :21-48)."""

import numpy as np
import pytest

import jax

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    MicroBatchSpec,
    OptimizerConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec, SaveLoadMeta
from areal_tpu.engine.sft.lm_engine import (
    JaxLMEngine,
    compute_packed_sft_loss,
    sft_loss_weight,
)
from areal_tpu.models.qwen2 import ModelConfig
from areal_tpu.utils.data import pad_sequences_to_tensors

TINY_MODEL = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)


def mock_batch(n=4, lens=(9, 13, 7, 11), vocab=64, seed=0):
    rng = np.random.RandomState(seed)
    seqs = []
    for i in range(n):
        L = lens[i % len(lens)]
        ids = rng.randint(1, vocab, (L,))
        loss_mask = np.zeros(L, dtype=np.int32)
        loss_mask[L // 2 :] = 1  # "answer" half
        seqs.append(dict(input_ids=ids, loss_mask=loss_mask))
    return pad_sequences_to_tensors(seqs)


@pytest.fixture(scope="module")
def engine(cpu_devices):
    cfg = TrainEngineConfig(
        experiment_name="test",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=64),
        optimizer=OptimizerConfig(
            lr=5e-3, warmup_steps_proportion=0.0, lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=False,
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = TINY_MODEL
    eng.create_process_group(
        ParallelStrategy(
            data_parallel_size=2, tensor_parallel_size=2, context_parallel_size=2
        )
    )
    eng.initialize(None, FinetuneSpec(1, 128, 4))
    return eng


@pytest.mark.slow
def test_sft_overfit_loss_decreases(engine):
    batch = mock_batch()
    losses = [engine.train_lm(batch)["loss"] for _ in range(12)]
    assert losses[-1] < losses[0] * 0.7, losses
    assert np.isfinite(losses).all()


@pytest.mark.slow
def test_eval_batch(engine):
    batch = mock_batch(seed=3)
    loss = engine.evaluate_lm(batch)
    assert np.isfinite(loss)


@pytest.mark.slow
def test_forward_reorders_to_input_order(engine):
    batch = mock_batch()
    lens = batch["attention_mask"].sum(1).astype(int)

    def post_hook(logits, mb):
        return logits.argmax(-1)

    out = engine.forward(batch, post_hook=post_hook, aggregate_fn=list)
    assert len(out) == 4
    for i, o in enumerate(out):
        assert o.shape[0] == lens[i], (i, o.shape, lens)


@pytest.mark.slow
def test_train_stats_contract(engine):
    stats = engine.train_lm(mock_batch(seed=5))
    for key in ("loss", "grad_norm", "lr", "n_mbs", "update_steps"):
        assert key in stats
    assert stats["grad_norm"] >= 0


@pytest.mark.slow
def test_save_load_roundtrip(engine, tmp_path):
    batch = mock_batch(seed=7)
    loss_before = engine.evaluate_lm(batch)
    engine.save(SaveLoadMeta(path=str(tmp_path / "ckpt"), with_optim=True))
    # perturb weights by training, then restore
    for _ in range(3):
        engine.train_lm(batch)
    engine.load(SaveLoadMeta(path=str(tmp_path / "ckpt"), with_optim=True))
    loss_after = engine.evaluate_lm(batch)
    assert abs(loss_before - loss_after) < 1e-4


def test_loss_weight_counts_answer_tokens():
    batch = mock_batch(n=2, lens=(8, 8))
    from areal_tpu.utils.data import pack_tensor_dict

    packed = pack_tensor_dict(batch)
    from areal_tpu.models.qwen2 import segment_ids_from_cu_seqlens

    packed["segment_ids"] = segment_ids_from_cu_seqlens(
        np.asarray(packed["cu_seqlens"]), int(packed["cu_seqlens"][-1])
    )
    w = sft_loss_weight(packed)
    # each 8-token seq trains 4 answer labels (positions 3..6 predict 4..7)
    assert w == 8.0
