"""Zig-zag context-parallel layout (tier-1, CPU, fast).

The zig-zag permutation is a pure relabeling of the packed token axis —
shard i holds the chunk pair (i, 2n-1-i) — so every invariant here is
exactness, not approximation: permute → ring-attend → unpermute must equal
the contiguous layout, at the kernel level and through the whole model.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.models.qwen2 import ModelConfig, forward, init_params
from areal_tpu.ops.ring_attention import (
    _shard_positions,
    cp_ring_shards,
    ring_flash_attention,
    zigzag_eligible,
)
from areal_tpu.parallel import mesh as mesh_lib
from areal_tpu.utils.data import zigzag_indices, zigzag_inverse_indices
from tests.test_flash_attention import dense_reference, make_inputs


@pytest.mark.parametrize("total,n", [(256, 2), (512, 4), (96, 3)])
def test_zigzag_permutation_roundtrip(total, n):
    perm = zigzag_indices(total, n)
    inv = zigzag_inverse_indices(total, n)
    x = np.arange(total)
    np.testing.assert_array_equal(x[perm][inv], x)
    np.testing.assert_array_equal(np.sort(perm), x)
    # every shard holds exactly the chunk pair (i, 2n-1-i)
    c = total // (2 * n)
    for i in range(n):
        shard = perm[i * 2 * c : (i + 1) * 2 * c]
        chunks = {int(t) // c for t in shard}
        assert chunks == {i, 2 * n - 1 - i}


@pytest.mark.parametrize("n", [2, 4])
def test_ring_positions_match_data_layout(n):
    """The ring body's position maps ARE the data helper's permutation —
    the one contract that keeps kernel causality and host layout in sync."""
    Tl = 128 * 2 // 2  # any even local length
    total = n * Tl
    perm = zigzag_indices(total, n)
    for i in range(n):
        pos = np.asarray(
            _shard_positions(jnp.int32(i), Tl, n, zigzag=True)
        )
        np.testing.assert_array_equal(pos, perm[i * Tl : (i + 1) * Tl])
        contig = np.asarray(
            _shard_positions(jnp.int32(i), Tl, n, zigzag=False)
        )
        np.testing.assert_array_equal(contig, np.arange(Tl) + i * Tl)


@pytest.fixture()
def cp2_mesh(cpu_devices):
    mesh = mesh_lib.build_mesh(
        ParallelStrategy(data_parallel_size=2), devices=cpu_devices[:2]
    )
    mesh_lib.set_current_mesh(mesh)
    yield mesh
    mesh_lib.set_current_mesh(None)


def test_ring_zigzag_matches_dense(cp2_mesh):
    T, nH, nKV, hd = 256, 2, 2, 32
    q, k, v, seg = make_inputs(T, nH, nKV, hd, pad=19, n_seqs=3)
    n = cp_ring_shards(T, cp2_mesh)
    assert n == 2 and zigzag_eligible(T, cp2_mesh)
    perm = zigzag_indices(T, n)
    inv = zigzag_inverse_indices(T, n)
    out_z = ring_flash_attention(
        q[perm], k[perm], v[perm], seg[perm],
        mesh=cp2_mesh, zigzag=True, interpret=True,
    )
    ref = dense_reference(q, k, v, seg)
    np.testing.assert_allclose(
        np.asarray(out_z)[inv], np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ring_zigzag_gradients_match(cp2_mesh):
    T, nH, nKV, hd = 256, 2, 2, 32
    q, k, v, seg = make_inputs(T, nH, nKV, hd, pad=7, seed=5, n_seqs=2)
    n = cp_ring_shards(T, cp2_mesh)
    perm = jnp.asarray(zigzag_indices(T, n))
    inv = jnp.asarray(zigzag_inverse_indices(T, n))

    def loss_zig(q, k, v):
        o = ring_flash_attention(
            q[perm], k[perm], v[perm], seg[perm],
            mesh=cp2_mesh, zigzag=True, interpret=True,
        )
        return jnp.sum(jnp.sin(o[inv]))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(dense_reference(q, k, v, seg)))

    gz = jax.grad(loss_zig, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gz, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-4, rtol=3e-4, err_msg=name
        )


def test_model_forward_zigzag_matches_contiguous(cp2_mesh):
    """cp_zigzag=True permutes at forward entry and inverts on the logits:
    byte-for-byte the same contract as the contiguous ring layout."""
    cfg = ModelConfig(
        vocab_size=64,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        dtype="float32",
        param_dtype="float32",
        attn_impl="ring",
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    T = 256
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(1, 64, (T,)), jnp.int32)
    seg = jnp.asarray(np.repeat(np.arange(4), T // 4), jnp.int32)
    pos = jnp.asarray(np.tile(np.arange(T // 4, dtype=np.int32), 4))

    out_plain = forward(params, ids, pos, seg, cfg)
    out_zig = forward(
        params, ids, pos, seg, dataclasses.replace(cfg, cp_zigzag=True)
    )
    np.testing.assert_allclose(
        np.asarray(out_zig), np.asarray(out_plain), atol=2e-5, rtol=2e-5
    )


def test_zigzag_requires_ring_path():
    # No mesh bound: a zig-zag stream falling back to plain flash would be
    # silently wrong — must raise instead.
    T, nH, nKV, hd = 256, 2, 2, 32
    q, k, v, seg = make_inputs(T, nH, nKV, hd, pad=0, seed=7, n_seqs=2)
    with pytest.raises(ValueError, match="zigzag"):
        ring_flash_attention(q, k, v, seg, mesh=None, zigzag=True)
