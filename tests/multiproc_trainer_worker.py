"""Worker for the 2-process distributed-trainer test.

Launched twice by tests/test_multiprocess.py (the TPU-shaped counterpart
of the reference's torchrun-subprocess distributed tests, areal/tests/
torchrun/ + realhf StandaloneTestingProcess): each process owns 4 virtual
CPU devices, joins one 8-device global mesh via jax.distributed, feeds the
IDENTICAL global batch (the dist_rollout contract: every process converges
on the same batch after host all-gather), and trains — the engine's jit
programs then run as true multi-process SPMD, exercising the same
cross-process collectives a multi-host TPU pod uses.

Prints one line per step: LOSS <step> <value>; the parent asserts both
ranks emit identical, decreasing values.
"""

import os
import sys


def main() -> None:
    pid = int(sys.argv[1])
    coord = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.distributed.initialize(coord, num_processes=2, process_id=pid)
    assert jax.process_count() == 2 and jax.device_count() == 8

    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    from areal_tpu.api.alloc_mode import ParallelStrategy
    from areal_tpu.api.cli_args import (
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.sft.lm_engine import JaxLMEngine
    from areal_tpu.models.qwen2 import ModelConfig
    from areal_tpu.utils.data import pad_sequences_to_tensors

    cfg = TrainEngineConfig(
        experiment_name="mp",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=128),
        optimizer=OptimizerConfig(
            lr=5e-3,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = ModelConfig(
        vocab_size=64,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        dtype="float32",
        param_dtype="float32",
    )
    # dp spans BOTH processes (4 local devices each), tp within-process
    eng.create_process_group(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    assert eng.data_parallel_rank == pid
    assert eng.data_parallel_world_size == 2
    eng.initialize(None, FinetuneSpec(1, 50, 4))

    rng = np.random.RandomState(0)  # same seed -> identical global batch
    seqs = []
    for L in (11, 9, 13, 7):
        ids = rng.randint(1, 64, (L,))
        mask = np.zeros(L, dtype=np.int32)
        mask[1:] = 1
        seqs.append(dict(input_ids=ids, loss_mask=mask))
    batch = pad_sequences_to_tensors(seqs)

    for step in range(4):
        stats = eng.train_lm(batch)
        print(f"LOSS {step} {stats['loss']:.6f}", flush=True)

    # Drive the ENGINE's dcn weight push: both ranks join the
    # process_allgather collective inside update_weights; only process 0
    # streams to the (stub) rollout engine.
    from areal_tpu.api.io_struct import WeightUpdateMeta

    pushed = {}

    class _StubRollout:
        def update_weights_from_tensor(self, named, version, chunk_mb=512):
            pushed["n_tensors"] = len(named)

    eng.rollout_engine = _StubRollout()
    eng.update_weights(WeightUpdateMeta(type="dcn"))
    if pid == 0:
        assert pushed["n_tensors"] > 0, pushed
        print(f"GATHERED {pushed['n_tensors']}", flush=True)
    else:
        assert not pushed
        print("GATHERED participated", flush=True)
    eng.destroy()


if __name__ == "__main__":
    main()
