"""Int8 weight serving end-to-end (ISSUE 16).

Coverage layers:

1. Scheme unit contracts (ops/quant.py, the axis-generic absmax module
   hoisted out of ops/kv_quant.py): per-output-channel symmetric round
   trip bounded by amax/254 for every contraction-axes shape the weight
   path uses, zero channels exact, and the KV path still delegates to
   the SAME functions (one scheme, two consumers).
2. Weight-tree helpers (models/qwen2.py): quantize_weights hits exactly
   the transformer matmul kernels — embeddings, lm_head, norms and
   biases stay fp, MoE expert mlps are skipped while their attn still
   quantizes — idempotently, with scale shapes = kernel shape minus the
   contraction axes; dequantize_weights round-trips within the scheme
   bound.
3. Kernel agreement: the Pallas fused dequant-matmul (interpret mode)
   and the XLA dequant-then-einsum fallback agree on the SAME
   dequantized values within float-reassociation tolerance, for 2D and
   kernel-shaped (4D-weight) contractions; misaligned shapes fall back
   instead of mis-tiling.
4. weight_dtype="fp" is the numerics ORACLE: greedy + sampled streams
   on both kv_layouts pinned bit-for-bit against a committed golden
   (regenerate with AREAL_WRITE_GOLDEN=1 after an INTENTIONAL numerics
   change) — the int8 fast path must not perturb the default path.
5. Serving + push invariants: unknown weight_dtype rejected; the
   producer-quantized full-tree push installs int8 payloads VERBATIM
   (no recast); fp-named pushes into an int8 engine fail with the
   dtype-mismatch diagnosis, not a bare KeyError; torn int8 frames are
   rejected before a byte stages; drift vs the fp oracle is measured,
   bounded and deterministic.
6. LoRA on a quantized base: fold-then-requantize — the served kernel
   is EXACTLY quantize(dequant(pristine int8 base) + scale * A @ B)
   (pinned bitwise against that oracle, and re-pushing the same delta
   is a no-op because the fold starts from the pristine snapshot), and
   stays within the scheme bound of the quantize-after-fold fp oracle
   (one absmax round of the true merged weights, never a round-trip of
   a round-trip).
"""

import json
import os
from dataclasses import replace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.core.weight_transfer import (
    WeightStaging,
    flatten_named,
    pack_buckets,
    raw_wire_nbytes,
)
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.models.qwen2 import (
    ModelConfig,
    dequantize_weights,
    init_lora_params,
    init_params,
    is_weight_quantized,
    merge_lora,
    quantize_weights,
    wq_contraction_axes,
)
from areal_tpu.ops.quant import dequantize_absmax, quantize_absmax

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

MOE = replace(
    TINY,
    num_experts=4,
    num_experts_per_tok=2,
    moe_intermediate_size=16,
    attn_impl="dense",
)

_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = init_params(TINY, jax.random.PRNGKey(0))
    return _PARAMS


# -- 1. scheme unit contracts ------------------------------------------


@pytest.mark.parametrize("axes", [(0,), (0, 1), (1,)])
def test_absmax_roundtrip_error_bound_per_channel(axes):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(6, 10, 16).astype(np.float32) * 2.5)
    q, s = quantize_absmax(x, axis=axes)
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    assert q.shape == x.shape
    assert s.shape == tuple(
        d for i, d in enumerate(x.shape) if i not in axes
    )
    back = np.asarray(dequantize_absmax(q, s, jnp.float32, axis=axes))
    amax = np.abs(np.asarray(x)).max(axis=axes, keepdims=True)
    err = np.abs(back - np.asarray(x))
    # symmetric round-to-nearest on a 127-step grid: error <= amax/254
    assert (err <= amax / 254 + 1e-7).all(), err.max()


def test_absmax_zero_channels_exact():
    x = jnp.zeros((4, 8), jnp.float32)
    q, s = quantize_absmax(x, axis=(0,))
    assert np.array_equal(np.asarray(q), np.zeros_like(q))
    # scale 1.0 on all-zero channels: dequant is exact zero, never 0/0
    assert np.array_equal(np.asarray(s), np.ones((8,), np.float32))


def test_kv_path_delegates_to_shared_scheme():
    from areal_tpu.ops import kv_quant, quant

    # ops/kv_quant re-exports the hoisted functions, not copies of them
    assert kv_quant.quantize_absmax is quant.quantize_absmax
    assert kv_quant.dequantize_absmax is quant.dequantize_absmax
    assert kv_quant.INT8_QMAX is quant.INT8_QMAX
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(3, 2, 8).astype(np.float32))
    qk, sk = kv_quant.quantize_kv(x)
    qa, sa = quant.quantize_absmax(x, axis=-1)
    assert np.array_equal(np.asarray(qk), np.asarray(qa))
    assert np.array_equal(np.asarray(sk), np.asarray(sa))


# -- 2. weight-tree helpers ---------------------------------------------

# stacked [L, ...] scan layout: leaf -> (contraction axes, scale shape)
_EXPECT = {
    ("attn", "q_kernel"): ((1,), (2, 4, 8)),
    ("attn", "k_kernel"): ((1,), (2, 2, 8)),
    ("attn", "v_kernel"): ((1,), (2, 2, 8)),
    ("attn", "o_kernel"): ((1, 2), (2, 32)),
    ("mlp", "gate_kernel"): ((1,), (2, 64)),
    ("mlp", "up_kernel"): ((1,), (2, 64)),
    ("mlp", "down_kernel"): ((1,), (2, 32)),
}


def test_quantize_weights_targets_exact_kernel_set():
    p = _params()
    qt = quantize_weights(p)
    assert is_weight_quantized(qt) and not is_weight_quantized(p)
    for (sub, leaf), (axes, sshape) in _EXPECT.items():
        node = qt["layers"][sub][leaf]
        assert isinstance(node, dict) and set(node) == {"q", "scale"}
        assert node["q"].dtype == jnp.int8
        assert node["q"].shape == p["layers"][sub][leaf].shape
        assert node["scale"].dtype == jnp.float32
        assert node["scale"].shape == sshape, (sub, leaf)
        # the quantization is THE shared scheme, bit for bit
        eq, es = quantize_absmax(p["layers"][sub][leaf], axis=axes)
        assert np.array_equal(np.asarray(node["q"]), np.asarray(eq))
        assert np.array_equal(np.asarray(node["scale"]), np.asarray(es))
    # everything vocab/norm/bias-shaped stays fp, bit-identical
    for name in (
        "embed/embedding", "lm_head/kernel", "final_norm",
        "layers/input_norm", "layers/post_attn_norm",
        "layers/attn/q_bias", "layers/attn/k_bias", "layers/attn/v_bias",
    ):
        a, b = flatten_named(p)[name], flatten_named(qt)[name]
        assert np.array_equal(a, b), name
    # idempotent: quantizing a quantized tree changes nothing
    qt2 = quantize_weights(qt)
    fa, fb = flatten_named(qt), flatten_named(qt2)
    assert set(fa) == set(fb)
    for k in fa:
        assert np.array_equal(fa[k], fb[k]), k


def test_dequantize_roundtrip_bounded():
    p = _params()
    back = dequantize_weights(quantize_weights(p), jnp.float32)
    for (sub, leaf), (axes, _) in _EXPECT.items():
        w = np.asarray(p["layers"][sub][leaf])
        r = np.asarray(back["layers"][sub][leaf])
        assert r.dtype == w.dtype
        amax = np.abs(w).max(axis=axes, keepdims=True)
        assert (np.abs(r - w) <= amax / 254 + 1e-7).all(), (sub, leaf)


def test_wq_contraction_axes_table():
    assert wq_contraction_axes("q_kernel", stacked=False) == (0,)
    assert wq_contraction_axes("q_kernel", stacked=True) == (1,)
    assert wq_contraction_axes("o_kernel", stacked=False) == (0, 1)
    assert wq_contraction_axes("o_kernel", stacked=True) == (1, 2)
    assert wq_contraction_axes("down_kernel", stacked=True) == (1,)
    assert wq_contraction_axes("q_bias", stacked=True) is None
    assert wq_contraction_axes("router_kernel", stacked=True) is None


def test_moe_mlp_skipped_attn_still_quantized():
    p = init_params(MOE, jax.random.PRNGKey(2))
    qt = quantize_weights(p)
    mlp = qt["layers"]["mlp"]
    # routed-expert kernels ship fp (router numerics are drift-sensitive
    # and expert kernels are gathered, not plain matmuls)
    for k, v in mlp.items():
        assert not isinstance(v, dict), k
        assert np.array_equal(
            np.asarray(v), np.asarray(p["layers"]["mlp"][k])
        ), k
    # the attention stack still takes the fast path
    assert isinstance(qt["layers"]["attn"]["q_kernel"], dict)


# -- 3. Pallas vs XLA agreement -----------------------------------------


def test_quant_matmul_tile_gate():
    from areal_tpu.ops.quant_matmul import quant_matmul_tiles_ok

    assert quant_matmul_tiles_ok(128, 128)
    assert quant_matmul_tiles_ok(256, 384)
    assert not quant_matmul_tiles_ok(130, 128)
    assert not quant_matmul_tiles_ok(128, 64)


def test_pallas_and_xla_agree_on_quantized_matmul():
    from areal_tpu.ops.quant_matmul import quant_einsum

    rng = np.random.RandomState(3)
    for tshape, wshape, nc in (
        ((5, 128), (128, 256), 1),       # 2D, T not tile-aligned
        ((3, 4, 128), (128, 8, 16), 1),  # q_kernel-like: N = 8*16 = 128
        ((2, 8, 16), (8, 16, 128), 2),   # o_kernel-like: K = 8*16 = 128
    ):
        x = jnp.asarray(rng.randn(*tshape).astype(np.float32))
        w = jnp.asarray(rng.randn(*wshape).astype(np.float32))
        wq, ws = quantize_absmax(w, axis=tuple(range(nc)))
        o_xla = quant_einsum(x, wq, ws, nc, impl="xla")
        o_pl = quant_einsum(x, wq, ws, nc, impl="pallas", interpret=True)
        assert o_xla.shape == o_pl.shape == tshape[:-nc] + wshape[nc:]
        np.testing.assert_allclose(
            np.asarray(o_xla), np.asarray(o_pl), atol=2e-5, rtol=1e-5
        )
        # both implementations score the dequantized values: pin against
        # the plain dequant-then-dot reference
        ref = jnp.einsum(
            "tk,kn->tn",
            x.reshape(-1, int(np.prod(wshape[:nc]))),
            dequantize_absmax(
                wq, ws, jnp.float32, axis=tuple(range(nc))
            ).reshape(int(np.prod(wshape[:nc])), -1),
        ).reshape(o_xla.shape)
        np.testing.assert_allclose(
            np.asarray(o_xla), np.asarray(ref), atol=2e-5, rtol=1e-5
        )


def test_misaligned_shapes_fall_back_not_mistile():
    from areal_tpu.ops.quant_matmul import quant_einsum

    rng = np.random.RandomState(4)
    # K=48, N=40: no legal Pallas tiling — impl="auto" must fall back
    x = jnp.asarray(rng.randn(3, 48).astype(np.float32))
    w = jnp.asarray(rng.randn(48, 40).astype(np.float32))
    wq, ws = quantize_absmax(w, axis=(0,))
    o_auto = quant_einsum(x, wq, ws, 1, impl="auto")
    o_xla = quant_einsum(x, wq, ws, 1, impl="xla")
    assert np.array_equal(np.asarray(o_auto), np.asarray(o_xla))


# -- engine helpers -----------------------------------------------------


def _engine(*, weight_dtype="fp", kv_layout="workspace", R=3, chunk=4,
            context=160, params=None, seed=1):
    cfg = JaxDecodeConfig(
        context_length=context,
        max_running_requests=R,
        new_tokens_per_chunk=chunk,
        kv_layout=kv_layout,
        weight_dtype=weight_dtype,
        dtype="float32",
        kv_cache_dtype="float32",
        random_seed=seed,
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(params if params is not None else _params(), TINY)
    eng.initialize()
    return eng


def _prompt(n=40, seed=5):
    return np.random.RandomState(seed).randint(1, 64, (n,)).tolist()


_GREEDY = GenerationHyperparameters(max_new_tokens=12, greedy=True)
_SAMPLED = GenerationHyperparameters(
    max_new_tokens=12, temperature=0.8, top_p=0.9
)


def _stream(eng, g, prompt=None):
    r = eng.generate(
        ModelRequest(input_ids=prompt or _prompt(), gconfig=g),
        timeout=120,
    )
    return list(r.output_tokens), [float(x) for x in r.output_logprobs]


# -- 4. weight_dtype="fp" is the numerics oracle ------------------------

GOLDEN = os.path.join(
    os.path.dirname(__file__), "fixtures", "wquant_fp_golden.json"
)


@pytest.mark.parametrize("kv_layout", ["workspace", "paged"])
def test_fp_streams_bit_identical_to_golden(cpu_devices, kv_layout):
    """The default path must stay BITWISE what it was before the int8
    fast path landed: weight_dtype="fp" routes every matmul through the
    exact pre-existing jnp.einsum call (no quantize, no dequant, no
    recast), so its streams are pinned token-for-token AND
    logprob-for-logprob against the committed golden. Regenerate with
    AREAL_WRITE_GOLDEN=1 only for an INTENTIONAL numerics change."""
    eng = _engine(weight_dtype="fp", kv_layout=kv_layout)
    try:
        got = {}
        for gname, g in (("greedy", _GREEDY), ("sampled", _SAMPLED)):
            toks, lps = _stream(eng, g)
            got[gname] = {"tokens": toks, "logprobs": lps}
    finally:
        eng.destroy()

    golden = {}
    if os.path.exists(GOLDEN):
        with open(GOLDEN) as f:
            golden = json.load(f)
    if os.environ.get("AREAL_WRITE_GOLDEN"):
        golden[kv_layout] = got
        with open(GOLDEN, "w") as f:
            json.dump(golden, f, indent=1, sort_keys=True)
        pytest.skip("golden regenerated")
    assert kv_layout in golden, f"golden missing; regen {GOLDEN}"
    for gname in ("greedy", "sampled"):
        assert got[gname]["tokens"] == golden[kv_layout][gname]["tokens"]
        assert (
            got[gname]["logprobs"] == golden[kv_layout][gname]["logprobs"]
        ), gname


# -- 5. serving + push invariants ---------------------------------------


def test_unknown_weight_dtype_rejected(cpu_devices):
    cfg = JaxDecodeConfig(
        weight_dtype="int4", dtype="float32", kv_cache_dtype="float32"
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(_params(), TINY)
    with pytest.raises(ValueError, match="weight_dtype"):
        eng.initialize()


def _wire(params, dtype="int8"):
    """The producer's exact payload: bf16 push cast, then quantize —
    jax_engine._dcn_payload's order."""
    bf16 = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        params,
    )
    return flatten_named(
        quantize_weights(bf16) if dtype == "int8" else bf16
    )


def test_quantized_push_installs_verbatim_and_decodes(cpu_devices):
    """Producer-quantized full tree -> framed wire -> int8 engine: the
    int8 payloads and f32 scales install byte-for-byte (the consumer
    cast targets the RESIDENT dtype, so no recast corrupts them), the
    version stamps, and the engine decodes from the pushed weights."""
    fresh = init_params(TINY, jax.random.PRNGKey(7))
    wire = _wire(fresh)
    eng = _engine(weight_dtype="int8")
    try:
        assert eng.get_metrics()["weight_dtype"] == "int8"
        eng.update_weights_from_tensor(dict(wire), version=3)
        assert eng.get_version() == 3
        node = eng.params["layers"]["attn"]["q_kernel"]
        assert node["q"].dtype == jnp.int8
        assert np.array_equal(
            np.asarray(node["q"]), wire["layers/attn/q_kernel/q"]
        )
        assert node["scale"].dtype == jnp.float32
        assert np.array_equal(
            np.asarray(node["scale"]), wire["layers/attn/q_kernel/scale"]
        )
        toks, _ = _stream(eng, _GREEDY)
        assert len(toks) == _GREEDY.max_new_tokens
    finally:
        eng.destroy()


def test_fp_named_push_into_int8_engine_diagnosed(cpu_devices):
    """An fp producer pushing whole-kernel names at an int8 consumer is
    a config mismatch, and the error must SAY so — every kernel name
    shifts by the /q + /scale suffix, so a bare KeyError would read as
    tree corruption."""
    eng = _engine(weight_dtype="int8")
    try:
        with pytest.raises(KeyError, match="weight_dtype"):
            eng.update_weights_from_tensor(
                _wire(_params(), dtype="fp"), version=2
            )
        # and nothing committed
        assert eng.get_version() == 0
    finally:
        eng.destroy()


def test_torn_int8_frame_rejected_before_staging():
    wire = _wire(_params())
    frames = list(pack_buckets(wire, chunk_mb=0.002))
    assert len(frames) > 1
    st = WeightStaging()
    with pytest.raises(ValueError, match="torn"):
        st.add_bucket(frames[0][:-3])
    # the torn attempt staged nothing; intact frames still land with
    # int8 + f32 dtypes preserved through the framing
    for f in frames:
        st.add_bucket(f)
    staged = st.finalize()
    assert set(staged) == set(wire)
    assert staged["layers/attn/q_kernel/q"].dtype == np.int8
    assert staged["layers/attn/q_kernel/scale"].dtype == np.float32
    assert np.array_equal(
        staged["layers/attn/q_kernel/q"], wire["layers/attn/q_kernel/q"]
    )


def test_raw_wire_accounting_bf16_equivalent():
    """wire_bytes_raw prices the int8 push at what the fp wire WOULD
    have shipped: /q counts twice its int8 bytes (bf16 equivalent),
    /scale counts zero (pure overhead of the scheme), everything else
    at face value — so raw/sent is the honest compression ratio."""
    assert raw_wire_nbytes("layers/attn/q_kernel/q", 100, "int8") == 200
    assert raw_wire_nbytes("layers/attn/q_kernel/scale", 64, "float32") == 0
    assert raw_wire_nbytes("embed/embedding", 100, "bfloat16") == 100
    # a leaf literally NAMED q/scale but not int8/f32 is not the scheme
    assert raw_wire_nbytes("x/q", 100, "bfloat16") == 100
    wire_q = _wire(_params())
    wire_f = _wire(_params(), dtype="fp")
    raw = sum(
        raw_wire_nbytes(n, a.nbytes, str(a.dtype))
        for n, a in wire_q.items()
    )
    # the bf16-equivalent of the quantized KERNELS is exactly the bytes
    # the fp wire ships for them
    fp_kernels = sum(
        wire_f[n[: -len("/q")]].nbytes
        for n in wire_q
        if n.endswith("/q")
    )
    unquantized = sum(
        a.nbytes for n, a in wire_q.items()
        if not n.endswith(("/q", "/scale"))
    )
    assert raw == fp_kernels + unquantized


@pytest.mark.parametrize("gname", ["greedy", "sampled"])
def test_int8_drift_vs_fp_oracle_bounded_and_deterministic(
    cpu_devices, gname
):
    """Int8 weights change the numerics — the contract is the drift is
    SMALL and DETERMINISTIC, not zero: over the token-matched prefix
    the per-token |logprob delta| stays bounded, and two independent
    int8 engines reproduce the identical stream (the drift is a fixed
    property of the scheme, not noise). Random weights are the worst
    case for argmax flips, so the bound is on logprobs, not tokens."""
    g = _GREEDY if gname == "greedy" else _SAMPLED
    prompt = _prompt(40, seed=19)

    def run(dt):
        e = _engine(weight_dtype=dt)
        try:
            return _stream(e, g, prompt)
        finally:
            e.destroy()

    fp_t, fp_l = run("fp")
    i8_t, i8_l = run("int8")
    i8_t2, i8_l2 = run("int8")
    assert i8_t == i8_t2 and i8_l == i8_l2

    matched = 0
    for a, b in zip(fp_t, i8_t):
        if a != b:
            break
        matched += 1
    assert matched >= 1
    deltas = [abs(a - b) for a, b in zip(fp_l[:matched], i8_l[:matched])]
    # measured drift, pinned: per-channel int8 weights on this tiny f32
    # model stay well under 0.25 logprob on the matched prefix (seen
    # ~0.03 typical); a scheme regression (wrong contraction axis,
    # double quantization, scale downcast) blows far past this
    if deltas:
        assert max(deltas) < 0.25, (matched, deltas)


# -- 6. LoRA on a quantized base ----------------------------------------

LORA_CFG = replace(
    TINY, lora_rank=4, lora_alpha=8.0, lora_targets=("q_proj", "v_proj")
)


def _rand_lora(seed):
    lora = init_lora_params(LORA_CFG, jax.random.PRNGKey(seed))
    leaves, td = jax.tree.flatten(lora)
    rng = np.random.RandomState(seed)
    leaves = [
        np.asarray(l) + rng.randn(*np.shape(l)).astype(np.float32) * 0.05
        for l in leaves
    ]
    return jax.tree.unflatten(td, leaves)


def test_lora_fold_then_requantize_matches_oracle(cpu_devices):
    scale = LORA_CFG.lora_alpha / LORA_CFG.lora_rank
    lora = _rand_lora(11)
    eng = _engine(weight_dtype="int8")
    try:
        # pristine int8 base BEFORE any delta lands
        snap = {
            leaf: (
                np.asarray(eng.params["layers"]["attn"][leaf]["q"]),
                np.asarray(eng.params["layers"]["attn"][leaf]["scale"]),
            )
            for leaf in ("q_kernel", "v_kernel")
        }
        eng.update_weights_from_tensor(
            flatten_named({"lora": lora}), version=2, lora_scale=scale
        )
        for leaf in ("q_kernel", "v_kernel"):
            # the oracle replays the engine's exact op sequence (jnp
            # einsum + dequant + requant) so the pin can be BITWISE
            a = jnp.asarray(lora["attn"][f"{leaf}_lora_a"], jnp.float32)
            b = jnp.asarray(lora["attn"][f"{leaf}_lora_b"], jnp.float32)
            delta = jnp.einsum("lhr,lrnd->lhnd", a, b)
            axes = wq_contraction_axes(leaf, stacked=True)
            merged = (
                dequantize_absmax(
                    jnp.asarray(snap[leaf][0]),
                    jnp.asarray(snap[leaf][1]),
                    jnp.float32,
                    axis=axes,
                )
                + scale * delta
            )
            q_exp, s_exp = quantize_absmax(merged, axis=axes)
            node = eng.params["layers"]["attn"][leaf]
            # fold-then-requantize, EXACTLY: one absmax round of the
            # true merged weights
            assert np.array_equal(np.asarray(node["q"]), np.asarray(q_exp))
            assert np.array_equal(
                np.asarray(node["scale"]), np.asarray(s_exp)
            )
            # and within the scheme bound of the quantize-after-fold fp
            # oracle (differs only by the base's own round trip)
            fp_merged = np.asarray(
                merge_lora(
                    {**_params(), "lora": lora}, LORA_CFG
                )["layers"]["attn"][leaf]
            )
            got = np.asarray(
                dequantize_absmax(
                    node["q"], node["scale"], jnp.float32, axis=axes
                )
            )
            amax = np.abs(fp_merged).max(axis=axes, keepdims=True)
            assert (np.abs(got - fp_merged) <= 3 * amax / 254 + 1e-6).all()

        # untouched kernels keep the pristine int8 payload bit-for-bit
        assert np.array_equal(
            np.asarray(eng.params["layers"]["attn"]["k_kernel"]["q"]),
            np.asarray(
                quantize_weights(_params())["layers"]["attn"]["k_kernel"]["q"]
            ),
        )

        # re-pushing the SAME delta refolds from the pristine snapshot:
        # the served tree is unchanged (not base + 2x delta)
        before = {
            leaf: np.asarray(eng.params["layers"]["attn"][leaf]["q"])
            for leaf in ("q_kernel", "v_kernel")
        }
        eng.update_weights_from_tensor(
            flatten_named({"lora": lora}), version=3, lora_scale=scale
        )
        for leaf in ("q_kernel", "v_kernel"):
            assert np.array_equal(
                np.asarray(eng.params["layers"]["attn"][leaf]["q"]),
                before[leaf],
            )
        assert eng.get_version() == 3
    finally:
        eng.destroy()
