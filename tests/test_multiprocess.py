"""Two-process SPMD trainer test — the multi-host path without a pod.

Reference counterpart: torchrun-launched distributed tests (areal/tests/
torchrun/) and realhf's StandaloneTestingProcess multi-rank harness. Here
two OS processes (4 virtual CPU devices each) build ONE global 8-device
mesh through jax.distributed, run identical train steps, and host-gather
the weight-push tree — exercising the engine's cross-process code paths
(global mesh build, process-spanning dp, process_allgather) that
single-process tests cannot reach.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_trainer_converges_identically():
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + os.environ.get("PYTHONPATH", "")
    # each worker sets its own JAX_PLATFORMS/XLA_FLAGS before importing jax
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.join(_REPO, "tests", "multiproc_trainer_worker.py"),
                str(pid),
                coord,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    # read both pipes concurrently (a worker blocked on a full pipe while
    # the other is awaited would deadlock the collective), and always reap
    # both children even when one fails
    from concurrent.futures import ThreadPoolExecutor

    try:
        with ThreadPoolExecutor(max_workers=2) as pool:
            futs = [pool.submit(p.communicate, timeout=420) for p in procs]
            outs = [f.result()[0] for f in futs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]

    losses = []
    for out in outs:
        vals = [
            float(line.split()[2])
            for line in out.splitlines()
            if line.startswith("LOSS ")
        ]
        assert len(vals) == 4, out[-2000:]
        losses.append(vals)
        assert "GATHERED" in out
    # both ranks run the same SPMD program on the same data: identical
    # losses, and training actually progresses
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
    assert losses[0][-1] < losses[0][0]
