"""Randomized decode-scheduler stress test against a naive oracle.

The scheduler in `engine/jax_decode.py` interleaves admission budgeting,
wave-batched prefill with same-wave dup forking, partial-prefix suffix
prefill, covering-donor reuse, parked-KV resume, LRU eviction, and
pool-pressure preemption. The scenario tests pin each feature alone; this
test drives them all CONCURRENTLY with seeded randomness and checks every
completed request against a naive re-prefill oracle (step-by-step greedy
forward) — the property that makes RL rollouts trustworthy: no scheduling
interleaving may change a single emitted token.

Chaos ops (pause → weight re-install → version bump → resume, and
pause → abort_all → resume) run from a separate thread while clients use
the reference's interrupt-accumulate-resubmit protocol
(areal/engine/remote_inf_engine.py:428-478), so parked-KV resume and
post-swap re-prefill are exercised under pool pressure, not in isolation.

Weights are re-installed with IDENTICAL values, so greedy outputs are
deterministic regardless of interleaving; version stamps still bump, which
lets us assert the stamping invariants without racing the swap clock.
"""

import asyncio
import threading
import uuid
from dataclasses import replace

import numpy as np
import pytest

import jax

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.models.qwen2 import ModelConfig, forward, init_params

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

SEED = 1234
N_JOBS = 48
N_CHAOS_ROUNDS = 8


class DigitTok:
    eos_token_id = None

    def decode(self, ids):
        return "".join(str(i % 10) for i in ids)


_ORACLE_PAD = 80  # >= max prompt + max_new of any job here, ONE compile


def _make_oracle(params):
    """Step-by-step greedy continuation via the training forward pass,
    jitted ONCE at a padded length (pad rows carry a different segment id so
    the packed-attention mask isolates them); the eager per-shape version
    costs minutes across 48 jobs x 12 steps on CPU."""

    @jax.jit
    def step(ids, true_len):
        positions = np.arange(_ORACLE_PAD, dtype=np.int32)
        seg = (positions >= true_len).astype(np.int32)  # pads in segment 1
        logits = forward(params, ids, positions, seg, TINY)
        return jax.numpy.argmax(logits[true_len - 1])

    def greedy_reference(prompt, n_new):
        seq = list(prompt)
        for _ in range(n_new):
            ids = np.zeros(_ORACLE_PAD, dtype=np.int32)
            ids[: len(seq)] = seq
            # the oracle IS a per-token host sync: each step feeds the
            # emitted token back into the next python-built input
            seq.append(int(step(ids, len(seq))))  # areal-lint: disable=AR201
        return seq[len(prompt):]

    return greedy_reference


def oracle_truncate(full, gconfig):
    """Pure-python model of the engine's stop semantics: walk the greedy
    continuation token by token; stop-token ids halt inclusively at first
    occurrence; stop STRINGS halt at the earliest token boundary whose
    decoded output contains the string (cf. test_stop_strings)."""
    tok = DigitTok()
    out = []
    for t in full[: gconfig.max_new_tokens]:
        out.append(t)
        if gconfig.stop_token_ids and t in gconfig.stop_token_ids:
            return out, "stop"
        if gconfig.stop and any(s in tok.decode(out) for s in gconfig.stop):
            return out, "stop"
    return out, "length"


def _make_jobs(rng, greedy_reference):
    """Prompt families engineered to hit the sharing machinery: exact
    duplicates (same-wave dup fork / covering donor), extensions
    (partial-prefix suffix prefill), and fresh prompts, with a mix of
    stop-token / stop-string / plain termination."""
    bases = [
        [1, 5, 9, 13, 2],
        [3, 7, 11],
        [2, 4, 6, 8, 10, 12],
        [9, 9, 1, 4],
    ]
    jobs = []
    for i in range(N_JOBS):
        kind = rng.integers(0, 4)
        if kind == 0:  # exact duplicate of a base
            prompt = list(bases[rng.integers(0, len(bases))])
        elif kind == 1:  # extension of a base (partial-prefix candidate)
            b = bases[rng.integers(0, len(bases))]
            prompt = list(b) + [int(x) for x in rng.integers(1, 60, rng.integers(1, 5))]
        else:  # fresh
            prompt = [int(x) for x in rng.integers(1, 60, rng.integers(2, 8))]
        max_new = int(rng.integers(4, 13))
        full = greedy_reference(prompt, max_new)
        stop_ids, stop_strs = [], []
        style = rng.random()
        if style < 0.25:
            # a stop id guaranteed to occur (some position in the oracle)
            stop_ids = [int(full[rng.integers(1, len(full))])]
        elif style < 0.35:
            stop_ids = [63]  # vocab edge, very unlikely to occur
        elif style < 0.5:
            text = DigitTok().decode(full)
            k = int(rng.integers(1, max(2, len(text) - 1)))
            stop_strs = [text[k : k + 2]]
        g = GenerationHyperparameters(
            greedy=True,
            max_new_tokens=max_new,
            stop_token_ids=stop_ids,
            stop=stop_strs,
        )
        jobs.append(
            {
                "prompt": prompt,
                "gconfig": g,
                "full": full,
                "delay": float(rng.random() * 1.5),
            }
        )
    return jobs


async def _run_job(eng, job):
    """Client protocol: on "interrupt", accumulate partials and resubmit
    prompt+tokens under the SAME rid (parked-KV resume path). Stop-string
    jobs do not resubmit: once partial output is folded into the prompt the
    engine (by design) only scans NEW tokens for the string, so the
    cross-interrupt oracle is not defined — prefix parity is still checked.
    """
    g = job["gconfig"]
    rid = str(uuid.uuid4())
    cur_prompt = list(job["prompt"])
    remaining = g.max_new_tokens
    acc_t, acc_lp, acc_v = [], [], []
    n_interrupts = 0
    while True:
        resp = await eng.agenerate(
            ModelRequest(
                rid=rid,
                input_ids=cur_prompt,
                gconfig=replace(g, max_new_tokens=remaining),
            )
        )
        acc_t += list(resp.output_tokens)
        acc_lp += list(resp.output_logprobs)
        acc_v += list(resp.output_versions)
        if resp.stop_reason != "interrupt":
            return dict(job, tokens=acc_t, logprobs=acc_lp, versions=acc_v,
                        reason=resp.stop_reason, interrupts=n_interrupts)
        n_interrupts += 1
        if g.stop:
            return dict(job, tokens=acc_t, logprobs=acc_lp, versions=acc_v,
                        reason="interrupt", interrupts=n_interrupts)
        remaining -= resp.output_len
        cur_prompt += list(resp.output_tokens)
        if remaining <= 0:
            return dict(job, tokens=acc_t, logprobs=acc_lp, versions=acc_v,
                        reason="length", interrupts=n_interrupts)


def test_pool_pressure_preemption_runahead_paged(cpu_devices):
    """Pool-pressure preemption x run-ahead x the paged KV layout.

    Geometry: 3 distinct 8-token prompts admit together, each reserving
    the 64-token prefill bucket (8 blocks at page_size=8) — exactly the
    pool's 24 usable blocks, zero slack. Every generation runs past 64
    total tokens, so each slot eventually needs a 9th block; with no
    parked KV and no free-slot donors to reclaim, `_dispatch_chunk`'s
    ensure loop MUST go through `_preempt_slot` while
    `decode_runahead_chunks=1` keeps a speculative chunk in flight on
    the in-pool attention path. The preempted request requeues
    invisibly and re-admits with its generated tokens as coverage
    prompt — every completed stream must still match the naive greedy
    oracle token for token. CPU-sized (tiny model, 3 requests): tier-1,
    not slow."""
    params = init_params(TINY, jax.random.PRNGKey(0))
    cfg = JaxDecodeConfig(
        context_length=128,
        max_running_requests=3,
        new_tokens_per_chunk=4,
        page_size=8,
        # 24 usable blocks: 3 x 8-block admissions fit exactly; the first
        # slot to cross 64 tokens finds the pool dry and must preempt
        kv_pool_tokens=192,
        decode_runahead_chunks=1,
        kv_layout="paged",
        paged_attn_impl="xla",
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig(), tokenizer=DigitTok())
    eng.set_model(params, TINY)
    eng.initialize()
    greedy_reference = _make_oracle(params)
    rng = np.random.default_rng(SEED + 7)
    jobs = []
    for _ in range(3):
        prompt = [int(x) for x in rng.integers(1, 60, 8)]
        jobs.append(
            {
                "prompt": prompt,
                "full": greedy_reference(prompt, 60),
                "gconfig": GenerationHyperparameters(
                    greedy=True, max_new_tokens=60
                ),
            }
        )

    async def main():
        return await asyncio.gather(
            *[
                eng.agenerate(
                    ModelRequest(input_ids=j["prompt"], gconfig=j["gconfig"])
                )
                for j in jobs
            ]
        )

    try:
        results = asyncio.run(main())
        m = eng.get_metrics()
    finally:
        eng.destroy()
    for i, (j, r) in enumerate(zip(jobs, results)):
        assert r.output_tokens == j["full"], (
            f"job {i}: preemption+requeue broke greedy parity on the paged "
            f"path: {r.output_tokens} != {j['full']}"
        )
        assert r.stop_reason == "length", (i, r.stop_reason)
        assert len(r.output_logprobs) == len(r.output_tokens), i
    # the pool pressure must actually have bitten
    assert m["preemptions_total"] > 0, m
    assert m["kv_layout"] == "paged"


def test_pool_pressure_offload_swapback_runahead_spec_paged(cpu_devices):
    """Zero-slack pool + HOST TIER x run-ahead x speculation x paged.

    Same 24-usable-block geometry as the preemption test above, but with
    `kv_host_pool_mb` enabled and `spec_decode="ngram"` on: the forced
    `_preempt_slot` now OFFLOADS the victim's KV to host RAM, and the
    invisible re-admission promotes it back (fresh blocks + async
    upload) instead of re-prefilling — while runahead=1 keeps a chunk in
    flight and the drafter/verify path is live. Every completed stream
    must still match the naive greedy oracle token for token, and the
    metrics must prove the preempt -> offload -> swap-back cycle
    actually ran (nonzero swap traffic + avoided re-prefill tokens)."""
    params = init_params(TINY, jax.random.PRNGKey(0))
    cfg = JaxDecodeConfig(
        context_length=128,
        max_running_requests=3,
        new_tokens_per_chunk=4,
        page_size=8,
        kv_pool_tokens=192,
        kv_host_pool_mb=64,
        decode_runahead_chunks=1,
        kv_layout="paged",
        paged_attn_impl="xla",
        spec_decode="ngram",
        spec_k=3,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig(), tokenizer=DigitTok())
    eng.set_model(params, TINY)
    eng.initialize()
    greedy_reference = _make_oracle(params)
    rng = np.random.default_rng(SEED + 7)
    jobs = []
    for _ in range(3):
        prompt = [int(x) for x in rng.integers(1, 60, 8)]
        jobs.append(
            {
                "prompt": prompt,
                "full": greedy_reference(prompt, 60),
                "gconfig": GenerationHyperparameters(
                    greedy=True, max_new_tokens=60
                ),
            }
        )

    async def main():
        return await asyncio.gather(
            *[
                eng.agenerate(
                    ModelRequest(input_ids=j["prompt"], gconfig=j["gconfig"])
                )
                for j in jobs
            ]
        )

    try:
        results = asyncio.run(main())
        m = eng.get_metrics()
    finally:
        eng.destroy()
    for i, (j, r) in enumerate(zip(jobs, results)):
        assert r.output_tokens == j["full"], (
            f"job {i}: preempt->offload->swap-back broke greedy parity: "
            f"{r.output_tokens} != {j['full']}"
        )
        assert r.stop_reason == "length", (i, r.stop_reason)
        assert len(r.output_logprobs) == len(r.output_tokens), i
    # the whole tiered lifecycle must actually have run
    assert m["preemptions_total"] > 0, m
    assert m["kv_swap_out_bytes_total"] > 0, m
    assert m["kv_swap_in_bytes_total"] > 0, m
    assert m["kv_host_hits_total"] > 0, m
    assert m["reprefill_tokens_avoided_total"] > 0, m
    assert m["spec_chunks_total"] > 0, m  # speculation was live throughout


@pytest.mark.slow
def test_randomized_scheduler_greedy_parity(cpu_devices):
    rng = np.random.default_rng(SEED)
    params = init_params(TINY, jax.random.PRNGKey(0))
    cfg = JaxDecodeConfig(
        context_length=96,
        max_running_requests=3,
        new_tokens_per_chunk=4,
        page_size=16,
        # ~2 full slots' worth of blocks for 3 running slots + parked KV:
        # admission must preempt/evict under load
        kv_pool_tokens=160,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig(), tokenizer=DigitTok())
    eng.set_model(params, TINY)
    eng.initialize()

    jobs = _make_jobs(rng, _make_oracle(params))
    results = []
    job_err = []
    versions_set = [0]
    done = threading.Event()

    async def _main():
        async def delayed(j):
            await asyncio.sleep(j["delay"])
            return await _run_job(eng, j)

        return await asyncio.gather(*[delayed(j) for j in jobs])

    def loop_thread():
        try:
            results.extend(asyncio.run(_main()))
        except BaseException as e:  # noqa: BLE001
            job_err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=loop_thread, daemon=True)
    try:
        t.start()
        # chaos: interleave weight re-installs (identical values, version
        # bump) and abort_all storms while jobs are in flight
        chaos_rng = np.random.default_rng(SEED + 1)
        for round_i in range(N_CHAOS_ROUNDS):
            if done.wait(0.35 + float(chaos_rng.random()) * 0.4):
                break
            eng.pause_generation()
            try:
                if round_i % 2 == 0:
                    eng.abort_all()
                else:
                    eng.update_weights_from_distributed(None, params=params)
                    v = versions_set[-1] + 1
                    eng.set_version(v)
                    versions_set.append(v)
            finally:
                eng.continue_generation()
        assert done.wait(600), "stress jobs did not finish in 600s"
        if job_err:
            raise job_err[0]
    finally:
        done.wait(5)
        eng.destroy()

    assert len(results) == N_JOBS
    n_interrupted = sum(r["interrupts"] > 0 for r in results)
    for i, r in enumerate(results):
        exp_tokens, exp_reason = oracle_truncate(r["full"], r["gconfig"])
        if r["reason"] == "interrupt":
            # stop-string job cut short: oracle prefix parity only
            assert r["tokens"] == exp_tokens[: len(r["tokens"])], i
        else:
            assert r["tokens"] == exp_tokens, (
                f"job {i}: greedy parity broken under scheduling chaos: "
                f"{r['tokens']} != {exp_tokens}"
            )
            assert r["reason"] == exp_reason, (i, r["reason"], exp_reason)
        # stamping invariants: one version+logprob per token, versions
        # non-decreasing across interrupt resumes, all from set_version
        assert len(r["versions"]) == len(r["tokens"]), i
        assert len(r["logprobs"]) == len(r["tokens"]), i
        assert all(v in versions_set for v in r["versions"]), i
        assert r["versions"] == sorted(r["versions"]), i
        assert all(np.isfinite(lp) and lp <= 1e-6 for lp in r["logprobs"]), i
    # the chaos must have actually bitten: some jobs interrupted, some
    # preemptions or parked evictions occurred under the tiny pool
    m = eng.get_metrics()
    assert n_interrupted > 0, "abort storms never interrupted a job"
    assert m["preemptions_total"] + m["prefix_forks_total"] > 0, m
