"""AEnt clamped-entropy regularization (parity: recipe/AEnt/).

Covers the clamped-entropy math (dense + fused-head token-chunked paths),
the GRPO-loss bonus's effect on measured entropy, and the adaptive
coefficient controller.
"""

import numpy as np
import pytest

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    MicroBatchSpec,
    OptimizerConfig,
    PPOActorConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.engine.ppo.actor import JaxPPOActor
from areal_tpu.models.qwen2 import ModelConfig

TINY = ModelConfig(
    vocab_size=32,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)


def _clamped_entropy_oracle(logits: np.ndarray, clamp: float, temp: float = 1.0):
    """Reference semantics (recipe/AEnt/functional.py:16): mask the
    floor(V*clamp) smallest logits to -inf, renormalize, entropy."""
    x = logits.astype(np.float64) / temp
    k = int(x.shape[-1] * clamp)
    out = np.empty(x.shape[:-1])
    for idx in np.ndindex(*x.shape[:-1]):
        row = x[idx].copy()
        order = np.argsort(row, kind="stable")
        row[order[:k]] = -np.inf
        row -= row.max()
        p = np.exp(row)
        p /= p.sum()
        lp = np.where(p > 0, np.log(np.clip(p, 1e-300, None)), 0.0)
        out[idx] = -np.sum(p * lp)
    return out


def test_clamped_entropy_matches_oracle(cpu_devices):
    from areal_tpu.utils.functional import clamped_softmax_entropy

    rng = np.random.RandomState(0)
    logits = rng.randn(5, 40).astype(np.float32) * 3
    for clamp, temp in [(0.2, 1.0), (0.5, 0.7), (0.0, 1.0)]:
        got = np.asarray(clamped_softmax_entropy(logits, clamp, temp))
        want = _clamped_entropy_oracle(logits, clamp, temp)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_clamped_entropy_fused_matches_dense(cpu_devices):
    import jax.numpy as jnp

    from areal_tpu.ops.fused_xent import chunked_clamped_entropy
    from areal_tpu.utils.functional import clamped_softmax_entropy

    rng = np.random.RandomState(1)
    T, H, V = 50, 16, 64  # T deliberately not a multiple of token_chunk
    hidden = rng.randn(T, H).astype(np.float32)
    w_hv = rng.randn(H, V).astype(np.float32)
    dense = clamped_softmax_entropy(jnp.asarray(hidden) @ jnp.asarray(w_hv), 0.25)
    fused = chunked_clamped_entropy(
        jnp.asarray(hidden), jnp.asarray(w_hv), head_is_vh=False,
        entropy_clamp=0.25, token_chunk=16,
    )
    np.testing.assert_allclose(np.asarray(fused), np.asarray(dense), rtol=1e-5)
    fused_vh = chunked_clamped_entropy(
        jnp.asarray(hidden), jnp.asarray(w_hv.T), head_is_vh=True,
        entropy_clamp=0.25, token_chunk=16,
    )
    np.testing.assert_allclose(np.asarray(fused_vh), np.asarray(dense), rtol=1e-5)


def test_clamped_entropy_gradient_only_through_kept(cpu_devices):
    """The bonus must be differentiable w.r.t. kept logits; removed-tail
    entries get no gradient (their mask is stop_gradient'd)."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.utils.functional import clamped_softmax_entropy

    logits = jnp.asarray(np.linspace(-4, 4, 8, dtype=np.float32))[None, :]
    g = jax.grad(lambda x: clamped_softmax_entropy(x, 0.25).sum())(logits)
    g = np.asarray(g)[0]
    assert np.all(g[:2] == 0.0), g  # the 2 smallest logits were clamped out
    assert np.any(g[2:] != 0.0), g


def _actor(**overrides):
    kw = dict(
        experiment_name="t",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
        optimizer=OptimizerConfig(
            lr=5e-3, warmup_steps_proportion=0.0, lr_scheduler_type="constant"
        ),
        gradient_checkpointing=False,
        group_size=2,
        ppo_n_minibatches=1,
        eps_clip=0.2,
        kl_ctl=0.0,
        use_decoupled_loss=False,
        recompute_logprob=True,
        temperature=1.0,
    )
    kw.update(overrides)
    actor = JaxPPOActor(PPOActorConfig(**kw))
    actor.model_config = TINY
    actor.create_process_group(ParallelStrategy(data_parallel_size=8))
    actor.initialize(None, FinetuneSpec(1, 64, 8))
    return actor


def _synthetic_batch():
    B, T = 4, 8
    ids = np.zeros((B, T), dtype=np.int64)
    ids[:, :3] = [1, 2, 3]
    ids[0, 3:] = 16
    ids[1, 3:] = 5
    ids[2, 3:] = 16
    ids[3, 3:] = 5
    return dict(
        input_ids=ids,
        attention_mask=np.ones((B, T), dtype=np.int64),
        loss_mask=np.pad(np.ones((B, 5), np.int64), ((0, 0), (3, 0))),
        rewards=np.array([1.0, 0.0, 1.0, 0.0], dtype=np.float32),
        logprobs=np.zeros((B, T), dtype=np.float32),
    )


def _final_entropy(actor, steps=6):
    ent = None
    for _ in range(steps):
        batch = _synthetic_batch()
        batch["prox_logp"] = actor.compute_logp(batch)
        actor.compute_advantages(batch)
        stats = actor.ppo_update(batch)[0]
        ent = next(v for k, v in stats.items() if k.endswith("entropy"))
    return ent


@pytest.mark.slow
def test_entropy_bonus_raises_entropy(cpu_devices):
    """Ablation: same init/data, entropy_coeff>0 must land at visibly
    higher policy entropy than coeff=0 (the AEnt claim)."""
    # clamp on both so the logged metric is the same (clamped) entropy
    plain = _final_entropy(_actor(entropy_coeff=0.0, entropy_clamp=0.25))
    bonus = _final_entropy(
        _actor(entropy_coeff=0.5, entropy_clamp=0.25)
    )
    assert bonus > plain + 0.05, (plain, bonus)


@pytest.mark.slow
def test_adaptive_coeff_reaches_loss_as_traced_operand(cpu_devices):
    """Adaptive mode feeds the coefficient through the batch as a traced
    token-aligned operand. Two checks: (a) the host-side controller moves
    the coefficient, and (b) the operand actually lands in the loss — the
    static coeff baked into the jit is 0.0 here, so any entropy response
    must have traveled through the batch."""

    def run(forced_coeff):
        actor = _actor(
            entropy_coeff=0.0,  # static partial contributes nothing
            entropy_clamp=0.25,
            adaptive_entropy_coeff=True,
            entropy_coeff_lr=0.0,  # freeze: isolate the operand's effect
            entropy_coeff_box_low=0.0,
            entropy_coeff_box_high=10.0,
        )
        actor.actor.entropy_coeff = forced_coeff
        ent = None
        for _ in range(6):
            batch = _synthetic_batch()
            batch["prox_logp"] = actor.compute_logp(batch)
            actor.compute_advantages(batch)
            stats = actor.ppo_update(batch)[0]
            ent = next(v for k, v in stats.items() if k.endswith("entropy"))
        return ent

    assert run(0.5) > run(0.0) + 0.05

    # (a) controller direction: entropy below the band raises the coeff
    actor = _actor(
        entropy_coeff=5e-3,
        adaptive_entropy_coeff=True,
        entropy_low=5.0,
        entropy_high=50.0,
        entropy_coeff_lr=1e-3,
        entropy_coeff_box_high=0.05,
    )
    coeffs = []
    for _ in range(2):
        batch = _synthetic_batch()
        batch["prox_logp"] = actor.compute_logp(batch)
        actor.compute_advantages(batch)
        actor.ppo_update(batch)
        coeffs.append(actor.actor.entropy_coeff)
    assert coeffs[0] < coeffs[1] <= 0.05, coeffs


def test_adaptive_coeff_controller(cpu_devices):
    actor = _actor(
        entropy_coeff=5e-3,
        adaptive_entropy_coeff=True,
        entropy_low=0.1,
        entropy_high=0.5,
        entropy_coeff_lr=0.01,
        entropy_coeff_box_low=1e-5,
        entropy_coeff_box_high=0.01,
        entropy_warmup_steps=1,
    ).actor
    # warmup: no change
    actor._update_steps = 1
    actor._adapt_entropy_coeff(0.01)
    assert actor.entropy_coeff == 5e-3
    # low entropy -> coeff rises (clipped by box_high)
    actor._update_steps = 2
    actor._adapt_entropy_coeff(0.0)
    assert actor.entropy_coeff == pytest.approx(6e-3)
    # high entropy -> coeff falls, clipped at box_low
    actor._adapt_entropy_coeff(5.0)
    assert actor.entropy_coeff == pytest.approx(1e-5)
