"""Remote batch verification (parity: /root/reference/functioncall/ —
client batching/retries + the service the reference assumes externally)."""

import asyncio
import threading

import pytest

from areal_tpu.reward.remote_verify import (
    batch_code_verify,
    batch_math_verify,
    grade_code_batch,
    grade_math_batch,
)

MATH_INFO = {
    "m1": dict(solutions=[r"\boxed{\frac{1}{2}}"]),
    "m2": dict(solutions=["4", "four"]),
}
CODE_INFO = {
    "c1": dict(
        input_output=dict(inputs=["3 4\n"], outputs=["7\n"], fn_name="")
    ),
}
GOOD_CODE = "```python\na, b = map(int, input().split())\nprint(a + b)\n```"
BAD_CODE = "```python\nprint(0)\n```"


def test_local_fallback_math(monkeypatch):
    monkeypatch.delenv("AREAL_VERIFIER_SERVICE", raising=False)
    monkeypatch.delenv("FUNCTIONCALL_SERVICE_DOMAIN", raising=False)
    out = batch_math_verify(
        MATH_INFO,
        [r"so \boxed{0.5}", r"\boxed{3}", "the answer is 4"],
        ["m1", "m1@idx:0", "m2"],
    )
    assert out == [1, 0, 1]


def test_local_fallback_code(monkeypatch):
    monkeypatch.delenv("AREAL_VERIFIER_SERVICE", raising=False)
    monkeypatch.delenv("FUNCTIONCALL_SERVICE_DOMAIN", raising=False)
    out = batch_code_verify(
        CODE_INFO, [GOOD_CODE, BAD_CODE], ["c1", "c1@1"]
    )
    assert out == [1, 0]


@pytest.fixture
def verify_service():
    """A real VerifyServer on a private loop thread."""
    from areal_tpu.reward.verify_server import VerifyServer

    srv = VerifyServer(max_workers=2)
    loop = asyncio.new_event_loop()
    addr_box = {}

    def run():
        asyncio.set_event_loop(loop)
        addr_box["addr"] = loop.run_until_complete(srv.start("127.0.0.1", 0))
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = 50
    while "addr" not in addr_box and deadline:
        import time

        time.sleep(0.1)
        deadline -= 1
    assert "addr" in addr_box, "verify server failed to start"
    yield addr_box["addr"]
    asyncio.run_coroutine_threadsafe(srv.stop(), loop).result(timeout=10)
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=10)


def test_service_round_trip(monkeypatch, verify_service):
    monkeypatch.setenv("AREAL_VERIFIER_SERVICE", verify_service)
    out = batch_math_verify(
        MATH_INFO, [r"\boxed{1/2}", r"\boxed{9}"], ["m1", "m1@2"]
    )
    assert out == [1, 0]
    out = batch_code_verify(CODE_INFO, [GOOD_CODE], ["c1"])
    assert out == [1]


def test_service_down_degrades_to_local(monkeypatch):
    # nothing listens here: the client must retry, then grade locally,
    # never zeroing out rewards
    monkeypatch.setenv("AREAL_VERIFIER_SERVICE", "127.0.0.1:1")
    out = batch_math_verify(MATH_INFO, ["the answer is 4"], ["m2"])
    assert out == [1]


def test_grade_batches_direct():
    assert grade_math_batch([r"\boxed{2/4}"], [r"\frac{1}{2}"]) == [1]
    assert grade_code_batch(
        [dict(completion=GOOD_CODE, input_output=CODE_INFO["c1"]["input_output"])]
    ) == [1]
