"""Param realloc as GSPMD resharding (parity: realhf param_realloc.py's
train->gen topology moves + the eta-mixing hook, re-expressed as
device_put; SURVEY.md §2.3 notes interval_op is subsumed this way)."""

import numpy as np

import jax

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.models.qwen2 import ModelConfig, init_params
from areal_tpu.parallel.resharding import (
    eta_mix,
    reshard_to_strategy,
    shardings_for,
)

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)


def test_reshard_train_to_gen_topology(cpu_devices):
    """The reference's flagship realloc shape: train d4t2 -> a smaller
    gen topology on a device subset (disjoint layouts). Values must be
    bit-identical; layouts must match the target."""
    params = init_params(TINY, jax.random.PRNGKey(0))
    train_params, _, train_sh = reshard_to_strategy(
        params,
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2),
        TINY,
    )
    gen_devices = cpu_devices[:2]
    gen_params, gen_mesh, gen_sh = reshard_to_strategy(
        train_params,
        ParallelStrategy(tensor_parallel_size=2),
        TINY,
        devices=gen_devices,
        fsdp=False,
    )
    # target layout applied...
    q = gen_params["layers"]["attn"]["q_kernel"]
    assert q.sharding == gen_sh["layers"]["attn"]["q_kernel"]
    assert set(q.sharding.device_set) <= set(gen_devices)
    # ...and values survived the topology change exactly
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(gen_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_reshard_into_pp_layout(cpu_devices):
    """Resharding into a pp=2 strategy lands the scanned layer stack on the
    pp axis (the pipeline engine's expected layout)."""
    params = init_params(TINY, jax.random.PRNGKey(1))
    out, mesh, sh = reshard_to_strategy(
        params,
        ParallelStrategy(
            pipeline_parallel_size=2,
            data_parallel_size=2,
            tensor_parallel_size=2,
        ),
        TINY,
    )
    spec = out["layers"]["attn"]["q_kernel"].sharding.spec
    assert spec[0] == "pp", spec


def test_eta_mix(cpu_devices):
    """target <- eta*src + (1-eta)*target across different layouts."""
    a = init_params(TINY, jax.random.PRNGKey(2))
    b = init_params(TINY, jax.random.PRNGKey(3))
    ta, _, _ = reshard_to_strategy(
        a, ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2), TINY
    )
    tb, _, _ = reshard_to_strategy(
        b, ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2),
        TINY, fsdp=False
    )
    mixed = eta_mix(ta, tb, eta=0.25)
    la, lb, lm = (
        jax.tree.leaves(a),
        jax.tree.leaves(b),
        jax.tree.leaves(mixed),
    )
    for x, y, m in zip(la, lb, lm):
        np.testing.assert_allclose(
            np.asarray(m),
            0.25 * np.asarray(y) + 0.75 * np.asarray(x),
            rtol=1e-6,
            atol=1e-7,
        )
    # eta=1 is a pure reshard of src onto target's layout
    full = eta_mix(ta, tb, eta=1.0)
    for y, m in zip(lb, jax.tree.leaves(full)):
        np.testing.assert_array_equal(np.asarray(y), np.asarray(m))
