"""Experiment status broadcast + decode-server self-termination watch
(ref: realhf/system/master_worker.py:485-495 ExpStatus)."""

import threading
import time

from areal_tpu.api.cli_args import NameResolveConfig
from areal_tpu.utils import name_resolve
from areal_tpu.utils.experiment import (
    ExpStatus,
    get_status,
    publish_status,
    watch_until_terminal,
)


def setup_function(_fn):
    name_resolve.reconfigure(NameResolveConfig(type="memory"))


def test_publish_get_round_trip():
    assert get_status("e", "t") is None
    publish_status("e", "t", ExpStatus.RUNNING)
    assert get_status("e", "t") == ExpStatus.RUNNING
    publish_status("e", "t", "COMPLETE")
    assert get_status("e", "t") == ExpStatus.COMPLETE


def test_watcher_fires_once_on_terminal_status():
    fired = []
    t = watch_until_terminal(
        "e2", "t2", lambda s: fired.append(s), poll_interval=0.05
    )
    publish_status("e2", "t2", ExpStatus.RUNNING)
    time.sleep(0.2)
    assert fired == []  # RUNNING is not terminal
    publish_status("e2", "t2", ExpStatus.ABORTED)
    t.join(timeout=5)
    assert fired == [ExpStatus.ABORTED]
    assert not t.is_alive()


def test_watcher_stop_event():
    ev = threading.Event()
    t = watch_until_terminal(
        "e3", "t3", lambda s: None, poll_interval=0.05, stop_event=ev
    )
    ev.set()
    t.join(timeout=5)
    assert not t.is_alive()


def test_stale_terminal_ignored_until_running_seen():
    """A relaunched fleet must not die on the PREVIOUS run's persistent
    terminal status (review regression)."""
    publish_status("e4", "t4", ExpStatus.COMPLETE)  # stale, previous run
    fired = []
    t = watch_until_terminal(
        "e4", "t4", lambda s: fired.append(s), poll_interval=0.05
    )
    time.sleep(0.25)
    assert fired == []  # stale COMPLETE ignored
    publish_status("e4", "t4", ExpStatus.RUNNING)
    time.sleep(0.2)
    publish_status("e4", "t4", ExpStatus.COMPLETE)
    t.join(timeout=5)
    assert fired == [ExpStatus.COMPLETE]
