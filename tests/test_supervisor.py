"""Fleet supervisor (ISSUE 13): the pure planner's invariants — hysteresis
bands, per-action cooldowns, the min-capacity floor, crash-loop gating,
replace/re-role priority — plus executor-level crash-loop escalation and
dead-replica replacement against stub handles (no HTTP, no jax)."""

import asyncio
import threading

import pytest

from areal_tpu.api.cli_args import SupervisorConfig
from areal_tpu.launcher.supervisor import (
    FleetSnapshot,
    FleetSupervisor,
    ReplicaView,
    plan_actions,
)


def _pol(**kw):
    base = dict(
        min_replicas=1,
        max_replicas=8,
        util_inflight_target=8,
        scale_up_util=0.85,
        scale_down_util=0.30,
        scale_up_queue_depth=4,
        scale_up_cooldown_s=2.0,
        scale_down_cooldown_s=20.0,
        replace_cooldown_s=2.0,
        rerole_cooldown_s=30.0,
        spawn_max_attempts=3,
        rerole_enabled=True,
        rerole_band=0.25,
    )
    base.update(kw)
    return SupervisorConfig(**base)


def _fleet(n, roles=None, loads=None, alive=None, breakers=None):
    roles = roles or ["unified"] * n
    loads = loads or [0.0] * n
    alive = alive if alive is not None else [True] * n
    breakers = breakers or ["closed"] * n
    return tuple(
        ReplicaView(
            addr=f"r{i}:1",
            alive=alive[i],
            role=roles[i],
            breaker_state=breakers[i],
            load=loads[i],
        )
        for i in range(n)
    )


def _snap(**kw):
    base = dict(now=1000.0, replicas=_fleet(2))
    base.update(kw)
    return FleetSnapshot(**base)


# (name, snapshot, policy, expected-kind-or-None, extra-check)
PLAN_TABLE = [
    (
        "dead_band_plans_nothing",  # hysteresis: between the marks = hold
        _snap(util=0.5),
        _pol(),
        None,
        None,
    ),
    (
        "scale_up_on_queue_depth",
        _snap(queue_depth=4),
        _pol(),
        "scale_up",
        lambda a: a.role == "unified",
    ),
    (
        "scale_up_on_util_high_mark",
        _snap(util=0.9),
        _pol(),
        "scale_up",
        None,
    ),
    (
        "scale_up_on_sheds",
        _snap(shed_rate=2.0),
        _pol(),
        "scale_up",
        None,
    ),
    (
        "scale_up_respects_cooldown",
        _snap(util=0.9, last_action_t={"scale_up": 999.0}),
        _pol(scale_up_cooldown_s=2.0),
        None,
        None,
    ),
    (
        "scale_up_cooldown_elapsed",
        _snap(util=0.9, last_action_t={"scale_up": 997.0}),
        _pol(scale_up_cooldown_s=2.0),
        "scale_up",
        None,
    ),
    (
        "scale_up_capped_at_max",
        _snap(util=2.0, replicas=_fleet(3)),
        _pol(max_replicas=3),
        None,
        None,
    ),
    (
        "scale_up_waits_for_pending_spawn",
        _snap(util=2.0, pending_spawns=1),
        _pol(),
        None,
        None,
    ),
    (
        # crash-loop escalation: after spawn_max_attempts consecutive
        # failures the planner STOPS buying capacity — degraded > fork bomb
        "crash_loop_gives_up_after_n_attempts",
        _snap(util=2.0, queue_depth=50, spawn_failures=3),
        _pol(spawn_max_attempts=3),
        None,
        None,
    ),
    (
        "crash_loop_not_yet_final_attempt_still_spawns",
        _snap(util=2.0, spawn_failures=2),
        _pol(spawn_max_attempts=3),
        "scale_up",
        None,
    ),
    (
        "scale_down_when_idle_picks_least_loaded",
        _snap(util=0.1, replicas=_fleet(3, loads=[5.0, 1.0, 3.0])),
        _pol(scale_down_util=0.30),
        "scale_down",
        lambda a: a.target == "r1:1",
    ),
    (
        # the min-capacity floor no plan may violate
        "scale_down_blocked_at_floor",
        _snap(util=0.0, replicas=_fleet(2)),
        _pol(min_replicas=2),
        None,
        None,
    ),
    (
        "scale_down_respects_cooldown",
        _snap(util=0.0, replicas=_fleet(3), last_action_t={"scale_down": 990.0}),
        _pol(scale_down_cooldown_s=20.0),
        None,
        None,
    ),
    (
        # the global settle window: a just-finished replace resets the
        # scale-down clock even though no scale_down ever ran, so the
        # replacement's zero load can't read as fleet idleness
        "scale_down_blocked_right_after_replace",
        _snap(util=0.0, replicas=_fleet(3), last_action_t={"replace": 999.5}),
        _pol(scale_down_cooldown_s=2.0),
        None,
        None,
    ),
    (
        "scale_down_blocked_by_queue",
        _snap(util=0.1, queue_depth=1, replicas=_fleet(3)),
        _pol(),
        None,
        None,
    ),
    (
        "disruptive_single_flight",
        _snap(util=0.0, replicas=_fleet(3), disruptive_inflight=True),
        _pol(),
        None,
        None,
    ),
    (
        # restoring promised capacity beats every optimization
        "replace_dead_wins_over_scale_up",
        _snap(util=2.0, queue_depth=50, replicas=_fleet(3, alive=[True, False, True])),
        _pol(),
        "replace",
        lambda a: a.target == "r1:1" and a.reason == "dead",
    ),
    (
        "replace_breaker_open",
        _snap(replicas=_fleet(2, breakers=["closed", "open"])),
        _pol(),
        "replace",
        lambda a: a.target == "r1:1" and a.reason == "breaker_open",
    ),
    (
        "replace_respects_cooldown",
        _snap(replicas=_fleet(2, alive=[True, False]), last_action_t={"replace": 999.5}),
        _pol(replace_cooldown_s=2.0),
        None,
        None,
    ),
    (
        # mix shift: rebalancing existing capacity beats buying more,
        # even under scale-up pressure
        "rerole_wins_over_scale_up_on_mix_shift",
        _snap(
            util=0.9,
            prefill_share=0.7,
            replicas=_fleet(
                4,
                roles=["prefill", "decode", "decode", "decode"],
                loads=[0.0, 3.0, 1.0, 2.0],
            ),
        ),
        _pol(rerole_band=0.25),
        "rerole",
        lambda a: a.target == "r2:1" and a.role == "prefill",
    ),
    (
        "rerole_band_holds_then_pressure_scales_up_decode",
        _snap(
            util=0.9,
            prefill_share=0.4,  # |0.4 - 0.25| < band: inside hysteresis
            replicas=_fleet(4, roles=["prefill", "decode", "decode", "decode"]),
        ),
        _pol(rerole_band=0.25),
        "scale_up",
        lambda a: a.role == "decode",
    ),
    (
        # a fleet of only prefill replicas can decode nothing
        "rerole_never_flips_last_decode",
        _snap(
            util=0.5,  # dead band, so the only possible plan is a rerole
            prefill_share=1.0,
            replicas=_fleet(2, roles=["prefill", "decode"]),
        ),
        _pol(),
        None,
        None,
    ),
    (
        "rerole_flips_prefill_back_to_decode",
        _snap(
            prefill_share=0.0,
            replicas=_fleet(2, roles=["prefill", "decode"]),
        ),
        _pol(),
        "rerole",
        lambda a: a.target == "r0:1" and a.role == "decode",
    ),
    (
        "rerole_needs_disaggregated_fleet",
        _snap(util=0.5, prefill_share=0.9, replicas=_fleet(3)),
        _pol(),
        None,
        None,
    ),
    (
        "rerole_disabled_by_policy",
        _snap(
            util=0.5,
            prefill_share=0.7,
            replicas=_fleet(4, roles=["prefill", "decode", "decode", "decode"]),
        ),
        _pol(rerole_enabled=False),
        None,
        None,
    ),
]


@pytest.mark.parametrize(
    "name,snap,pol,expected,check",
    PLAN_TABLE,
    ids=[c[0] for c in PLAN_TABLE],
)
def test_plan_actions_table(name, snap, pol, expected, check):
    plan = plan_actions(snap, pol)
    assert len(plan) <= 1, f"{name}: more than one action per tick: {plan}"
    if expected is None:
        assert plan == [], f"{name}: expected no action, got {plan}"
    else:
        assert plan and plan[0].kind == expected, f"{name}: {plan}"
        if check is not None:
            assert check(plan[0]), f"{name}: {plan[0]}"


def test_plan_actions_is_pure():
    """Same frozen snapshot in, same plan out — no hidden state."""
    snap = _snap(util=0.9)
    pol = _pol()
    assert plan_actions(snap, pol) == plan_actions(snap, pol)


def test_min_floor_never_violated_under_sweep():
    """Property sweep: across a grid of pressures, no plan ever retires a
    replica when the fleet sits at (or below) the floor, and no plan ever
    contains more than one action."""
    pol = _pol(min_replicas=2)
    for n in (1, 2):
        for util in (0.0, 0.1, 0.3, 0.5, 0.9, 2.0):
            for queue in (0, 4, 50):
                for shed in (0.0, 1.0):
                    plan = plan_actions(
                        _snap(
                            replicas=_fleet(n),
                            util=util,
                            queue_depth=queue,
                            shed_rate=shed,
                        ),
                        pol,
                    )
                    assert len(plan) <= 1
                    assert all(a.kind != "scale_down" for a in plan), (
                        n, util, queue, shed, plan,
                    )


# -- executor: crash-loop escalation + replace against stub handles ---------


class _Handle:
    def __init__(self, addr):
        self.addr = addr
        self.killed = threading.Event()

    def kill(self):
        self.killed.set()


def _run(coro, timeout=60):
    result = {}

    def go():
        result["v"] = asyncio.run(coro)

    t = threading.Thread(target=go, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "supervisor scenario timed out"
    return result.get("v")


async def _settle_spawns(sup):
    for _ in range(200):
        await asyncio.sleep(0.005)
        if not any(s.spawning for s in sup._slots.values()):
            return
    raise AssertionError("spawn tasks never settled")


async def _scenario_crash_loop():
    clock = {"t": 100.0}
    attempts = []

    def spawn_fn(role):
        attempts.append(role)
        raise RuntimeError("broken image")

    cfg = SupervisorConfig(
        min_replicas=1,
        max_replicas=4,
        spawn_max_attempts=3,
        spawn_backoff_s=0.01,
        spawn_backoff_max_s=0.02,
        spawn_backoff_jitter=0.0,
        scale_up_cooldown_s=0.0,
        scale_up_queue_depth=1,
    )
    sup = FleetSupervisor(
        "127.0.0.1:1", spawn_fn, config=cfg, time_fn=lambda: clock["t"]
    )

    async def router():
        return {"queue_depth": 10}  # permanent pressure

    async def healths():
        return []

    sup._poll_router = router
    sup._poll_healths = healths

    for _ in range(20):
        await sup._tick()
        await _settle_spawns(sup)
        clock["t"] += 1.0
        if sup.get_metrics()["crash_loops_total"]:
            break
    m = sup.get_metrics()
    assert m["crash_loops_total"] == 1
    assert m["spawn_failures_total"] == 3
    assert len(attempts) == 3  # gave up after N — no fourth retry

    # degraded steady state: pressure persists, but the crash-looped slot
    # gates any further buying — the loop must not fork-bomb
    for _ in range(5):
        await sup._tick()
        await _settle_spawns(sup)
        clock["t"] += 1.0
    m = sup.get_metrics()
    assert len(attempts) == 3
    assert m["scale_ups_total"] == 1
    assert m["crash_looped_slots"] == 1
    return True


def test_executor_crash_loop_gives_up_and_degrades():
    assert _run(_scenario_crash_loop())


async def _scenario_replace_dead():
    clock = {"t": 100.0}
    spawned = []

    def spawn_fn(role):
        h = _Handle(f"new{len(spawned)}:1")
        spawned.append(h)
        return h

    cfg = SupervisorConfig(
        # floor == fleet size: the idle fleet must NOT plan a scale-down
        # while we watch the replace path (replace is always allowed)
        min_replicas=2,
        max_replicas=4,
        spawn_max_attempts=3,
        spawn_backoff_s=0.01,
        replace_cooldown_s=0.0,
        health_fail_threshold=2,
    )
    sup = FleetSupervisor(
        "127.0.0.1:1", spawn_fn, config=cfg, time_fn=lambda: clock["t"]
    )
    dead, ok = _Handle("dead:1"), _Handle("ok:1")
    sup.adopt(dead)
    sup.adopt(ok)

    async def router():
        return {}

    async def healths():
        # dead:1 fails every probe; everything else (incl. a respawned
        # handle) reports healthy
        return [
            (s.slot_id, s.addr != "dead:1")
            for s in sup._slots.values()
            if s.handle is not None
        ]

    sup._poll_router = router
    sup._poll_healths = healths

    for _ in range(30):
        await sup._tick()
        await _settle_spawns(sup)
        if sup._disruptive_task is not None:
            # the replace runs as a task: let it finish before advancing
            await sup._disruptive_task
        clock["t"] += 1.0
        m = sup.get_metrics()
        # gauges lag one tick (the disruptive task runs after the
        # snapshot), so gate on the live slot table, not the gauges
        if m["replacements_total"] >= 1 and all(
            s.handle is not None for s in sup._slots.values()
        ):
            break
    await sup._tick()  # refresh gauges with the respawned handle
    m = sup.get_metrics()
    assert m["replacements_total"] == 1
    assert m["kills_total"] == 1
    assert dead.killed.is_set()
    assert not ok.killed.is_set()  # the healthy replica was untouched
    assert m["fleet_alive"] == 2
    addrs = {s.addr for s in sup._slots.values()}
    assert addrs == {"new0:1", "ok:1"}
    return True


def test_executor_replaces_dead_replica_and_respawns():
    assert _run(_scenario_replace_dead())


async def _scenario_endpoint():
    def spawn_fn(role):  # pragma: no cover — never called
        raise AssertionError("no spawn expected")

    sup = FleetSupervisor("127.0.0.1:1", spawn_fn, config=SupervisorConfig())

    async def router():
        return {}

    sup._poll_router = router
    addr = await sup.start(host="127.0.0.1", port=0)
    try:
        from areal_tpu.utils.http import (
            arequest_with_retry,
            close_current_session,
        )

        h = await arequest_with_retry(addr, "/health", method="GET")
        assert h["status"] == "ok"
        body = await arequest_with_retry(addr, "/supervisor", method="GET")
        # counters + gauges + slot table ride on one endpoint
        for key in (
            "scale_ups_total",
            "scale_downs_total",
            "replacements_total",
            "reroles_total",
            "crash_loops_total",
            "drain_rollbacks_total",
            "fleet_alive",
            "replica_seconds",
            "slots",
        ):
            assert key in body, key
        assert body["slots"] == []
        await close_current_session()
    finally:
        await sup.stop()
    return True


def test_supervisor_endpoint_serves_counters_and_gauges():
    assert _run(_scenario_endpoint())
