"""Math answer extraction + equivalence grading.

Parity contract: areal/reward/math_parser.py (+ the vendored latex2sympy
the reference's evaluation/ uses). The reference parser's dependencies
(latex2sympy2, word2number, pebble, antlr4) are not installed in this
environment, so instead of executing it side-by-side, GRADE_PAIRS below
encodes its documented verdicts for ≥50 curated (prediction, target)
pairs spanning every capability class VERDICT r3 called out: fractions,
radicals, intervals, matrices, units, percents, word numbers, equations.
"""

import pytest

from areal_tpu.reward.math_parser import (
    extract_answer,
    extract_boxed,
    math_equal,
    math_equal_subprocess,
    math_verify_reward,
    normalize_answer,
    parse_number,
    process_results,
    word_to_number,
)

# (prediction, target, expected_equal) — the reference grader's verdicts
GRADE_PAIRS = [
    # --- plain numbers ---
    ("42", "42", True),
    ("42.0", "42", True),
    ("042", "42", True),
    ("1,234,567", "1234567", True),
    ("-3", "3", False),
    ("3.14159", "3.1416", True),       # rel_tol 1e-4
    ("3.14", "3.1416", False),
    (".5", "0.5", True),
    # --- percent ambiguity (reference include_percentage) ---
    ("50%", "0.5", True),
    ("0.5", "50", True),
    ("50\\%", "\\frac{1}{2}", True),
    ("12%", "0.13", False),
    # --- fractions ---
    ("\\frac{1}{2}", "1/2", True),
    ("\\dfrac{3}{4}", "0.75", True),
    ("\\tfrac12", "\\frac{1}{2}", True),
    ("\\frac{2}{4}", "\\frac{1}{2}", True),   # symbolic reduce
    ("-\\frac{1}{3}", "-1/3", True),
    ("\\frac{1}{3}", "0.3333", True),
    ("\\frac{1}{3}", "0.3", False),
    ("7/8", "\\frac{7}{8}", True),
    ("\\frac{22}{7}", "\\pi", False),          # close but not equal
    # --- radicals ---
    ("\\sqrt{8}", "2\\sqrt{2}", True),
    ("\\sqrt2", "\\sqrt{2}", True),
    ("\\frac{\\sqrt{3}}{3}", "\\frac{1}{\\sqrt{3}}", True),
    ("\\sqrt[3]{27}", "3", True),
    ("\\sqrt{16}", "4", True),
    ("\\sqrt{5}", "2.2360679", True),
    ("\\sqrt{5}", "2.23", False),
    # --- scientific notation / latex operators must survive unit strip ---
    ("9 \\times 10^8", "900000000", True),
    ("3 \\times 4", "12", True),
    # trailing "times" is a countable unit; interior "times" is a product
    # whose operands must NOT concatenate
    ("8 times", "8", True),
    ("4 times 5", "45", False),
    # --- pi / constants ---
    ("\\frac{\\pi}{4}", "0.7853981", True),
    ("2\\pi", "6.2831853", True),
    ("\\pi^2", "9.8696", True),
    # --- units / decorations ---
    ("5 \\text{ miles}", "5", True),
    ("90^\\circ", "90", True),
    ("\\$15", "15", True),
    ("15 dollars", "15", True),
    ("3 \\text{cm}", "3", True),
    # --- word numbers ---
    ("twenty-five", "25", True),
    ("one hundred seven", "107", True),
    ("eleven", "11", True),
    # --- variable bindings ---
    ("x = 7", "7", True),
    ("k=\\frac{1}{2}", "0.5", True),
    # --- intervals / tuples (reference compares elementwise; bracket
    #     style is not distinguished) ---
    ("[2, 5)", "[2,5)", True),
    ("(1, 2)", "(1, 2)", True),
    ("(\\frac{1}{2}, 3)", "(0.5, 3)", True),
    ("[1, 2]", "[1, 3]", False),
    ("(-\\infty, 4)", "(-\\infty, 4)", True),
    ("(2,5)", "(2,4)", False),
    # --- sets vs bare ---
    ("{3}", "3", True),
    ("(4)", "4", True),
    # --- matrices ---
    (
        "\\begin{pmatrix} 1 & 2 \\\\ 3 & 4 \\end{pmatrix}",
        "\\begin{pmatrix}1&2\\\\3&4\\end{pmatrix}",
        True,
    ),
    (
        "\\begin{bmatrix} 1 \\\\ \\frac{2}{4} \\end{bmatrix}",
        "\\begin{pmatrix}1\\\\0.5\\end{pmatrix}",
        True,
    ),
    (
        "\\begin{pmatrix} 1 & 2 \\\\ 3 & 4 \\end{pmatrix}",
        "\\begin{pmatrix}1&2\\\\3&5\\end{pmatrix}",
        False,
    ),
    ("\\begin{pmatrix}2\\\\3\\end{pmatrix}", "{2,3}", True),
    # --- equations ---
    ("y = 2x + 1", "2x - y + 1 = 0", True),
    ("x + y = 5", "y = 5 - x", True),
    ("y = 2x", "y = 3x", False),
    # --- symbolic expressions ---
    ("(x+1)^2", "x^2 + 2x + 1", True),
    ("\\frac{x^2-1}{x-1}", "x+1", True),
    ("2^{10}", "1024", True),
    ("x^2", "x^3", False),
    ("x+1", "1+x", True),
    # --- choice answers ---
    ("The answer is (C).", "C", True),
    ("B", "C", False),
    # --- strings ---
    ("\\text{east}", "east", True),
    ("no solution", "no solution", True),
]


@pytest.mark.parametrize("pred,target,expected", GRADE_PAIRS)
def test_grade_pairs(pred, target, expected):
    assert math_equal(pred, target) == expected, (pred, target)


def test_pair_count_contract():
    # VERDICT r3 item 3 asks for >=50 curated pairs
    assert len(GRADE_PAIRS) >= 50


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------


def test_extract_boxed_nested():
    assert extract_boxed(r"so \boxed{\frac{1}{2}}") == r"\frac{1}{2}"
    assert extract_boxed(r"\boxed{a{b{c}}} and \boxed{7}") == "7"
    assert extract_boxed("no box") is None


def test_extract_answer_patterns():
    assert extract_answer(r"blah \boxed{42} done") == "42"
    assert extract_answer("The answer is 17.") == "17"
    assert (
        extract_answer("the final answer is $\\frac{3}{4}$. I hope it helps")
        == r"\frac{3}{4}"
    )
    # last-number fallback
    assert extract_answer("we get 12 then 15") == "15"
    assert extract_answer("nothing here") is None
    # choice datasets reduce to the letter
    assert extract_answer("So the answer is (B).", data_name="aqua") == "B"


def test_extract_answer_normalizes():
    assert extract_answer(r"\boxed{\dfrac{1}{2}}") == r"\frac{1}{2}"
    assert extract_answer(r"\boxed{90^\circ}") == "90"


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------


def test_word_to_number():
    assert word_to_number("twenty-five") == 25
    assert word_to_number("one hundred and seven") == 107
    assert word_to_number("three thousand forty") == 3040
    assert word_to_number("banana") is None


def test_parse_number():
    assert parse_number("1,234.5") == 1234.5
    assert parse_number("50%") == 0.5
    assert parse_number(r"\frac{3}{4}") == 0.75
    assert parse_number(r"1\frac{1}{2}") == 1.5
    assert parse_number("-7/2") == -3.5
    assert parse_number("x+1") is None


def test_normalize_answer():
    assert normalize_answer(r"\dfrac{1}{2}") == r"\frac{1}{2}"
    assert normalize_answer(r"\frac12") == r"\frac{1}{2}"
    assert normalize_answer("5.000") == "5"
    assert normalize_answer(r"90^\circ") == "90"
    assert normalize_answer("x = 5") == "5"
    assert normalize_answer("1,234,567") == "1234567"
    assert normalize_answer(r"\sqrt5") == r"\sqrt{5}"


def test_subprocess_grader():
    assert math_equal_subprocess("1/2", "0.5", timeout_s=10)
    assert not math_equal_subprocess("1/2", "0.6", timeout_s=10)


def test_process_results():
    ok, (pred, gt) = process_results(
        r"...so we find \boxed{\frac{2}{4}}", r"\boxed{\frac{1}{2}}"
    )
    assert ok == 1 and pred and gt


def test_math_verify_reward():
    assert math_verify_reward(None, r"hence \boxed{10}", answer="10") == 1.0
    assert math_verify_reward(None, r"hence \boxed{11}", answer="10") == 0.0
    assert (
        math_verify_reward(
            None, "The answer is 7", answer="#### 7".split("####")[-1].strip()
        )
        == 1.0
    )
    assert math_verify_reward(None, None, answer="1") == 0.0
    assert math_verify_reward(None, "junk", answer=None) == 0.0


def test_math_items_schema():
    """MATH500/AIME loader mapping (network-free via an in-memory HF
    dataset): problem/solution/answer -> RLVR messages/prompt/answer."""
    import datasets as hf_datasets

    from areal_tpu.dataset import _math_items

    ds = hf_datasets.Dataset.from_list(
        [
            dict(problem="What is 2+2?", solution=r"easy: \boxed{4}", answer="4"),
            dict(problem="Half?", solution=r"\boxed{\frac{1}{2}}", answer=None),
        ]
    )
    items = list(_math_items(ds))
    assert items[0]["answer"] == "4"
    assert items[0]["messages"][0]["content"] == "What is 2+2?"
    # missing answer field falls back to the solution's boxed value
    assert items[1]["answer"] == r"\frac{1}{2}"
