import pytest

from areal_tpu.reward.math_parser import (
    extract_answer,
    extract_boxed,
    math_equal,
    math_verify_reward,
)


def test_extract_boxed_balanced():
    assert extract_boxed(r"so \boxed{42}") == "42"
    assert extract_boxed(r"\boxed{\frac{1}{2}}") == r"\frac{1}{2}"
    assert extract_boxed(r"\boxed{a} then \boxed{b}") == "b"
    assert extract_boxed("no box") is None


def test_extract_answer_fallbacks():
    assert extract_answer("The answer is 17.") == "17"
    assert extract_answer("blah 3 then 42") == "42"
    assert extract_answer("nothing here") is None


@pytest.mark.parametrize(
    "a,b,eq",
    [
        ("42", "42", True),
        ("42.0", "42", True),
        ("1/2", "0.5", True),
        (r"\frac{1}{2}", "0.5", True),
        ("1,234", "1234", True),
        ("41", "42", False),
        ("x+1", "1+x", True),  # sympy path
    ],
)
def test_math_equal(a, b, eq):
    assert math_equal(a, b) == eq


def test_reward_fn():
    assert math_verify_reward(None, r"... \boxed{10}", answer="10") == 1.0
    assert math_verify_reward(None, r"... \boxed{11}", answer="10") == 0.0
    assert math_verify_reward(None, "The answer is 7", answer="#### 7".split("####")[-1].strip()) == 1.0
    assert math_verify_reward(None, None, answer="1") == 0.0
    assert math_verify_reward(None, "junk", answer=None) == 0.0
