"""Agent layer + offline evaluation harness with scripted engines."""

import asyncio

import numpy as np
import pytest

from areal_tpu.agent.math_single_step import (
    AgentWorkflow,
    MathSingleStepAgent,
    MathSingleStepEnv,
)
from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.evaluation import evaluate_offline
from areal_tpu.evaluation.offline import pass_at_k_estimate
from tests.test_workflows import FakeTokenizer, ScriptedEngine


class MathTokenizer(FakeTokenizer):
    def decode(self, ids):
        # token 42 decodes to the correct boxed answer
        return "the answer is \\boxed{4}" if ids == [42] else "\\boxed{9}"


def test_math_agent_collects_group():
    agent = MathSingleStepAgent(
        GenerationHyperparameters(n_samples=4, max_new_tokens=4),
        MathTokenizer(),
    )
    wf = AgentWorkflow(agent, MathSingleStepEnv)
    eng = ScriptedEngine([[42], [7], [42], [7]])
    batch = asyncio.run(
        wf.arun_episode(eng, {"input_ids": [1, 2], "answer": "4"})
    )
    rewards = np.asarray(batch["rewards"])
    assert batch["input_ids"].shape[0] == 4
    assert sorted(rewards.tolist()) == [0.0, 0.0, 1.0, 1.0]


def test_math_agent_rejects_out_of_band_groups():
    agent = MathSingleStepAgent(
        GenerationHyperparameters(n_samples=2, max_new_tokens=4),
        MathTokenizer(),
        success_rate_lb=0.1,
        success_rate_ub=0.9,
    )
    wf = AgentWorkflow(agent, MathSingleStepEnv)
    eng = ScriptedEngine([[42], [42]])  # all correct -> rate 1.0 > ub
    out = asyncio.run(wf.arun_episode(eng, {"input_ids": [1], "answer": "4"}))
    assert out is None


def test_pass_at_k_estimator():
    assert pass_at_k_estimate(10, 10, 1) == 1.0
    assert pass_at_k_estimate(10, 0, 5) == 0.0
    # n=4, c=1, k=1 -> 1/4
    assert abs(pass_at_k_estimate(4, 1, 1) - 0.25) < 1e-9
    # n=4, c=1, k=4 -> 1.0 (some sample always included)
    assert pass_at_k_estimate(4, 1, 4) == 1.0


def test_evaluate_offline_metrics():
    from areal_tpu.reward.math_parser import math_verify_reward

    # Engine answers correctly only on calls 0 and 2 of each 2-sample pair.
    class AltEngine(ScriptedEngine):
        async def agenerate(self, req):
            out = [42] if self.calls % 2 == 0 else [7]
            self.calls += 1
            from areal_tpu.api.io_struct import ModelResponse

            return ModelResponse(
                input_tokens=list(req.input_ids),
                output_tokens=out,
                output_logprobs=[-0.1],
                output_versions=[0],
                stop_reason="stop",
            )

    eng = AltEngine([])
    res = evaluate_offline(
        eng,
        [
            {"input_ids": [1], "answer": "4"},
            {"input_ids": [2], "answer": "4"},
        ],
        reward_fn=math_verify_reward,
        gconfig=GenerationHyperparameters(max_new_tokens=4),
        tokenizer=MathTokenizer(),
        n_samples=2,
        ks=(1, 2),
    )
    assert res.n_problems == 2 and res.n_samples == 2
    assert abs(res.mean_reward - 0.5) < 1e-9
    assert abs(res.pass_at_1 - 0.5) < 1e-9
    assert res.pass_at_k[2] == 1.0  # each problem has one correct sample
    d = res.to_dict()
    assert "pass@2" in d


def test_vqa_rewards():
    from areal_tpu.reward.vqa import clevr_count_reward, geometry3k_reward

    assert clevr_count_reward(None, "I count \\boxed{3} objects", answer="3") == 1.0
    assert clevr_count_reward(None, "<answer>5</answer>", answer="3") == 0.0
    assert clevr_count_reward(None, "no digits here", answer="3") == 0.0
    assert geometry3k_reward(None, "<answer>B</answer>", answer="b") == 1.0
    assert geometry3k_reward(None, "\\boxed{2\\pi}", answer="2\\pi") == 1.0
    assert geometry3k_reward(None, "\\boxed{7}", answer="8") == 0.0


def test_dataset_registry_mappers():
    hf_datasets = pytest.importorskip("datasets")
    from areal_tpu.dataset import _REGISTRY

    raw = hf_datasets.Dataset.from_list(
        [{"chosen": "good text", "rejected": "bad text"}]
    )
    import unittest.mock as mock

    with mock.patch.object(hf_datasets, "load_dataset", return_value=raw):
        ds = _REGISTRY["hh-rlhf"](path="hh-rlhf", split="train", type="rw")
    assert ds[0]["chosen"] == "good text"

    raw2 = hf_datasets.Dataset.from_list(
        [{"question": "2+2?", "answer": "4"}]
    )
    with mock.patch.object(hf_datasets, "load_dataset", return_value=raw2):
        ds2 = _REGISTRY["torl_data"](path="x/torl_data", split="train", type="rl")
    assert ds2[0]["messages"][0]["content"] == "2+2?"
    assert ds2[0]["answer"] == "4"


def test_env_registry_and_null_env():
    """Env registry parity (realhf/api/core/env_api.py): envs resolve by
    name; the null env terminates immediately."""
    from areal_tpu.api.agent_api import ALL_ENV_CLASSES, make_env

    env = make_env("null")
    obs, reward, term, trunc, info = asyncio.run(env.step("anything"))
    assert term and not trunc and reward == 0.0
    # lazy import registered the built-in envs too
    assert "math-code-single-step" in ALL_ENV_CLASSES


def test_math_code_env_obs_act_queues():
    """Drive the math+code env through obs/act queues the way the
    reference RolloutWorker does (parity:
    realhf/impl/environment/math_code_single_step_env.py): the agent side
    pushes (qid, answers) actions, the env side pushes observations
    (reward groups) back."""
    from areal_tpu.api.agent_api import make_env

    id2info = {
        "q-math": dict(task="math", solutions=[r"\boxed{\frac{1}{2}}"]),
        "q-code": dict(
            task="code",
            input_output=dict(
                inputs=["3 4\n"], outputs=["7\n"], fn_name=""
            ),
        ),
    }
    env = make_env("math-code-single-step", id2info=id2info)

    async def run():
        act_q: asyncio.Queue = asyncio.Queue()
        obs_q: asyncio.Queue = asyncio.Queue()

        async def env_loop():
            await env.reset()
            while True:
                action = await act_q.get()
                if action is None:
                    return
                obs = await env.step(action)
                await obs_q.put(obs)

        loop_task = asyncio.create_task(env_loop())
        # math group: one right (equivalent fraction), one wrong
        await act_q.put(
            ("q-math@0", ["the answer is $\\frac{2}{4}$... \\boxed{2/4}",
                          "\\boxed{3}"])
        )
        _, rewards, term, _, info = await obs_q.get()
        assert rewards == [1.0, 0.0] and term and info["task"] == "math"
        # code group: one program that passes the testcase, one that fails
        good = "```python\na, b = map(int, input().split())\nprint(a + b)\n```"
        bad = "```python\nprint(0)\n```"
        await act_q.put(("q-code", [f"reasoning... {good}", bad]))
        _, rewards, term, _, info = await obs_q.get()
        assert rewards == [1.0, 0.0] and term and info["task"] == "code"
        await act_q.put(None)
        await loop_task

    asyncio.run(run())


def test_math_code_env_unknown_qid_raises():
    from areal_tpu.api.agent_api import make_env

    env = make_env("math-code-single-step", id2info={})
    with pytest.raises(KeyError):
        asyncio.run(env.step(("missing", ["x"])))


def test_maj_at_n_clusters_equivalent_answers():
    """maj@n votes by mathematical equivalence: \\frac{1}{2} and 0.5 are
    ONE vote. String-identity voting would split them 1-1-1 against the
    wrong answer; equivalence clustering restores the true 2-1 majority."""
    from areal_tpu.api.io_struct import ModelResponse
    from areal_tpu.reward.math_parser import math_verify_reward

    class FormTokenizer:
        eos_token_id = None

        def decode(self, ids):
            forms = {
                1: r"the answer is $\boxed{\frac{1}{2}}$",
                2: r"so \boxed{0.5}",
                3: r"hence \boxed{7}",
            }
            return " ".join(forms.get(int(i), str(i)) for i in ids)

    class FormEngine(ScriptedEngine):
        async def agenerate(self, req):
            out = [1 + self.calls % 3]  # cycles 1, 2, 3
            self.calls += 1
            return ModelResponse(
                input_tokens=list(req.input_ids),
                output_tokens=out,
                output_logprobs=[-0.1],
                output_versions=[0],
                stop_reason="stop",
            )

    res = evaluate_offline(
        FormEngine([]),
        [{"input_ids": [9], "answer": "0.5"}],
        reward_fn=math_verify_reward,
        gconfig=GenerationHyperparameters(max_new_tokens=4),
        tokenizer=FormTokenizer(),
        n_samples=3,
        ks=(1,),
    )
    assert res.maj_at_n == 1.0
    assert abs(res.mean_reward - 2 / 3) < 1e-9
