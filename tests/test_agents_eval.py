"""Agent layer + offline evaluation harness with scripted engines."""

import asyncio

import numpy as np
import pytest

from areal_tpu.agent.math_single_step import (
    AgentWorkflow,
    MathSingleStepAgent,
    MathSingleStepEnv,
)
from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.evaluation import evaluate_offline
from areal_tpu.evaluation.offline import pass_at_k_estimate
from tests.test_workflows import FakeTokenizer, ScriptedEngine


class MathTokenizer(FakeTokenizer):
    def decode(self, ids):
        # token 42 decodes to the correct boxed answer
        return "the answer is \\boxed{4}" if ids == [42] else "\\boxed{9}"


def test_math_agent_collects_group():
    agent = MathSingleStepAgent(
        GenerationHyperparameters(n_samples=4, max_new_tokens=4),
        MathTokenizer(),
    )
    wf = AgentWorkflow(agent, MathSingleStepEnv)
    eng = ScriptedEngine([[42], [7], [42], [7]])
    batch = asyncio.run(
        wf.arun_episode(eng, {"input_ids": [1, 2], "answer": "4"})
    )
    rewards = np.asarray(batch["rewards"])
    assert batch["input_ids"].shape[0] == 4
    assert sorted(rewards.tolist()) == [0.0, 0.0, 1.0, 1.0]


def test_math_agent_rejects_out_of_band_groups():
    agent = MathSingleStepAgent(
        GenerationHyperparameters(n_samples=2, max_new_tokens=4),
        MathTokenizer(),
        success_rate_lb=0.1,
        success_rate_ub=0.9,
    )
    wf = AgentWorkflow(agent, MathSingleStepEnv)
    eng = ScriptedEngine([[42], [42]])  # all correct -> rate 1.0 > ub
    out = asyncio.run(wf.arun_episode(eng, {"input_ids": [1], "answer": "4"}))
    assert out is None


def test_pass_at_k_estimator():
    assert pass_at_k_estimate(10, 10, 1) == 1.0
    assert pass_at_k_estimate(10, 0, 5) == 0.0
    # n=4, c=1, k=1 -> 1/4
    assert abs(pass_at_k_estimate(4, 1, 1) - 0.25) < 1e-9
    # n=4, c=1, k=4 -> 1.0 (some sample always included)
    assert pass_at_k_estimate(4, 1, 4) == 1.0


def test_evaluate_offline_metrics():
    from areal_tpu.reward.math_parser import math_verify_reward

    # Engine answers correctly only on calls 0 and 2 of each 2-sample pair.
    class AltEngine(ScriptedEngine):
        async def agenerate(self, req):
            out = [42] if self.calls % 2 == 0 else [7]
            self.calls += 1
            from areal_tpu.api.io_struct import ModelResponse

            return ModelResponse(
                input_tokens=list(req.input_ids),
                output_tokens=out,
                output_logprobs=[-0.1],
                output_versions=[0],
                stop_reason="stop",
            )

    eng = AltEngine([])
    res = evaluate_offline(
        eng,
        [
            {"input_ids": [1], "answer": "4"},
            {"input_ids": [2], "answer": "4"},
        ],
        reward_fn=math_verify_reward,
        gconfig=GenerationHyperparameters(max_new_tokens=4),
        tokenizer=MathTokenizer(),
        n_samples=2,
        ks=(1, 2),
    )
    assert res.n_problems == 2 and res.n_samples == 2
    assert abs(res.mean_reward - 0.5) < 1e-9
    assert abs(res.pass_at_1 - 0.5) < 1e-9
    assert res.pass_at_k[2] == 1.0  # each problem has one correct sample
    d = res.to_dict()
    assert "pass@2" in d


def test_vqa_rewards():
    from areal_tpu.reward.vqa import clevr_count_reward, geometry3k_reward

    assert clevr_count_reward(None, "I count \\boxed{3} objects", answer="3") == 1.0
    assert clevr_count_reward(None, "<answer>5</answer>", answer="3") == 0.0
    assert clevr_count_reward(None, "no digits here", answer="3") == 0.0
    assert geometry3k_reward(None, "<answer>B</answer>", answer="b") == 1.0
    assert geometry3k_reward(None, "\\boxed{2\\pi}", answer="2\\pi") == 1.0
    assert geometry3k_reward(None, "\\boxed{7}", answer="8") == 0.0


def test_dataset_registry_mappers():
    hf_datasets = pytest.importorskip("datasets")
    from areal_tpu.dataset import _REGISTRY

    raw = hf_datasets.Dataset.from_list(
        [{"chosen": "good text", "rejected": "bad text"}]
    )
    import unittest.mock as mock

    with mock.patch.object(hf_datasets, "load_dataset", return_value=raw):
        ds = _REGISTRY["hh-rlhf"](path="hh-rlhf", split="train", type="rw")
    assert ds[0]["chosen"] == "good text"

    raw2 = hf_datasets.Dataset.from_list(
        [{"question": "2+2?", "answer": "4"}]
    )
    with mock.patch.object(hf_datasets, "load_dataset", return_value=raw2):
        ds2 = _REGISTRY["torl_data"](path="x/torl_data", split="train", type="rl")
    assert ds2[0]["messages"][0]["content"] == "2+2?"
    assert ds2[0]["answer"] == "4"
