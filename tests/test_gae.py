"""GAE associative-scan vs sequential oracle (parity with the reference's
cugae kernel tests, realhf/tests/cpp_extensions/test_cugae.py)."""

import numpy as np
import pytest

import jax.numpy as jnp

from areal_tpu.ops.gae import gae_packed, gae_padded, gae_padded_reference


def _random_case(B=4, T=24, seed=0, mask_prob=0.7):
    rng = np.random.RandomState(seed)
    rewards = rng.randn(B, T).astype(np.float32)
    values = rng.randn(B, T).astype(np.float32)
    loss_mask = (rng.rand(B, T) < mask_prob).astype(np.float32)
    no_eos = (rng.rand(B) < 0.5).astype(np.float32)
    return rewards, values, loss_mask, no_eos


@pytest.mark.parametrize("discount,lam", [(1.0, 1.0), (0.99, 0.95), (0.9, 0.5)])
def test_gae_padded_matches_oracle(discount, lam):
    rewards, values, loss_mask, no_eos = _random_case(seed=int(lam * 100))
    adv, ret = gae_padded(rewards, values, loss_mask, no_eos, discount, lam)
    adv_ref, ret_ref = gae_padded_reference(
        rewards, values, loss_mask, no_eos, discount, lam
    )
    np.testing.assert_allclose(np.asarray(adv), adv_ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(ret), ret_ref, rtol=1e-4, atol=1e-4)


def test_gae_last_position_zero():
    rewards, values, loss_mask, no_eos = _random_case(seed=7)
    adv, _ = gae_padded(rewards, values, loss_mask, no_eos, 0.99, 0.95)
    np.testing.assert_allclose(np.asarray(adv)[:, -1], 0.0)


def test_gae_grpo_mode_reward_to_go():
    # values == 0, discount = lam = 1: advantage is the masked reward-to-go
    B, T = 2, 8
    rewards = np.zeros((B, T), dtype=np.float32)
    rewards[:, 5] = 1.0  # terminal-ish reward
    values = np.zeros((B, T), dtype=np.float32)
    loss_mask = np.ones((B, T), dtype=np.float32)
    no_eos = np.zeros(B, dtype=np.float32)
    adv, _ = gae_padded(rewards, values, loss_mask, no_eos, 1.0, 1.0)
    adv = np.asarray(adv)
    np.testing.assert_allclose(adv[:, :6], 1.0, atol=1e-6)
    np.testing.assert_allclose(adv[:, 6:], 0.0, atol=1e-6)


def test_gae_packed_matches_padded():
    lens = [6, 9, 4]
    rng = np.random.RandomState(3)
    B, T = len(lens), max(lens)
    rewards = np.zeros((B, T), dtype=np.float32)
    values = np.zeros((B, T), dtype=np.float32)
    loss_mask = np.zeros((B, T), dtype=np.float32)
    for i, L in enumerate(lens):
        rewards[i, :L] = rng.randn(L)
        values[i, :L] = rng.randn(L)
        loss_mask[i, :L] = (rng.rand(L) < 0.8).astype(np.float32)
        # invariant from the rolled loss mask: a sequence's final position is
        # never trained (its label falls outside the sequence)
        loss_mask[i, L - 1] = 0.0
    no_eos = np.zeros(B, dtype=np.float32)

    adv_pad, _ = gae_padded(rewards, values, loss_mask, no_eos, 0.97, 0.9)
    adv_pad = np.asarray(adv_pad)

    # packed layout
    seg, r1, v1, m1, ne1 = [], [], [], [], []
    for i, L in enumerate(lens):
        seg += [i] * L
        r1 += list(rewards[i, :L])
        v1 += list(values[i, :L])
        m1 += list(loss_mask[i, :L])
        ne1 += [0.0] * L
    adv_packed, _ = gae_packed(
        jnp.asarray(r1), jnp.asarray(v1), jnp.asarray(m1),
        jnp.asarray(np.array(seg)), jnp.asarray(ne1), 0.97, 0.9
    )
    adv_packed = np.asarray(adv_packed)
    ofs = 0
    for i, L in enumerate(lens):
        np.testing.assert_allclose(
            adv_packed[ofs : ofs + L], adv_pad[i, :L], rtol=1e-4,
            atol=1e-4, err_msg=f"seq {i}"
        )
        ofs += L
