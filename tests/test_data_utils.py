import numpy as np
import pytest

from areal_tpu.api.cli_args import MicroBatchSpec, NormConfig
from areal_tpu.utils.data import (
    KLEstimator,
    Normalization,
    amend_position_ids,
    concat_padded_tensors,
    pack_tensor_dict,
    pad_packed_tensor_dict,
    pad_sequences_to_tensors,
    split_padded_tensor_dict_into_mb_list,
    unpack_sequence,
)


def _mk_batch(lens, max_len=None):
    seqs = [dict(input_ids=np.arange(n) + 1, rewards=np.float32(n)) for n in lens]
    return pad_sequences_to_tensors(seqs)


def test_pad_sequences():
    b = _mk_batch([3, 5])
    assert b["input_ids"].shape == (2, 5)
    assert b["attention_mask"].sum() == 8
    assert b["rewards"].shape == (2,)


def test_concat_padded_repads():
    b1 = _mk_batch([3])
    b2 = _mk_batch([6])
    out = concat_padded_tensors([b1, b2])
    assert out["input_ids"].shape == (2, 6)
    assert out["attention_mask"][0].sum() == 3
    assert out["attention_mask"][1].sum() == 6


def test_pack_unpack_roundtrip():
    b = _mk_batch([3, 5, 2])
    packed = pack_tensor_dict(b)
    assert packed["input_ids"].shape == (10,)
    assert list(packed["cu_seqlens"]) == [0, 3, 8, 10]
    assert packed["max_seqlen"] == 5
    seqs = unpack_sequence(packed["input_ids"], packed["cu_seqlens"])
    assert [len(s) for s in seqs] == [3, 5, 2]
    np.testing.assert_array_equal(seqs[0], [1, 2, 3])


def test_pad_packed_bucketing():
    b = _mk_batch([3, 5])
    packed = pack_tensor_dict(b)
    padded, pad_len = pad_packed_tensor_dict(packed, pad_to_multiple=16)
    assert padded["input_ids"].shape == (16,)
    assert pad_len == 8
    # fake tail sequence appended
    assert list(padded["cu_seqlens"]) == [0, 3, 8, 16]


def test_amend_position_ids():
    b = _mk_batch([3, 2])
    packed = pack_tensor_dict(b)
    packed = amend_position_ids(packed)
    np.testing.assert_array_equal(packed["position_ids"], [0, 1, 2, 0, 1])


def test_split_into_mbs_covers_batch():
    b = _mk_batch([3, 5, 2, 7, 1, 4])
    mbl = split_padded_tensor_dict_into_mb_list(
        b, MicroBatchSpec(max_tokens_per_mb=10, n_mbs=None), pad_to_multiple=8
    )
    all_idx = sorted(i for idx in mbl.forward_indices for i in idx)
    assert all_idx == list(range(6))
    for mb in mbl.mbs:
        # each mb padded to multiple of 8 and within budget before padding
        assert mb["input_ids"].shape[0] % 8 == 0


def test_split_respects_granularity():
    b = _mk_batch([3, 5, 2, 7])
    mbl = split_padded_tensor_dict_into_mb_list(
        b, MicroBatchSpec(max_tokens_per_mb=9, granularity=2), pad_to_multiple=8
    )
    for idx in mbl.forward_indices:
        # groups of 2 adjacent samples stay together
        assert all(idx[i + 1] == idx[i] + 1 for i in range(0, len(idx) - 1, 2))


def test_normalization_batch():
    norm = Normalization(NormConfig(mean_level="batch", std_level="batch"))
    x = np.array([[1.0, 2.0], [3.0, 4.0]])
    out = norm(x)
    assert abs(out.mean()) < 1e-5
    assert abs(out.std() - 1.0) < 0.01


def test_normalization_group():
    norm = Normalization(
        NormConfig(mean_level="group", std_level="group", group_size=2)
    )
    x = np.array([[1.0], [3.0], [10.0], [20.0]])
    out = norm(x)
    # each group centered independently
    assert abs(out[:2].mean()) < 1e-6
    assert abs(out[2:].mean()) < 1e-6


def test_normalization_leave1out():
    norm = Normalization(
        NormConfig(mean_level="group", mean_leave1out=True, std_level=None, group_size=2)
    )
    x = np.array([[1.0], [3.0]])
    out = norm(x)
    # leave-one-out mean of sample0 is 3 -> 1-3 = -2; sample1: 3-1 = 2
    np.testing.assert_allclose(out.flatten(), [-2.0, 2.0])


def test_normalization_masked_all_zero():
    norm = Normalization(NormConfig())
    x = np.array([[5.0, 5.0]])
    out = norm(x, loss_mask=np.zeros_like(x))
    np.testing.assert_array_equal(out, x)


def test_kl_estimators():
    lp = np.array([0.0, -1.0])
    lp_base = np.array([-1.0, -1.0])
    k1 = KLEstimator("k1")(lp, lp_base)
    np.testing.assert_allclose(k1, [1.0, 0.0])
    k2 = KLEstimator("k2")(lp, lp_base)
    np.testing.assert_allclose(k2, [0.5, 0.0])
    k3 = KLEstimator("k3")(lp, lp_base)
    np.testing.assert_allclose(k3, [np.exp(-1) - 1 + 1, 0.0])
    with pytest.raises(ValueError):
        KLEstimator("k9")
