import numpy as np
import pytest

from areal_tpu.utils.datapack import (
    ffd_allocate,
    flat2d,
    min_abs_diff_partition,
    partition_balanced,
    reorder_to_balanced_batches,
)


def test_flat2d():
    assert flat2d([[1, 2], [3], []]) == [1, 2, 3]


def test_partition_balanced_covers_all():
    nums = np.array([5, 1, 1, 1, 5, 1, 1, 1])
    parts = partition_balanced(nums, 4)
    assert sorted(flat2d(parts)) == list(range(8))
    sums = [sum(nums[i] for i in p) for p in parts]
    assert max(sums) <= 6  # optimal max-sum


def test_partition_balanced_min_size():
    with pytest.raises(ValueError):
        partition_balanced(np.array([1, 2]), 3)


def test_min_abs_diff_partition_bounds():
    bounds = min_abs_diff_partition(np.array([1, 1, 1, 1]), 2)
    assert bounds == [(0, 2), (2, 4)]


def test_ffd_respects_capacity():
    values = [30, 20, 20, 10, 10, 10]
    bins = ffd_allocate(values, capacity=40)
    assert sorted(flat2d(bins)) == list(range(6))
    for b in bins:
        assert sum(values[i] for i in b) <= 40


def test_ffd_min_groups():
    bins = ffd_allocate([1, 1, 1, 1], capacity=100, min_groups=2)
    assert len(bins) >= 2
    assert sorted(flat2d(bins)) == [0, 1, 2, 3]


def test_ffd_oversized_item_gets_own_bin():
    bins = ffd_allocate([100, 1], capacity=50)
    big_bin = [b for b in bins if 0 in b][0]
    assert big_bin == [0]


def test_reorder_to_balanced_batches():
    seqlens = np.array([100, 1, 1, 100, 50, 50])
    chunks = reorder_to_balanced_batches(seqlens, batch_size_per_chunk=2)
    assert sorted(flat2d(chunks)) == list(range(6))
    sums = [sum(int(seqlens[i]) for i in c) for c in chunks]
    assert max(sums) - min(sums) <= 100


def test_native_matches_python_fallback(monkeypatch):
    """C++ kernels (csrc/datapack.cc) must be bit-identical to the numpy
    spec, including the min_groups bin-splitting path."""
    import areal_tpu.utils.datapack as dp
    from areal_tpu.utils import _native

    if _native.load_datapack() is None:
        pytest.skip("no native build available")

    rng = np.random.RandomState(0)
    for trial in range(20):
        n = int(rng.randint(1, 300))
        values = rng.randint(1, 500, n).tolist()
        cap = int(rng.randint(300, 1500))
        min_groups = int(rng.randint(1, 5))
        native = dp.ffd_allocate(values, cap, min_groups=min_groups)
        with monkeypatch.context() as m:
            m.setattr(_native, "load_datapack", lambda: None)
            python = dp.ffd_allocate(values, cap, min_groups=min_groups)
        assert native == python, (trial, values[:8], cap, min_groups)

    for trial in range(20):
        k = int(rng.randint(1, 6))
        n = int(rng.randint(k, 60))
        nums = rng.randint(1, 200, n)
        native = dp.partition_balanced(nums, k)
        with monkeypatch.context() as m:
            m.setattr(_native, "load_datapack", lambda: None)
            python = dp.partition_balanced(nums, k)
        # DP tie-breaks identically (strict <, same scan order)
        assert native == python, (trial, k, nums[:8])
