"""Flash attention kernel vs dense reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models.qwen2 import PADDING_SEGMENT, segment_causal_mask
from areal_tpu.ops.flash_attention import flash_attention


def dense_reference(q, k, v, seg):
    """[T, nH, hd] x [T, nKV, hd] -> [T, nH, hd], causal-within-segment."""
    T, nH, hd = q.shape
    nKV = k.shape[1]
    group = nH // nKV
    qf = q.astype(jnp.float32).reshape(T, nKV, group, hd)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("tkgd,skd->kgts", qf, kf) / np.sqrt(hd)
    mask = segment_causal_mask(seg)
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    # zero fully-masked (padding) rows
    valid = (seg != PADDING_SEGMENT)[None, None, :, None]
    p = jnp.where(valid, p, 0.0)
    o = jnp.einsum("kgts,skd->tkgd", p, vf)
    return o.reshape(T, nH, hd)


def make_inputs(T, nH, nKV, hd, seed=0, n_seqs=3, pad=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(T, nH, hd), dtype=jnp.float32) * 0.5
    k = jnp.asarray(rng.randn(T, nKV, hd), dtype=jnp.float32) * 0.5
    v = jnp.asarray(rng.randn(T, nKV, hd), dtype=jnp.float32) * 0.5
    body = T - pad
    cuts = np.sort(rng.choice(np.arange(1, body), size=n_seqs - 1, replace=False))
    seg = np.zeros(T, dtype=np.int32)
    prev = 0
    for si, c in enumerate(list(cuts) + [body]):
        seg[prev:c] = si
        prev = c
    seg[body:] = PADDING_SEGMENT
    return q, k, v, jnp.asarray(seg)


@pytest.mark.parametrize(
    "T,nH,nKV,hd,pad",
    [
        (256, 4, 4, 64, 0),
        (256, 4, 2, 64, 37),  # GQA + ragged pad tail
        (384, 8, 2, 32, 5),
    ],
)
def test_forward_matches_dense(T, nH, nKV, hd, pad):
    q, k, v, seg = make_inputs(T, nH, nKV, hd, pad=pad)
    out = flash_attention(q, k, v, seg, block_q=128, block_k=128, interpret=True)
    ref = dense_reference(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_backward_matches_dense():
    T, nH, nKV, hd = 256, 4, 2, 32
    q, k, v, seg = make_inputs(T, nH, nKV, hd, pad=19, seed=1)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, seg, block_q=128, block_k=128, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(dense_reference(q, k, v, seg)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4, err_msg=name
        )


def test_nonaligned_length_padding():
    # T not a multiple of the block: wrapper pads and slices back.
    T, nH, nKV, hd = 200, 2, 2, 32
    q, k, v, seg = make_inputs(T, nH, nKV, hd, pad=0, seed=2, n_seqs=2)
    out = flash_attention(q, k, v, seg, block_q=128, block_k=128, interpret=True)
    ref = dense_reference(q, k, v, seg)
    assert out.shape == (T, nH, hd)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_segment_isolation():
    # Tokens in one segment must not see another segment even acausally.
    T, nH, nKV, hd = 128, 2, 2, 32
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(T, nH, hd), dtype=jnp.float32)
    k = jnp.asarray(rng.randn(T, nKV, hd), dtype=jnp.float32)
    v = jnp.asarray(rng.randn(T, nKV, hd), dtype=jnp.float32)
    seg = jnp.asarray(np.repeat([0, 1], T // 2).astype(np.int32))
    out = flash_attention(q, k, v, seg, block_q=128, block_k=128, interpret=True)
    # Perturb segment 0's k/v: segment 1 outputs must not change.
    k2 = k.at[: T // 2].add(10.0)
    v2 = v.at[: T // 2].add(10.0)
    out2 = flash_attention(q, k2, v2, seg, block_q=128, block_k=128, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out[T // 2 :]), np.asarray(out2[T // 2 :]), atol=1e-6
    )
    assert not np.allclose(np.asarray(out[: T // 2]), np.asarray(out2[: T // 2]))


@pytest.mark.slow
def test_model_forward_flash_vs_dense():
    # Full decoder forward parity between attention implementations.
    from areal_tpu.models.qwen2 import (
        ModelConfig,
        forward,
        init_params,
        segment_ids_from_cu_seqlens,
    )

    base = dict(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        dtype="float32",
        param_dtype="float32",
    )
    cfg_d = ModelConfig(**base, attn_impl="dense")
    cfg_f = ModelConfig(**base, attn_impl="flash")
    params = init_params(cfg_d, jax.random.PRNGKey(0))
    T = 160
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(1, 128, (T,)), dtype=jnp.int32)
    cu = np.array([0, 70, 150], dtype=np.int32)
    seg = np.asarray(segment_ids_from_cu_seqlens(cu, T))
    seg[150:] = PADDING_SEGMENT
    seg = jnp.asarray(seg)
    pos = jnp.asarray(
        np.concatenate([np.arange(70), np.arange(80), np.zeros(10)]).astype(np.int32)
    )
    out_d = forward(params, ids, pos, seg, cfg_d)
    out_f = forward(params, ids, pos, seg, cfg_f)
    np.testing.assert_allclose(
        np.asarray(out_d[:150]), np.asarray(out_f[:150]), atol=3e-4, rtol=3e-4
    )
