"""Paged KV cache: allocator accounting, memory bounds, fork aliasing,
and pool-pressure preemption parity (parity target: the paged/radix KV
the reference inherits from SGLang, areal/engine/sglang_remote.py:22)."""

import jax
import numpy as np
import pytest

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.engine.kv_pool import KVBlockAllocator, PoolDry
from areal_tpu.models.qwen2 import ModelConfig, forward, init_params

TINY = ModelConfig(
    vocab_size=48,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)


def greedy_reference(params, prompt, n_new):
    seq = list(prompt)
    for _ in range(n_new):
        T = len(seq)
        logits = forward(
            params,
            np.array(seq, dtype=np.int32),
            np.arange(T, dtype=np.int32),
            np.zeros(T, dtype=np.int32),
            TINY,
        )
        seq.append(int(np.argmax(np.asarray(logits[-1]))))
    return seq[len(prompt):]


# -- allocator unit tests ----------------------------------------------


def test_allocator_ensure_and_free():
    a = KVBlockAllocator(n_slots=4, n_blocks=9, block_size=128,
                         max_blocks_per_slot=8)
    assert a.free_blocks == 8  # block 0 is the pinned null block
    assert a.ensure(0, 200)  # 2 blocks
    assert a.nblocks[0] == 2 and a.free_blocks == 6
    assert a.ensure(0, 200)  # idempotent
    assert a.free_blocks == 6
    assert a.ensure(0, 500)  # grow to 4
    assert a.nblocks[0] == 4 and a.free_blocks == 4
    assert a.allocated_tokens() == 4 * 128
    a.free_slot(0)
    assert a.free_blocks == 8 and a.nblocks[0] == 0
    assert (a.tables[0] == 0).all()


def test_allocator_pool_dry_and_guard():
    a = KVBlockAllocator(4, 9, 128, 8)
    assert a.ensure(0, 8 * 128)
    assert not a.ensure(1, 1)  # dry
    with pytest.raises(AssertionError):
        KVBlockAllocator(4, 8, 128, 8)  # pool smaller than one full slot


def test_allocator_fork_aliases_full_blocks():
    a = KVBlockAllocator(4, 17, 128, 8)
    assert a.ensure(0, 300)  # 3 blocks: 2 full + 1 partial under covered=300
    free_before = a.free_blocks
    cp = a.fork(0, 1, covered=300)
    # 2 aliased + 1 fresh partial: only ONE new block consumed
    assert a.free_blocks == free_before - 1
    assert cp is not None and cp[0] == a.tables[0, 2] and cp[1] == a.tables[1, 2]
    assert (a.tables[1, :2] == a.tables[0, :2]).all()
    assert a.refcount[a.tables[0, 0]] == 2
    # aliased blocks survive one holder's free
    a.free_slot(0)
    assert a.refcount[a.tables[1, 0]] == 1
    # block-aligned boundary: no copy needed
    assert a.ensure(2, 256)
    assert a.fork(2, 3, covered=256) is None
    assert (a.tables[3, :2] == a.tables[2, :2]).all()


def test_allocator_fork_rolls_back_on_dry():
    a = KVBlockAllocator(3, 9, 128, 8)
    assert a.ensure(0, 300)  # 3 blocks
    assert a.ensure(2, 5 * 128)  # hog the remaining 5; free now 0
    with pytest.raises(PoolDry):
        a.fork(0, 1, covered=300)  # needs 1 block for the boundary copy
    # rollback: slot 1 empty, slot 0's refcounts back to 1
    assert a.nblocks[1] == 0
    assert a.refcount[a.tables[0, 0]] == 1


# -- engine integration -------------------------------------------------


@pytest.mark.slow
def test_pool_reserves_far_less_than_dense(cpu_devices):
    """The headline paging property: 8 slots x 2048 context reserves a
    17-block pool (2176 tokens), not 8 x 2048 = 16384 rows — and short
    concurrent requests all serve correctly out of it."""
    cfg = JaxDecodeConfig(
        context_length=2048,
        max_running_requests=8,
        new_tokens_per_chunk=8,
        kv_pool_tokens=1024,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    params = init_params(TINY, jax.random.PRNGKey(0))
    eng.set_model(params, TINY)
    eng.initialize()
    try:
        n_blocks = eng._k_cache.shape[1]
        assert n_blocks == 17, n_blocks  # max(8, 16) + 1
        assert n_blocks * eng._k_cache.shape[2] < 8 * 2048 / 4
        prompts = [[i + 1, 5, 9, 2] for i in range(6)]
        import threading

        results = [None] * len(prompts)

        def run(i):
            results[i] = eng.generate(
                ModelRequest(
                    input_ids=list(prompts[i]),
                    gconfig=GenerationHyperparameters(
                        greedy=True, max_new_tokens=6
                    ),
                ),
                timeout=600,
            )

        ts = [threading.Thread(target=run, args=(i,)) for i in range(len(prompts))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(600)
        for i, p in enumerate(prompts):
            assert results[i] is not None
            assert results[i].output_tokens == greedy_reference(params, p, 6)
        m = eng.get_metrics()
        assert m["kv_blocks_total"] == 16
        assert m["kv_tokens_allocated"] <= 16 * 128
    finally:
        eng.destroy()


@pytest.mark.slow
def test_pool_pressure_preempts_and_stays_exact(cpu_devices):
    """When concurrent long generations outgrow the pool, the engine
    preempts (frees blocks, requeues internally) and every request still
    returns the exact greedy continuation — the client never sees the
    preemption."""
    cfg = JaxDecodeConfig(
        context_length=2048,
        max_running_requests=4,
        new_tokens_per_chunk=8,
        kv_pool_tokens=128,  # floor: 16 usable blocks (one full slot)
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    params = init_params(TINY, jax.random.PRNGKey(0))
    eng.set_model(params, TINY)
    eng.initialize()
    try:
        # 4 x 450-token prompts prefill into 4 blocks each (16 = the
        # whole pool); once a generation crosses the 512-row boundary the
        # chunk needs a 5th block and must preempt a peer
        rng = np.random.RandomState(0)
        prompts = [
            [int(t) for t in rng.randint(1, 40, size=450)] for _ in range(4)
        ]
        import threading

        results = [None] * 4

        def run(i):
            results[i] = eng.generate(
                ModelRequest(
                    input_ids=list(prompts[i]),
                    gconfig=GenerationHyperparameters(
                        greedy=True, max_new_tokens=72
                    ),
                ),
                timeout=900,
            )

        ts = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(900)
        for i in range(4):
            assert results[i] is not None, f"request {i} did not finish"
            assert results[i].output_tokens == greedy_reference(
                params, prompts[i], 72
            ), f"request {i} diverged"
        assert eng.get_metrics()["preemptions_total"] > 0
    finally:
        eng.destroy()


@pytest.mark.slow
def test_group_fork_shares_blocks(cpu_devices):
    """A GRPO group's shared prompt is stored ONCE: later group members
    alias the donor's full blocks and own only the boundary block plus
    their generation tail."""
    cfg = JaxDecodeConfig(
        context_length=2048,
        max_running_requests=8,
        new_tokens_per_chunk=8,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    params = init_params(TINY, jax.random.PRNGKey(0))
    eng.set_model(params, TINY)
    eng.initialize()
    try:
        prompt = [1 + (i % 40) for i in range(300)]  # covered=299: 2 full + 1
        import threading

        results = [None] * 4

        def run(i):
            results[i] = eng.generate(
                ModelRequest(
                    input_ids=list(prompt),
                    gconfig=GenerationHyperparameters(
                        greedy=True, max_new_tokens=4
                    ),
                ),
                timeout=600,
            )

        ts = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(600)
        expected = greedy_reference(params, prompt, 4)
        for i in range(4):
            assert results[i] is not None
            assert results[i].output_tokens == expected
        m = eng.get_metrics()
        assert m["prefix_forks_total"] >= 3, m
        # dense would hold 4 x 3 = 12+ blocks of prompt KV; aliasing holds
        # the 2 full blocks once + one boundary/tail block per request
        assert m["kv_tokens_allocated"] <= (2 + 4 * 1 + 2) * 128, m
    finally:
        eng.destroy()


@pytest.mark.slow
def test_reclaim_never_eats_inflight_donor(cpu_devices):
    """Regression (round-5 review): a fork that hits PoolDry must not
    reclaim its own DONOR — here a PARKED slot whose admission-time
    registration makes it the prefix donor. Pre-fix, _reclaim_blocks
    evicted that parked slot, zeroed its block table, and the retried
    fork aliased null-block garbage and REGISTERED it as a valid shared
    prefix (silent rollout corruption). Post-fix the fork defers, the
    donor survives, and the deferred request later decodes exactly."""
    from areal_tpu.engine.jax_decode import _Slot

    cfg = JaxDecodeConfig(
        context_length=2048,
        max_running_requests=4,
        new_tokens_per_chunk=8,
        kv_pool_tokens=128,  # floor: 16 usable blocks
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    params = init_params(TINY, jax.random.PRNGKey(0))
    eng.set_model(params, TINY)
    eng.initialize()
    try:
        eng.pause_generation()  # drive the scheduler by hand
        # A prefills (registers its prompt prefix), decodes one chunk,
        # then is interrupted -> parked in slot 0, registration intact
        prompt_a = [1 + (i % 40) for i in range(300)]  # 3 blocks
        a = _Slot(rid="a", prompt=list(prompt_a),
                  gconfig=GenerationHyperparameters(greedy=True,
                                                    max_new_tokens=64),
                  future=None, loop=None)
        eng._request_q.put(a)
        with eng._sched_lock:
            eng._admit()
            eng._run_chunk(eng._active_mask())
        eng.abort_all()
        (donor_slot, _, _) = eng._parked["a"]
        assert tuple(prompt_a[:-1]) in eng._prefix_lookup
        donor_blocks = list(eng._alloc.tables[donor_slot, :3])

        # hog exactly the remaining 13 blocks with a long active request
        hog = _Slot(rid="hog",
                    prompt=[2 + (i % 30) for i in range(1657)],
                    gconfig=GenerationHyperparameters(greedy=True,
                                                      max_new_tokens=120),
                    future=None, loop=None)
        eng._request_q.put(hog)
        with eng._sched_lock:
            eng._admit()
        assert eng._alloc.free_blocks == 0, eng._alloc.free_blocks

        # same-prompt request: donor fork needs a boundary block -> dry.
        # The reclaim scan must NOT evict the parked donor.
        c = _Slot(rid="c", prompt=list(prompt_a),
                  gconfig=GenerationHyperparameters(greedy=True,
                                                    max_new_tokens=4),
                  future=None, loop=None)
        eng._request_q.put(c)
        with eng._sched_lock:
            eng._admit()
        assert "a" in eng._parked, "reclaim evicted the in-flight donor"
        assert list(eng._alloc.tables[donor_slot, :3]) == donor_blocks
        assert all(b != 0 for b in donor_blocks)
        assert tuple(prompt_a[:-1]) in eng._prefix_lookup

        # drive to completion: the hog finishes (pool pressure may evict
        # the parked donor NOW - legal, c is no longer mid-fork), then c
        # admits and must decode the exact greedy continuation
        for _ in range(60):
            with eng._sched_lock:
                eng._admit()
                act = eng._active_mask()
                if act.any():
                    eng._run_chunk(act)
            if c.stop_reason is not None and hog.stop_reason is not None:
                break
        assert c.stop_reason is not None, "c never completed"
        assert c.tokens == greedy_reference(params, prompt_a, 4)
    finally:
        eng.destroy()
