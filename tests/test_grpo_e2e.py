"""End-to-end GRPO slice: decode engine -> RLVR workflow -> PPO actor ->
weight update back into the decode engine.

This is the TPU analogue of the reference's 2-GPU GRPO integration test
(areal/tests/grpo/test_grpo.py:13-63), shrunk to a tiny random model on the
8-virtual-device CPU mesh. We assert the full pipeline contract (shapes,
stats, version flow, weight propagation) and that training moves the policy
toward a dense verifiable reward.
"""

import numpy as np
import pytest

import jax

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
    MicroBatchSpec,
    NormConfig,
    OptimizerConfig,
    PPOActorConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec, WeightUpdateMeta
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.engine.ppo.actor import JaxPPOActor
from areal_tpu.models.qwen2 import ModelConfig
from areal_tpu.workflow.rlvr import RLVRWorkflow

TINY = ModelConfig(
    vocab_size=32,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

TARGET_TOKEN = 16


def dense_reward(prompt, completion, prompt_ids, completion_ids, **kwargs):
    """Reward pulling the first generated token toward TARGET_TOKEN."""
    return 1.0 - abs(completion_ids[0] - TARGET_TOKEN) / 32.0


class ListLoader:
    def __init__(self, items, batch_size):
        self.items = items
        self.batch_size = batch_size

    def __iter__(self):
        for i in range(0, len(self.items), self.batch_size):
            yield self.items[i : i + self.batch_size]


@pytest.fixture(scope="module")
def pipeline(cpu_devices):
    actor_cfg = PPOActorConfig(
        experiment_name="e2e",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
        optimizer=OptimizerConfig(
            lr=3e-3, warmup_steps_proportion=0.0, lr_scheduler_type="constant"
        ),
        gradient_checkpointing=False,
        group_size=4,
        ppo_n_minibatches=2,
        eps_clip=0.2,
        kl_ctl=0.0,
        adv_norm=NormConfig(mean_level="group", std_level="group", group_size=4),
        use_decoupled_loss=True,
        temperature=1.0,
    )
    actor = JaxPPOActor(actor_cfg)
    actor.model_config = TINY
    actor.create_process_group(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    actor.initialize(None, FinetuneSpec(1, 64, 8))

    rollout = JaxDecodeEngine(
        JaxDecodeConfig(
            context_length=64,
            max_running_requests=8,
            new_tokens_per_chunk=4,
            dtype="float32",
            kv_cache_dtype="float32",
        ),
        InferenceEngineConfig(
            max_concurrent_rollouts=16,
            consumer_batch_size=8,
            max_head_offpolicyness=2,
        ),
    )
    rollout.set_model(actor.params, TINY)
    rollout.initialize()
    actor.connect_engine(rollout, WeightUpdateMeta.from_memory())
    yield actor, rollout
    rollout.destroy()
    actor.destroy()


@pytest.mark.slow
def test_grpo_end_to_end(pipeline):
    actor, rollout = pipeline
    gconfig = GenerationHyperparameters(
        n_samples=4, max_new_tokens=8, temperature=1.0
    )
    workflow = RLVRWorkflow(dense_reward, gconfig)
    loader = ListLoader(
        [dict(input_ids=[1 + (i % 4), 2, 3]) for i in range(64)], batch_size=2
    )

    mean_rewards = []
    for step in range(6):
        batch = rollout.prepare_batch(loader, workflow=workflow)
        assert batch["input_ids"].shape[0] == 8  # 2 prompts x 4 samples
        assert "logprobs" in batch and "versions" in batch
        mean_rewards.append(float(np.mean(batch["rewards"])))

        # decoupled PPO: recompute proximal logp under current weights
        batch["prox_logp"] = actor.compute_logp(batch)
        actor.compute_advantages(batch)
        assert np.isfinite(batch["advantages"]).all()
        stats = actor.ppo_update(batch)
        assert np.isfinite(stats[0]["actor/loss"]) if "actor/loss" in stats[0] else True

        actor.set_version(step + 1)
        rollout.pause()
        actor.update_weights(None)
        rollout.set_version(step + 1)
        rollout.resume()

    # Version stamping flowed through generation. prepare_batch keeps >=2
    # batches in flight, so the returned rollout may have been generated up
    # to max_head_offpolicyness (=2) versions before the current one (6) —
    # the guaranteed lower bound is 4, not 6.
    batch = rollout.prepare_batch(loader, workflow=workflow)
    out_versions = batch["versions"][batch["versions"] >= 0]
    assert out_versions.max() >= 4

    # Reward trend over 6 tiny steps is dominated by sampling noise; the
    # deterministic update-direction check lives in test_ppo_actor.py. Here
    # we assert the pipeline stayed numerically sane.
    assert np.isfinite(mean_rewards).all()
    assert 0.0 <= min(mean_rewards) and max(mean_rewards) <= 1.0
