"""In-memory weight push: wire format + end-to-end HTTP path to a server."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.core.weight_transfer import (
    flatten_named,
    pack_buckets,
    set_named,
    unpack_bucket,
)
from areal_tpu.models.qwen2 import init_params
from tests.test_remote_inf_engine import TINY, _greedy_req, _ServerThread


@pytest.fixture(scope="module")
def served_engine(cpu_devices):
    from areal_tpu.api.cli_args import InferenceEngineConfig, JaxDecodeConfig
    from areal_tpu.engine.jax_decode import JaxDecodeEngine

    cfg = JaxDecodeConfig(
        context_length=96,
        max_running_requests=4,
        new_tokens_per_chunk=4,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    st = _ServerThread(eng)
    yield eng, st.addr
    st.stop()
    eng.destroy()


@pytest.fixture(scope="module")
def client(served_engine):
    from areal_tpu.api.cli_args import InferenceEngineConfig
    from areal_tpu.core.remote_inf_engine import RemoteInfEngine

    _, addr = served_engine
    c = RemoteInfEngine(
        InferenceEngineConfig(setup_timeout=30, request_timeout=60)
    )
    c.initialize(addr=addr)
    yield c
    c.destroy()


def test_pack_unpack_roundtrip_bf16():
    rng = np.random.RandomState(0)
    import ml_dtypes

    named = {
        "a/w": rng.randn(16, 8).astype(np.float32),
        "a/b": rng.randn(8).astype(ml_dtypes.bfloat16),
        "c": np.arange(10, dtype=np.int32),
    }
    buckets = list(pack_buckets(named, chunk_mb=512))
    assert len(buckets) == 1
    out = unpack_bucket(buckets[0])
    assert set(out) == set(named)
    for k in named:
        assert out[k].dtype == named[k].dtype
        np.testing.assert_array_equal(
            np.asarray(out[k], np.float32), np.asarray(named[k], np.float32)
        )


def test_pack_respects_chunk_limit():
    named = {f"p{i}": np.zeros((256, 1024), np.float32) for i in range(8)}  # 1 MiB each
    buckets = list(pack_buckets(named, chunk_mb=2))
    assert len(buckets) == 4  # 2 tensors per 2 MiB bucket
    merged = {}
    for b in buckets:
        merged.update(unpack_bucket(b))
    assert set(merged) == set(named)


def test_flatten_set_named_roundtrip():
    params = init_params(TINY, jax.random.PRNGKey(1))
    named = flatten_named(params)
    assert any(k.startswith("layers/") for k in named)
    # perturb one leaf by name, set back
    key = "final_norm"
    named2 = {key: np.asarray(named[key]) + 1.0}
    new = set_named(params, named2)
    np.testing.assert_allclose(
        np.asarray(new["final_norm"]), np.asarray(params["final_norm"]) + 1.0
    )
    with pytest.raises(KeyError):
        set_named(params, {"not/a/leaf": np.zeros(1)})


@pytest.mark.slow
def test_dcn_push_end_to_end(served_engine, client):
    """Push perturbed weights over HTTP; server output must change and the
    version must be stamped."""
    import asyncio

    eng, _ = served_engine
    prompt = [3, 1, 4, 1, 5]
    before = asyncio.run(client.agenerate(_greedy_req(prompt, 6)))

    new_params = init_params(TINY, jax.random.PRNGKey(99))
    client.update_weights_from_tensor(
        flatten_named(new_params), version=7, chunk_mb=1
    )
    assert eng.get_version() == 7
    after = asyncio.run(client.agenerate(_greedy_req(prompt, 6)))
    assert after.output_versions == [7] * after.output_len
    assert after.output_tokens != before.output_tokens
    # and the server's params really are the pushed ones
    np.testing.assert_allclose(
        np.asarray(eng.params["final_norm"]),
        np.asarray(new_params["final_norm"]),
        atol=1e-6,
    )


def test_oversized_tensor_splits_into_parts():
    """A tensor bigger than the bucket limit streams as multiple frames and
    reassembles via WeightStaging."""
    from areal_tpu.core.weight_transfer import WeightStaging

    rng = np.random.RandomState(2)
    big = rng.randn(1200, 1024).astype(np.float32)  # ~4.7 MiB
    named = {"big": big, "small": np.ones(4, np.float32)}
    buckets = list(pack_buckets(named, chunk_mb=1))
    assert len(buckets) >= 5  # split across >= ceil(4.7) frames
    st = WeightStaging()
    for b in buckets:
        st.add_bucket(b)
    out = st.finalize()
    np.testing.assert_array_equal(out["big"], big)
    np.testing.assert_array_equal(out["small"], named["small"])


def test_staging_rejects_incomplete():
    from areal_tpu.core.weight_transfer import WeightStaging

    named = {"w": np.zeros((600, 1024), np.float32)}  # ~2.3 MiB
    buckets = list(pack_buckets(named, chunk_mb=1))
    st = WeightStaging()
    st.add_bucket(buckets[0])  # only the first part
    with pytest.raises(RuntimeError, match="incomplete"):
        st.finalize()


def test_staging_ignores_duplicate_frames():
    """arequest_with_retry re-sends frames whose response was lost; coverage
    is tracked by byte range so duplicates must not double-count (a
    duplicated middle part previously materialised tensors with zero-filled
    tails)."""
    from areal_tpu.core.weight_transfer import WeightStaging

    rng = np.random.RandomState(3)
    big = rng.randn(1200, 1024).astype(np.float32)  # splits at chunk_mb=1
    named = {"big": big, "small": np.arange(8, dtype=np.float32)}
    buckets = list(pack_buckets(named, chunk_mb=1))
    st = WeightStaging()
    # every frame delivered twice, including after completion
    for b in buckets:
        st.add_bucket(b)
        st.add_bucket(b)
    for b in buckets:
        st.add_bucket(b)
    out = st.finalize()
    np.testing.assert_array_equal(out["big"], big)
    np.testing.assert_array_equal(out["small"], named["small"])


def test_staging_overlapping_resplit_never_materializes_holes():
    """Coverage is tracked as MERGED intervals: a retry that re-splits a
    tensor differently must not let the SUM of part lengths reach total
    while the union still has a hole (the old sum-accounting materialized
    tensors with zero-filled gaps)."""
    import json
    import struct

    from areal_tpu.core.weight_transfer import WeightStaging

    rng = np.random.RandomState(5)
    data = rng.randint(0, 255, 100, dtype=np.uint8).tobytes()

    def frame(parts):
        """Build one wire frame holding byte ranges [(off, n), ...]."""
        manifest, chunks, size = [], [], 0
        for off, n in parts:
            manifest.append(
                dict(
                    name="w", shape=[100], dtype="uint8", offset=size,
                    nbytes=n, part_offset=off, total_nbytes=100,
                )
            )
            chunks.append(data[off : off + n])
            size += n
        mjson = json.dumps(manifest).encode()
        return struct.pack("<Q", len(mjson)) + mjson + b"".join(chunks)

    st = WeightStaging()
    # split A delivers [0, 60); split B (a re-chunked retry) delivers
    # [0, 40) — summed lengths 100 >= total, union only covers [0, 60)
    st.add_bucket(frame([(0, 60)]))
    st.add_bucket(frame([(0, 40)]))
    assert "w" not in st.ready, "tensor materialized with a 40-byte hole"
    with pytest.raises(RuntimeError, match="incomplete"):
        st.finalize()
    # the missing range arrives -> correct bytes
    st.add_bucket(frame([(40, 60)]))
    out = st.finalize()
    np.testing.assert_array_equal(out["w"], np.frombuffer(data, np.uint8))


def test_pack_buckets_accepts_iterables_and_noncontiguous():
    """pack_buckets takes lazy (name, array) producers (the pipelined push
    path) and handles non-contiguous views through its zero-copy slicing."""
    rng = np.random.RandomState(6)
    base = rng.randn(64, 48).astype(np.float32)
    named = {"t": base.T, "s": base[::2, 1:5]}  # non-contiguous views

    def produce():
        for k, v in named.items():
            yield k, v

    from areal_tpu.core.weight_transfer import WeightStaging

    st = WeightStaging()
    for b in pack_buckets(produce(), chunk_mb=0.005):
        st.add_bucket(b)
    merged = st.finalize()
    assert set(merged) == set(named)
    for k in named:
        np.testing.assert_array_equal(merged[k], np.asarray(named[k]))


def test_staging_reset_clears_partial_state():
    from areal_tpu.core.weight_transfer import WeightStaging

    named = {"w": np.zeros((600, 1024), np.float32)}
    buckets = list(pack_buckets(named, chunk_mb=1))
    st = WeightStaging()
    st.add_bucket(buckets[0])
    st.reset()
    # a fresh complete push reassembles cleanly after the reset
    for b in buckets:
        st.add_bucket(b)
    out = st.finalize()
    assert out["w"].shape == (600, 1024)


# -- KV-frame payloads on the shared staging plumbing (ISSUE 10) --------
# The migrated-session wire format rides the SAME framed buckets as the
# weight push; these pin the staging contracts the migration relies on
# for non-weight payloads: torn-frame rejection before a byte stages,
# interval re-merge across differently-split retry frames, and the
# empty-manifest edge cases.


def _kv_session_parts(n_tokens=12, nb=3, seed=0):
    from areal_tpu.core.weight_transfer import pack_kv_session

    rng = np.random.RandomState(seed)
    k = rng.rand(2, nb, 4, 2, 4).astype(np.float32)
    v = rng.rand(2, nb, 4, 2, 4).astype(np.float32)
    meta = dict(
        rid="sess", covered=n_tokens, tokens=list(range(n_tokens)),
        rope_delta=0, base_key=[1, 2], weight_version=0, nb=nb,
    )
    return meta, k, v, pack_kv_session


def test_kv_frame_torn_rejection():
    """A truncated KV frame must raise BEFORE anything stages (silently
    staging a short part would count phantom coverage and materialize a
    corrupt session)."""
    from areal_tpu.core.weight_transfer import WeightStaging

    meta, k, v, pack_kv_session = _kv_session_parts()
    frames = list(pack_kv_session(meta, k, v, chunk_mb=0.001))
    assert len(frames) >= 2
    st = WeightStaging()
    for cut in (3, len(frames[0]) // 2, len(frames[0]) - 1):
        with pytest.raises(ValueError, match="torn"):
            st.add_bucket(frames[0][:cut])
    # nothing staged by the torn attempts; the intact frames still land
    assert len(st) == 0 and not st._bufs
    for f in frames:
        st.add_bucket(f)
    from areal_tpu.core.weight_transfer import unpack_kv_sessions

    (got_meta, got_k, got_v, got_scales), = unpack_kv_sessions(st.finalize())
    assert got_meta == meta and got_scales is None
    assert np.array_equal(got_k, k) and np.array_equal(got_v, v)


def test_kv_frames_interval_remerge_across_resplit_retries():
    """A retry that re-packs the same session at a DIFFERENT chunk size
    overlaps the original frames' byte ranges arbitrarily; merged-interval
    coverage must count each byte once and still materialize exact
    tensors (a plain coverage sum would double-count and either corrupt
    or wedge the session)."""
    from areal_tpu.core.weight_transfer import (
        WeightStaging,
        unpack_kv_sessions,
    )

    meta, k, v, pack_kv_session = _kv_session_parts(seed=1)
    frames_a = list(pack_kv_session(meta, k, v, chunk_mb=0.001))
    frames_b = list(pack_kv_session(meta, k, v, chunk_mb=0.0017))
    assert len(frames_a) != len(frames_b)  # genuinely different splits
    st = WeightStaging()
    # half of split A lands, then the full re-split retry replays B
    for f in frames_a[: len(frames_a) // 2]:
        st.add_bucket(f)
    for f in frames_b:
        st.add_bucket(f)
    (got_meta, got_k, got_v, got_scales), = unpack_kv_sessions(st.finalize())
    assert got_meta == meta and got_scales is None
    assert np.array_equal(got_k, k) and np.array_equal(got_v, v)


def test_unpack_bucket_parts_empty_manifest_cases():
    """Empty payload sets: pack of nothing yields no frames; a frame
    whose manifest is an empty list unpacks to no parts (not an error);
    an empty staging finalizes to {} and holds no sessions."""
    import json as _json
    import struct as _struct

    from areal_tpu.core.weight_transfer import (
        WeightStaging,
        pack_buckets,
        unpack_bucket_parts,
        unpack_kv_sessions,
    )

    assert list(pack_buckets({})) == []
    mjson = _json.dumps([]).encode()
    empty_frame = _struct.pack("<Q", len(mjson)) + mjson
    assert unpack_bucket_parts(empty_frame) == []
    st = WeightStaging()
    st.add_bucket(empty_frame)
    assert unpack_kv_sessions(st.finalize()) == []
    # sub-header garbage is torn, not empty
    with pytest.raises(ValueError, match="torn"):
        unpack_bucket_parts(b"\x01\x02")


def test_unpack_kv_sessions_rejects_structurally_incomplete():
    from areal_tpu.core.weight_transfer import (
        WeightStaging,
        unpack_kv_sessions,
    )

    meta, k, v, pack_kv_session = _kv_session_parts(seed=2)
    frames = list(pack_kv_session(meta, k, v, chunk_mb=64))
    st = WeightStaging()
    for f in frames:
        st.add_bucket(f)
    staged = st.finalize()
    # blocks without metadata
    no_meta = {n: a for n, a in staged.items() if not n.startswith("kvmeta/")}
    with pytest.raises(ValueError, match="without session metadata"):
        unpack_kv_sessions(no_meta)
    # metadata without blocks
    no_blocks = {n: a for n, a in staged.items() if n.startswith("kvmeta/")}
    with pytest.raises(ValueError, match="incomplete"):
        unpack_kv_sessions(no_blocks)
    # malformed metadata (missing required resume fields)
    import json as _json

    bad = dict(staged)
    bad_meta = {kk: vv for kk, vv in meta.items() if kk != "base_key"}
    bad["kvmeta/sess"] = np.frombuffer(
        _json.dumps(bad_meta).encode(), dtype=np.uint8
    )
    with pytest.raises(ValueError, match="malformed"):
        unpack_kv_sessions(bad)
