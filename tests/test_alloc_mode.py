"""Allocation-mode grammar tests (parity: areal/tests/test_allocation_mode.py)."""

import pytest

from areal_tpu.api.alloc_mode import (
    AllocationMode,
    AllocationType,
    InvalidAllocationModeError,
    ParallelStrategy,
)


def test_train_only_colocate():
    m = AllocationMode.from_str("d4t2p1")
    assert m.type_ == AllocationType.COLOCATE
    assert m.train.dp_size == 4
    assert m.train.tp_size == 2
    assert m.train.pp_size == 1
    assert m.train.world_size == 8
    assert m.train_backend == "jax"


def test_decoupled_train():
    m = AllocationMode.from_str("jax:d4t2+jax:d8")
    assert m.type_ == AllocationType.DECOUPLED_TRAIN
    assert m.gen.world_size == 8
    assert m.train.world_size == 8
    assert m.gen_backend == "jax"
    assert m.gen_instance_size == 2


def test_reference_syntax_accepted():
    m = AllocationMode.from_str("sglang:d4t2+fsdp:d8")
    assert m.gen_backend == "sglang"
    assert m.train_backend == "fsdp"


def test_colocate_rl():
    m = AllocationMode.from_str("jax:d2t4|jax:d2t4")
    assert m.type_ == AllocationType.COLOCATE
    assert m.gen.tp_size == 4
    assert m.train.tp_size == 4


def test_llm_server_only():
    m = AllocationMode.from_str("vllm:d2t4")
    assert m.type_ == AllocationType.LLM_SERVER_ONLY
    assert m.gen.world_size == 8


def test_decoupled_eval():
    m = AllocationMode.from_str("jax:d4t2+eval")
    assert m.type_ == AllocationType.DECOUPLED_EVAL


def test_context_parallel_dim():
    m = AllocationMode.from_str("d2c2t2")
    assert m.train.cp_size == 2
    assert m.train.world_size == 8


def test_moe_hybrid():
    m = AllocationMode.from_str("jax:d4+(attn:d2t2|ffn:d2e2)")
    assert m.train.ep_size == 2
    assert m.train.tp_size == 2
    assert m.train.world_size == 4


def test_moe_hybrid_world_size_mismatch():
    with pytest.raises(Exception):
        AllocationMode.from_str("(attn:d4t2|ffn:d2e2)")


def test_duplicate_dim_rejected():
    with pytest.raises(Exception):
        AllocationMode.from_str("d2d4")


def test_garbage_rejected():
    with pytest.raises(InvalidAllocationModeError):
        AllocationMode.from_str("notavalidmode:::")


def test_parallel_strategy_props():
    p = ParallelStrategy(
        tensor_parallel_size=2,
        data_parallel_size=2,
        context_parallel_size=2,
        expert_parallel_size=2,
    )
    assert p.world_size == 8
    assert p.expert_model_parallel_size == 2
    assert p.expert_data_parallel_size == 4
    assert str(ParallelStrategy(data_parallel_size=4)) == "d4"


def test_standalone_jax_is_inference_only():
    # "jax" serves both roles; standalone it is ALWAYS inference (documented).
    m = AllocationMode.from_str("jax:d8")
    assert m.type_ == AllocationType.LLM_SERVER_ONLY
    assert m.train is None


def test_inference_side_rejects_cp_ep_dims():
    with pytest.raises(Exception, match="train-only"):
        AllocationMode.from_str("jax:d4c2")


def test_train_backend_on_inference_side_rejected():
    with pytest.raises(Exception, match="not an inference backend"):
        AllocationMode.from_str("megatron:d4+jax:d4")


def test_standalone_fsdp_is_trainer():
    m = AllocationMode.from_str("fsdp:d8")
    assert m.type_ == AllocationType.COLOCATE
    assert m.train.world_size == 8


def test_colocate_world_size_mismatch_rejected():
    with pytest.raises(Exception, match="matching world"):
        AllocationMode.from_str("jax:d2|d8")


def test_moe_hybrid_rejected_on_inference_side():
    with pytest.raises(Exception, match="not valid for an inference"):
        AllocationMode.from_str("jax:(attn:d2t2|ffn:d2e2)")


def test_unbalanced_parens_rejected():
    with pytest.raises(InvalidAllocationModeError):
        AllocationMode.from_str("(attn:d2t2|ffn:d2e2")
