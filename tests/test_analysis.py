"""areal-lint tier-1 suite.

One test per rule against the seeded known-bad fixtures under
tests/fixtures/lint/, the pragma/baseline semantics, the repo-wide
clean-against-baseline gate (the acceptance invariant:
`python -m areal_tpu.analysis areal_tpu/` exits 0), and a regression test
reproducing the PR 3 zero-copy alias hazard pattern.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from areal_tpu.analysis import Baseline, analyze_paths
from areal_tpu.analysis.core import RULES

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "lint"


def _codes(findings):
    return {f.rule for f in findings}


def _run_fixture(name):
    return analyze_paths([str(FIXTURES / name)])


# -- one test per rule -------------------------------------------------------


def test_ar101_unguarded_multi_context_write():
    fs = _run_fixture("ar101_unguarded.py")
    assert _codes(fs) == {"AR101"}
    (f,) = fs
    assert f.key == "Worker._counter"
    # negative space: the queue attr, the lock-guarded attr and the
    # registry-declared attr must NOT fire
    assert "_safe_q" not in f.message
    assert all("locked_total" not in x.key and "_fenced" not in x.key for x in fs)


def test_ar102_lock_order_cycle():
    fs = _run_fixture("ar102_cycle.py")
    assert _codes(fs) == {"AR102"}
    (f,) = fs
    assert "Pipeline._a" in f.key and "Pipeline._b" in f.key


def test_ar103_rank_violation():
    fs = _run_fixture("ar103_rank.py")
    assert _codes(fs) == {"AR103"}
    (f,) = fs
    assert f.key == "Ranked._high->Ranked._low"


def test_ar104_unknown_guard():
    fs = _run_fixture("ar104_unknown_guard.py")
    assert _codes(fs) == {"AR104"}
    keys = {f.key for f in fs}
    assert keys == {
        "Annotated._registry_attr",
        "NoSuchClass._x",
        "Annotated._bad",
    }


def test_ar201_host_sync_in_loop():
    fs = _run_fixture("ar201_host_sync.py")
    assert _codes(fs) == {"AR201"}
    # .item(), float(), np.asarray() — one finding each, all inside the loop
    assert len(fs) == 3
    assert {f.line for f in fs} == {18, 19, 20}


def test_ar202_donated_buffer_reuse():
    fs = _run_fixture("ar202_donated.py")
    assert _codes(fs) == {"AR202"}
    (f,) = fs
    assert f.key == "bad.state"  # good() rebinding must not fire


def test_ar203_alias_upload():
    fs = _run_fixture("ar203_alias.py")
    assert _codes(fs) == {"AR203"}
    keys = {f.key for f in fs}
    # local pattern AND the cross-method self-attribute pattern; the
    # explicit-copy variant must not fire
    assert keys == {
        "upload_then_mutate.lengths",
        "Engine.self._slot_lengths",
    }


def test_ar204_retrace_hazards():
    fs = _run_fixture("ar204_retrace.py")
    assert _codes(fs) == {"AR204"}
    keys = {f.key for f in fs}
    assert keys == {"bad_loop.step.arg1", "bad_static.bucketed.arg1"}


def test_ar106_swallowed_exceptions():
    fs = _run_fixture("ar106_swallow.py")
    assert _codes(fs) == {"AR106"}
    keys = {f.key for f in fs}
    # the four swallow shapes fire; re-raise / log / preserve / narrow
    # escapes must not
    assert keys == {
        "swallow_pass.except#0",
        "swallow_bare.except#0",
        "swallow_busy.except#0",
        "swallow_tuple.except#0",
    }


def test_ar106_scoped_to_fault_bearing_packages(tmp_path):
    """AR106 runs only over areal_tpu/{core,launcher,engine}/ — a swallow
    in, say, utils/ (the retry loop's home) is out of scope; a fixture
    outside the areal_tpu tree is always checked."""
    src = textwrap.dedent(
        """
        def f(x):
            try:
                return 1 / x
            except Exception:
                pass
        """
    )
    tree = tmp_path / "areal_tpu"
    for pkg, expect in [("core", True), ("utils", False), ("models", False)]:
        d = tree / pkg
        d.mkdir(parents=True)
        mod = d / "mod.py"
        mod.write_text(src)
        fs = [f for f in analyze_paths([str(mod)]) if f.rule == "AR106"]
        assert bool(fs) == expect, (pkg, fs)


def test_ar106_pragma_suppresses():
    import tempfile, os

    src = (
        "def f(x):\n"
        "    try:\n"
        "        return 1 / x\n"
        "    except Exception:  # areal-lint: disable=AR106\n"
        "        pass\n"
    )
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "frag.py")
        with open(p, "w") as fh:
            fh.write(src)
        assert not [f for f in analyze_paths([p]) if f.rule == "AR106"]


def test_ar301_route_pairing():
    fs = _run_fixture("ar301_routes.py")
    assert _codes(fs) == {"AR301"}
    assert {f.key for f in fs} == {"/missing", "/dead_route"}
    # negative space: the paired route, the `# wire: external` route, and
    # the f-string ref with a query string must all stay clean
    assert not any("paired" in f.key or "ops_surface" in f.key for f in fs)


def test_ar301_client_only_sweep_stays_quiet(tmp_path):
    """No registrations harvested -> pairing cannot be judged; a bench.py
    style client-only sweep must not drown in unregistered-path noise."""
    mod = tmp_path / "client.py"
    mod.write_text(
        "async def poll(arequest_with_retry, addr):\n"
        "    return await arequest_with_retry(addr, '/not_registered')\n"
    )
    assert not [f for f in analyze_paths([str(mod)]) if f.rule == "AR301"]


def test_ar302_seam_validity():
    fs = _run_fixture("ar302_seams.py")
    assert _codes(fs) == {"AR302"}
    # the typo'd FaultPoint AND the embedded {"site": ...} plan fire; the
    # kv.* pattern that matches real seams must not
    assert {f.key for f in fs} == {"kv.sendd", "weight.push.*"}


def test_ar302_seam_collision(tmp_path):
    """One seam name fired from two modules: a single fnmatch pattern now
    perturbs two unrelated boundaries."""
    for mod in ("a", "b"):
        (tmp_path / f"{mod}.py").write_text(
            "from areal_tpu.core import fault_injection\n"
            "def go():\n"
            "    fault_injection.fire('shared.seam')\n"
        )
    fs = [f for f in analyze_paths([str(tmp_path)]) if f.rule == "AR302"]
    assert len(fs) == 1 and fs[0].key == "shared.seam"


def test_ar303_metrics_contract():
    fs = _run_fixture("ar303_metrics.py")
    assert _codes(fs) == {"AR303"}
    keys = {f.key for f in fs}
    # counter drift + undeclared *_KEYS entry + unproduced consumer read;
    # the declared counter, the produced poll key, and the produced
    # consumer read must not fire
    assert keys == {
        "Server._req_stats[rejectd]",
        "POLL_KEYS.kv_occupancy",
        "autoscale.prefill_lag",
    }


def test_ar304_stale_registry():
    fs = _run_fixture("ar304_stale_registry.py")
    assert _codes(fs) == {"AR304"}
    (f,) = fs
    # the still-live entry must not fire
    assert f.key == "Tracker._retired_attr"


def test_ar305_knob_drift():
    fs = _run_fixture("ar305_knob_drift.py")
    assert _codes(fs) == {"AR305"}
    # dest drift + phantom /info field; the mirrored flag, the explicit
    # dest= repair, the launcher-only annotation, and --host must not fire
    assert {f.key for f in fs} == {"tp_size", "info.legacy_knob"}


def test_ar3xx_pragma_suppresses(tmp_path):
    """Inline pragmas silence wire findings at their anchor site like any
    other rule — including the cross-file ones emitted from finalize()."""
    d = tmp_path / "fixtures"  # path keeps the registration checks scoped
    d.mkdir()
    mod = d / "wire_frag.py"
    mod.write_text(
        "def build(app, arequest_with_retry):\n"
        "    app.router.add_get('/dead', None)"
        "  # areal-lint: disable=AR301\n"
        "    # areal-lint: disable=AR301\n"
        "    return arequest_with_retry('a', '/missing')\n"
    )
    assert not [f for f in analyze_paths([str(mod)]) if f.rule == "AR301"]


def test_ar3xx_baseline_round_trip(tmp_path):
    """Baseline keys for the wire family are stable identifiers (paths,
    seam names, dests) and survive a save/load cycle; stale-entry and
    invalid-justification reporting applies to AR3xx unchanged."""
    fs = _run_fixture("ar301_routes.py")
    bl = Baseline.from_findings(fs)
    assert all(bl.covers(f) for f in fs)
    p = tmp_path / "bl.json"
    bl.save(str(p))
    bl2 = Baseline.load(str(p))
    assert all(bl2.covers(f) for f in fs)
    # stale reporting: fix the dead route -> its entry is reported unused
    remaining = [f for f in fs if f.key != "/dead_route"]
    stale = bl2.unused(remaining)
    assert [e["key"] for e in stale] == ["/dead_route"]
    # invalid(): the from_findings placeholders are flagged until justified
    assert len(bl2.invalid()) == len(bl2.entries) > 0


def test_cli_rules_family_filter_and_json():
    """`--rules AR3XX` expands to the whole family and excludes the rest;
    `--json` emits the stable schema CI and tools/lint.sh gate on."""
    r = subprocess.run(
        [
            sys.executable,
            "-m",
            "areal_tpu.analysis",
            str(FIXTURES / "ar301_routes.py"),
            str(FIXTURES / "ar201_host_sync.py"),
            "--no-baseline",
            "--rules",
            "AR3XX",
            "--json",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert r.returncode == 1
    data = json.loads(r.stdout)
    assert set(data) == {"findings", "baselined", "total", "invalid_baseline"}
    assert {f["rule"] for f in data["findings"]} == {"AR301"}
    for f in data["findings"]:
        assert set(f) == {"rule", "file", "line", "key", "message"}


# -- pragma + baseline semantics --------------------------------------------


def test_pragmas_suppress_everything():
    assert _run_fixture("pragmas_ok.py") == []


def test_baseline_covers_and_reports_stale(tmp_path):
    fs = _run_fixture("ar201_host_sync.py")
    bl = Baseline.from_findings(fs)
    assert all(bl.covers(f) for f in fs)
    # an entry whose finding disappeared is reported as stale
    bl.entries.append(
        {"file": "gone.py", "rule": "AR999", "key": "x", "justification": "j"}
    )
    stale = bl.unused(fs)
    assert len(stale) == 1 and stale[0]["file"] == "gone.py"
    # round-trips through disk
    p = tmp_path / "bl.json"
    bl.save(str(p))
    assert len(Baseline.load(str(p)).entries) == len(bl.entries)


def test_baseline_invalid_justifications_reported(tmp_path):
    """Regression (ISSUE 6 satellite): entries whose justification is
    empty, whitespace, missing, or still the `--write-baseline`
    placeholder are INVALID — they waive a rule without the review the
    justification field exists to force. Baseline.invalid() must surface
    them, and the CLI must report them through the same stderr-note
    channel as stale entries (exit code unchanged: the entry still
    suppresses its finding until someone justifies or fixes it)."""
    fs = _run_fixture("ar201_host_sync.py")
    bl = Baseline.from_findings(fs)  # placeholder justifications
    assert len(bl.invalid()) == len(bl.entries) > 0
    bl.entries[0]["justification"] = "real reason: oracle loop, sync is fine"
    bl.entries.append(
        {"file": "a.py", "rule": "AR201", "key": "k", "justification": "   "}
    )
    bl.entries.append({"file": "b.py", "rule": "AR201", "key": "k2"})
    invalid = bl.invalid()
    assert bl.entries[0] not in invalid
    assert bl.entries[-1] in invalid and bl.entries[-2] in invalid
    # CLI channel: same stderr note stream as stale entries, exit 0 when
    # every finding is covered
    p = tmp_path / "bl.json"
    bl.save(str(p))
    r = subprocess.run(
        [
            sys.executable,
            "-m",
            "areal_tpu.analysis",
            str(FIXTURES / "ar201_host_sync.py"),
            "--baseline",
            str(p),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "invalid baseline entry" in r.stderr
    # the justified entry is not reported; the placeholder/empty ones are
    assert r.stderr.count("invalid baseline entry") == len(invalid)


def test_cli_exit_codes(tmp_path):
    bad = FIXTURES / "ar201_host_sync.py"
    env_cmd = [sys.executable, "-m", "areal_tpu.analysis"]
    r = subprocess.run(
        env_cmd + [str(bad), "--no-baseline"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert r.returncode == 1
    assert "AR201" in r.stdout
    # --write-baseline then a rerun against it exits 0
    bl = tmp_path / "bl.json"
    r = subprocess.run(
        env_cmd + [str(bad), "--baseline", str(bl), "--write-baseline"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert r.returncode == 0, r.stderr
    r = subprocess.run(
        env_cmd + [str(bad), "--baseline", str(bl)],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr


# -- repo-wide gate ----------------------------------------------------------


def test_repo_clean_against_baseline():
    """THE acceptance invariant: the whole package is clean against the
    checked-in baseline. New multi-thread writes, lock inversions, or
    hot-path hazards land here as failures with a rule code and a fix /
    annotate / baseline decision to make."""
    findings = analyze_paths([str(REPO / "areal_tpu")])
    baseline = Baseline.load(str(REPO / "tools" / "lint_baseline.json"))
    new = [f.format() for f in findings if not baseline.covers(f)]
    assert not new, "\n".join(new)


def test_repo_wire_contracts_clean_without_baseline():
    """The AR3xx family gates STRICTER than the others: real wire-contract
    violations get fixed, never baselined, so the tree must be clean for
    the family even with the baseline ignored."""
    findings = [
        f
        for f in analyze_paths([str(REPO / "areal_tpu")])
        if f.rule.startswith("AR3")
    ]
    assert not findings, "\n".join(f.format() for f in findings)
    data = json.loads((REPO / "tools" / "lint_baseline.json").read_text())
    assert not [e for e in data["entries"] if e["rule"].startswith("AR3")]


def test_baseline_entries_justified():
    data = json.loads((REPO / "tools" / "lint_baseline.json").read_text())
    for e in data["entries"]:
        assert e.get("justification", "").strip(), f"unjustified entry {e}"
        assert e["rule"] in RULES


# -- PR 3 alias-hazard regression -------------------------------------------


def test_pr3_alias_hazard_pattern_detected(tmp_path):
    """The exact bug class PR 3 found by hand: the run-ahead dispatcher
    uploaded `self._slot_lengths` via jnp.asarray (zero-copy on CPU), then
    projected the host array forward in place while the dispatched chunk
    still read the device view. The analyzer must flag the pattern; the
    shipped fix (upload through np.array) must be clean."""
    bug = tmp_path / "bug.py"
    bug.write_text(
        textwrap.dedent(
            """
            import jax.numpy as jnp
            import numpy as np

            class Sched:
                def __init__(self):
                    self._slot_lengths = np.zeros(8, np.int32)
                    self._dev_lengths = None

                def dispatch(self, active, n_chunk):
                    self._dev_lengths = jnp.asarray(self._slot_lengths)
                    self._slot_lengths[active] += n_chunk
            """
        )
    )
    fs = analyze_paths([str(bug)])
    assert any(
        f.rule == "AR203" and "self._slot_lengths" in f.key for f in fs
    ), fs

    fixed = tmp_path / "fixed.py"
    fixed.write_text(
        textwrap.dedent(
            """
            import jax.numpy as jnp
            import numpy as np

            class Sched:
                def __init__(self):
                    self._slot_lengths = np.zeros(8, np.int32)
                    self._dev_lengths = None

                def dispatch(self, active, n_chunk):
                    self._dev_lengths = jnp.asarray(np.array(self._slot_lengths))
                    self._slot_lengths[active] += n_chunk
            """
        )
    )
    assert not [f for f in analyze_paths([str(fixed)]) if f.rule == "AR203"]


def test_fixture_rule_coverage():
    """Every cataloged rule has at least one seeded fixture that triggers
    it — adding a rule without a fixture fails here."""
    all_found = set()
    for p in sorted(FIXTURES.glob("ar*.py")):
        all_found |= _codes(analyze_paths([str(p)]))
    assert all_found == set(RULES), set(RULES) - all_found
