"""Decode engine: greedy parity with the training forward pass, stop
handling, version stamping across weight swaps, concurrent requests."""

import asyncio

import numpy as np
import pytest

import jax

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.models.qwen2 import ModelConfig, forward, init_params

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)


@pytest.fixture(scope="module")
def engine(cpu_devices):
    cfg = JaxDecodeConfig(
        context_length=96,
        max_running_requests=4,
        new_tokens_per_chunk=8,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    yield eng
    eng.destroy()


def greedy_reference(params, prompt, n_new):
    """Step-by-step greedy continuation using the training forward pass."""
    seq = list(prompt)
    for _ in range(n_new):
        T = len(seq)
        logits = forward(
            params,
            np.array(seq, dtype=np.int32),
            np.arange(T, dtype=np.int32),
            np.zeros(T, dtype=np.int32),
            TINY,
        )
        seq.append(int(np.argmax(np.asarray(logits[-1]))))
    return seq[len(prompt):]


@pytest.mark.slow
def test_greedy_decode_matches_forward(engine):
    prompt = [1, 5, 9, 13, 2]
    n_new = 11
    resp = engine.generate(
        ModelRequest(
            input_ids=prompt,
            gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=n_new),
        ),
        timeout=300,
    )
    assert resp.output_len == n_new
    assert resp.stop_reason == "length"
    expected = greedy_reference(engine.params, prompt, n_new)
    assert resp.output_tokens == expected
    # logprobs are the chosen-token logprobs, finite and <= 0
    assert all(lp <= 1e-6 and np.isfinite(lp) for lp in resp.output_logprobs)


@pytest.mark.slow
def test_stop_token_truncates(engine):
    prompt = [1, 5, 9, 13, 2]
    full = greedy_reference(engine.params, prompt, 12)
    stop_tok = full[4]
    # generation halts at the stop token's FIRST occurrence (inclusive)
    cut = full.index(stop_tok) + 1
    resp = engine.generate(
        ModelRequest(
            input_ids=prompt,
            gconfig=GenerationHyperparameters(
                greedy=True, max_new_tokens=12, stop_token_ids=[stop_tok]
            ),
        ),
        timeout=300,
    )
    assert resp.stop_reason == "stop"
    assert resp.output_tokens == full[:cut]
    assert len(resp.output_logprobs) == cut
    assert len(resp.output_versions) == cut


@pytest.mark.slow
def test_concurrent_requests_isolated(engine):
    async def run_all():
        reqs = [
            ModelRequest(
                input_ids=[2 + i, 7, 11],
                gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=6),
            )
            for i in range(6)  # more than max_running_requests
        ]
        return await asyncio.gather(*[engine.agenerate(r) for r in reqs])

    resps = asyncio.run(run_all())
    for i, resp in enumerate(resps):
        expected = greedy_reference(engine.params, [2 + i, 7, 11], 6)
        assert resp.output_tokens == expected, i


@pytest.mark.slow
def test_version_stamping_across_weight_update(engine):
    engine.set_version(3)
    resp = engine.generate(
        ModelRequest(
            input_ids=[1, 2, 3],
            gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=4),
        ),
        timeout=300,
    )
    assert resp.output_versions == [3, 3, 3, 3]
    # swap weights (same values) and bump version
    engine.update_weights_from_distributed(None, params=engine.params)
    engine.set_version(4)
    resp = engine.generate(
        ModelRequest(
            input_ids=[1, 2, 3],
            gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=4),
        ),
        timeout=300,
    )
    assert resp.output_versions == [4, 4, 4, 4]


@pytest.mark.slow
def test_pause_continue_generation(engine):
    engine.pause_generation()
    assert engine._gen_paused.is_set()
    engine.continue_generation()
    resp = engine.generate(
        ModelRequest(
            input_ids=[4, 4],
            gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=3),
        ),
        timeout=300,
    )
    assert resp.output_len == 3


@pytest.mark.slow
def test_sharded_decode_tp2(cpu_devices):
    """Gen-side tensor parallelism: params + KV cache sharded over a
    [1,1,1,2] decode mesh must reproduce the unsharded greedy output."""
    cfg = JaxDecodeConfig(
        context_length=64,
        max_running_requests=2,
        new_tokens_per_chunk=4,
        dtype="float32",
        kv_cache_dtype="float32",
        tensor_parallel_size=2,
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    try:
        assert eng.mesh is not None
        # every param leaf actually lives on 2 devices
        leaf = jax.tree.leaves(eng.params)[0]
        assert len(leaf.sharding.device_set) == 2
        assert len(eng._k_cache.sharding.device_set) == 2
        prompt = [1, 5, 9, 13, 2]
        # generous timeout: the tp=2 GSPMD compiles run on one CPU core and
        # slow down further when the full suite shares it (observed >900s
        # under a fully loaded suite run)
        resp = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=7),
            ),
            timeout=2400,
        )
        expected = greedy_reference(eng.params, prompt, 7)
        assert resp.output_tokens == expected
    finally:
        eng.destroy()


@pytest.mark.slow
def test_interrupt_resume_reuses_parked_kv(cpu_devices):
    """An interrupted request's KV stays parked in its slot; resuming with
    rid affinity (prompt + partial tokens) prefills NOTHING and continues
    the exact greedy continuation."""
    from areal_tpu.engine.jax_decode import _Slot

    cfg = JaxDecodeConfig(
        context_length=64,
        max_running_requests=2,
        new_tokens_per_chunk=4,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    try:
        eng.pause_generation()  # drive the scheduler by hand
        prompt = [1, 5, 9, 13, 2]
        full = greedy_reference(eng.params, prompt, 12)
        g = GenerationHyperparameters(greedy=True, max_new_tokens=12)
        item = _Slot(rid="r1", prompt=prompt, gconfig=g, future=None, loop=None)
        eng._request_q.put(item)
        with eng._sched_lock:
            eng._admit()
            eng._run_chunk(eng._active_mask())  # 4 tokens
        assert item.tokens == full[:4]
        n = eng.abort_all()
        assert n == 1 and item.stop_reason == "interrupt"
        assert "r1" in eng._parked

        # resume: prompt + partial tokens, same rid; count prefill calls
        calls = []
        orig = eng._get_prefill_fn
        eng._get_prefill_fn = lambda b: calls.append(b) or orig(b)
        g2 = GenerationHyperparameters(greedy=True, max_new_tokens=8)
        item2 = _Slot(
            rid="r1", prompt=prompt + item.tokens, gconfig=g2,
            future=None, loop=None,
        )
        eng._request_q.put(item2)
        with eng._sched_lock:
            eng._admit()
            for _ in range(2):
                if eng._active_mask().any():
                    eng._run_chunk(eng._active_mask())
        assert calls == [], "resume must not prefill anything"
        assert item2.tokens == full[4:12]
        assert "r1" not in eng._parked
    finally:
        eng.destroy()


@pytest.mark.slow
def test_gqa_kv_head_repeat_tp4(cpu_devices):
    """tp=4 > nKV=2: the engine repeats kv heads to tp so the cache shards
    4-ways instead of replicating, and greedy output is unchanged (the
    repeat transformation is semantics-preserving)."""
    cfg = JaxDecodeConfig(
        context_length=64,
        max_running_requests=2,
        new_tokens_per_chunk=4,
        dtype="float32",
        kv_cache_dtype="float32",
        tensor_parallel_size=4,
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    original = init_params(TINY, jax.random.PRNGKey(0))
    eng.set_model(original, TINY)
    eng.initialize()
    try:
        assert eng.model_config.num_key_value_heads == 4  # repeated 2 -> 4
        # cache kv-head dim is sharded over tp, not replicated
        spec = eng._k_cache.sharding.spec
        assert spec[3] == "tp", f"kv cache not sharded: {spec}"
        k = eng.params["layers"]["attn"]["k_kernel"]
        assert k.shape[-2] == 4
        prompt = [1, 5, 9, 13, 2]
        resp = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=6),
            ),
            timeout=900,
        )
        # reference computed with the ORIGINAL (unrepeated) params
        expected = greedy_reference(original, prompt, 6)
        assert resp.output_tokens == expected

        # Weight pushes carry UNREPEATED trainer weights; both ingest paths
        # must re-apply the repeat (regression: round-3 review finding).
        trained = init_params(TINY, jax.random.PRNGKey(1))
        eng.update_weights_from_distributed(None, trained, TINY)
        assert eng.model_config.num_key_value_heads == 4
        resp2 = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=4),
            ),
            timeout=900,
        )
        assert resp2.output_tokens == greedy_reference(trained, prompt, 4)

        from areal_tpu.core.weight_transfer import flatten_named

        trained2 = init_params(TINY, jax.random.PRNGKey(2))
        eng.update_weights_from_tensor(flatten_named(trained2), version=7)
        resp3 = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=4),
            ),
            timeout=900,
        )
        assert resp3.output_tokens == greedy_reference(trained2, prompt, 4)
    finally:
        eng.destroy()


def test_prefill_budget_bounds_admission(cpu_devices):
    """A burst of admissions must not all prefill in one scheduler pass:
    per-pass prefill work is capped at max_prefill_tokens, excess requests
    stay queued (order preserved) and still complete."""
    cfg = JaxDecodeConfig(
        context_length=192,
        max_running_requests=8,
        new_tokens_per_chunk=2,
        max_prefill_tokens=64,  # one 64-token bucket per pass
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    try:
        from areal_tpu.engine.jax_decode import _Slot

        eng.pause_generation()  # drive by hand
        g = GenerationHyperparameters(greedy=True, max_new_tokens=2)
        items = [
            _Slot(
                rid=f"r{i}",
                prompt=[1 + i] * 60,  # 64-token prefill bucket each
                gconfig=g,
                future=None,
                loop=None,
            )
            for i in range(4)
        ]
        for it in items:
            eng._request_q.put(it)
        with eng._sched_lock:
            eng._admit()
            # only the first fits the 64-token budget this pass
            rids = lambda: {s.rid for s in eng._slots if s is not None}
            assert rids() == {"r0"}
            eng._admit()
            assert rids() == {"r0", "r1"}
        eng.continue_generation()
        # the scheduler loop admits the rest across passes; all complete
        deadline = 300
        import time as _time

        t0 = _time.monotonic()
        while any(it.stop_reason is None for it in items):
            assert _time.monotonic() - t0 < deadline, "burst did not drain"
            _time.sleep(0.05)
    finally:
        eng.destroy()


@pytest.mark.slow
def test_stop_strings(cpu_devices):
    """Stop STRINGS (gconfig.stop) truncate generation at the earliest
    token boundary whose decoded prefix contains the string."""

    class DigitTok:
        eos_token_id = None

        def decode(self, ids):
            return "".join(str(i % 10) for i in ids)

    cfg = JaxDecodeConfig(
        context_length=64,
        max_running_requests=2,
        new_tokens_per_chunk=4,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig(), tokenizer=DigitTok())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    try:
        prompt = [1, 5, 9, 13, 2]
        full = greedy_reference(eng.params, prompt, 8)
        text = "".join(str(t % 10) for t in full)
        stop_s = text[2:4]  # a substring that first completes at token 4
        # precondition: the substring must not occur earlier, or the
        # expected boundary below is wrong (guards against TINY changes)
        assert stop_s not in text[:3]
        resp = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(
                    greedy=True, max_new_tokens=8, stop=[stop_s]
                ),
            ),
            timeout=600,
        )
        assert resp.stop_reason == "stop"
        assert resp.output_tokens == full[:4]
    finally:
        eng.destroy()


def test_frequency_penalty_reduces_repeats(cpu_devices):
    """A strong frequency penalty must strictly reduce token repetition vs
    the unpenalized run (same seed)."""
    def run(freq):
        cfg = JaxDecodeConfig(
            context_length=96,
            max_running_requests=1,
            new_tokens_per_chunk=8,
            dtype="float32",
            kv_cache_dtype="float32",
            random_seed=11,
        )
        eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
        eng.set_model(init_params(TINY, jax.random.PRNGKey(2)), TINY)
        eng.initialize()
        try:
            resp = eng.generate(
                ModelRequest(
                    input_ids=[3, 7, 11],
                    gconfig=GenerationHyperparameters(
                        max_new_tokens=48,
                        temperature=0.3,  # peaked -> repetitive baseline
                        frequency_penalty=freq,
                    ),
                ),
                timeout=600,
            )
            return resp.output_tokens
        finally:
            eng.destroy()

    base = run(0.0)
    pen = run(8.0)  # forceful penalty on a 64-token vocab
    uniq_base = len(set(base)) / len(base)
    uniq_pen = len(set(pen)) / len(pen)
    assert uniq_pen > uniq_base, (uniq_base, uniq_pen)


@pytest.mark.slow
def test_decode_under_foreign_global_mesh(cpu_devices):
    """Regression: a decode engine must trace against ITS OWN mesh even when
    another engine (the COLOCATE train engine) has installed a different
    process-global ambient mesh. Before the thread-local `mesh_scope`
    binding, `constrain` inside the prefill trace resolved the foreign
    8-device mesh while the decode params lived on 2 devices — the
    scheduler thread died on an incompatible-devices compile error and
    every subsequent request hung forever. An UNSHARDED engine (params on
    one device) under a foreign 8-device mesh triggers the same mismatch
    and compiles in seconds, so this guard runs in the default suite."""
    from areal_tpu.api.alloc_mode import ParallelStrategy
    from areal_tpu.parallel import mesh as mesh_lib

    foreign = mesh_lib.build_mesh(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    eng = None
    mesh_lib.set_current_mesh(foreign)
    try:
        cfg = JaxDecodeConfig(
            context_length=64,
            max_running_requests=2,
            new_tokens_per_chunk=4,
            dtype="float32",
            kv_cache_dtype="float32",
        )
        eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
        eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
        eng.initialize()
        prompt = [1, 5, 9, 13, 2]
        resp = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=5),
            ),
            timeout=2400,
        )
        assert resp.output_len == 5
        expected = greedy_reference(eng.params, prompt, 5)
        assert resp.output_tokens == expected
    finally:
        if eng is not None:
            eng.destroy()
        mesh_lib.set_current_mesh(None)


@pytest.mark.slow
def test_prefix_fork_group_decode(cpu_devices):
    """GRPO-group admission path: group_size same-prompt requests prefill
    ONCE; the rest fork the donor slot's prompt KV (a memcpy), and outputs
    stay exactly equal to the greedy reference. Parity target: the radix
    prefix cache the reference inherits from SGLang
    (areal/engine/sglang_remote.py:22)."""
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    cfg = JaxDecodeConfig(
        context_length=96,
        max_running_requests=4,
        new_tokens_per_chunk=8,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    try:
        prompt = [3, 7, 11, 2, 9, 4]
        n_new = 9
        g = GenerationHyperparameters(greedy=True, max_new_tokens=n_new)

        eng.pause_generation()
        with ThreadPoolExecutor(max_workers=4) as pool:
            futs = [
                pool.submit(
                    eng.generate,
                    ModelRequest(input_ids=list(prompt), gconfig=g),
                    600,
                )
                for _ in range(4)
            ]
            deadline = _time.monotonic() + 30
            while eng._request_q.qsize() < 4:
                assert _time.monotonic() < deadline, "requests never enqueued"
                _time.sleep(0.01)
            eng.continue_generation()
            results = [f.result(timeout=600) for f in futs]

        expected = greedy_reference(eng.params, prompt, n_new)
        for r in results:
            assert r.output_tokens == expected
            # latency observability: itl filled, one entry per token
            assert len(r.itl) == r.output_len
            assert all(v > 0 for v in r.itl)
            assert r.ttft != float("inf")
        assert eng._n_prefills == 1
        assert eng._n_prefix_forks == 3
        m = eng.get_metrics()
        assert m["prefix_forks_total"] == 3
        assert m["generated_tokens_total"] >= 4 * n_new

        # Retired slots keep their prompt KV: a later same-prompt request
        # reuses it (fork or in-place) without any new prefill.
        r = eng.generate(ModelRequest(input_ids=list(prompt), gconfig=g), timeout=600)
        assert r.output_tokens == expected
        assert eng._n_prefills == 1

        # A weight install invalidates the registry (old-weight KV must not
        # seed new-weight generation) — the next admission prefills again.
        eng.update_weights_from_tensor({}, version=1)
        r = eng.generate(ModelRequest(input_ids=list(prompt), gconfig=g), timeout=600)
        assert r.output_tokens == expected
        assert eng._n_prefills == 2
    finally:
        eng.destroy()


@pytest.mark.slow
def test_bucketed_chunk_attention_parity(cpu_devices):
    """Length-bucketed decode: with a large context_length the chunk fn
    runs on a sliced KV bucket (256 rows here) instead of the full cache;
    outputs must exactly match the dense greedy reference."""
    cfg = JaxDecodeConfig(
        context_length=2048,
        max_running_requests=2,
        new_tokens_per_chunk=8,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    try:
        prompt = [1, 5, 9, 13, 2, 7]
        n_new = 10
        resp = eng.generate(
            ModelRequest(
                input_ids=list(prompt),
                gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=n_new),
            ),
            timeout=600,
        )
        assert resp.output_tokens == greedy_reference(eng.params, prompt, n_new)
        # the bucketed variant (2 blocks = 256 rows << the 2048 context)
        # actually compiled and ran
        assert any(k[2] == 2 for k in eng._chunk_fns), eng._chunk_fns.keys()
        # paged accounting: this short request only ever held 2 of the 32
        # context-worth blocks (bucketed gather, not dense reservation)
        m = eng.get_metrics()
        assert m["kv_block_size"] == 128
        assert m["kv_tokens_allocated"] <= 2 * 128, m
    finally:
        eng.destroy()


@pytest.mark.slow
def test_parked_long_sequence_survives_bucketed_chunks(cpu_devices):
    """A parked long sequence must survive other slots' bucketed chunks:
    decode_step's active-masked cache write means the short request can
    run on a small bucket while the parked slot's KV (partly inside,
    partly beyond the bucket) passes through untouched — and the parked
    request then resumes with the exact greedy continuation."""
    from areal_tpu.engine.jax_decode import _Slot

    cfg = JaxDecodeConfig(
        context_length=2048,
        max_running_requests=2,
        new_tokens_per_chunk=8,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    try:
        eng.pause_generation()  # drive the scheduler by hand
        # long request: run until its KV extends past the 256-row bucket
        long_prompt = [1 + (i % 40) for i in range(300)]
        g_long = GenerationHyperparameters(greedy=True, max_new_tokens=64)
        item = _Slot(rid="long", prompt=list(long_prompt), gconfig=g_long,
                     future=None, loop=None)
        eng._request_q.put(item)
        with eng._sched_lock:
            eng._admit()
            eng._run_chunk(eng._active_mask())  # 8 tokens, len ~307
        partial = list(item.tokens)
        assert len(partial) == 8
        eng.abort_all()
        assert "long" in eng._parked

        # short request decodes alone on the SMALL (256-row) bucket even
        # though the parked slot's KV extends to ~307 rows — safe because
        # inactive slots never write
        g_short = GenerationHyperparameters(greedy=True, max_new_tokens=8)
        short = _Slot(rid="short", prompt=[2, 4, 6], gconfig=g_short,
                      future=None, loop=None)
        eng._request_q.put(short)
        with eng._sched_lock:
            eng._admit()
            eng._run_chunk(eng._active_mask())
        assert any(k[2] == 2 for k in eng._chunk_fns), (
            "short request should use the small 2-block bucket",
            list(eng._chunk_fns),
        )
        eng._slots = [None] * cfg.max_running_requests  # retire short slot

        # resume the long request: continuation must be exact
        resume = _Slot(
            rid="long", prompt=list(long_prompt) + partial,
            gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=8),
            future=None, loop=None,
        )
        eng._request_q.put(resume)
        with eng._sched_lock:
            eng._admit()
            eng._run_chunk(eng._active_mask())
        expected = greedy_reference(eng.params, long_prompt, 16)
        assert partial + resume.tokens == expected
    finally:
        eng.destroy()


@pytest.mark.slow
def test_retired_donor_survives_later_chunks(cpu_devices):
    """Staggered completion: a slot retires (stop_reason stop/length)
    while others keep chunking, then a same-prompt request forks from the
    retired donor's registered prefix. The fork must be exact — i.e.
    later chunks must not have written into the retired slot's rows
    (decode_step masks inactive-slot writes)."""
    from areal_tpu.engine.jax_decode import _Slot

    cfg = JaxDecodeConfig(
        context_length=96,
        max_running_requests=2,
        new_tokens_per_chunk=4,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    try:
        eng.pause_generation()  # drive the scheduler by hand
        prompt_a = [3, 7, 11, 2, 9]
        prompt_b = [4, 8, 12, 1]
        # A finishes after one chunk; B keeps going for several more
        a = _Slot(rid="a", prompt=list(prompt_a), future=None, loop=None,
                  gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=4))
        b = _Slot(rid="b", prompt=list(prompt_b), future=None, loop=None,
                  gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=20))
        eng._request_q.put(a)
        eng._request_q.put(b)
        with eng._sched_lock:
            eng._admit()
            eng._run_chunk(eng._active_mask())  # A hits max_new_tokens -> retires
            assert a.stop_reason == "length"
            # retirement registers the FULL conversation; the covering-donor
            # lookup serves plain-prompt matches from its head
            pa = tuple(prompt_a[:-1])
            assert any(
                len(k) >= len(pa) and k[: len(pa)] == pa
                for k in eng._prefix_lookup
            ), eng._prefix_lookup
            # B alone keeps chunking — these chunks must not corrupt A's rows
            for _ in range(4):
                if eng._active_mask().any():
                    eng._run_chunk(eng._active_mask())
        assert b.stop_reason == "length"

        # fork a same-prompt request from the retired donor's rows
        forks_before = eng._n_prefix_forks + eng._n_prefix_inplace
        c = _Slot(rid="c", prompt=list(prompt_a), future=None, loop=None,
                  gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=4))
        eng._request_q.put(c)
        with eng._sched_lock:
            eng._admit()
            eng._run_chunk(eng._active_mask())
        assert eng._n_prefix_forks + eng._n_prefix_inplace == forks_before + 1
        assert c.tokens == greedy_reference(eng.params, prompt_a, 4)
        assert c.tokens == a.tokens
    finally:
        eng.destroy()


@pytest.mark.slow
def test_partial_prefix_sharing_multi_turn(cpu_devices):
    """Multi-turn shape: request 2 = request 1's full conversation (prompt
    + generated answer) + a new user turn. The engine forks the shared
    history's KV from the registry and prefills ONLY the suffix
    (prefill_with_prefix), with exactly the dense greedy output."""
    cfg = JaxDecodeConfig(
        context_length=512,
        max_running_requests=2,
        new_tokens_per_chunk=8,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    try:
        # turn 1: long enough that its covered prefix >= _MIN_SHARED_PREFIX
        turn1 = [1 + (i % 40) for i in range(100)]
        g = GenerationHyperparameters(greedy=True, max_new_tokens=8)
        r1 = eng.generate(
            ModelRequest(input_ids=list(turn1), gconfig=g), timeout=600
        )
        assert r1.output_tokens == greedy_reference(eng.params, turn1, 8)
        assert eng._n_prefills == 1

        # turn 2: history + answer + a fresh user segment, NEW rid
        turn2 = list(turn1) + list(r1.output_tokens) + [5, 17, 3, 29, 11]
        r2 = eng.generate(
            ModelRequest(input_ids=list(turn2), gconfig=g), timeout=600
        )
        assert r2.output_tokens == greedy_reference(eng.params, turn2, 8)
        # the shared history was NOT re-prefilled
        assert eng._n_prefills == 1
        assert eng._n_suffix_prefills == 1
        m = eng.get_metrics()
        assert m["suffix_prefills_total"] == 1

        # turn 3 extends turn 2 — the registry now holds the longer key
        turn3 = list(turn2) + list(r2.output_tokens) + [7, 2]
        r3 = eng.generate(
            ModelRequest(input_ids=list(turn3), gconfig=g), timeout=600
        )
        assert r3.output_tokens == greedy_reference(eng.params, turn3, 8)
        assert eng._n_prefills == 1
        assert eng._n_suffix_prefills == 2
    finally:
        eng.destroy()


@pytest.mark.slow
def test_batched_prefill_wave_unique_prompts(cpu_devices):
    """An admission wave of distinct prompts prefills in ONE batched
    dispatch (vmapped) instead of serial per-request passes; outputs stay
    exactly equal to the greedy reference."""
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    cfg = JaxDecodeConfig(
        context_length=96,
        max_running_requests=4,
        new_tokens_per_chunk=8,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    try:
        prompts = [[2 + i, 7, 11, 3 + i] for i in range(4)]
        g = GenerationHyperparameters(greedy=True, max_new_tokens=6)
        eng.pause_generation()
        with ThreadPoolExecutor(max_workers=4) as pool:
            futs = [
                pool.submit(
                    eng.generate,
                    ModelRequest(input_ids=list(p), gconfig=g),
                    600,
                )
                for p in prompts
            ]
            deadline = _time.monotonic() + 30
            while eng._request_q.qsize() < 4:
                assert _time.monotonic() < deadline
                _time.sleep(0.01)
            eng.continue_generation()
            results = [f.result(timeout=600) for f in futs]
        for p, r in zip(prompts, results):
            assert r.output_tokens == greedy_reference(eng.params, p, 6), p
        assert eng._n_prefills == 4
        # the 4-wide batched prefill fn actually compiled and ran
        assert (64, 4) in eng._batched_prefill_fns, list(
            eng._batched_prefill_fns
        )
    finally:
        eng.destroy()


@pytest.mark.slow
def test_prewarm_compiles_all_wave_variants(cpu_devices):
    """prewarm() must deterministically populate every jit-variant cache a
    live load burst could hit — batched prefill at each admissible wave
    size, the decode chunk, and the dup-fork block copy — and must leave
    the engine fully serviceable (greedy parity afterwards)."""
    cfg = JaxDecodeConfig(
        context_length=96,
        max_running_requests=4,
        new_tokens_per_chunk=8,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    try:
        dt = eng.prewarm(prompt_len=16, new_tokens=4)
        assert dt > 0.0
        # prompt_len 16 -> 64-token prefill bucket; max_running 4 caps the
        # admissible wave sizes at {4, 2, 1}
        assert set(eng._batched_prefill_fns) >= {(64, 4), (64, 2), (64, 1)}
        # both sampler variants (top_p == 1 and top_p < 1) compiled
        assert {k[0] for k in eng._chunk_fns} == {False, True}, eng._chunk_fns
        assert True in eng._fork_fns, "dup-fork block copy not compiled"
        # misconfiguration must fail loudly, not silently warm nothing
        with pytest.raises(ValueError, match="length-rejected"):
            eng.prewarm(prompt_len=96, new_tokens=4)
        # engine state must be untouched: fresh greedy request still exact
        prompt = [3, 7, 11, 2, 9]
        resp = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(
                    greedy=True, max_new_tokens=6
                ),
            ),
            timeout=300,
        )
        assert resp.output_tokens == greedy_reference(eng.params, prompt, 6)
    finally:
        eng.destroy()
