"""ArealOpenAI client: OpenAI surface, reward plumbing, training export."""

import asyncio

import numpy as np

from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.experimental.openai import ArealOpenAI
from tests.test_workflows import FakeTokenizer, ScriptedEngine


class ChatTokenizer(FakeTokenizer):
    def apply_chat_template(self, messages, **kw):
        # deterministic: hash roles+content lengths into tokens
        ids = []
        for m in messages:
            content = m["content"]
            ids += [5 + (len(str(content)) % 20)]
        return ids + [2]


def make_client(completions):
    eng = ScriptedEngine(completions)
    return ArealOpenAI(
        eng,
        ChatTokenizer(),
        gconfig=GenerationHyperparameters(max_new_tokens=8),
    ), eng


def test_chat_completion_surface():
    client, eng = make_client([[11, 12, 13]])
    resp = asyncio.run(
        client.chat.completions.create(
            messages=[{"role": "user", "content": "hi"}], temperature=0.0
        )
    )
    assert resp.choices[0].message.role == "assistant"
    assert resp.choices[0].message.content == "11 12 13"
    assert resp.usage.completion_tokens == 3
    it = client.get_interaction(resp.id)
    assert it.output_tokens == [11, 12, 13]
    assert it.output_versions == [3, 3, 3]
    assert it.parent_id is None


def test_multi_turn_parent_chain_and_discount():
    client, eng = make_client([[11, 12], [21], [31]])

    async def convo():
        messages = [{"role": "user", "content": "solve"}]
        r1 = await client.chat.completions.create(messages=messages)
        # second turn: prompt = turn-1 prompt + turn-1 output + new tokens.
        # Simulate by feeding engine the prior sequence through the tokenizer:
        it1 = client.get_interaction(r1.id)
        client.tokenizer.apply_chat_template = (
            lambda msgs, **kw: it1.seq + [7, 2]
        )
        r2 = await client.chat.completions.create(
            messages=messages + [{"role": "assistant", "content": "..."}]
        )
        return r1, r2

    r1, r2 = asyncio.run(convo())
    it2 = client.get_interaction(r2.id)
    assert it2.parent_id == r1.id

    client.set_reward(r2.id, 1.0)
    client.apply_reward_discount(turn_discount=0.5)
    assert client.get_interaction(r1.id).reward == 0.5

    batch = client.export_interactions()
    assert batch["input_ids"].shape[0] == 2
    rewards = sorted(float(x) for x in np.asarray(batch["rewards"]))
    assert rewards == [0.5, 1.0]
    # loss mask covers only that turn's own completion
    lm = np.asarray(batch["loss_mask"])
    assert lm.sum() == 2 + 1  # turn1: 2 output tokens, turn2: 1
