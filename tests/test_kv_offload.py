"""Tiered KV cache: host-RAM offload under the paged pool (ISSUE 7).

Three layers of coverage:

1. `HostKVStore` unit contracts (pure host accounting, no engine): byte
   budget + LRU eviction with tombstones, exact-resume matching with
   stale-entry drop, pending-copy materialisation window, counter
   bookkeeping incl. the take/restore promotion dance.
2. The engine invariant the tier is FOR: a stream that was interrupted,
   EVICTED to host RAM and promoted back is bit-identical — tokens AND
   logprobs — to the never-evicted oracle, greedy and sampled, on both
   `kv_layout`s, at `decode_runahead_chunks=1` with `spec_decode="ngram"`
   on (the acceptance matrix of the issue). The restored bytes ARE the
   original KV and the slot's sampling base key travels with the entry,
   so fold_in(original_key, position) sampling makes the whole stream a
   pure function of token index again.
3. Degradation contracts: a host-tier MISS (budget-evicted entry) falls
   back to the pre-tier re-prefill and still matches the greedy oracle;
   `kv_host_pool_mb=0` reproduces today's drop-and-reprefill behavior
   exactly (all host metrics stay zero); weight installs flush the tier.
"""

import asyncio
import threading
import time
import uuid
from dataclasses import replace

import numpy as np
import pytest

import jax

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.engine.kv_pool import HostKVEntry, HostKVStore
from areal_tpu.models.qwen2 import ModelConfig, init_params

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

# K+V bytes per pool block for TINY at page_size=8, float32:
# 2 sides * L=2 * bs=8 * nKV=2 * hd=8 * 4B = 2048
_TINY_BLOCK_NBYTES = 2 * 2 * 8 * 2 * 8 * 4


# -- 1. HostKVStore unit contracts -------------------------------------


def _entry(rid, nb=2, covered=None, tokens=None, pending=False):
    covered = covered if covered is not None else nb * 4
    tokens = tokens if tokens is not None else list(range(covered))
    return HostKVEntry(
        rid=rid,
        k=np.zeros((1, nb, 4, 1, 2), np.float32),
        v=np.zeros((1, nb, 4, 1, 2), np.float32),
        nb=nb,
        covered=covered,
        tokens=tokens,
        rope_delta=0,
        base_key=np.zeros(2, np.uint32),
        ts=time.monotonic(),
        pending=pending,
    )


def test_store_budget_lru_and_tombstones():
    # budget: 2 blocks' worth; each entry below is 1 block
    st = HostKVStore(budget_bytes=200, block_nbytes=100, block_size=4)
    assert st.put(_entry("a", nb=1))
    assert st.put(_entry("b", nb=1))
    assert st.bytes_used == 200 and len(st) == 2
    # third entry LRU-evicts "a" (oldest) and tombstones it
    assert st.put(_entry("c", nb=1))
    assert len(st) == 2 and st.evictions == 1
    assert not st.match("a", 4, list(range(4)))  # tombstone -> counted miss
    assert st.misses == 1
    # the tombstone is consumed: a second lookup is silent
    assert not st.match("a", 4, list(range(4)))
    assert st.misses == 1
    # an entry bigger than the whole budget is rejected outright — and
    # tombstoned, so the dropped KV's resume counts as a miss
    assert not st.put(_entry("huge", nb=3))
    assert st.rejected_puts == 1
    assert not st.match("huge", 12, list(range(12)))
    assert st.misses == 2
    # match-hit keeps the entry; take pops it; note_hit counts the swap-in
    assert st.match("b", 4, list(range(4)))
    e = st.take("b")
    assert e is not None and st.bytes_used == 100
    st.note_hit(e)
    assert st.hits == 1 and st.swap_in_bytes_total == 100
    assert st.reprefill_tokens_avoided == e.covered


def test_store_stale_entry_drops_and_counts_miss():
    st = HostKVStore(budget_bytes=1000, block_nbytes=100, block_size=4)
    st.put(_entry("a", nb=1, covered=4, tokens=[1, 2, 3, 4]))
    # same rid, diverged tokens (edited prompt): stale -> dropped + miss
    assert not st.match("a", 4, [1, 2, 3, 9])
    assert st.misses == 1 and len(st) == 0
    # coverage-length mismatch is stale too
    st.put(_entry("b", nb=1, covered=4, tokens=[1, 2, 3, 4]))
    assert not st.match("b", 3, [1, 2, 3])
    assert st.misses == 2 and len(st) == 0


def test_store_take_restore_roundtrip():
    st = HostKVStore(budget_bytes=1000, block_nbytes=100, block_size=4)
    st.put(_entry("a", nb=2))
    e = st.take("a")
    assert len(st) == 0 and st.bytes_used == 0
    st.restore(e)  # promotion failed (device pool dry): entry comes back
    assert len(st) == 1 and st.bytes_used == 200
    assert st.hits == 0 and st.swap_in_bytes_total == 0
    assert st.match("a", e.covered, e.tokens)


def test_store_clear_tombstones_everything():
    st = HostKVStore(budget_bytes=1000, block_nbytes=100, block_size=4)
    st.put(_entry("a", nb=1))
    st.put(_entry("b", nb=1))
    assert st.clear() == 2
    assert len(st) == 0 and st.bytes_used == 0
    # weight-install invalidation: later resumes are honest misses
    assert not st.match("a", 4, list(range(4)))
    assert not st.match("b", 4, list(range(4)))
    assert st.misses == 2


class _CountingArray:
    """Stand-in device array: np.asarray(x) goes through __array__, so the
    store's materialisation points are observable."""

    def __init__(self, arr):
        self.arr = arr
        self.materialized = 0

    def __array__(self, dtype=None, copy=None):
        self.materialized += 1
        return self.arr

    def copy_to_host_async(self):
        pass


def test_store_pending_window_materializes_like_iter_prefetched():
    st = HostKVStore(
        budget_bytes=10_000, block_nbytes=100, block_size=4, pending_window=2
    )
    arrays = []
    for rid in ("a", "b", "c", "d"):
        e = _entry(rid, nb=1, pending=True)
        e.k = _CountingArray(np.asarray(e.k))
        e.v = _CountingArray(np.asarray(e.v))
        arrays.append((e.k, e.v))
        st.put(e)
    # window=2: entries beyond the two most recent have been materialised
    # (device refs dropped), the last two are still in flight
    assert arrays[0][0].materialized == 1 and arrays[1][0].materialized == 1
    assert arrays[2][0].materialized == 0 and arrays[3][0].materialized == 0
    # take() of a still-pending entry materialises on the spot
    e = st.take("d")
    assert arrays[3][0].materialized == 1 and not e.pending
    st.flush_pending()
    assert arrays[2][0].materialized == 1


# -- engine-level helpers ----------------------------------------------


class DigitTok:
    eos_token_id = None

    def decode(self, ids):
        return "".join(str(i % 10) for i in ids)


def _engine(params, host_mb, *, R=2, kv_layout="paged", spec="ngram",
            pool_tokens=None, context=256, page=8, chunk=4, runahead=1):
    cfg = JaxDecodeConfig(
        context_length=context,
        max_running_requests=R,
        new_tokens_per_chunk=chunk,
        page_size=page,
        kv_pool_tokens=pool_tokens,
        kv_host_pool_mb=host_mb,
        decode_runahead_chunks=runahead,
        kv_layout=kv_layout,
        paged_attn_impl="xla",
        spec_decode=spec,
        spec_k=3,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig(), tokenizer=DigitTok())
    eng.set_model(params, TINY)
    eng.initialize()
    return eng


def _wait_tokens(eng, n, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if eng.get_metrics()["generated_tokens_total"] >= n:
            return True
        time.sleep(0.002)
    return False


def _generate(eng, req, timeout=180.0):
    out = {}

    def _go():
        async def _r():
            return await eng.agenerate(req)

        try:
            out["r"] = asyncio.run(_r())
        except BaseException as e:  # noqa: BLE001
            out["e"] = e

    t = threading.Thread(target=_go, daemon=True)
    t.start()
    t.join(timeout)
    if "e" in out:
        raise out["e"]
    assert "r" in out, "generate timed out"
    return out["r"]


def _interrupt_first_segment(eng, rid, prompt, g, min_new_tokens=1):
    """Submit one request, let it emit a few tokens, then pause+abort:
    returns the interrupted partial response (the request is now PARKED
    server-side). Deterministic: nothing else is in flight, and the
    resume is NOT yet queued when this returns."""
    out = {}

    def _go():
        async def _r():
            return await eng.agenerate(
                ModelRequest(rid=rid, input_ids=prompt, gconfig=g)
            )

        out["r"] = asyncio.run(_r())

    base = eng.get_metrics()["generated_tokens_total"]
    t = threading.Thread(target=_go, daemon=True)
    t.start()
    assert _wait_tokens(eng, base + min_new_tokens), "no tokens emitted"
    eng.pause_generation()
    eng.abort_all()
    eng.continue_generation()
    t.join(120)
    resp = out["r"]
    assert resp.stop_reason == "interrupt", resp.stop_reason
    assert len(resp.output_tokens) >= min_new_tokens
    return resp


def _resume_segment(eng, rid, prompt, partial, g):
    """Client interrupt protocol: resubmit prompt + partial under the same
    rid with the remaining token budget."""
    return _generate(
        eng,
        ModelRequest(
            rid=rid,
            input_ids=list(prompt) + list(partial),
            gconfig=replace(
                g, max_new_tokens=g.max_new_tokens - len(partial)
            ),
        ),
    )


def _run_fillers(eng, prompts, g):
    async def _main():
        return await asyncio.gather(
            *[
                eng.agenerate(ModelRequest(input_ids=p, gconfig=g))
                for p in prompts
            ]
        )

    out = {}

    def _go():
        out["r"] = asyncio.run(_main())

    t = threading.Thread(target=_go, daemon=True)
    t.start()
    t.join(180)
    assert "r" in out, "fillers did not finish"
    return out["r"]


def _oracle_streams(params, prompts, gconfigs, kv_layout, spec):
    """Never-evicted reference: same engine settings but enough slots (and
    the dense full-provisioned pool) that nothing is ever parked-out or
    preempted — every request runs straight through. Per-slot sampling
    purity makes slot geometry irrelevant to the streams."""
    eng = _engine(
        params, 0, R=len(prompts) + 1, kv_layout=kv_layout, spec=spec
    )
    try:

        async def _main():
            return await asyncio.gather(
                *[
                    eng.agenerate(ModelRequest(input_ids=p, gconfig=g))
                    for p, g in zip(prompts, gconfigs)
                ]
            )

        out = {}

        def _go():
            out["r"] = asyncio.run(_main())

        t = threading.Thread(target=_go, daemon=True)
        t.start()
        t.join(180)
        assert "r" in out
        res = out["r"]
    finally:
        eng.destroy()
    return {
        tuple(p): (list(r.output_tokens), list(r.output_logprobs))
        for p, r in zip(prompts, res)
    }


# -- 2. bit-identity vs the never-evicted oracle ------------------------


@pytest.mark.parametrize("kv_layout", ["paged", "workspace"])
@pytest.mark.parametrize("greedy", [True, False], ids=["greedy", "sampled"])
def test_evicted_resume_bit_identical_to_oracle(cpu_devices, kv_layout, greedy):
    """park -> LRU-evict -> host offload -> promote: the resumed stream's
    tokens AND logprobs equal the never-evicted oracle's, greedy and
    sampled, on both kv_layouts, at runahead=1 with spec_decode="ngram"
    on. Sampled identity is what the traveling base key buys: every
    position samples with fold_in(original_key, position) regardless of
    where the interrupt/eviction landed."""
    params = init_params(TINY, jax.random.PRNGKey(0))
    rng = np.random.default_rng(11)
    prompts = [[int(x) for x in rng.integers(1, 60, 8)] for _ in range(3)]
    g = GenerationHyperparameters(
        greedy=greedy, temperature=1.0, top_p=1.0, max_new_tokens=48
    )
    g_fill = replace(g, max_new_tokens=12)
    oracle = _oracle_streams(
        params, prompts, [g, g_fill, g_fill], kv_layout, "ngram"
    )

    eng = _engine(params, 64, R=2, kv_layout=kv_layout, spec="ngram")
    try:
        rid = str(uuid.uuid4())
        seg1 = _interrupt_first_segment(eng, rid, prompts[0], g)
        # fillers admit while A's resume is NOT queued: their slot demand
        # LRU-evicts A's parked KV -> offloaded to the host tier
        fillers = _run_fillers(eng, prompts[1:], g_fill)
        assert eng.get_metrics()["kv_swap_out_bytes_total"] > 0, (
            "fillers never evicted the parked slot"
        )
        # A resumes: exact host-tier match -> promotion, no prefill
        seg2 = _resume_segment(eng, rid, prompts[0], seg1.output_tokens, g)
        m = eng.get_metrics()
    finally:
        eng.destroy()
    assert m["kv_host_hits_total"] >= 1, m
    assert m["kv_swap_in_bytes_total"] > 0, m
    assert m["reprefill_tokens_avoided_total"] > 0, m
    a_tokens = list(seg1.output_tokens) + list(seg2.output_tokens)
    a_logps = list(seg1.output_logprobs) + list(seg2.output_logprobs)
    oa_tokens, oa_logps = oracle[tuple(prompts[0])]
    tag = f"[{kv_layout}/{'greedy' if greedy else 'sampled'}]"
    assert a_tokens == oa_tokens, (
        f"{tag} evicted resume diverged from the never-evicted oracle:\n"
        f"{a_tokens}\n{oa_tokens}"
    )
    assert a_logps == oa_logps, f"{tag} logprobs diverged (not bit-identical)"
    for p, r in zip(prompts[1:], fillers):
        assert list(r.output_tokens) == oracle[tuple(p)][0], "filler diverged"
        assert list(r.output_logprobs) == oracle[tuple(p)][1]


def test_preempt_offload_swapback_bit_identical(cpu_devices):
    """Pool-pressure preemption (the internal requeue, invisible to the
    client) with the host tier: the preempted slot's KV is offloaded and
    promoted back at re-admission — SAMPLED stream bit-identical to a
    run with a pool big enough to never preempt (the base key rides on
    the _Slot across the requeue)."""
    params = init_params(TINY, jax.random.PRNGKey(0))
    rng = np.random.default_rng(7)
    prompts = [[int(x) for x in rng.integers(1, 60, 8)] for _ in range(3)]
    g = GenerationHyperparameters(
        greedy=False, temperature=1.0, top_p=1.0, max_new_tokens=60
    )

    def run(pool_tokens, host_mb):
        eng = _engine(
            params, host_mb, R=3, pool_tokens=pool_tokens, context=128,
            spec="ngram",
        )
        try:

            async def _main():
                return await asyncio.gather(
                    *[
                        eng.agenerate(ModelRequest(input_ids=p, gconfig=g))
                        for p in prompts
                    ]
                )

            out = {}

            def _go():
                out["r"] = asyncio.run(_main())

            t = threading.Thread(target=_go, daemon=True)
            t.start()
            t.join(180)
            assert "r" in out
            m = eng.get_metrics()
        finally:
            eng.destroy()
        return out["r"], m

    oracle, om = run(None, 0)  # full provisioning: no preemption possible
    assert om["preemptions_total"] == 0
    # zero-slack pool (24 usable blocks = 3 x 8-block admissions, exactly):
    # crossing 64 tokens forces _preempt_slot; the host tier catches it
    got, m = run(192, 64)
    assert m["preemptions_total"] > 0, m
    assert m["kv_host_hits_total"] > 0, m
    for i, (a, b) in enumerate(zip(got, oracle)):
        assert a.output_tokens == b.output_tokens, (
            f"job {i}: preempt+offload+swap-back changed the sampled stream"
        )
        assert a.output_logprobs == b.output_logprobs, i


# -- 3. degradation contracts ------------------------------------------


def test_host_miss_falls_back_to_reprefill(cpu_devices):
    """A host-tier MISS (the entry was budget-evicted from host RAM) must
    fall back to the pre-tier re-prefill and still produce the greedy
    oracle stream.

    Geometry: two 30-token-prompt sessions — each offload entry is 4-6
    blocks (coverage 30..48 even with run-ahead overshoot at chunk=2) —
    against a 6-block host budget: session 0's entry fits alone, but
    session 1's offload must LRU-evict it (two entries are >= 8 blocks).
    Session 0's resume is then a tombstoned MISS that re-prefills;
    session 1's resume is a HIT that promotes."""
    params = init_params(TINY, jax.random.PRNGKey(0))
    rng = np.random.default_rng(23)
    prompts = [
        [int(x) for x in rng.integers(1, 60, 30)],  # session 0 (miss)
        [int(x) for x in rng.integers(1, 60, 30)],  # session 1 (hit)
        [int(x) for x in rng.integers(1, 60, 8)],  # fillers
        [int(x) for x in rng.integers(1, 60, 8)],
    ]
    g = GenerationHyperparameters(greedy=True, max_new_tokens=48)
    g_fill = replace(g, max_new_tokens=12)
    oracle = _oracle_streams(
        params, prompts, [g, g, g_fill, g_fill], "paged", "ngram"
    )

    host_mb = (6 * _TINY_BLOCK_NBYTES) / (1024 * 1024)
    eng = _engine(params, host_mb, R=2, spec="ngram", chunk=2)
    try:
        rids = [str(uuid.uuid4()), str(uuid.uuid4())]
        seg1 = [
            _interrupt_first_segment(eng, rids[i], prompts[i], g)
            for i in range(2)
        ]
        # both sessions parked; fillers evict BOTH (LRU: session 0 first),
        # and session 1's offload LRU-evicts session 0's host entry
        _run_fillers(eng, prompts[2:], g_fill)
        m_mid = eng.get_metrics()
        assert m_mid["kv_host_evictions_total"] >= 1, m_mid
        assert m_mid["kv_host_pool_entries"] == 1, m_mid
        # session 0 resumes -> tombstoned MISS -> re-prefill fallback
        seg2_0 = _resume_segment(
            eng, rids[0], prompts[0], seg1[0].output_tokens, g
        )
        # session 1 resumes -> host HIT -> promotion
        seg2_1 = _resume_segment(
            eng, rids[1], prompts[1], seg1[1].output_tokens, g
        )
        m = eng.get_metrics()
    finally:
        eng.destroy()
    assert m["kv_host_misses_total"] >= 1, m
    assert m["kv_host_hits_total"] >= 1, m
    assert 0.0 < m["kv_host_hit_rate"] < 1.0, m
    for i, seg2 in enumerate((seg2_0, seg2_1)):
        toks = list(seg1[i].output_tokens) + list(seg2.output_tokens)
        assert toks == oracle[tuple(prompts[i])][0], (
            f"session {i}: fallback/promotion broke the greedy stream"
        )


def test_disabled_host_tier_reproduces_todays_behavior(cpu_devices):
    """kv_host_pool_mb=0 (the default): eviction drops KV, resumes
    re-prefill, every host metric stays zero — the pre-tier engine
    exactly (the acceptance criterion's no-regression clause)."""
    params = init_params(TINY, jax.random.PRNGKey(0))
    rng = np.random.default_rng(31)
    prompts = [[int(x) for x in rng.integers(1, 60, 8)] for _ in range(3)]
    g = GenerationHyperparameters(greedy=True, max_new_tokens=48)
    g_fill = replace(g, max_new_tokens=12)
    oracle = _oracle_streams(
        params, prompts, [g, g_fill, g_fill], "paged", "ngram"
    )

    eng = _engine(params, 0, R=2, spec="ngram")
    try:
        assert eng._host_store is None
        rid = str(uuid.uuid4())
        seg1 = _interrupt_first_segment(eng, rid, prompts[0], g)
        _run_fillers(eng, prompts[1:], g_fill)
        seg2 = _resume_segment(eng, rid, prompts[0], seg1.output_tokens, g)
        m = eng.get_metrics()
    finally:
        eng.destroy()
    assert not m["kv_host_pool_enabled"]
    for k in (
        "kv_host_pool_tokens",
        "kv_host_pool_entries",
        "kv_swap_out_bytes_total",
        "kv_swap_in_bytes_total",
        "kv_host_hits_total",
        "kv_host_misses_total",
        "reprefill_tokens_avoided_total",
    ):
        assert m[k] == 0, (k, m[k])
    assert m["kv_host_hit_rate"] == 0.0
    # greedy parity still holds through the drop-and-reprefill path
    toks = list(seg1.output_tokens) + list(seg2.output_tokens)
    assert toks == oracle[tuple(prompts[0])][0]


def test_weight_update_invalidates_host_tier(cpu_devices):
    """Weight installs must clear the host tier (offloaded KV was computed
    by the OLD weights) — the resume after the install re-prefills, and
    the drop is visible as a tombstoned miss."""
    params = init_params(TINY, jax.random.PRNGKey(0))
    rng = np.random.default_rng(41)
    prompts = [[int(x) for x in rng.integers(1, 60, 8)] for _ in range(3)]
    g = GenerationHyperparameters(greedy=True, max_new_tokens=48)
    g_fill = replace(g, max_new_tokens=12)
    oracle = _oracle_streams(
        params, prompts, [g, g_fill, g_fill], "paged", "off"
    )

    eng = _engine(params, 64, R=2, spec="off")
    try:
        rid = str(uuid.uuid4())
        seg1 = _interrupt_first_segment(eng, rid, prompts[0], g)
        _run_fillers(eng, prompts[1:], g_fill)
        assert eng.get_metrics()["kv_swap_out_bytes_total"] > 0
        # identical weights, so the greedy oracle is unchanged — but the
        # install must still flush the tier
        eng.update_weights_from_distributed(None, params=params)
        assert eng.get_metrics()["kv_host_pool_entries"] == 0
        seg2 = _resume_segment(eng, rid, prompts[0], seg1.output_tokens, g)
        m = eng.get_metrics()
    finally:
        eng.destroy()
    assert m["kv_host_hits_total"] == 0, m
    assert m["kv_host_misses_total"] >= 1, m  # tombstoned resume
    toks = list(seg1.output_tokens) + list(seg2.output_tokens)
    assert toks == oracle[tuple(prompts[0])][0]
