import threading
import time

import pytest

from areal_tpu.utils.name_resolve import (
    MemoryNameRecordRepository,
    NameEntryExistsError,
    NameEntryNotFoundError,
    NfsNameRecordRepository,
)


@pytest.fixture(params=["memory", "nfs"])
def repo(request, tmp_path):
    if request.param == "memory":
        return MemoryNameRecordRepository()
    return NfsNameRecordRepository(str(tmp_path / "nr"))


def test_add_get_delete(repo):
    repo.add("a/b/c", "v1")
    assert repo.get("a/b/c") == "v1"
    with pytest.raises(NameEntryExistsError):
        repo.add("a/b/c", "v2")
    repo.add("a/b/c", "v2", replace=True)
    assert repo.get("a/b/c") == "v2"
    repo.delete("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.get("a/b/c")


def test_subtree(repo):
    repo.add("root/x/1", "a")
    repo.add("root/x/2", "b")
    repo.add("root/y/1", "c")
    assert repo.find_subtree("root/x") == ["root/x/1", "root/x/2"]
    assert sorted(repo.get_subtree("root")) == ["a", "b", "c"]
    repo.clear_subtree("root/x")
    assert repo.find_subtree("root/x") == []
    assert repo.get_subtree("root") == ["c"]


def test_wait_blocks_until_added(repo):
    def _adder():
        time.sleep(0.2)
        repo.add("late/key", "42")

    t = threading.Thread(target=_adder)
    t.start()
    assert repo.wait("late/key", timeout=5) == "42"
    t.join()


def test_wait_timeout(repo):
    with pytest.raises(TimeoutError):
        repo.wait("never/appears", timeout=0.2)


def test_reset_removes_owned(repo):
    repo.add("owned/key", "1", delete_on_exit=True)
    repo.add("kept/key", "2", delete_on_exit=False)
    repo.reset()
    with pytest.raises(NameEntryNotFoundError):
        repo.get("owned/key")
    assert repo.get("kept/key") == "2"


def test_nfs_ttl_expiry(tmp_path):
    repo = NfsNameRecordRepository(str(tmp_path / "ttl"))
    repo.add("ephemeral", "x", keepalive_ttl=0.3)
    assert repo.get("ephemeral") == "x"
    # Stop the keepalive to simulate owner death, entry should expire.
    repo._keepalive_stop.set()
    with repo._lock:
        repo._keepalive_entries.clear()
    time.sleep(0.6)
    with pytest.raises(NameEntryNotFoundError):
        repo.get("ephemeral")


def test_nfs_keepalive_survives_reset(tmp_path):
    # Regression: reset() used to permanently stop the keepalive thread.
    repo = NfsNameRecordRepository(str(tmp_path / "ka"))
    repo.add("first", "1", keepalive_ttl=10)
    repo.reset()
    repo.add("second", "2", keepalive_ttl=0.4)
    time.sleep(0.8)  # > TTL; keepalive must be refreshing mtime
    assert repo.get("second") == "2"


def test_nfs_clear_subtree_prefix_boundary(tmp_path):
    # Regression: clear_subtree("foo") must not orphan sibling "foobar".
    repo = NfsNameRecordRepository(str(tmp_path / "pb"))
    repo.add("foo/x", "1")
    repo.add("foobar/y", "2")
    repo.clear_subtree("foo")
    assert repo.get("foobar/y") == "2"
    repo.reset()  # must delete foobar/y since still owned
    with pytest.raises(NameEntryNotFoundError):
        repo.get("foobar/y")


def test_nfs_get_subtree_skips_concurrently_deleted(tmp_path, monkeypatch):
    repo = NfsNameRecordRepository(str(tmp_path / "race"))
    repo.add("s/a", "1")
    repo.add("s/b", "2")
    orig_get = repo.get

    def racy_get(name):
        if name == "s/a":
            raise NameEntryNotFoundError(name)
        return orig_get(name)

    monkeypatch.setattr(repo, "get", racy_get)
    assert repo.get_subtree("s") == ["2"]


def test_nfs_replace_without_ttl_stops_keepalive(tmp_path):
    repo = NfsNameRecordRepository(str(tmp_path / "rk"))
    repo.add("k", "1", keepalive_ttl=5)
    repo.add("k", "2", replace=True)  # now permanent
    assert not repo._keepalive_entries, "keepalive entry leaked after replace"
    assert repo.get("k") == "2"
