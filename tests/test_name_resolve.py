import asyncio
import base64
import threading
import time

import pytest

from areal_tpu.utils.name_resolve import (
    Etcd3NameRecordRepository,
    MemoryNameRecordRepository,
    NameEntryExistsError,
    NameEntryNotFoundError,
    NfsNameRecordRepository,
)


class FakeEtcdGateway:
    """In-memory etcd v3 JSON gRPC-gateway: kv put/range/deleterange/txn +
    lease grant/keepalive/revoke with TTL expiry. Runs aiohttp on a thread."""

    def __init__(self):
        self.kv: dict[bytes, tuple[bytes, int]] = {}  # key -> (value, lease)
        self.created: dict[bytes, int] = {}
        self.leases: dict[int, float] = {}  # id -> deadline
        self.ttls: dict[int, float] = {}
        self._rev = 0
        self._next_lease = 1000
        self._lock = threading.Lock()
        self.addr = None
        self._loop = None
        self._runner = None

    def _expire(self):
        now = time.monotonic()
        dead = {lid for lid, dl in self.leases.items() if dl < now}
        for lid in dead:
            self.leases.pop(lid, None)
            self.ttls.pop(lid, None)
        if dead:
            for k in [k for k, (_, l) in self.kv.items() if l in dead]:
                self.kv.pop(k, None)
                self.created.pop(k, None)

    async def _handle(self, request):
        from aiohttp import web

        body = await request.json()
        ep = request.path
        with self._lock:
            self._expire()
            if ep == "/v3/lease/grant":
                lid = self._next_lease = self._next_lease + 1
                ttl = float(body["TTL"])
                self.leases[lid] = time.monotonic() + ttl
                self.ttls[lid] = ttl
                return web.json_response({"ID": str(lid), "TTL": str(int(ttl))})
            if ep == "/v3/lease/keepalive":
                lid = int(body["ID"])
                if lid in self.leases:
                    self.leases[lid] = time.monotonic() + self.ttls[lid]
                return web.json_response({"result": {"ID": str(lid)}})
            if ep == "/v3/lease/revoke":
                lid = int(body["ID"])
                self.leases[lid] = -1.0
                self._expire()
                return web.json_response({})
            key = base64.b64decode(body.get("key", ""))
            if ep == "/v3/kv/put":
                self._put(key, base64.b64decode(body.get("value", "")),
                          int(body.get("lease", 0) or 0))
                return web.json_response({})
            if ep == "/v3/kv/range":
                kvs = self._range(key, body.get("range_end"))
                return web.json_response(
                    {
                        "kvs": [
                            {
                                "key": base64.b64encode(k).decode(),
                                "value": base64.b64encode(v).decode(),
                            }
                            for k, (v, _) in kvs
                        ],
                        "count": str(len(kvs)),
                    }
                )
            if ep == "/v3/kv/deleterange":
                kvs = self._range(key, body.get("range_end"))
                for k, _ in kvs:
                    self.kv.pop(k, None)
                    self.created.pop(k, None)
                return web.json_response({"deleted": str(len(kvs))})
            if ep == "/v3/kv/txn":
                cmp = body["compare"][0]
                ckey = base64.b64decode(cmp["key"])
                exists = ckey in self.kv
                # only CREATE == 0 comparisons are modeled
                succeeded = not exists
                if succeeded:
                    put = body["success"][0]["request_put"]
                    self._put(
                        base64.b64decode(put["key"]),
                        base64.b64decode(put.get("value", "")),
                        int(put.get("lease", 0) or 0),
                    )
                return web.json_response({"succeeded": succeeded})
        return web.json_response({}, status=404)

    def _put(self, key, value, lease):
        self._rev += 1
        if key not in self.kv:
            self.created[key] = self._rev
        self.kv[key] = (value, lease)

    def _range(self, key, range_end_b64):
        if not range_end_b64:
            return [(key, self.kv[key])] if key in self.kv else []
        end = base64.b64decode(range_end_b64)
        return sorted(
            (k, v) for k, v in self.kv.items() if key <= k < end
        )

    def start(self):
        from aiohttp import web

        started = threading.Event()

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def go():
                app = web.Application()
                app.router.add_post("/v3/{tail:.*}", self._handle)
                self._runner = web.AppRunner(app)
                await self._runner.setup()
                site = web.TCPSite(self._runner, "127.0.0.1", 0)
                await site.start()
                self.addr = f"127.0.0.1:{self._runner.addresses[0][1]}"
                started.set()

            self._loop.run_until_complete(go())
            self._loop.run_forever()

        threading.Thread(target=run, daemon=True).start()
        assert started.wait(10)
        return self.addr

    def stop(self):
        if self._loop is not None:
            asyncio.run_coroutine_threadsafe(
                self._runner.cleanup(), self._loop
            ).result(5)
            self._loop.call_soon_threadsafe(self._loop.stop)


@pytest.fixture(scope="module")
def etcd_gateway():
    gw = FakeEtcdGateway()
    gw.start()
    yield gw
    gw.stop()


@pytest.fixture(params=["memory", "nfs", "etcd3"])
def repo(request, tmp_path):
    if request.param == "memory":
        yield MemoryNameRecordRepository()
    elif request.param == "etcd3":
        gw = FakeEtcdGateway()
        gw.start()
        yield Etcd3NameRecordRepository(gw.addr)
        gw.stop()
    else:
        yield NfsNameRecordRepository(str(tmp_path / "nr"))


def test_add_get_delete(repo):
    repo.add("a/b/c", "v1")
    assert repo.get("a/b/c") == "v1"
    with pytest.raises(NameEntryExistsError):
        repo.add("a/b/c", "v2")
    repo.add("a/b/c", "v2", replace=True)
    assert repo.get("a/b/c") == "v2"
    repo.delete("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.get("a/b/c")


def test_subtree(repo):
    repo.add("root/x/1", "a")
    repo.add("root/x/2", "b")
    repo.add("root/y/1", "c")
    assert repo.find_subtree("root/x") == ["root/x/1", "root/x/2"]
    assert sorted(repo.get_subtree("root")) == ["a", "b", "c"]
    repo.clear_subtree("root/x")
    assert repo.find_subtree("root/x") == []
    assert repo.get_subtree("root") == ["c"]


def test_wait_blocks_until_added(repo):
    def _adder():
        time.sleep(0.2)
        repo.add("late/key", "42")

    t = threading.Thread(target=_adder)
    t.start()
    assert repo.wait("late/key", timeout=5) == "42"
    t.join()


def test_wait_timeout(repo):
    with pytest.raises(TimeoutError):
        repo.wait("never/appears", timeout=0.2)


def test_reset_removes_owned(repo):
    repo.add("owned/key", "1", delete_on_exit=True)
    repo.add("kept/key", "2", delete_on_exit=False)
    repo.reset()
    with pytest.raises(NameEntryNotFoundError):
        repo.get("owned/key")
    assert repo.get("kept/key") == "2"


def test_nfs_ttl_expiry(tmp_path):
    repo = NfsNameRecordRepository(str(tmp_path / "ttl"))
    repo.add("ephemeral", "x", keepalive_ttl=0.3)
    assert repo.get("ephemeral") == "x"
    # Stop the keepalive to simulate owner death, entry should expire.
    repo._keepalive_stop.set()
    with repo._lock:
        repo._keepalive_entries.clear()
    time.sleep(0.6)
    with pytest.raises(NameEntryNotFoundError):
        repo.get("ephemeral")


def test_nfs_keepalive_survives_reset(tmp_path):
    # Regression: reset() used to permanently stop the keepalive thread.
    repo = NfsNameRecordRepository(str(tmp_path / "ka"))
    repo.add("first", "1", keepalive_ttl=10)
    repo.reset()
    repo.add("second", "2", keepalive_ttl=0.4)
    time.sleep(0.8)  # > TTL; keepalive must be refreshing mtime
    assert repo.get("second") == "2"


def test_nfs_clear_subtree_prefix_boundary(tmp_path):
    # Regression: clear_subtree("foo") must not orphan sibling "foobar".
    repo = NfsNameRecordRepository(str(tmp_path / "pb"))
    repo.add("foo/x", "1")
    repo.add("foobar/y", "2")
    repo.clear_subtree("foo")
    assert repo.get("foobar/y") == "2"
    repo.reset()  # must delete foobar/y since still owned
    with pytest.raises(NameEntryNotFoundError):
        repo.get("foobar/y")


def test_nfs_get_subtree_skips_concurrently_deleted(tmp_path, monkeypatch):
    repo = NfsNameRecordRepository(str(tmp_path / "race"))
    repo.add("s/a", "1")
    repo.add("s/b", "2")
    orig_get = repo.get

    def racy_get(name):
        if name == "s/a":
            raise NameEntryNotFoundError(name)
        return orig_get(name)

    monkeypatch.setattr(repo, "get", racy_get)
    assert repo.get_subtree("s") == ["2"]


def test_nfs_replace_without_ttl_stops_keepalive(tmp_path):
    repo = NfsNameRecordRepository(str(tmp_path / "rk"))
    repo.add("k", "1", keepalive_ttl=5)
    repo.add("k", "2", replace=True)  # now permanent
    assert not repo._keepalive_entries, "keepalive entry leaked after replace"
    assert repo.get("k") == "2"


def test_etcd3_ttl_expiry_and_keepalive(etcd_gateway):
    """A TTL entry expires when its owner stops refreshing; the keepalive
    thread keeps it alive while the repo lives."""
    repo = Etcd3NameRecordRepository(etcd_gateway.addr)
    repo.add("svc/one", "v", keepalive_ttl=1.0)
    time.sleep(2.0)  # > TTL: keepalive thread must have refreshed the lease
    assert repo.get("svc/one") == "v"
    repo.reset()  # revokes the lease
    with pytest.raises(NameEntryNotFoundError):
        repo.get("svc/one")


def test_etcd3_prefix_boundary(etcd_gateway):
    repo = Etcd3NameRecordRepository(etcd_gateway.addr)
    repo.add("pb/a", "1")
    repo.add("pb/ab", "2")
    repo.add("pb/a/c", "3")
    assert repo.find_subtree("pb/a") == ["pb/a", "pb/a/c"]
    repo.clear_subtree("pb/a")
    assert repo.get("pb/ab") == "2"
    repo.reset()
