"""Fleet-launcher planning: ray placement groups (mocked cluster) and
slurm decoupled-allocation sbatch plans (parity: areal/launcher/ray.py:68,
328 placement-group PACK scheduling; areal/launcher/slurm.py:46 job
planning). No cluster needed — plans are pure and ray is stubbed."""

import sys
import types
from concurrent.futures import ThreadPoolExecutor

import pytest

from areal_tpu.launcher.ray import PlacementPlan, build_placement_plan
from areal_tpu.launcher.slurm import plan_decoupled_jobs, render_sbatch_script


# ---------------------------------------------------------------------------
# placement plan (pure)
# ---------------------------------------------------------------------------


def test_build_placement_plan_pack():
    plan = build_placement_plan(
        8, 2, tpus_per_task=1, cpus_per_task=4, mem_mb_per_task=1024
    )
    assert plan.strategy == "PACK"
    assert plan.nodes == 2
    # per-node bundle aggregates that node's 4 tasks
    assert plan.bundles[0] == {
        "CPU": 16.0,
        "memory": float(4 * 1024 * 1024 * 1024),
        "TPU": 4.0,
    }
    # ranks fill node 0 first, then node 1 (adjacency for ICI/DCN)
    assert plan.bundle_index == [0, 0, 0, 0, 1, 1, 1, 1]


def test_build_placement_plan_rejects_ragged():
    with pytest.raises(ValueError):
        build_placement_plan(5, 2)
    with pytest.raises(ValueError):
        build_placement_plan(4, 0)


# ---------------------------------------------------------------------------
# mocked-ray submit_array + coordinator rendezvous
# ---------------------------------------------------------------------------


class _FakePG:
    def __init__(self, bundles, strategy):
        self.bundles = bundles
        self.strategy = strategy

    def ready(self):
        return "ready-ref"


class _FakeStrategy:
    def __init__(self, placement_group, placement_group_bundle_index,
                 placement_group_capture_child_tasks):
        self.pg = placement_group
        self.bundle_index = placement_group_bundle_index
        self.capture = placement_group_capture_child_tasks


def _install_fake_ray(monkeypatch, record):
    """A minimal `ray` that executes tasks on a thread pool so the real
    coordinator rendezvous (name_resolve) runs across 'ranks'."""
    pool = ThreadPoolExecutor(max_workers=8)
    record["pool"] = pool

    ray = types.ModuleType("ray")
    ray_util = types.ModuleType("ray.util")
    ray_sched = types.ModuleType("ray.util.scheduling_strategies")
    ray_sched.PlacementGroupSchedulingStrategy = _FakeStrategy
    ray_util.scheduling_strategies = ray_sched

    def placement_group(bundles, strategy):
        pg = _FakePG(bundles, strategy)
        record["pgs"].append(pg)
        return pg

    ray_util.placement_group = placement_group
    ray_util.remove_placement_group = lambda pg: record["removed"].append(pg)
    ray.util = ray_util
    ray.is_initialized = lambda: True
    ray.nodes = lambda: []

    def ray_get(ref_or_list, timeout=None):
        if ref_or_list == "ready-ref":
            return True
        return [f.result(timeout=60) for f in ref_or_list]

    ray.get = ray_get
    ray.cancel = lambda ref, force=False: None

    def remote(**opts):
        def deco(fn):
            class Remote:
                def remote(self, *args):
                    record["tasks"].append(opts)
                    return pool.submit(fn, *args)

            return Remote()

        return deco

    ray.remote = remote
    monkeypatch.setitem(sys.modules, "ray", ray)
    monkeypatch.setitem(sys.modules, "ray.util", ray_util)
    monkeypatch.setitem(sys.modules, "ray.util.scheduling_strategies", ray_sched)
    return ray


@pytest.fixture
def _clean_dist_env():
    """The dist task wrapper exports AREAL_TPU_* into os.environ (the
    fake ray runs tasks in this process's threads); drain the task pool,
    then scrub, so later engine tests don't try to join a phantom
    jax.distributed cluster."""
    import os

    keys = ("AREAL_TPU_NUM_PROCESSES", "AREAL_TPU_PROCESS_ID",
            "AREAL_TPU_COORDINATOR")
    saved = {k: os.environ.get(k) for k in keys}
    record: dict = {}
    yield record
    pool = record.get("pool")
    if pool is not None:
        # in-flight tasks re-export the env as they start; wait them out
        pool.shutdown(wait=True)
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def test_ray_submit_array_placement_and_rendezvous(monkeypatch, _clean_dist_env):
    from areal_tpu.launcher.ray import RayLauncher
    from areal_tpu.utils import name_resolve

    name_resolve.reconfigure(name_resolve.NameResolveConfig(type="memory"))
    record = _clean_dist_env
    record.update({"pgs": [], "tasks": [], "removed": []})
    _install_fake_ray(monkeypatch, record)

    got = []

    def fn(rank, marker):
        # the dist wrapper resolved + exported the coordinator before us
        import os

        got.append((rank, os.environ["AREAL_TPU_COORDINATOR"], marker))
        return rank

    launcher = RayLauncher("rexp", "rt")
    refs = launcher.submit_array(
        "trainer",
        fn,
        count=4,
        nodes=2,
        tpus_per_task=1,
        cpus_per_task=2,
        mem_mb_per_task=512,
        env_hook=lambda rank: {"RANK_HINT": str(rank)},
        args=("m",),
    )
    import ray as fake_ray

    results = fake_ray.get(refs)
    assert sorted(results) == [0, 1, 2, 3]

    # one PACK placement group with 2 node bundles, each 2 tasks' worth
    assert len(record["pgs"]) == 1
    pg = record["pgs"][0]
    assert pg.strategy == "PACK"
    assert len(pg.bundles) == 2 and pg.bundles[0]["TPU"] == 2.0

    # every task scheduled into its node's bundle with capture enabled
    strategies = [t["scheduling_strategy"] for t in record["tasks"]]
    assert [s.bundle_index for s in strategies] == [0, 0, 1, 1]
    assert all(s.pg is pg and s.capture for s in strategies)
    # env hook flowed into runtime_env per rank
    envs = [t["runtime_env"]["env_vars"]["RANK_HINT"] for t in record["tasks"]]
    assert envs == ["0", "1", "2", "3"]

    # all ranks agreed on ONE coordinator (rank 0 published, others waited)
    coords = {c for _, c, _ in got}
    assert len(coords) == 1 and ":" in next(iter(coords))

    # recover path: same name + same plan reuses the PG
    launcher.submit_array(
        "trainer", fn, count=4, nodes=2, tpus_per_task=1,
        cpus_per_task=2, mem_mb_per_task=512,
    )
    assert len(record["pgs"]) == 1
    # a CHANGED topology must release the old reservation, not reuse it
    launcher.submit_array(
        "trainer", fn, count=8, nodes=2, tpus_per_task=1,
        cpus_per_task=2, mem_mb_per_task=512,
    )
    assert len(record["pgs"]) == 2 and record["removed"] == [pg]
    launcher.stop_all()
    assert record["removed"] == [pg, record["pgs"][1]]


# ---------------------------------------------------------------------------
# slurm decoupled plan
# ---------------------------------------------------------------------------


def test_slurm_decoupled_plan_two_node():
    jobs = plan_decoupled_jobs(
        experiment_name="exp",
        trial_name="t0",
        allocation_mode="jax:d2t2+jax:d8",
        trainer_cmd="python -m examples.gsm8k_grpo --config c.yaml",
        model_path="/models/qwen",
        accelerators_per_node=4,
        partition="tpu-v5p",
        container_image="ghcr.io/org/areal-tpu:latest",
        container_mounts="/data:/data",
        trainer_nodelist="tpu-[01-02]",
        name_resolve_env={"AREAL_NAME_RESOLVE_TYPE": "nfs"},
    )
    by_name = {j.name.split(":")[-1]: j for j in jobs}
    assert set(by_name) == {"server0", "server1", "router", "trainer"}

    # 2 decode replicas, tp=2 chips each, one node apiece
    s0 = by_name["server0"]
    assert s0.accelerators_per_node == 2 and s0.n_nodes == 1
    assert "--tp-size 2" in s0.cmd and "/models/qwen" in s0.cmd
    assert s0.env["AREAL_NAME_RESOLVE_TYPE"] == "nfs"

    # trainer: d8 over 4-chip nodes -> 2 nodes, gres tpu:4, pinned nodelist
    tr = by_name["trainer"]
    assert tr.n_nodes == 2 and tr.accelerators_per_node == 4
    script = render_sbatch_script(tr, "/tmp/logs")
    assert "#SBATCH --nodes=2" in script
    assert "#SBATCH --gres=tpu:4" in script
    assert "#SBATCH --partition=tpu-v5p" in script
    assert "#SBATCH --nodelist=tpu-[01-02]" in script
    assert "--container-image=ghcr.io/org/areal-tpu:latest" in script
    assert "--container-mounts=/data:/data" in script
    assert "export AREAL_EXPERIMENT_NAME=exp" in script
    # rendezvous env renders inside the srun task, not the batch shell
    assert "AREAL_TPU_PROCESS_ID=$SLURM_PROCID" in script

    # router is accelerator-free
    assert by_name["router"].accelerators_per_node == 0


def test_slurm_colocate_plan_trainer_only():
    jobs = plan_decoupled_jobs(
        experiment_name="exp",
        trial_name="t1",
        allocation_mode="d4t2",
        trainer_cmd="python train.py",
        accelerators_per_node=8,
    )
    assert len(jobs) == 1
    assert jobs[0].n_nodes == 1 and jobs[0].accelerators_per_node == 8


def test_ray_submit_array_without_placement_group(monkeypatch, _clean_dist_env):
    """nodes=None (the default) schedules by plain resource requests — no
    placement group is created and no scheduling_strategy is attached."""
    from areal_tpu.launcher.ray import RayLauncher
    from areal_tpu.utils import name_resolve

    name_resolve.reconfigure(name_resolve.NameResolveConfig(type="memory"))
    record = _clean_dist_env
    record.update({"pgs": [], "tasks": [], "removed": []})
    _install_fake_ray(monkeypatch, record)

    launcher = RayLauncher("rexp2", "rt2")
    refs = launcher.submit_array(
        "plain", lambda rank: rank, count=3, tpus_per_task=1,
        cpus_per_task=1, mem_mb_per_task=256,
    )
    import ray as fake_ray

    assert sorted(fake_ray.get(refs)) == [0, 1, 2]
    assert record["pgs"] == [], "no placement group expected"
    assert all("scheduling_strategy" not in t for t in record["tasks"])
    launcher.stop_all()
    assert record["removed"] == []
