"""Local launcher process lifecycle (fast: trivial subprocess jobs)."""

import sys
import time

import pytest

from areal_tpu.launcher.base import JobState
from areal_tpu.launcher.local import LocalLauncher
from areal_tpu.utils import name_resolve, names
from areal_tpu.utils.name_resolve import NameResolveConfig


@pytest.fixture()
def launcher(tmp_path):
    l = LocalLauncher("exp", "trial", str(tmp_path))
    yield l
    l.stop_all()


def test_job_completes_and_logs(launcher):
    job = launcher.submit(
        "hello", [sys.executable, "-c", "print('hi from job')"]
    )
    deadline = time.monotonic() + 30
    while job.state is JobState.RUNNING and time.monotonic() < deadline:
        time.sleep(0.1)
    assert job.state is JobState.COMPLETED
    with open(job.log_path) as f:
        assert "hi from job" in f.read()


def test_failure_raises_with_log_tail(launcher):
    launcher.submit(
        "trainer_0",
        [sys.executable, "-c", "import sys; print('boom reason'); sys.exit(3)"],
    )
    with pytest.raises(RuntimeError) as ei:
        launcher.wait(check_interval=0.1)
    assert "boom reason" in str(ei.value)
    assert "rc=3" in str(ei.value)


def test_wait_returns_when_trainers_done(launcher):
    # a long-running "server" plus a quick "trainer": wait() must return
    # when trainers complete even though the server is still alive.
    launcher.submit(
        "decode_server_0", [sys.executable, "-c", "import time; time.sleep(60)"]
    )
    launcher.submit("trainer_0", [sys.executable, "-c", "print('done')"])
    t0 = time.monotonic()
    launcher.wait(check_interval=0.1)
    assert time.monotonic() - t0 < 30
    launcher.stop_all()
    assert launcher.jobs == []


def test_stop_all_kills_process_tree(launcher):
    job = launcher.submit(
        "spin", [sys.executable, "-c", "import time; time.sleep(120)"]
    )
    proc = job.proc
    launcher.stop_all()
    assert proc.poll() is not None


def test_wait_decode_servers_discovery(launcher, tmp_path):
    name_resolve.reconfigure(
        NameResolveConfig(type="nfs", nfs_record_root=str(tmp_path / "nr"))
    )
    try:
        key = names.gen_server("exp", "trial", "10.0.0.1:7001")
        name_resolve.add(key, "10.0.0.1:7001", delete_on_exit=False)
        addrs = launcher.wait_decode_servers(1, timeout=10)
        assert addrs == ["10.0.0.1:7001"]
        with pytest.raises(TimeoutError):
            launcher.wait_decode_servers(2, timeout=1)
    finally:
        name_resolve.reconfigure(NameResolveConfig(type="memory"))


def test_slurm_script_rendering(tmp_path):
    from areal_tpu.launcher.slurm import SlurmJobSpec, render_sbatch_script

    spec = SlurmJobSpec(
        name="trainer",
        cmd="python train.py --config c.yaml",
        n_nodes=4,
        accelerators_per_node=4,
        partition="tpu",
        env={"FOO": "bar"},
        container_image="img:latest",
        container_mounts="/data:/data",
    )
    script = render_sbatch_script(spec, str(tmp_path))
    assert "#SBATCH --nodes=4" in script
    assert "#SBATCH --gres=tpu:4" in script
    assert "#SBATCH --partition=tpu" in script
    assert "export FOO=bar" in script
    assert "AREAL_TPU_NUM_PROCESSES=$SLURM_JOB_NUM_NODES" in script
    assert "--container-image=img:latest" in script
    assert "python train.py --config c.yaml" in script


def test_ray_launcher_gated_without_ray():
    from areal_tpu.launcher.ray import RayLauncher

    l = RayLauncher("exp", "t")
    try:
        import ray  # noqa: F401
        has_ray = True
    except ImportError:
        has_ray = False
    if not has_ray:
        with pytest.raises(RuntimeError, match="requires the `ray` package"):
            l.submit_array("x", lambda rank: rank, 1)


def test_slurm_procid_expands_inside_srun(tmp_path):
    from areal_tpu.launcher.slurm import SlurmJobSpec, render_sbatch_script

    script = render_sbatch_script(
        SlurmJobSpec(name="t", cmd="python x.py", n_nodes=2), str(tmp_path)
    )
    # PROCESS_ID must be set inside the srun-launched shell, not the batch shell.
    assert "export AREAL_TPU_PROCESS_ID=$SLURM_PROCID; python x.py" in script
    batch_part = script.split("srun")[0]
    assert "AREAL_TPU_PROCESS_ID" not in batch_part


def test_ray_coordinator_rendezvous(tmp_path):
    from areal_tpu.launcher.ray import resolve_coordinator

    name_resolve.reconfigure(
        NameResolveConfig(type="nfs", nfs_record_root=str(tmp_path / "nr"))
    )
    try:
        addr0 = resolve_coordinator("exp", "t", 0)
        addr1 = resolve_coordinator("exp", "t", 1, timeout=5)
        assert addr0 == addr1 and ":" in addr0
    finally:
        name_resolve.reconfigure(NameResolveConfig(type="memory"))


def test_job_failure_recoverable_classification(launcher):
    from areal_tpu.launcher.base import JobFailure

    launcher.submit(
        "trainer_0",
        [sys.executable, "-c", "import os, signal; os.kill(os.getpid(), signal.SIGTERM)"],
    )
    with pytest.raises(JobFailure) as ei:
        launcher.wait(check_interval=0.1)
    assert ei.value.recoverable  # SIGTERM'd = preemption-style

def test_wait_no_matching_jobs_returns(launcher):
    launcher.submit(
        "decode_server_0", [sys.executable, "-c", "import time; time.sleep(30)"]
    )
    t0 = time.monotonic()
    launcher.wait(check_interval=0.1)  # no trainer jobs: return, don't spin
    assert time.monotonic() - t0 < 5


@pytest.mark.slow
def test_decoupled_e2e_smoke(tmp_path):
    """Full DECOUPLED-mode E2E, fully offline: run_experiment spawns a
    from-scratch decode server (+ name_resolve registration), then the GRPO
    example as the trainer subprocess, which discovers the server over
    HTTP, rolls out, trains, and pushes weights back over the DCN staging
    path. Two steps must complete and tear down cleanly."""
    import os
    import sys
    import uuid

    from areal_tpu.api.cli_args import GRPOConfig, load_expr_config
    from areal_tpu.launcher.local import run_experiment

    trial = uuid.uuid4().hex[:8]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    overrides = [
        "--config",
        os.path.join(repo, "examples/configs/arith_grpo_smoke.yaml"),
        f"trial_name={trial}",
        f"cluster.fileroot={tmp_path}",
        f"cluster.name_resolve.nfs_record_root={tmp_path}/nr",
        "allocation_mode=jax:d1+d8",
        # minimal workload: the decode server's continuous-batching loop
        # saturates the single CI core, so every extra episode directly
        # starves the trainer's compiles (observed: 16-episode batches push
        # the E2E past 20 min; 4-episode batches finish in ~6)
        "total_train_steps=2",
        "train_dataset.batch_size=2",
        "gconfig.n_samples=2",
        "rollout.consumer_batch_size=4",
        "rollout.max_concurrent_rollouts=8",
        "evaluator.freq_steps=1000",
    ]
    config, _ = load_expr_config(overrides, GRPOConfig)
    entry = [
        sys.executable,
        os.path.join(repo, "examples/gsm8k_grpo.py"),
    ] + overrides
    run_experiment(config, entry, max_restarts=0)
    # the trainer's stats log proves steps ran
    log_dir = os.path.join(str(tmp_path), "logs", config.experiment_name, trial)
    trainer_log = os.path.join(log_dir, "trainer_0.log")
    with open(trainer_log) as f:
        text = f.read()
    assert "global step 1" in text, text[-2000:]
    assert "Traceback" not in text, text[-3000:]
