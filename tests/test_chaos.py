"""Deterministic fault injection + graceful degradation (ISSUE 9).

Tier-1 chaos surface, all fast (stub engines, no jax):

  - FaultInjector semantics: seeded determinism, `at` / `p` / `times` /
    `match` gating, the four modes, counters, global configure/deactivate.
  - Seam behavior at each boundary the injector perturbs: HTTP send /
    recv / torn-body (retry + idempotency dedup), weight staging (torn
    frames rejected, retries re-cover), host-KV swap (faults degrade to
    re-prefill, never crash), task.run (failure accounting).
  - The short seeded chaos smoke: a 2-replica stub fleet + router +
    RemoteInfEngine replay a request wave under a 4-mode fault schedule;
    every request completes exactly once with streams identical to the
    fault-free function of the prompt.
"""

import asyncio
import struct
import threading
import time

import numpy as np
import pytest
from aiohttp import web

from areal_tpu.api.cli_args import (
    FaultInjectionConfig,
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest, ModelResponse
from areal_tpu.core import fault_injection
from areal_tpu.core.async_task_runner import AsyncTaskRunner
from areal_tpu.core.fault_injection import (
    FaultInjector,
    FaultPlan,
    FaultPoint,
    InjectedFault,
)
from areal_tpu.core.remote_inf_engine import RemoteInfEngine
from areal_tpu.core.weight_transfer import WeightStaging, pack_buckets
from areal_tpu.engine.kv_pool import HostKVEntry, HostKVStore
from areal_tpu.launcher.decode_server import DecodeServer
from areal_tpu.launcher.router import DecodeRouter
from areal_tpu.utils import name_resolve
from areal_tpu.utils.http import (
    HttpRequestError,
    arequest_with_retry,
    backoff_delays,
    close_current_session,
)


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    fault_injection.deactivate()
    yield
    fault_injection.deactivate()


def _run_async(coro, timeout=60):
    result = {}

    def go():
        result["v"] = asyncio.run(coro)

    t = threading.Thread(target=go, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "async scenario timed out"
    return result.get("v")


# -- injector semantics ------------------------------------------------------


def test_fault_point_validation():
    with pytest.raises(ValueError):
        FaultPoint(site="x", mode="explode")
    p = FaultPoint(site="x", at=[1, "2"])
    assert p.at == (1, 2)


def test_plan_from_json_and_config():
    plan = FaultPlan.from_json(
        '[{"site": "a.b", "mode": "delay", "at": [0], "delay_s": 0.5}]',
        seed=7,
    )
    assert plan.seed == 7
    assert plan.points[0].mode == "delay"
    # dict form carries its own seed
    plan = FaultPlan.from_json('{"seed": 3, "points": [{"site": "a"}]}')
    assert plan.seed == 3 and plan.points[0].site == "a"
    # config form; disabled -> None
    assert FaultPlan.from_config(FaultInjectionConfig()) is None
    cfg = FaultInjectionConfig(
        enabled=True, seed=9, plan='[{"site": "s", "mode": "abort"}]'
    )
    plan = FaultPlan.from_config(cfg)
    assert plan.seed == 9 and plan.points[0].site == "s"


def test_at_times_and_match_gating():
    inj = FaultInjector(
        FaultPlan(
            seed=0,
            points=[
                FaultPoint(site="s.*", mode="abort", at=(1, 3), times=2,
                           match={"addr": "good"}),
            ],
        )
    )
    fired = []
    for i in range(6):
        try:
            inj.fire("s.x", addr="good-host")
        except InjectedFault:
            fired.append(i)
    assert fired == [1, 3]
    # non-matching context never fires (and keeps its own hit count)
    inj2 = FaultInjector(
        FaultPlan(points=[FaultPoint(site="s.*", match={"addr": "good"})])
    )
    for _ in range(4):
        inj2.fire("s.x", addr="other")
    assert inj2.snapshot() == {}


def test_probability_gate_is_seed_deterministic():
    def run(seed):
        inj = FaultInjector(
            FaultPlan(
                seed=seed,
                points=[FaultPoint(site="s", mode="abort", p=0.5, times=0)],
            )
        )
        hits = []
        for i in range(32):
            try:
                inj.fire("s")
            except InjectedFault:
                hits.append(i)
        return hits

    a, b = run(11), run(11)
    assert a == b and 0 < len(a) < 32
    assert run(12) != a  # a different seed draws a different schedule


def test_delay_and_torn_modes():
    inj = FaultInjector(
        FaultPlan(
            seed=5,
            points=[
                FaultPoint(site="d", mode="delay", at=(0,), delay_s=0.05,
                           jitter_s=0.05),
                FaultPoint(site="t", mode="torn", at=(0,)),
            ],
        )
    )
    t0 = time.monotonic()
    inj.fire("d")  # delay sleeps, never raises
    assert 0.05 <= time.monotonic() - t0 < 1.0
    data = b"x" * 100
    torn = inj.tear("t", data)
    assert 1 <= len(torn) < len(data)
    # the same seed reproduces the same tear fraction
    inj2 = FaultInjector(
        FaultPlan(seed=5, points=[
            FaultPoint(site="d", mode="delay", at=(0,), delay_s=0.05,
                       jitter_s=0.05),
            FaultPoint(site="t", mode="torn", at=(0,)),
        ])
    )
    inj2.fire("d")
    assert inj2.tear("t", data) == torn
    # non-torn points fall through tear() untouched
    inj3 = FaultInjector(
        FaultPlan(points=[FaultPoint(site="t", mode="abort", at=(0,))])
    )
    assert inj3.tear("t", data) == data


def test_afire_delay_and_counters():
    async def go():
        # NOTE per-point hit counters count visits that REACH the point:
        # visit 0 fires the first point (short-circuit), so the second
        # point's counter first ticks on visit 1 — its hit index 0
        inj = FaultInjector(
            FaultPlan(points=[
                FaultPoint(site="a", mode="delay", at=(0,), delay_s=0.03),
                FaultPoint(site="a", mode="error_after_effect", at=(0,)),
            ])
        )
        t0 = time.monotonic()
        await inj.afire("a")
        assert time.monotonic() - t0 >= 0.03
        with pytest.raises(InjectedFault) as ei:
            await inj.afire("a")
        assert ei.value.mode == "error_after_effect"
        return inj.snapshot()

    counters = _run_async(go())
    assert counters == {"a|delay": 1, "a|error_after_effect": 1}


def test_global_injector_fast_path():
    # inactive: module-level seams are no-ops
    fault_injection.fire("anything")
    assert fault_injection.tear("anything", b"zz") == b"zz"
    assert fault_injection.snapshot() == {}
    fault_injection.configure(
        FaultPlan(points=[FaultPoint(site="g", mode="abort", at=(0,))])
    )
    with pytest.raises(InjectedFault):
        fault_injection.fire("g")
    fault_injection.deactivate()
    fault_injection.fire("g")  # cleared


def test_injected_fault_is_catchable_degradation():
    """Every engine degradation path catches `Exception` — an injected
    fault must be one (and must not masquerade as cancellation)."""
    f = InjectedFault("s", "abort", FaultPoint(site="s"))
    assert isinstance(f, Exception)
    assert not isinstance(f, asyncio.CancelledError)


# -- seam: weight staging ----------------------------------------------------


def _bucket_frames(names_arrays, chunk_mb=10.0):
    return list(pack_buckets(names_arrays, chunk_mb=chunk_mb))


def test_weight_stage_seam_abort_and_retry():
    arr = np.arange(64, dtype=np.float32).reshape(8, 8)
    frames = _bucket_frames([("w", arr)])
    staging = WeightStaging()
    fault_injection.configure(
        FaultPlan(points=[FaultPoint(site="weight.stage.add", at=(0,))])
    )
    with pytest.raises(InjectedFault):
        staging.add_bucket(frames[0])
    assert len(staging) == 0  # nothing partially staged
    # the retry (fault exhausted) lands the full frame
    for f in frames:
        staging.add_bucket(f)
    np.testing.assert_array_equal(staging.finalize()["w"], arr)


def test_weight_stage_torn_frame_rejected():
    """A torn frame must raise (-> 5xx -> client bucket retry), never
    stage partial coverage."""
    arr = np.arange(256, dtype=np.float32)
    (frame,) = _bucket_frames([("w", arr)])
    staging = WeightStaging()
    fault_injection.configure(
        FaultPlan(
            seed=2,
            points=[FaultPoint(site="weight.stage.add", mode="torn", at=(0,))],
        )
    )
    with pytest.raises(ValueError, match="torn weight frame"):
        staging.add_bucket(frame)
    assert len(staging) == 0
    fault_injection.deactivate()
    staging.add_bucket(frame)  # full retry covers
    np.testing.assert_array_equal(staging.finalize()["w"], arr)


def test_unpack_rejects_short_payloads():
    from areal_tpu.core.weight_transfer import unpack_bucket_parts

    with pytest.raises(ValueError):
        unpack_bucket_parts(b"\x01")  # no header
    arr = np.ones(16, dtype=np.float32)
    (frame,) = _bucket_frames([("w", arr)])
    (mlen,) = struct.unpack_from("<Q", frame, 0)
    with pytest.raises(ValueError):
        unpack_bucket_parts(frame[: 8 + mlen - 2])  # torn manifest
    with pytest.raises(ValueError):
        unpack_bucket_parts(frame[:-4])  # torn tensor body


# -- seam: host-KV swap ------------------------------------------------------


def _host_entry(rid="r", nb=1):
    return HostKVEntry(
        rid=rid, k=np.zeros(4), v=np.zeros(4), nb=nb, covered=16,
        tokens=list(range(16)), rope_delta=0, base_key=np.zeros(2),
        ts=time.monotonic(),
    )


def test_kv_swap_seams_fire():
    store = HostKVStore(budget_bytes=1 << 20, block_nbytes=64, block_size=16)
    fault_injection.configure(
        FaultPlan(points=[
            FaultPoint(site="kv.swap_out", at=(0,)),
            FaultPoint(site="kv.swap_in", at=(0,)),
        ])
    )
    with pytest.raises(InjectedFault):
        store.put(_host_entry())
    store.put(_host_entry())  # fault exhausted: offload lands
    with pytest.raises(InjectedFault):
        store.take("r")
    e = store.take("r")
    assert e is not None and e.rid == "r"


# -- seam: task.run + failure accounting -------------------------------------


def test_task_run_seam_releases_capacity():
    runner = AsyncTaskRunner(name="chaos-test")
    runner.start()
    try:
        fault_injection.configure(
            FaultPlan(points=[FaultPoint(site="task.run", at=(0,))])
        )

        async def ok():
            return 42

        runner.submit(lambda: ok())
        runner.submit(lambda: ok())
        results = runner.wait(2, timeout=10)
        excs = [r for r in results if r.exception is not None]
        oks = [r for r in results if r.exception is None]
        assert len(excs) == 1 and isinstance(excs[0].exception, InjectedFault)
        assert len(oks) == 1 and oks[0].result == 42
        assert runner.inflight == 0  # the faulted task released its slot
    finally:
        runner.destroy()


# -- seam: client HTTP (send / recv / torn body) -----------------------------


class _CountingApp:
    """Tiny aiohttp endpoint: counts hits, returns a fixed JSON body."""

    def __init__(self):
        self.hits = 0
        self._runner = None
        self.addr = None

    async def _handler(self, request):
        self.hits += 1
        return web.json_response({"ok": True, "n": 123})

    async def start(self):
        app = web.Application()
        app.router.add_post("/gen", self._handler)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.addr = f"127.0.0.1:{self._runner.addresses[0][1]}"

    async def stop(self):
        await self._runner.cleanup()


def test_http_send_abort_is_pre_effect():
    """An abort at client.http.send is a clean loss: the server never saw
    the request; the transport retry delivers exactly one effect."""

    async def go():
        srv = _CountingApp()
        await srv.start()
        try:
            fault_injection.configure(
                FaultPlan(points=[FaultPoint(site="client.http.send", at=(0,))])
            )
            out = await arequest_with_retry(
                srv.addr, "/gen", payload={}, max_retries=3, retry_delay=0.01
            )
            assert out["ok"] is True
            return srv.hits
        finally:
            await close_current_session()
            await srv.stop()

    assert _run_async(go()) == 1


def test_http_recv_abort_is_error_after_effect():
    """An abort at client.http.recv loses the RESPONSE after the server
    processed the request — the retry is a duplicate delivery (two server
    hits); real /generate seams rely on the xid table to dedup it."""

    async def go():
        srv = _CountingApp()
        await srv.start()
        try:
            fault_injection.configure(
                FaultPlan(points=[
                    FaultPoint(site="client.http.recv",
                               mode="error_after_effect", at=(0,)),
                ])
            )
            out = await arequest_with_retry(
                srv.addr, "/gen", payload={}, max_retries=3, retry_delay=0.01
            )
            assert out["ok"] is True
            return srv.hits
        finally:
            await close_current_session()
            await srv.stop()

    assert _run_async(go()) == 2


def test_http_torn_body_retried():
    async def go():
        srv = _CountingApp()
        await srv.start()
        try:
            fault_injection.configure(
                FaultPlan(seed=4, points=[
                    FaultPoint(site="client.http.body", mode="torn", at=(0,)),
                ])
            )
            out = await arequest_with_retry(
                srv.addr, "/gen", payload={}, max_retries=3, retry_delay=0.01
            )
            assert out["n"] == 123
            return srv.hits
        finally:
            await close_current_session()
            await srv.stop()

    assert _run_async(go()) == 2


def test_error_body_is_structured():
    """4xx payloads surface as parsed dicts on HttpRequestError.body —
    the satellite replacing the stringified-exception regex."""

    async def go():
        app = web.Application()

        async def shed(request):
            return web.json_response(
                {"error": "shed", "retry_after": 0.25}, status=429
            )

        app.router.add_post("/gen", shed)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        addr = f"127.0.0.1:{runner.addresses[0][1]}"
        try:
            with pytest.raises(HttpRequestError) as ei:
                await arequest_with_retry(addr, "/gen", payload={})
            assert ei.value.status == 429
            assert ei.value.body["retry_after"] == 0.25
        finally:
            await close_current_session()
            await runner.cleanup()
        return True

    assert _run_async(go())


def test_backoff_delays_jittered_and_capped():
    ds = list(backoff_delays(1.0, 6, jitter=0.25, cap=8.0))
    assert len(ds) == 6
    for i, d in enumerate(ds):
        base = min(1.0 * (2**i), 8.0)
        assert base * 0.75 <= d <= base * 1.25
    # zero jitter: exact exponential
    assert list(backoff_delays(1.0, 3, jitter=0.0)) == [1.0, 2.0, 4.0]


# -- the seeded chaos smoke (stub fleet, exactly-once + stream identity) -----


class DetStubEngine:
    """Deterministic stub: the stream is a pure function of the prompt
    (the oracle contract), with per-rid generation counts recorded so
    duplicate engine-side generations are directly observable."""

    def __init__(self, n_tokens=4):
        self.n_tokens = n_tokens
        self.calls: dict[str, int] = {}
        self._version = 0

    def get_version(self):
        return self._version

    def get_metrics(self):
        return {"active_tokens": 0}

    @staticmethod
    def expected(input_ids, n_tokens=4):
        s = sum(input_ids) % 997
        return [(s + k) % 997 for k in range(n_tokens)]

    async def agenerate(self, req):
        self.calls[req.rid] = self.calls.get(req.rid, 0) + 1
        await asyncio.sleep(0.02)
        toks = self.expected(req.input_ids, self.n_tokens)
        return ModelResponse(
            input_tokens=list(req.input_ids),
            output_tokens=toks,
            output_logprobs=[0.0] * self.n_tokens,
            output_versions=[0] * self.n_tokens,
            stop_reason="stop",
            latency=0.02,
            ttft=0.02,
        )


async def _scenario_chaos_smoke():
    name_resolve.reconfigure(name_resolve.NameResolveConfig(type="memory"))
    engines = [DetStubEngine(), DetStubEngine()]
    servers = []
    addrs = []
    for eng in engines:
        srv = DecodeServer(JaxDecodeConfig(), engine=eng, shutdown_grace=0.2)
        addrs.append(await srv.start(host="127.0.0.1", port=0))
        servers.append(srv)
    router = DecodeRouter(
        "chaosexp", "t", addrs,
        health_poll_interval=0.15, dead_after_failures=8,
    )
    raddr = await router.start("127.0.0.1", 0)
    client = RemoteInfEngine(
        InferenceEngineConfig(
            experiment_name="chaosexp",
            trial_name="t",
            request_timeout=30,
            request_retries=3,
            fleet_failover_retries=2,
        )
    )
    client.addresses = list(addrs)
    n_reqs = 8
    prompts = {f"r{i}": [i + 1, i + 2, i + 3, 7 * i + 1] for i in range(n_reqs)}
    plan = FaultPlan(
        seed=77,
        points=[
            FaultPoint(site="client.http.send", mode="abort", at=(1,),
                       times=1, match={"endpoint": "/generate"}),
            FaultPoint(site="client.http.recv", mode="error_after_effect",
                       at=(0,), times=1, match={"endpoint": "/generate"}),
            FaultPoint(site="client.http.body", mode="torn", at=(2,),
                       times=1, match={"endpoint": "/generate"}),
            FaultPoint(site="server.generate", mode="delay", at=(1,),
                       times=1, delay_s=0.1),
        ],
    )
    results = {}
    try:
        await asyncio.sleep(0.4)
        fault_injection.configure(plan)

        async def one(rid):
            r = await client.agenerate(
                ModelRequest(
                    rid=rid,
                    input_ids=prompts[rid],
                    gconfig=GenerationHyperparameters(max_new_tokens=4),
                )
            )
            assert rid not in results, f"duplicate completion {rid}"
            results[rid] = list(r.output_tokens)

        await asyncio.gather(*[one(rid) for rid in prompts])
        counters = fault_injection.snapshot()
        # 0 lost, streams identical to the fault-free pure function
        assert len(results) == n_reqs
        for rid, toks in results.items():
            assert toks == DetStubEngine.expected(prompts[rid]), rid
        # 0 duplicated engine-side generations (replay served retries)
        per_rid = {}
        for eng in engines:
            for rid, c in eng.calls.items():
                per_rid[rid] = per_rid.get(rid, 0) + c
        assert per_rid == {rid: 1 for rid in prompts}, per_rid
        # the schedule exercised all four modes
        assert {k.split("|")[1] for k in counters} == {
            "abort", "error_after_effect", "delay", "torn",
        }, counters
        # error-after-effect + torn both forced an idempotency replay
        idem_hits = 0
        for a in addrs:
            m = await arequest_with_retry(a, "/metrics", method="GET",
                                          max_retries=1, timeout=5)
            idem_hits += m["idem_hits_total"]
        assert idem_hits >= 2, idem_hits
        return True
    finally:
        fault_injection.deactivate()
        await close_current_session()
        await router.stop()
        for srv in servers:
            await srv.stop()


def test_chaos_smoke_exactly_once():
    assert _run_async(_scenario_chaos_smoke(), timeout=90)


def test_config_arms_global_injector():
    """An enabled FaultInjectionConfig on the client config installs the
    process-global injector (the production wiring for chaos runs)."""
    cfg = InferenceEngineConfig(
        fault_injection=FaultInjectionConfig(
            enabled=True, seed=1,
            plan='[{"site": "cfg.site", "mode": "abort", "at": [0]}]',
        )
    )
    RemoteInfEngine(cfg)
    assert fault_injection.get() is not None
    with pytest.raises(InjectedFault):
        fault_injection.fire("cfg.site")
    # disabled config does NOT clear an armed injector (the bench arms
    # globally, then builds clients with default configs)
    RemoteInfEngine(InferenceEngineConfig())
    assert fault_injection.get() is not None
