"""Run-ahead decode scheduler: output bit-identity with the synchronous
path, retire-mid-run-ahead reconciliation, pause/commit fencing, and the
consumed-token throughput accounting.

The run-ahead scheduler (`decode_runahead_chunks` >= 1) dispatches chunk
k+1 against device state before the host has consumed chunk k, so the
stop-string scan / retire / admission work overlaps the in-flight device
chunk. Per-slot sampling keys (`fold_in(base_key, slot_length)`) make the
emitted streams a pure function of admission order and token index —
these tests pin that: every token and logprob must be bit-identical
between `decode_runahead_chunks=0` and `1`.
"""

import asyncio
import time

import numpy as np
import pytest

import jax

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.models.qwen2 import ModelConfig, forward, init_params

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)


def _make_engine(runahead: int, **kw):
    cfg = JaxDecodeConfig(
        context_length=kw.pop("context_length", 128),
        max_running_requests=kw.pop("max_running_requests", 4),
        new_tokens_per_chunk=kw.pop("new_tokens_per_chunk", 4),
        decode_runahead_chunks=runahead,
        dtype="float32",
        kv_cache_dtype="float32",
        random_seed=kw.pop("random_seed", 5),
        **kw,
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    return eng


def _run_requests(eng, reqs):
    async def run_all():
        return await asyncio.gather(*[eng.agenerate(r) for r in reqs])

    return asyncio.run(run_all())


def _gather_both(make_reqs):
    """Run the same request set on a runahead=0 and a runahead=1 engine."""
    outs = []
    for runahead in (0, 1):
        eng = _make_engine(runahead)
        try:
            outs.append(_run_requests(eng, make_reqs()))
        finally:
            eng.destroy()
    return outs


def test_greedy_bit_identical_runahead(cpu_devices):
    def make_reqs():
        return [
            ModelRequest(
                input_ids=[2 + i, 7, 11, 3],
                gconfig=GenerationHyperparameters(
                    greedy=True, max_new_tokens=10
                ),
            )
            for i in range(6)  # more than max_running_requests
        ]

    sync, ahead = _gather_both(make_reqs)
    for i, (a, b) in enumerate(zip(sync, ahead)):
        assert a.output_tokens == b.output_tokens, i
        assert a.output_logprobs == b.output_logprobs, i
        assert a.stop_reason == b.stop_reason, i


def test_sampled_bit_identical_runahead(cpu_devices):
    """Sampled streams (temperature on, mixed top-p classes) must be
    bit-identical too: the per-slot fold_in(base_key, length) keying makes
    a slot's stream independent of how tokens were grouped into chunks and
    of which other slots shared the batch."""

    def make_reqs():
        reqs = []
        for i in range(5):
            reqs.append(
                ModelRequest(
                    input_ids=[1 + i, 9, 4],
                    gconfig=GenerationHyperparameters(
                        temperature=1.0,
                        top_p=0.9 if i % 2 else 1.0,
                        max_new_tokens=9,
                    ),
                )
            )
        return reqs

    sync, ahead = _gather_both(make_reqs)
    for i, (a, b) in enumerate(zip(sync, ahead)):
        assert a.output_tokens == b.output_tokens, i
        assert a.output_logprobs == b.output_logprobs, i


def test_stop_token_bit_identical_and_lengths_rewound(cpu_devices):
    """A stop token found mid-chunk retires the slot while the run-ahead
    chunk is already in flight: the speculative tokens must be discarded,
    the slot length rewound to the true end, and the emitted sequence must
    equal the synchronous path's."""
    prompt = [1, 5, 9, 13, 2]

    def greedy_ref(params, p, n):
        seq = list(p)
        for _ in range(n):
            T = len(seq)
            logits = forward(
                params,
                np.array(seq, dtype=np.int32),
                np.arange(T, dtype=np.int32),
                np.zeros(T, dtype=np.int32),
                TINY,
            )
            seq.append(int(np.argmax(np.asarray(logits[-1]))))
        return seq[len(p):]

    eng = _make_engine(1)
    try:
        full = greedy_ref(eng.params, prompt, 12)
        stop_tok = full[5]  # mid-chunk boundary (chunk size 4)
        cut = full.index(stop_tok) + 1
        resp = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(
                    greedy=True, max_new_tokens=12, stop_token_ids=[stop_tok]
                ),
            ),
            timeout=300,
        )
        assert resp.stop_reason == "stop"
        assert resp.output_tokens == full[:cut]
        # quiesce, then check the reconcile rewound the slot's coverage to
        # the true end (prompt[:-1] + consumed tokens), not the run-ahead
        # horizon: retirement registers the slot as a prefix donor with
        # exactly that many rows (a claim over garbage rows would hand
        # later forks junk KV), and zeroes _slot_lengths
        eng.pause_generation()
        assert not eng._inflight
        assert all(int(x) == 0 for x in eng._slot_lengths)
        keys = [k for k in eng._slot_prefix if k is not None]
        assert keys and len(keys[0]) == len(prompt) - 1 + cut, (
            [len(k) for k in keys],
            len(prompt) - 1 + cut,
        )
        # run-ahead garbage was dispatched and dropped, never emitted
        m = eng.get_metrics()
        assert m["generated_tokens_total"] == cut
        eng.continue_generation()
        # the engine stays healthy: a follow-up greedy request on the
        # (retired-donor) KV still matches the step-by-step reference
        resp2 = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=6),
            ),
            timeout=300,
        )
        assert resp2.output_tokens == full[:6]
    finally:
        eng.destroy()


def test_pause_drains_inflight_chunks(cpu_devices):
    """pause_generation must not return while a chunk is dispatched: weight
    swaps and abort_all run behind it, and swapping weights under a
    dispatched computation would break the version-stamp contract."""
    eng = _make_engine(1, context_length=512, max_running_requests=2)
    try:
        import threading

        done = threading.Event()
        result = {}

        def run():
            result["resp"] = eng.generate(
                ModelRequest(
                    input_ids=[3, 1, 4],
                    gconfig=GenerationHyperparameters(
                        greedy=True, max_new_tokens=200
                    ),
                ),
                timeout=300,
            )
            done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if eng.get_metrics()["running_requests"] > 0:
                break
            time.sleep(0.002)
        for _ in range(3):
            eng.pause_generation()
            # the fence: nothing dispatched survives the pause
            assert not eng._inflight
            # a weight-version bump inside the fence must never stamp a
            # token that was produced by the pre-bump weights
            eng.set_version(eng.get_version() + 1)
            eng.continue_generation()
            time.sleep(0.02)
        assert done.wait(120)
        resp = result["resp"]
        # tokens are stamped with a monotonically nondecreasing version
        # sequence (each bump happened on a drained chunk boundary)
        assert resp.output_versions == sorted(resp.output_versions)
    finally:
        eng.destroy()


def test_commit_weights_fenced_by_drain(cpu_devices):
    """update_weights_from_tensor (PR2's commit path) pauses internally:
    with run-ahead on, that pause must consume the in-flight chunk before
    the install, and post-commit tokens must carry the new version."""
    from areal_tpu.core.weight_transfer import flatten_named

    eng = _make_engine(1, context_length=512, max_running_requests=2)
    try:
        import threading

        done = threading.Event()
        result = {}

        def run():
            result["resp"] = eng.generate(
                ModelRequest(
                    input_ids=[3, 1, 4],
                    gconfig=GenerationHyperparameters(
                        greedy=True, max_new_tokens=160
                    ),
                ),
                timeout=300,
            )
            done.set()

        threading.Thread(target=run, daemon=True).start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if eng.get_metrics()["running_requests"] > 0:
                break
            time.sleep(0.002)
        new_params = init_params(TINY, jax.random.PRNGKey(9))
        eng.update_weights_from_tensor(flatten_named(new_params), version=7)
        assert not eng._inflight  # commit drained before installing
        assert done.wait(120)
        resp = result["resp"]
        versions = set(resp.output_versions)
        assert versions <= {0, 7}, versions
        # no token produced by the new weights carries the old stamp: the
        # version sequence flips at most once, 0...0 7...7
        assert resp.output_versions == sorted(resp.output_versions)
    finally:
        eng.destroy()


def test_generated_token_count_counts_consumed_only(cpu_devices):
    """Regression (satellite): _gen_token_count used to add
    active x n_chunk before truncation, so tokens trimmed past a stop
    boundary inflated server throughput metrics."""
    eng = _make_engine(0, new_tokens_per_chunk=8)
    try:
        # find a greedy continuation, then stop on its 2nd token: 6 of the
        # chunk's 8 tokens are trimmed and must not be counted
        probe = eng.generate(
            ModelRequest(
                input_ids=[2, 7, 11],
                gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=8),
            ),
            timeout=300,
        )
        count0 = eng.get_metrics()["generated_tokens_total"]
        assert count0 == len(probe.output_tokens)
        stop_tok = probe.output_tokens[1]
        resp = eng.generate(
            ModelRequest(
                input_ids=[2, 7, 11],
                gconfig=GenerationHyperparameters(
                    greedy=True, max_new_tokens=8, stop_token_ids=[stop_tok]
                ),
            ),
            timeout=300,
        )
        assert resp.stop_reason == "stop"
        assert len(resp.output_tokens) == 2
        assert (
            eng.get_metrics()["generated_tokens_total"]
            == count0 + len(resp.output_tokens)
        )
    finally:
        eng.destroy()


def test_decode_timing_metrics_exported(cpu_devices):
    """The honest ITL split: get_metrics must report device-only ITL
    percentiles and the device-idle fraction, and a completed run must
    have accumulated a busy window."""
    eng = _make_engine(1)
    try:
        eng.generate(
            ModelRequest(
                input_ids=[2, 7, 11],
                gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=12),
            ),
            timeout=300,
        )
        m = eng.get_metrics()
        assert m["chunks_dispatched_total"] >= 3
        assert m["device_busy_s"] > 0.0
        assert 0.0 <= m["device_idle_frac"] <= 1.0
        assert m["itl_p50_ms"] > 0.0
        assert m["itl_p99_ms"] >= m["itl_p50_ms"]
        assert m["decode_runahead_chunks"] == 1
        # per-request ITL entries are device-window only and positive
        assert all(v > 0 for v in eng._chunk_itl_ms)
    finally:
        eng.destroy()


def test_prewarm_compiles_runahead_chunk_variants(cpu_devices):
    """Prewarm must leave every (sampler class x nb bucket) chunk variant
    the run-ahead path can hit compiled, so the first overlapped chunk
    never traces mid-stream."""
    eng = _make_engine(1, context_length=1024, max_running_requests=2)
    try:
        eng.prewarm(prompt_len=200, new_tokens=80, include_fork=False)
        # generation span crosses the 256->512 KV bucket boundary: both
        # buckets' nb variants must exist for both sampler classes
        bsz = eng._alloc.block_size
        for b in eng._expected_chunk_buckets(200, 80):
            nb = -(-b // bsz)
            for use_topp in (False, True):
                assert (use_topp, False, nb) in eng._chunk_fns, (
                    use_topp,
                    nb,
                    list(eng._chunk_fns),
                )
    finally:
        eng.destroy()
