"""Example entry-point smoke: every offline config must launch, train a
few steps, and exit 0 (ref: areal/tests/test_examples.py — example configs
are part of the product surface, and config-tree drift breaks them first).
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(script, config, *overrides, timeout=420):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO
    # examples must run on the CPU mesh exactly as documented
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "examples", script),
            "--config",
            os.path.join(_REPO, "examples", "configs", config),
            *overrides,
        ],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    return r.stdout + r.stderr


@pytest.mark.slow
def test_sft_example_smoke(tmp_path):
    out = _run_example(
        "gsm8k_sft.py",
        "arith_sft_smoke.yaml",
        "total_train_steps=3",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=sft-smoke-test",
    )
    assert "loss" in out


@pytest.mark.slow
def test_rw_example_smoke(tmp_path):
    out = _run_example(
        "hhrlhf_rw.py",
        "arith_rw_smoke.yaml",
        "total_train_steps=3",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=rw-smoke-test",
    )
    assert "rw_loss" in out


@pytest.mark.slow
def test_grpo_example_smoke(tmp_path):
    out = _run_example(
        "gsm8k_grpo.py",
        "arith_grpo_smoke.yaml",
        "total_train_steps=2",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=grpo-smoke-test",
    )
    assert "grpo_actor/loss" in out


@pytest.mark.slow
def test_grpo_multiturn_example_smoke(tmp_path):
    out = _run_example(
        "gsm8k_grpo.py",
        "arith_grpo_multiturn_smoke.yaml",
        "total_train_steps=2",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=grpo-mt-smoke-test",
    )
    assert "grpo_actor/loss" in out


@pytest.mark.slow
def test_boba2_plan_check(tmp_path):
    """The north-star recipe's --plan-check validates the 7B HBM plan and
    AOT-compiles the full-depth sharded program on the CPU mesh (downsized
    to the test harness's 8 virtual devices; the documented 64-device
    command runs the same code on the real d16t4 mesh)."""
    out = _run_example(
        "boba2_grpo.py",
        "boba2_7b_grpo.yaml",
        "--plan-check",
        "allocation_mode=jax:d4t2+d2t4",
        f"cluster.fileroot={tmp_path}",
        timeout=600,
    )
    assert "[plan-check] HBM fit" in out
    assert "full-depth train program compiled" in out
    assert "[plan-check] PASS" in out


@pytest.mark.slow
def test_boba2_tiny_smoke(tmp_path):
    """The boba² entry runs the real async-GRPO loop at tiny geometry:
    same yaml, smoke overrides (scratch model, synthetic math prompts,
    colocated decode)."""
    out = _run_example(
        "boba2_grpo.py",
        "boba2_7b_grpo.yaml",
        "total_train_steps=2",
        "total_train_epochs=1",
        "tokenizer_path=synthetic-arith",
        "allocation_mode=",
        "train_dataset.path=synthetic-arith",
        "train_dataset.batch_size=4",
        "valid_dataset.path=synthetic-arith",
        "valid_dataset.batch_size=8",
        "gconfig.n_samples=4",
        "gconfig.max_new_tokens=8",
        "rollout.max_concurrent_rollouts=32",
        "rollout.consumer_batch_size=16",
        "decode.model_path=",
        "decode.context_length=64",
        "decode.max_running_requests=16",
        "decode.kv_pool_tokens=null",
        "decode.new_tokens_per_chunk=8",
        "decode.dtype=float32",
        "decode.kv_cache_dtype=float32",
        "actor.path=",
        "actor.init_from_scratch=true",
        "actor.dtype=float32",
        "actor.gradient_checkpointing=false",
        "actor.group_size=4",
        "actor.ppo_n_minibatches=2",
        "actor.mb_spec.max_tokens_per_mb=512",
        "actor.optimizer.lr=3.0e-3",
        "actor.adv_norm.group_size=4",
        "saver.freq_steps=null",
        "evaluator.freq_steps=null",
        "recover.mode=disabled",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=boba2-smoke-test",
    )
    assert "grpo_actor/loss" in out


_OFFLINE_RL_OVERRIDES = (
    "total_train_steps=2",
    "total_train_epochs=1",
    "tokenizer_path=synthetic-arith",
    "allocation_mode=",
    "train_dataset.batch_size=4",
    "valid_dataset.path=synthetic-arith",
    "valid_dataset.batch_size=8",
    "gconfig.n_samples=4",
    "gconfig.max_new_tokens=8",
    "rollout.max_concurrent_rollouts=32",
    "rollout.consumer_batch_size=16",
    "decode.model_path=",
    "decode.context_length=64",
    "decode.max_running_requests=16",
    "decode.new_tokens_per_chunk=8",
    "decode.dtype=float32",
    "decode.kv_cache_dtype=float32",
    "actor.path=",
    "actor.init_from_scratch=true",
    "actor.dtype=float32",
    "actor.gradient_checkpointing=false",
    "actor.group_size=4",
    "actor.ppo_n_minibatches=2",
    "actor.mb_spec.max_tokens_per_mb=512",
    "actor.optimizer.lr=3.0e-3",
    "actor.adv_norm.group_size=4",
    "saver.freq_steps=null",
    "evaluator.freq_steps=null",
    "recover.mode=disabled",
)


@pytest.mark.slow
def test_tir_example_smoke(tmp_path):
    """The TIR entry drives the tool-integrated workflow end-to-end on the
    real tir_math.yaml with offline overrides (the workflow's sandbox loop
    runs; the random policy simply rarely emits code blocks)."""
    out = _run_example(
        "tir_math.py",
        "tir_math.yaml",
        *_OFFLINE_RL_OVERRIDES,
        "train_dataset.path=synthetic-arith",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=tir-smoke-test",
    )
    assert "grpo_actor/loss" in out


@pytest.mark.slow
def test_multi_turn_example_smoke(tmp_path):
    out = _run_example(
        "multi_turn_math.py",
        "multi_turn_math.yaml",
        *_OFFLINE_RL_OVERRIDES,
        "train_dataset.path=synthetic-arith",
        "max_turns=2",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=mtm-smoke-test",
    )
    assert "grpo_actor/loss" in out


@pytest.mark.slow
def test_clevr_example_smoke(tmp_path):
    """The vision entry runs fully offline: synthetic counting images
    through the tiny smoke vision tower (set_vision_model), token-only
    training."""
    out = _run_example(
        "clevr_grpo.py",
        "clevr_grpo.yaml",
        *_OFFLINE_RL_OVERRIDES,
        "train_dataset.path=synthetic-vision",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=clevr-smoke-test",
    )
    assert "grpo_actor/loss" in out


@pytest.mark.slow
def test_ppo_example_smoke(tmp_path):
    out = _run_example(
        "gsm8k_ppo.py",
        "arith_ppo_smoke.yaml",
        "total_train_steps=2",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=ppo-smoke-test",
    )
    assert "ppo_critic" in out


@pytest.mark.slow
def test_sft_lora_example_smoke(tmp_path):
    out = _run_example(
        "gsm8k_sft.py",
        "arith_sft_smoke.yaml",
        "total_train_steps=3",
        "model.use_lora=true",
        "model.lora_rank=4",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=sft-lora-smoke-test",
    )
    assert "loss" in out
