"""Example entry-point smoke: every offline config must launch, train a
few steps, and exit 0 (ref: areal/tests/test_examples.py — example configs
are part of the product surface, and config-tree drift breaks them first).
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(script, config, *overrides, timeout=420):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO
    # examples must run on the CPU mesh exactly as documented
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "examples", script),
            "--config",
            os.path.join(_REPO, "examples", "configs", config),
            *overrides,
        ],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    return r.stdout + r.stderr


@pytest.mark.slow
def test_sft_example_smoke(tmp_path):
    out = _run_example(
        "gsm8k_sft.py",
        "arith_sft_smoke.yaml",
        "total_train_steps=3",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=sft-smoke-test",
    )
    assert "loss" in out


@pytest.mark.slow
def test_rw_example_smoke(tmp_path):
    out = _run_example(
        "hhrlhf_rw.py",
        "arith_rw_smoke.yaml",
        "total_train_steps=3",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=rw-smoke-test",
    )
    assert "rw_loss" in out


@pytest.mark.slow
def test_grpo_example_smoke(tmp_path):
    out = _run_example(
        "gsm8k_grpo.py",
        "arith_grpo_smoke.yaml",
        "total_train_steps=2",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=grpo-smoke-test",
    )
    assert "grpo_actor/loss" in out


@pytest.mark.slow
def test_grpo_multiturn_example_smoke(tmp_path):
    out = _run_example(
        "gsm8k_grpo.py",
        "arith_grpo_multiturn_smoke.yaml",
        "total_train_steps=2",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=grpo-mt-smoke-test",
    )
    assert "grpo_actor/loss" in out


@pytest.mark.slow
def test_ppo_example_smoke(tmp_path):
    out = _run_example(
        "gsm8k_ppo.py",
        "arith_ppo_smoke.yaml",
        "total_train_steps=2",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=ppo-smoke-test",
    )
    assert "ppo_critic" in out


@pytest.mark.slow
def test_sft_lora_example_smoke(tmp_path):
    out = _run_example(
        "gsm8k_sft.py",
        "arith_sft_smoke.yaml",
        "total_train_steps=3",
        "model.use_lora=true",
        "model.lora_rank=4",
        f"cluster.fileroot={tmp_path}",
        "experiment_name=sft-lora-smoke-test",
    )
    assert "loss" in out
