"""chunked_label_logprobs == dense gather_logprobs(_entropy), values AND
gradients — the fused loss must be a drop-in for the dense path it
replaces (reference math: areal/utils/functional.py:43,:84)."""

import numpy as np

import jax
import jax.numpy as jnp

from areal_tpu.ops.fused_xent import chunked_label_logprobs
from areal_tpu.utils.functional import (
    gather_logprobs,
    gather_logprobs_entropy,
)


def _setup(T=24, H=16, V=103, seed=0):
    rng = np.random.RandomState(seed)
    h = jnp.asarray(rng.randn(T, H), jnp.float32)
    w = jnp.asarray(rng.randn(H, V) * 0.3, jnp.float32)
    labels = jnp.asarray(rng.randint(0, V, (T,)), jnp.int32)
    return h, w, labels


def test_values_match_dense_nondividing_vocab():
    # V=103 prime: exercises full chunks + remainder chunk
    h, w, labels = _setup()
    dense = gather_logprobs(h @ w, labels)
    for cs in (16, 32, 103, 1000):
        fused = chunked_label_logprobs(h, w, labels, vocab_chunk=cs)
        np.testing.assert_allclose(
            np.asarray(fused), np.asarray(dense), atol=1e-5, rtol=1e-5
        )


def test_entropy_and_temperature_match_dense():
    h, w, labels = _setup(seed=1)
    for temp in (1.0, 0.7):
        dense_lp, dense_ent = gather_logprobs_entropy(
            h @ w, labels, temperature=temp
        )
        lp, ent = chunked_label_logprobs(
            h, w, labels, temperature=temp, with_entropy=True, vocab_chunk=17
        )
        np.testing.assert_allclose(
            np.asarray(lp), np.asarray(dense_lp), atol=1e-5, rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(ent), np.asarray(dense_ent), atol=1e-5, rtol=1e-5
        )


def test_tied_vh_layout():
    h, w, labels = _setup(seed=2)
    dense = gather_logprobs(h @ w, labels)
    fused = chunked_label_logprobs(
        h, jnp.asarray(np.asarray(w).T), labels, head_is_vh=True,
        vocab_chunk=32,
    )
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(dense), atol=1e-5, rtol=1e-5
    )


def test_gradients_match_dense():
    h, w, labels = _setup(seed=3)
    mask = jnp.asarray((np.arange(24) % 3 != 0).astype(np.float32))

    def dense_loss(h, w):
        return -(gather_logprobs(h @ w, labels) * mask).sum() / mask.sum()

    def fused_loss(h, w):
        lp = chunked_label_logprobs(h, w, labels, vocab_chunk=16)
        return -(lp * mask).sum() / mask.sum()

    ld, (dh_d, dw_d) = jax.value_and_grad(dense_loss, argnums=(0, 1))(h, w)
    lf, (dh_f, dw_f) = jax.value_and_grad(fused_loss, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(float(lf), float(ld), atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(dh_f), np.asarray(dh_d), atol=1e-5, rtol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(dw_f), np.asarray(dw_d), atol=1e-5, rtol=1e-4
    )


def test_entropy_gradients_match_dense():
    h, w, labels = _setup(seed=4)

    def dense_loss(h, w):
        lp, ent = gather_logprobs_entropy(h @ w, labels)
        return -(lp.sum()) + 0.01 * ent.sum()

    def fused_loss(h, w):
        lp, ent = chunked_label_logprobs(
            h, w, labels, with_entropy=True, vocab_chunk=16
        )
        return -(lp.sum()) + 0.01 * ent.sum()

    _, (dh_d, dw_d) = jax.value_and_grad(dense_loss, argnums=(0, 1))(h, w)
    _, (dh_f, dw_f) = jax.value_and_grad(fused_loss, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(
        np.asarray(dh_f), np.asarray(dh_d), atol=1e-5, rtol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(dw_f), np.asarray(dw_d), atol=1e-5, rtol=1e-4
    )
