"""Fleet exactly-once surface (ISSUE 8): the decode server's idempotency
table (xid dedup), the client's least-token-load local fallback, and
router-aware failover — a replica dying mid-request must cost latency,
never a duplicated or lost rollout."""

import asyncio
import threading
import time

import pytest

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
    RouterConfig,
)
from areal_tpu.api.io_struct import ModelRequest, ModelResponse
from areal_tpu.core.remote_inf_engine import RemoteInfEngine
from areal_tpu.launcher.decode_server import DecodeServer
from areal_tpu.launcher.router import DecodeRouter
from areal_tpu.utils import name_resolve, names
from areal_tpu.utils.http import arequest_with_retry, close_current_session


class StubEngine:
    """Counts generations; no jax. `delay` holds each call in flight long
    enough for duplicates/kills to race it."""

    def __init__(self, delay=0.05, n_tokens=3, metrics=None):
        self.calls = 0
        self.delay = delay
        self.n_tokens = n_tokens
        self.metrics = metrics if metrics is not None else {"active_tokens": 0}
        self._version = 0

    def get_version(self):
        return self._version

    def get_metrics(self):
        return dict(self.metrics)

    async def agenerate(self, req):
        self.calls += 1
        call = self.calls
        await asyncio.sleep(self.delay)
        return ModelResponse(
            input_tokens=list(req.input_ids),
            # tokens encode the call ordinal: two requests sharing an xid
            # must observe the SAME generation, not merely equal-length ones
            output_tokens=[call] * self.n_tokens,
            output_logprobs=[0.0] * self.n_tokens,
            output_versions=[0] * self.n_tokens,
            stop_reason="stop",
            latency=self.delay,
            ttft=self.delay,
        )


def _run_async(coro, timeout=60):
    result = {}

    def go():
        result["v"] = asyncio.run(coro)

    t = threading.Thread(target=go, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "async scenario timed out"
    return result.get("v")


async def _start_stub_server(engine, **cfg_kw):
    srv = DecodeServer(
        JaxDecodeConfig(**cfg_kw), engine=engine, shutdown_grace=0.2
    )
    addr = await srv.start(host="127.0.0.1", port=0)
    return srv, addr


def _gen_payload(xid=None, rid="r", n=3):
    p = dict(
        rid=rid,
        input_ids=[1, 2, 3],
        gconfig=dict(max_new_tokens=n),
    )
    if xid is not None:
        p["xid"] = xid
    return p


# -- server-side idempotency table ------------------------------------------


async def _scenario_idempotency():
    eng = StubEngine(delay=0.2)
    srv, addr = await _start_stub_server(eng)
    try:
        # concurrent duplicates of one xid: ONE generation, same tokens
        r1, r2 = await asyncio.gather(
            arequest_with_retry(addr, "/generate", payload=_gen_payload("x1")),
            arequest_with_retry(addr, "/generate", payload=_gen_payload("x1")),
        )
        assert eng.calls == 1
        assert r1["output_tokens"] == r2["output_tokens"]
        assert {r1.get("dedup"), r2.get("dedup")} == {None, "in_progress"}

        # replay after completion: cached response, still one generation
        r3 = await arequest_with_retry(
            addr, "/generate", payload=_gen_payload("x1")
        )
        assert eng.calls == 1
        assert r3["dedup"] == "completed"
        assert r3["output_tokens"] == r1["output_tokens"]

        # a different xid (and no xid at all) generate fresh
        await arequest_with_retry(addr, "/generate", payload=_gen_payload("x2"))
        await arequest_with_retry(addr, "/generate", payload=_gen_payload())
        assert eng.calls == 3

        # dedup observability rides on /metrics
        m = await arequest_with_retry(addr, "/metrics", method="GET")
        assert m["idem_hits_total"] == 2
        assert m["idem_entries"] == 2  # x1 + x2 (xid-less never recorded)
        return True
    finally:
        await close_current_session()
        await srv.stop()


def test_decode_server_idempotency():
    assert _run_async(_scenario_idempotency())


async def _scenario_idem_bounds():
    eng = StubEngine(delay=0.0)
    srv, addr = await _start_stub_server(
        eng, idempotency_entries=2, idempotency_ttl_s=1e9
    )
    try:
        for i in range(4):
            await arequest_with_retry(
                addr, "/generate", payload=_gen_payload(f"b{i}")
            )
        assert eng.calls == 4
        assert len(srv._idem) == 2  # LRU-bounded
        # evicted xids regenerate (bounded table = bounded memory, the
        # dedup window is recent deliveries, which is what retries need)
        await arequest_with_retry(addr, "/generate", payload=_gen_payload("b0"))
        assert eng.calls == 5
        # surviving xid replays without regenerating
        await arequest_with_retry(addr, "/generate", payload=_gen_payload("b3"))
        assert eng.calls == 5

        # TTL expiry of completed entries
        srv.config.idempotency_ttl_s = 0.01
        await asyncio.sleep(0.05)
        await arequest_with_retry(addr, "/generate", payload=_gen_payload("c0"))
        assert set(srv._idem) == {"c0"}
        return True
    finally:
        await close_current_session()
        await srv.stop()


def test_decode_server_idempotency_bounds():
    assert _run_async(_scenario_idem_bounds())


async def _scenario_idem_error_path():
    class FailingEngine(StubEngine):
        async def agenerate(self, req):
            self.calls += 1
            if self.calls == 1:
                raise RuntimeError("boom")
            return await super().agenerate(req)

    eng = FailingEngine(delay=0.0)
    srv, addr = await _start_stub_server(eng)
    try:
        with pytest.raises(Exception):
            await arequest_with_retry(
                addr, "/generate", payload=_gen_payload("e1"), max_retries=1
            )
        # a failed submission must NOT poison the xid: the retry generates
        # (calls: 1 boom + 2 from the wrapper AND super on the success)
        out = await arequest_with_retry(
            addr, "/generate", payload=_gen_payload("e1")
        )
        assert eng.calls == 3
        assert out["output_tokens"] == [3, 3, 3]
        assert "dedup" not in out
        return True
    finally:
        await close_current_session()
        await srv.stop()


def test_decode_server_idempotency_error_path():
    assert _run_async(_scenario_idem_error_path())


# -- /drain idempotency (ISSUE 13 satellite) --------------------------------


class DrainStubEngine(StubEngine):
    """StubEngine + the drain surface: two exportable sessions, no jax."""

    def pause_generation(self):
        pass

    def continue_generation(self):
        pass

    def abort_all(self):
        return 0

    def list_exportable_sessions(self):
        return ["s1", "s2"]


async def _scenario_drain_idempotent():
    eng = DrainStubEngine(delay=0.0)
    srv, addr = await _start_stub_server(eng)
    moved = []

    async def slow_migrate(target, rid, xid, retries=1):
        # each export mints a fresh drain-xid, so a double export could
        # NOT be deduped downstream — the per-server guard is the fix
        moved.append((target, rid, xid))
        await asyncio.sleep(0.3)
        return {"bytes": 10}

    srv._migrate_session_out = slow_migrate
    payload = {"targets": ["127.0.0.1:1"]}
    try:
        # concurrent drains (a supervisor retry racing an operator): ONE
        # export of each session, the duplicate replays the first result
        r1, r2 = await asyncio.gather(
            arequest_with_retry(addr, "/drain", payload=payload,
                                max_retries=1, timeout=30),
            arequest_with_retry(addr, "/drain", payload=payload,
                                max_retries=1, timeout=30),
        )
        assert len(moved) == 2, f"double export: {moved}"
        assert {m[1] for m in moved} == {"s1", "s2"}
        assert len({m[2] for m in moved}) == 2  # one fresh xid per rid
        assert {r1.get("dedup"), r2.get("dedup")} == {None, "in_progress"}
        strip = lambda r: {k: v for k, v in r.items() if k != "dedup"}  # noqa: E731
        assert strip(r1) == strip(r2)  # the replay IS the first result
        assert strip(r1)["drained"] == 2 and strip(r1)["status"] == "ok"

        # a later (non-concurrent) drain is a fresh run, not a stale replay
        r3 = await arequest_with_retry(
            addr, "/drain", payload=payload, max_retries=1, timeout=30
        )
        assert "dedup" not in r3
        assert len(moved) == 4
        return True
    finally:
        await close_current_session()
        await srv.stop()


def test_drain_concurrent_calls_export_once():
    assert _run_async(_scenario_drain_idempotent())


# -- client: least-token-load local fallback (ISSUE 8 satellite) ------------


def test_choose_server_least_token_load():
    c = RemoteInfEngine(InferenceEngineConfig())
    c.addresses = ["a:1", "b:1"]
    a1 = c.choose_server("r1", cost=100.0)
    a2 = c.choose_server("r2", cost=1.0)
    assert a2 != a1  # second pick avoids the loaded server
    # the lightly-loaded server keeps winning until loads cross
    a3 = c.choose_server("r3", cost=1.0)
    assert a3 == a2
    # affinity still caches per rid
    assert c.choose_server("r1") == a1
    # releasing r1's cost rebalances back
    c._release_local("r1")
    assert c.choose_server("r4", cost=1.0) == a1
    # exclude skips a failed address even with cached affinity
    assert c.choose_server("r2", exclude=a2) == a1


def test_choose_server_round_robin_tiebreak():
    c = RemoteInfEngine(InferenceEngineConfig())
    c.addresses = ["a:1", "b:1", "c:1"]
    picks = [c.choose_server() for _ in range(6)]
    # zero-cost picks must still rotate (no dogpiling one server)
    assert set(picks[:3]) == set(c.addresses)
    assert picks[:3] == picks[3:]


# -- client failover through the router (exactly-once e2e) ------------------


async def _scenario_failover_exactly_once():
    name_resolve.reconfigure(name_resolve.NameResolveConfig(type="memory"))
    # both wedge until the victim is known (address sort order decides
    # which replica the router picks first)
    eng_a = StubEngine(delay=30.0)
    eng_b = StubEngine(delay=30.0)
    srv_a, addr_a = await _start_stub_server(eng_a)
    srv_b, addr_b = await _start_stub_server(eng_b)
    router = DecodeRouter(
        "fexp",
        "ft",
        [addr_a, addr_b],
        config=RouterConfig(
            schedule_policy="round_robin",
            health_poll_interval=0.15,
            dead_after_failures=2,
        ),
    )
    r_addr = await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.4)
        client = RemoteInfEngine(
            InferenceEngineConfig(
                experiment_name="fexp",
                trial_name="ft",
                request_timeout=60,
                request_retries=1,
                fleet_failover_retries=3,
            )
        )
        client.addresses = [addr_a, addr_b]
        task = asyncio.create_task(
            client.agenerate(
                ModelRequest(rid="fo-1", input_ids=[1, 2, 3],
                             gconfig=GenerationHyperparameters(max_new_tokens=3))
            )
        )
        await asyncio.sleep(0.3)
        assert eng_a.calls + eng_b.calls == 1, "request not in flight yet"
        if eng_a.calls:
            victim, victim_eng, live, live_eng = srv_a, eng_a, srv_b, eng_b
        else:
            victim, victim_eng, live, live_eng = srv_b, eng_b, srv_a, eng_a
        live_eng.delay = 0.05  # survivor answers fast
        # the victim dies mid-request: its handler is cancelled, the
        # client's retry re-schedules (requeue) and lands on the survivor
        await victim.stop()
        resp = await asyncio.wait_for(task, timeout=30)
        assert resp.stop_reason == "stop"
        assert len(resp.output_tokens) == 3
        assert live_eng.calls == 1  # exactly one completion, zero lost
        m = await arequest_with_retry(r_addr, "/metrics", method="GET")
        assert m["client_requeues_total"] >= 1
        return True
    finally:
        await close_current_session()
        await router.stop()
        await srv_a.stop()
        await srv_b.stop()


def test_client_failover_exactly_once():
    assert _run_async(_scenario_failover_exactly_once())


async def _scenario_router_429_fallback():
    """A router that sheds (429) must not wedge the client forever: past
    the request deadline the client degrades to local least-load policy."""
    name_resolve.reconfigure(name_resolve.NameResolveConfig(type="memory"))
    eng = StubEngine(
        delay=0.01,
        # a reported kv pool + kv_pressure_high=0.0 below makes NOTHING
        # admissible — every schedule sheds
        metrics={
            "active_tokens": 0,
            "kv_blocks_total": 10,
            "kv_block_size": 16,
            "kv_tokens_allocated": 0,
        },
    )
    srv, addr = await _start_stub_server(eng)
    router = DecodeRouter(
        "qexp",
        "qt",
        [addr],
        config=RouterConfig(
            health_poll_interval=0.15,
            queue_max=0,  # every unschedulable request sheds immediately
            retry_after_s=0.2,
            # a saturated "pool": nothing is admissible
            kv_pressure_high=0.0,
        ),
    )
    await router.start("127.0.0.1", 0)
    try:
        await asyncio.sleep(0.4)
        client = RemoteInfEngine(
            InferenceEngineConfig(
                experiment_name="qexp",
                trial_name="qt",
                request_timeout=1.0,  # bounded 429-honor window
                request_retries=1,
            )
        )
        client.addresses = [addr]
        t0 = time.monotonic()
        resp = await client.agenerate(
            ModelRequest(rid="q-1", input_ids=[1, 2, 3],
                         gconfig=GenerationHyperparameters(max_new_tokens=3))
        )
        assert resp.stop_reason == "stop"
        # it honored Retry-After at least once before degrading
        assert time.monotonic() - t0 >= 0.2
        assert eng.calls == 1
        return True
    finally:
        await close_current_session()
        await router.stop()
        await srv.stop()


def test_client_honors_429_then_falls_back():
    assert _run_async(_scenario_router_429_fallback())
