"""LoRA fine-tuning: frozen base, trainable adapters, merged export.

Parity: the reference's peft path (areal/engine/fsdp_engine.py:164-295,
TrainEngineConfig.use_lora/lora_rank/lora_alpha/target_modules). TPU shape:
adapters are a separate params["lora"] subtree; the engine differentiates
and optimizes ONLY that subtree (base under stop_gradient), and folds the
deltas into the base kernels on save/push.
"""

import numpy as np
import pytest

import jax

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    MicroBatchSpec,
    OptimizerConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec, SaveLoadMeta
from areal_tpu.engine.sft.lm_engine import JaxLMEngine
from areal_tpu.models.qwen2 import (
    ModelConfig,
    forward,
    init_lora_params,
    init_params,
    merge_lora,
)
from areal_tpu.utils.data import pad_sequences_to_tensors


def _batch(vocab=64, seed=0):
    rng = np.random.RandomState(seed)
    seqs = []
    for L in (11, 9, 13, 7):
        ids = rng.randint(1, vocab, (L,))
        mask = np.zeros(L, dtype=np.int32)
        mask[1:] = 1
        seqs.append(dict(input_ids=ids, loss_mask=mask))
    return pad_sequences_to_tensors(seqs)


def _engine(tmp_path, use_lora, strategy=None):
    cfg = TrainEngineConfig(
        experiment_name="lora",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=64),
        optimizer=OptimizerConfig(
            lr=5e-2,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        use_lora=use_lora,
        lora_rank=4,
        lora_alpha=8,
        target_modules=["q_proj", "v_proj", "down_proj"],
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = ModelConfig(
        vocab_size=64,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        dtype="float32",
        param_dtype="float32",
        lora_rank=4 if use_lora else 0,
        lora_alpha=8.0,
        lora_targets=("q_proj", "v_proj", "down_proj"),
    )
    eng.create_process_group(
        strategy
        or ParallelStrategy(
            data_parallel_size=2, tensor_parallel_size=2,
            context_parallel_size=2,
        )
    )
    eng.initialize(None, FinetuneSpec(1, 100, 4))
    return eng


def test_lora_trains_adapters_only_and_merges(tmp_path):
    eng = _engine(tmp_path, use_lora=True)
    assert "lora" in eng.params
    base_before = jax.tree.map(
        lambda x: np.asarray(x).copy(),
        {k: v for k, v in eng.params.items() if k != "lora"},
    )
    lora_before = jax.tree.map(lambda x: np.asarray(x).copy(), eng.params["lora"])

    batch = _batch()
    losses = [float(eng.train_lm(batch)["loss"]) for _ in range(6)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses

    # base frozen bit-exactly; adapters moved
    jax.tree_util.tree_map_with_path(
        lambda p, a, b: np.testing.assert_array_equal(
            np.asarray(a), b, err_msg=str(p)
        ),
        {k: v for k, v in eng.params.items() if k != "lora"},
        base_before,
    )
    moved = jax.tree.leaves(
        jax.tree.map(
            lambda a, b: float(np.abs(np.asarray(a) - b).max()),
            eng.params["lora"],
            lora_before,
        )
    )
    assert max(moved) > 0.0

    # optimizer state covers only the adapter subtree
    n_opt = sum(
        int(np.prod(x.shape))
        for x in jax.tree.leaves(eng.opt_state)
        if hasattr(x, "shape") and x.ndim > 0
    )
    n_lora = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(eng.params["lora"])
    )
    n_base = sum(
        int(np.prod(x.shape))
        for x in jax.tree.leaves(
            {k: v for k, v in eng.params.items() if k != "lora"}
        )
    )
    assert n_opt <= 2 * n_lora + 8, (n_opt, n_lora)
    assert n_opt < n_base  # the memory story: moments don't cover the base

    # merged export == engine's own eval, loaded back as a PLAIN model
    ev = float(eng.evaluate_lm(batch))
    out = str(tmp_path / "merged")
    eng.save(SaveLoadMeta(path=out, weight_format="hf"))
    eng.destroy()

    from areal_tpu.models.hf_io import load_hf_params

    plain_cfg = ModelConfig(
        vocab_size=64,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        dtype="float32",
        param_dtype="float32",
    )
    plain = load_hf_params(out, plain_cfg, dtype="float32")

    eng2 = _engine(tmp_path, use_lora=False)
    eng2.params = jax.tree.map(
        lambda x, s: jax.device_put(x, s), plain, eng2._param_shardings
    )
    ev2 = float(eng2.evaluate_lm(batch))
    eng2.destroy()
    np.testing.assert_allclose(ev2, ev, rtol=2e-5, atol=2e-5)


def test_lora_zero_init_matches_base_forward():
    cfg = ModelConfig(
        vocab_size=64,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        dtype="float32",
        param_dtype="float32",
        lora_rank=4,
        lora_targets=("q_proj", "k_proj", "v_proj", "o_proj",
                      "gate_proj", "up_proj", "down_proj"),
    )
    p = init_params(cfg, jax.random.PRNGKey(0))
    full = {**p, "lora": init_lora_params(cfg, jax.random.PRNGKey(1))}
    ids = np.arange(12) % 64
    o_base = forward(p, ids, np.arange(12), np.zeros(12, np.int32), cfg)
    o_lora = forward(full, ids, np.arange(12), np.zeros(12, np.int32), cfg)
    np.testing.assert_allclose(
        np.asarray(o_lora), np.asarray(o_base), atol=1e-6
    )


@pytest.mark.slow
def test_lora_activation_delta_equals_weight_merge():
    cfg = ModelConfig(
        vocab_size=64,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        dtype="float32",
        param_dtype="float32",
        lora_rank=4,
        lora_targets=("q_proj", "k_proj", "v_proj", "o_proj",
                      "gate_proj", "up_proj", "down_proj"),
    )
    p = init_params(cfg, jax.random.PRNGKey(0))
    lora = init_lora_params(cfg, jax.random.PRNGKey(1))
    lora = jax.tree_util.tree_map_with_path(
        lambda pth, x: jax.random.normal(jax.random.PRNGKey(7), x.shape) * 0.05
        if pth[-1].key.endswith("_lora_b")
        else x,
        lora,
    )
    full = {**p, "lora": lora}
    ids = np.arange(12) % 64
    o_act = forward(full, ids, np.arange(12), np.zeros(12, np.int32), cfg)
    merged = merge_lora(full, cfg)
    assert "lora" not in merged
    o_merged = forward(merged, ids, np.arange(12), np.zeros(12, np.int32), cfg)
    np.testing.assert_allclose(
        np.asarray(o_act), np.asarray(o_merged), atol=1e-4, rtol=1e-4
    )


def test_lora_rejects_bad_target():
    with pytest.raises(ValueError):
        init_lora_params(
            ModelConfig(lora_rank=4, lora_targets=("nope",)),
            jax.random.PRNGKey(0),
        )
