"""Controller-mode RPC loopback tests (parity: areal/tests/test_rpc.py).

Covers the client/server pair (areal_tpu/scheduler/rpc/) and the
LocalScheduler end to end: spawn a worker subprocess, instantiate an engine
in it by import path, call methods (with args/kwargs and error paths), tear
down.
"""

import asyncio
import threading

import pytest

from areal_tpu.api.scheduler_api import SchedulingSpec
from areal_tpu.scheduler.local_scheduler import LocalScheduler
from areal_tpu.scheduler.rpc.rpc_client import RPCClient
from areal_tpu.scheduler.rpc.rpc_server import RPCServer


class ToyEngine:
    """Importable engine for loopback tests."""

    def __init__(self, base=0):
        self.base = base
        self.version = 0

    def add(self, x, y=1):
        return self.base + x + y

    def set_version(self, v):
        self.version = v

    def get_version(self):
        return self.version

    def boom(self):
        raise ValueError("kaboom")


@pytest.fixture()
def inproc_server():
    """RPCServer in a background thread within this process."""
    loop = asyncio.new_event_loop()
    server = RPCServer()
    started = threading.Event()
    addr_box = {}

    def run():
        asyncio.set_event_loop(loop)

        async def go():
            addr_box["addr"] = await server.start("127.0.0.1", 0)
            started.set()

        loop.run_until_complete(go())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(10)
    yield addr_box["addr"]
    asyncio.run_coroutine_threadsafe(server.stop(), loop).result(5)
    loop.call_soon_threadsafe(loop.stop)
    t.join(5)


def test_rpc_loopback_inprocess(inproc_server):
    addr = inproc_server
    client = RPCClient(timeout=10)
    assert client.wait_healthy(addr)["engine"] is None

    client.create_engine(addr, "tests.test_rpc:ToyEngine", base=100)
    assert client.health(addr)["engine"] == "ToyEngine"
    assert client.call_engine(addr, "add", 2, y=3) == 105
    client.call_engine(addr, "set_version", 7)
    assert client.call_engine(addr, "get_version") == 7


def test_rpc_worker_exception_propagates(inproc_server):
    client = RPCClient(timeout=10)
    client.create_engine(inproc_server, "tests.test_rpc:ToyEngine")
    with pytest.raises(ValueError, match="kaboom"):
        client.call_engine(inproc_server, "boom")


def test_rpc_call_without_engine_fails(inproc_server):
    client = RPCClient(timeout=10)
    from areal_tpu.scheduler.rpc.rpc_client import RPCError

    with pytest.raises(RPCError):
        client.call_engine(inproc_server, "add", 1)


@pytest.mark.slow
def test_local_scheduler_subprocess_loopback():
    sched = LocalScheduler()
    try:
        ids = sched.create_workers("trainer", SchedulingSpec(), count=2)
        assert len(ids) == 2
        workers = sched.get_workers("trainer", timeout=30)
        assert len(workers) == 2
        for wid in ids:
            sched.create_engine(wid, "tests.test_rpc:ToyEngine", base=10)
        assert sched.call_engine(ids[0], "add", 5) == 16
        assert sched.call_engine(ids[1], "add", 5, y=0) == 15
    finally:
        sched.delete_workers()
