"""Multi-turn and vision RLVR workflows against a scripted mock engine."""

import asyncio

import numpy as np

from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelResponse
from areal_tpu.workflow.multi_turn import MultiTurnWorkflow
from areal_tpu.workflow.vision_rlvr import VisionRLVRWorkflow


class FakeTokenizer:
    def encode(self, text):
        return [10 + (ord(c) % 50) for c in text[:5]] or [7]

    def decode(self, ids):
        return " ".join(str(i) for i in ids)

    def apply_chat_template(self, messages, **kw):
        return [1, 2, 3]


class ScriptedEngine:
    """Returns scripted completions in order; stamps version 3."""

    def __init__(self, completions):
        self.completions = list(completions)
        self.calls = 0

    async def agenerate(self, req):
        out = self.completions[min(self.calls, len(self.completions) - 1)]
        self.calls += 1
        return ModelResponse(
            input_tokens=list(req.input_ids),
            output_tokens=list(out),
            output_logprobs=[-0.5] * len(out),
            output_versions=[3] * len(out),
            stop_reason="stop",
        )

    def get_version(self):
        return 3


def test_multi_turn_retries_and_discounts():
    # Reward: only the completion [42] is correct.
    def reward_fn(prompt, completion, prompt_ids, completion_ids, **kw):
        return 1.0 if completion_ids == [42] else 0.0

    eng = ScriptedEngine([[5, 6], [42]])
    wf = MultiTurnWorkflow(
        reward_fn,
        GenerationHyperparameters(n_samples=1, max_new_tokens=8),
        FakeTokenizer(),
        max_turns=3,
        turn_discount=0.5,
    )
    batch = asyncio.run(wf.arun_episode(eng, {"input_ids": [1, 2, 3]}))
    assert eng.calls == 2  # wrong once, then right
    # Discounted: 1.0 * 0.5 (one feedback round)
    assert float(batch["rewards"][0]) == 0.5
    ids = np.asarray(batch["input_ids"][0])
    lm = np.asarray(batch["loss_mask"][0])
    # Loss mask covers exactly the two completions (2 + 1 tokens).
    assert int(lm.sum()) == 3
    # The feedback tokens sit between the turns with loss_mask 0.
    first_completion_at = np.flatnonzero(lm)[0]
    assert ids[first_completion_at] == 5


def test_multi_turn_gives_up_at_max_turns():
    def reward_fn(prompt, completion, prompt_ids, completion_ids, **kw):
        return 0.0

    eng = ScriptedEngine([[5], [6], [7], [8]])
    wf = MultiTurnWorkflow(
        reward_fn,
        GenerationHyperparameters(n_samples=1, max_new_tokens=8),
        FakeTokenizer(),
        max_turns=2,
        turn_discount=0.5,
    )
    batch = asyncio.run(wf.arun_episode(eng, {"input_ids": [1, 2]}))
    assert eng.calls == 2
    assert float(batch["rewards"][0]) == 0.0


def test_vision_rlvr_passes_images_and_groups():
    seen_image_data = []

    class VisionEngine(ScriptedEngine):
        async def agenerate(self, req):
            seen_image_data.append(req.image_data)
            return await super().agenerate(req)

    def reward_fn(prompt, completion, prompt_ids, completion_ids, **kw):
        return float(len(completion_ids))

    eng = VisionEngine([[4, 4]])
    wf = VisionRLVRWorkflow(
        reward_fn,
        GenerationHyperparameters(n_samples=3, max_new_tokens=8),
        tokenizer=FakeTokenizer(),
    )
    data = {"input_ids": [1, 2], "images": ["imgbytes"]}
    batch = asyncio.run(wf.arun_episode(eng, data))
    assert len(seen_image_data) == 3 and seen_image_data[0] == ["imgbytes"]
    assert batch["input_ids"].shape[0] == 3  # the GRPO group
    assert np.allclose(np.asarray(batch["rewards"]), 2.0)


def test_image_data_rides_generate_payload():
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.core.remote_inf_engine import JaxDecodeBackend

    req = ModelRequest(input_ids=[1, 2], image_data=[b"rawbytes", "already-b64"])
    payload = JaxDecodeBackend().build_generate_payload(req)
    import base64

    assert payload["image_data"] == [
        base64.b64encode(b"rawbytes").decode(),
        "already-b64",
    ]
    # Text-only requests keep the lean payload.
    assert "image_data" not in JaxDecodeBackend().build_generate_payload(
        ModelRequest(input_ids=[1])
    )


def test_rlvr_reward_fn_survives_prompt_key_in_data():
    """Dataset items carrying a 'prompt' text field (gsm8k, synthetic-arith)
    must not shadow the reward fn's positional args — regression for the
    TypeError('got multiple values') that silently zeroed every reward."""
    from areal_tpu.workflow.rlvr import RLVRWorkflow

    def reward_fn(prompt, completion, prompt_ids, completion_ids, **kw):
        assert kw.get("answer") == "4"
        return 1.0

    eng = ScriptedEngine([[42]])
    wf = RLVRWorkflow(
        reward_fn,
        GenerationHyperparameters(n_samples=1, max_new_tokens=4),
        FakeTokenizer(),
    )
    batch = asyncio.run(
        wf.arun_episode(
            eng,
            {"input_ids": [1, 2], "prompt": "2+2=", "answer": "4"},
        )
    )
    assert float(np.asarray(batch["rewards"])[0]) == 1.0
