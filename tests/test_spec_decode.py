"""Draft-free speculative decoding (spec_decode="ngram"): correctness.

The verify chunk scores up to spec_k draft positions in one forward and
accepts the longest prefix matching what greedy/sampling would have
emitted — so every emitted token is, by construction, the token the
non-speculative oracle produces, and these tests pin the strong form of
that claim: tokens AND logprobs bit-identical to `spec_decode="off"`
across forks, suffix prefills, stop boundaries mid-accepted-draft,
rejection rewinds under run-ahead, and both kv_layout values (workspace
kept as the bitwise numerics oracle). Plus the telemetry, prewarm
coverage, and the honest per-token ITL accounting.
"""

import asyncio
import time

import numpy as np
import pytest

import jax

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.engine.jax_decode import (
    JaxDecodeEngine,
    _Inflight,
    _Slot,
    _ngram_draft,
)
from areal_tpu.models.qwen2 import ModelConfig, forward, init_params

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = init_params(TINY, jax.random.PRNGKey(0))
    return _PARAMS


def _echo_params():
    """Zero the residual-mixing kernels: greedy decoding becomes a
    deterministic last-token map, which must enter a short cycle — a
    synthetic stand-in for the prompt-quoting repetition of trained
    math/code rollouts, with GUARANTEED n-gram acceptance once the cycle
    repeats (bench.py bench_spec_compare uses the same construction)."""
    p = init_params(TINY, jax.random.PRNGKey(0))
    layers = dict(p["layers"])
    layers["attn"] = {
        **layers["attn"], "o_kernel": layers["attn"]["o_kernel"] * 0.0
    }
    layers["mlp"] = {
        **layers["mlp"], "down_kernel": layers["mlp"]["down_kernel"] * 0.0
    }
    return {**p, "layers": layers}


def _make_engine(spec: str, params=None, tokenizer=None, **kw):
    cfg = JaxDecodeConfig(
        context_length=kw.pop("context_length", 256),
        max_running_requests=kw.pop("max_running_requests", 4),
        new_tokens_per_chunk=kw.pop("new_tokens_per_chunk", 4),
        decode_runahead_chunks=kw.pop("decode_runahead_chunks", 1),
        spec_decode=spec,
        spec_k=kw.pop("spec_k", 4),
        spec_ngram_max=kw.pop("spec_ngram_max", 3),
        dtype="float32",
        kv_cache_dtype="float32",
        random_seed=kw.pop("random_seed", 5),
        **kw,
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig(), tokenizer=tokenizer)
    eng.set_model(params if params is not None else _params(), TINY)
    eng.initialize()
    return eng


def _run_requests(eng, reqs):
    async def run_all():
        return await asyncio.gather(*[eng.agenerate(r) for r in reqs])

    return asyncio.run(run_all())


def _gather_spec_pair(make_reqs, **kw):
    """Run the same request set on a spec-off and a spec-on engine;
    returns (off, on, on_metrics)."""
    outs = []
    metrics = None
    for spec in ("off", "ngram"):
        eng = _make_engine(spec, **kw)
        try:
            outs.append(_run_requests(eng, make_reqs()))
            if spec == "ngram":
                metrics = eng.get_metrics()
        finally:
            eng.destroy()
    return outs[0], outs[1], metrics


# ---------------------------------------------------------------------------
# drafter unit behavior
# ---------------------------------------------------------------------------


def test_ngram_draft_prompt_lookup():
    # trailing 3-gram [2, 3, 4] matched at its earlier occurrence, the
    # continuation (overlapping into the suffix — self-extension) proposed
    assert _ngram_draft([1, 2, 3, 4, 9, 2, 3, 4], 3, 3) == [9, 2, 3]
    # most RECENT occurrence wins
    assert _ngram_draft([5, 1, 7, 5, 2, 7, 5], 2, 2) == [2, 7]
    # longest n wins over a shorter, more recent match
    assert _ngram_draft([1, 2, 3, 9, 9, 1, 2, 3], 2, 3)[0] == 9
    # no earlier occurrence -> no draft; degenerate inputs -> no draft
    assert _ngram_draft([1, 2, 3, 4], 4, 3) == []
    assert _ngram_draft([7], 4, 3) == []
    assert _ngram_draft([1, 1, 1], 0, 3) == []
    # periodic context: the draft IS the next period
    assert _ngram_draft([4, 5, 6] * 4, 5, 3) == [4, 5, 6, 4, 5]


# ---------------------------------------------------------------------------
# bit-identity vs the non-speculative oracle
# ---------------------------------------------------------------------------


def test_greedy_bit_identical_spec(cpu_devices):
    """Greedy streams and logprobs bitwise-equal to spec_decode="off",
    across same-wave duplicate forks and a >=64-token suffix prefill."""

    def make_reqs():
        g = GenerationHyperparameters(greedy=True, max_new_tokens=10)
        base = [1, 5, 9, 13, 2, 4, 6, 8]
        reqs = [
            ModelRequest(input_ids=list(base), gconfig=g),
            ModelRequest(input_ids=list(base), gconfig=g),  # dup -> fork
            # periodic prompt: the drafter proposes from the first chunk on
            ModelRequest(input_ids=[3, 7, 11] * 5, gconfig=g),
            ModelRequest(input_ids=[2, 7, 11, 3], gconfig=g),
        ]
        return reqs

    off, on, m = _gather_spec_pair(make_reqs)
    for i, (a, b) in enumerate(zip(off, on)):
        assert a.output_tokens == b.output_tokens, i
        assert a.output_logprobs == b.output_logprobs, i
        assert a.stop_reason == b.stop_reason, i
    # the spec engine really dispatched verify chunks and drafted tokens
    assert m["spec_chunks_total"] > 0
    assert m["spec_drafted_tokens_total"] > 0
    assert m["prefix_forks_total"] >= 1


def test_greedy_bit_identical_spec_suffix_prefill(cpu_devices):
    """A conversation extension past the 64-token shared-prefix floor
    (fork + suffix prefill) stays bit-identical with speculation on."""

    def run(spec):
        eng = _make_engine(spec)
        try:
            g = GenerationHyperparameters(greedy=True, max_new_tokens=8)
            long_prompt = [(i % 60) + 1 for i in range(70)]
            donor = eng.generate(
                ModelRequest(input_ids=list(long_prompt), gconfig=g),
                timeout=300,
            )
            ext = eng.generate(
                ModelRequest(
                    input_ids=list(long_prompt)
                    + list(donor.output_tokens)
                    + [5, 3],
                    gconfig=g,
                ),
                timeout=300,
            )
            m = eng.get_metrics()
            return [donor, ext], m
        finally:
            eng.destroy()

    off, _ = run("off")
    on, m = run("ngram")
    for i, (a, b) in enumerate(zip(off, on)):
        assert a.output_tokens == b.output_tokens, i
        assert a.output_logprobs == b.output_logprobs, i
    assert m["suffix_prefills_total"] >= 1, m


def test_sampled_bit_identical_spec(cpu_devices):
    """Sampled streams with MIXED top-p classes in one batch: the verify
    chunk flattens positions through the same sampler with the same
    fold_in(base_key, position) keys, so speculation cannot perturb any
    slot's stream — including co-scheduled top_p == 1 slots that must
    keep the primary subkey."""

    def make_reqs():
        reqs = []
        for i in range(5):
            prompt = ([1 + i, 9, 4] * 3) if i % 2 else [1 + i, 9, 4]
            reqs.append(
                ModelRequest(
                    input_ids=prompt,
                    gconfig=GenerationHyperparameters(
                        temperature=1.0,
                        top_p=0.9 if i % 2 else 1.0,
                        max_new_tokens=9,
                    ),
                )
            )
        return reqs

    off, on, m = _gather_spec_pair(make_reqs)
    for i, (a, b) in enumerate(zip(off, on)):
        assert a.output_tokens == b.output_tokens, i
        assert a.output_logprobs == b.output_logprobs, i
    assert m["spec_chunks_total"] > 0


# ---------------------------------------------------------------------------
# stop handling + rejection rewind
# ---------------------------------------------------------------------------


class DigitTok:
    eos_token_id = None

    def decode(self, ids):
        return "".join(str(i % 10) for i in ids)


def test_stop_string_lands_mid_accepted_draft(cpu_devices):
    """A stop string completing INSIDE an accepted draft run must truncate
    exactly where the oracle truncates: the verify chunk emitted past the
    boundary in one batch, and _truncate_at_stop + the retire rewind drop
    the overrun."""
    prompt = [2, 7, 11, 3]
    g_probe = GenerationHyperparameters(greedy=True, max_new_tokens=24)

    eng_off = _make_engine("off", params=_echo_params(), tokenizer=DigitTok())
    try:
        full = eng_off.generate(
            ModelRequest(input_ids=prompt, gconfig=g_probe), timeout=300
        ).output_tokens
        text = "".join(str(t % 10) for t in full)
        # deepest stop string with a determinate FIRST completion: inside
        # the established cycle every short window repeats each period, so
        # scan (boundary, length) pairs for the latest boundary a window
        # (anchored into the unique pre-cycle prefix) first completes at —
        # deep enough that drafts are already riding accepted
        boundary, stop_s = 0, ""
        for b in range(6, len(full) + 1):
            for L in range(2, min(14, b) + 1):
                cand = text[b - L : b]
                if cand not in text[: b - 1]:
                    if b > boundary:
                        boundary, stop_s = b, cand
                    break
        assert boundary >= 8, (boundary, text)
        assert stop_s not in text[: boundary - 1]
        g_stop = GenerationHyperparameters(
            greedy=True, max_new_tokens=24, stop=[stop_s]
        )
        oracle = eng_off.generate(
            ModelRequest(input_ids=prompt, gconfig=g_stop), timeout=300
        )
    finally:
        eng_off.destroy()
    assert oracle.stop_reason == "stop"
    assert oracle.output_tokens == full[:boundary]

    eng = _make_engine(
        "ngram", params=_echo_params(), tokenizer=DigitTok(), spec_k=7
    )
    try:
        resp = eng.generate(
            ModelRequest(input_ids=prompt, gconfig=g_stop), timeout=300
        )
        m = eng.get_metrics()
        assert resp.stop_reason == "stop"
        assert resp.output_tokens == oracle.output_tokens
        assert resp.output_logprobs == oracle.output_logprobs
        # the stop really landed in speculative territory: drafts were
        # accepted during this run (echo params guarantee the cycle)
        assert m["spec_accepted_per_chunk_mean"] > 0, m
        # quiesce: the retire rewound the slot to the TRUE end (prompt[:-1]
        # + consumed tokens), not the verify chunk's worst-case horizon
        eng.pause_generation()
        assert not eng._inflight
        keys = [k for k in eng._slot_prefix if k is not None]
        assert keys and len(keys[0]) == len(prompt) - 1 + len(
            resp.output_tokens
        )
    finally:
        eng.destroy()


def test_rejection_rewind_under_runahead(cpu_devices):
    """Rejected drafts + a stop token found mid-chunk while the NEXT
    verify chunk is already in flight (runahead=1): the speculative
    tokens are discarded, the worst-case length projection reconciles,
    and the donor registration covers exactly the true end."""
    prompt = [1, 5, 9, 13, 2]

    def greedy_ref(params, p, n):
        seq = list(p)
        for _ in range(n):
            T = len(seq)
            logits = forward(
                params,
                np.array(seq, dtype=np.int32),
                np.arange(T, dtype=np.int32),
                np.zeros(T, dtype=np.int32),
                TINY,
            )
            seq.append(int(np.argmax(np.asarray(logits[-1]))))
        return seq[len(p):]

    eng = _make_engine("ngram", decode_runahead_chunks=1)
    try:
        full = greedy_ref(eng.params, prompt, 12)
        stop_tok = full[5]
        cut = full.index(stop_tok) + 1
        resp = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(
                    greedy=True, max_new_tokens=12, stop_token_ids=[stop_tok]
                ),
            ),
            timeout=300,
        )
        assert resp.stop_reason == "stop"
        assert resp.output_tokens == full[:cut]
        eng.pause_generation()
        assert not eng._inflight
        # every worst-case projection must have reconciled away: retired
        # slot lengths are zeroed, the donor registration is the true end
        assert all(int(x) == 0 for x in eng._slot_lengths)
        keys = [k for k in eng._slot_prefix if k is not None]
        assert keys and len(keys[0]) == len(prompt) - 1 + cut
        m = eng.get_metrics()
        assert m["generated_tokens_total"] == cut
        eng.continue_generation()
        # engine stays healthy after the rewind
        resp2 = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(greedy=True, max_new_tokens=6),
            ),
            timeout=300,
        )
        assert resp2.output_tokens == full[:6]
    finally:
        eng.destroy()


# ---------------------------------------------------------------------------
# layout parity
# ---------------------------------------------------------------------------


def test_layout_parity_with_spec(cpu_devices):
    """kv_layout='workspace' stays the bitwise numerics oracle with
    speculation ON: the paged xla verify gathers its blocks and runs the
    identical attention op sequence (ops/chunked_attention.
    verify_attention), so tokens AND logprobs match exactly."""

    def run(layout):
        eng = _make_engine(
            "ngram", kv_layout=layout, paged_attn_impl="xla", page_size=16,
            spec_k=4,
        )
        try:
            g = GenerationHyperparameters(greedy=True, max_new_tokens=10)
            gs = GenerationHyperparameters(
                temperature=1.0, top_p=0.9, max_new_tokens=8
            )
            return _run_requests(
                eng,
                [
                    ModelRequest(input_ids=[3, 7, 11] * 5, gconfig=g),
                    ModelRequest(input_ids=[2, 7, 11, 3], gconfig=g),
                    ModelRequest(input_ids=[5, 9] * 4, gconfig=gs),
                ],
            )
        finally:
            eng.destroy()

    ws = run("workspace")
    pg = run("paged")
    for i, (a, b) in enumerate(zip(ws, pg)):
        assert a.output_tokens == b.output_tokens, i
        assert a.output_logprobs == b.output_logprobs, i


def test_paged_verify_op_pallas_matches_xla(cpu_devices):
    """Op level: the q_len>1 Pallas split-KV verify kernel (interpret mode
    on CPU) agrees with the gather+verify_attention XLA path."""
    from areal_tpu.ops.paged_attention import paged_attention_qlen

    rng = np.random.RandomState(3)
    R, W, nH, nKV, hd, bsz, nb = 3, 4, 4, 2, 16, 8, 3
    n_blocks = 1 + R * nb
    q = rng.randn(R, W, nH, hd).astype(np.float32)
    kp = rng.randn(n_blocks, bsz, nKV, hd).astype(np.float32)
    vp = rng.randn(n_blocks, bsz, nKV, hd).astype(np.float32)
    bt = np.arange(1, 1 + R * nb, dtype=np.int32).reshape(R, nb)
    base = np.array([5, 11, 0], dtype=np.int32)
    pos = base[:, None] + np.arange(W)[None, :]
    valid = np.arange(nb * bsz)[None, None, :] <= pos[:, :, None]
    import jax.numpy as jnp

    args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(valid))
    out_x = paged_attention_qlen(*args, impl="xla")
    out_p = paged_attention_qlen(*args, impl="pallas", interpret=True)
    np.testing.assert_allclose(
        np.asarray(out_x), np.asarray(out_p), atol=1e-5
    )


# ---------------------------------------------------------------------------
# telemetry, prewarm, ITL accounting
# ---------------------------------------------------------------------------


def test_spec_metrics_accounting(cpu_devices):
    """On the echo workload the acceptance telemetry must show real
    acceptance and stay internally consistent: histogram mass equals the
    verify-chunk count, drafted = accepted + rejected, and the mean
    accepted-per-chunk clears 1.0 (the bench acceptance bar)."""
    eng = _make_engine(
        "ngram", params=_echo_params(), spec_k=7, new_tokens_per_chunk=8
    )
    try:
        g = GenerationHyperparameters(greedy=True, max_new_tokens=96)
        eng.generate(
            ModelRequest(input_ids=[2, 7, 11, 3], gconfig=g), timeout=300
        )
        m = eng.get_metrics()
        assert m["spec_decode"] == "ngram"
        assert m["spec_chunks_total"] > 0
        hist = m["spec_accepted_per_chunk"]
        assert sum(hist.values()) == m["spec_chunks_total"]
        accepted = sum(int(k) * v for k, v in hist.items())
        assert (
            m["spec_drafted_tokens_total"]
            == accepted + m["spec_rejected_tokens_total"]
        )
        assert m["spec_accepted_per_chunk_mean"] > 1.0, m
        assert 0.0 < m["spec_draft_hit_rate"] <= 1.0
        assert (
            m["spec_emitted_per_chunk_mean"]
            == pytest.approx(m["spec_accepted_per_chunk_mean"] + 1.0)
        )
    finally:
        eng.destroy()


def test_prewarm_compiles_verify_variants(cpu_devices):
    """Prewarm must ghost-compile every (q-width bucket x sampler class x
    nb bucket) verify variant the drafter can select, alongside the
    normal chunk variants — no first-request compile stall when
    spec_decode='ngram' is live."""
    eng = _make_engine(
        "ngram", context_length=1024, max_running_requests=2, spec_k=4
    )
    try:
        eng.prewarm(prompt_len=200, new_tokens=80, include_fork=False)
        bsz = eng._alloc.block_size
        assert eng._spec_draft_buckets() == [1, 2, 4]
        spec_k = int(eng.config.spec_k)
        for b in eng._expected_chunk_buckets(200, 80, grow=spec_k + 1):
            nb = -(-b // bsz)
            for use_topp in (False, True):
                # normal chunk variants still covered
                for db in eng._spec_draft_buckets():
                    assert (use_topp, nb, db + 1) in eng._verify_fns, (
                        use_topp, nb, db + 1, list(eng._verify_fns),
                    )
        for b in eng._expected_chunk_buckets(200, 80):
            nb = -(-b // bsz)
            for use_topp in (False, True):
                assert (use_topp, False, nb) in eng._chunk_fns
    finally:
        eng.destroy()


def test_consume_divides_by_emitted_tokens(cpu_devices):
    """Regression (ISSUE 6 satellite): per-token ITL divides the device
    window by tokens actually emitted (accepted + bonus), NOT the
    dispatched draft width — a verify chunk that emitted 3 of 5
    dispatched positions delivered 3 tokens in that window."""
    eng = _make_engine("ngram", spec_k=4)
    try:
        eng.pause_generation()
        R = eng.config.max_running_requests
        item = _Slot(
            rid="itl-test",
            prompt=[1, 2, 3],
            gconfig=GenerationHyperparameters(max_new_tokens=100),
            future=None,
            loop=None,
        )
        eng._slots[0] = item
        eng._slot_lengths[0] = 2 + 5  # base 2, worst-case projected +W
        W = 5
        active = np.zeros(R, dtype=bool)
        active[0] = True
        rec = _Inflight(
            toks=np.full((W, R), 7, dtype=np.int32),
            logps=np.zeros((W, R), dtype=np.float32),
            items=list(eng._slots),
            active=active,
            epochs=eng._slot_epoch.copy(),
            version=0,
            t_dispatch=time.monotonic() - 0.9,
            n_chunk=W,
            spec_w=W,
            accepted=np.array([2] + [0] * (R - 1), dtype=np.int32),
            draft_lens=np.array([4] + [0] * (R - 1), dtype=np.int32),
        )
        eng._consume_chunk(rec)
        # accepted 2 + bonus = 3 emitted tokens
        assert len(item.tokens) == 3
        assert len(item.itl) == 3
        # each per-token ITL ~= 0.9s / 3 = 0.3s; dividing by the dispatched
        # width W=5 would report ~0.18s — the dishonest number
        for v in item.itl:
            assert 0.25 < v < 0.45, item.itl
        # worst-case projection reconciled: 7 - (W - emitted) = 5
        assert int(eng._slot_lengths[0]) == 5
        m = eng.get_metrics()
        assert m["spec_chunks_total"] == 1
        assert m["spec_rejected_tokens_total"] == 2  # drafted 4, accepted 2
        eng._slots[0] = None
        eng.continue_generation()
    finally:
        eng.destroy()
