"""SampleLedger / SampleWAL: exactly-once sample accounting units
(ISSUE 14 tentpole part 2)."""

import json
import os

from areal_tpu.core.sample_ledger import SampleLedger, SampleWAL


def test_rid_issuance_monotonic():
    led = SampleLedger()
    assert [led.new_rid() for _ in range(5)] == [0, 1, 2, 3, 4]


def test_accept_consume_lifecycle():
    led = SampleLedger()
    rids = [led.new_rid() for _ in range(4)]
    for r in rids:
        assert led.on_accepted(r, version=0)
    assert led.pending_count() == 4
    assert led.consumed_count() == 0
    led.on_consumed(rids, version=0)
    assert led.pending_count() == 0
    assert led.consumed_count() == 4
    for r in rids:
        assert led.is_consumed(r)


def test_duplicate_accept_is_deduped():
    """A trajectory re-arriving for an already-consumed (or already
    pending) rid must be rejected — the double-train path."""
    led = SampleLedger()
    rid = led.new_rid()
    assert led.on_accepted(rid, 0)
    assert not led.on_accepted(rid, 0)  # still pending
    led.on_consumed([rid], 0)
    assert not led.on_accepted(rid, 1)  # consumed long ago
    assert led.deduped_total() == 2


def test_external_rid_advances_issuance():
    led = SampleLedger()
    assert led.on_accepted(100, 0)
    assert led.new_rid() == 101


def test_state_dict_excludes_pending():
    """Accepted-but-unconsumed trajectories die with the process — they
    must NOT be restored (the executor recomputes accepted := consumed)."""
    led = SampleLedger()
    a, b = led.new_rid(), led.new_rid()
    led.on_accepted(a, 0)
    led.on_accepted(b, 0)
    led.on_consumed([a], 0)
    st = led.state_dict()
    assert st["consumed"] == [a]
    assert st["next_rid"] == 2
    led2 = SampleLedger()
    led2.load_state_dict(st)
    assert led2.consumed_count() == 1
    assert led2.pending_count() == 0
    # b was pending: after restore it is NOT consumed, so regeneration is
    # accepted normally (no false dedup)
    assert led2.on_accepted(b, 1)


def test_wal_append_replay(tmp_path):
    wal = SampleWAL(str(tmp_path / "ledger.wal"))
    wal.append(1, 0, [3, 1, 2])
    wal.append(2, 1, [4, 5])
    entries = wal.replay()
    assert [e["seq"] for e in entries] == [1, 2]
    assert entries[0]["rids"] == [1, 2, 3]  # stored sorted
    assert entries[1]["version"] == 1


def test_wal_drops_torn_trailing_line(tmp_path):
    path = str(tmp_path / "ledger.wal")
    wal = SampleWAL(path)
    wal.append(1, 0, [1])
    with open(path, "a") as f:
        f.write('{"seq": 2, "version"')  # crash mid-append
    assert [e["seq"] for e in wal.replay()] == [1]


def test_wal_rollback_truncates_uncommitted(tmp_path):
    path = str(tmp_path / "ledger.wal")
    wal = SampleWAL(path)
    for seq in (1, 2, 3):
        wal.append(seq, seq - 1, [seq * 10])
    assert wal.rollback_to(1) == 2
    assert [e["seq"] for e in wal.replay()] == [1]
    # idempotent
    assert wal.rollback_to(1) == 0


def test_ledger_restore_rolls_wal_back(tmp_path):
    """The committed checkpoint carries wal_seq; entries journaled after it
    (the wait()-to-dump window) are rolled back on restore, so their
    regenerated samples re-journal without duplicate entries."""
    path = str(tmp_path / "ledger.wal")
    led = SampleLedger()
    led.attach_wal(SampleWAL(path))
    r0, r1 = led.new_rid(), led.new_rid()
    led.on_accepted(r0, 0)
    led.on_consumed([r0], 0)
    committed = led.state_dict()  # checkpoint commits here (wal_seq=1)
    led.on_accepted(r1, 0)
    led.on_consumed([r1], 0)  # journaled but never committed
    assert len(SampleWAL(path).replay()) == 2

    led2 = SampleLedger()
    led2.attach_wal(SampleWAL(path))
    led2.load_state_dict(committed)
    entries = SampleWAL(path).replay()
    assert [e["seq"] for e in entries] == [1]
    # the regenerated r1 consumes again under a fresh seq with no collision
    assert led2.on_accepted(r1, 0)
    led2.on_consumed([r1], 0)
    entries = SampleWAL(path).replay()
    assert [e["seq"] for e in entries] == [1, 2]
    rids = [r for e in entries for r in e["rids"]]
    assert sorted(rids) == sorted([r0, r1])  # each sample exactly once


def test_wal_entries_are_json_lines(tmp_path):
    path = str(tmp_path / "ledger.wal")
    SampleWAL(path).append(1, 7, [9])
    with open(path) as f:
        e = json.loads(f.readline())
    assert e == dict(seq=1, version=7, rids=[9])
    assert os.path.getsize(path) > 0
