"""Chunked online-softmax attention == dense reference (values + grads),
incl. segments, padding, GQA, sliding window, chunk-boundary cases."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.models.qwen2 import (
    ModelConfig,
    PADDING_SEGMENT,
    resolve_attn_impl,
    segment_causal_mask,
)
from areal_tpu.ops.chunked_attention import chunked_attention


def _dense_ref(q, k, v, seg, window=None):
    T, nH, hd = q.shape
    nKV = k.shape[1]
    kf = jnp.repeat(k, nH // nKV, axis=1)
    vf = jnp.repeat(v, nH // nKV, axis=1)
    s = jnp.einsum("thd,shd->hts", q, kf).astype(jnp.float32) / np.sqrt(hd)
    m = segment_causal_mask(seg, window)
    s = jnp.where(m[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(m[None], p, 0.0)
    return jnp.einsum("hts,shd->thd", p, vf).astype(q.dtype)


def _setup(T, nH=4, nKV=2, hd=8, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(T, nH, hd), jnp.float32)
    k = jnp.asarray(rng.randn(T, nKV, hd), jnp.float32)
    v = jnp.asarray(rng.randn(T, nKV, hd), jnp.float32)
    seg = np.zeros(T, np.int32)
    seg[T // 3 : 2 * T // 3] = 1
    seg[2 * T // 3 :] = 2
    seg[T - max(T // 8, 1):] = PADDING_SEGMENT
    return q, k, v, jnp.asarray(seg)


@pytest.mark.parametrize("T,chunk", [(48, 16), (50, 16), (32, 64), (64, 64)])
def test_matches_dense(T, chunk):
    q, k, v, seg = _setup(T)
    out = chunked_attention(q, k, v, seg, kv_chunk=chunk)
    ref = _dense_ref(q, k, v, seg)
    mask = np.asarray(seg) != PADDING_SEGMENT
    np.testing.assert_allclose(
        np.asarray(out)[mask], np.asarray(ref)[mask], atol=1e-5, rtol=1e-5
    )


@pytest.mark.parametrize("window", [1, 7, 16])
def test_sliding_window_matches_dense(window):
    q, k, v, seg = _setup(56, seed=1)
    out = chunked_attention(q, k, v, seg, sliding_window=window, kv_chunk=16)
    ref = _dense_ref(q, k, v, seg, window=window)
    mask = np.asarray(seg) != PADDING_SEGMENT
    np.testing.assert_allclose(
        np.asarray(out)[mask], np.asarray(ref)[mask], atol=1e-5, rtol=1e-5
    )


@pytest.mark.slow
def test_gradients_match_dense():
    q, k, v, seg = _setup(40, seed=2)
    w = jnp.asarray(np.asarray(seg) != PADDING_SEGMENT, jnp.float32)

    def loss_c(q, k, v):
        o = chunked_attention(q, k, v, seg, sliding_window=9, kv_chunk=16)
        return jnp.sum((o * w[:, None, None]) ** 2)

    def loss_d(q, k, v):
        o = _dense_ref(q, k, v, seg, window=9)
        return jnp.sum((o * w[:, None, None]) ** 2)

    gc = jax.grad(loss_c, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gc, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        )


def test_impl_resolution_for_windowed_models():
    assert resolve_attn_impl(
        ModelConfig(sliding_window=8, attn_impl="auto")
    ) == "chunked"
    assert resolve_attn_impl(
        ModelConfig(sliding_window=8, attn_impl="dense")
    ) == "dense"
    with pytest.raises(NotImplementedError):
        resolve_attn_impl(ModelConfig(sliding_window=8, attn_impl="flash"))
