"""Ring attention on the 8-virtual-device CPU mesh vs single-shard reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.ops.ring_attention import ring_flash_attention
from areal_tpu.parallel import mesh as mesh_lib
from tests.test_flash_attention import dense_reference, make_inputs


@pytest.fixture()
def sp_mesh(cpu_devices):
    mesh = mesh_lib.build_mesh(
        ParallelStrategy(data_parallel_size=2, context_parallel_size=2,
                         tensor_parallel_size=2)
    )
    mesh_lib.set_current_mesh(mesh)
    yield mesh
    mesh_lib.set_current_mesh(None)


@pytest.mark.slow
def test_ring_matches_dense(sp_mesh):
    # ring over dp*sp = 4 shards, tp=2 sharding the 4 query heads.
    T, nH, nKV, hd = 512, 4, 2, 32
    q, k, v, seg = make_inputs(T, nH, nKV, hd, pad=41, n_seqs=4)
    out = ring_flash_attention(q, k, v, seg, mesh=sp_mesh, interpret=True)
    ref = dense_reference(q, k, v, seg)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


@pytest.mark.slow
def test_ring_gradients_match(sp_mesh):
    T, nH, nKV, hd = 512, 4, 2, 32
    q, k, v, seg = make_inputs(T, nH, nKV, hd, pad=17, seed=5, n_seqs=3)

    def loss_ring(q, k, v):
        o = ring_flash_attention(q, k, v, seg, mesh=sp_mesh, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(dense_reference(q, k, v, seg)))

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-4, rtol=3e-4, err_msg=name
        )


def test_ring_fallback_no_mesh():
    # No mesh registered: silently uses the single-shard kernel.
    T, nH, nKV, hd = 256, 2, 2, 32
    q, k, v, seg = make_inputs(T, nH, nKV, hd, pad=0, seed=7, n_seqs=2)
    out = ring_flash_attention(q, k, v, seg, mesh=None, interpret=True)
    ref = dense_reference(q, k, v, seg)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ring_under_jit_with_sharded_inputs(sp_mesh):
    # The real call pattern: inside jit, token axis sharded over (dp, sp).
    T, nH, nKV, hd = 512, 4, 2, 32
    q, k, v, seg = make_inputs(T, nH, nKV, hd, pad=9, seed=9, n_seqs=4)
    tok_sharding = mesh_lib.packed_sharding(sp_mesh)
    q = jax.device_put(q, jax.sharding.NamedSharding(
        sp_mesh, jax.sharding.PartitionSpec(("dp", "sp"), None, None)))
    seg_s = jax.device_put(seg, tok_sharding)

    @jax.jit
    def f(q, k, v, seg):
        return ring_flash_attention(q, k, v, seg, mesh=sp_mesh, interpret=True)

    out = f(q, k, v, seg_s)
    ref = dense_reference(q, k, v, seg)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )
