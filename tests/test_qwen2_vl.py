"""Qwen2-VL-class vision tower + multimodal decode serving.

Parity target: the reference's VLM rollout path (areal/workflow/
vision_rlvr.py carrying image_data to an SGLang Qwen2-VL server); here the
in-process decode engine owns the tower. Oracle for the E2E test: a
step-by-step greedy loop over `prefill(..., input_embeds=...)` with the
same spliced embeddings and m-rope tables.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.models.qwen2 import ModelConfig, init_params, prefill, rope_table
from areal_tpu.models.qwen2_vl import (
    VisionConfig,
    forward_vision,
    init_vision_params,
    mrope_positions,
    mrope_table,
    patch_grid_coords,
    splice_image_embeds,
    vision_param_shapes,
)

TEXT = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)
VIS = VisionConfig(
    embed_dim=16,
    depth=2,
    num_heads=2,
    mlp_dim=32,
    in_channels=3,
    patch_size=2,
    temporal_patch_size=1,
    spatial_merge_size=2,
    hidden_size=32,  # language hidden
)
IMG_TOK = 63
MERGE = VIS.spatial_merge_size


def test_vision_tower_shapes_and_mask():
    params = init_vision_params(VIS, jax.random.PRNGKey(0))
    # one 1x4x4-patch image -> 16 patches -> 4 merged embeddings
    thw = np.array([[1, 4, 4]])
    coords = patch_grid_coords(thw, MERGE)
    pv = np.random.RandomState(0).randn(16, VIS.patch_dim).astype(np.float32)
    out = forward_vision(params, jnp.asarray(pv), jnp.asarray(coords), VIS)
    assert out.shape == (4, VIS.hidden_size)
    assert np.isfinite(np.asarray(out)).all()
    # pad rows masked out of attention must not change real outputs
    pv_pad = np.concatenate([pv, np.zeros((8, VIS.patch_dim), np.float32)])
    co_pad = np.concatenate([coords, np.zeros((8, 2), np.int64)])
    valid = np.concatenate([np.ones(16, bool), np.zeros(8, bool)])
    out_pad = forward_vision(
        params,
        jnp.asarray(pv_pad),
        jnp.asarray(co_pad),
        VIS,
        valid=jnp.asarray(valid),
    )
    np.testing.assert_allclose(
        np.asarray(out_pad)[:4], np.asarray(out), rtol=1e-5, atol=1e-6
    )


def test_vision_variants_both_run():
    """Qwen2-VL (layer norm + gelu MLP) and Qwen2.5-VL (rms + SwiGLU)
    configurations both build and run."""
    for cfg in (
        VisionConfig.from_hf_dict(
            dict(embed_dim=16, depth=1, num_heads=2, mlp_ratio=2,
                 patch_size=2, temporal_patch_size=1, hidden_size=32,
                 in_channels=3)
        ),
        VisionConfig.from_hf_dict(
            dict(hidden_size=16, depth=1, num_heads=2, intermediate_size=32,
                 patch_size=2, temporal_patch_size=1, out_hidden_size=32,
                 in_channels=3)
        ),
    ):
        params = init_vision_params(cfg, jax.random.PRNGKey(0))
        thw = np.array([[1, 2, 2]])
        pv = np.random.RandomState(1).randn(4, cfg.patch_dim).astype(np.float32)
        out = forward_vision(
            params,
            jnp.asarray(pv),
            jnp.asarray(patch_grid_coords(thw, cfg.spatial_merge_size)),
            cfg,
        )
        assert out.shape == (1, 32)
        assert np.isfinite(np.asarray(out)).all()
    assert cfg.norm_type == "rms" and cfg.mlp_type == "silu_glu"


def test_patch_grid_coords_window_major():
    """Coords follow HF rot_pos_emb's merge-window permutation: the first
    merge^2 rows are the top-left 2x2 window."""
    coords = patch_grid_coords(np.array([[1, 4, 4]]), 2)
    np.testing.assert_array_equal(
        coords[:4], [[0, 0], [0, 1], [1, 0], [1, 1]]
    )
    np.testing.assert_array_equal(
        coords[4:8], [[0, 2], [0, 3], [1, 2], [1, 3]]
    )


def test_mrope_positions_hf_semantics():
    """The HF get_rope_index docstring example: a 3x2x2 vision span then 5
    text tokens (merge=1 so llm grid == patch grid)."""
    ids = [IMG_TOK] * 12 + [1, 2, 3, 4, 5]
    pos, delta = mrope_positions(
        np.array(ids), np.array([[3, 2, 2]]), IMG_TOK, merge=1
    )
    np.testing.assert_array_equal(
        pos[0, :12], [0, 0, 0, 0, 1, 1, 1, 1, 2, 2, 2, 2]
    )
    np.testing.assert_array_equal(
        pos[1, :12], [0, 0, 1, 1, 0, 0, 1, 1, 0, 0, 1, 1]
    )
    np.testing.assert_array_equal(
        pos[2, :12], [0, 1, 0, 1, 0, 1, 0, 1, 0, 1, 0, 1]
    )
    np.testing.assert_array_equal(pos[0, 12:], [3, 4, 5, 6, 7])
    assert (pos[1, 12:] == pos[0, 12:]).all()
    # delta: next position (8) minus sequence length (17)
    assert delta == 8 - 17


def test_mrope_table_reduces_to_1d_rope_for_text():
    """When all three position dims are equal (text tokens), the m-rope
    table equals the standard 1-D table regardless of sections."""
    pos = np.arange(6)
    pos3 = np.stack([pos, pos, pos])
    cos_m, sin_m = mrope_table(pos3, 8, 10000.0, (1, 1, 2))
    cos_1, sin_1 = rope_table(jnp.asarray(pos), 8, 10000.0)
    np.testing.assert_allclose(np.asarray(cos_m), np.asarray(cos_1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sin_m), np.asarray(sin_1), rtol=1e-6)


def test_splice_image_embeds_order():
    H = 8
    tok = jnp.zeros((5, H))
    img = jnp.stack([jnp.full((H,), 1.0), jnp.full((H,), 2.0)])
    ids = jnp.array([7, IMG_TOK, 9, IMG_TOK, 11])
    out = np.asarray(splice_image_embeds(tok, ids, img, IMG_TOK))
    assert (out[1] == 1.0).all() and (out[3] == 2.0).all()
    assert (out[0] == 0).all() and (out[2] == 0).all() and (out[4] == 0).all()


def _greedy_vlm_reference(params, vparams, prompt, image_data, n_new):
    """Oracle: per-step full prefill from spliced embeddings + m-rope."""
    pv = np.concatenate([np.asarray(d["pixel_values"]) for d in image_data])
    thw = np.concatenate(
        [np.asarray(d["image_grid_thw"]).reshape(-1, 3) for d in image_data]
    )
    img = forward_vision(
        vparams,
        jnp.asarray(pv, dtype=jnp.float32),
        jnp.asarray(patch_grid_coords(thw, MERGE)),
        VIS,
    )
    sections = (8, 4, 4)  # head_dim 16 -> half=16? hd=32/4=8 -> half=4
    hd = TEXT.head_dim_
    sections = (hd // 4, hd // 8, hd // 8)
    seq = list(prompt)
    for _ in range(n_new):
        ids = jnp.asarray(np.array(seq, dtype=np.int32))
        embeds = params["embed"]["embedding"][ids].astype(jnp.float32)
        splice_ids = np.array(seq, dtype=np.int32)
        splice_ids[len(prompt):] = 0  # generated tokens never splice
        embeds = splice_image_embeds(
            embeds, jnp.asarray(splice_ids), img, IMG_TOK
        )
        pos3, _ = mrope_positions(np.array(seq), thw, IMG_TOK, MERGE)
        cos, sin = mrope_table(pos3, hd, TEXT.rope_theta, sections)
        logits, _, _ = prefill(
            params,
            ids,
            jnp.arange(len(seq), dtype=jnp.int32),
            TEXT,
            with_logits=True,
            input_embeds=embeds,
            rope_cos=cos,
            rope_sin=sin,
        )
        seq.append(int(np.argmax(np.asarray(logits[-1]))))
    return seq[len(prompt):]


@pytest.mark.slow
def test_vlm_decode_end_to_end_mrope(cpu_devices):
    params = init_params(TEXT, jax.random.PRNGKey(0))
    vparams = init_vision_params(VIS, jax.random.PRNGKey(1))
    hd = TEXT.head_dim_
    sections = (hd // 4, hd // 8, hd // 8)
    eng = JaxDecodeEngine(
        JaxDecodeConfig(
            context_length=64,
            max_running_requests=2,
            new_tokens_per_chunk=4,
            dtype="float32",
            kv_cache_dtype="float32",
        ),
        InferenceEngineConfig(),
    )
    eng.set_model(params, TEXT)
    eng.set_vision_model(vparams, VIS, IMG_TOK, mrope_sections=sections)
    eng.initialize()
    try:
        rng = np.random.RandomState(3)
        # 1x4x4 grid -> 16 patches -> 4 merged embeddings -> 4 image tokens
        image = dict(
            pixel_values=rng.randn(16, VIS.patch_dim).astype(np.float32),
            image_grid_thw=np.array([[1, 4, 4]]),
        )
        prompt = [5, IMG_TOK, IMG_TOK, IMG_TOK, IMG_TOK, 9, 2]
        resp = eng.generate(
            ModelRequest(
                input_ids=prompt,
                gconfig=GenerationHyperparameters(
                    greedy=True, max_new_tokens=6
                ),
                image_data=[image],
            ),
            timeout=900,
        )
        expected = _greedy_vlm_reference(params, vparams, prompt, [image], 6)
        assert resp.output_tokens == expected
        # the m-rope delta was applied to this slot (image span compresses
        # positions: 4 image tokens -> max(1, 2, 2) = 2 positions)
        assert eng._slot_rope_delta.min() == -2
        # text-only requests still work beside vision ones
        resp2 = eng.generate(
            ModelRequest(
                input_ids=[1, 2, 3],
                gconfig=GenerationHyperparameters(
                    greedy=True, max_new_tokens=3
                ),
            ),
            timeout=900,
        )
        assert resp2.output_len == 3
    finally:
        eng.destroy()


def test_vlm_without_tower_raises(cpu_devices):
    eng = JaxDecodeEngine(
        JaxDecodeConfig(
            context_length=32,
            max_running_requests=1,
            dtype="float32",
            kv_cache_dtype="float32",
        ),
        InferenceEngineConfig(),
    )
    eng.set_model(init_params(TEXT, jax.random.PRNGKey(0)), TEXT)
    eng.initialize()
    try:
        with pytest.raises(NotImplementedError):
            eng.generate(
                ModelRequest(
                    input_ids=[1, 2],
                    gconfig=GenerationHyperparameters(max_new_tokens=2),
                    image_data=[{"pixel_values": np.zeros((4, 12))}],
                ),
                timeout=60,
            )
    finally:
        eng.destroy()


def test_vision_param_shapes_consistent():
    shapes = vision_param_shapes(VIS)
    params = init_vision_params(VIS, jax.random.PRNGKey(0))

    def walk(exp, got, path=""):
        if isinstance(exp, tuple):
            assert got.shape == exp, f"{path}: {got.shape} != {exp}"
            return
        for k in exp:
            walk(exp[k], got[k], f"{path}/{k}")

    walk(shapes, params)
