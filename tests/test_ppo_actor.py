"""PPOActor unit/behavior tests: advantage semantics, update direction,
minibatch splitting (parity focus: areal/engine/ppo/actor.py)."""

import numpy as np
import pytest

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    MicroBatchSpec,
    NormConfig,
    OptimizerConfig,
    PPOActorConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.engine.ppo.actor import JaxPPOActor, _split_minibatches
from areal_tpu.models.qwen2 import ModelConfig

TINY = ModelConfig(
    vocab_size=32,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)


def _actor(**overrides):
    kw = dict(
        experiment_name="t",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=512),
        optimizer=OptimizerConfig(
            lr=5e-3, warmup_steps_proportion=0.0, lr_scheduler_type="constant"
        ),
        gradient_checkpointing=False,
        group_size=2,
        ppo_n_minibatches=1,
        eps_clip=0.2,
        kl_ctl=0.0,
        use_decoupled_loss=False,
        recompute_logprob=True,
        temperature=1.0,
    )
    kw.update(overrides)
    actor = JaxPPOActor(PPOActorConfig(**kw))
    actor.model_config = TINY
    actor.create_process_group(ParallelStrategy(data_parallel_size=8))
    actor.initialize(None, FinetuneSpec(1, 64, 8))
    return actor


def _synthetic_batch():
    """4 seqs of len 8 (3 prompt + 5 answer): rows 0/2 rewarded."""
    B, T = 4, 8
    ids = np.zeros((B, T), dtype=np.int64)
    ids[:, :3] = [1, 2, 3]
    ids[0, 3:] = 16
    ids[1, 3:] = 5
    ids[2, 3:] = 16
    ids[3, 3:] = 5
    return dict(
        input_ids=ids,
        attention_mask=np.ones((B, T), dtype=np.int64),
        loss_mask=np.pad(np.ones((B, 5), np.int64), ((0, 0), (3, 0))),
        rewards=np.array([1.0, 0.0, 1.0, 0.0], dtype=np.float32),
        logprobs=np.zeros((B, T), dtype=np.float32),
    )


@pytest.fixture(scope="module")
def actor(cpu_devices):
    return _actor()


@pytest.mark.slow
def test_advantages_are_reward_to_go(actor):
    batch = _synthetic_batch()
    batch["prox_logp"] = actor.compute_logp(batch)
    actor.compute_advantages(batch)
    adv = batch["advantages"]
    # GRPO mode (values=0, gamma=lam=1): adv == reward-to-go on trained span
    np.testing.assert_allclose(adv[0, 2:7], 1.0, atol=1e-5)
    np.testing.assert_allclose(adv[1], 0.0, atol=1e-5)
    # last position has no label
    np.testing.assert_allclose(adv[:, -1], 0.0, atol=1e-5)
    # rolled loss mask: position 2 (label = first answer token) is trained
    assert batch["loss_mask"][0, 2] == 1
    assert batch["loss_mask"][0, 7] == 0


@pytest.mark.slow
def test_update_moves_policy_toward_reward(actor):
    def p_first_answer(batch):
        lp = actor.compute_logp(dict(batch))
        return np.exp(lp[:, 2])

    base = _synthetic_batch()
    before = p_first_answer(base)
    for _ in range(8):
        batch = _synthetic_batch()
        batch["prox_logp"] = actor.compute_logp(batch)
        actor.compute_advantages(batch)
        actor.ppo_update(batch)
    after = p_first_answer(base)
    # rewarded rows rise substantially; unrewarded do not rise
    assert after[0] > before[0] * 5
    assert after[2] > before[2] * 5
    assert after[1] < before[1] * 2


def test_ppo_update_reports_loss_stats(actor):
    """The loss's aux stats (entropy, clip/KL ratios — the set the
    reference records from inside grpo_loss_fn) must surface through
    train_batch instead of being discarded."""
    batch = _synthetic_batch()
    batch["prox_logp"] = actor.compute_logp(batch)
    actor.compute_advantages(batch)
    stats = actor.ppo_update(batch)[0]
    for k in ("entropy", "importance_weight", "approx_kl", "clip_ratio",
              "behave_imp_weight"):
        assert any(key.endswith(k) for key in stats), (k, sorted(stats))
    ent = next(v for key, v in stats.items() if key.endswith("entropy"))
    assert 0.0 < ent < 10.0, ent


def test_split_minibatches_covers_batch():
    B, T = 6, 10
    rng = np.random.RandomState(0)
    attn = np.zeros((B, T), dtype=np.int64)
    for i in range(B):
        attn[i, : rng.randint(3, T)] = 1
    data = dict(
        attention_mask=attn,
        input_ids=rng.randint(0, 10, (B, T)),
        rewards=np.arange(B, dtype=np.float32),
    )
    mbs = _split_minibatches(data, 3)
    assert len(mbs) >= 3
    all_rewards = np.concatenate([mb["rewards"] for mb in mbs])
    assert sorted(all_rewards.tolist()) == list(range(B))


@pytest.mark.slow
def test_decoupled_loss_uses_behav_logp(cpu_devices):
    actor = _actor(use_decoupled_loss=True, recompute_logprob=False,
                   behav_imp_weight_cap=5.0)
    batch = _synthetic_batch()
    # pretend the inference engine produced slightly different logprobs
    batch["logprobs"] = np.full_like(batch["logprobs"], -2.0)
    batch["prox_logp"] = actor.compute_logp(batch)
    actor.compute_advantages(batch)
    stats = actor.ppo_update(batch)
    assert stats and np.isfinite(list(stats[0].values())).all()
