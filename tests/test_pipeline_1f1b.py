"""1F1B pipeline schedule vs the GPipe reference (tier-1, CPU, fast).

Two invariants the schedule swap must preserve / deliver:

1. EXACTNESS — per-step losses and parameter gradients from the explicit
   interleaved 1F1B loop (parallel/pipeline.pipeline_1f1b_grads via
   engine `jax.pipeline_schedule="1f1b"`) match the autodiff-through-GPipe
   path within fp32 roundoff on the same weights and stacked micro-batch
   stream.
2. MEMORY — at identical M >= 2·pp the compiled 1F1B program's temp
   (activation) memory is strictly lower than GPipe's: GPipe's backward
   holds residuals for all M + pp - 1 scan steps while 1F1B's stash is
   capped at 2·pp - 1 stage inputs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    MicroBatchSpec,
    OptimizerConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.engine.jax_engine import _memory_analysis_dict
from areal_tpu.engine.sft.lm_engine import (
    JaxLMEngine,
    compute_packed_sft_loss,
)
from areal_tpu.models.qwen2 import ModelConfig

TINY4 = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,  # 2 layers per stage at pp=2
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

PP = 2
M = 8  # >= 2*pp, several microbatches in flight at the 1f1b steady state
T = 64


@pytest.fixture(scope="module")
def pp_engine(cpu_devices):
    cfg = TrainEngineConfig(
        experiment_name="pp1f1b",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=T),
        optimizer=OptimizerConfig(
            lr=1e-2,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=True,
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = TINY4
    eng.create_process_group(
        ParallelStrategy(
            pipeline_parallel_size=PP,
            data_parallel_size=2,
            tensor_parallel_size=2,
        )
    )
    eng.initialize(None, FinetuneSpec(1, 64, 8))
    yield eng
    eng.destroy()


@pytest.fixture(scope="module")
def stacked_batch():
    rng = np.random.RandomState(0)
    return (
        {
            "input_ids": jnp.asarray(
                rng.randint(1, TINY4.vocab_size, (M, T)), jnp.int32
            ),
            "position_ids": jnp.asarray(
                np.tile(np.arange(T, dtype=np.int32), (M, 1))
            ),
            "segment_ids": jnp.asarray(
                np.repeat(np.arange(2, dtype=np.int32), T // 2)[None].repeat(
                    M, 0
                )
            ),
            "loss_mask": jnp.asarray(
                rng.randint(0, 2, (M, T)).astype(np.int32)
            ),
        },
        jnp.asarray(rng.rand(M).astype(np.float32) + 0.5),
    )


def _run(eng, schedule, stacked, weights):
    eng.config.jax.pipeline_schedule = schedule
    fn = eng._get_pipelined_grad_step(compute_packed_sft_loss)
    compiled = fn.lower(eng.params, stacked, weights).compile()
    losses, _stats, grads = fn(eng.params, stacked, weights)
    return (
        np.asarray(losses),
        jax.tree.map(np.asarray, grads),
        _memory_analysis_dict(compiled),
    )


def test_1f1b_matches_gpipe_and_uses_less_memory(pp_engine, stacked_batch):
    stacked, weights = stacked_batch
    l_1f1b, g_1f1b, mem_1f1b = _run(pp_engine, "1f1b", stacked, weights)
    l_gpipe, g_gpipe, mem_gpipe = _run(pp_engine, "gpipe", stacked, weights)

    np.testing.assert_allclose(l_1f1b, l_gpipe, rtol=2e-5, atol=1e-6)
    flat1, tree1 = jax.tree_util.tree_flatten(g_1f1b)
    flat2, tree2 = jax.tree_util.tree_flatten(g_gpipe)
    assert tree1 == tree2
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)

    # Acceptance: compiled peak activation (temp) memory strictly lower at
    # identical M. CPU XLA exposes temp_size_in_bytes; if a future jaxlib
    # stops reporting it, skip rather than assert on garbage.
    t1, tg = (
        mem_1f1b.get("temp_size_in_bytes"),
        mem_gpipe.get("temp_size_in_bytes"),
    )
    if not t1 or not tg:
        pytest.skip("backend exposes no temp_size_in_bytes")
    assert t1 < tg, (t1, tg)


def test_1f1b_train_step_matches_gpipe_engine(cpu_devices):
    """Full train_batch parity: same batch through two fresh engines, one
    per schedule — losses and grad norms agree step over step."""
    from areal_tpu.utils.data import pad_sequences_to_tensors

    def _engine(schedule):
        cfg = TrainEngineConfig(
            experiment_name="pp1f1b",
            trial_name=schedule,
            path="",
            init_from_scratch=True,
            dtype="float32",
            mb_spec=MicroBatchSpec(max_tokens_per_mb=64),
            optimizer=OptimizerConfig(
                lr=1e-2,
                warmup_steps_proportion=0.0,
                lr_scheduler_type="constant",
                gradient_clipping=1.0,
            ),
            gradient_checkpointing=False,
        )
        cfg.jax.pipeline_schedule = schedule
        eng = JaxLMEngine(cfg)
        eng.model_config = TINY4
        eng.create_process_group(
            ParallelStrategy(
                pipeline_parallel_size=2,
                data_parallel_size=2,
                tensor_parallel_size=2,
            )
        )
        eng.initialize(None, FinetuneSpec(1, 64, 8))
        return eng

    rng = np.random.RandomState(3)
    seqs = []
    for L in (9, 30, 7, 25, 11, 13, 8, 21):
        ids = rng.randint(1, 64, (L,))
        mask = np.zeros(L, dtype=np.int32)
        mask[L // 2 :] = 1
        seqs.append(dict(input_ids=ids, loss_mask=mask))
    batch = pad_sequences_to_tensors(seqs)

    e1 = _engine("1f1b")
    e2 = _engine("gpipe")
    try:
        for _ in range(2):
            s1 = e1.train_lm(batch)
            s2 = e2.train_lm(batch)
            np.testing.assert_allclose(
                s1["loss"], s2["loss"], rtol=2e-5, atol=1e-6
            )
            np.testing.assert_allclose(
                s1["grad_norm"], s2["grad_norm"], rtol=2e-4, atol=1e-6
            )
    finally:
        e1.destroy()
        e2.destroy()


def test_unknown_schedule_rejected(pp_engine):
    pp_engine.config.jax.pipeline_schedule = "interleaved"
    try:
        with pytest.raises(ValueError, match="pipeline_schedule"):
            pp_engine._get_pipelined_grad_step(compute_packed_sft_loss)
    finally:
        pp_engine.config.jax.pipeline_schedule = "1f1b"
