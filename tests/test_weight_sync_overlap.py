"""Overlapped weight sync: stage while generating, pause only for commit.

The tentpole invariants of the staged "dcn" push:
- bucket staging NEVER pauses generation — tokens keep flowing until the
  commit, whose pause window covers only the install/apply;
- commits are version-fenced: a stale push_id is rejected (409), so no
  token can mix weight versions;
- a failed/aborted push drops server-side staging (explicit /abort_weights)
  instead of leaking multi-GiB buffers;
- weight-sync observability on both ends (n_pushes, wire bytes, staging
  seconds vs commit-pause seconds), with commit-pause « transfer time;
- LoRA delta pushes ship only the trainable adapter subtree and fold
  base + scale*A@B onto the PRISTINE base kernels at commit.
"""

import asyncio
import dataclasses
import threading
import time

import numpy as np
import pytest

import jax

from areal_tpu.api.cli_args import InferenceEngineConfig, JaxDecodeConfig
from areal_tpu.core.remote_inf_engine import RemoteInfEngine
from areal_tpu.core.weight_transfer import flatten_named
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.models.qwen2 import init_lora_params, init_params, merge_lora
from areal_tpu.utils.http import HttpRequestError
from tests.test_remote_inf_engine import TINY, _ServerThread, _greedy_req


@pytest.fixture(scope="module")
def served(cpu_devices):
    cfg = JaxDecodeConfig(
        context_length=160,
        max_running_requests=4,
        new_tokens_per_chunk=2,  # many small dispatches -> long decode window
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    st = _ServerThread(eng)
    client = RemoteInfEngine(
        InferenceEngineConfig(setup_timeout=30, request_timeout=60)
    )
    client.initialize(addr=st.addr)
    yield eng, st, client
    client.destroy()
    st.stop()
    eng.destroy()


def _fresh_named(seed: int):
    return flatten_named(init_params(TINY, jax.random.PRNGKey(seed)))


def test_staging_keeps_tokens_flowing_until_commit(served):
    """Generation must run uninterrupted through the whole bucket transfer;
    the only pause is the commit, and version stamps stay consistent."""
    eng, _, client = served
    old_version = eng.get_version()
    pauses = []
    orig_pause = eng.pause_generation

    def counting_pause(*a, **kw):
        pauses.append(time.monotonic())
        return orig_pause(*a, **kw)

    eng.pause_generation = counting_pause
    try:
        result = {}

        def _bg():
            result["resp"] = asyncio.run(
                client.agenerate(_greedy_req([5, 3, 1], 64))
            )

        t = threading.Thread(target=_bg)
        t.start()
        # wait until the request is actually decoding
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if any(s is not None for s in eng._slots):
                break
            time.sleep(0.005)
        tok0 = eng._gen_token_count
        n_pauses_before = len(pauses)
        # tiny buckets -> dozens of staged frames, generation live throughout
        push_id = client.stage_weights(_fresh_named(3), chunk_mb=0.02)
        assert len(pauses) == n_pauses_before, (
            "bucket staging paused generation"
        )
        # fully staged but uncommitted: tokens must KEEP flowing
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not result.get("resp"):
            if eng._gen_token_count > tok0:
                break
            time.sleep(0.005)
        assert result.get("resp") or eng._gen_token_count > tok0, (
            "no tokens generated while a fully-staged push awaited commit"
        )
        t.join(timeout=60)
        assert not t.is_alive()
        # everything generated pre-commit carries the OLD version
        assert result["resp"].output_versions == [old_version] * 64
        client.commit_staged(push_id, version=old_version + 7)
        assert len(pauses) > n_pauses_before  # commit is the pause window
        assert eng.get_version() == old_version + 7
        after = asyncio.run(client.agenerate(_greedy_req([5, 3, 1], 4)))
        assert after.output_versions == [old_version + 7] * 4
    finally:
        eng.pause_generation = orig_pause


def test_commit_version_fencing_rejects_stale_push(served):
    eng, _, client = served
    v = eng.get_version()
    push_a = client.stage_weights(_fresh_named(4), chunk_mb=0.05)
    # a newer push supersedes A's staging server-side
    push_b = client.stage_weights(_fresh_named(5), chunk_mb=0.05)
    with pytest.raises(HttpRequestError) as ei:
        client.commit_staged(push_a, version=v + 1)
    assert ei.value.status == 409
    assert eng.get_version() == v  # stale commit must not move the version
    client.commit_staged(push_b, version=v + 1)
    assert eng.get_version() == v + 1
    np.testing.assert_allclose(
        np.asarray(eng.params["final_norm"]),
        _fresh_named(5)["final_norm"],
        atol=1e-6,
    )


def test_abort_weights_drops_staging(served):
    eng, st, client = served
    push_id = client.stage_weights(_fresh_named(6), chunk_mb=0.05)
    assert len(st.server._weight_staging) > 0
    client.abort_push(push_id)
    assert len(st.server._weight_staging) == 0
    assert not st.server._weight_staging._bufs
    with pytest.raises(HttpRequestError):
        client.commit_staged(push_id, version=99)


def test_failed_push_auto_aborts_server_staging(served):
    """A client crash mid-stream must POST /abort_weights so the server
    does not sit on partial staging until the next push."""
    eng, st, client = served

    def _explodes():
        yield "p0", np.ones((4096,), np.float32)  # flushes several buckets
        yield "p1", np.ones((4096,), np.float32)
        raise RuntimeError("producer died mid-push")

    aborts_before = client.get_metrics()["aborts"]
    with pytest.raises(RuntimeError, match="producer died"):
        client.stage_weights(_explodes(), chunk_mb=0.005)
    assert client.get_metrics()["aborts"] == aborts_before + 1
    # server-side staging fully released (no leaked buffers/tensors)
    assert len(st.server._weight_staging) == 0
    assert not st.server._weight_staging._bufs


def test_sync_metrics_commit_pause_much_less_than_transfer(served):
    eng, st, client = served
    before = client.get_metrics()
    v = eng.get_version()
    # ~hundreds of tiny buckets: the transfer window dwarfs the apply
    client.update_weights_from_tensor(
        _fresh_named(7), version=v + 1, chunk_mb=0.005
    )
    m = client.get_metrics()
    assert m["n_pushes"] == before["n_pushes"] + 1
    assert m["last_push_bytes"] > 0
    assert m["wire_bytes"] > before["wire_bytes"]
    staging = m["staging_secs"] - before["staging_secs"]
    commit = m["commit_pause_secs"] - before["commit_pause_secs"]
    assert staging > 0 and commit > 0
    # the headline claim: the observed pause is the apply, not the transfer
    assert commit < staging, (commit, staging)
    # server-side mirror via /metrics
    from areal_tpu.utils.http import aget_with_retry

    srv = asyncio.run(aget_with_retry(st.addr, "/metrics"))
    ws = srv["weight_sync"]
    assert ws["n_pushes"] >= 1
    assert ws["wire_bytes"] > 0
    assert ws["commit_pause_secs"] < ws["staging_secs"]
    assert ws["staged_tensors"] == 0  # nothing left behind


def test_legacy_non_overlap_mode_still_works(served):
    eng, _, client = served
    v = eng.get_version()
    client.update_weights_from_tensor(
        _fresh_named(8), version=v + 1, chunk_mb=1, overlap=False
    )
    assert eng.get_version() == v + 1
    np.testing.assert_allclose(
        np.asarray(eng.params["final_norm"]),
        _fresh_named(8)["final_norm"],
        atol=1e-6,
    )


# -- LoRA delta push ----------------------------------------------------

LORA_CFG = dataclasses.replace(
    TINY, lora_rank=4, lora_alpha=8.0, lora_targets=("q_proj", "v_proj")
)


@pytest.fixture()
def lora_served(cpu_devices):
    cfg = JaxDecodeConfig(
        context_length=96,
        max_running_requests=4,
        new_tokens_per_chunk=4,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    base = init_params(TINY, jax.random.PRNGKey(0))
    eng.set_model(base, TINY)
    eng.initialize()
    st = _ServerThread(eng)
    client = RemoteInfEngine(
        InferenceEngineConfig(setup_timeout=30, request_timeout=60)
    )
    client.initialize(addr=st.addr)
    yield eng, base, client
    client.destroy()
    st.stop()
    eng.destroy()


def _rand_lora(seed: int):
    lora = init_lora_params(LORA_CFG, jax.random.PRNGKey(seed))
    # B initialises to zero (delta = 0); perturb so the delta is nonzero
    leaves, td = jax.tree.flatten(lora)
    rng = np.random.RandomState(seed)
    leaves = [
        np.asarray(l) + rng.randn(*np.shape(l)).astype(np.float32) * 0.05
        for l in leaves
    ]
    return jax.tree.unflatten(td, leaves)


def test_lora_delta_push_wire_bytes_and_numerics(lora_served):
    eng, base, client = lora_served
    scale = LORA_CFG.lora_alpha / LORA_CFG.lora_rank
    full_bytes = sum(a.nbytes for a in flatten_named(base).values())

    lora = _rand_lora(11)
    client.update_weights_from_tensor(
        flatten_named({"lora": lora}), version=3, lora_scale=scale
    )
    m = client.get_metrics()
    lora_bytes = sum(
        np.asarray(l).nbytes for l in jax.tree.leaves(lora)
    )
    # only trainable-subtree bytes went over the wire (+ manifest framing)
    assert m["last_push_bytes"] < full_bytes / 4
    assert m["last_push_bytes"] < lora_bytes * 2
    expected = merge_lora({**base, "lora": lora}, LORA_CFG)
    for sub, leaf in (("attn", "q_kernel"), ("attn", "v_kernel")):
        np.testing.assert_allclose(
            np.asarray(eng.params["layers"][sub][leaf]),
            np.asarray(expected["layers"][sub][leaf]),
            rtol=1e-5,
            atol=1e-6,
        )
    # untouched leaves stay bit-identical
    np.testing.assert_array_equal(
        np.asarray(eng.params["final_norm"]), np.asarray(base["final_norm"])
    )
    assert eng.get_version() == 3

    # second delta folds onto the PRISTINE base, not the previous merge
    lora2 = _rand_lora(12)
    client.update_weights_from_tensor(
        flatten_named({"lora": lora2}), version=4, lora_scale=scale
    )
    expected2 = merge_lora({**base, "lora": lora2}, LORA_CFG)
    np.testing.assert_allclose(
        np.asarray(eng.params["layers"]["attn"]["q_kernel"]),
        np.asarray(expected2["layers"]["attn"]["q_kernel"]),
        rtol=1e-5,
        atol=1e-6,
    )


def test_lora_delta_requires_scale(lora_served):
    eng, base, client = lora_served
    with pytest.raises(HttpRequestError, match="lora_scale"):
        client.update_weights_from_tensor(
            flatten_named({"lora": _rand_lora(13)}), version=5
        )


# -- trainer-side: update_weights_async + delta push ---------------------


def _train_engine(use_lora: bool):
    from areal_tpu.api.alloc_mode import ParallelStrategy
    from areal_tpu.api.cli_args import (
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.api.io_struct import FinetuneSpec
    from areal_tpu.engine.sft.lm_engine import JaxLMEngine
    from areal_tpu.models.qwen2 import ModelConfig

    cfg = TrainEngineConfig(
        experiment_name="ws",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=64),
        optimizer=OptimizerConfig(
            lr=5e-2,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=False,
        use_lora=use_lora,
        lora_rank=4,
        lora_alpha=8,
        target_modules=["q_proj", "v_proj"],
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = ModelConfig(
        vocab_size=64,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        dtype="float32",
        param_dtype="float32",
        lora_rank=4 if use_lora else 0,
        lora_alpha=8.0,
        lora_targets=("q_proj", "v_proj"),
    )
    eng.create_process_group(
        ParallelStrategy(data_parallel_size=2, tensor_parallel_size=2,
                         context_parallel_size=2)
    )
    eng.initialize(None, FinetuneSpec(1, 100, 4))
    return eng


def _train_batch(vocab=64, seed=0):
    from areal_tpu.utils.data import pad_sequences_to_tensors

    rng = np.random.RandomState(seed)
    seqs = []
    for L in (11, 9, 13, 7):
        ids = rng.randint(1, vocab, (L,))
        mask = np.zeros(L, dtype=np.int32)
        mask[1:] = 1
        seqs.append(dict(input_ids=ids, loss_mask=mask))
    return pad_sequences_to_tensors(seqs)


def _serve_for_trainer(base_params):
    cfg = JaxDecodeConfig(
        context_length=96,
        max_running_requests=4,
        new_tokens_per_chunk=4,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(base_params, TINY)
    eng.initialize()
    st = _ServerThread(eng)
    client = RemoteInfEngine(
        InferenceEngineConfig(setup_timeout=30, request_timeout=60)
    )
    client.initialize(addr=st.addr)
    return eng, st, client


def test_trainer_update_weights_async_overlaps_training(cpu_devices):
    from areal_tpu.api.io_struct import WeightUpdateMeta

    trainer = _train_engine(use_lora=False)
    dec, st, client = _serve_for_trainer(
        init_params(TINY, jax.random.PRNGKey(0))
    )
    try:
        trainer.connect_engine(client, WeightUpdateMeta(type="dcn"))
        trainer.set_version(5)
        handle = trainer.update_weights_async()
        # the learner trains its next batch while buckets drain
        stats = trainer.train_lm(_train_batch())
        assert np.isfinite(stats["loss"])
        handle.commit()
        assert handle.committed
        handle.commit()  # idempotent
        assert dec.get_version() == 5
        assert client.get_metrics()["n_pushes"] == 1
        # the pushed snapshot predates the concurrent train step (bf16 wire)
        np.testing.assert_allclose(
            np.asarray(dec.params["final_norm"], np.float32),
            np.asarray(
                jax.numpy.asarray(trainer.params["final_norm"]).astype(
                    jax.numpy.bfloat16
                ),
                np.float32,
            ),
            rtol=2e-2,
            atol=2e-2,
        )
    finally:
        client.destroy()
        st.stop()
        dec.destroy()
        trainer.destroy()


def test_trainer_lora_push_is_delta_only(cpu_devices):
    """With LoRA active the dcn push ships ONLY the adapter subtree —
    asserted on wire-byte metrics — and the server folds the delta."""
    from areal_tpu.api.io_struct import WeightUpdateMeta

    trainer = _train_engine(use_lora=True)
    base_host = jax.tree.map(
        lambda x: np.asarray(x),
        {k: v for k, v in trainer.params.items() if k != "lora"},
    )
    dec, st, client = _serve_for_trainer(base_host)
    try:
        trainer.connect_engine(client, WeightUpdateMeta(type="dcn"))
        # make adapters nonzero so the delta actually changes kernels
        for _ in range(2):
            trainer.train_lm(_train_batch())
        q_before = np.asarray(dec.params["layers"]["attn"]["q_kernel"]).copy()
        trainer.set_version(2)
        trainer.update_weights(WeightUpdateMeta(type="dcn"))
        m = client.get_metrics()
        full_bytes = sum(a.nbytes for a in flatten_named(base_host).values())
        lora_bytes = sum(
            np.asarray(l).nbytes
            for l in jax.tree.leaves(trainer.params["lora"])
        )
        assert m["last_push_bytes"] < full_bytes / 4
        assert m["last_push_bytes"] < lora_bytes * 2  # bf16 wire halves it
        assert dec.get_version() == 2
        # targeted kernels moved, untouched leaves stayed bit-identical
        assert (
            np.abs(
                np.asarray(dec.params["layers"]["attn"]["q_kernel"])
                - q_before
            ).max()
            > 0
        )
        np.testing.assert_array_equal(
            np.asarray(dec.params["final_norm"]),
            np.asarray(base_host["final_norm"]),
        )
    finally:
        client.destroy()
        st.stop()
        dec.destroy()
        trainer.destroy()
