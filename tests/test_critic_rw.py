"""PPO critic + reward-model engines (parity: areal/engine/ppo/critic.py,
areal/engine/rw/rw_engine.py)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    MicroBatchSpec,
    OptimizerConfig,
    PPOCriticConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.engine.ppo.critic import JaxPPOCritic
from areal_tpu.engine.rw.rw_engine import JaxRWEngine
from areal_tpu.models.qwen2 import ModelConfig

TINY_CRITIC = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
    is_critic=True,
)


def _cfg(cls=TrainEngineConfig, **kw):
    return cls(
        experiment_name="t",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=256),
        optimizer=OptimizerConfig(
            lr=5e-3,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=False,
        **kw,
    )


def _padded_batch(B=4, T=16, seed=0):
    rng = np.random.RandomState(seed)
    lens = rng.randint(T // 2, T + 1, B)
    input_ids = np.zeros((B, T), dtype=np.int64)
    attention_mask = np.zeros((B, T), dtype=np.int64)
    for i, l in enumerate(lens):
        input_ids[i, :l] = rng.randint(1, 64, l)
        attention_mask[i, :l] = 1
    return input_ids, attention_mask, lens


@pytest.fixture(scope="module")
def critic(cpu_devices):
    eng = JaxPPOCritic(_cfg(PPOCriticConfig, ppo_n_minibatches=2, eps_clip=0.5))
    eng.model_config = TINY_CRITIC
    eng.create_process_group(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    eng.initialize(None, FinetuneSpec(1, 64, 4))
    yield eng
    eng.destroy()


def test_critic_values_shape_and_update(critic):
    input_ids, attention_mask, lens = _padded_batch()
    B, T = input_ids.shape
    data = dict(input_ids=input_ids, attention_mask=attention_mask)
    values = critic.compute_values(data)
    assert values.shape == (B, T)
    # padding positions untouched (zeros)
    for i, l in enumerate(lens):
        assert np.all(values[i, l:] == 0)

    # regress toward constant target returns; loss must drop
    loss_mask = attention_mask.astype(np.float32)
    returns = np.where(loss_mask > 0, 1.5, 0.0).astype(np.float32)
    losses = []
    for _ in range(8):
        vals = critic.compute_values(dict(data))
        batch = dict(
            input_ids=input_ids,
            attention_mask=attention_mask,
            loss_mask=loss_mask,
            values=vals,
            returns=returns,
        )
        stats = critic.ppo_update(batch)
        losses.append(np.mean([s["critic_loss"] for s in stats]))
    assert losses[-1] < losses[0] * 0.7, losses

    # values should now be near the target on real tokens
    vals = critic.compute_values(dict(data))
    err = np.abs(vals - 1.5)[loss_mask > 0].mean()
    assert err < 0.6, err


@pytest.fixture(scope="module")
def rw(cpu_devices):
    eng = JaxRWEngine(_cfg())
    eng.model_config = TINY_CRITIC
    eng.create_process_group(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    eng.initialize(None, FinetuneSpec(1, 64, 4))
    yield eng
    eng.destroy()


def _pair_batch(N=4, T=12, seed=1):
    """Chosen rows end in token 7, rejected rows end in token 3."""
    rng = np.random.RandomState(seed)
    B = 2 * N
    input_ids = np.zeros((B, T), dtype=np.int64)
    attention_mask = np.zeros((B, T), dtype=np.int64)
    for i in range(B):
        l = rng.randint(T // 2, T + 1)
        input_ids[i, :l] = rng.randint(1, 64, l)
        input_ids[i, l - 1] = 7 if i % 2 == 0 else 3
        attention_mask[i, :l] = 1
    return dict(input_ids=input_ids, attention_mask=attention_mask)


def test_rw_pairwise_training(rw):
    first = last = None
    for step in range(30):
        stat = rw.train_rw(_pair_batch(seed=step % 10))
        if first is None:
            first = stat["loss"]
        last = stat["loss"]
    assert last < first, (first, last)
    assert last < 0.6, last  # learned to separate (ln2 ≈ 0.69 at chance)

    scores = rw.compute_scores(_pair_batch(seed=99))
    chosen, rejected = scores[0::2], scores[1::2]
    assert (chosen > rejected).mean() >= 0.75, scores
