"""Capacity math parity with areal/tests/test_staleness_manager.py."""

from areal_tpu.core.staleness_manager import StalenessManager


def test_concurrency_cap():
    m = StalenessManager(max_concurrent_rollouts=4, consumer_batch_size=100,
                         max_staleness=100)
    assert m.get_capacity(0) == 4
    for _ in range(4):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0


def test_staleness_cap_version0():
    # on-policy: (0 + 0 + 1) * bs samples admissible at version 0
    m = StalenessManager(max_concurrent_rollouts=1000, consumer_batch_size=8,
                         max_staleness=0)
    assert m.get_capacity(0) == 8
    for _ in range(8):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    # version bump releases another batch
    assert m.get_capacity(1) == 8


def test_accepted_counts_against_staleness():
    m = StalenessManager(max_concurrent_rollouts=1000, consumer_batch_size=4,
                         max_staleness=1)
    for _ in range(8):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    for _ in range(4):
        m.on_rollout_accepted()
    # accepted + running unchanged in total
    assert m.get_capacity(0) == 0


def test_rejected_frees_capacity():
    m = StalenessManager(max_concurrent_rollouts=1000, consumer_batch_size=4,
                         max_staleness=0)
    for _ in range(4):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    m.on_rollout_rejected()
    assert m.get_capacity(0) == 1


def test_stats_snapshot():
    m = StalenessManager(4, 4, 0)
    m.on_rollout_submitted()
    m.on_rollout_submitted()
    m.on_rollout_accepted()
    st = m.get_stats()
    assert (st.submitted, st.accepted, st.running) == (2, 1, 1)


# -- checkpointing (ISSUE 14) ------------------------------------------------


def test_state_dict_roundtrip():
    m = StalenessManager(16, 4, 2)
    for _ in range(6):
        m.on_rollout_submitted()
    for _ in range(3):
        m.on_rollout_accepted()
    st = m.state_dict()
    assert st == dict(submitted=6, accepted=3, running=3)
    m2 = StalenessManager(16, 4, 2)
    m2.load_state_dict(st)
    assert m2.state_dict() == st
    for v in range(4):
        assert m2.get_capacity(v) == m.get_capacity(v)


def test_restored_capacity_arithmetic():
    """After a trainer restart the executor restores accepted := ledger
    consumed count and running := 0; the staleness cap must continue the
    boba² formula from exactly those counters."""
    m = StalenessManager(
        max_concurrent_rollouts=100, consumer_batch_size=4, max_staleness=1
    )
    # two batches trained+committed before the crash, nothing in flight
    m.load_state_dict(dict(submitted=8, accepted=8, running=0))
    # version 2: (1 + 2 + 1) * 4 - (8 + 0) = 8 admissible
    assert m.get_capacity(2) == 8
    # version 1: (1 + 1 + 1) * 4 - 8 = 4
    assert m.get_capacity(1) == 4
    # running slots count against both caps again after restore
    for _ in range(4):
        m.on_rollout_submitted()
    assert m.get_capacity(1) == 0
