"""Capacity math parity with areal/tests/test_staleness_manager.py."""

from areal_tpu.core.staleness_manager import StalenessManager


def test_concurrency_cap():
    m = StalenessManager(max_concurrent_rollouts=4, consumer_batch_size=100,
                         max_staleness=100)
    assert m.get_capacity(0) == 4
    for _ in range(4):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0


def test_staleness_cap_version0():
    # on-policy: (0 + 0 + 1) * bs samples admissible at version 0
    m = StalenessManager(max_concurrent_rollouts=1000, consumer_batch_size=8,
                         max_staleness=0)
    assert m.get_capacity(0) == 8
    for _ in range(8):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    # version bump releases another batch
    assert m.get_capacity(1) == 8


def test_accepted_counts_against_staleness():
    m = StalenessManager(max_concurrent_rollouts=1000, consumer_batch_size=4,
                         max_staleness=1)
    for _ in range(8):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    for _ in range(4):
        m.on_rollout_accepted()
    # accepted + running unchanged in total
    assert m.get_capacity(0) == 0


def test_rejected_frees_capacity():
    m = StalenessManager(max_concurrent_rollouts=1000, consumer_batch_size=4,
                         max_staleness=0)
    for _ in range(4):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    m.on_rollout_rejected()
    assert m.get_capacity(0) == 1


def test_stats_snapshot():
    m = StalenessManager(4, 4, 0)
    m.on_rollout_submitted()
    m.on_rollout_submitted()
    m.on_rollout_accepted()
    st = m.get_stats()
    assert (st.submitted, st.accepted, st.running) == (2, 1, 1)
