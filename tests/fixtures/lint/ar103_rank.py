"""Seeded-bad fixture: AR103 — acquisition against declared OrderedLock
ranks. `bad` takes the rank-20 lock then the rank-10 lock (the only
nesting in the file, so no AR102 cycle — this isolates the rank rule)."""

from areal_tpu.utils.lock import OrderedLock


class Ranked:
    def __init__(self):
        self._low = OrderedLock("ranked._low", rank=10)
        self._high = OrderedLock("ranked._high", rank=20)

    def uses_low(self):
        with self._low:
            pass

    def bad(self):
        with self._high:
            with self._low:  # AR103: rank 20 held while taking rank 10
                pass
