"""Seeded-bad fixture: AR106 — broad except that swallows silently.

Four swallow shapes (bare except, `except Exception: pass`, a handler
whose body does unrelated work, a tuple catch containing Exception) and
the four escapes that must NOT fire: re-raise, a logging call, preserving
the exception object, and a NARROW catch.
"""

import logging

logger = logging.getLogger(__name__)


def swallow_pass(x):
    try:
        return 1 / x
    except Exception:  # AR106: silent
        pass


def swallow_bare(x):
    try:
        return int(x)
    except:  # noqa: E722 — AR106: bare and silent
        return 0


def swallow_busy(items):
    out = []
    try:
        out.append(items[0])
    except Exception:  # AR106: does work, but the failure vanishes
        out.clear()
    return out


def swallow_tuple(x):
    try:
        return float(x)
    except (ValueError, Exception):  # AR106: tuple containing Exception
        return 0.0


def ok_reraise(x):
    try:
        return 1 / x
    except Exception as e:
        raise RuntimeError("wrapped") from e


def ok_logged(x):
    try:
        return 1 / x
    except Exception as e:
        logger.warning(f"divide failed: {e!r}")
        return 0


def ok_preserved(x):
    last_exc = None
    try:
        return 1 / x
    except Exception as e:
        last_exc = e
    return last_exc


def ok_narrow(x):
    try:
        return int(x)
    except ValueError:  # narrow: the caller chose what to absorb
        return 0
