"""Seeded-bad fixture: AR101 — multi-context write without a guard.

`_counter` is bumped by the worker thread and reset from the public
(main-thread) API with no lock in common and no guarded-by declaration.
`_safe_q` must NOT fire (thread-safe type); `_locked_total` must NOT fire
(every write site holds the same lock — implicit guard); `_fenced` must NOT
fire (declared via the module registry).
"""

import queue
import threading

_GUARDED_BY = {
    "Worker._fenced": "_lock",
}


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._counter = 0
        self._locked_total = 0
        self._fenced = 0
        self._safe_q = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            self._counter += 1  # thread context write
            with self._lock:
                self._locked_total += 1
            self._fenced += 1
            self._safe_q.put(1)

    def reset(self):
        self._counter = 0  # main context write, unguarded -> AR101
        with self._lock:
            self._locked_total = 0
        self._fenced = 0
        self._safe_q.put(0)
