"""Seeded-bad fixture: AR102 — lock acquisition-order cycle.

`step_ab` acquires A then B; `step_ba` acquires B then A. Two threads
running one each deadlock; the analyzer must report the A<->B cycle.
The interprocedural edge (C held -> helper acquires A) must not create a
false cycle on its own.
"""

import threading


class Pipeline:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()

    def step_ab(self):
        with self._a:
            with self._b:
                pass

    def step_ba(self):
        with self._b:
            with self._a:  # AR102: closes the cycle
                pass

    def _helper(self):
        with self._a:
            pass

    def step_c(self):
        with self._c:
            self._helper()  # edge c -> a (no cycle by itself)
