"""Seeded-bad fixture: AR201 — implicit host syncs inside a step loop.

Three hazard forms on device arrays inside the loop (.item(), float(),
np.asarray()); the pre-loop conversions and the host-array float() must
not fire.
"""

import jax.numpy as jnp
import numpy as np


def decode_loop(n):
    logits = jnp.ones((8,))
    host_before = np.asarray(logits)  # outside the loop: fine
    total = 0.0
    for _ in range(n):
        x = jnp.sum(logits)
        total += x.item()  # AR201: per-iteration sync
        total += float(x)  # AR201
        host = np.asarray(x)  # AR201
        total += float(host_before[0])  # host array: fine
    return total, host
