"""Seeded-bad fixture: AR301 — route pairing across server and client.

Self-contained: registrations and client references live in one module so
a standalone run can judge pairing (the analyzer skips AR301 entirely when
a sweep harvests no registrations)."""

GENERATE_ENDPOINT = "/paired"  # client ref via *_ENDPOINT constant


async def handle_paired(request):
    return None


async def handle_dead(request):
    return None


async def handle_ops(request):
    return None


def build_app(app):
    app.router.add_get("/paired", handle_paired)  # paired below: clean
    app.router.add_post("/dead_route", handle_dead)  # AR301: no client
    # wire: external
    app.router.add_get("/ops_surface", handle_ops)  # annotated: clean


async def poll(arequest_with_retry, addr, block):
    await arequest_with_retry(addr, "/paired", method="GET")
    # AR301: nothing registers /missing
    await arequest_with_retry(addr, "/missing", method="POST")
    # f-string with query string still pairs with /paired: clean
    return await arequest_with_retry(addr, f"/paired?block={block}")
