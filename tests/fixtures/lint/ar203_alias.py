"""Seeded-bad fixture: AR203 — jnp.asarray zero-copy alias of a host
mirror that is mutated in place afterwards (the PR 3 run-ahead bug class).

`upload_then_mutate` reproduces the exact local pattern; `Engine` the
cross-method self-attribute pattern (upload in dispatch, mutation in
retire). `safe_copy` uploads through an explicit np.array copy and must
not fire.
"""

import jax.numpy as jnp
import numpy as np


def upload_then_mutate(active, n_chunk):
    lengths = np.zeros(8, dtype=np.int32)
    dev_lengths = jnp.asarray(lengths)  # AR203: aliases the host buffer
    lengths[active] += n_chunk  # ... which this then mutates
    return dev_lengths


def safe_copy(active, n_chunk):
    lengths = np.zeros(8, dtype=np.int32)
    dev_lengths = jnp.asarray(np.array(lengths))  # explicit copy: fine
    lengths[active] += n_chunk
    return dev_lengths


class Engine:
    def __init__(self):
        self._slot_lengths = np.zeros(8, dtype=np.int32)

    def dispatch(self):
        return jnp.asarray(self._slot_lengths)  # AR203 (cross-method)

    def retire(self, slot):
        self._slot_lengths[slot] = 0
