"""Seeded-bad fixture: AR104 — guard declarations naming unknown locks."""

import threading

_GUARDED_BY = {
    "Annotated._registry_attr": "_phantom_lock",  # AR104: no such lock
    "NoSuchClass._x": "_lock",  # AR104: no such class
}


class Annotated:
    def __init__(self):
        self._lock = threading.Lock()
        self._ok = 0  # guarded-by: _lock
        self._bad = 0  # guarded-by: _ghost_lock  (AR104: undeclared)
        self._registry_attr = 0
