"""Seeded-bad fixture: AR302 — fault-seam validity.

Seams and plan patterns live in one module so a standalone run can judge
matching (pattern checks are skipped when a sweep harvests no seams)."""

from areal_tpu.core import fault_injection
from areal_tpu.core.fault_injection import FaultPoint


def transfer(payload):
    fault_injection.fire("kv.send", payload=payload)
    return payload


async def receive(payload):
    await fault_injection.afire("kv.recv", payload=payload)
    return payload


PLAN = [
    FaultPoint(site="kv.*"),  # matches both seams: clean
    FaultPoint(site="kv.sendd"),  # AR302: typo'd pattern, never fires
]

EMBEDDED = {"site": "weight.push.*"}  # AR302: no such seam anywhere
