"""Seeded-bad fixture: AR202 — reading a buffer after donating it.

`bad` reads `state` after it was donated; `good` rebinds the name to the
returned array (the standard donation pattern) and must not fire.
"""

import jax
import jax.numpy as jnp


def _step(state, x):
    return state + x


step = jax.jit(_step, donate_argnums=(0,))


def bad():
    state = jnp.zeros((4,))
    new_state = step(state, jnp.ones((4,)))
    return state + new_state  # AR202: `state` was donated


def good():
    state = jnp.zeros((4,))
    state = step(state, jnp.ones((4,)))
    return state
