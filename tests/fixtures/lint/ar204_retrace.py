"""Seeded-bad fixture: AR204 — retrace hazards at jit call sites.

`bad_loop` feeds the loop counter straight into a jit function (retrace
per iteration); `bad_static` passes an unhashable literal at a static
position. `good_loop` wraps the varying value in jnp.asarray (traced
array argument — single compile) and must not fire.
"""

import jax
import jax.numpy as jnp


def _f(x, k):
    return x * k


step = jax.jit(_f)
bucketed = jax.jit(_f, static_argnums=(1,))


def bad_loop(x):
    for i in range(16):
        x = step(x, i)  # AR204: i re-specializes every iteration
    return x


def good_loop(x):
    for i in range(16):
        x = step(x, jnp.asarray(i))  # traced argument: fine
    return x


def bad_static(x):
    return bucketed(x, [1, 2, 3])  # AR204: unhashable static arg
