"""Fixture: every violation here is suppressed by a pragma — the analyzer
must report nothing. Exercises same-line, preceding-comment-line, and
file-level pragma forms."""

# areal-lint: disable-file=AR202

import threading

import jax
import jax.numpy as jnp
import numpy as np


class Suppressed:
    def __init__(self):
        self._counter = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        self._counter += 1

    def reset(self):
        self._counter = 0  # areal-lint: disable=AR101


def loop(n):
    x = jnp.ones(())
    total = 0.0
    for _ in range(n):
        # areal-lint: disable=AR201
        total += float(x)
    return total


_step = jax.jit(lambda s, v: s + v, donate_argnums=(0,))


def donated():
    s = jnp.zeros((2,))
    out = _step(s, jnp.ones((2,)))
    return s, out  # AR202 suppressed file-wide


def alias():
    h = np.zeros(4)
    d = jnp.asarray(h)  # areal-lint: disable=AR203
    h[0] = 1
    return d


def wire(app, arequest_with_retry):
    app.router.add_get("/pragma_dead", alias)  # areal-lint: disable=AR301
    # areal-lint: disable=AR301
    return arequest_with_retry("addr", "/pragma_missing")
