"""Seeded-bad fixture: AR304 — stale _GUARDED_BY registry entry.

Both entries name a real lock of a real class (so AR104 stays quiet);
one names an attribute a refactor removed."""

import threading

_GUARDED_BY = {
    "Tracker._inflight": "_lock",  # attr exists: clean
    "Tracker._retired_attr": "_lock",  # AR304: attr refactored away
}


class Tracker:
    def __init__(self):
        self._lock = threading.Lock()
        self._inflight = 0

    def bump(self):
        with self._lock:
            self._inflight += 1
