"""Seeded-bad fixture: AR305 — config-knob drift (argparse + /info)."""

import argparse
from dataclasses import dataclass


@dataclass
class ServeConfig:
    max_tokens: int = 512
    tensor_parallel_size: int = 1
    tick_interval_s: float = 1.0


class Server:
    def __init__(self, config):
        self.config = config

    async def _info(self, request):
        return {
            "max_tokens": self.config.max_tokens,  # real field: clean
            "legacy_knob": self.config.legacy_knob,  # AR305: no such field
        }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--max-tokens", type=int, default=512)  # mirrors: clean
    p.add_argument("--tp-size", type=int, default=1)  # AR305: dest drift
    p.add_argument(  # explicit dest repairs the mirror: clean
        "--tick-interval", dest="tick_interval_s", type=float, default=1.0
    )
    # knob: launcher-only
    p.add_argument("--server-id", default="")
    p.add_argument("--host", default="0.0.0.0")  # infra dest: clean
    return p
