"""Seeded-bad fixture: AR303 — metrics contract drift.

Producers (get_metrics, stats initializers) and consumers (*_KEYS tuples,
annotated readers) live in one module so a standalone run can judge the
pairing (cross-file checks are skipped when no producer keys exist)."""


class Server:
    def __init__(self):
        self._req_stats = {"completed": 0, "rejected": 0}

    def finish(self):
        self._req_stats["completed"] += 1  # declared in initializer: clean

    def reject(self):
        self._req_stats["rejectd"] += 1  # AR303: key not in initializer

    def get_metrics(self):
        return {
            "active_tokens": 0,
            "queue_depth": 0,
            **self._req_stats,
        }


POLL_KEYS = ("active_tokens", "kv_occupancy")  # AR303: kv_occupancy unproduced


# metrics-consumer
def autoscale(snapshot):
    depth = snapshot.get("queue_depth")  # produced: clean
    stale = snapshot.get("prefill_lag")  # AR303: no producer exports it
    return depth, stale
