"""Golden-loss SFT regression gate.

Parity: the reference's SFT integration test asserts per-step losses match
a stored `ref_losses.json` (areal/tests/sft/, SURVEY.md §4) — the guard
against silent numerical regressions in the train path. Golden values were
produced by this exact scenario (fixed seeds, dp4·tp2 mesh on the 8-CPU
devices) at the commit that introduced this test; a legitimate numerical
change (e.g. a different reduction order) must regenerate them
consciously, not silently.

Goldens regenerated 2026-08 for the current container: the original values
came from a different jax/XLA build whose CPU reduction orders differ
(~5% loss drift at this toy scale). The train path itself was cleared
first — the repo's seed commit and HEAD produce bit-identical losses in
this container, so the drift is environmental, not a code regression.
"""

import json
import os

import numpy as np

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    MicroBatchSpec,
    OptimizerConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.engine.sft.lm_engine import JaxLMEngine
from areal_tpu.models.qwen2 import ModelConfig
from areal_tpu.utils.data import pad_sequences_to_tensors

GOLDEN = os.path.join(os.path.dirname(__file__), "golden_sft_losses.json")


def test_sft_losses_match_golden(cpu_devices):
    cfg = TrainEngineConfig(
        experiment_name="golden",
        trial_name="t",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=128),
        optimizer=OptimizerConfig(
            lr=1e-2,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=False,
    )
    eng = JaxLMEngine(cfg)
    eng.model_config = ModelConfig(
        vocab_size=64,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        dtype="float32",
        param_dtype="float32",
    )
    eng.create_process_group(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    eng.initialize(None, FinetuneSpec(1, 64, 8))
    rng = np.random.RandomState(7)
    losses = []
    for _ in range(6):
        seqs = []
        for L in (9, 13, 7, 11):
            ids = rng.randint(1, 64, (L,))
            mask = np.zeros(L, dtype=np.int32)
            mask[L // 2 :] = 1
            seqs.append(dict(input_ids=ids, loss_mask=mask))
        losses.append(
            float(eng.train_lm(pad_sequences_to_tensors(seqs))["loss"])
        )
    eng.destroy()
    with open(GOLDEN) as f:
        golden = json.load(f)
    np.testing.assert_allclose(losses, golden, rtol=1e-4, atol=1e-5)
