"""Code-benchmark offline eval: HumanEval/MBPP fixture loaders, the
assert-harness sandbox mode, and pass@k through evaluate_offline with
code_eval_reward_fn (the pipeline behind the reference's code numbers,
functioncall/code/verify.py + eval_and_aggregate)."""

import json

import pytest

from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.evaluation import evaluate_offline
from areal_tpu.reward.code_verify import code_eval_reward_fn, run_problem
from tests.test_workflows import ScriptedEngine

FIXTURE = [
    {
        "task_id": "Fix/0",
        "prompt": "def add(a, b):\n    \"\"\"Return a + b.\"\"\"\n",
        "entry_point": "add",
        "test": (
            "def check(candidate):\n"
            "    assert candidate(1, 2) == 3\n"
            "    assert candidate(-1, 1) == 0\n"
        ),
    },
    {
        "task_id": "Fix/1",
        "prompt": "def double(x):\n    \"\"\"Return 2*x.\"\"\"\n",
        "entry_point": "double",
        "test": (
            "def check(candidate):\n"
            "    assert candidate(3) == 6\n"
            "    assert candidate(0) == 0\n"
        ),
    },
    {
        "task_id": "Fix/2",
        "prompt": "def neg(x):\n    \"\"\"Return -x.\"\"\"\n",
        "entry_point": "neg",
        "test": "def check(candidate):\n    assert candidate(5) == -5\n",
    },
]


@pytest.fixture()
def fixture_path(tmp_path):
    p = tmp_path / "humaneval_fixture.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in FIXTURE))
    return str(p)


def test_assert_harness_mode():
    ok = run_problem(
        "def f(x):\n    return x + 1\n",
        {"asserts": ["assert f(1) == 2", "assert f(0) == 1"]},
    )
    assert ok is True
    bad = run_problem(
        "def f(x):\n    return x\n", {"asserts": ["assert f(1) == 2"]}
    )
    assert bad is False
    # harness exceptions (not just AssertionError) also fail the case
    assert run_problem("x = 1\n", {"asserts": ["undefined_name"]}) is False


def test_humaneval_loader_fixture(fixture_path):
    from areal_tpu.dataset import _REGISTRY

    items = _REGISTRY["humaneval"](
        path=fixture_path, split="test", type="rl", tokenizer=None
    )
    assert len(items) == 3
    assert items[0]["code_prompt"].startswith("def add")
    assert "check(add)" in items[0]["input_output"]["asserts"][0]
    assert "```python" in items[0]["messages"][0]["content"]


def test_mbpp_loader_fixture(tmp_path):
    rows = [
        {
            "task_id": 1,
            "text": "Write a function add(a, b) returning a+b.",
            "code": "def add(a, b):\n    return a + b\n",
            "test_list": ["assert add(1, 2) == 3"],
            "test_setup_code": "",
        }
    ]
    p = tmp_path / "mbpp_fixture.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    from areal_tpu.dataset import _REGISTRY

    items = _REGISTRY["mbpp"](
        path=str(p), split="test", type="rl", tokenizer=None
    )
    assert items[0]["input_output"]["asserts"] == ["assert add(1, 2) == 3"]


def test_code_eval_reward_continuation_and_block():
    item = {
        "code_prompt": FIXTURE[0]["prompt"],
        "input_output": {
            "asserts": [FIXTURE[0]["test"] + "\ncheck(add)\n"]
        },
    }
    # continuation style (no code fence): prompt + completion is the program
    r = code_eval_reward_fn(
        None, "    return a + b\n", [], [], **item
    )
    assert r == 1.0
    # fenced style: the block replaces the continuation assembly
    r2 = code_eval_reward_fn(
        None,
        "Here you go:\n```python\ndef add(a, b):\n    return a + b\n```",
        [],
        [],
        **item,
    )
    assert r2 == 1.0
    assert code_eval_reward_fn(None, "    return a - b\n", [], [], **item) == 0.0


class CodeTokenizer:
    """Token id 1 decodes to a correct continuation, 2 to a wrong one."""

    def decode(self, ids):
        return "    return a + b\n" if ids == [1] else "    return a * 9\n"

    def encode(self, text):
        return [7, 8]

    def apply_chat_template(self, messages, **kw):
        return [7, 8]


def test_evaluate_offline_code_pass_at_k(fixture_path):
    from areal_tpu.dataset import _REGISTRY

    items = _REGISTRY["humaneval"](
        path=fixture_path, split="test", type="rl", tokenizer=None
    )
    # 3 problems x 2 samples; every problem's add-style continuation
    # "return a + b" is correct ONLY for problem 0, so script per-problem:
    # p0 -> [correct, wrong], p1/p2 -> [wrong, wrong]
    eng = ScriptedEngine([[1], [2], [2], [2], [2], [2]])
    res = evaluate_offline(
        eng,
        items,
        reward_fn=code_eval_reward_fn,
        gconfig=GenerationHyperparameters(max_new_tokens=8),
        tokenizer=CodeTokenizer(),
        n_samples=2,
        ks=(1, 2),
        max_concurrency=1,  # keep the scripted order deterministic
    )
    assert res.n_problems == 3 and res.n_samples == 2
    # p0: 1 of 2 correct -> pass@1 contribution 0.5; p1, p2: 0
    assert abs(res.pass_at_1 - 0.5 / 3) < 1e-9
    assert abs(res.pass_at_k[2] - 1.0 / 3) < 1e-9
