"""TIR workflow: fence parsing, sandboxed tool, and the generate ⇄ execute
loop against a scripted engine (ref: examples/tir/tir_workflow.py)."""

import asyncio

import numpy as np

from areal_tpu.api.cli_args import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelResponse
from areal_tpu.workflow.tir import TIRWorkflow, run_python_tool


class _CharTok:
    """Character tokenizer over raw codepoints (deterministic round-trip)."""

    eos_token_id = 0
    pad_token_id = 0

    def encode(self, text, **kw):
        return [ord(c) % 1000 + 1 for c in text]

    def decode(self, ids, **kw):
        return "".join(chr((i - 1) % 1000) for i in np.asarray(ids).reshape(-1))

    def apply_chat_template(self, messages, **kw):
        return self.encode("\n".join(m["content"] for m in messages))


class _ScriptedEngine:
    """Returns pre-scripted generations; records the prompts it saw."""

    def __init__(self, tok, outputs):
        self.tok = tok
        self.outputs = list(outputs)
        self.seen_prompts = []

    async def agenerate(self, req):
        self.seen_prompts.append(self.tok.decode(req.input_ids))
        text, stop_reason = self.outputs.pop(0)
        ids = self.tok.encode(text)
        if len(ids) > req.gconfig.max_new_tokens:  # engines honor the cap
            ids = ids[: req.gconfig.max_new_tokens]
            stop_reason = "length"
        return ModelResponse(
            input_tokens=list(req.input_ids),
            output_tokens=ids,
            output_logprobs=[-0.5] * len(ids),
            output_versions=[0] * len(ids),
            stop_reason=stop_reason,
        )


def test_run_python_tool_sandbox():
    assert run_python_tool("print(6*7)") == "42\n"
    out = run_python_tool("import time; time.sleep(60)", timeout_seconds=1.0)
    assert "TimeoutError" in out
    out = run_python_tool("print('x' * 10000)", max_output_chars=100)
    assert out.endswith("...(truncated)\n")
    assert "NameError" in run_python_tool("nope()")


def test_tool_output_budgeted_against_max_new_tokens():
    tok = _CharTok()
    eng = _ScriptedEngine(
        tok,
        [("x ```python\n", "stop"), ("print(1)\n```\n", "stop"),
         ("done", "length")],
    )
    wf = TIRWorkflow(
        reward_fn=lambda p, c, pi, ci, **kw: 0.0,
        gconfig=GenerationHyperparameters(n_samples=1, max_new_tokens=40),
        tokenizer=tok,
        tool_fn=lambda code: "x" * 500,  # huge tool output
    )
    traj = asyncio.run(wf.arun_episode(eng, dict(prompt="q")))
    total_new = int(np.asarray(traj["attention_mask"]).sum()) - len("q")
    # generated + spliced tool tokens never exceed the new-token budget
    assert total_new <= 40


def test_tool_loop_executes_code_and_masks_output():
    tok = _CharTok()
    # round 1: model writes a code block and halts on the closing fence;
    # round 2: model answers and hits eos
    eng = _ScriptedEngine(
        tok,
        [
            # phase A: halts on the OPENING fence
            ("I'll compute. ```python\n", "stop"),
            # phase B: code body, halts on the closing fence
            ("print(2+3)\n```\n", "stop"),
            ("So the answer is 5.", "length"),
        ],
    )
    wf = TIRWorkflow(
        reward_fn=lambda p, c, pi, ci, **kw: 1.0 if "5" in c else 0.0,
        gconfig=GenerationHyperparameters(n_samples=1, max_new_tokens=256),
        tokenizer=tok,
    )
    traj = asyncio.run(
        wf.arun_episode(eng, dict(prompt="what is 2+3?"))
    )
    assert float(np.asarray(traj["rewards"]).reshape(-1)[0]) == 1.0
    # the post-execution request's prompt must contain the REAL tool output
    assert "```output\n5\n```" in eng.seen_prompts[2]
    # tool-output tokens are loss-masked; generated tokens are not
    ids = np.asarray(traj["input_ids"]).reshape(-1)
    mask = np.asarray(traj["loss_mask"]).reshape(-1)
    text = tok.decode(ids[: int(np.asarray(traj["attention_mask"]).sum())])
    out_start = text.index("```output")
    out_end = text.index("So the answer")
    assert mask[out_start:out_end].sum() == 0
    assert mask[out_end:].sum() > 0


def test_no_code_block_means_single_round():
    tok = _CharTok()
    eng = _ScriptedEngine(tok, [("just an answer: 7", "stop")])
    wf = TIRWorkflow(
        reward_fn=lambda p, c, pi, ci, **kw: 0.0,
        gconfig=GenerationHyperparameters(n_samples=1, max_new_tokens=64),
        tokenizer=tok,
    )
    asyncio.run(
        wf.arun_episode(eng, dict(prompt="q"))
    )
    assert len(eng.seen_prompts) == 1


def test_tool_call_budget_bounds_rounds_and_executions():
    tok = _CharTok()
    open_f = ("```python\n", "stop")
    close_f = ("print(1)\n```\n", "stop")
    eng = _ScriptedEngine(
        tok, [open_f, close_f] * 3 + [("done", "stop")]
    )
    executed = []
    wf = TIRWorkflow(
        reward_fn=lambda p, c, pi, ci, **kw: 0.0,
        gconfig=GenerationHyperparameters(n_samples=1, max_new_tokens=2048),
        tokenizer=tok,
        max_tool_calls=2,
        tool_fn=lambda code: executed.append(code) or "1\n",
    )
    asyncio.run(wf.arun_episode(eng, dict(prompt="q")))
    # budget of 2 -> exactly 2 sandbox executions; the loop ends when the
    # third block closes with no budget left
    assert len(executed) == 2
    assert len(eng.seen_prompts) == 6


def test_bare_markdown_fence_does_not_end_episode():
    tok = _CharTok()
    # a plain ``` fence in prose is NOT a tool call: phase A only stops on
    # the ```python opener, so the answer generates through to its end
    eng = _ScriptedEngine(
        tok, [("table:\n```\n1 2 3\n```\nanswer is 6", "length")]
    )
    executed = []
    wf = TIRWorkflow(
        reward_fn=lambda p, c, pi, ci, **kw: 1.0 if "answer is 6" in c else 0.0,
        gconfig=GenerationHyperparameters(n_samples=1, max_new_tokens=256),
        tokenizer=tok,
        tool_fn=lambda code: executed.append(code) or "x\n",
    )
    traj = asyncio.run(wf.arun_episode(eng, dict(prompt="q")))
    assert not executed
    assert float(np.asarray(traj["rewards"]).reshape(-1)[0]) == 1.0


def test_bpe_boundary_overshoot_joins_code_correctly():
    """The engine's stop-string cut is token-aligned: retained text can run
    a few chars past the fence. The state machine must stitch the code body
    across the overshoot instead of aborting (review regression)."""
    tok = _CharTok()
    eng = _ScriptedEngine(
        tok,
        [
            ("ok ```python\nimp", "stop"),            # overshoot into code
            ("ort math\nprint(7)\n```\nSo", "stop"),  # overshoot past fence
            (" the answer is 7", "length"),
        ],
    )
    executed = []
    wf = TIRWorkflow(
        reward_fn=lambda p, c, pi, ci, **kw: 0.0,
        gconfig=GenerationHyperparameters(n_samples=1, max_new_tokens=256),
        tokenizer=tok,
        tool_fn=lambda code: executed.append(code) or "7\n",
    )
    asyncio.run(wf.arun_episode(eng, dict(prompt="q")))
    assert executed == ["import math\nprint(7)\n"]


def test_calculator_and_search_tools_dispatch():
    """Multi-tool registry: each opening marker routes to its own tool
    (ref: examples/tir/tool_manager.py + search-agent's retrieval)."""
    from areal_tpu.workflow.tir import calculator_tool, search_tool

    tok = _CharTok()
    corpus = [
        "The Eiffel Tower is in Paris France",
        "Mount Everest is the tallest mountain on Earth",
        "Paris is the capital of France",
    ]
    eng = _ScriptedEngine(
        tok,
        [
            ("let me compute <calculator>", "stop"),
            ("(3 + 4) * 2</calculator>", "stop"),
            ("now look up <search>", "stop"),
            ("eiffel tower paris</search>", "stop"),
            ("answer: 14, Paris", "length"),
        ],
    )
    wf = TIRWorkflow(
        reward_fn=lambda p, c, pi, ci, **kw: 0.0,
        gconfig=GenerationHyperparameters(n_samples=1, max_new_tokens=512),
        tokenizer=tok,
        tools=[calculator_tool(), search_tool(corpus, top_k=2)],
    )
    asyncio.run(wf.arun_episode(eng, dict(prompt="q")))
    # calculator result spliced into request 3's prompt
    assert "14" in eng.seen_prompts[2]
    # search results spliced into request 5's prompt, best match first
    assert "Eiffel Tower" in eng.seen_prompts[4]


def test_search_tool_ranking_and_misses():
    from areal_tpu.workflow.tir import search_tool

    t = search_tool(
        ["alpha beta gamma", "alpha only here", "unrelated text"], top_k=2
    )
    out = t.fn("alpha beta")
    assert out.startswith("[1] alpha beta gamma")
    assert "[2] alpha only here" in out
    assert "unrelated" not in out
    assert t.fn("zzz qqq") == "no results\n"


def test_calculator_tool_safe():
    from areal_tpu.workflow.tir import calculator_tool

    t = calculator_tool()
    assert t.fn(" (3 + 4) * 2 ") == "14\n"
    assert "error" in t.fn("__import__('os')")


def test_task_stop_inside_tool_block_ends_episode():
    """A marker-lookalike inside the tool input followed by a TASK stop
    must end the episode rather than execute truncated input (review
    regression: phase-B proximity guard)."""
    tok = _CharTok()
    eng = _ScriptedEngine(
        tok,
        [
            ("```python\n", "stop"),
            # bare ``` inside a string literal, then the task stop fires
            # far past it
            ('s = "``` fake" ' + "x" * 60 + "</answer>", "stop"),
        ],
    )
    executed = []
    wf = TIRWorkflow(
        reward_fn=lambda p, c, pi, ci, **kw: 0.0,
        gconfig=GenerationHyperparameters(
            n_samples=1, max_new_tokens=512, stop=["</answer>"]
        ),
        tokenizer=tok,
        tool_fn=lambda code: executed.append(code) or "never\n",
    )
    asyncio.run(wf.arun_episode(eng, dict(prompt="q")))
    assert not executed
    assert len(eng.seen_prompts) == 2


def test_calculator_exact_large_integers():
    from areal_tpu.workflow.tir import calculator_tool

    t = calculator_tool()
    assert t.fn("1234567*2") == "2469134\n"
    assert t.fn("3.5*2") == "7\n"  # integral float renders exactly
    # beyond-2^53 integer arithmetic stays exact (int-preserving walk)
    assert t.fn("123456789123456789+1") == "123456789123456790\n"


def test_python_tool_reaps_grandchildren():
    """A spawned grandchild holding the stdout pipe must not stall the
    call past its deadline (process-group kill)."""
    import time

    t0 = time.monotonic()
    out = run_python_tool(
        "import subprocess\n"
        "subprocess.Popen(['sleep', '100'])\n"
        "print('spawned')\n",
        timeout_seconds=3.0,
    )
    assert time.monotonic() - t0 < 10.0
    assert "spawned" in out or "TimeoutError" in out
