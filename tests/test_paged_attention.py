"""In-pool paged-attention decode: op-level correctness and engine-level
layout parity.

The decode engine's `kv_layout="paged"` path (the default) must produce
the SAME streams as the retained `kv_layout="workspace"` numerics oracle:
identical greedy tokens, and per-token logprobs that are bitwise equal on
the XLA gather impl (it reproduces the workspace op sequence exactly) /
allclose (fp32, atol 1e-4) on the Pallas split-KV kernel. The engine
sweep covers the full scheduling surface the ISSUE names: prefix forks
(duplicate prompts), suffix prefills (conversation extensions past the
shared-prefix threshold), retire-mid-chunk reconcile under run-ahead,
and frequency-penalty + top-p sampling.
"""

import asyncio
from dataclasses import replace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.models.qwen2 import ModelConfig, decode_step, init_params
from areal_tpu.ops.paged_attention import paged_attention, resolve_impl

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)


# ---------------------------------------------------------------------------
# op level
# ---------------------------------------------------------------------------


def _random_pool(rng, n_blocks, bsz, nKV, hd):
    k = rng.standard_normal((n_blocks, bsz, nKV, hd)).astype(np.float32)
    v = rng.standard_normal((n_blocks, bsz, nKV, hd)).astype(np.float32)
    return jnp.asarray(k), jnp.asarray(v)


def _dense_reference(q, kp, vp, bt, valid):
    """Gather + plain masked softmax attention in f64-free numpy."""
    R, nH, hd = q.shape
    bsz, nKV = kp.shape[1], kp.shape[2]
    nb = bt.shape[1]
    group = nH // nKV
    kc = np.asarray(kp)[np.asarray(bt).reshape(-1)].reshape(
        R, nb * bsz, nKV, hd
    )
    vc = np.asarray(vp)[np.asarray(bt).reshape(-1)].reshape(
        R, nb * bsz, nKV, hd
    )
    qg = np.asarray(q).reshape(R, nKV, group, hd)
    out = np.zeros((R, nH, hd), np.float32)
    for r in range(R):
        for k_h in range(nKV):
            for g in range(group):
                s = kc[r, :, k_h] @ qg[r, k_h, g] / np.sqrt(hd)
                s = np.where(np.asarray(valid)[r], s, -1e30)
                p = np.exp(s - s.max())
                p /= p.sum()
                out[r, k_h * group + g] = p @ vc[r, :, k_h]
    return out


def test_paged_attention_xla_vs_dense(cpu_devices):
    rng = np.random.default_rng(0)
    R, nH, nKV, hd, bsz, nb, n_blocks = 3, 4, 2, 8, 16, 3, 12
    kp, vp = _random_pool(rng, n_blocks, bsz, nKV, hd)
    q = jnp.asarray(rng.standard_normal((R, nH, hd)).astype(np.float32))
    bt = jnp.asarray(
        rng.choice(np.arange(1, n_blocks), size=(R, nb), replace=False)
        .astype(np.int32)
    )
    lengths = np.array([5, 17, nb * bsz - 1], np.int32)
    valid = jnp.asarray(np.arange(nb * bsz)[None, :] <= lengths[:, None])
    out = paged_attention(q, kp, vp, bt, valid, impl="xla")
    ref = _dense_reference(q, kp, vp, bt, valid)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


def test_paged_attention_pallas_vs_xla(cpu_devices):
    """The split-KV online-softmax kernel (interpret mode on CPU) must
    match the gather fallback on every slot, including slots whose valid
    span ends mid-block and a fully-masked (length-0 equivalent) row."""
    rng = np.random.default_rng(1)
    R, nH, nKV, hd, bsz, nb, n_blocks = 4, 8, 2, 16, 16, 4, 20
    kp, vp = _random_pool(rng, n_blocks, bsz, nKV, hd)
    q = jnp.asarray(rng.standard_normal((R, nH, hd)).astype(np.float32))
    bt = jnp.asarray(
        rng.choice(np.arange(1, n_blocks), size=(R, nb), replace=False)
        .astype(np.int32)
    )
    lengths = np.array([0, 9, 30, nb * bsz - 1], np.int32)
    valid = jnp.asarray(np.arange(nb * bsz)[None, :] <= lengths[:, None])
    a = paged_attention(q, kp, vp, bt, valid, impl="xla")
    b = paged_attention(q, kp, vp, bt, valid, impl="pallas", interpret=True)
    np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
    )


def test_resolve_impl(cpu_devices):
    assert resolve_impl("xla") == "xla"
    assert resolve_impl("pallas") == "pallas"
    assert resolve_impl("auto") in ("pallas", "xla")
    with pytest.raises(ValueError):
        resolve_impl("cuda")


def test_decode_step_paged_matches_workspace(cpu_devices):
    """One decode step: the paged write (O(1) dynamic scatter) + in-pool
    attention must produce the same logits as decode_step over the
    gathered workspace, and must write the SAME bytes into the written
    row while leaving every other live block untouched."""
    from areal_tpu.models.qwen2 import decode_step_paged

    rng = np.random.default_rng(2)
    params = init_params(TINY, jax.random.PRNGKey(0))
    L, nKV, hd = TINY.num_hidden_layers, TINY.num_key_value_heads, TINY.head_dim_
    R, bsz, nb, n_blocks = 3, 8, 3, 10
    kp = jnp.asarray(
        rng.standard_normal((L, n_blocks, bsz, nKV, hd)).astype(np.float32)
    )
    vp = jnp.asarray(
        rng.standard_normal((L, n_blocks, bsz, nKV, hd)).astype(np.float32)
    )
    bt = jnp.asarray(
        rng.choice(np.arange(1, n_blocks), size=(R, nb), replace=False)
        .astype(np.int32)
    )
    tokens = jnp.asarray([3, 7, 11], jnp.int32)
    positions = jnp.asarray([4, 11, 20], jnp.int32)
    active = jnp.asarray([True, True, False])

    # workspace oracle: gather, step, scatter
    idx = bt.reshape(-1)
    kc = jnp.take(kp, idx, axis=1).reshape(L, R, nb * bsz, nKV, hd)
    vc = jnp.take(vp, idx, axis=1).reshape(L, R, nb * bsz, nKV, hd)
    logits_ws, kc2, vc2 = decode_step(
        params, tokens, positions, kc, vc, TINY, active=active
    )
    kp_ws = kp.at[:, idx].set(kc2.reshape(L, R * nb, bsz, nKV, hd))
    vp_ws = vp.at[:, idx].set(vc2.reshape(L, R * nb, bsz, nKV, hd))

    logits_pg, kp_pg, vp_pg = decode_step_paged(
        params, tokens, positions, kp, vp, bt, TINY, active=active,
        attn_impl="xla",
    )
    np.testing.assert_array_equal(np.asarray(logits_ws), np.asarray(logits_pg))
    # every block except the reserved null block 0 (paged parks inactive
    # writes there; workspace masks them) must match bit for bit
    np.testing.assert_array_equal(
        np.asarray(kp_ws)[:, 1:], np.asarray(kp_pg)[:, 1:]
    )
    np.testing.assert_array_equal(
        np.asarray(vp_ws)[:, 1:], np.asarray(vp_pg)[:, 1:]
    )


# ---------------------------------------------------------------------------
# engine level: full-trace layout parity
# ---------------------------------------------------------------------------

_BASE = [1, 5, 9, 13, 2, 4, 6, 8]  # shared prompt for fork coverage


def _engine(layout: str, impl: str = "auto", **kw):
    cfg = JaxDecodeConfig(
        context_length=kw.pop("context_length", 256),
        max_running_requests=kw.pop("max_running_requests", 4),
        new_tokens_per_chunk=kw.pop("new_tokens_per_chunk", 4),
        page_size=kw.pop("page_size", 16),
        decode_runahead_chunks=kw.pop("decode_runahead_chunks", 1),
        kv_layout=layout,
        paged_attn_impl=impl,
        dtype="float32",
        kv_cache_dtype="float32",
        random_seed=7,
        **kw,
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    return eng


def _run_trace(eng):
    """One request trace hitting forks, suffix prefill, retire-mid-chunk
    and the sampler variants; returns responses in a deterministic order."""

    async def main():
        g = GenerationHyperparameters(greedy=True, max_new_tokens=10)
        # wave of duplicates (same-wave dup fork) + distinct prompts
        wave = await asyncio.gather(
            eng.agenerate(ModelRequest(input_ids=list(_BASE), gconfig=g)),
            eng.agenerate(ModelRequest(input_ids=list(_BASE), gconfig=g)),
            eng.agenerate(ModelRequest(input_ids=[2, 7, 11, 3], gconfig=g)),
            # stop token likely mid-chunk: retire-mid-chunk reconcile under
            # run-ahead (the chunk after the stop is already dispatched)
            eng.agenerate(
                ModelRequest(
                    input_ids=[9, 9, 1, 4],
                    gconfig=replace(g, max_new_tokens=9, stop_token_ids=[1]),
                )
            ),
        )
        # conversation extension PAST the 64-token shared-prefix floor:
        # long donor finishes, then a request re-submits donor prompt +
        # answer + a new suffix -> fork + suffix prefill
        long_prompt = [(i % 60) + 1 for i in range(70)]
        donor = await eng.agenerate(
            ModelRequest(input_ids=list(long_prompt), gconfig=g)
        )
        ext = await eng.agenerate(
            ModelRequest(
                input_ids=list(long_prompt)
                + list(donor.output_tokens)
                + [5, 3],
                gconfig=g,
            )
        )
        # sampled variants: freq penalty and top-p classes share a batch
        sampled = await asyncio.gather(
            eng.agenerate(
                ModelRequest(
                    input_ids=[1, 2, 3],
                    gconfig=GenerationHyperparameters(
                        temperature=1.0,
                        top_p=0.9,
                        max_new_tokens=8,
                        frequency_penalty=0.7,
                    ),
                )
            ),
            eng.agenerate(
                ModelRequest(
                    input_ids=[4, 5, 6],
                    gconfig=GenerationHyperparameters(
                        temperature=0.8, top_p=1.0, max_new_tokens=8
                    ),
                )
            ),
        )
        return list(wave) + [donor, ext] + list(sampled)

    return asyncio.run(main())


def _trace_and_metrics(layout, impl="auto"):
    eng = _engine(layout, impl)
    try:
        out = _run_trace(eng)
        m = eng.get_metrics()
    finally:
        eng.destroy()
    return out, m


def test_engine_layout_parity_xla(cpu_devices):
    """kv_layout='paged' (xla impl) vs 'workspace': bitwise-identical
    tokens AND logprobs across forks, suffix prefill, retire-mid-chunk
    under run-ahead, and freq-penalty/top-p sampling."""
    ws, m_ws = _trace_and_metrics("workspace")
    pg, m_pg = _trace_and_metrics("paged", "xla")
    assert len(ws) == len(pg)
    for i, (a, b) in enumerate(zip(ws, pg)):
        assert a.output_tokens == b.output_tokens, i
        assert a.output_logprobs == b.output_logprobs, i
        assert a.stop_reason == b.stop_reason, i
    # the trace really exercised the sharing paths, on both engines
    for m in (m_ws, m_pg):
        assert m["prefix_forks_total"] >= 1, m
        assert m["suffix_prefills_total"] >= 1, m
        assert m["prefix_cache_hit_rate"] > 0.0, m
    # and the layouts differ where they should: workspace pays gather +
    # scatter per chunk; the paged xla impl keeps only the gather (the
    # scatter-back half of the round trip is eliminated — exactly half
    # the bytes on the same chunk trace)
    assert m_ws["kv_workspace_copy_bytes_total"] > 0
    assert (
        m_pg["kv_workspace_copy_bytes_total"]
        == m_ws["kv_workspace_copy_bytes_total"] // 2
    ), (m_pg["kv_workspace_copy_bytes_total"],
        m_ws["kv_workspace_copy_bytes_total"])
    assert m_pg["kv_layout"] == "paged"


def test_engine_layout_parity_pallas(cpu_devices):
    """The Pallas split-KV kernel (interpret mode on CPU) keeps greedy
    streams identical and logprobs allclose (fp32, atol 1e-4)."""
    ws, _ = _trace_and_metrics("workspace")
    pg, m_pg = _trace_and_metrics("paged", "pallas")
    # the true in-pool path copies NOTHING per chunk
    assert m_pg["kv_workspace_copy_bytes_total"] == 0
    for i, (a, b) in enumerate(zip(ws, pg)):
        assert a.output_tokens == b.output_tokens, i
        np.testing.assert_allclose(
            np.asarray(a.output_logprobs),
            np.asarray(b.output_logprobs),
            atol=1e-4,
            err_msg=str(i),
        )


def test_block_table_upload_dirty_tracking(cpu_devices):
    """Steady-state chunks must NOT re-upload the block table: uploads
    are keyed on (allocator mutation version, nb), so a long generation
    with a stable slot set uploads only when admission/retire/growth
    actually moved the table."""
    eng = _engine("paged", "xla", new_tokens_per_chunk=2)
    try:

        async def main():
            g = GenerationHyperparameters(greedy=True, max_new_tokens=24)
            return await eng.agenerate(
                ModelRequest(input_ids=[3, 1, 4], gconfig=g)
            )

        asyncio.run(main())
        m = eng.get_metrics()
    finally:
        eng.destroy()
    # 24 tokens at 2/chunk = 12 chunks; table mutates only at admission
    # and on block-boundary growth (page_size 16 -> at most a few times)
    assert m["chunks_dispatched_total"] >= 12
    assert m["block_table_uploads_total"] < m["chunks_dispatched_total"], m
    assert m["block_table_uploads_total"] >= 1


def test_prewarm_covers_paged_variants(cpu_devices):
    """Prewarm on a paged engine must ghost-compile the paged chunk
    variants (and the patch fn) so the first overlapped dispatch never
    traces: after prewarm, serving a request compiles nothing new."""
    eng = _engine("paged", "xla")
    try:
        eng.prewarm(prompt_len=8, new_tokens=4, sampler_top_ps=(1.0,))
        compiled = set(eng._chunk_fns)
        assert compiled, "prewarm compiled no chunk variants"
        assert eng._patch_fn is not None

        async def main():
            g = GenerationHyperparameters(greedy=True, max_new_tokens=4)
            return await eng.agenerate(
                ModelRequest(input_ids=[3, 1, 4, 1, 5, 9, 2, 6], gconfig=g)
            )

        asyncio.run(main())
        assert set(eng._chunk_fns) == compiled, (
            "live traffic needed a chunk variant prewarm did not compile"
        )
    finally:
        eng.destroy()


def test_fragmentation_metric(cpu_devices):
    """kv_pool_fragmentation counts the free-block remainder that cannot
    back another max-context admission."""
    eng = _engine(
        "paged", "xla", context_length=64, page_size=16, kv_pool_tokens=112
    )
    try:
        m = eng.get_metrics()
        # 7 usable blocks, max_bps = 4 -> one full-context reservation
        # fits, 3 blocks are structural remainder
        assert m["kv_blocks_free"] == 7
        assert m["kv_pool_fragmentation"] == 3
    finally:
        eng.destroy()
