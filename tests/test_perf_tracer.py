"""PerfTracer catapult emitter + xprof hooks (ref: areal/tests/
test_perf_tracer.py over areal/utils/perf_tracer.py)."""

import json
import os

from areal_tpu.utils import perf_tracer
from areal_tpu.utils.perf_tracer import PerfTracer


def test_scopes_async_and_instant_round_trip(tmp_path):
    out = str(tmp_path / "t.json")
    tr = PerfTracer(rank=3, save_path=out)
    with tr.trace_scope("fwd", "compute", step=1):
        pass
    tr.atrace_begin("rollout", "r1")
    tr.atrace_end("rollout", "r1")
    tr.instant("weights_pushed", "comm", version=2)
    with tr.trace_scope("oddcat", "not-a-category"):
        pass
    assert tr.save() == out
    events = json.load(open(out))["traceEvents"]
    by_ph = {}
    for e in events:
        by_ph.setdefault(e["ph"], []).append(e)
    assert len(by_ph["X"]) == 2 and by_ph["X"][0]["name"] == "fwd"
    assert by_ph["X"][0]["args"] == {"step": 1}
    assert by_ph["X"][1]["cat"] == "misc"  # unknown category folded
    assert [e["ph"] for e in by_ph["b"] + by_ph["e"]] == ["b", "e"]
    assert by_ph["i"][0]["args"]["version"] == 2
    assert all(e["pid"] == 3 for e in events)


def test_disabled_tracer_is_free_and_saves_nothing(tmp_path):
    tr = PerfTracer(rank=0, save_path=str(tmp_path / "x.json"), enabled=False)
    with tr.trace_scope("a"):
        pass
    tr.instant("b")
    assert tr.save() is None
    assert not os.path.exists(tmp_path / "x.json")


def test_merge_ranks(tmp_path):
    files = []
    for r in (0, 1):
        tr = PerfTracer(rank=r, save_path=str(tmp_path / f"r{r}.json"))
        with tr.trace_scope(f"work{r}"):
            pass
        files.append(tr.save())
    merged = PerfTracer.merge(
        files + [str(tmp_path / "missing.json")], str(tmp_path / "m.json")
    )
    events = json.load(open(merged))["traceEvents"]
    assert {e["pid"] for e in events} == {0, 1}


def test_init_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("AREAL_TPU_PERF_TRACE", "1")
    monkeypatch.setenv("AREAL_TPU_PERF_TRACE_DIR", str(tmp_path))
    tr = perf_tracer.init_from_env(rank=5)
    assert tr.enabled and tr.save_path.endswith("trace-rank5.json")
    monkeypatch.setenv("AREAL_TPU_PERF_TRACE", "0")
    tr = perf_tracer.init_from_env(rank=5)
    assert not tr.enabled


def test_xprof_trace_noop_without_dir(monkeypatch):
    monkeypatch.delenv("AREAL_TPU_XPROF_DIR", raising=False)
    with perf_tracer.xprof_trace() as t:
        assert t is None


def test_maybe_xprof_step_window(tmp_path, monkeypatch):
    """The env-gated window starts at the first configured step and stops
    exactly once after the last — captured via the real jax profiler."""
    import glob

    monkeypatch.setenv("AREAL_TPU_XPROF_DIR", str(tmp_path))
    monkeypatch.setenv("AREAL_TPU_XPROF_STEPS", "1-2")
    monkeypatch.setitem(perf_tracer._xprof_state, "active", False)
    monkeypatch.setitem(perf_tracer._xprof_state, "done", False)
    import jax
    import jax.numpy as jnp

    for step in range(5):
        perf_tracer.maybe_xprof_step(step)
        jax.block_until_ready(jax.jit(lambda x: x + 1)(jnp.ones(8)))
    assert perf_tracer._xprof_state["done"]
    assert not perf_tracer._xprof_state["active"]
    assert glob.glob(str(tmp_path) + "/**/*.xplane.pb", recursive=True)
