"""Interleaved virtual-stage 1F1B + hybrid mesh + ZeRO-1 (tier-1, CPU).

Acceptance invariants for the trainer scale-out PR:

1. EXACTNESS — the interleaved schedule (`pipeline_schedule=
   "1f1b_interleaved"`, virtual_pp_size=2) produces fp32-bitwise-identical
   per-microbatch losses and parameter gradients to the plain 1F1B oracle
   (`pipeline_1f1b_grads`) at pp=2, M=8: both schedules apply the same
   layer sequence per microbatch and accumulate per-layer grads over
   microbatches in the same (increasing-round) order. Bitwise identity
   needs dp-replicated params (fsdp off — under fsdp, GSPMD orders the
   grad-reduction collectives per program, so distinct-HLO schedules are
   only allclose) and `gradient_checkpointing=True` (the default): remat
   makes each layer's backward a self-contained recompute region that XLA
   compiles identically whether the enclosing vjp scans 1 layer (a v=2
   chunk) or 2 (a v=1 stage); without remat, fusion across the scan
   boundary reassociates the layer backward differently per granularity
   (~1e-7 drift — still well inside the allclose train-step check).
2. ZeRO-1 — with `zero1_optimizer` the dp-sharded optimizer update yields
   params bitwise equal to the replicated oracle after train steps
   (AdamW is elementwise; clipping is off so the gnorm reduction order
   cannot couple into the update).
3. PLAN — `plan_compile_check` AOT-compiles the pp=2 x v=2 x dp=2 program
   on a faked two-slice hybrid mesh, including the pipelined step.
4. STABILITY — `opt_state_sharding` is invariant under
   `jax.pipeline_schedule` switches, so an orbax restore that flips the
   schedule cannot silently re-replicate dp-sharded moments.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    MicroBatchSpec,
    OptimizerConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.engine.sft.lm_engine import (
    JaxLMEngine,
    compute_packed_sft_loss,
)
from areal_tpu.models.qwen2 import ModelConfig
from areal_tpu.parallel import mesh as mesh_lib
from areal_tpu.parallel.pipeline import (
    interleave_layer_indices,
    inverse_interleave_layer_indices,
)

TINY4 = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,  # 1 layer per virtual chunk at pp=2, v=2
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
    remat=True,  # see module docstring: required for bitwise identity
)

PP = 2
V = 2
M = 8
T = 64


def _engine(
    schedule,
    *,
    virtual=1,
    clip=1.0,
    zero1=False,
    strategy=None,
    remat=True,
    fsdp=False,
):
    cfg = TrainEngineConfig(
        experiment_name="ppvirt",
        trial_name=f"{schedule}-v{virtual}-z{int(zero1)}",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=T),
        optimizer=OptimizerConfig(
            lr=1e-2,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=clip,
        ),
        gradient_checkpointing=remat,
    )
    cfg.jax.pipeline_schedule = schedule
    cfg.jax.virtual_pp_size = virtual
    cfg.jax.zero1_optimizer = zero1
    # default to dp-replicated params (no fsdp): with fsdp-sharded params
    # GSPMD picks the grad-reduction collective order per program, so the
    # v=1 and v=2 programs (different HLO) are only allclose, not bitwise
    # — test_interleaved_train_step_matches_1f1b_engine covers that regime
    if not fsdp:
        cfg.jax.fsdp_axes = []
    eng = JaxLMEngine(cfg)
    eng.model_config = TINY4
    eng.create_process_group(
        strategy
        or ParallelStrategy(
            pipeline_parallel_size=PP,
            data_parallel_size=2,
            tensor_parallel_size=2,
        )
    )
    eng.initialize(None, FinetuneSpec(1, 64, 8))
    return eng


@pytest.fixture(scope="module")
def stacked_batch():
    rng = np.random.RandomState(0)
    return (
        {
            "input_ids": jnp.asarray(
                rng.randint(1, TINY4.vocab_size, (M, T)), jnp.int32
            ),
            "position_ids": jnp.asarray(
                np.tile(np.arange(T, dtype=np.int32), (M, 1))
            ),
            "segment_ids": jnp.asarray(
                np.repeat(np.arange(2, dtype=np.int32), T // 2)[None].repeat(
                    M, 0
                )
            ),
            "loss_mask": jnp.asarray(
                rng.randint(0, 2, (M, T)).astype(np.int32)
            ),
        },
        jnp.asarray(rng.rand(M).astype(np.float32) + 0.5),
    )


def test_layer_interleave_roundtrip():
    for L, pp, v in ((4, 2, 2), (12, 2, 3), (24, 4, 2), (8, 2, 1)):
        perm = interleave_layer_indices(L, pp, v)
        inv = inverse_interleave_layer_indices(L, pp, v)
        assert sorted(perm) == list(range(L))
        assert [perm[i] for i in inv] == list(range(L))
        # stage s of the chunk-major layout holds the layers of chunks
        # s, pp+s, 2*pp+s, ... (round-robin), each chunk contiguous
        Lc = L // (pp * v)
        for s in range(pp):
            rank_layers = perm[s * v * Lc : (s + 1) * v * Lc]
            chunks = [
                rank_layers[vc * Lc : (vc + 1) * Lc] for vc in range(v)
            ]
            for vc, chunk in enumerate(chunks):
                c = vc * pp + s
                assert chunk == list(range(c * Lc, (c + 1) * Lc))


def test_interleaved_grads_bitwise_match_1f1b(cpu_devices, stacked_batch):
    stacked, weights = stacked_batch

    def _run(eng):
        fn = eng._get_pipelined_grad_step(compute_packed_sft_loss)
        losses, _stats, grads = fn(eng.params, stacked, weights)
        # compare in MODEL layer order — the interleaved engine stores
        # layers (and grads) chunk-major at rest
        grads = eng._to_model_layout(grads)
        return np.asarray(losses), jax.tree.map(np.asarray, grads)

    e_ref = _engine("1f1b")
    e_int = _engine("1f1b_interleaved", virtual=V)
    try:
        l_ref, g_ref = _run(e_ref)
        l_int, g_int = _run(e_int)
    finally:
        e_ref.destroy()
        e_int.destroy()

    np.testing.assert_array_equal(l_int, l_ref)
    flat_r, tree_r = jax.tree_util.tree_flatten(g_ref)
    flat_i, tree_i = jax.tree_util.tree_flatten(g_int)
    assert tree_r == tree_i
    for a, b in zip(flat_r, flat_i):
        np.testing.assert_array_equal(a, b)


def test_interleaved_train_step_matches_1f1b_engine(cpu_devices):
    """Full train_batch parity across fresh engines, schedule x virtual."""
    from areal_tpu.utils.data import pad_sequences_to_tensors

    rng = np.random.RandomState(3)
    seqs = []
    for L in (9, 30, 7, 25, 11, 13, 8, 21):
        ids = rng.randint(1, 64, (L,))
        mask = np.zeros(L, dtype=np.int32)
        mask[L // 2 :] = 1
        seqs.append(dict(input_ids=ids, loss_mask=mask))
    batch = pad_sequences_to_tensors(seqs)

    e1 = _engine("1f1b_interleaved", virtual=V, fsdp=True)
    e2 = _engine("1f1b", fsdp=True)
    try:
        for _ in range(2):
            s1 = e1.train_lm(batch)
            s2 = e2.train_lm(batch)
            np.testing.assert_allclose(
                s1["loss"], s2["loss"], rtol=2e-5, atol=1e-6
            )
            np.testing.assert_allclose(
                s1["grad_norm"], s2["grad_norm"], rtol=2e-4, atol=1e-6
            )
    finally:
        e1.destroy()
        e2.destroy()


def test_virtual_requires_interleaved_schedule(cpu_devices):
    eng = _engine("1f1b_interleaved", virtual=V)
    try:
        eng.config.jax.pipeline_schedule = "1f1b"
        with pytest.raises(ValueError, match="1f1b_interleaved"):
            eng._get_pipelined_grad_step(compute_packed_sft_loss)
    finally:
        eng.destroy()


def test_zero1_params_bitwise_match_replicated(cpu_devices):
    """dp-sharded optimizer update == replicated oracle, bit for bit.

    Clipping is disabled: the global-norm reduction order differs under
    dp-sharded grads, and a clipped step would couple that roundoff into
    the params. The update itself (AdamW) is elementwise, so sharding the
    state changes nothing.
    """
    from areal_tpu.utils.data import pad_sequences_to_tensors

    rng = np.random.RandomState(7)
    seqs = []
    for L in (12, 28, 9, 17, 23, 8, 31, 14):
        ids = rng.randint(1, 64, (L,))
        mask = np.zeros(L, dtype=np.int32)
        mask[L // 3 :] = 1
        seqs.append(dict(input_ids=ids, loss_mask=mask))
    batch = pad_sequences_to_tensors(seqs)

    strat = ParallelStrategy(
        pipeline_parallel_size=1,
        data_parallel_size=4,
        tensor_parallel_size=2,
    )
    e_z = _engine("1f1b", clip=0.0, zero1=True, strategy=strat)
    e_r = _engine("1f1b", clip=0.0, zero1=False, strategy=strat)
    try:
        assert e_z._zero1 and not e_r._zero1
        # the moments really are dp-extended somewhere in the tree
        specs_z = {
            s.spec
            for s in jax.tree_util.tree_leaves(e_z._opt_state_shardings())
        }
        specs_r = {
            s.spec
            for s in jax.tree_util.tree_leaves(e_r._opt_state_shardings())
        }
        assert specs_z != specs_r
        for _ in range(2):
            s_z = e_z.train_lm(batch)
            s_r = e_r.train_lm(batch)
            np.testing.assert_array_equal(s_z["loss"], s_r["loss"])
        flat_z = jax.tree_util.tree_leaves(
            jax.tree.map(np.asarray, e_z.params)
        )
        flat_r = jax.tree_util.tree_leaves(
            jax.tree.map(np.asarray, e_r.params)
        )
        for a, b in zip(flat_z, flat_r):
            np.testing.assert_array_equal(a, b)
    finally:
        e_z.destroy()
        e_r.destroy()


def test_hybrid_mesh_fallback_shape(cpu_devices):
    """Faked two-slice hybrid mesh: pp granules map across the DCN
    boundary, every other axis stays within a slice."""
    strat = ParallelStrategy(
        pipeline_parallel_size=2,
        data_parallel_size=2,
        tensor_parallel_size=2,
    )
    mesh = mesh_lib.build_hybrid_mesh(strat, num_slices=2)
    assert mesh.shape[mesh_lib.AXIS_PP] == 2
    dev = np.asarray(mesh.devices)
    # pp is the slice axis: fixing pp and flattening the rest must yield
    # one contiguous half of the device ids (one fake "slice" each)
    pp_axis = mesh.axis_names.index(mesh_lib.AXIS_PP)
    ids0 = sorted(
        d.id for d in np.take(dev, 0, axis=pp_axis).flatten()
    )
    ids1 = sorted(
        d.id for d in np.take(dev, 1, axis=pp_axis).flatten()
    )
    assert ids0 == list(range(0, 4))
    assert ids1 == list(range(4, 8))


def test_hybrid_mesh_rejects_bad_factoring(cpu_devices):
    strat = ParallelStrategy(
        pipeline_parallel_size=1,
        data_parallel_size=4,
        tensor_parallel_size=2,
    )
    with pytest.raises(ValueError, match="num_slices"):
        mesh_lib.build_hybrid_mesh(strat, num_slices=3, dcn_axes=("pp",))


def test_plan_check_interleaved_hybrid(cpu_devices):
    """Tier-1 regression: the pp=2 x v=2 x dp=2 interleaved program on a
    faked multi-slice topology AOT-compiles, pipelined step included."""
    cfg = TrainEngineConfig(
        experiment_name="ppvirt",
        trial_name="plan",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=T),
        optimizer=OptimizerConfig(
            lr=1e-2,
            warmup_steps_proportion=0.0,
            lr_scheduler_type="constant",
            gradient_clipping=1.0,
        ),
        gradient_checkpointing=False,
    )
    cfg.jax.pipeline_schedule = "1f1b_interleaved"
    cfg.jax.virtual_pp_size = 2
    cfg.jax.zero1_optimizer = True
    cfg.jax.mesh_num_slices = 2
    eng = JaxLMEngine(cfg)
    eng.model_config = TINY4
    eng.create_process_group(
        ParallelStrategy(
            pipeline_parallel_size=2,
            data_parallel_size=2,
            tensor_parallel_size=2,
        )
    )
    try:
        report = eng.plan_compile_check(T)
        assert "grad_step" in report
        assert "pipelined_step" in report
        assert report["pipelined_step"].get("argument_size_in_bytes", 0) >= 0
    finally:
        eng.destroy()


def test_opt_state_sharding_stable_across_schedule_switch(cpu_devices):
    """A restore that flips jax.pipeline_schedule must land on identical
    moment shardings — otherwise restored moments silently re-replicate."""
    eng = _engine("1f1b", zero1=True)
    try:
        base = eng._opt_state_shardings()
        for schedule in ("gpipe", "1f1b", "1f1b_interleaved"):
            eng.config.jax.pipeline_schedule = schedule
            eng._opt_shardings = None  # what a fresh restore would see
            again = eng._opt_state_shardings()
            assert jax.tree_util.tree_structure(
                base
            ) == jax.tree_util.tree_structure(again)
            for a, b in zip(
                jax.tree_util.tree_leaves(base),
                jax.tree_util.tree_leaves(again),
            ):
                assert a == b
    finally:
        eng.destroy()
