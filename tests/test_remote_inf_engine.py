"""Remote inference stack: DecodeServer (HTTP) + RemoteInfEngine client.

Covers the control-plane parity surface of areal/core/remote_inf_engine.py +
areal/engine/sglang_remote.py: /generate round-trips with logprobs+versions,
greedy parity with the in-process engine, pause-with-abort producing
"interrupt" partials that the client resumes, version fanout, and rid→server
affinity.
"""

import asyncio
import threading

import numpy as np
import pytest

import jax

from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
)
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.core.remote_inf_engine import RemoteInfEngine
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.launcher.decode_server import DecodeServer
from areal_tpu.models.qwen2 import ModelConfig, init_params

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)


class _ServerThread:
    """Run a DecodeServer on a private event loop in a daemon thread."""

    def __init__(self, engine: JaxDecodeEngine):
        self.server = DecodeServer(
            JaxDecodeConfig(), engine=engine
        )
        self.addr = None
        self._loop = None
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        assert self._ready.wait(30), "server failed to start"

    def _run(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def _start():
            self.addr = await self.server.start(host="127.0.0.1", port=0)
            self._ready.set()

        self._loop.run_until_complete(_start())
        self._loop.run_forever()

    def stop(self):
        async def _stop():
            await self.server.stop()
            self._loop.stop()

        asyncio.run_coroutine_threadsafe(_stop(), self._loop)
        self._thread.join(timeout=10)


@pytest.fixture(scope="module")
def served_engine(cpu_devices):
    cfg = JaxDecodeConfig(
        context_length=96,
        max_running_requests=4,
        new_tokens_per_chunk=4,
        dtype="float32",
        kv_cache_dtype="float32",
    )
    eng = JaxDecodeEngine(cfg, InferenceEngineConfig())
    eng.set_model(init_params(TINY, jax.random.PRNGKey(0)), TINY)
    eng.initialize()
    st = _ServerThread(eng)
    yield eng, st.addr
    st.stop()
    eng.destroy()


@pytest.fixture(scope="module")
def client(served_engine):
    _, addr = served_engine
    c = RemoteInfEngine(
        InferenceEngineConfig(setup_timeout=30, request_timeout=60)
    )
    c.initialize(addr=addr)
    yield c
    c.destroy()


def _greedy_req(prompt, n_new, rid=None):
    g = GenerationHyperparameters(greedy=True, max_new_tokens=n_new)
    kw = {"input_ids": prompt, "gconfig": g}
    if rid:
        kw["rid"] = rid
    return ModelRequest(**kw)


def _run(coro):
    return asyncio.run(coro)


def test_generate_roundtrip_matches_local(served_engine, client):
    eng, _ = served_engine
    prompt = [3, 14, 15, 9, 2]
    local = eng.generate(_greedy_req(prompt, 12))
    remote = _run(client.agenerate(_greedy_req(prompt, 12)))
    assert remote.output_tokens == local.output_tokens
    np.testing.assert_allclose(
        remote.output_logprobs, local.output_logprobs, rtol=1e-5, atol=1e-6
    )
    assert remote.output_versions == local.output_versions
    assert remote.stop_reason in ("stop", "length")
    assert len(remote.output_tokens) == 12


def test_concurrent_remote_generations(client):
    async def _many():
        reqs = [_greedy_req([i + 1, i + 2, i + 3], 8) for i in range(8)]
        return await asyncio.gather(*[client.agenerate(r) for r in reqs])

    resps = _run(_many())
    assert all(len(r.output_tokens) == 8 for r in resps)


def test_interrupt_resume_loop(served_engine, client):
    """Pause+abort mid-generation; the client must resume transparently and
    the final sequence must equal an uninterrupted greedy decode."""
    eng, _ = served_engine
    prompt = [5, 11, 7]
    uninterrupted = eng.generate(_greedy_req(prompt, 24)).output_tokens

    result = {}

    def _bg():
        result["resp"] = _run(client.agenerate(_greedy_req(prompt, 24)))

    t = threading.Thread(target=_bg)
    t.start()
    # let some chunks land, then flush in-flight requests like a weight
    # update would
    import time

    interrupted = False
    for _ in range(50):
        time.sleep(0.05)
        if result.get("resp"):
            break
        eng.pause_generation()
        if any(s is not None for s in eng._slots):
            eng.abort_all()
            interrupted = True
        eng.continue_generation()
        if interrupted:
            break
    t.join(timeout=60)
    assert not t.is_alive()
    resp = result["resp"]
    assert resp.output_tokens == uninterrupted
    assert len(resp.output_logprobs) == 24
    assert len(resp.output_versions) == 24


def test_set_version_fans_out(served_engine, client):
    eng, _ = served_engine
    client.set_version(7)
    assert eng.get_version() == 7
    resp = _run(client.agenerate(_greedy_req([1, 2, 3], 4)))
    assert all(v == 7 for v in resp.output_versions)
    client.set_version(0)


def test_rid_affinity_and_round_robin(client):
    a1 = client.choose_server("rid-x")
    a2 = client.choose_server("rid-x")
    assert a1 == a2  # affinity caches the first assignment
