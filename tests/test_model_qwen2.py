"""Model correctness: structure, masking, and numerical parity with the HF
transformers Qwen2 implementation (the reference's source of truth for model
behavior, areal/engine/base_hf_engine.py loads these directly)."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from areal_tpu.models.qwen2 import (
    ModelConfig,
    forward,
    init_params,
    param_logical_axes,
    param_shapes,
    segment_ids_from_cu_seqlens,
)

TINY = dict(
    vocab_size=96,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)


@pytest.fixture(scope="module")
def tiny_cfg():
    return ModelConfig(**TINY)


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return init_params(tiny_cfg, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def fwd():
    return jax.jit(forward, static_argnums=(4,))


def _packed_inputs(lens, vocab=96, seed=0):
    rng = np.random.RandomState(seed)
    total = sum(lens)
    ids = rng.randint(0, vocab, (total,))
    cu = np.concatenate([[0], np.cumsum(lens)])
    seg = segment_ids_from_cu_seqlens(cu, total)
    pos = np.concatenate([np.arange(n) for n in lens])
    return ids, pos, seg, cu


def test_param_tree_matches_shapes(tiny_cfg, tiny_params):
    expected = param_shapes(tiny_cfg)

    def check(exp, got):
        assert set(exp) == set(got)
        for k in exp:
            if isinstance(exp[k], dict):
                check(exp[k], got[k])
            else:
                assert tuple(got[k].shape) == tuple(exp[k]), k

    check(expected, tiny_params)


def test_axes_tree_structure_matches(tiny_cfg, tiny_params):
    axes = param_logical_axes(tiny_cfg)
    jax.tree.map(
        lambda a, b: None,
        jax.tree.map(lambda x: 0, tiny_params),
        jax.tree.map(lambda x: 0, axes, is_leaf=lambda x: isinstance(x, tuple)),
    )


def test_segment_isolation(tiny_cfg, tiny_params, fwd):
    ids, pos, seg, _ = _packed_inputs([5, 7, 4])
    base = fwd(tiny_params, ids, pos, seg, tiny_cfg)
    ids2 = ids.copy()
    ids2[5:12] = (ids2[5:12] + 1) % 96  # mutate segment 1
    out = fwd(tiny_params, ids2, pos, seg, tiny_cfg)
    np.testing.assert_allclose(base[:5], out[:5], atol=1e-5)
    np.testing.assert_allclose(base[12:], out[12:], atol=1e-5)


def test_causality(tiny_cfg, tiny_params, fwd):
    ids, pos, seg, _ = _packed_inputs([8])
    base = fwd(tiny_params, ids, pos, seg, tiny_cfg)
    ids2 = ids.copy()
    ids2[5] = (ids2[5] + 1) % 96
    out = fwd(tiny_params, ids2, pos, seg, tiny_cfg)
    np.testing.assert_allclose(base[:5], out[:5], atol=1e-5)
    assert not np.allclose(base[5], out[5], atol=1e-5)


def test_packed_equals_separate(tiny_cfg, tiny_params, fwd):
    # forward over packed [5,7] must equal two independent forwards
    ids, pos, seg, cu = _packed_inputs([5, 7])
    packed = np.asarray(fwd(tiny_params, ids, pos, seg, tiny_cfg))
    for i, n in enumerate([5, 7]):
        sl = slice(cu[i], cu[i + 1])
        alone = np.asarray(
            fwd(
                tiny_params,
                ids[sl],
                np.arange(n),
                np.zeros(n, dtype=np.int32),
                tiny_cfg,
            )
        )
        np.testing.assert_allclose(packed[sl], alone, atol=2e-4)


def test_scan_vs_unrolled_equivalence(tiny_cfg, tiny_params):
    import dataclasses

    from areal_tpu.models.hf_io import assemble_params, flatten_params

    unroll_cfg = dataclasses.replace(tiny_cfg, scan_layers=False)
    flat = flatten_params(tiny_params, tiny_cfg)
    unroll_params = assemble_params(flat, unroll_cfg, "float32")
    ids, pos, seg, _ = _packed_inputs([6, 3])
    a = forward(tiny_params, ids, pos, seg, tiny_cfg)
    b = forward(unroll_params, ids, pos, seg, unroll_cfg)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
def test_hf_numerical_parity(tmp_path):
    """Golden test: our forward matches transformers' Qwen2ForCausalLM on a
    tiny random model saved to HF format."""
    torch = pytest.importorskip("torch")
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_cfg = Qwen2Config(
        vocab_size=96,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = Qwen2ForCausalLM(hf_cfg).eval().float()
    model_dir = tmp_path / "hf"
    model.save_pretrained(model_dir, safe_serialization=True)
    with open(model_dir / "config.json") as f:
        assert json.load(f)["model_type"] == "qwen2"

    from areal_tpu.models.hf_io import load_hf_params

    cfg = ModelConfig.from_hf_config(
        str(model_dir), dtype="float32", param_dtype="float32"
    )
    assert cfg.qkv_bias and not cfg.qk_norm
    params = load_hf_params(str(model_dir), cfg)

    T = 12
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 96, (T,))
    with torch.no_grad():
        hf_logits = model(torch.tensor(ids)[None]).logits[0].numpy()
    ours = np.asarray(
        forward(
            params,
            ids,
            np.arange(T),
            np.zeros(T, dtype=np.int32),
            cfg,
        )
    )
    np.testing.assert_allclose(ours, hf_logits, atol=2e-3, rtol=1e-3)


def test_hf_save_load_roundtrip(tiny_cfg, tiny_params, tmp_path):
    from areal_tpu.models.hf_io import load_hf_params, save_hf_params

    out = save_hf_params(tiny_params, tiny_cfg, str(tmp_path / "ckpt"))
    reloaded = load_hf_params(out, tiny_cfg, dtype="float32")
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6),
        tiny_params,
        reloaded,
    )


def test_llama_config_and_rope_scaling():
    """Llama-3.x checkpoints load through the same decoder: biasless qkv,
    no qk-norm, and the "llama3" NTK-by-parts RoPE scaling must match the
    HF reference formula (transformers modeling_rope_utils
    _compute_llama3_parameters)."""
    from areal_tpu.models.qwen2 import ModelConfig, rope_table

    hf_cfg = dict(
        model_type="llama",
        vocab_size=128256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        rope_theta=500000.0,
        rope_scaling=dict(
            rope_type="llama3",
            factor=8.0,
            low_freq_factor=1.0,
            high_freq_factor=4.0,
            original_max_position_embeddings=8192,
        ),
    )
    cfg = ModelConfig.from_hf_config(hf_cfg)
    assert not cfg.qkv_bias and not cfg.qk_norm
    assert cfg.rope_scaling_ == ("llama3", 8.0, 1.0, 4.0, 8192)

    # numpy transcription of the HF formula
    hd, theta = cfg.head_dim_, cfg.rope_theta
    inv_freq = 1.0 / (theta ** (np.arange(0, hd, 2, dtype=np.float64) / hd))
    factor, low_f, high_f, orig = 8.0, 1.0, 4.0, 8192
    wavelen = 2 * np.pi / inv_freq
    ref = np.where(wavelen > orig / low_f, inv_freq / factor, inv_freq)
    smooth = (orig / wavelen - low_f) / (high_f - low_f)
    smoothed = (1 - smooth) / factor * inv_freq + smooth * inv_freq
    medium = ~(wavelen < orig / high_f) & ~(wavelen > orig / low_f)
    ref = np.where(medium, smoothed, ref)

    pos = np.arange(7, dtype=np.int32)
    cos, sin = rope_table(
        jnp.asarray(pos), hd, theta, cfg.rope_scaling_
    )
    np.testing.assert_allclose(
        np.asarray(cos), np.cos(pos[:, None] * ref[None, :]), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(sin), np.sin(pos[:, None] * ref[None, :]), rtol=1e-5
    )

    # linear scaling = position interpolation: scaled positions 2k land
    # where unscaled positions k do
    lin = ModelConfig.from_hf_config(
        {**hf_cfg, "rope_scaling": {"type": "linear", "factor": 2.0}}
    )
    assert lin.rope_scaling_ == ("linear", 2.0)
    c2, _ = rope_table(jnp.asarray(pos * 2), hd, theta, lin.rope_scaling_)
    c1, _ = rope_table(jnp.asarray(pos), hd, theta, None)
    np.testing.assert_allclose(np.asarray(c2), np.asarray(c1), rtol=1e-5)
