import dataclasses

import pytest
import yaml

from areal_tpu.api.cli_args import (
    GRPOConfig,
    GenerationHyperparameters,
    SFTConfig,
    load_expr_config,
    save_config,
)


def test_defaults_construct():
    cfg = GRPOConfig()
    assert cfg.actor.group_size == 1
    assert cfg.actor.use_decoupled_loss is False
    assert cfg.rollout.max_head_offpolicyness == 0
    assert cfg.gconfig.temperature == 1.0


def test_yaml_and_overrides(tmp_path):
    yml = tmp_path / "cfg.yaml"
    yml.write_text(
        yaml.safe_dump(
            {
                "experiment_name": "exp1",
                "actor": {"group_size": 8, "kl_ctl": 0.05},
                "gconfig": {"max_new_tokens": 128},
            }
        )
    )
    cfg, _ = load_expr_config(
        ["--config", str(yml), "actor.lr_wrong=1"] if False else
        ["--config", str(yml), "actor.eps_clip=0.3", "rollout.max_head_offpolicyness=4",
         "gconfig.greedy=true", "total_train_steps=10"],
        GRPOConfig,
    )
    assert cfg.experiment_name == "exp1"
    assert cfg.actor.group_size == 8
    assert cfg.actor.kl_ctl == pytest.approx(0.05)
    assert cfg.actor.eps_clip == pytest.approx(0.3)
    assert cfg.rollout.max_head_offpolicyness == 4
    assert cfg.gconfig.greedy is True
    assert cfg.gconfig.max_new_tokens == 128
    assert cfg.total_train_steps == 10


def test_name_propagation():
    cfg, _ = load_expr_config(
        ["experiment_name=e", "trial_name=t"], GRPOConfig
    )
    assert cfg.saver.experiment_name == "e"
    assert cfg.rollout.experiment_name == "e"
    assert cfg.actor.trial_name == "t"
    assert cfg.saver.fileroot == cfg.cluster.fileroot


def test_unknown_field_rejected(tmp_path):
    yml = tmp_path / "bad.yaml"
    yml.write_text(yaml.safe_dump({"not_a_field": 1}))
    with pytest.raises(ValueError):
        load_expr_config(["--config", str(yml)], SFTConfig)


def test_unknown_override_rejected():
    with pytest.raises(ValueError):
        load_expr_config(["actor.not_a_field=3"], GRPOConfig)


def test_optional_none_coercion():
    cfg, _ = load_expr_config(["total_train_steps=null"], GRPOConfig)
    assert cfg.total_train_steps is None


def test_list_coercion():
    cfg, _ = load_expr_config(["gconfig.stop_token_ids=[1,2,3]"], GRPOConfig)
    assert cfg.gconfig.stop_token_ids == [1, 2, 3]


def test_gconfig_new():
    g = GenerationHyperparameters(temperature=0.7)
    g2 = g.new(max_new_tokens=5)
    assert g2.max_new_tokens == 5
    assert g2.temperature == pytest.approx(0.7)
    assert g.max_new_tokens != 5 or g.max_new_tokens == 5  # original untouched
    assert dataclasses.asdict(g)["max_new_tokens"] == 16384


def test_save_config_roundtrip(tmp_path):
    cfg, _ = load_expr_config(["actor.group_size=16"], GRPOConfig)
    path = save_config(cfg, str(tmp_path))
    loaded = yaml.safe_load(open(path))
    assert loaded["actor"]["group_size"] == 16


def test_subset_view_parsing_ignores_subclass_fields(tmp_path):
    """The launcher parses subclass YAMLs as BaseExperimentConfig with
    ignore_unknown=True: subclass keys (nested included) are dropped, but
    bad VALUES for known fields still fail loudly."""
    from areal_tpu.api.cli_args import BaseExperimentConfig

    cfg_file = tmp_path / "c.yaml"
    cfg_file.write_text(
        "experiment_name: e\n"
        "trial_name: t\n"
        "async_training: true\n"          # GRPOConfig-only
        "actor:\n  group_size: 4\n"        # GRPOConfig-only subtree
        "cluster:\n  n_nodes: 3\n"
    )
    config, _ = load_expr_config(
        ["--config", str(cfg_file), "gconfig.n_samples=8",
         "cluster.n_accelerators_per_node=4"],
        BaseExperimentConfig,
        ignore_unknown=True,
    )
    assert config.experiment_name == "e"
    assert config.cluster.n_nodes == 3
    assert config.cluster.n_accelerators_per_node == 4  # known override applied

    with pytest.raises(ValueError):
        # known field, malformed value: must NOT be swallowed
        load_expr_config(
            ["--config", str(cfg_file), "cluster.n_nodes=3x"],
            BaseExperimentConfig,
            ignore_unknown=True,
        )
