"""Prewarm/startup hardening (ADVICE r05 #1-#4) — tier-1, CPU, fast.

1. decode_server binds its HTTP listener only AFTER prewarm finishes, so a
   request or /pause can never land mid-warmup.
2. prewarm's load-bearing guards are RuntimeError, not assert — `python -O`
   must not silently cancel an externally held pause.
3. bench's pause-latency probe records a -1 sentinel instead of timing an
   idle-engine pause when the load window is missed.
4. prewarm warns when a wave's promised batched-prefill variant never
   compiled (KV-pool pressure split the wave).
"""

import asyncio
import logging
import threading

import pytest

from areal_tpu.api.cli_args import InferenceEngineConfig, JaxDecodeConfig
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.launcher.decode_server import DecodeServer


def _engine():
    return JaxDecodeEngine(
        JaxDecodeConfig(context_length=96, max_running_requests=4),
        InferenceEngineConfig(),
    )


def test_prewarm_requires_initialize():
    eng = _engine()
    with pytest.raises(RuntimeError, match="initialize"):
        eng.prewarm(prompt_len=8)


def test_prewarm_refuses_external_pause():
    eng = _engine()
    # Simulate an initialized engine holding an external pause (the
    # weight-update window): prewarm must refuse — and must do so even
    # under `python -O`, hence RuntimeError, not assert.
    eng._thread = threading.Thread(target=lambda: None)
    eng._gen_paused.set()
    with pytest.raises(RuntimeError, match="un-paused"):
        eng.prewarm(prompt_len=8)


def test_prewarm_wave_warning():
    eng = _engine()
    eng._batched_prefill_fns = {(64, 4): object()}
    # the areal_tpu root logger has propagate=False, so capture with a
    # handler attached directly to the module logger
    records: list[logging.LogRecord] = []

    class _Cap(logging.Handler):
        def emit(self, record):
            records.append(record)

    log = logging.getLogger("areal_tpu.jax_decode")
    cap = _Cap(level=logging.WARNING)
    log.addHandler(cap)
    try:
        eng._warn_wave_not_compiled(64, 4)  # compiled: silent
        assert not records
        eng._warn_wave_not_compiled(64, 8)  # promised but missing: warn
        assert any(
            "B=8" in r.getMessage() and "not compiled" in r.getMessage()
            for r in records
        )
        records.clear()
        eng._warn_wave_not_compiled(64, 1)  # single prefill: not batched
        assert not records
    finally:
        log.removeHandler(cap)


class _StubEngine:
    """Engine double for DecodeServer lifecycle tests: records whether the
    HTTP listener existed at each call."""

    def __init__(self):
        self.calls = []
        self.server: DecodeServer | None = None

    def initialize(self):
        self.calls.append(("initialize", self.server._runner is None))

    def prewarm(self, **kw):
        # The listener must NOT be bound yet: no socket, no addr.
        self.calls.append(
            (
                "prewarm",
                self.server._runner is None and self.server.addr is None,
            )
        )

    def get_version(self):
        return 0

    def destroy(self):
        self.calls.append(("destroy", True))


def test_server_prewarms_before_binding():
    stub = _StubEngine()
    server = DecodeServer(JaxDecodeConfig(), engine=stub)
    server._owns_engine = True  # exercise initialize() ordering too
    stub.server = server

    async def run():
        addr = await server.start(
            host="127.0.0.1", port=0, prewarm=dict(prompt_len=8)
        )
        assert addr
        await server.stop()

    asyncio.run(run())
    names = [c[0] for c in stub.calls]
    assert names[:2] == ["initialize", "prewarm"]
    assert all(flag for _, flag in stub.calls), stub.calls


def test_server_start_without_prewarm_unchanged():
    stub = _StubEngine()
    server = DecodeServer(JaxDecodeConfig(), engine=stub)
    stub.server = server

    async def run():
        addr = await server.start(host="127.0.0.1", port=0)
        assert addr
        await server.stop()

    asyncio.run(run())
    assert [c[0] for c in stub.calls] == []  # not owned: no engine calls


def test_bench_wait_for_running_sentinel():
    import bench

    class _Idle:
        def get_metrics(self):
            return {"running_requests": 0}

    class _Busy:
        def get_metrics(self):
            return {"running_requests": 2}

    assert bench._wait_for_running(_Busy(), timeout_s=1.0) is True
    assert bench._wait_for_running(_Idle(), timeout_s=0.05) is False
