"""bench.py mode wiring: every `--mode` choice maps to a runnable bench.

Regression surface (ISSUE 6 satellite): the mode list used to live in three
places — the argparse `choices`, the `want(...)` if-chains, and the
dev-mode headline dict — so a new bench could ship selectable-but-unwired
(`--mode foo` accepted, nothing runs, or KeyError at the headline print).
The dispatch table `BENCH_MODE_FNS` is now the single source the choices
derive from; these tests pin that every choice resolves to a callable and
every dev mode has its headline metric.
"""

import importlib.util
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_mode_choice_maps_to_a_runnable_bench():
    bench = _load_bench()
    modes = set(bench.BENCH_MODES)
    assert "all" in modes
    # choices == {"all"} + dispatch-table keys, exactly
    assert modes - {"all"} == set(bench.BENCH_MODE_FNS), (
        modes, set(bench.BENCH_MODE_FNS),
    )
    for mode, fn in bench.BENCH_MODE_FNS.items():
        assert callable(fn), mode
        # a dispatch entry must be a real bench function defined in bench.py
        assert fn.__name__.startswith("bench_"), (mode, fn.__name__)


def test_kvoffload_mode_is_pinned():
    """ISSUE 7 satellite: the tiered-KV bench must stay reachable as
    `--mode kvoffload` — a rename/removal of the dispatch entry (which
    the derived-choices tests above would silently absorb) is a breaking
    CLI change and must fail here."""
    bench = _load_bench()
    assert "kvoffload" in bench.BENCH_MODE_FNS
    assert bench.BENCH_MODE_FNS["kvoffload"] is bench.bench_kvoffload
    assert bench.MODE_HEADLINES["kvoffload"] == (
        "kvoffload_resume_ttft_speedup", "x",
    )


def test_kvquant_mode_is_pinned():
    """ISSUE 11: the int8-KV bench must stay reachable as `--mode
    kvquant` with its fixed-MB capacity-ratio headline — the acceptance
    proof for quantized pools (capacity, tok/s, swap/wire bytes, drift,
    spec accept-rate shift) lives behind this entry point."""
    bench = _load_bench()
    assert "kvquant" in bench.BENCH_MODE_FNS
    assert bench.BENCH_MODE_FNS["kvquant"] is bench.bench_kvquant
    assert bench.MODE_HEADLINES["kvquant"] == (
        "kvquant_capacity_ratio", "x",
    )


def test_fleet_mode_is_pinned():
    """ISSUE 8 satellite: the fleet-router bench must stay reachable as
    `--mode fleet` with its prefix-affinity-vs-least_requests headline —
    a rename/removal of the dispatch entry is a breaking CLI change."""
    bench = _load_bench()
    assert "fleet" in bench.BENCH_MODE_FNS
    assert bench.BENCH_MODE_FNS["fleet"] is bench.bench_fleet
    assert bench.MODE_HEADLINES["fleet"] == (
        "fleet_affinity_ttft_p50_speedup", "x",
    )


def test_chaos_mode_is_pinned():
    """ISSUE 9: the fault-injection chaos bench must stay reachable as
    `--mode chaos` with its exactly-once headline — the acceptance proof
    for the robustness layer lives behind this entry point."""
    bench = _load_bench()
    assert "chaos" in bench.BENCH_MODE_FNS
    assert bench.BENCH_MODE_FNS["chaos"] is bench.bench_chaos
    assert bench.MODE_HEADLINES["chaos"] == ("chaos_exactly_once", "bool")


def test_chaostrain_mode_is_pinned():
    """ISSUE 14: the trainer-side chaos bench must stay reachable as
    `--mode chaostrain` with its exactly-once headline — the acceptance
    proof for crash-atomic recovery + the sample ledger (seeded kills at
    every trainer seam, oracle-matched resume, torn-newest fallback)
    lives behind this entry point."""
    bench = _load_bench()
    assert "chaostrain" in bench.BENCH_MODE_FNS
    assert bench.BENCH_MODE_FNS["chaostrain"] is bench.bench_chaostrain
    assert bench.MODE_HEADLINES["chaostrain"] == (
        "chaostrain_exactly_once", "bool",
    )


def test_disagg_mode_is_pinned():
    """ISSUE 10: the disaggregated prefill/decode bench must stay
    reachable as `--mode disagg` with its decode-ITL headline — the
    acceptance proof for role fleets + KV migration lives behind this
    entry point."""
    bench = _load_bench()
    assert "disagg" in bench.BENCH_MODE_FNS
    assert bench.BENCH_MODE_FNS["disagg"] is bench.bench_disagg
    assert bench.MODE_HEADLINES["disagg"] == (
        "disagg_decode_itl_p99_speedup", "x",
    )


def test_autoscale_mode_is_pinned():
    """ISSUE 13: the supervised-vs-static autoscale bench must stay
    reachable as `--mode autoscale` with its replica-seconds-savings
    headline — the acceptance proof for the fleet control plane (SLO
    held at materially fewer replica-seconds, exactly-once under a
    mid-trace kill) lives behind this entry point."""
    bench = _load_bench()
    assert "autoscale" in bench.BENCH_MODE_FNS
    assert bench.BENCH_MODE_FNS["autoscale"] is bench.bench_autoscale
    assert bench.MODE_HEADLINES["autoscale"] == (
        "autoscale_replica_seconds_ratio", "x",
    )


def test_wquant_mode_is_pinned():
    """ISSUE 16: the int8 weight-serving bench must stay reachable as
    `--mode wquant` with its wire-bytes-ratio headline — the acceptance
    proof for producer-side weight quantization (freed HBM -> resident
    KV capacity, decode tok/s, push wire bytes + commit pause ~2x
    smaller, drift vs the fp oracle) lives behind this entry point."""
    bench = _load_bench()
    assert "wquant" in bench.BENCH_MODE_FNS
    assert bench.BENCH_MODE_FNS["wquant"] is bench.bench_wquant
    assert bench.MODE_HEADLINES["wquant"] == (
        "wquant_wire_bytes_ratio", "x",
    )


def test_kvfabric_mode_is_pinned():
    """ISSUE 17: the fleet KV-fabric bench must stay reachable as
    `--mode kvfabric` with its warm-start TTFT headline — the acceptance
    proof for content-addressed blocks (intra-replica dedup, peer fetch
    instead of re-prefill, cold-replica warm start, weight-flip honest
    misses) lives behind this entry point."""
    bench = _load_bench()
    assert "kvfabric" in bench.BENCH_MODE_FNS
    assert bench.BENCH_MODE_FNS["kvfabric"] is bench.bench_kvfabric
    assert bench.MODE_HEADLINES["kvfabric"] == (
        "kvfabric_warm_ttft_speedup", "x",
    )


def test_every_dev_mode_has_a_headline_metric():
    bench = _load_bench()
    # dev modes = everything but "all" and "train" (those emit the trainer
    # MFU line); each needs a (metric_key, unit) headline or main() KeyErrors
    dev_modes = set(bench.BENCH_MODE_FNS) - {"train"}
    assert dev_modes == set(bench.MODE_HEADLINES), (
        dev_modes, set(bench.MODE_HEADLINES),
    )
    for mode, (key, unit) in bench.MODE_HEADLINES.items():
        assert isinstance(key, str) and key, mode
        assert isinstance(unit, str) and unit, mode


def test_argparse_choices_accept_every_mode():
    """The CLI surface itself: argparse must accept exactly BENCH_MODES
    (a mode present in the table but missing from choices would be
    unreachable from the command line)."""
    bench = _load_bench()
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--mode", choices=list(bench.BENCH_MODES))
    for m in bench.BENCH_MODES:
        assert p.parse_args(["--mode", m]).mode == m


def test_ppsched_mode_is_pinned():
    """ISSUE 15: the pipeline-schedule bench must stay reachable as
    `--mode ppsched` with the interleaved-vs-1f1b legs. The headline is
    the v1/v2 bubble ratio — the whole point of virtual stages."""
    bench = _load_bench()
    assert "ppsched" in bench.BENCH_MODE_FNS
    assert bench.BENCH_MODE_FNS["ppsched"] is bench.bench_pp_schedules
    assert bench.MODE_HEADLINES["ppsched"] == (
        "pp_bubble_ratio_v1_over_v2", "x",
    )


def test_ppsched_bubble_sim_interleaving_wins():
    """The timetable simulator behind the ppsched bubble numbers must
    reproduce the Megatron closed forms — bubble = (pp-1)/(v*M + pp-1)
    for the interleaved 1F1B family — so v=2 strictly beats v=1 and the
    win grows with pp. gpipe matches 1F1B on bubble (its loss is the
    stash, which the temp-memory legs price)."""
    bench = _load_bench()
    sim = bench._pp_bubble_sim
    for pp, M in ((2, 8), (4, 8), (4, 16)):
        vals = {
            v: sim(pp, v, M, 1.0 / v, 1.0 / v) for v in (1, 2)
        }
        for v in (1, 2):
            expect = (pp - 1) / (v * M + pp - 1)
            assert abs(vals[v] - expect) < 1e-9, (pp, v, M)
        assert vals[2] < vals[1], (pp, M)
    g = sim(2, 1, 8, 1.0, 1.0, schedule="gpipe")
    assert abs(g - sim(2, 1, 8, 1.0, 1.0)) < 1e-9
