"""GRPO end-to-end learning proof.

Parity: the reference's integration gate asserts a real reward threshold
after training (areal/tests/grpo/test_grpo.py:13-63, final reward > 0.6).
Scaled to the CPU toy: a dense verifiable reward on the first generated
token; after N updates through the FULL pipeline (decode engine -> RLVR
workflow -> decoupled-PPO actor -> weight push back into decode), the mean
reward must rise significantly over its starting level.

Discriminating power: the same pipeline with lr=0 must show no rise — so a
broken optimizer path makes the learning assertion fail, unlike the round-2
E2E test that only asserted numerical sanity (flagged in VERDICT.md).
"""

import numpy as np
import pytest

from areal_tpu.api.alloc_mode import ParallelStrategy
from areal_tpu.api.cli_args import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    JaxDecodeConfig,
    MicroBatchSpec,
    NormConfig,
    OptimizerConfig,
    PPOActorConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec, WeightUpdateMeta
from areal_tpu.engine.jax_decode import JaxDecodeEngine
from areal_tpu.engine.ppo.actor import JaxPPOActor
from areal_tpu.models.qwen2 import ModelConfig
from areal_tpu.workflow.rlvr import RLVRWorkflow

TINY = ModelConfig(
    vocab_size=32,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

TARGET_TOKEN = 16
GROUP = 8


def dense_reward(prompt, completion, prompt_ids, completion_ids, **kwargs):
    """Dense verifiable reward pulling the first generated token to 16."""
    return 1.0 - abs(completion_ids[0] - TARGET_TOKEN) / 32.0


def _run_training(lr: float, steps: int, cpu_devices) -> list[float]:
    actor_cfg = PPOActorConfig(
        experiment_name="learn",
        trial_name=f"lr{lr}",
        path="",
        init_from_scratch=True,
        dtype="float32",
        mb_spec=MicroBatchSpec(max_tokens_per_mb=1024),
        optimizer=OptimizerConfig(
            lr=lr, warmup_steps_proportion=0.0, lr_scheduler_type="constant"
        ),
        gradient_checkpointing=False,
        group_size=GROUP,
        ppo_n_minibatches=1,
        eps_clip=0.2,
        kl_ctl=0.0,
        adv_norm=NormConfig(
            mean_level="group", std_level="group", group_size=GROUP
        ),
        use_decoupled_loss=True,
        temperature=1.0,
    )
    actor = JaxPPOActor(actor_cfg)
    actor.model_config = TINY
    actor.create_process_group(ParallelStrategy(data_parallel_size=8))
    actor.initialize(None, FinetuneSpec(1, 256, 8))

    rollout = JaxDecodeEngine(
        JaxDecodeConfig(
            context_length=16,
            max_running_requests=32,
            new_tokens_per_chunk=2,
            dtype="float32",
            kv_cache_dtype="float32",
            random_seed=7,
        ),
        # capacity must cover a whole batch of episodes: with the default
        # consumer_batch_size=1 + max_head_offpolicyness=0 the staleness
        # gate admits ONE episode per weight version and rollout_batch
        # starves forever waiting for the rest
        InferenceEngineConfig(
            max_concurrent_rollouts=64,
            consumer_batch_size=8,
            max_head_offpolicyness=2,
        ),
    )
    rollout.set_model(actor.params, TINY)
    rollout.initialize()
    actor.connect_engine(rollout, WeightUpdateMeta.from_memory())

    gconfig = GenerationHyperparameters(
        n_samples=GROUP, max_new_tokens=2, temperature=1.0
    )
    workflow = RLVRWorkflow(dense_reward, gconfig)
    prompts = [dict(input_ids=[1 + (i % 4), 2, 3]) for i in range(8)]

    mean_rewards = []
    try:
        for step in range(steps):
            batch = rollout.rollout_batch(list(prompts), workflow=workflow)
            mean_rewards.append(float(np.mean(batch["rewards"])))
            batch["prox_logp"] = actor.compute_logp(batch)
            actor.compute_advantages(batch)
            actor.ppo_update(batch)
            actor.set_version(step + 1)
            rollout.pause()
            actor.update_weights(None)
            rollout.set_version(step + 1)
            rollout.resume()
    finally:
        rollout.destroy()
        actor.destroy()
    return mean_rewards


@pytest.mark.slow
def test_grpo_learns_dense_reward(cpu_devices):
    rewards = _run_training(lr=3e-2, steps=12, cpu_devices=cpu_devices)
    start = float(np.mean(rewards[:3]))
    end = float(np.mean(rewards[-3:]))
    # Random 32-vocab sampling gives E[reward] ~= 0.75 with spread; pulling
    # the first token to TARGET drives it toward 1.0. Require a significant
    # rise AND a high absolute level — the toy-scale analogue of the
    # reference's `reward > 0.6` gate.
    assert end - start > 0.05, f"no learning: {rewards}"
    assert end > 0.9, f"final reward too low: {rewards}"


@pytest.mark.slow
def test_grpo_lr_zero_does_not_learn(cpu_devices):
    """Control: with lr=0 the learning assertions must fail — proves the
    test above has discriminating power over the optimizer path."""
    rewards = _run_training(lr=0.0, steps=12, cpu_devices=cpu_devices)
    start = float(np.mean(rewards[:3]))
    end = float(np.mean(rewards[-3:]))
    assert not (end - start > 0.05 and end > 0.9), (
        f"lr=0 run 'learned' — reward metric is not discriminating: {rewards}"
    )
