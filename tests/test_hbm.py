"""HBM estimator: exact param counts, plan fit/reject decisions, and the
AOT compile-check that proves a full-depth 7B program builds on a CPU host.

The estimator (utils/hbm.py) is the feasibility half of VERDICT r4 #4: an
allocation plan is validated against the chip's HBM *before* launch, and
`plan_compile_check` AOT-compiles the real sharded train step (full depth
28, full width, full vocab) without materializing a single parameter."""

import jax
import pytest

from areal_tpu.api.alloc_mode import (
    AllocationMode,
    AllocationValidationError,
    ParallelStrategy,
)
from areal_tpu.models.qwen2 import ModelConfig, init_params
from areal_tpu.utils import hbm

TINY = ModelConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    dtype="float32",
    param_dtype="float32",
)

QWEN25_05B = ModelConfig(
    vocab_size=151936,
    hidden_size=896,
    intermediate_size=4864,
    num_hidden_layers=24,
    num_attention_heads=14,
    num_key_value_heads=2,
    tie_word_embeddings=True,
    dtype="bfloat16",
    param_dtype="bfloat16",
)

QWEN25_7B = ModelConfig(
    vocab_size=152064,
    hidden_size=3584,
    intermediate_size=18944,
    num_hidden_layers=28,
    num_attention_heads=28,
    num_key_value_heads=4,
    tie_word_embeddings=False,
    dtype="bfloat16",
    param_dtype="bfloat16",
)


def _actual_count(cfg):
    p = init_params(cfg, jax.random.PRNGKey(0))
    return sum(x.size for x in jax.tree.leaves(p))


def test_param_count_exact_dense_and_tied():
    assert hbm.param_count(TINY) == _actual_count(TINY)
    # the known flagship number: Qwen2.5-0.5B = 494M
    assert hbm.param_count(QWEN25_05B) == _actual_count(QWEN25_05B) == 494032768


def test_param_count_exact_moe():
    moe = ModelConfig(
        vocab_size=64,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_experts=4,
        num_experts_per_tok=2,
        moe_intermediate_size=48,
        dtype="float32",
        param_dtype="float32",
    )
    assert hbm.param_count(moe) == _actual_count(moe)


def test_05b_bench_config_fits_v5e():
    """The config the r03/r05 bench actually ran on one v5e chip (bf16
    packed SFT, 8192-token micro-batches) must be judged feasible."""
    est = hbm.estimate_train_hbm(QWEN25_05B, microbatch_tokens=8192)
    hbm.check_fit(est, "TPU v5 lite")  # must not raise
    # adamw f32 moments dominate: 2 x 494M x 4B ~ 3.7 GiB
    assert 3.2 * hbm.GiB < est.opt_bytes < 4.2 * hbm.GiB
    assert est.total_bytes < 16 * hbm.GiB


def test_7b_rejected_on_one_v5e_accepted_on_v5p_mesh():
    single = hbm.estimate_train_hbm(QWEN25_7B, microbatch_tokens=8192)
    with pytest.raises(MemoryError, match="GiB"):
        hbm.check_fit(single, "TPU v5 lite")
    # the documented v5p plan: fsdp dp=8 x tp=4 (docs/PARITY.md "7B recipe")
    sharded = hbm.estimate_train_hbm(
        QWEN25_7B, dp=8, tp=4, microbatch_tokens=8192
    )
    hbm.check_fit(sharded, "TPU v5p")  # must not raise
    # opt state per chip: 2 x 7.6B x 4 / 32 ~ 1.9 GiB
    assert sharded.opt_bytes < 2.5 * hbm.GiB


def test_alloc_mode_check_hbm_integration():
    mode = AllocationMode.from_str("jax:d4t4+d8t4")
    report = mode.check_hbm(QWEN25_7B, "TPU v5p", microbatch_tokens=8192)
    assert "train" in report and "gen" in report
    assert report["train"]["total_gib"] < 95 * 0.9
    # on v5e the gen half's dense 64x32k KV reservation is what breaks
    with pytest.raises(AllocationValidationError, match="gen half"):
        mode.check_hbm(QWEN25_7B, "TPU v5e", microbatch_tokens=8192)
    # ...unless a paged pool is sized; then it passes
    mode.check_hbm(
        QWEN25_7B,
        "TPU v5e",
        microbatch_tokens=8192,
        decode_pool_tokens=256 * 1024,
    )
    # a 7B trainer on ONE chip is a train-half rejection
    with pytest.raises(AllocationValidationError, match="train half"):
        AllocationMode.from_str("jax:d4t4+d1t1").check_hbm(
            QWEN25_7B, "TPU v5e", microbatch_tokens=8192
        )


def test_zero1_opt_state_pricing():
    """ZeRO-1 (params replicated, moments dp-sharded) must price the opt
    state at 1/dp of the replicated bill and surface the freed bytes."""
    rep = hbm.estimate_train_hbm(
        QWEN25_7B, dp=8, tp=4, microbatch_tokens=8192, fsdp=False
    )
    z1 = hbm.estimate_train_hbm(
        QWEN25_7B, dp=8, tp=4, microbatch_tokens=8192, fsdp=False, zero1=True
    )
    # params/grads identical (still replicated over dp) ...
    assert z1.params_bytes == rep.params_bytes
    assert z1.grads_bytes == rep.grads_bytes
    # ... but the f32 moments divide by dp, and the delta is reported
    assert rep.opt_bytes == 8 * z1.opt_bytes
    assert z1.opt_freed_bytes == rep.opt_bytes - z1.opt_bytes
    assert "zero1_freed_gib" in z1.breakdown()
    assert "zero1_freed_gib" not in rep.breakdown()
    # the fsdp default (dp-sharded everything) is unchanged by the flag
    fs = hbm.estimate_train_hbm(QWEN25_7B, dp=8, tp=4, microbatch_tokens=8192)
    assert fs.opt_bytes == z1.opt_bytes and fs.opt_freed_bytes == 0


def test_interleaved_stash_pricing():
    """The 1f1b stash prices (2*pp-1) stage inputs; interleaved multiplies
    by v: v*(2*pp-1) virtual-chunk inputs, each a full [T_local, d] slab."""
    kw = dict(dp=2, tp=2, pp=2, microbatch_tokens=8192)
    plain = hbm.estimate_train_hbm(QWEN25_7B, **kw)
    inter = hbm.estimate_train_hbm(
        QWEN25_7B, pipeline_schedule="1f1b_interleaved", virtual_pp=2, **kw
    )
    gpipe = hbm.estimate_train_hbm(
        QWEN25_7B, pipeline_schedule="gpipe", **kw
    )
    t_local = 8192 // 2
    entry = t_local * QWEN25_7B.hidden_size * 2  # bf16
    assert plain.stash_bytes == 3 * entry  # 2*pp-1 = 3
    assert inter.stash_bytes == 2 * plain.stash_bytes
    assert gpipe.stash_bytes == 0
    assert inter.total_bytes - plain.total_bytes == plain.stash_bytes
    # no pipeline, no stash
    flat = hbm.estimate_train_hbm(QWEN25_7B, dp=4, microbatch_tokens=8192)
    assert flat.stash_bytes == 0 and "stash_gib" in flat.breakdown()


def test_device_kind_spellings():
    """GKE-style v5e spellings must not fall through to the v5p row."""
    for kind in ("TPU v5 lite", "tpu-v5-lite-podslice", "v5litepod", "V5E"):
        assert hbm.hbm_bytes(kind) == 16 * hbm.GiB, kind
    assert hbm.hbm_bytes("TPU v5p") == 95 * hbm.GiB
    assert hbm.hbm_bytes("TPU v5") == 95 * hbm.GiB
    from areal_tpu.utils.flops import peak_flops

    assert peak_flops("tpu-v5-lite-podslice") == 197e12
    assert peak_flops("TPU v5") == 459e12


def test_decode_paged_pool_vs_dense():
    """The paged pool's reservation is the knob: 64 slots x 32k dense
    reserves ~2M KV rows; a 256k-token pool is 8x smaller, and the
    estimator prices exactly that difference."""
    dense = hbm.estimate_decode_hbm(QWEN25_7B, tp=4, slots=64)
    paged = hbm.estimate_decode_hbm(QWEN25_7B, tp=4, pool_tokens=256 * 1024)
    assert dense.kv_bytes == 8 * paged.kv_bytes
    with pytest.raises(MemoryError):
        hbm.check_fit(dense, "TPU v5e")
    hbm.check_fit(paged, "TPU v5e")


@pytest.mark.slow
def test_full_depth_7b_plan_compiles(cpu_devices):
    """Full-geometry Qwen2.5-7B (depth 28, width 3584, vocab 152064) on the
    documented d4t2 mesh: the ENTIRE sharded grad step + optimizer update
    compiles to an XLA program on the CPU host, no parameters materialized.
    This is the "prove the program builds" half of a real-scale story that
    tiny-geometry dryruns cannot give."""
    from areal_tpu.api.cli_args import (
        MicroBatchSpec,
        OptimizerConfig,
        TrainEngineConfig,
    )
    from areal_tpu.engine.sft.lm_engine import JaxLMEngine

    cfg7 = dataclasses_replace_scan(QWEN25_7B)
    eng = JaxLMEngine(
        TrainEngineConfig(
            experiment_name="plan",
            trial_name="7b",
            path="",
            init_from_scratch=True,
            dtype="bfloat16",
            mb_spec=MicroBatchSpec(max_tokens_per_mb=8192),
            optimizer=OptimizerConfig(
                lr=1e-5,
                warmup_steps_proportion=0.0,
                lr_scheduler_type="constant",
                gradient_clipping=1.0,
            ),
            gradient_checkpointing=True,
        )
    )
    eng.model_config = cfg7
    eng.create_process_group(
        ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    )
    try:
        report = eng.plan_compile_check(mb_tokens=8192)
        assert "grad_step" in report and "apply_update" in report
        ma = report["apply_update"]
        if ma.get("argument_size_in_bytes"):
            # params bf16 + grads f32 + opt f32 moments, dp*tp-sharded:
            # the arguments alone should land within 2x of the closed-form
            # estimate's static terms (cross-check estimator vs XLA)
            est = hbm.estimate_train_hbm(
                QWEN25_7B, dp=4, tp=2, microbatch_tokens=8192
            )
            static = est.params_bytes + est.opt_bytes + 2 * est.grads_bytes
            assert 0.5 < ma["argument_size_in_bytes"] / static < 2.0, (
                ma,
                est.breakdown(),
            )
    finally:
        eng.destroy()


def dataclasses_replace_scan(cfg):
    import dataclasses

    return dataclasses.replace(
        cfg, scan_layers=True, remat=True, remat_policy="full"
    )
